"""Split-factor sweep harness for the split-KV paged decode path.

The on-chip autotune surface for ISSUE 6's tentpole (c): sweeps the
``decode.splits`` knob — the per-request split-KV partition factor —
across the short-context/large-batch decode shape grid (the round-5
VERDICT's 0.21–0.54 TB/s cliff cells plus the long-context control
rows), emits ``ROW {json}`` lines, and quality-stamps every row through
``obs.bench_audit.RowAuditor`` against the BENCH_BANKED.md history (the
same <0.35x implausibility rule as bench.py).

Rows are roofline-stamped by the shared cost model
(``obs.costmodel.decode_split``) with the split metadata fields
(``num_splits``, ``merge_bytes`` — docs/observability.md), and
candidates are RANKED on ``effective_pct_roofline`` — the fraction of
the binding roofline counting only useful work, so a candidate can't
win by streaming masked chunk tails or writing padded partials.

Usage::

    python benchmarks/bench_decode_splits.py            # on-chip sweep
    python benchmarks/bench_decode_splits.py --smoke    # CPU interpret
    python benchmarks/bench_decode_splits.py --emit-config > decode.json

``--emit-config`` prints a ready-to-paste ``"decode"`` section for
``flashinfer_tpu/tuning_configs/<gen>.json`` with each shape's winner —
the step that graduates the shipped section from ``"seed": true``
(cost-model-derived) to measured (docs/performance.md walks the
workflow).  Each shape also prints the cost model's own predicted
ranking next to the measured one, so every banked run doubles as a
predicted-vs-measured check on the split chooser.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable from any cwd (sys.path[0] is benchmarks/)
    sys.path.insert(0, _REPO)

_AUDITOR = None

SPLIT_CANDIDATES = (1, 2, 4, 8)


def _emit_row(**kw):
    """One measurement, RowAuditor-stamped, parseable by orchestrators."""
    global _AUDITOR
    try:
        from flashinfer_tpu.obs import bench_audit

        if _AUDITOR is None:
            _AUDITOR = bench_audit.RowAuditor(
                bench_audit.load_banked_history(
                    os.path.join(_REPO, "BENCH_BANKED.md")))
        _AUDITOR.stamp(kw)
    except Exception as e:  # noqa: BLE001 - the audit must never cost a row
        print(f"# row audit failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    print("ROW " + json.dumps(kw), flush=True)
    return kw


def shape_grid(smoke: bool):
    """(bs, ctx, HQ, HKV, D, PS) sweep shapes: the VERDICT short-context
    cliff cells first (bs=256/ctx=512 is the headline target), then
    long-context controls where the cost model predicts S=1 must win."""
    if smoke:
        return [(4, 128, 8, 2, 64, 16)]
    return [
        (256, 512, 32, 8, 128, 16),   # the 0.21-0.54 TB/s cliff cell
        (64, 512, 32, 8, 128, 16),
        (16, 2048, 32, 8, 128, 16),
        (64, 4096, 32, 8, 128, 16),   # long-context control: S=1 expected
    ]


def sweep(smoke: bool, repeats: int):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu.ops.paged_decode import (
        build_decode_split_units, paged_decode_attention,
        paged_decode_attention_split, split_pages_per_chunk,
    )
    from flashinfer_tpu.obs import costmodel, hwspec, roofline
    from flashinfer_tpu.testing import bench_fn_device
    from flashinfer_tpu import compile_guard

    chip = hwspec.current_spec()

    winners = {}
    for bs, ctx, HQ, HKV, D, PS in shape_grid(smoke):
        ppr = -(-ctx // PS)
        npages = bs * ppr
        rng = np.random.default_rng(0)
        key = jax.random.PRNGKey(0)
        kc = jax.random.normal(key, (npages, HKV, PS, D), jnp.bfloat16)
        vc = jax.random.normal(jax.random.fold_in(key, 1),
                               (npages, HKV, PS, D), jnp.bfloat16)
        q = jax.random.normal(jax.random.fold_in(key, 2),
                              (bs, HQ, D), jnp.bfloat16)
        table = rng.permutation(npages).astype(np.int32).reshape(bs, ppr)
        kv_lens = np.full((bs,), ctx, np.int64)
        ppc = split_pages_per_chunk(PS, HKV, D, 2)
        shape_key = "_".join(map(str, (
            bs, ppr, HQ, HKV, D, PS, ppc, "bfloat16")))

        # the chooser's own prediction, printed next to the measurement
        # (the predicted-vs-measured loop ROADMAP item 5 asks for)
        pred_best, pred = costmodel.choose_decode_splits(
            bs, ctx, HQ, HKV, D, hbm_tbps=chip.hbm_tbps, page_size=PS,
            pages_per_chunk=ppc)

        best = None
        for S in SPLIT_CANDIDATES:
            if S == 1:
                pt = jnp.asarray(table)
                lens = jnp.asarray(kv_lens.astype(np.int32))

                def thunk(qq, kk, vv, pt=pt, lens=lens):
                    return paged_decode_attention(
                        qq, kk, vv, pt, lens, sm_scale=D ** -0.5,
                        kv_layout="HND")
            else:
                plan_np = build_decode_split_units(
                    table, kv_lens, num_splits=S, page_size=PS,
                    pages_per_chunk=ppc)
                statics = dict(
                    num_units=plan_np.pop("num_units"),
                    num_splits=plan_np.pop("num_splits"),
                    single_chunk=plan_np.pop("single_chunk"),
                    pages_per_chunk=plan_np.pop("pages_per_chunk"),
                )
                plan_np.pop("stats")
                plan = {k: jnp.asarray(v) for k, v in plan_np.items()}

                def thunk(qq, kk, vv, plan=plan, statics=statics):
                    return paged_decode_attention_split(
                        qq, kk, vv, plan, sm_scale=D ** -0.5, **statics)
            try:
                t = compile_guard.guarded(
                    "bench.decode_splits",
                    (bs, ctx, HQ, HKV, D, PS, ppc, S),
                    lambda: bench_fn_device(thunk, q, kc, vc,
                                            repeats=repeats),
                )
            except Exception as e:  # noqa: BLE001 - one cell, not the sweep
                first = (str(e).splitlines() or ["?"])[0][:120]
                print(f"# splits S={S} FAILED {type(e).__name__}: "
                      f"{first}", file=sys.stderr)
                continue
            bd = costmodel.decode_split_breakdown(
                bs, ctx, HQ, HKV, D, num_splits=S, page_size=PS,
                pages_per_chunk=ppc)
            cost = costmodel.decode_split(
                bs, ctx, HQ, HKV, D, num_splits=S, page_size=PS,
                pages_per_chunk=ppc)
            tbps = cost.bytes_total / t / 1e12
            row = _emit_row(**roofline.stamp_row(
                dict(phase="decode_splits", bs=bs, ctx=ctx,
                     us=round(t * 1e6, 1), tbps=round(tbps, 4),
                     pred_us=round(pred.get(S, {}).get(
                         "seconds", 0.0) * 1e6, 1)),
                cost, t, chip, num_splits=S,
                merge_bytes=bd["merge_bytes"]))
            eff = row["effective_pct_roofline"]
            print(f"# splits bs={bs:4d} ctx={ctx:5d} S={S}: "
                  f"{t*1e6:9.1f} us  {tbps:6.4f} TB/s  "
                  f"eff_roof {eff:6.3f}  [{row.get('quality', '?')}]",
                  file=sys.stderr)
            if row.get("quality") != "poison" and (
                    best is None or eff > best[0]):
                best = (eff, S)
        if best is not None:
            winners[f"decode.splits|{shape_key}"] = best[1]
            agree = "agrees" if best[1] == pred_best else "DISAGREES"
            print(f"# winner bs={bs} ctx={ctx}: S={best[1]} "
                  f"(cost model predicted S={pred_best} — {agree})",
                  file=sys.stderr)
    return winners


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes, interpret-safe (CPU CI)")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--emit-config", action="store_true",
                    help="print a tuning_configs 'decode' section with "
                         "each shape's winner on stdout")
    args = ap.parse_args()
    if not args.smoke:
        from flashinfer_tpu.env import apply_platform_from_env

        apply_platform_from_env()
    winners = sweep(args.smoke, args.repeats)
    if args.emit_config:
        print(json.dumps({"decode": {
            "comment": "measured by benchmarks/bench_decode_splits.py "
                       "(replace the shipped seed section with this)",
            "seed": bool(args.smoke),
            "tactics": winners,
        }}, indent=1))
    else:
        print(json.dumps({"winners": winners}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
