"""Exact-EP vs capacity-EP under routing skew (round-5 verdict item 5).

Runs the three fused_moe_ep dispatch modes on an 8-virtual-device CPU
mesh over routing distributions from uniform to pathological, and
reports per mode:

- wall ms/step (median; CPU-mesh — NOT hardware numbers, labeled so),
- exact-mode ROUND COUNT (exact property of the routing, analytically
  recomputed from the same bucket math the kernel uses — valid on any
  backend),
- capacity-mode DROP FRACTION (exact property, measured via
  return_dropped),
- per-rank bytes moved per step (analytic: allgather moves
  T_global * H * itemsize; alltoall moves rounds * ep * cap * H *
  itemsize each way plus the id buckets).

Usage: python benchmarks/bench_ep_skew.py [--json]
The results table is banked in BENCH_BANKED.md behind the
mode-selection guidance in fused_moe_ep's docstring.
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from flashinfer_tpu.utils import jax_shard_map as shard_map
from jax.sharding import Mesh, PartitionSpec as P


def make_routing(kind: str, T: int, K: int, E: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    if kind == "uniform":
        ids = rng.integers(0, E, (T, K))
    elif kind.startswith("zipf"):
        a = float(kind.split("-")[1])
        ids = (rng.zipf(a, (T, K)) - 1) % E
    elif kind == "hot90":
        # 90% of routes hit expert 0 (worst-case hot expert)
        ids = rng.integers(0, E, (T, K))
        hot = rng.random((T, K)) < 0.9
        ids = np.where(hot, 0, ids)
    else:
        raise ValueError(kind)
    return jnp.asarray(ids, jnp.int32)


def exact_rounds(ids: np.ndarray, ep: int, E: int, T_local: int, K: int,
                 cf: float) -> int:
    """Trip count of the alltoall_exact while_loop, recomputed from the
    same bucket math (_route_buckets): cap per (src, dst) bucket, rounds
    = ceil(max bucket load / cap) agreed via pmax."""
    e_local = E // ep
    cap = max(int(np.ceil(cf * T_local * K / ep)), 1)
    worst = 0
    for src in range(ep):
        sl = ids[src * T_local:(src + 1) * T_local].reshape(-1)
        dst = sl // e_local
        counts = np.bincount(dst, minlength=ep)
        worst = max(worst, int(counts.max()))
    return -(-worst // cap)


def run(args):
    ep, T_local, K, H, I = 8, 128, 2, 256, 512
    E = 16
    cf = 2.0
    T = ep * T_local
    mesh = Mesh(np.asarray(jax.devices()[:ep]), ("ep",))
    from flashinfer_tpu.fused_moe import fused_moe_ep

    k0, k1, k2 = jax.random.split(jax.random.PRNGKey(0), 3)
    hidden = jax.random.normal(k0, (T, H), jnp.float32)
    w_gu = jax.random.normal(k1, (E, H, 2 * I), jnp.float32) * 0.05
    w_dn = jax.random.normal(k2, (E, I, H), jnp.float32) * 0.05
    wts = jnp.full((T, K), 1.0 / K, jnp.float32)

    rows = []
    for kind in ("uniform", "zipf-1.5", "zipf-1.1", "hot90"):
        ids = make_routing(kind, T, K, E)
        ids_np = np.asarray(ids)
        rounds = exact_rounds(ids_np, ep, E, T_local, K, cf)
        cap = max(int(np.ceil(cf * T_local * K / ep)), 1)
        for mode in ("allgather", "alltoall", "alltoall_exact"):
            fn = jax.jit(shard_map(
                functools.partial(
                    fused_moe_ep, num_experts=E, axis="ep", dispatch=mode,
                    capacity_factor=cf, return_dropped=True,
                ),
                mesh=mesh,
                in_specs=(P("ep"), P("ep"), P("ep"), P("ep"), P("ep")),
                out_specs=(P("ep"), P("ep")),
                check_vma=False,
            ))
            out, dropped = fn(hidden, w_gu, w_dn, wts, ids)
            jax.block_until_ready(out)
            times = []
            for _ in range(args.iters):
                t0 = time.perf_counter()
                out, dropped = fn(hidden, w_gu, w_dn, wts, ids)
                jax.block_until_ready(out)
                times.append((time.perf_counter() - t0) * 1e3)
            drop_frac = float(np.asarray(dropped).sum()) / (T * K)
            itemsize = 4
            if mode == "allgather":
                bytes_rank = T * H * itemsize  # gathered tokens
                r = 1
            else:
                r = rounds if mode == "alltoall_exact" else 1
                # dispatch + combine, ep buckets of cap tokens each way
                bytes_rank = 2 * r * ep * cap * H * itemsize
            rows.append(dict(
                skew=kind, mode=mode, ms=float(np.median(times)),
                rounds=(r if mode != "allgather" else 0),
                drop_frac=round(drop_frac, 4),
                mbytes_per_rank=round(bytes_rank / 1e6, 2),
            ))
            print(json.dumps(rows[-1]))
    return rows


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()
    run(args)
