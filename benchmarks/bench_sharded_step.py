"""Mesh-axis sweep harness for the compile-once sharded serving step.

The multi-chip autotune surface for ISSUE 9's tentpole: sweeps every
valid (dp, tp) split of the visible device count for the sharded
serving step (``parallel/plan.py``), A/B-ing the fused one-program step
against the per-op sharded loop at each split, and prints the ICI cost
model's predicted step time next to every measurement — each run
doubles as a predicted-vs-measured check on
``costmodel.predict_step_seconds`` / the ``parallel.*`` knob seeds.

Rows are roofline-stamped by the shared cost model
(``obs.costmodel.serving_step_sharded`` — HBM + MXU + the collective
ICI dimension) and carry BOTH configuration identities:
``mesh_axes`` (dp/tp shape) and ``step_mode`` (fused | per_op), so no
split's rows ever compete with another's banked history
(``obs.bench_audit``).

Usage::

    python benchmarks/bench_sharded_step.py             # on-mesh sweep
    python benchmarks/bench_sharded_step.py --smoke     # 8-virtual-dev CPU
    python benchmarks/bench_sharded_step.py --emit-config > parallel.json

``--emit-config`` prints a ready-to-paste ``"parallel"`` section for
``flashinfer_tpu/tuning_configs/<gen>.json`` with the fused-step
winner's axis split — the step that graduates the shipped section from
``"seed": true`` (ICI-cost-model-derived) to measured.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable from any cwd (sys.path[0] is benchmarks/)
    sys.path.insert(0, _REPO)

_AUDITOR = None


def _emit_row(**kw):
    """One measurement, RowAuditor-stamped, parseable by orchestrators."""
    global _AUDITOR
    try:
        from flashinfer_tpu.obs import bench_audit

        if _AUDITOR is None:
            _AUDITOR = bench_audit.RowAuditor(
                bench_audit.load_banked_history(
                    os.path.join(_REPO, "BENCH_BANKED.md")))
        _AUDITOR.stamp(kw)
    except Exception as e:  # noqa: BLE001 - the audit must never cost a row
        print(f"# row audit failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    print("ROW " + json.dumps(kw), flush=True)
    return kw


def _axis_splits(world: int, hq: int, hkv: int):
    """Every (dp, tp) with dp*tp == world and tp tiling both head
    counts — the sweep grid."""
    out = []
    for tp in range(1, world + 1):
        if world % tp == 0 and hq % tp == 0 and hkv % tp == 0:
            out.append((world // tp, tp))
    return out


def sweep(smoke: bool, emit_config: bool):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flashinfer_tpu.obs import costmodel, hwspec, roofline
    from flashinfer_tpu.parallel.plan import (
        ShardingPlan, build_sharded_fused_step,
        build_sharded_per_op_step, split_shard_weights_for_spec,
        validate_dp_page_table)
    from flashinfer_tpu.quantization import quantize_int8
    from flashinfer_tpu.serve.shard import Int8ShardSpec
    from flashinfer_tpu.utils import is_tpu
    from jax.sharding import Mesh

    if smoke:
        bs, ctx, PS = 4, 128, 16
        hidden, hq, hkv, hd, inter, vocab = 512, 8, 4, 128, 1024, 1024
        L = 2
    else:
        bs, ctx, PS = 64, 4096, 16
        hidden, hq, hkv, hd, inter, vocab = 8192, 64, 8, 128, 28672, 128256
        L = 8
    world = len(jax.devices())
    spec_hw = hwspec.current_spec()
    key = jax.random.PRNGKey(0)

    def qw(k, shape):
        w = jax.random.normal(k, shape, jnp.float32) / np.sqrt(shape[0])
        wq, ws = quantize_int8(w, axis=0)
        return wq, ws.reshape(1, -1)

    spec = Int8ShardSpec(bs=bs, hidden=hidden, hq=hq, hkv=hkv, hd=hd,
                         inter=inter, vocab_shard=vocab, page_size=PS,
                         use_pallas=is_tpu())
    qdim, kvdim = spec.qdim, spec.kvdim
    ks = jax.random.split(key, 6 * L + 2)
    layer_ws = split_shard_weights_for_spec([(
        *qw(ks[6 * i], (hidden, qdim + 2 * kvdim)),
        *qw(ks[6 * i + 1], (qdim, hidden)),
        *qw(ks[6 * i + 2], (hidden, 2 * inter)),
        *qw(ks[6 * i + 3], (inter, hidden)),
        jax.random.normal(ks[6 * i + 4], (hidden,)) * 0.02 + 1.0,
        jax.random.normal(ks[6 * i + 5], (hidden,)) * 0.02 + 1.0,
    ) for i in range(L)], spec)
    head, head_s = qw(jax.random.fold_in(key, 999), (hidden, vocab))
    pages_per_req = ctx // PS
    num_pages = bs * pages_per_req
    lens0 = np.full((bs,), ctx - 1, np.int32)
    x0 = jax.random.normal(jax.random.fold_in(key, 7), (bs, hidden),
                           jnp.bfloat16)
    shape = dict(hidden=hidden, hq=hq, hkv=hkv, hd=hd, inter=inter,
                 vocab_shard=vocab, page_size=PS, weight_bytes=1,
                 kv_bytes=1)

    def mk_caches():
        return [(jax.random.randint(
                    jax.random.fold_in(ks[-2], i),
                    (num_pages, hkv, PS, hd), -127, 127, jnp.int8),
                 jax.random.randint(
                    jax.random.fold_in(ks[-1], i),
                    (num_pages, hkv, PS, hd), -127, 127, jnp.int8))
                for i in range(L)]

    def wall(stepfn, pt0, warm=2, steps=8, repeats=3):
        best = float("inf")
        for _ in range(repeats):
            caches = mk_caches()
            p, l = jnp.asarray(pt0), jnp.asarray(lens0)
            sk = jax.random.PRNGKey(3)
            for _ in range(warm):
                tok, caches, p, l, sk = stepfn(
                    x0, layer_ws, caches, head, head_s, p, l, sk)
            float(tok[0])
            t0 = time.perf_counter()
            for _ in range(steps):
                tok, caches, p, l, sk = stepfn(
                    x0, layer_ws, caches, head, head_s, p, l, sk)
            float(tok[0])
            best = min(best, (time.perf_counter() - t0) / steps)
        return best

    splits = [s for s in _axis_splits(world, hq, hkv) if bs % s[0] == 0]
    print(f"# sweeping {len(splits)} axis split(s) of {world} device(s):"
          f" {splits}", file=sys.stderr)
    best_fused = None
    for dp, tp in splits:
        devs = np.array(jax.devices()[:world]).reshape(dp, tp)
        plan = ShardingPlan(Mesh(devs, ("dp", "tp")))
        bs_l, pages_l = bs // dp, num_pages // dp
        rng = np.random.default_rng(0)
        pt0 = np.stack([
            rng.permutation(pages_l)[:pages_per_req]
            + (b // bs_l) * pages_l for b in range(bs)]).astype(np.int32)
        validate_dp_page_table(pt0, num_pages, plan)
        cost = costmodel.serving_step_sharded(bs, ctx, L, dp=dp, tp=tp,
                                              **shape)
        pred = costmodel.predict_step_seconds(
            cost, hbm_tbps=spec_hw.hbm_tbps,
            peak_tflops=spec_hw.peak_tflops(cost.dtype),
            ici_gbps=spec_hw.ici_gbps)
        for name, build in (
            ("fused", lambda: build_sharded_fused_step(
                spec, plan, num_layers=L)),
            ("per_op", lambda: build_sharded_per_op_step(spec, plan)),
        ):
            try:
                t = wall(build(), pt0)
            except Exception as e:  # noqa: BLE001 - one split must not
                print(f"# {plan.mesh_axes}/{name} FAILED "  # cost the rest
                      f"{type(e).__name__}: "
                      f"{(str(e).splitlines() or ['?'])[0][:120]}",
                      file=sys.stderr)
                continue
            row = _emit_row(**roofline.stamp_row(
                dict(phase="serving_sharded", model="llama70b_int8",
                     variant=name, bs=bs, ctx=ctx, layers=L,
                     us_step=round(t * 1e6, 1),
                     pred_us=round(pred * 1e6, 1)),
                cost, t, spec_hw, step_mode=name,
                mesh_axes=plan.mesh_axes))
            print(f"# {plan.mesh_axes:10s} {name:7s} "
                  f"{t * 1e6:10.1f} us/step (pred {pred * 1e6:9.1f}) "
                  f"quality={row.get('quality')}", file=sys.stderr)
            if name == "fused" and (best_fused is None
                                    or t < best_fused[0]):
                best_fused = (t, dp, tp)

    if emit_config and best_fused is not None:
        _, dp, tp = best_fused
        key_str = f"{world}_{hidden}_{hq}_{hkv}"
        section = {"parallel": {
            "comment": f"Measured winner of benchmarks/"
                       f"bench_sharded_step.py on {spec_hw.name} "
                       f"({world} devices).",
            "tactics": {
                f"parallel.tp|{key_str}": tp,
                f"parallel.dp|{key_str}": dp,
                f"parallel.ep|{key_str}": 1,
            },
        }}
        print(json.dumps(section, indent=1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny dims on an 8-virtual-device CPU mesh")
    ap.add_argument("--emit-config", action="store_true",
                    help="print a tuning_configs 'parallel' section "
                         "with the measured winner")
    args = ap.parse_args()
    if args.smoke and "jax" not in sys.modules:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from flashinfer_tpu.env import apply_platform_from_env

    apply_platform_from_env()
    sweep(args.smoke, args.emit_config)
    return 0


if __name__ == "__main__":
    sys.exit(main())
