"""Unified benchmark harness.

TPU re-design of the reference's ``benchmarks/flashinfer_benchmark.py`` +
``benchmarks/routines/`` (SURVEY §2.7/L5): one CLI spanning the op families,
emitting CSV rows of latency / TFLOPS / TB/s.

    python benchmarks/flashinfer_benchmark.py --routine decode \
        --batch 64 --ctx 4096 [--csv out.csv]
    python benchmarks/flashinfer_benchmark.py --routine all --quick

Routines: decode (paged batch decode), prefill (causal ragged), gemm
(bf16 square), moe (fused MoE forward), sampling (top-k/top-p over 128k
vocab).  Runs on whatever backend jax selects (TPU on hardware; CPU with
the xla backend elsewhere — pass --quick for CI-sized shapes).
"""

import argparse
import csv
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _bench(args, fn, *operands):
    """Slope-fit device timing (see testing.bench_fn_device) — the plain
    per-call timer reports dispatch overhead, not kernel time, through the
    axon tunnel.  The whole first timing call (which contains the Mosaic
    compile) runs under ``compile_guard.guarded`` so an ad-hoc routine can
    never wedge the chip outside the quarantine protocol (the round-2
    escape path)."""
    from flashinfer_tpu import compile_guard
    from flashinfer_tpu.testing import bench_fn_device

    hi = max(args.iters, 3)
    lo = max(hi // 4, 1)
    # fingerprint by the bench fn's source location + operand signature:
    # stable across reruns/routine subsets (a call-order counter would make
    # persisted quarantine entries miss on any differently-ordered rerun)
    code = getattr(fn, "__code__", None)
    fn_id = (f"{getattr(code, 'co_filename', '?')}:"
             f"{getattr(code, 'co_firstlineno', 0)}")
    statics = (fn_id, args.routine,
               tuple((getattr(o, "shape", None), str(getattr(o, "dtype", "")))
                     for o in operands))
    return compile_guard.guarded(
        f"flashinfer_benchmark.{args.routine}", statics,
        lambda: bench_fn_device(fn, *operands, iters_low=lo, iters_high=hi,
                                repeats=2),
    )


def _rows_decode(args):
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi
    from flashinfer_tpu.testing import attention_bytes

    dtype = jnp.bfloat16
    hq, hkv, hd, ps = args.num_qo_heads, args.num_kv_heads, args.head_dim, 16
    for bs in args.batch:
        for ctx in args.ctx:
            ppr = ctx // ps
            npages = bs * ppr
            indptr = np.arange(bs + 1, dtype=np.int32) * ppr
            idx = np.random.default_rng(0).permutation(npages).astype(np.int32)
            last = np.full((bs,), ps, np.int32)
            kc = jax.random.normal(jax.random.PRNGKey(0), (npages, hkv, ps, hd), dtype)
            vc = jax.random.normal(jax.random.PRNGKey(1), (npages, hkv, ps, hd), dtype)
            q = jax.random.normal(jax.random.PRNGKey(2), (bs, hq, hd), dtype)
            w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="HND")
            w.plan(indptr, idx, last, hq, hkv, hd, ps)
            t = _bench(args, lambda qq, kk, vv: w.run(qq, (kk, vv)), q, kc, vc)
            tb = bs * attention_bytes(1, ctx, hq, hkv, hd, hd, 2) / t / 1e12
            yield dict(routine="decode", config=f"bs{bs}_ctx{ctx}",
                       latency_us=t * 1e6, tbps=tb, tflops="")


def _rows_prefill(args):
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi
    from flashinfer_tpu.testing import attention_flops

    dtype = jnp.bfloat16
    hq, hkv, hd = args.num_qo_heads, args.num_kv_heads, args.head_dim
    for ctx in args.ctx:
        q = jax.random.normal(jax.random.PRNGKey(0), (ctx, hq, hd), dtype)
        k = jax.random.normal(jax.random.PRNGKey(1), (ctx, hkv, hd), dtype)
        v = jax.random.normal(jax.random.PRNGKey(2), (ctx, hkv, hd), dtype)
        t = _bench(
            args,
            lambda qq, kk, vv: fi.single_prefill_with_kv_cache(qq, kk, vv, causal=True),
            q, k, v,
        )
        fl = attention_flops(ctx, ctx, hq, hd, hd, causal=True)
        yield dict(routine="prefill", config=f"ctx{ctx}",
                   latency_us=t * 1e6, tbps="", tflops=fl / t / 1e12)


def _rows_gemm(args):
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi

    for n in args.gemm_sizes:
        a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
        b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16)
        t = _bench(args, lambda aa, bb: fi.mm_bf16(aa, bb), a, b)
        yield dict(routine="gemm_bf16", config=f"{n}x{n}x{n}",
                   latency_us=t * 1e6, tbps="", tflops=2 * n**3 / t / 1e12)


def _rows_moe(args):
    import jax
    import jax.numpy as jnp
    from flashinfer_tpu.fused_moe import fused_moe, route_renormalize

    T, E, K = args.moe_tokens, args.moe_experts, 2
    h, inter = args.moe_hidden, 4 * args.moe_hidden
    x = jax.random.normal(jax.random.PRNGKey(0), (T, h), jnp.bfloat16)
    w1 = jax.random.normal(jax.random.PRNGKey(1), (E, h, 2 * inter), jnp.bfloat16)
    w2 = jax.random.normal(jax.random.PRNGKey(2), (E, inter, h), jnp.bfloat16)
    logits = jax.random.normal(jax.random.PRNGKey(3), (T, E))
    wts, ids = route_renormalize(logits, K)
    t = _bench(args, lambda xx, ww1, ww2, wt, ii: fused_moe(xx, ww1, ww2, wt, ii, E),
               x, w1, w2, wts, ids)
    fl = 2 * T * K * (h * 2 * inter + inter * h)
    yield dict(routine="moe", config=f"T{T}_E{E}_h{h}",
               latency_us=t * 1e6, tbps="", tflops=fl / t / 1e12)


def _rows_sampling(args):
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi

    bs, vocab = args.sampling_batch, args.vocab
    logits = jax.random.normal(jax.random.PRNGKey(0), (bs, vocab))
    key = jax.random.PRNGKey(1)
    t = _bench(
        args,
        lambda lg, kk: fi.top_k_top_p_sampling_from_logits(lg, kk, 40, 0.9),
        logits, key,
    )
    yield dict(routine="sampling_topk_topp", config=f"bs{bs}_v{vocab}",
               latency_us=t * 1e6, tbps="", tflops="")


def _rows_mamba(args):
    """SSM routines (reference bench: mamba/SSD kernels): chunked SSD
    prefill (Mamba-2 shapes) + the bandwidth-bound selective-state decode
    step at serving batch."""
    import jax
    import jax.numpy as jnp
    from flashinfer_tpu.mamba import (
        mamba_chunk_scan_combined, selective_state_update,
    )

    B, L = args.mamba_batch, args.mamba_seqlen
    H, dim, G, dstate = args.mamba_heads, 64, 1, 128
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (B, L, H, dim), jnp.bfloat16)
    dt = jax.nn.softplus(
        jax.random.normal(jax.random.fold_in(key, 1), (B, L, H)) - 4
    )
    A = -jnp.exp(jax.random.normal(jax.random.fold_in(key, 2), (H,)))
    Bm = jax.random.normal(jax.random.fold_in(key, 3), (B, L, G, dstate),
                           jnp.bfloat16)
    Cm = jax.random.normal(jax.random.fold_in(key, 4), (B, L, G, dstate),
                           jnp.bfloat16)
    t = _bench(
        args,
        lambda xx, dd, bb, cc: mamba_chunk_scan_combined(xx, dd, A, bb, cc)[0],
        x, dt, Bm, Cm,
    )
    fl = 6 * B * L * H * dim * dstate  # score + state matmul pairs
    yield dict(routine="mamba_prefill", config=f"B{B}_L{L}_H{H}",
               latency_us=t * 1e6, tbps="", tflops=fl / t / 1e12)

    state = jax.random.normal(key, (B, H, dim, dstate), jnp.float32)
    xd = jax.random.normal(jax.random.fold_in(key, 5), (B, H, dim), jnp.bfloat16)
    dtd = jnp.ones((B, H, dim), jnp.float32) * 0.1
    Ad = -jnp.ones((H, dim, dstate), jnp.float32)
    Bd = jax.random.normal(jax.random.fold_in(key, 6), (B, G, dstate), jnp.bfloat16)
    td = _bench(
        args,
        lambda ss, xx, bb, cc: selective_state_update(ss, xx, dtd, Ad, bb, cc)[0],
        state, xd, Bd, Cm[:, 0],
    )
    state_bytes = 2 * B * H * dim * dstate * 4  # read + write f32 state
    yield dict(routine="mamba_decode", config=f"B{B}_H{H}",
               latency_us=td * 1e6, tbps=state_bytes / td / 1e12, tflops="")


ROUTINES = {
    "decode": _rows_decode,
    "prefill": _rows_prefill,
    "gemm": _rows_gemm,
    "moe": _rows_moe,
    "sampling": _rows_sampling,
    "mamba": _rows_mamba,
}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--routine", default="all",
                   choices=["all"] + sorted(ROUTINES))
    p.add_argument("--batch", type=int, nargs="+", default=[64])
    p.add_argument("--ctx", type=int, nargs="+", default=[4096])
    p.add_argument("--num-qo-heads", type=int, default=32)
    p.add_argument("--num-kv-heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--gemm-sizes", type=int, nargs="+", default=[4096])
    p.add_argument("--moe-tokens", type=int, default=512)
    p.add_argument("--moe-experts", type=int, default=32)
    p.add_argument("--moe-hidden", type=int, default=1024)
    p.add_argument("--sampling-batch", type=int, default=64)
    p.add_argument("--vocab", type=int, default=128256)
    p.add_argument("--mamba-batch", type=int, default=8)
    p.add_argument("--mamba-seqlen", type=int, default=4096)
    p.add_argument("--mamba-heads", type=int, default=24)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--quick", action="store_true",
                   help="CI-sized shapes (CPU-friendly)")
    p.add_argument("--csv", default=None)
    args = p.parse_args(argv)
    if args.quick:
        args.batch, args.ctx = [2], [256]
        args.gemm_sizes = [256]
        args.moe_tokens, args.moe_experts, args.moe_hidden = 16, 4, 64
        args.sampling_batch, args.vocab = 4, 1024
        args.mamba_batch, args.mamba_seqlen, args.mamba_heads = 1, 128, 2
        args.iters = 3

    names = sorted(ROUTINES) if args.routine == "all" else [args.routine]
    rows = []
    for name in names:
        for row in ROUTINES[name](args):
            rows.append(row)
            print(
                f"{row['routine']:>18} {row['config']:>16} "
                f"{row['latency_us']:10.1f} us"
                + (f"  {row['tbps']:.3f} TB/s" if row["tbps"] != "" else "")
                + (f"  {row['tflops']:.2f} TFLOPS" if row["tflops"] != "" else "")
            )
    if args.csv:
        with open(args.csv, "w", newline="") as f:
            wr = csv.DictWriter(
                f, fieldnames=["routine", "config", "latency_us", "tbps", "tflops"]
            )
            wr.writeheader()
            wr.writerows(rows)
        print(f"wrote {len(rows)} rows to {args.csv}")
    return 0


if __name__ == "__main__":
    import jax

    from flashinfer_tpu.env import apply_platform_from_env

    if "--cpu" in sys.argv:
        os.environ["JAX_PLATFORMS"] = "cpu"
    apply_platform_from_env()
    sys.exit(main([a for a in sys.argv[1:] if a != "--cpu"]))
