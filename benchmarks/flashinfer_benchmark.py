"""Unified benchmark harness.

TPU re-design of the reference's ``benchmarks/flashinfer_benchmark.py`` +
``benchmarks/routines/`` (SURVEY §2.7/L5): one CLI spanning the op families,
emitting CSV rows of latency / TFLOPS / TB/s.

    python benchmarks/flashinfer_benchmark.py --routine decode \
        --batch 64 --ctx 4096 [--csv out.csv]
    python benchmarks/flashinfer_benchmark.py --routine all --quick

Routines: decode (paged batch decode), prefill (causal ragged), gemm
(bf16 square), moe (fused MoE forward), sampling (top-k/top-p over 128k
vocab), mamba (SSD prefill + selective-state-update decode), gdn
(GDN/KDA prefill + decode steps), norm (rmsnorm family), rope,
quantization (fp8/int8/fp4), sparse_attention (BSR), mla (paged MLA
decode at DeepSeek shapes).  Runs on whatever backend jax selects (TPU
on hardware; CPU with the xla backend elsewhere — pass --quick for
CI-sized shapes).
"""

import argparse
import csv
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _bench(args, fn, *operands):
    """Slope-fit device timing (see testing.bench_fn_device) — the plain
    per-call timer reports dispatch overhead, not kernel time, through the
    axon tunnel.  The whole first timing call (which contains the Mosaic
    compile) runs under ``compile_guard.guarded`` so an ad-hoc routine can
    never wedge the chip outside the quarantine protocol (the round-2
    escape path)."""
    from flashinfer_tpu import compile_guard
    from flashinfer_tpu.testing import bench_fn_device

    hi = max(args.iters, 3)
    lo = max(hi // 4, 1)
    # fingerprint by the bench fn's source location + operand signature:
    # stable across reruns/routine subsets (a call-order counter would make
    # persisted quarantine entries miss on any differently-ordered rerun)
    code = getattr(fn, "__code__", None)
    fn_id = (f"{getattr(code, 'co_filename', '?')}:"
             f"{getattr(code, 'co_firstlineno', 0)}")
    statics = (fn_id, args.routine,
               tuple((getattr(o, "shape", None), str(getattr(o, "dtype", "")))
                     for o in operands))
    return compile_guard.guarded(
        f"flashinfer_benchmark.{args.routine}", statics,
        lambda: bench_fn_device(fn, *operands, iters_low=lo, iters_high=hi,
                                repeats=2),
    )


def _rows_decode(args):
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi
    from flashinfer_tpu.testing import attention_bytes

    dtype = jnp.bfloat16
    hq, hkv, hd, ps = args.num_qo_heads, args.num_kv_heads, args.head_dim, 16
    for bs in args.batch:
        for ctx in args.ctx:
            ppr = ctx // ps
            npages = bs * ppr
            indptr = np.arange(bs + 1, dtype=np.int32) * ppr
            idx = np.random.default_rng(0).permutation(npages).astype(np.int32)
            last = np.full((bs,), ps, np.int32)
            kc = jax.random.normal(jax.random.PRNGKey(0), (npages, hkv, ps, hd), dtype)
            vc = jax.random.normal(jax.random.PRNGKey(1), (npages, hkv, ps, hd), dtype)
            q = jax.random.normal(jax.random.PRNGKey(2), (bs, hq, hd), dtype)
            w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="HND")
            w.plan(indptr, idx, last, hq, hkv, hd, ps)
            t = _bench(args, lambda qq, kk, vv: w.run(qq, (kk, vv)), q, kc, vc)
            tb = bs * attention_bytes(1, ctx, hq, hkv, hd, hd, 2) / t / 1e12
            yield dict(routine="decode", config=f"bs{bs}_ctx{ctx}",
                       latency_us=t * 1e6, tbps=tb, tflops="")


def _rows_prefill(args):
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi
    from flashinfer_tpu.testing import attention_flops

    dtype = jnp.bfloat16
    hq, hkv, hd = args.num_qo_heads, args.num_kv_heads, args.head_dim
    for ctx in args.ctx:
        q = jax.random.normal(jax.random.PRNGKey(0), (ctx, hq, hd), dtype)
        k = jax.random.normal(jax.random.PRNGKey(1), (ctx, hkv, hd), dtype)
        v = jax.random.normal(jax.random.PRNGKey(2), (ctx, hkv, hd), dtype)
        t = _bench(
            args,
            lambda qq, kk, vv: fi.single_prefill_with_kv_cache(qq, kk, vv, causal=True),
            q, k, v,
        )
        fl = attention_flops(ctx, ctx, hq, hd, hd, causal=True)
        yield dict(routine="prefill", config=f"ctx{ctx}",
                   latency_us=t * 1e6, tbps="", tflops=fl / t / 1e12)


def _rows_gemm(args):
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi

    for n in args.gemm_sizes:
        a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
        b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16)
        t = _bench(args, lambda aa, bb: fi.mm_bf16(aa, bb), a, b)
        yield dict(routine="gemm_bf16", config=f"{n}x{n}x{n}",
                   latency_us=t * 1e6, tbps="", tflops=2 * n**3 / t / 1e12)


def _rows_moe(args):
    import jax
    import jax.numpy as jnp
    from flashinfer_tpu.fused_moe import fused_moe, route_renormalize

    T, E, K = args.moe_tokens, args.moe_experts, 2
    h, inter = args.moe_hidden, 4 * args.moe_hidden
    x = jax.random.normal(jax.random.PRNGKey(0), (T, h), jnp.bfloat16)
    w1 = jax.random.normal(jax.random.PRNGKey(1), (E, h, 2 * inter), jnp.bfloat16)
    w2 = jax.random.normal(jax.random.PRNGKey(2), (E, inter, h), jnp.bfloat16)
    logits = jax.random.normal(jax.random.PRNGKey(3), (T, E))
    wts, ids = route_renormalize(logits, K)
    t = _bench(args, lambda xx, ww1, ww2, wt, ii: fused_moe(xx, ww1, ww2, wt, ii, E),
               x, w1, w2, wts, ids)
    fl = 2 * T * K * (h * 2 * inter + inter * h)
    yield dict(routine="moe", config=f"T{T}_E{E}_h{h}",
               latency_us=t * 1e6, tbps="", tflops=fl / t / 1e12)


def _rows_sampling(args):
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi

    bs, vocab = args.sampling_batch, args.vocab
    logits = jax.random.normal(jax.random.PRNGKey(0), (bs, vocab))
    key = jax.random.PRNGKey(1)
    t = _bench(
        args,
        lambda lg, kk: fi.top_k_top_p_sampling_from_logits(lg, kk, 40, 0.9),
        logits, key,
    )
    yield dict(routine="sampling_topk_topp", config=f"bs{bs}_v{vocab}",
               latency_us=t * 1e6, tbps="", tflops="")


def _rows_mamba(args):
    """SSM routines (reference bench: mamba/SSD kernels): chunked SSD
    prefill (Mamba-2 shapes) + the bandwidth-bound selective-state decode
    step at serving batch."""
    import jax
    import jax.numpy as jnp
    from flashinfer_tpu.mamba import (
        mamba_chunk_scan_combined, selective_state_update,
    )

    B, L = args.mamba_batch, args.mamba_seqlen
    H, dim, G, dstate = args.mamba_heads, 64, 1, 128
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (B, L, H, dim), jnp.bfloat16)
    dt = jax.nn.softplus(
        jax.random.normal(jax.random.fold_in(key, 1), (B, L, H)) - 4
    )
    A = -jnp.exp(jax.random.normal(jax.random.fold_in(key, 2), (H,)))
    Bm = jax.random.normal(jax.random.fold_in(key, 3), (B, L, G, dstate),
                           jnp.bfloat16)
    Cm = jax.random.normal(jax.random.fold_in(key, 4), (B, L, G, dstate),
                           jnp.bfloat16)
    t = _bench(
        args,
        lambda xx, dd, bb, cc: mamba_chunk_scan_combined(xx, dd, A, bb, cc)[0],
        x, dt, Bm, Cm,
    )
    fl = 6 * B * L * H * dim * dstate  # score + state matmul pairs
    yield dict(routine="mamba_prefill", config=f"B{B}_L{L}_H{H}",
               latency_us=t * 1e6, tbps="", tflops=fl / t / 1e12)

    state = jax.random.normal(key, (B, H, dim, dstate), jnp.float32)
    xd = jax.random.normal(jax.random.fold_in(key, 5), (B, H, dim), jnp.bfloat16)
    dtd = jnp.ones((B, H, dim), jnp.float32) * 0.1
    Ad = -jnp.ones((H, dim, dstate), jnp.float32)
    Bd = jax.random.normal(jax.random.fold_in(key, 6), (B, G, dstate), jnp.bfloat16)
    td = _bench(
        args,
        lambda ss, xx, bb, cc: selective_state_update(ss, xx, dtd, Ad, bb, cc)[0],
        state, xd, Bd, Cm[:, 0],
    )
    state_bytes = 2 * B * H * dim * dstate * 4  # read + write f32 state
    yield dict(routine="mamba_decode", config=f"B{B}_H{H}",
               latency_us=td * 1e6, tbps=state_bytes / td / 1e12, tflops="")


def _rows_gdn(args):
    """GDN + KDA chunked prefill and decode steps (reference
    routines/gdn.py)."""
    import jax
    import jax.numpy as jnp
    from flashinfer_tpu.gdn import (
        gdn_chunk_prefill, gdn_decode_step, kda_chunk_prefill,
        kda_decode_step,
    )

    B, L, H = args.mamba_batch, args.mamba_seqlen, args.mamba_heads
    dk = dv = 32 if args.quick else 128
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, L, H, dk), jnp.float32) * 0.3
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, L, H, dk)) * 0.3
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, L, H, dv))
    beta = jax.nn.sigmoid(
        jax.random.normal(jax.random.fold_in(key, 3), (B, L, H)))
    a_g = jnp.exp(-0.05 * jax.random.uniform(
        jax.random.fold_in(key, 4), (B, L, H)))
    a_k = jnp.exp(-0.05 * jax.random.uniform(
        jax.random.fold_in(key, 5), (B, L, H, dk)))
    flops = 2 * B * L * H * dk * dv * 2
    # backend="xla" pins the reference form: auto resolves to the pallas
    # kernel on these eligible shapes since the 2026-07-31 default flip,
    # and these rows are banked against XLA-form history
    for name, fn, aa in (
        ("gdn_prefill",
         lambda *a: gdn_chunk_prefill(*a, backend="xla")[0], a_g),
        ("kda_prefill",
         lambda *a: kda_chunk_prefill(*a, backend="xla")[0], a_k),
    ):
        t = _bench(args, fn, q, k, v, aa, beta)
        yield dict(routine=name, config=f"B{B}_L{L}_H{H}",
                   latency_us=t * 1e6, tbps="", tflops=flops / t / 1e12)
    s = jax.random.normal(key, (B, H, dk, dv), jnp.float32)
    state_bytes = 2 * B * H * dk * dv * 4
    # bench the WHOLE (o, new_state) tuple: selecting [1] would let XLA
    # dead-code-eliminate the output einsum (o depends on the state, not
    # vice versa) and under-report the step
    for name, fn, aa in (
        ("gdn_decode", gdn_decode_step, a_g[:, 0]),
        ("kda_decode", kda_decode_step, a_k[:, 0]),
    ):
        t = _bench(args, fn, s, q[:, 0], k[:, 0], v[:, 0], aa, beta[:, 0])
        yield dict(routine=name, config=f"B{B}_H{H}",
                   latency_us=t * 1e6, tbps=state_bytes / t / 1e12,
                   tflops="")


def _rows_norm(args):
    """rmsnorm family (reference routines/norm.py)."""
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi

    h = 256 if args.quick else 8192
    for tname, tokens in (("small", 128 if args.quick else 1024),
                          ("large", 512 if args.quick else 16384)):
        x = jax.random.normal(jax.random.PRNGKey(0), (tokens, h),
                              jnp.bfloat16)
        r = jax.random.normal(jax.random.PRNGKey(1), (tokens, h),
                              jnp.bfloat16)
        w = jnp.ones((h,), jnp.bfloat16)
        gbytes = 2 * tokens * h * 2
        t = _bench(args, lambda xx, ww: fi.rmsnorm(xx, ww), x, w)
        yield dict(routine="rmsnorm", config=f"{tname}_t{tokens}_h{h}",
                   latency_us=t * 1e6, tbps=gbytes / t / 1e12, tflops="")
        t = _bench(args, lambda xx, rr, ww: fi.fused_add_rmsnorm(xx, rr, ww),
                   x, r, w)
        yield dict(routine="fused_add_rmsnorm",
                   config=f"{tname}_t{tokens}_h{h}",
                   latency_us=t * 1e6, tbps=2 * gbytes / t / 1e12, tflops="")


def _rows_rope(args):
    """RoPE family (reference routines/rope.py)."""
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi

    hq, hkv, hd = args.num_qo_heads, args.num_kv_heads, args.head_dim
    tokens = 256 if args.quick else 8192
    q = jax.random.normal(jax.random.PRNGKey(0), (tokens, hq, hd),
                          jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (tokens, hkv, hd),
                          jnp.bfloat16)
    pos = jnp.arange(tokens, dtype=jnp.int32)
    gbytes = 2 * tokens * (hq + hkv) * hd * 2
    t = _bench(args, lambda qq, kk, pp: fi.apply_rope_pos_ids(qq, kk, pp),
               q, k, pos)
    yield dict(routine="rope", config=f"t{tokens}_h{hq}/{hkv}",
               latency_us=t * 1e6, tbps=gbytes / t / 1e12, tflops="")


def _rows_quantization(args):
    """Quantize family (reference routines/quantization.py)."""
    import jax
    import jax.numpy as jnp
    from flashinfer_tpu.quantization import (
        quantize_fp4, quantize_fp8_per_tensor, quantize_int8,
    )

    m = 256 if args.quick else 8192
    k = 256 if args.quick else 4096
    x = jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.bfloat16)
    gbytes = m * k * 3  # read bf16 + write ~1B
    for name, fn in (
        ("quant_fp8", lambda xx: quantize_fp8_per_tensor(xx)[0]),
        ("quant_int8", lambda xx: quantize_int8(xx)[0]),
        ("quant_fp4", lambda xx: quantize_fp4(xx)[0]),
    ):
        t = _bench(args, fn, x)
        yield dict(routine=name, config=f"{m}x{k}",
                   latency_us=t * 1e6, tbps=gbytes / t / 1e12, tflops="")


def _rows_sparse_attention(args):
    """Block-sparse attention (reference routines/sparse_attention.py)."""
    import numpy as _np
    import jax
    import jax.numpy as jnp
    import flashinfer_tpu as fi
    from flashinfer_tpu.testing import attention_flops

    hd = args.head_dim
    n = 512 if args.quick else 4096
    R = C = 64
    MB, NB = n // R, n // C
    rng = _np.random.default_rng(0)
    # ~25%-dense random BSR mask
    mask = rng.random((MB, NB)) < 0.25
    _np.fill_diagonal(mask, True)
    indptr = _np.zeros(MB + 1, _np.int32)
    indices = []
    for i in range(MB):
        cols = _np.nonzero(mask[i])[0]
        indices.extend(cols)
        indptr[i + 1] = len(indices)
    q = jax.random.normal(jax.random.PRNGKey(0), (n, 1, hd), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (n, 1, hd), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (n, 1, hd), jnp.bfloat16)
    w = fi.BlockSparseAttentionWrapper()
    w.plan(_np.asarray(indptr), _np.asarray(indices, _np.int32), n, n,
           R, C, 1, 1, hd)
    t = _bench(args, lambda qq, kk, vv: w.run(qq, kk, vv), q, k, v)
    density = mask.mean()
    fl = attention_flops(n, n, 1, hd, hd, causal=False) * density
    yield dict(routine="sparse_attention",
               config=f"n{n}_{R}x{C}_d{density:.2f}",
               latency_us=t * 1e6, tbps="", tflops=fl / t / 1e12)


def _rows_mla(args):
    """MLA paged decode (reference bench_deepseek_mla.py shapes)."""
    import jax
    import jax.numpy as jnp
    from flashinfer_tpu.ops.mla_decode import (
        mla_paged_decode_attention, xla_mla_paged_decode,
    )
    from flashinfer_tpu.utils import is_tpu

    rank, rope, ps = (64, 64, 8) if args.quick else (512, 64, 16)
    H = 4 if args.quick else 128
    for bs in args.batch:
        for ctx in args.ctx:
            ppr = ctx // ps
            npages = bs * ppr
            qn = jax.random.normal(jax.random.PRNGKey(0), (bs, H, rank),
                                   jnp.bfloat16)
            qp = jax.random.normal(jax.random.PRNGKey(1), (bs, H, rope),
                                   jnp.bfloat16)
            ckv = jax.random.normal(jax.random.PRNGKey(2),
                                    (npages, ps, rank), jnp.bfloat16)
            kpe = jax.random.normal(jax.random.PRNGKey(3),
                                    (npages, ps, 128), jnp.bfloat16)
            # permuted pages, like _rows_decode: contiguous tables would
            # benchmark an unrealistically sequential gather pattern
            table = jnp.asarray(
                np.random.default_rng(0).permutation(npages)
                .reshape(bs, ppr).astype(np.int32)
            )
            lens = jnp.full((bs,), ctx, jnp.int32)
            fn = (mla_paged_decode_attention if is_tpu()
                  else xla_mla_paged_decode)
            sm = 1.0 / float(128 + rope) ** 0.5
            t = _bench(
                args,
                lambda a, b, c, d: fn(a, b, c, d, table, lens, sm_scale=sm),
                qn, qp, ckv, kpe,
            )
            gbytes = bs * ctx * (rank + rope) * 2  # cache read per step
            yield dict(routine="mla_decode", config=f"bs{bs}_ctx{ctx}",
                       latency_us=t * 1e6, tbps=gbytes / t / 1e12,
                       tflops="")


ROUTINES = {
    "decode": _rows_decode,
    "prefill": _rows_prefill,
    "gemm": _rows_gemm,
    "moe": _rows_moe,
    "sampling": _rows_sampling,
    "mamba": _rows_mamba,
    "gdn": _rows_gdn,
    "norm": _rows_norm,
    "rope": _rows_rope,
    "quantization": _rows_quantization,
    "sparse_attention": _rows_sparse_attention,
    "mla": _rows_mla,
}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--routine", default="all",
                   choices=["all"] + sorted(ROUTINES))
    p.add_argument("--batch", type=int, nargs="+", default=[64])
    p.add_argument("--ctx", type=int, nargs="+", default=[4096])
    p.add_argument("--num-qo-heads", type=int, default=32)
    p.add_argument("--num-kv-heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--gemm-sizes", type=int, nargs="+", default=[4096])
    p.add_argument("--moe-tokens", type=int, default=512)
    p.add_argument("--moe-experts", type=int, default=32)
    p.add_argument("--moe-hidden", type=int, default=1024)
    p.add_argument("--sampling-batch", type=int, default=64)
    p.add_argument("--vocab", type=int, default=128256)
    p.add_argument("--mamba-batch", type=int, default=8)
    p.add_argument("--mamba-seqlen", type=int, default=4096)
    p.add_argument("--mamba-heads", type=int, default=24)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--quick", action="store_true",
                   help="CI-sized shapes (CPU-friendly)")
    p.add_argument("--csv", default=None)
    args = p.parse_args(argv)
    if args.quick:
        args.batch, args.ctx = [2], [256]
        args.gemm_sizes = [256]
        args.moe_tokens, args.moe_experts, args.moe_hidden = 16, 4, 64
        args.sampling_batch, args.vocab = 4, 1024
        args.mamba_batch, args.mamba_seqlen, args.mamba_heads = 1, 128, 2
        args.iters = 3

    names = sorted(ROUTINES) if args.routine == "all" else [args.routine]
    rows = []
    for name in names:
        for row in ROUTINES[name](args):
            rows.append(row)
            print(
                f"{row['routine']:>18} {row['config']:>16} "
                f"{row['latency_us']:10.1f} us"
                + (f"  {row['tbps']:.3f} TB/s" if row["tbps"] != "" else "")
                + (f"  {row['tflops']:.2f} TFLOPS" if row["tflops"] != "" else "")
            )
    if args.csv:
        with open(args.csv, "w", newline="") as f:
            wr = csv.DictWriter(
                f, fieldnames=["routine", "config", "latency_us", "tbps", "tflops"]
            )
            wr.writeheader()
            wr.writerows(rows)
        print(f"wrote {len(rows)} rows to {args.csv}")
    return 0


if __name__ == "__main__":
    import jax

    from flashinfer_tpu.env import apply_platform_from_env

    if "--cpu" in sys.argv:
        os.environ["JAX_PLATFORMS"] = "cpu"
    apply_platform_from_env()
    sys.exit(main([a for a in sys.argv[1:] if a != "--cpu"]))
