"""Block-shape sweep harness for the pipelined fused prefill kernel.

The on-chip autotune surface for ISSUE 3's tentpole (c): sweeps the
``fused_prefill.blocks`` knob — (qo-tile ``block_q``, kv-chunk
``pages_per_chunk``) — across the paged chunked-prefill shape grid,
emits ``ROW {json}`` lines carrying the full block-config metadata, and
quality-stamps every row through ``obs.bench_audit.RowAuditor`` against
the BENCH_BANKED.md history (the same <0.35x implausibility rule as
bench.py).  Rows are roofline-stamped by the shared cost model from
each candidate's own plan stats, so ``tflops`` (and the ranking) count
*effective* work — a candidate can't win by padding — while
``pct_roofline`` vs ``effective_pct_roofline`` shows the waste
(docs/observability.md §"Roofline attribution").

Usage::

    python benchmarks/bench_prefill_blocks.py            # on-chip sweep
    python benchmarks/bench_prefill_blocks.py --smoke    # CPU interpret
    python benchmarks/bench_prefill_blocks.py --emit-config > prefill.json

``--emit-config`` prints a ready-to-paste ``"prefill"`` section for
``flashinfer_tpu/tuning_configs/<gen>.json`` with each shape's winner —
the step that graduates the shipped section from ``"seed": true`` to
measured (docs/performance.md walks the workflow).

Candidate ceiling note: chunk_tokens stays <= 256 (ppc <= 16 at page 16)
— each work unit unrolls 2 DMAs/page and 32 in-flight copies is the
on-chip-validated queue ceiling; ppc=32 would be the W002 queue-unroll
wedge class (see ops/paged_prefill.py kv_dmas).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable from any cwd (sys.path[0] is benchmarks/)
    sys.path.insert(0, _REPO)

_AUDITOR = None


def _emit_row(**kw):
    """One measurement, RowAuditor-stamped, parseable by orchestrators."""
    global _AUDITOR
    try:
        from flashinfer_tpu.obs import bench_audit

        if _AUDITOR is None:
            _AUDITOR = bench_audit.RowAuditor(
                bench_audit.load_banked_history(
                    os.path.join(_REPO, "BENCH_BANKED.md")))
        _AUDITOR.stamp(kw)
    except Exception as e:  # noqa: BLE001 - the audit must never cost a row
        print(f"# row audit failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    print("ROW " + json.dumps(kw), flush=True)
    return kw


def candidate_grid(page_size: int, smoke: bool):
    """(block_q, pages_per_chunk) candidates — the SAME grid the
    wrapper's in-run tuner explores (ops/paged_prefill.block_candidates,
    W002-safe chunk ceiling), so banked winners are always reproducible
    by runtime autotune."""
    if smoke:
        return [(32, 2), (64, 2), (64, 4)]
    from flashinfer_tpu.ops.paged_prefill import block_candidates

    return block_candidates(page_size)


def shape_grid(smoke: bool):
    """(bs, qlen, ctx, HQ, HKV, D, page_size) sweep shapes — the bench.py
    prefill phase configs plus the VERDICT next-round target cell."""
    if smoke:
        return [(2, 32, 64, 4, 2, 64, 8)]
    return [
        (8, 512, 4096, 32, 8, 128, 16),   # VERDICT target: >= 60 TFLOPS
        (2, 2048, 8192, 32, 8, 128, 16),
        (16, 256, 2048, 32, 8, 128, 16),
    ]


def sweep(smoke: bool, repeats: int):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu.ops.paged_prefill import (
        build_prefill_work_units, fused_paged_prefill,
    )
    from flashinfer_tpu.obs import costmodel, hwspec, roofline
    from flashinfer_tpu.testing import bench_fn_device
    from flashinfer_tpu import compile_guard

    chip = hwspec.current_spec()

    winners = {}
    for bs, qlen, ctx, HQ, HKV, D, PS in shape_grid(smoke):
        ppr = ctx // PS
        npages = bs * ppr
        rng = np.random.default_rng(0)
        key = jax.random.PRNGKey(0)
        kc = jax.random.normal(key, (npages, HKV, PS, D), jnp.bfloat16)
        vc = jax.random.normal(jax.random.fold_in(key, 1),
                               (npages, HKV, PS, D), jnp.bfloat16)
        q = jax.random.normal(jax.random.fold_in(key, 2),
                              (bs * qlen, HQ, D), jnp.bfloat16)
        qo_indptr = np.arange(bs + 1, dtype=np.int32) * qlen
        kv_page_indptr = np.arange(bs + 1, dtype=np.int32) * ppr
        kv_page_indices = rng.permutation(npages).astype(np.int32)
        kv_lens = np.full((bs,), ctx, np.int64)
        fused_key = "_".join(map(str, (
            bs, max(1 << (bs * qlen - 1).bit_length(), 128), HQ, HKV, D, PS,
        )))

        best = None
        for bq, ppc in candidate_grid(PS, smoke):
            plan_np = build_prefill_work_units(
                qo_indptr, kv_page_indptr, kv_page_indices, kv_lens,
                block_q=bq, pages_per_chunk=ppc, page_size=PS, causal=True,
            )
            statics = dict(
                num_units=plan_np.pop("num_units"),
                block_q=plan_np.pop("block_q"),
                pages_per_chunk=plan_np.pop("pages_per_chunk"),
            )
            stats = plan_np.pop("stats")
            plan = {k: jnp.asarray(v) for k, v in plan_np.items()}
            try:
                t = compile_guard.guarded(
                    "bench.prefill_blocks",
                    (bs, qlen, ctx, HQ, HKV, D, PS, bq, ppc),
                    lambda: bench_fn_device(
                        lambda qq, kk, vv: fused_paged_prefill(
                            qq, kk, vv, plan, sm_scale=D ** -0.5,
                            causal=True, **statics),
                        q, kc, vc, repeats=repeats,
                    ),
                )
            except Exception as e:  # noqa: BLE001 - one cell, not the sweep
                first = (str(e).splitlines() or ["?"])[0][:120]
                print(f"# blocks ({bq},{ppc}) FAILED "
                      f"{type(e).__name__}: {first}", file=sys.stderr)
                continue
            # shared cost model: launched work from THIS candidate's
            # plan stats, effective = attended tokens — `tflops` stays
            # the effective number so candidates with different padding
            # waste compare on useful work (and the stamped
            # effective_pct_roofline ranks them the same way)
            cost = costmodel.paged_prefill(
                bs, qlen, ctx, HQ, HKV, D, causal=True, stats=stats,
                block_q=bq, pages_per_chunk=ppc, page_size=PS)
            tflops = cost.effective_flops / t / 1e12
            row = _emit_row(**roofline.stamp_row(
                dict(phase="prefill_blocks", bs=bs, qlen=qlen, ctx=ctx,
                     block_q=bq, pages_per_chunk=ppc,
                     num_units=statics["num_units"],
                     units_pruned=stats["units_pruned"],
                     us=round(t * 1e6, 1), tflops=round(tflops, 2)),
                cost, t, chip))
            print(f"# blocks bs={bs} qlen={qlen} ctx={ctx} "
                  f"bq={bq:3d} ppc={ppc:2d}: {t*1e6:9.1f} us  "
                  f"{tflops:6.2f} TFLOP/s  [{row.get('quality', '?')}]",
                  file=sys.stderr)
            if row.get("quality") != "poison" and (
                    best is None or tflops > best[0]):
                best = (tflops, bq, ppc)
        if best is not None:
            winners[f"fused_prefill.blocks|{fused_key}"] = [best[1], best[2]]
    return winners


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes, interpret-safe (CPU CI)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--emit-config", action="store_true",
                    help="print a tuning_configs 'prefill' section with "
                         "each shape's winner on stdout")
    args = ap.parse_args()
    if not args.smoke:
        from flashinfer_tpu.env import apply_platform_from_env

        apply_platform_from_env()
    winners = sweep(args.smoke, args.repeats)
    if args.emit_config:
        print(json.dumps({"prefill": {
            "comment": "measured by benchmarks/bench_prefill_blocks.py "
                       "(replace the shipped seed section with this)",
            "seed": bool(args.smoke),
            "tactics": winners,
        }}, indent=1))
    else:
        print(json.dumps({"winners": winners}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
