"""Block-shape sweep harness for the pipelined fused prefill kernel.

The on-chip autotune surface for ISSUE 3's tentpole (c): sweeps the
``fused_prefill.blocks`` knob — (qo-tile ``block_q``, kv-chunk
``pages_per_chunk``) — across the paged chunked-prefill shape grid,
emits ``ROW {json}`` lines carrying the full block-config metadata, and
quality-stamps every row through ``obs.bench_audit.RowAuditor`` against
the BENCH_BANKED.md history (the same <0.35x implausibility rule as
bench.py).  Rows are roofline-stamped by the shared cost model from
each candidate's own plan stats, so ``tflops`` (and the ranking) count
*effective* work — a candidate can't win by padding — while
``pct_roofline`` vs ``effective_pct_roofline`` shows the waste
(docs/observability.md §"Roofline attribution").

Usage::

    python benchmarks/bench_prefill_blocks.py            # on-chip sweep
    python benchmarks/bench_prefill_blocks.py --smoke    # CPU interpret
    python benchmarks/bench_prefill_blocks.py --emit-config > prefill.json

``--emit-config`` prints a ready-to-paste ``"prefill"`` section for
``flashinfer_tpu/tuning_configs/<gen>.json`` with each shape's winner —
the step that graduates the shipped section from ``"seed": true`` to
measured (docs/performance.md walks the workflow).

Candidate ceiling note: chunk_tokens stays <= 256 (ppc <= 16 at page 16)
— each work unit unrolls 2 DMAs/page and 32 in-flight copies is the
on-chip-validated queue ceiling; ppc=32 would be the W002 queue-unroll
wedge class (see ops/paged_prefill.py kv_dmas).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable from any cwd (sys.path[0] is benchmarks/)
    sys.path.insert(0, _REPO)

_AUDITOR = None


def _emit_row(**kw):
    """One measurement, RowAuditor-stamped, parseable by orchestrators."""
    global _AUDITOR
    try:
        from flashinfer_tpu.obs import bench_audit

        if _AUDITOR is None:
            _AUDITOR = bench_audit.RowAuditor(
                bench_audit.load_banked_history(
                    os.path.join(_REPO, "BENCH_BANKED.md")))
        _AUDITOR.stamp(kw)
    except Exception as e:  # noqa: BLE001 - the audit must never cost a row
        print(f"# row audit failed: {type(e).__name__}: {e}",
              file=sys.stderr)
    print("ROW " + json.dumps(kw), flush=True)
    return kw


def candidate_grid(page_size: int, smoke: bool):
    """(block_q, pages_per_chunk) candidates — the SAME grid the
    wrapper's in-run tuner explores (ops/paged_prefill.block_candidates,
    W002-safe chunk ceiling), so banked winners are always reproducible
    by runtime autotune."""
    if smoke:
        return [(32, 2), (64, 2), (64, 4)]
    from flashinfer_tpu.ops.paged_prefill import block_candidates

    return block_candidates(page_size)


def shape_grid(smoke: bool):
    """(bs, qlen, ctx, HQ, HKV, D, page_size) sweep shapes — the bench.py
    prefill phase configs plus the VERDICT next-round target cell."""
    if smoke:
        return [(2, 32, 64, 4, 2, 64, 8)]
    return [
        (8, 512, 4096, 32, 8, 128, 16),   # VERDICT target: >= 60 TFLOPS
        (2, 2048, 8192, 32, 8, 128, 16),
        (16, 256, 2048, 32, 8, 128, 16),
    ]


def sweep(smoke: bool, repeats: int):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu.ops.paged_prefill import (
        build_prefill_work_units, fused_paged_prefill,
    )
    from flashinfer_tpu.obs import costmodel, hwspec, roofline
    from flashinfer_tpu.testing import bench_fn_device
    from flashinfer_tpu import compile_guard

    chip = hwspec.current_spec()

    winners = {}
    for bs, qlen, ctx, HQ, HKV, D, PS in shape_grid(smoke):
        ppr = ctx // PS
        npages = bs * ppr
        rng = np.random.default_rng(0)
        key = jax.random.PRNGKey(0)
        kc = jax.random.normal(key, (npages, HKV, PS, D), jnp.bfloat16)
        vc = jax.random.normal(jax.random.fold_in(key, 1),
                               (npages, HKV, PS, D), jnp.bfloat16)
        q = jax.random.normal(jax.random.fold_in(key, 2),
                              (bs * qlen, HQ, D), jnp.bfloat16)
        qo_indptr = np.arange(bs + 1, dtype=np.int32) * qlen
        kv_page_indptr = np.arange(bs + 1, dtype=np.int32) * ppr
        kv_page_indices = rng.permutation(npages).astype(np.int32)
        kv_lens = np.full((bs,), ctx, np.int64)
        fused_key = "_".join(map(str, (
            bs, max(1 << (bs * qlen - 1).bit_length(), 128), HQ, HKV, D, PS,
        )))

        best = None
        for bq, ppc in candidate_grid(PS, smoke):
            plan_np = build_prefill_work_units(
                qo_indptr, kv_page_indptr, kv_page_indices, kv_lens,
                block_q=bq, pages_per_chunk=ppc, page_size=PS, causal=True,
            )
            statics = dict(
                num_units=plan_np.pop("num_units"),
                block_q=plan_np.pop("block_q"),
                pages_per_chunk=plan_np.pop("pages_per_chunk"),
            )
            stats = plan_np.pop("stats")
            plan = {k: jnp.asarray(v) for k, v in plan_np.items()}
            try:
                t = compile_guard.guarded(
                    "bench.prefill_blocks",
                    (bs, qlen, ctx, HQ, HKV, D, PS, bq, ppc),
                    lambda: bench_fn_device(
                        lambda qq, kk, vv: fused_paged_prefill(
                            qq, kk, vv, plan, sm_scale=D ** -0.5,
                            causal=True, **statics),
                        q, kc, vc, repeats=repeats,
                    ),
                )
            except Exception as e:  # noqa: BLE001 - one cell, not the sweep
                first = (str(e).splitlines() or ["?"])[0][:120]
                print(f"# blocks ({bq},{ppc}) FAILED "
                      f"{type(e).__name__}: {first}", file=sys.stderr)
                continue
            # shared cost model: launched work from THIS candidate's
            # plan stats, effective = attended tokens — `tflops` stays
            # the effective number so candidates with different padding
            # waste compare on useful work (and the stamped
            # effective_pct_roofline ranks them the same way)
            cost = costmodel.paged_prefill(
                bs, qlen, ctx, HQ, HKV, D, causal=True, stats=stats,
                block_q=bq, pages_per_chunk=ppc, page_size=PS)
            tflops = cost.effective_flops / t / 1e12
            row = _emit_row(**roofline.stamp_row(
                dict(phase="prefill_blocks", bs=bs, qlen=qlen, ctx=ctx,
                     block_q=bq, pages_per_chunk=ppc,
                     num_units=statics["num_units"],
                     units_pruned=stats["units_pruned"],
                     us=round(t * 1e6, 1), tflops=round(tflops, 2)),
                cost, t, chip))
            print(f"# blocks bs={bs} qlen={qlen} ctx={ctx} "
                  f"bq={bq:3d} ppc={ppc:2d}: {t*1e6:9.1f} us  "
                  f"{tflops:6.2f} TFLOP/s  [{row.get('quality', '?')}]",
                  file=sys.stderr)
            if row.get("quality") != "poison" and (
                    best is None or tflops > best[0]):
                best = (tflops, bq, ppc)
        if best is not None:
            winners[f"fused_prefill.blocks|{fused_key}"] = [best[1], best[2]]
    return winners


def sweep_ingest(smoke: bool, repeats: int):
    """The fused-ingest A/B (ISSUE 14): for each sweep shape, time the
    fused RoPE+quantize-append+attention launch against the separate-op
    composition THROUGH THE SAME ``run_ingest`` entry point (the plan
    static flips the mode), emit paired rows carrying the
    ``fused_ingest`` identity stamp + the cost model's
    ``ingest_bytes_avoided`` measurement, and return per-shape
    ``prefill.fused_ingest`` winners for ``--emit-config``."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    import flashinfer_tpu as fi
    from flashinfer_tpu.obs import costmodel, hwspec, roofline
    from flashinfer_tpu.testing import bench_fn_device

    if smoke:
        # the wrapper's fused work-unit path (run_ingest's requirement)
        # needs the pallas tier; interpret mode serves it off-chip
        os.environ.setdefault("FLASHINFER_TPU_BACKEND", "pallas")
    chip = hwspec.current_spec()
    winners = {}
    for bs, qlen, ctx, HQ, HKV, D, PS in shape_grid(smoke):
        ppr = ctx // PS
        npages = bs * ppr
        rng = np.random.default_rng(0)
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (bs * qlen, HQ, D), jnp.bfloat16)
        k_new = jax.random.normal(jax.random.fold_in(key, 1),
                                  (bs * ctx, HKV, D), jnp.bfloat16)
        v_new = jax.random.normal(jax.random.fold_in(key, 2),
                                  (bs * ctx, HKV, D), jnp.bfloat16)
        kc = jnp.zeros((npages, HKV, PS, D), jnp.bfloat16)
        vc = jnp.zeros((npages, HKV, PS, D), jnp.bfloat16)
        qo_indptr = np.arange(bs + 1, dtype=np.int32) * qlen
        kv_page_indptr = np.arange(bs + 1, dtype=np.int32) * ppr
        kv_page_indices = rng.permutation(npages).astype(np.int32)
        fused_key = "_".join(map(str, (
            bs, max(1 << (bs * qlen - 1).bit_length(), 128), HQ, HKV, D,
            PS)))
        bd = costmodel.prefill_ingest_breakdown(
            bs * qlen, bs * ctx, HQ, HKV, D)
        pair = {}
        for mode in (True, False):
            w = fi.BatchPrefillWithPagedKVCacheWrapper(kv_layout="HND")
            w.plan(qo_indptr, kv_page_indptr, kv_page_indices,
                   np.full((bs,), PS, np.int32), HQ, HKV, D, PS,
                   causal=True, fused_ingest=mode)
            try:
                t = bench_fn_device(
                    lambda qq, kk, vv, kc_, vc_: w.run_ingest(
                        qq, kk, vv, (kc_, vc_)),
                    q, k_new, v_new, kc, vc, repeats=repeats)
            except Exception as e:  # noqa: BLE001 - one cell, not the sweep
                first = (str(e).splitlines() or ["?"])[0][:120]
                print(f"# ingest mode={mode} FAILED "
                      f"{type(e).__name__}: {first}", file=sys.stderr)
                continue
            if mode:
                cost = costmodel.prefill_ingest(
                    bs * qlen, bs * ctx, HQ, HKV, D,
                    stats=getattr(w, "_ingest_stats", None),
                    block_q=(w.fused_prefill_config or {}).get("block_q"),
                    pages_per_chunk=(w.fused_prefill_config or {}).get(
                        "pages_per_chunk"),
                    page_size=PS)
            else:
                # the separate row's wall covers rope + append +
                # attention: price the three-pass traffic (same op
                # family as the fused row), not attention alone
                cost = costmodel.prefill_ingest_separate(
                    bs * qlen, bs * ctx, HQ, HKV, D, causal=True)
            _emit_row(**roofline.stamp_row(
                dict(phase="prefill_blocks", kind="ingest_ab", bs=bs,
                     qlen=qlen, ctx=ctx, us=round(t * 1e6, 1),
                     tflops=round(cost.effective_flops / t / 1e12, 2)),
                cost, t, chip, fused_ingest=mode,
                ingest_bytes_avoided=bd["bytes_avoided"]))
            pair[mode] = t
            print(f"# ingest bs={bs} qlen={qlen} ctx={ctx} "
                  f"{'fused   ' if mode else 'separate'}: "
                  f"{t*1e6:9.1f} us  (pred avoided "
                  f"{bd['bytes_avoided']/1e6:.1f} MB)", file=sys.stderr)
        if True in pair and False in pair:
            win = pair[True] < pair[False] * 0.98
            winners[f"prefill.fused_ingest|{fused_key}"] = \
                "on" if win else "off"
            print(f"# ingest bs={bs} qlen={qlen} ctx={ctx} winner: "
                  f"{'fused' if win else 'separate'} "
                  f"({pair[False]/pair[True]:.2f}x)", file=sys.stderr)
    return winners


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes, interpret-safe (CPU CI)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--emit-config", action="store_true",
                    help="print a tuning_configs 'prefill' section with "
                         "each shape's winner on stdout")
    ap.add_argument("--sweep-ingest", action="store_true",
                    help="also A/B the fused prefill ingest "
                         "(prefill.fused_ingest) per shape")
    args = ap.parse_args()
    if not args.smoke:
        from flashinfer_tpu.env import apply_platform_from_env

        apply_platform_from_env()
    winners = sweep(args.smoke, args.repeats)
    ingest_winners = (sweep_ingest(args.smoke, args.repeats)
                      if args.sweep_ingest else {})
    if args.emit_config:
        out = {"prefill": {
            "comment": "measured by benchmarks/bench_prefill_blocks.py "
                       "(replace the shipped seed section with this)",
            "seed": bool(args.smoke),
            "tactics": winners,
        }}
        if ingest_winners:
            out["prefill_ingest"] = {
                "comment": "measured by benchmarks/bench_prefill_blocks"
                           ".py --sweep-ingest (replace the shipped "
                           "seed section with this)",
                "seed": bool(args.smoke),
                "tactics": ingest_winners,
            }
        print(json.dumps(out, indent=1))
    else:
        print(json.dumps({"winners": winners,
                          **({"ingest_winners": ingest_winners}
                             if ingest_winners else {})}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
