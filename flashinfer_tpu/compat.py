"""Reference top-level API compatibility surface.

Every public name importable as ``flashinfer.X`` resolves as
``flashinfer_tpu.X`` (reference ``flashinfer/__init__.py``), so a
migrating user finds the full surface.  Three classes of binding:

1. **Aliases** — the reference name for functionality this library ships
   under its own (TPU-idiomatic) name; the docstring says what it maps to.
2. **Thin composites** — small reference convenience ops expressed in a
   few lines over existing ops (fused norm+rope forms, quantize+act
   combos, routed-MoE entry points).
3. **Layout no-ops** — the reference's weight pre-shuffle/interleave
   helpers exist to feed specific CUDA kernel layouts; on TPU, XLA owns
   layout, so the semantically-correct implementation is identity
   (documented per function).

Vendor dtype mapping (gemm.py module docs): NVFP4/MXFP4 -> block-int4
storage, FP8/MXFP8 -> fp8 storage with bf16 or int8 MXU compute — the
v5e/v5p low-precision story.  ``test_compat_surface.py`` machine-checks
this file against the reference's ``__init__`` export list.
"""

from __future__ import annotations

import collections
import enum
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# --- submodule attributes the reference exposes (``from . import x``) ---
from flashinfer_tpu import gdn as gdn  # noqa: F401  (GDN/KDA kernels home)
from flashinfer_tpu import mamba as mamba  # noqa: F401
from flashinfer_tpu import mhc as mhc  # noqa: F401
from flashinfer_tpu import msa_ops as msa_ops  # noqa: F401
from flashinfer_tpu import topk as topk  # noqa: F401
from flashinfer_tpu import env as jit  # noqa: F401  # reference `jit` module
#   role (compile cache + artifacts) lives in env/compile_guard/aot here
from flashinfer_tpu import quantization as nvfp4_attention_sm120  # noqa: F401
#   arch-specific quantized-attention module collapses to the one
#   quantization home (Mosaic owns arch specialization)

from flashinfer_tpu.activation import silu_and_mul
from flashinfer_tpu.decode import BatchDecodeWithPagedKVCacheWrapper
from flashinfer_tpu.fused_moe import (
    MoE,
    RoutingMethodType,  # noqa: F401  (reference top-level enum)
    fused_moe as _fused_moe,
    route_renormalize,
)
from flashinfer_tpu.gemm import (
    grouped_gemm,
    mm_fp4,
    mm_svdquant,
)

# call-compatible adapters: reference signatures, TPU ops underneath
# (VERDICT r3 #5 — name parity promoted to call parity; see the module
# docstring for the rejected-semantics policy)
from flashinfer_tpu.compat_calls import (
    bmm_bf16,
    bmm_fp8,
    bmm_mxfp8,
    cutlass_fused_moe,
    fp4_quantize,
    grouped_mm_bf16,
    grouped_mm_fp4,
    grouped_mm_fp8,
    grouped_mm_mxfp8,
    mm_bf16,
    mm_fp8,
    mxfp8_quantize,
    trtllm_bf16_moe,
    trtllm_fp4_block_scale_moe,
    trtllm_fp8_block_scale_moe,
    trtllm_fp8_per_tensor_scale_moe,
    trtllm_mxint4_block_scale_moe,
    trtllm_mxint4_block_scale_routed_moe,
)
from flashinfer_tpu.norm import (
    fused_add_rmsnorm_quant_fp8,
    gate_residual,
    layernorm_scale_shift,
    qk_rmsnorm,
    rmsnorm,
    rmsnorm_quant_fp8,
    rmsnorm_silu,
)
from flashinfer_tpu.quantization import (
    dequantize_fp4,
    dequantize_fp8,
    quantize_fp4,
    quantize_fp8_per_tensor,
    quantize_int8,
)
from flashinfer_tpu.rope import (
    apply_llama31_rope,
    apply_llama31_rope_pos_ids,
    apply_rope,
    apply_rope_pos_ids,
    apply_rope_with_cos_sin_cache,
)
from flashinfer_tpu.trace import traced_api as fi_trace  # noqa: F401
from flashinfer_tpu.utils import next_power_of_two
from flashinfer_tpu.version import __version__

# the reference records its build commit; this build is versioned by the
# package version only.  (Dunder names skip star-imports — the package
# __init__ imports this one explicitly.)
__git_version__ = __version__

next_positive_power_of_2 = next_power_of_two
"""Reference utils name for the pow2 bucketing helper."""


# ---------------------------------------------------------------------------
# enums / small types
# ---------------------------------------------------------------------------


class ActivationType(enum.Enum):
    """Reference activation selector (fused_moe/core.py ActivationType)."""

    Silu = "silu"
    Gelu = "gelu"
    Relu2 = "relu2"
    SwigluBias = "swiglu_bias"


_GATED_ACTIVATIONS = {ActivationType.Silu, ActivationType.Gelu,
                      ActivationType.SwigluBias}


def is_gated_activation(act) -> bool:
    """True for gate*up activations (reference is_gated_activation)."""
    if isinstance(act, str):
        act = ActivationType(act)
    return act in _GATED_ACTIVATIONS


class TopKTieBreak(enum.IntEnum):
    """Top-k tie-break mode — the reference's int-valued enum verbatim
    (topk.py:40: NONE=0 legacy order, SMALL=1 prefer smaller indices,
    LARGE=2 prefer larger indices).  This library's backends naturally
    prefer LOWEST index on exact ties (xla sort order and the threshold
    kernel's cut both do), so NONE == SMALL here; LARGE is served by the
    reversed-input transform in :func:`top_k`.  The pre-round-5 member
    names remain as aliases."""

    NONE = 0
    SMALL = 1
    LARGE = 2
    # legacy aliases (same values -> IntEnum aliasing)
    SortOrder = 0
    LowestIndex = 1

    def __str__(self):  # reference topk.py: str() -> "none"/"small"/"large"
        return self.name.lower()

    def __format__(self, spec):
        return format(str(self), spec)


class SfLayout(enum.Enum):
    """Scale-factor layout selector (reference SfLayout for NVFP4 swizzled
    scales).  TPU stores scales as plain row-major arrays — XLA owns
    layout — so the one member is the identity layout."""

    ROW_MAJOR = "row_major"
    # reference's 128x4 swizzle collapses to row-major on TPU
    SWIZZLED_128x4 = "row_major"


# ---------------------------------------------------------------------------
# top-k conveniences
# ---------------------------------------------------------------------------

def top_k(scores: jax.Array, k: int, sorted: bool = False,
          deterministic: bool = False,
          tie_break: int = TopKTieBreak.NONE,
          dsa_graph_safe: bool = False, backend: str = "xla"):
    """Exact top-k -> (values, indices) — the reference signature
    verbatim (``flashinfer.top_k``, topk.py:508).

    The xla backend returns value-sorted entries (a superset of both
    ``sorted`` settings); ``deterministic``/``dsa_graph_safe`` are inert
    (this backend is always deterministic and jit-replay-safe).
    ``tie_break``: NONE and SMALL are the backends' native
    lowest-index-on-ties order; LARGE runs on the column-reversed input
    so exact ties resolve to the LARGEST original index, then maps
    indices back.  Indices are int32 (JAX default; the reference returns
    int64 — documented in docs/migration.md).

    Order note: this order-sensitive entry pins ``backend="xla"`` rather
    than "auto" — the process-wide ``FLASHINFER_TPU_TOPK_BACKEND=
    threshold`` opt-in must not silently switch migrating callers to
    index-ordered output.  Set-semantics callers can pass
    ``backend="threshold"`` (or "auto") explicitly; ``sorted=True`` then
    post-sorts that backend's index-ordered output, and the threshold
    backend's -1 invalid-slot sentinel is preserved through the LARGE
    remap."""
    # Resolve the backend EAGERLY, on every call, before any jit
    # boundary: _top_k_large_ties is jitted with `backend` static, so an
    # "auto" passed through would read FLASHINFER_TPU_TOPK_BACKEND
    # inside the trace and pin the first resolution in the jit cache —
    # contradicting topk.py's documented per-call resolution (ADVICE.md
    # round-5 item 4, the motivating L003 true positive).  This also
    # makes the sorted= post-sort test below see the concrete backend.
    backend = topk._resolve_backend(backend)
    if int(tie_break) == int(TopKTieBreak.LARGE):
        vals, idx = _top_k_large_ties(scores, k, backend)
    else:
        vals, idx = topk.top_k_values_indices(scores, k, backend)
    if sorted and backend != "xla":
        # non-xla backends return index-ordered entries; honor sorted=
        vals, idx = _sort_desc_pairs(vals, idx)
    return vals, idx


@functools.partial(jax.jit, static_argnames=("k", "backend"))
def _top_k_large_ties(scores, k, backend):
    """LARGE tie-break: top-k of the column-reversed input (so exact ties
    cut at the LARGEST original index), indices mapped back, with the
    threshold backend's -1 invalid-slot sentinel preserved.  Jitted so
    XLA fuses the reverse/remap into the selection.  `backend` is
    static and arrives PRE-RESOLVED (never "auto") from top_k, so no
    env read can happen inside this trace."""
    v = scores.shape[-1]
    # graft-lint: ok backend pre-resolved eagerly in top_k, env branch dead
    vals, idx = topk.top_k_values_indices(scores[..., ::-1], k, backend)
    return vals, jnp.where(idx >= 0, v - 1 - idx, idx).astype(idx.dtype)


@jax.jit
def _sort_desc_pairs(vals, idx):
    order = jnp.argsort(-vals.astype(jnp.float32), axis=-1)
    return (jnp.take_along_axis(vals, order, -1),
            jnp.take_along_axis(idx, order, -1))


def top_k_ragged_transform(
    scores: jax.Array,  # [batch, max_kv]
    kv_indptr: jax.Array,  # [batch + 1] flat kv token offsets
    kv_lens: jax.Array,  # [batch]
    k: int,
    backend: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Top-k kv tokens per request -> flat RAGGED kv-axis rows (the
    ragged twin of ``top_k_page_table_transform``, reference topk.py).

    Returns (rows [batch, k] into the flat ragged kv axis, valid)."""
    masked = jnp.where(
        jnp.arange(scores.shape[1])[None, :] < kv_lens[:, None],
        scores.astype(jnp.float32), -jnp.inf,
    )
    vals, tok = topk.top_k_values_indices(masked, k, backend)
    valid = jnp.isfinite(vals) & (tok >= 0)
    rows = kv_indptr[:-1][:, None] + jnp.maximum(tok, 0)
    return jnp.where(valid, rows, -1).astype(jnp.int32), valid


# ---------------------------------------------------------------------------
# rope: reference in-place entry points (JAX is functional — each returns
# the new arrays; the reference's out-of-place twins behave identically)
# ---------------------------------------------------------------------------

apply_rope_inplace = apply_rope
apply_rope_pos_ids_inplace = apply_rope_pos_ids
apply_llama31_rope_inplace = apply_llama31_rope
apply_llama31_rope_pos_ids_inplace = apply_llama31_rope_pos_ids
apply_rope_with_cos_sin_cache_inplace = apply_rope_with_cos_sin_cache


def fused_qk_rmsnorm_rope(
    q: jax.Array,  # [T, Hq, D]
    k: jax.Array,  # [T, Hk, D]
    q_weight: jax.Array,  # [D]
    k_weight: jax.Array,  # [D]
    pos_ids: jax.Array,  # [T]
    eps: float = 1e-6,
    rope_theta: float = 1e4,
):
    """Per-head QK RMSNorm then RoPE (reference fused_qk_rmsnorm_rope) —
    expressed over qk_rmsnorm + apply_rope_pos_ids; XLA fuses the
    elementwise chain into the surrounding matmuls."""
    qn, kn = qk_rmsnorm(q, k, q_weight, k_weight, eps)
    return apply_rope_pos_ids(qn, kn, pos_ids, rope_theta=rope_theta)


fused_rmsnorm_silu = rmsnorm_silu
fused_add_rmsnorm_quant = fused_add_rmsnorm_quant_fp8
rmsnorm_quant = rmsnorm_quant_fp8


def add_rmsnorm_fp4quant(x, residual, weight, eps: float = 1e-6):
    """Residual add + RMSNorm + block-fp4 quantize (reference
    add_rmsnorm_fp4quant; fp4 storage = block-int4, gemm.py docs)."""
    h = x + residual
    n = rmsnorm(h, weight, eps)
    q, s = quantize_fp4(n)
    return q, s, h


def rmsnorm_fp4quant(x, weight, eps: float = 1e-6):
    """RMSNorm + block-fp4 quantize (reference rmsnorm_fp4quant)."""
    return quantize_fp4(rmsnorm(x, weight, eps))


# DiT norm family: the reference's fused gate/residual/scale-shift
# layernorm forms (diffusion transformers) over the norm module's blocks
def fused_dit_residual_layernorm_scale_shift(
    x, residual, scale, shift, eps: float = 1e-6
):
    """(x + residual) -> LayerNorm -> * (1 + scale) + shift (reference
    fused_dit_residual_layernorm_scale_shift)."""
    h = x + residual
    return layernorm_scale_shift(h, scale, shift, eps=eps), h


def fused_dit_gate_residual_layernorm_scale_shift(
    x, gate, residual, scale, shift, eps: float = 1e-6
):
    """gate_residual then layernorm_scale_shift (reference DiT gate
    variant)."""
    h = gate_residual(x, gate, residual)
    return layernorm_scale_shift(h, scale, shift, eps=eps), h


def fused_dit_gate_residual_layernorm_gamma_beta(
    x, gate, residual, gamma, beta, eps: float = 1e-6
):
    """gate_residual then affine LayerNorm (reference gamma/beta form)."""
    from flashinfer_tpu.norm import layernorm

    h = gate_residual(x, gate, residual)
    return layernorm(h, gamma, beta, eps=eps), h


# ---------------------------------------------------------------------------
# linear-attention conveniences
# ---------------------------------------------------------------------------

# reference gdn_kernels MTP decode surface (gdn_kernels/__init__.py:
# gated_delta_rule_mtp / run_mtp_decode, T>=1 draft tokens per call)
gated_delta_rule_mtp = gdn.gdn_decode_mtp
gated_delta_rule_bf16state_cooprow_mtp = gdn.gdn_decode_mtp
run_mtp_decode = gdn.gdn_decode_mtp

chunk_gated_delta_rule = gdn.gdn_chunk_prefill
"""Chunked gated delta rule (reference chunk_gated_delta_rule ->
gdn.gdn_chunk_prefill, the WY-transform chunked form)."""

recurrent_kda = gdn.kda_prefill
"""Sequential-recurrence KDA (reference kda_kernels/recurrent_kda.py ->
gdn.kda_prefill; the chunked form is kda_chunk_prefill)."""


def single_prefill_with_kv_cache_return_lse(*args, **kw):
    """Reference convenience: single prefill that always returns LSE."""
    from flashinfer_tpu.prefill import single_prefill_with_kv_cache

    kw["return_lse"] = True
    return single_prefill_with_kv_cache(*args, **kw)


# ---------------------------------------------------------------------------
# wrappers: reference class names whose role collapses on TPU
# ---------------------------------------------------------------------------

# CUDA-graph capture is subsumed by jit tracing: the same wrapper IS the
# graph-captured form (plan() fixes geometry, run() replays a compiled
# executable)
CUDAGraphBatchDecodeWithPagedKVCacheWrapper = (
    BatchDecodeWithPagedKVCacheWrapper
)


def _shared_prefix_wrapper(base):
    class _SharedPrefix(base):
        """Shared-prefix cascade wrapper (reference
        Batch*WithSharedPrefixPagedKVCacheWrapper, cascade.py:505+):
        the reference's LEGACY two-level API — ``begin_forward`` plans
        the UNIQUE-suffix paged geometry, ``forward(q, k_shared,
        v_shared, unique_kv_cache)`` computes non-causal attention over
        the dense shared prefix, the planned paged attention over the
        unique suffixes, and folds the two with ``merge_state`` (the
        same math MultiLevelCascadeAttentionWrapper runs per level)."""

        def plan(self, *args, **kw):
            # stash the geometry NAME-BOUND (a positional causal binds
            # correctly) so forward(...) can RE-plan exactly once when
            # causal or a scale override changes; stashing here (not in
            # begin_forward) also covers the modern plan() spelling
            bound = _ins.signature(base.plan).bind(self, *args, **kw)
            stash = {k: v for k, v in bound.arguments.items()
                     if k != "self"}
            stash.update(stash.pop("_unused", {}) or {})
            self._bf_kw = stash
            return base.plan(self, *args, **kw)

        begin_forward = plan  # legacy lifecycle name

        def forward(self, q, k_shared, v_shared, unique_kv_cache,
                    causal: bool = False, sm_scale=None,
                    logits_soft_cap=None, **kw):
            if kw:
                raise TypeError(
                    f"shared-prefix forward: unsupported kwargs "
                    f"{sorted(kw)}")
            if not hasattr(self, "_bf_kw"):
                raise RuntimeError(
                    "shared-prefix wrapper: call begin_forward()/plan() "
                    "before forward()")
            from flashinfer_tpu.ops.merge import merge_state
            from flashinfer_tpu.prefill import (
                single_prefill_with_kv_cache,
            )

            # BOTH halves must use the same logits math — merging
            # states computed under different scales is numerically
            # wrong — so ANY override (causal flag, sm_scale,
            # logits_soft_cap) RE-plans the unique half to match and
            # the shared half reads the resulting plan
            want = dict(self._bf_kw)
            if "causal" in _ins.signature(base.plan).parameters \
                    and causal != bool(want.get("causal", False)):
                want["causal"] = causal
            if sm_scale is not None:
                want["sm_scale"] = sm_scale
            if logits_soft_cap is not None:
                want["logits_soft_cap"] = logits_soft_cap
            if want != self._bf_kw:
                self.plan(**want)
            plan = self._plan
            sm = plan.sm_scale
            cap = plan.logits_soft_cap
            # shared prefix: every query row attends the WHOLE prefix
            # (non-causal by construction — the prefix precedes all);
            # single_prefill dispatches to the flash backend rather than
            # materializing dense scores
            o_s, lse_s = single_prefill_with_kv_cache(
                q, k_shared, v_shared, causal=False, sm_scale=sm,
                logits_soft_cap=cap or None, return_lse=True,
            )
            o_u, lse_u = self.run(q, unique_kv_cache, return_lse=True)
            o, _ = merge_state(o_s, lse_s, o_u, lse_u)
            return o

        def end_forward(self):  # legacy lifecycle no-op
            return None

    _SharedPrefix.__name__ = "SharedPrefix" + base.__name__
    return _SharedPrefix


import inspect as _ins  # noqa: E402


from flashinfer_tpu.prefill import (  # noqa: E402
    BatchPrefillWithPagedKVCacheWrapper,
)

BatchDecodeWithSharedPrefixPagedKVCacheWrapper = _shared_prefix_wrapper(
    BatchDecodeWithPagedKVCacheWrapper
)
BatchPrefillWithSharedPrefixPagedKVCacheWrapper = _shared_prefix_wrapper(
    BatchPrefillWithPagedKVCacheWrapper
)

from flashinfer_tpu.pod import (  # noqa: E402
    PODWithPagedKVCacheWrapper as BatchPODWithPagedKVCacheWrapper,  # noqa: F401
)


# ---------------------------------------------------------------------------
# MoE entry-point family: every reference backend name routes to the one
# fused_moe (backend dispatch happens inside; see fused_moe docstring)
# ---------------------------------------------------------------------------

# trtllm_*_moe / cutlass_fused_moe are call-compatible adapters imported
# from compat_calls above; the remaining backend-brand names share them
b12x_fused_moe = cutlass_fused_moe
cute_dsl_fused_moe_nvfp4 = cutlass_fused_moe
B12xMoEWrapper = MoE
CuteDslMoEWrapper = MoE


def _routed_moe(router_logits, hidden, w_gate_up, w_down, num_experts,
                top_k: int = 2, **kw):
    """Routed entry point: router logits in, combined output out
    (reference trtllm_*_routed_moe family); remaining kwargs forward to
    fused_moe."""
    wts, ids = route_renormalize(router_logits, top_k)
    return _fused_moe(hidden, w_gate_up, w_down, wts, ids, num_experts, **kw)


trtllm_bf16_routed_moe = _routed_moe
trtllm_fp8_block_scale_routed_moe = _routed_moe
trtllm_fp4_block_scale_routed_moe = _routed_moe


# ---------------------------------------------------------------------------
# GEMM family: vendor-dtype names -> the TPU precision story
# ---------------------------------------------------------------------------

# grouped_mm_* / bmm_mxfp8 are call-compatible adapters (compat_calls)
mm_mxfp8 = mm_fp8


def mm_bf16_fp4(a: jax.Array, b_prepared, block_size: int = 16,
                out_dtype=jnp.bfloat16) -> jax.Array:
    """bf16 activation x fp4-stored weight (reference mm_bf16_fp4).

    ``b_prepared`` is the ``(packed [n, k//2], scales)`` pair from
    :func:`prepare_bf16_fp4_weights` (k packed along the last axis).
    The weight dequantizes in-register to bf16 for the MXU; for both
    operands packed, use :func:`flashinfer_tpu.gemm.mm_fp4`."""
    b_packed, b_scale = b_prepared
    b = dequantize_fp4(b_packed, b_scale, block_size)  # [n, k]
    return jnp.dot(
        a, jnp.swapaxes(b, 0, 1), preferred_element_type=jnp.float32
    ).astype(out_dtype)


mm_nvfp4_svdquant = mm_svdquant
svdquant_linear = mm_svdquant
tgv_gemm_sm100 = mm_bf16  # arch-tagged GEMM name -> the one MXU matmul


def prepare_low_latency_gemm_weights(w, *_, **__):
    """Reference ``prepare_low_latency_gemm_weights`` (gemm_base.py:4240
    example flow): raw weight [n, k] -> the prepared 3-D layout
    ``(k // 128, n, 128)`` that reference ``mm_fp8`` consumes.

    XLA owns TPU layout so no swizzle is *needed*, but emitting the
    reference's 3-D shape keeps prepared-ness DETECTABLE: ``mm_fp8``
    accepts this 3-D form (reconstructing [k, n]) and a 2-D [k, n]
    native form, and cannot distinguish a raw square [n, k] — so porting
    callers must keep this prepare step (ADVICE r4; see
    docs/migration.md deviation table)."""
    w = jnp.asarray(w)
    if w.ndim == 3:  # already prepared
        return w
    n, k = w.shape
    if k % 128:
        raise ValueError(
            "prepare_low_latency_gemm_weights expects [n, k] with "
            f"k % 128 == 0 (reference block_size=128); got {w.shape}"
        )
    return jnp.swapaxes(w.reshape(n, k // 128, 128), 0, 1)


def prepare_bf16_fp4_weights(w, *_, **__):
    """Block-int4 quantize of a [k, n] weight along its contraction
    axis -> (packed [n, k//2], scales), the pair :func:`mm_bf16_fp4`
    consumes."""
    return quantize_fp4(jnp.swapaxes(w, 0, 1))


# layout shuffles: identity on TPU (XLA chooses layouts; reference
# helpers exist to feed fixed CUDA kernel swizzles)
def shuffle_matrix_a(w, *_, **__):
    return w


def shuffle_matrix_sf_a(s, *_, **__):
    return s


def block_scale_interleave(s, *_, **__):
    return s


def nvfp4_block_scale_interleave(s, *_, **__):
    return s


def reorder_rows_for_gated_act_gemm(w, *_, **__):
    return w


# ---------------------------------------------------------------------------
# fp4 / mxfp quantization family -> block-int4 + fp8 storage forms
# ---------------------------------------------------------------------------

# fp4_quantize / mxfp8_quantize are call-compatible adapters (compat_calls)
nvfp4_quantize = fp4_quantize
mxfp4_quantize = fp4_quantize
nvfp4_quantize_smooth = fp4_quantize
nvfp4_batched_quantize = fp4_quantize
scaled_fp4_grouped_quantize = fp4_quantize
mxfp4_dequantize = dequantize_fp4
mxfp4_dequantize_host = dequantize_fp4
mxfp8_grouped_quantize = mxfp8_quantize
mxfp8_dequantize_host = dequantize_fp8


def e2m1_and_ufp8sf_scale_to_float(vals, scales, *_, **__):
    """Dequantize the fp4 storage form back to float (reference
    e2m1_and_ufp8sf_scale_to_float; storage here is block-int4)."""
    return dequantize_fp4(vals, scales)


def get_fp4_quantization_module(*_, **__):
    """The reference returns an arch-specific JIT module; here the one
    quantization module serves every chip."""
    from flashinfer_tpu import quantization

    return quantization


def _module_getter(modname: str):
    """Factory for the reference's per-op JIT-module getters/generators
    (gen_*_module / get_*_module): the reference compiles a CUDA module
    per arch; here every getter returns the one TPU module."""

    def get(*_, **__):
        import importlib

        return importlib.import_module(f"flashinfer_tpu.{modname}")

    return get


gen_quantization_module = _module_getter("quantization")
gen_norm_module = _module_getter("norm")
get_norm_module = _module_getter("norm")
gen_rmsnorm_silu_module = _module_getter("norm")
gen_cascade_module = _module_getter("cascade")
gen_mhc_module = _module_getter("mhc")
get_mhc_module = _module_getter("mhc")
get_concat_mla_module = _module_getter("concat_ops")


# fp4 KV-cache family -> the token-pair int4 paged forms
def nvfp4_kv_quantize(k):
    from flashinfer_tpu.ops.paged_decode_fp4 import quantize_kv_int4_paged

    return quantize_kv_int4_paged(k)


nvfp4_quantize_paged_kv_cache = nvfp4_kv_quantize


def nvfp4_kv_dequantize(vals, scales):
    from flashinfer_tpu.ops.paged_decode_fp4 import dequantize_kv_int4_paged

    return dequantize_kv_int4_paged(vals, scales)


nvfp4_kv_dequantize_paged = nvfp4_kv_dequantize


def nvfp4_quantize_append_paged_kv_cache(*args, **kw):
    """fp4 quantizing append -> the fp8/int8 quantizing appends
    (page.py); int8 is the TPU low-precision append with a kernel-grade
    decode consumer (ops/paged_decode.py)."""
    from flashinfer_tpu.page import append_paged_kv_cache_quant_int8

    return append_paged_kv_cache_quant_int8(*args, **kw)


nvfp4_quantize_append_paged_kv_cache_with_slot_mapping = (
    nvfp4_quantize_append_paged_kv_cache
)


def nvfp4_attention_sm120_fwd(*args, **kw):
    """Arch-tagged fp4 attention -> the fused int4-KV decode kernel
    (ops/paged_decode_fp4.fp4_paged_decode_attention)."""
    from flashinfer_tpu.ops.paged_decode_fp4 import (
        fp4_paged_decode_attention,
    )

    return fp4_paged_decode_attention(*args, **kw)


def nvfp4_attention_sm120_quantize_qkv(q, k, v):
    """Quantize K/V to the int4 paged storage form (q stays bf16 — the
    fp4 decode kernel consumes high-precision q)."""
    from flashinfer_tpu.ops.paged_decode_fp4 import quantize_kv_int4_paged

    k4, ks = quantize_kv_int4_paged(k)
    v4, vs = quantize_kv_int4_paged(v)
    return q, (k4, ks), (v4, vs)


def silu_and_mul_nvfp4_quantize(x):
    """silu_and_mul then block-fp4 quantize (reference fused form; XLA
    fuses the chain)."""
    return quantize_fp4(silu_and_mul(x))


silu_and_mul_scaled_nvfp4_experts_quantize = silu_and_mul_nvfp4_quantize


def trtllm_sage_attention_quantize(x):
    """Sage-attention per-block quantize -> int8 per-row quantize (the
    TPU int8 MXU path)."""
    return quantize_int8(x)


# ---------------------------------------------------------------------------
# attention aliases
# ---------------------------------------------------------------------------


def trtllm_fmha_v2_prefill(*args, **kw):
    """fmha_v2 prefill entry -> the one batch-prefill surface
    (vendored CUDA codebase collapses to the segment flash kernel)."""
    from flashinfer_tpu.prefill import single_prefill_with_kv_cache

    return single_prefill_with_kv_cache(*args, **kw)


def xqa(*args, **kw):
    """XQA decode -> the head-fused paged decode path
    (aliases.xqa_batch_decode_with_kv_cache)."""
    from flashinfer_tpu.aliases import xqa_batch_decode_with_kv_cache

    return xqa_batch_decode_with_kv_cache(*args, **kw)


def xqa_mla(*args, **kw):
    """XQA-MLA decode -> the MLA decode kernel (ops/mla_decode.py)."""
    from flashinfer_tpu.ops.mla_decode import mla_paged_decode_attention

    return mla_paged_decode_attention(*args, **kw)


# ---------------------------------------------------------------------------
# reference submodule JIT-module accessors: the reference's get_*_module
# functions build/load per-arch CUDA modules; here each returns the one
# module serving every chip (XLA/Mosaic own arch specialization)
# ---------------------------------------------------------------------------


def get_sampling_module(*_, **__):
    from flashinfer_tpu import sampling

    return sampling


def get_page_module(*_, **__):
    from flashinfer_tpu import page

    return page


def get_cascade_module(*_, **__):
    from flashinfer_tpu import cascade

    return cascade


def get_rope_module(*_, **__):
    from flashinfer_tpu import rope

    return rope


def get_act_and_mul_module(*_, **__):
    from flashinfer_tpu import activation

    return activation


def get_seed_and_offset(key=None):
    """Reference helper extracting (seed, offset) from a CUDA generator
    for its Philox sampling kernels.  JAX keys are explicit: pass a
    ``jax.random.PRNGKey`` and get its raw (seed_word, offset_word)."""
    if key is None:
        return 0, 0
    data = jax.random.key_data(key).reshape(-1)
    return int(data[0]), int(data[-1])





# ---------------------------------------------------------------------------
# decode/prefill submodule surface: JIT-module getters + varlen/deepseek
# entry points (reference decode.py / prefill.py)
# ---------------------------------------------------------------------------


def _self_module(name):
    def getter(*_, **__):
        import importlib

        return importlib.import_module(f"flashinfer_tpu.{name}")

    getter.__doc__ = (
        f"Reference per-arch JIT-module getter; the one flashinfer_tpu."
        f"{name} module serves every chip (Mosaic owns arch "
        f"specialization)."
    )
    return getter


get_batch_decode_module = _self_module("decode")
get_batch_decode_jit_module = _self_module("decode")
get_batch_decode_mla_module = _self_module("mla")
get_single_decode_module = _self_module("decode")
get_trtllm_gen_decode_module = _self_module("decode")
get_trtllm_gen_fmha_module = _self_module("attention")
get_batch_prefill_module = _self_module("prefill")
get_batch_prefill_jit_module = _self_module("prefill")
get_customize_batch_prefill_module = _self_module("prefill")
get_single_prefill_module = _self_module("prefill")
get_fmha_module = _self_module("prefill")
get_trtllm_fmha_v2_module = _self_module("prefill")
get_trtllm_fmha_v2_sm120_module = _self_module("prefill")
get_trtllm_gen_prefill_module = _self_module("prefill")


class TrtllmGenDecodeModule:
    """Reference per-arch decode-module handle; here a thin view over the
    one decode surface."""

    def __init__(self, *_, **__):
        from flashinfer_tpu import decode

        self._mod = decode

    def __getattr__(self, name):
        return getattr(self._mod, name)


def make_hashable_cache(func):
    """functools.cache that tuple-izes list arguments first (reference
    prefill.py:142)."""
    import functools as _ft

    @_ft.cache
    def cached(*args, **kw):
        return func(*args, **kw)

    @_ft.wraps(func)
    def wrapper(*args, **kw):
        args = tuple(tuple(a) if isinstance(a, list) else a for a in args)
        kw = {k2: tuple(v) if isinstance(v, list) else v
              for k2, v in kw.items()}
        return cached(*args, **kw)

    return wrapper


def single_decode_with_kv_cache_with_jit_module(jit_module, *args, **kw):
    """Reference passes a prebuilt JIT module; compilation is implicit
    under jax.jit here, so this forwards to the one entry point."""
    from flashinfer_tpu.decode import single_decode_with_kv_cache

    return single_decode_with_kv_cache(*args, **kw)


def single_prefill_with_kv_cache_with_jit_module(jit_module, *args, **kw):
    from flashinfer_tpu.prefill import single_prefill_with_kv_cache

    return single_prefill_with_kv_cache(*args, **kw)


def fmha_varlen_plan(qo_segment_offsets, kv_segment_offsets, *_, **__):
    """Reference returns device plan buffers for fmha_varlen; the TPU
    form needs only the offsets themselves (token-axis plan happens
    inside the wrapper)."""
    return [qo_segment_offsets, kv_segment_offsets]


_VARLEN_PLAN_CACHE = collections.OrderedDict()


def fmha_varlen(
    q, k, v,
    qo_segment_offsets, kv_segment_offsets,
    plan_info=None, max_qo_len=None, out=None, lse=None,
    causal: bool = False, sm_scale=None,
    q_scale=None, k_scale=None, v_scale=None,
    return_lse: bool = False,
    window_left: int = -1,
):
    """Varlen (cu_seqlens) attention -> the ragged batch-prefill wrapper
    (reference prefill.py:4150).  Static scales fold into sm_scale /
    the output.  Planned wrappers are cached on the segment geometry, so
    the reference's plan-once/run-per-step split keeps its cost profile
    (``plan_info`` itself is unused — the offsets ARE the plan here)."""
    import numpy as np

    from flashinfer_tpu.prefill import BatchPrefillWithRaggedKVCacheWrapper
    from flashinfer_tpu.utils import get_sm_scale

    sm = get_sm_scale(q.shape[-1], sm_scale)
    if q_scale:
        sm *= float(q_scale)
    if k_scale:
        sm *= float(k_scale)
    qo_np = np.asarray(qo_segment_offsets)
    kv_np = np.asarray(kv_segment_offsets)
    key = (qo_np.tobytes(), kv_np.tobytes(), q.shape[1], k.shape[1],
           q.shape[2], bool(causal), float(sm), int(window_left))
    w = _VARLEN_PLAN_CACHE.get(key)
    if w is None:
        w = BatchPrefillWithRaggedKVCacheWrapper()
        w.plan(
            qo_np, kv_np, q.shape[1], k.shape[1], q.shape[2],
            causal=causal, sm_scale=sm, window_left=window_left,
        )
        while len(_VARLEN_PLAN_CACHE) >= 64:  # bound host memory: LRU, not
            _VARLEN_PLAN_CACHE.popitem(last=False)  # a clear-all replan storm
        _VARLEN_PLAN_CACHE[key] = w
    else:
        _VARLEN_PLAN_CACHE.move_to_end(key)
    o = w.run(q, k, v, return_lse=return_lse)
    if v_scale:
        if return_lse:
            o = (o[0] * float(v_scale), o[1])
        else:
            o = o * float(v_scale)
    return o


def trtllm_ragged_attention_deepseek(
    query, key, value, workspace_buffer=None, seq_lens=None,
    max_q_len=None, max_kv_len=None, bmm1_scale=1.0, bmm2_scale=1.0,
    o_sf_scale=None, batch_size=None, window_left=-1,
    cum_seq_lens_q=None, cum_seq_lens_kv=None, **_unused,
):
    """DeepSeek ragged prefill entry (reference prefill.py:4408) -> the
    ragged wrapper; bmm1/bmm2 scales fold into sm_scale / the output."""
    o = fmha_varlen(
        query, key, value, cum_seq_lens_q, cum_seq_lens_kv,
        causal=True, sm_scale=float(bmm1_scale), window_left=window_left,
    )
    return o * float(bmm2_scale) if bmm2_scale != 1.0 else o


def fmha_v2_prefill_deepseek(query, key, value, out=None, num_heads=None,
                             head_dim=None, seq_len=None,
                             scale_softmax=None, **_unused):
    """fmha_v2 DeepSeek prefill (reference prefill.py:5027) -> single
    prefill on the flash kernel."""
    from flashinfer_tpu.prefill import single_prefill_with_kv_cache

    return single_prefill_with_kv_cache(
        query, key, value, causal=True, sm_scale=scale_softmax,
    )


# star-import gate: only the compat API, not implementation imports
_NON_API = {"annotations", "collections", "enum", "jax", "jnp", "Optional",
            "Tuple"}
__all__ = [
    n for n in dict(globals())
    if not n.startswith("_") and n not in _NON_API
]
