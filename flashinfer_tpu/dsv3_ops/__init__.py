"""DeepSeek-V3 op namespace (reference ``flashinfer/dsv3_ops/__init__.py``):
re-exports the DSv3-relevant ops under one roof."""

from flashinfer_tpu.concat_ops import concat_mla_k, concat_mla_q  # noqa: F401
from flashinfer_tpu.fused_moe import fused_moe, route_deepseek_v3  # noqa: F401
from flashinfer_tpu.mla import BatchMLAPagedAttentionWrapper  # noqa: F401
from flashinfer_tpu.ops.mla_decode import (  # noqa: F401
    mla_paged_decode_attention,
)
from flashinfer_tpu.page import append_paged_mla_kv_cache  # noqa: F401


def router_gemm(hidden, router_weight):
    """DSv3 router GEMM (reference csrc/dsv3_router_gemm.cu): small-N
    latency-bound matmul; XLA's matmul emitter handles small N natively."""
    import jax.numpy as jnp

    return jnp.dot(hidden, router_weight, preferred_element_type=jnp.float32)


def fused_topk_deepseek(scores, bias, n_group, topk_group, topk,
                        routed_scaling_factor: float = 1.0,
                        topk_values=None, topk_indices=None, **_unused):
    """DSv3 fused expert routing (reference dsv3_ops.fused_topk_deepseek
    / trace/templates/sampling.py:898): sigmoid+bias grouped top-k with
    unbiased renormalized weights -> (values, indices).  Same algorithm
    as :func:`route_deepseek_v3`, reference argument order.

    The reference MUTATES caller-allocated ``topk_values``/
    ``topk_indices`` out-tensors; JAX arrays are immutable, so passing
    them raises with the functional alternative rather than silently
    leaving the caller's buffers unwritten."""
    if topk_values is not None or topk_indices is not None:
        raise ValueError(
            "TPU backend: fused_topk_deepseek out-tensors (topk_values/"
            "topk_indices) are not supported — JAX arrays are immutable; "
            "use the returned (values, indices)"
        )
    return route_deepseek_v3(
        scores, bias, int(topk), int(n_group), int(topk_group),
        float(routed_scaling_factor),
    )


def mm_M1_16_K7168_N128(a, b, *_, **__):
    """DSv3 tiny-M latency-specialized GEMM names (reference
    dsv3_ops router/gate tails): arch-specialized CUDA tile configs —
    XLA's matmul emitter owns tiling on TPU, so all three names are the
    one matmul."""
    import jax.numpy as jnp

    return jnp.dot(a, b, preferred_element_type=jnp.float32)


mm_M1_16_K7168_N256 = mm_M1_16_K7168_N128
mm_M1_16_K6144_N256 = mm_M1_16_K7168_N128
