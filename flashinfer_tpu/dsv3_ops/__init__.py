"""DeepSeek-V3 op namespace (reference ``flashinfer/dsv3_ops/__init__.py``):
re-exports the DSv3-relevant ops under one roof."""

from flashinfer_tpu.concat_ops import concat_mla_k, concat_mla_q  # noqa: F401
from flashinfer_tpu.fused_moe import fused_moe, route_deepseek_v3  # noqa: F401
from flashinfer_tpu.mla import BatchMLAPagedAttentionWrapper  # noqa: F401
from flashinfer_tpu.ops.mla_decode import (  # noqa: F401
    mla_paged_decode_attention,
)
from flashinfer_tpu.page import append_paged_mla_kv_cache  # noqa: F401


def router_gemm(hidden, router_weight):
    """DSv3 router GEMM (reference csrc/dsv3_router_gemm.cu): small-N
    latency-bound matmul; XLA's matmul emitter handles small N natively."""
    import jax.numpy as jnp

    return jnp.dot(hidden, router_weight, preferred_element_type=jnp.float32)
