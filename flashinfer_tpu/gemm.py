"""GEMM family: dense, batched, low-precision, segment/grouped.

TPU re-design of the reference GEMM layer (``flashinfer/gemm/gemm_base.py``
mm_bf16:542 / bmm_fp8:806 / mm_fp8:1419; SegmentGEMMWrapper gemm_base.py:1943;
grouped_mm core.py).  Backend collapse per SURVEY §7: cublas/cutlass/trtllm/
cute-dsl tactic selection disappears — XLA's matmul emitter owns tiling on
the MXU, and ``jax.lax.ragged_dot`` is the native grouped/segment GEMM
(megablox-style) for LoRA-batch and MoE shapes.

Low-precision mapping (documented capability gate, SURVEY §7 "FP8/FP4"):
v5e/v5p have no FP8 MXU mode, so fp8 inputs are stored as fp8 (HBM savings
preserved) and upcast to bf16 in-register for the MXU; int8 uses the native
int8 MXU path.  ``mm_fp4`` maps NVFP4 to int4-per-block storage — later
round.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from flashinfer_tpu.utils import canonicalize_dtype


def _scaled(x, scale):
    if scale is None:
        return x
    return x * scale


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def mm_bf16(a: jax.Array, b: jax.Array, out_dtype=jnp.bfloat16) -> jax.Array:
    """Dense bf16 matmul with f32 accumulation (reference ``mm_bf16``)."""
    return jnp.dot(
        a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def bmm_bf16(a: jax.Array, b: jax.Array, out_dtype=jnp.bfloat16) -> jax.Array:
    return jnp.einsum(
        "bmk,bkn->bmn", a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def mm_fp8(
    a: jax.Array,  # fp8 [m, k]
    b: jax.Array,  # fp8 [k, n]
    a_scale: Optional[jax.Array] = None,
    b_scale: Optional[jax.Array] = None,
    out_dtype=jnp.bfloat16,
) -> jax.Array:
    """FP8-stored matmul (reference ``mm_fp8``): fp8 operands dequantized
    in-register (bf16 MXU — no native fp8 matmul on v5)."""
    af = _scaled(a.astype(jnp.float32), a_scale).astype(jnp.bfloat16)
    bf = _scaled(b.astype(jnp.float32), b_scale).astype(jnp.bfloat16)
    return jnp.dot(af, bf, preferred_element_type=jnp.float32).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def bmm_fp8(
    a: jax.Array,  # fp8 [b, m, k]
    b: jax.Array,  # fp8 [b, k, n]
    a_scale: Optional[jax.Array] = None,
    b_scale: Optional[jax.Array] = None,
    out_dtype=jnp.bfloat16,
) -> jax.Array:
    """Batched fp8 matmul (reference ``bmm_fp8``, gemm_base.py:806)."""
    af = _scaled(a.astype(jnp.float32), a_scale).astype(jnp.bfloat16)
    bf = _scaled(b.astype(jnp.float32), b_scale).astype(jnp.bfloat16)
    return jnp.einsum(
        "bmk,bkn->bmn", af, bf, preferred_element_type=jnp.float32
    ).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def mm_int8(
    a: jax.Array,  # int8 [m, k]
    b: jax.Array,  # int8 [k, n]
    a_scale: Optional[jax.Array] = None,
    b_scale: Optional[jax.Array] = None,
    out_dtype=jnp.bfloat16,
) -> jax.Array:
    """int8 x int8 -> int32 on the native int8 MXU path, then rescale."""
    acc = jnp.dot(a, b, preferred_element_type=jnp.int32).astype(jnp.float32)
    if a_scale is not None:
        acc = acc * jnp.asarray(a_scale, jnp.float32)
    if b_scale is not None:
        acc = acc * jnp.asarray(b_scale, jnp.float32)
    return acc.astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("block_size", "out_dtype"))
def mm_fp4(
    a_packed: jax.Array,  # [m, k//2] int8 nibbles
    a_scale: jax.Array,  # [m, k//block] f32
    b_packed: jax.Array,  # [k//2, n] int8, packed along k
    b_scale: jax.Array,
    block_size: int = 16,
    out_dtype=jnp.bfloat16,
) -> jax.Array:
    """Block-int4 ("fp4-class") matmul (reference ``mm_fp4``): operands
    stored packed (0.5 B/elem + block scales), dequantized in-register to
    bf16 for the MXU.  ``b`` is packed along its FIRST axis (k)."""
    from flashinfer_tpu.quantization import dequantize_fp4

    a = dequantize_fp4(a_packed, a_scale, block_size)
    # b packed along k: transpose to pack-last, dequant, transpose back
    b = dequantize_fp4(
        jnp.swapaxes(b_packed, 0, 1), jnp.swapaxes(b_scale, 0, 1), block_size
    )
    b = jnp.swapaxes(b, 0, 1)
    return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def mm_fp8_groupwise(
    a: jax.Array,  # fp8 [m, k]
    b: jax.Array,  # fp8 [k, n]
    a_scale: jax.Array,  # [m, k // block_k] per-(row, k-group) scales
    b_scale: jax.Array,  # [k // block_k, n // block_n] per-tile scales
    out_dtype=jnp.bfloat16,
) -> jax.Array:
    """Groupwise-scaled fp8 matmul (reference gemm_groupwise_sm100 family:
    per-k-group activation scales x per-tile weight scales).  Block sizes
    are inferred from the scale shapes; dequantized in-register to bf16 for
    the MXU (no native fp8 matmul on v5)."""
    m, k = a.shape
    _, n = b.shape
    block_k = k // a_scale.shape[1]
    assert k % a_scale.shape[1] == 0 and k // b_scale.shape[0] == block_k
    block_n = n // b_scale.shape[1]
    af = a.astype(jnp.float32).reshape(m, k // block_k, block_k)
    af = (af * a_scale[:, :, None]).reshape(m, k).astype(jnp.bfloat16)
    bf = b.astype(jnp.float32).reshape(k // block_k, block_k, n // block_n,
                                       block_n)
    bf = (bf * b_scale[:, None, :, None]).reshape(k, n).astype(jnp.bfloat16)
    return jnp.dot(af, bf, preferred_element_type=jnp.float32).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("block_size", "out_dtype"))
def mm_svdquant(
    x: jax.Array,  # [m, k]
    w_packed: jax.Array,  # [k//2, n] int8 block-int4, packed along k
    w_scale: jax.Array,  # [k//block, n] f32
    lora_down: jax.Array,  # [k, r] low-rank correction factors
    lora_up: jax.Array,  # [r, n]
    block_size: int = 16,
    out_dtype=jnp.bfloat16,
) -> jax.Array:
    """SVDQuant linear (reference ``gemm_svdquant.py`` /
    nvfp4_svdquant_gemm): 4-bit weight matmul plus a low-rank (LoRA-style)
    correction of the quantization error:
    ``out = x @ dequant(w) + (x @ lora_down) @ lora_up``."""
    from flashinfer_tpu.quantization import dequantize_fp4

    w = dequantize_fp4(
        jnp.swapaxes(w_packed, 0, 1), jnp.swapaxes(w_scale, 0, 1), block_size
    )
    w = jnp.swapaxes(w, 0, 1)
    main = jnp.dot(
        x.astype(jnp.bfloat16), w, preferred_element_type=jnp.float32
    )
    corr = jnp.dot(
        jnp.dot(x.astype(jnp.bfloat16), lora_down.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32).astype(jnp.bfloat16),
        lora_up.astype(jnp.bfloat16), preferred_element_type=jnp.float32,
    )
    return (main + corr).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=())
def grouped_gemm(
    x: jax.Array,  # [total_m, k] ragged rows
    weights: jax.Array,  # [num_groups, k, n]
    group_sizes: jax.Array,  # [num_groups] int32, sum == total_m
) -> jax.Array:
    """Ragged grouped matmul — row-segment i multiplies weights[i].

    The TPU-native megablox equivalent (``jax.lax.ragged_dot`` lowers to a
    grouped MXU kernel); serves the reference's grouped/segment GEMM and the
    MoE expert GEMMs (group_gemm.cuh, fused MoE grouped stages).

    Accumulation is pinned to f32 (``preferred_element_type``): without
    it, sub-f32 inputs accumulate at input precision — at k=4096 an
    f16 x f16 contraction drifts ~2^-11*sqrt(k) ≈ 3% relative, 84% of
    elements outside the ported reference tolerances (the CUDA tensor-
    core reference always accumulates f32, so the tolerance encodes f32
    accumulation).  Output dtype stays the input's."""
    out = jax.lax.ragged_dot(
        x, weights, group_sizes.astype(jnp.int32),
        preferred_element_type=jnp.float32,
    )
    return out.astype(jnp.promote_types(x.dtype, weights.dtype))


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def group_gemm_int8(
    x: jax.Array,  # [total_m, k] bf16/f32 activations (quantized per-row here)
    weights: jax.Array,  # [num_groups, k, n] int8
    w_scale: jax.Array,  # [num_groups, n] (or [num_groups, 1, n]) per-channel
    group_sizes: jax.Array,  # [num_groups] int32
    out_dtype=jnp.bfloat16,
) -> jax.Array:
    """Grouped matmul on the native int8 MXU path (the v5e low-precision
    story; reference grouped-quantized GEMMs, group_gemm_fp8_nt_groupwise
    family).  Activations are dynamically quantized per row, weights carry
    per-(group, out-channel) scales; int8 x int8 -> int32 accumulate."""
    from flashinfer_tpu.quantization import quantize_int8

    xq, xs = quantize_int8(x, axis=-1)  # [total_m, k] int8, [total_m, 1]
    acc = jax.lax.ragged_dot(
        xq, weights, group_sizes.astype(jnp.int32),
        preferred_element_type=jnp.int32,
    )  # [total_m, n] int32
    # per-row group id -> per-row weight scale row
    gid = jnp.repeat(
        jnp.arange(weights.shape[0]), group_sizes, total_repeat_length=x.shape[0]
    )
    ws = w_scale.reshape(weights.shape[0], -1)[gid]  # [total_m, n]
    return (acc.astype(jnp.float32) * xs * ws).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def group_gemm_fp8_nt_groupwise(
    a: jax.Array,  # [total_m, k] fp8
    b: jax.Array,  # [num_groups, n, k] fp8 ("nt": row-major n-by-k)
    a_scale: jax.Array,  # [total_m, k // block_k]
    b_scale: jax.Array,  # [num_groups, k // block_k, n // block_n]
    group_sizes: jax.Array,  # [num_groups] int32
    out_dtype=jnp.bfloat16,
) -> jax.Array:
    """Groupwise-scaled fp8 grouped GEMM (reference
    ``group_gemm_fp8_nt_groupwise``): per-k-group activation scales x
    per-tile weight scales, one ragged MXU matmul over the expert-sorted
    rows.  fp8 storage, bf16 MXU compute (no native fp8 matmul on v5)."""
    g, n, k = b.shape
    block_k = k // a_scale.shape[1]
    block_n = n // b_scale.shape[2]
    af = a.astype(jnp.float32).reshape(a.shape[0], k // block_k, block_k)
    af = (af * a_scale[:, :, None]).reshape(a.shape[0], k).astype(jnp.bfloat16)
    bf = b.astype(jnp.float32).reshape(g, n // block_n, block_n,
                                       k // block_k, block_k)
    bf = (bf * jnp.swapaxes(b_scale, 1, 2)[:, :, None, :, None]).reshape(g, n, k)
    bw = jnp.swapaxes(bf, 1, 2).astype(jnp.bfloat16)  # [g, k, n]
    return jax.lax.ragged_dot(
        af, bw, group_sizes.astype(jnp.int32),
        preferred_element_type=jnp.float32,
    ).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("block_size", "out_dtype"))
def group_gemm_fp4(
    x: jax.Array,  # [total_m, k] bf16/f32
    w_packed: jax.Array,  # [num_groups, k//2, n] int8 block-int4 packed on k
    w_scale: jax.Array,  # [num_groups, k//block, n]
    group_sizes: jax.Array,
    block_size: int = 16,
    out_dtype=jnp.bfloat16,
) -> jax.Array:
    """Grouped 4-bit-weight matmul (reference mxfp4/nvfp4 grouped GEMMs):
    block-int4 expert weights dequantized in-register, ragged MXU dot."""
    from flashinfer_tpu.quantization import dequantize_fp4

    w = dequantize_fp4(
        jnp.swapaxes(w_packed, 1, 2), jnp.swapaxes(w_scale, 1, 2), block_size
    )  # [g, n, k]
    w = jnp.swapaxes(w, 1, 2)  # [g, k, n] bf16
    return jax.lax.ragged_dot(
        x.astype(jnp.bfloat16), w, group_sizes.astype(jnp.int32),
        preferred_element_type=jnp.float32,
    ).astype(out_dtype)


class SegmentGEMMWrapper:
    """LoRA-style segment GEMM (reference ``SegmentGEMMWrapper``,
    gemm_base.py:1943): per-segment weight selection over ragged batches,
    with optional ``weight_indices`` indirection."""

    def __init__(self, float_workspace_buffer=None, backend: str = "auto",
                 **_unused):
        pass

    def run(
        self,
        x: jax.Array,  # [total_m, k]
        weights: jax.Array,  # [num_weights, k, n] ("NK" layout transposed)
        batch_size: int,
        weight_column_major: bool = False,
        seg_lens: Optional[jax.Array] = None,
        seg_indptr: Optional[jax.Array] = None,
        weight_indices: Optional[jax.Array] = None,
    ) -> jax.Array:
        if weight_column_major:
            weights = jnp.swapaxes(weights, 1, 2)
        if seg_lens is None:
            if seg_indptr is None:
                raise ValueError("need seg_lens or seg_indptr")
            seg_lens = seg_indptr[1:] - seg_indptr[:-1]
        seg_lens = seg_lens.astype(jnp.int32)
        if weight_indices is not None:
            weights = weights[weight_indices.astype(jnp.int32)]
        return grouped_gemm(x, weights, seg_lens)

    forward = run


# ---------------------------------------------------------------------------
# Reference gemm-submodule name surface (gemm/__init__.py): the deepgemm /
# blockscale / cutile / tinygemm backend families collapse onto the
# precision-equivalent MXU paths above.  "nt" = weights row-major [n, k]
# (transposed here; XLA owns layout).
# ---------------------------------------------------------------------------


def gemm_fp8_nt_groupwise(a, b, a_scale, b_scale, out_dtype=jnp.bfloat16,
                          **_unused):
    """Dense fp8 NT groupwise GEMM (reference gemm_fp8_nt_groupwise /
    deep_gemm): b arrives [n, k] row-major; scales per the groupwise
    contract of :func:`mm_fp8_groupwise` with b_scale [n//bn, k//bk]
    transposed to match."""
    return mm_fp8_groupwise(
        a, jnp.swapaxes(b, 0, 1), a_scale, jnp.swapaxes(b_scale, 0, 1),
        out_dtype=out_dtype,
    )


gemm_fp8_nt_blockscaled = gemm_fp8_nt_groupwise
fp8_blockscale_gemm_sm90 = gemm_fp8_nt_groupwise


def group_deepgemm_fp8_nt_groupwise(a, b, a_scale, b_scale, m_indices=None,
                                    group_sizes=None, out_dtype=jnp.bfloat16,
                                    **_unused):
    """Grouped deepgemm fp8 NT (reference group_deepgemm_fp8_nt_groupwise):
    accepts either ``m_indices`` (per-row group ids, the deepgemm
    contract) or ``group_sizes`` and routes to the grouped fp8 path."""
    if group_sizes is None:
        if m_indices is None:
            raise ValueError("pass m_indices or group_sizes")
        ids = jnp.asarray(m_indices, jnp.int32)
        # deepgemm marks padding rows with -1; groups are contiguous and
        # non-decreasing, so forward-fill assigns each pad row to the
        # PRECEDING group (keeping later groups' row offsets aligned —
        # pad rows' outputs are garbage the caller ignores, but they
        # must still be COUNTED or every following group shifts)
        filled = jnp.maximum(jax.lax.cummax(ids), 0)
        group_sizes = jnp.bincount(
            filled, length=b.shape[0]
        ).astype(jnp.int32)
    return group_gemm_fp8_nt_groupwise(
        a, b, a_scale, b_scale, group_sizes, out_dtype=out_dtype
    )


def batch_deepgemm_fp8_nt_groupwise(a, b, a_scale, b_scale,
                                    out_dtype=jnp.bfloat16, **_unused):
    """Batched deepgemm fp8 NT (reference batch_deepgemm_fp8_nt_groupwise):
    uniform per-batch segments == a grouped GEMM with equal group sizes."""
    bsz, m, k = a.shape
    sizes = jnp.full((bsz,), m, jnp.int32)
    out = group_gemm_fp8_nt_groupwise(
        a.reshape(bsz * m, k),
        b,
        a_scale.reshape(bsz * m, -1),
        b_scale,
        sizes,
        out_dtype=out_dtype,
    )
    return out.reshape(bsz, m, -1)


def group_gemm_mxfp4_nt_groupwise(x, w_packed, w_scale, group_sizes,
                                  block_size: int = 32,
                                  out_dtype=jnp.bfloat16, **_unused):
    """mxfp4 grouped NT GEMM -> the block-int4 grouped path.  NT weights
    arrive row-major [g, n, k//2] (packed on k, the trailing dim) with
    scales [g, n, k//block]; group_gemm_fp4 wants them k-major, so both
    transpose here."""
    return group_gemm_fp4(
        x, jnp.swapaxes(w_packed, 1, 2), jnp.swapaxes(w_scale, 1, 2),
        group_sizes, block_size=block_size, out_dtype=out_dtype,
    )


group_gemm_nvfp4_nt_groupwise = group_gemm_mxfp4_nt_groupwise
group_gemm_mxfp8_mxfp4_nt_groupwise = group_gemm_mxfp4_nt_groupwise
moe_gemm_fp8_nt_groupwise = group_deepgemm_fp8_nt_groupwise
moe_gemm_mxfp8_nt_groupwise = group_deepgemm_fp8_nt_groupwise


def tinygemm_bf16(a, b, bias=None, out_dtype=jnp.bfloat16, **_unused):
    """Small-M latency GEMM (reference tinygemm backend): XLA's matmul
    emitter already specializes small M on TPU — one matmul serves."""
    out = mm_bf16(a, b, out_dtype=jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype)


def is_cuda_tile_available() -> bool:
    """Reference cuTile (cuda.tile DSL) availability probe — a CUDA
    backend that does not exist on TPU."""
    return False
