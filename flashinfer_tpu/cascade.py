"""Cascade (shared-prefix) attention and state-merge API.

TPU re-design of the reference cascade layer (``flashinfer/cascade.py:226``
``MultiLevelCascadeAttentionWrapper``; merge ops cascade.py:42-170; math
``docs/tutorials/recursive_attention.rst``): attention over a multi-level
shared-prefix KV structure is computed as one attention call per level
(each level a batch-prefill over that level's pages) and the per-level
states are combined with the associative merge operator.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from flashinfer_tpu.ops.merge import (  # noqa: F401  (public re-exports)
    merge_state,
    merge_state_in_place,
    merge_states,
    variable_length_merge_states,
)
from flashinfer_tpu.prefill import BatchPrefillWithPagedKVCacheWrapper


class MultiLevelCascadeAttentionWrapper:
    """Multi-level cascade attention (reference
    ``MultiLevelCascadeAttentionWrapper``, flashinfer/cascade.py:226).

    Level 0 is the most-shared prefix (e.g. system prompt pages shared by
    every request); the last level holds per-request suffix pages.  Each
    level runs as a paged batch prefill with its own (qo_indptr, page table)
    view, producing (out, lse); levels fold together with ``merge_state`` —
    composition identical to the reference (cascade.py:343-367)."""

    def __init__(
        self,
        num_levels: int,
        float_workspace_buffer=None,
        kv_layout: str = "NHD",
        backend: str = "auto",
        **_unused,
    ):
        self._num_levels = num_levels
        self._wrappers = [
            BatchPrefillWithPagedKVCacheWrapper(
                kv_layout=kv_layout, backend=backend
            )
            for _ in range(num_levels)
        ]

    def plan(
        self,
        qo_indptr_arr: Sequence,
        paged_kv_indptr_arr: Sequence,
        paged_kv_indices_arr: Sequence,
        paged_kv_last_page_len_arr: Sequence,
        num_qo_heads: int,
        num_kv_heads: int,
        head_dim: int,
        page_size: int,
        causal: bool = False,
        pos_encoding_mode: str = "NONE",
        window_left: int = -1,
        logits_soft_cap: Optional[float] = None,
        sm_scale: Optional[float] = None,
        q_data_type=jnp.bfloat16,
        **_unused,
    ) -> None:
        """Plan each level.  Causal masking applies only to the last level
        (a query never attends ahead of itself in its own suffix; shared
        prefixes are fully visible), matching the reference's usage."""
        if window_left >= 0 and self._num_levels > 1:
            # prefix levels use level-local positions, so a sliding window
            # would be misaligned across levels; needs global-position plumb
            raise NotImplementedError(
                "sliding window across cascade levels is not supported yet"
            )
        for lvl, w in enumerate(self._wrappers):
            w.plan(
                qo_indptr_arr[lvl],
                paged_kv_indptr_arr[lvl],
                paged_kv_indices_arr[lvl],
                paged_kv_last_page_len_arr[lvl],
                num_qo_heads, num_kv_heads, head_dim, page_size,
                causal=(causal and lvl == self._num_levels - 1),
                pos_encoding_mode=pos_encoding_mode,
                window_left=window_left,
                logits_soft_cap=logits_soft_cap,
                sm_scale=sm_scale,
                q_data_type=q_data_type,
            )

    def run(
        self,
        q: jax.Array,  # [total_q, num_qo_heads, head_dim]
        paged_kv_cache: Union[Tuple[jax.Array, jax.Array], jax.Array],
    ) -> jax.Array:
        out, lse = self._wrappers[0].run(q, paged_kv_cache, return_lse=True)
        for w in self._wrappers[1:]:
            o_i, lse_i = w.run(q, paged_kv_cache, return_lse=True)
            out, lse = merge_state(out, lse, o_i, lse_i)
        return out

    forward = run


def merge_state_with_shared_prefix(
    v_shared: jax.Array, s_shared: jax.Array,
    v_unique: jax.Array, s_unique: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Two-level convenience merge (reference's batch_attention-with-
    shared-prefix pattern)."""
    return merge_state(v_shared, s_shared, v_unique, s_unique)


def compose_cascade_levels(
    levels: Sequence[Tuple[jax.Array, jax.Array]],
) -> Tuple[jax.Array, jax.Array]:
    """Batched shared-prefix composition: fold per-level attention
    states ``[(out [T, H, D], lse [T, H]), ...]`` with the associative
    merge operator (reference cascade.cuh:45-471 merge math).

    The serving engine's cascade path (``serve/engine.py``): level 0 is
    the shared-prefix state (gathered once per prefix GROUP), level 1
    the per-request suffix state, both over rung-padded token axes.
    Two exactness properties the engine's bitwise contract leans on,
    both inherited from :func:`merge_state`'s guards:

    - an EMPTY level (``lse = -inf`` rows — e.g. a request with no
      shared prefix, or a suffix query still inside the shared span)
      passes the other level through BIT-EXACTLY: its weight is a hard
      0.0, the survivor's weight ``exp(0) = 1.0``, and ``(0*v_a +
      1*v_b) / 1`` is exact in IEEE arithmetic;
    - merging is performed in f32 LSE space regardless of the levels'
      compute dtype, so composition order inside one call is fixed.

    Returns ``(out, lse)`` in f32; callers cast once afterwards."""
    if not levels:
        raise ValueError("compose_cascade_levels needs >= 1 level")
    out, lse = levels[0]
    out = out.astype(jnp.float32)
    lse = lse.astype(jnp.float32)
    for o_i, s_i in levels[1:]:
        out, lse = merge_state(out, lse, o_i.astype(jnp.float32),
                               s_i.astype(jnp.float32))
    return out, lse
