"""Top-k selection ops.

TPU re-design of ``flashinfer/topk.py`` (radix/clusters-exact top-k +
fused page-table transforms used by sparse-MLA index selection).  XLA's
``jax.lax.top_k`` is the hardware-native exact top-k on TPU; the value-add
here is the fused transform forms that feed sparse attention.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_values_indices(scores: jax.Array, k: int):
    """Exact top-k -> (values, indices) (reference ``topk.topk``)."""
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_indices(scores: jax.Array, k: int) -> jax.Array:
    return jax.lax.top_k(scores, k)[1].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("k",))
def top_k_mask(scores: jax.Array, k: int) -> jax.Array:
    """Boolean mask of the top-k entries per row."""
    kth = jax.lax.top_k(scores, k)[0][..., -1:]
    return scores >= kth


@functools.partial(jax.jit, static_argnames=("k", "page_size"))
def top_k_page_table_transform(
    scores: jax.Array,  # [batch, max_kv] per-token selection scores
    page_table: jax.Array,  # [batch, max_pages]
    kv_lens: jax.Array,  # [batch]
    k: int,
    page_size: int,
) -> Tuple[jax.Array, jax.Array]:
    """Select top-k kv tokens per request and emit their flat cache rows —
    the fused top-k + page-table transform used by sparse-MLA index
    selection (reference topk.py fused transforms).

    Returns (rows [batch, k], valid [batch, k]); entries beyond a request's
    ``kv_len`` hold ``-1`` (the padding convention the sparse-MLA consumer
    ``BatchMLAPagedAttentionWrapper.run_sparse`` masks on), so ``rows`` can
    be fed forward directly."""
    masked = jnp.where(
        jnp.arange(scores.shape[1])[None, :] < kv_lens[:, None],
        scores.astype(jnp.float32),
        -jnp.inf,
    )
    vals, tok = jax.lax.top_k(masked, k)  # token positions within request
    page = jnp.take_along_axis(page_table, tok // page_size, axis=1)
    rows = page * page_size + tok % page_size
    valid = jnp.isfinite(vals)
    return jnp.where(valid, rows, -1).astype(jnp.int32), valid
