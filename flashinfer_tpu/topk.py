"""Top-k selection ops.

TPU re-design of ``flashinfer/topk.py`` (radix/clusters-exact top-k +
fused page-table transforms used by sparse-MLA index selection).  Two
backends:

- ``"xla"``: ``jax.lax.top_k`` — exact, returns entries sorted by value.
- ``"threshold"``: the sorting-free design (reference
  ``include/flashinfer/topk.cuh`` / ``fast_topk_clusters_exact.cuh``
  re-imagined for VMEM): a Pallas bit-space bisection kernel finds the
  EXACT k-th-largest value in one HBM pass
  (``ops/sampling_kernels.top_k_thresholds``), then XLA cumsum+scatter
  extracts exactly k indices (not value-sorted: strictly-above-threshold
  entries in index order, then threshold ties in index order).  Ties are
  EXACT (order-key comparisons, subnormal-safe): the kept SET matches
  the sort oracle except among entries exactly equal to the k-th value —
  that tie class is cut by lowest index where a sort cuts arbitrarily.
- ``"auto"``: env ``FLASHINFER_TPU_TOPK_BACKEND`` if set, else ``"xla"``
  BY MEASUREMENT: the banked v5e A/B (BENCH_BANKED.md 2026-07-31, bs=64
  vocab=128k) has xla at 1104/7794 us (k=40/2048) vs the threshold
  kernel's flat ~40.8 ms — ``jax.lax.top_k``'s native lowering wins
  ~37x, so the bisection kernel stays opt-in for set-semantics
  consumers; re-flip only on a banked win.

Consumers that treat the result as a SET (sparse-MLA page selection,
masks) can use either backend; order-sensitive consumers need ``"xla"``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _resolve_backend(backend: str) -> str:
    if backend == "auto":
        backend = os.environ.get("FLASHINFER_TPU_TOPK_BACKEND", "xla")
    if backend not in ("xla", "threshold"):
        raise ValueError(f"unknown topk backend {backend!r}")
    return backend


@functools.partial(jax.jit, static_argnames=("k",))
def _threshold_topk(scores: jax.Array, k: int):
    """Sorting-free exact-count top-k -> (values, indices).

    Two-tier trim: entries STRICTLY above the bisection threshold (an
    exact data value) are all kept; the remaining slots fill with
    exact-tie entries in index order.  Trimming the whole kept set by index instead would let
    a large tie class below the cut (e.g. many zeros in masked/ReLU
    scores) evict strictly-larger values.  Output order: strict entries
    in index order, then ties in index order.  Indices beyond a row's
    valid count (all--inf rows) are -1."""
    from flashinfer_tpu.ops.sampling_kernels import key_ge, top_k_thresholds

    batch, vocab = scores.shape
    t = top_k_thresholds(scores, jnp.full((batch,), k, jnp.float32))
    # order-key comparisons (exact for subnormals, NaN-excluding)
    keep, strict = key_ge(scores, t)
    tie = keep & ~strict
    n_strict = jnp.sum(strict.astype(jnp.int32), axis=1, keepdims=True)
    pos_strict = jnp.cumsum(strict.astype(jnp.int32), axis=1) - 1
    pos_tie = n_strict + jnp.cumsum(tie.astype(jnp.int32), axis=1) - 1
    pos = jnp.where(strict, pos_strict, pos_tie)
    sel = keep & (pos < k)
    # scatter column ids into their kept-rank slot; k-th slot absorbs drops
    rows = jnp.broadcast_to(jnp.arange(batch)[:, None], (batch, vocab))
    slot = jnp.where(sel, pos, k)
    idx = jnp.full((batch, k + 1), -1, jnp.int32).at[rows, slot].set(
        jnp.broadcast_to(jnp.arange(vocab, dtype=jnp.int32), (batch, vocab)),
        mode="drop",
    )[:, :k]
    vals = jnp.take_along_axis(
        scores, jnp.maximum(idx, 0), axis=1
    )
    vals = jnp.where(idx >= 0, vals, -jnp.inf)
    return vals, idx


def top_k_values_indices(scores: jax.Array, k: int, backend: str = "auto"):
    """Exact top-k -> (values, indices) (reference ``topk.topk``).

    ``"xla"`` returns value-sorted entries; ``"threshold"`` returns the
    same set in index order (see module docstring).  Backend resolution
    happens outside the jitted bodies so the env var is re-read on every
    eager call (an in-trace read would be pinned by the jit cache)."""
    if _resolve_backend(backend) == "threshold":
        return _threshold_topk(scores, k)
    return _xla_topk(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def _xla_topk(scores: jax.Array, k: int):
    return jax.lax.top_k(scores, k)


def top_k_indices(
    scores: jax.Array, k: int, backend: str = "auto"
) -> jax.Array:
    return top_k_values_indices(scores, k, backend)[1].astype(jnp.int32)


def top_k_mask(scores: jax.Array, k: int, backend: str = "auto") -> jax.Array:
    """Boolean mask of the top-k entries per row (threshold backend: the
    exact-equality tie class at the k-th value is marked whole, so the
    mask can exceed k only by true ties)."""
    if _resolve_backend(backend) == "threshold":
        return _threshold_mask(scores, k)
    return _xla_mask(scores, k)


@functools.partial(jax.jit, static_argnames=("k",))
def _threshold_mask(scores: jax.Array, k: int) -> jax.Array:
    from flashinfer_tpu.ops.sampling_kernels import key_ge, top_k_thresholds

    t = top_k_thresholds(scores, jnp.full((scores.shape[0],), k, jnp.float32))
    return key_ge(scores, t)[0]


@functools.partial(jax.jit, static_argnames=("k",))
def _xla_mask(scores: jax.Array, k: int) -> jax.Array:
    kth = jax.lax.top_k(scores, k)[0][..., -1:]
    return scores >= kth


def top_k_page_table_transform(
    scores: jax.Array,  # [batch, max_kv] per-token selection scores
    page_table: jax.Array,  # [batch, max_pages]
    kv_lens: jax.Array,  # [batch]
    k: int,
    page_size: int,
    backend: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Select top-k kv tokens per request and emit their flat cache rows —
    the fused top-k + page-table transform used by sparse-MLA index
    selection (reference topk.py fused transforms).

    Returns (rows [batch, k], valid [batch, k]); entries beyond a request's
    ``kv_len`` hold ``-1`` (the padding convention the sparse-MLA consumer
    ``BatchMLAPagedAttentionWrapper.run_sparse`` masks on), so ``rows`` can
    be fed forward directly."""
    return _page_transform_impl(
        scores, page_table, kv_lens, k, page_size, _resolve_backend(backend)
    )


@functools.partial(jax.jit, static_argnames=("k", "page_size", "backend"))
def _page_transform_impl(scores, page_table, kv_lens, k, page_size, backend):
    masked = jnp.where(
        jnp.arange(scores.shape[1])[None, :] < kv_lens[:, None],
        scores.astype(jnp.float32),
        -jnp.inf,
    )
    # the consumer (run_sparse) treats rows as a SET, so the threshold
    # backend's index-ordered result is equivalent
    # graft-lint: ok backend pre-resolved eagerly by the caller, never "auto"
    vals, tok = top_k_values_indices(masked, k, backend)
    valid = jnp.isfinite(vals) & (tok >= 0)
    tok = jnp.maximum(tok, 0)
    page = jnp.take_along_axis(page_table, tok // page_size, axis=1)
    rows = page * page_size + tok % page_size
    return jnp.where(valid, rows, -1).astype(jnp.int32), valid


# ---------------------------------------------------------------------------
# Reference clusters-top-k family (flashinfer/topk.py:352-505): CUDA
# cluster-cooperative exact top-k.  On TPU the sorting-free bit-space
# bisection IS the fast exact algorithm (one core, VMEM-resident row), so
# the clusters entry points route there and the capability predicates
# answer for this hardware.
# ---------------------------------------------------------------------------


def can_implement_filtered_topk() -> bool:
    """Reference: does the GPU have 128KB dynamic shared memory?  TPU's
    VMEM (~128MB) holds whole 128k-vocab rows, so the filtered algorithm's
    premise always holds."""
    return True


def can_use_clusters_topk(device=None, deterministic: bool = False,
                          dsa_graph_safe: bool = False) -> bool:
    """Reference gates on SM100 clusters; the TPU threshold backend is
    deterministic (exact k-th value + lowest-index ties), so it remains
    usable even when determinism is requested."""
    return not dsa_graph_safe


def get_fast_topk_clusters(batch_size: int) -> int:
    return 1  # one sequential core; no cluster split


def get_num_cached_for_topk(device=None, k: int = 0) -> int:
    return k  # whole rows are VMEM-resident; everything is "cached"


def roundup_kbyte(x: int) -> int:
    return (x + 1023) // 1024 * 1024


def get_topk_module(*_, **__):
    import flashinfer_tpu.topk as _self

    return _self


def topk_clusters_exact(logits, top_k_: int, output_values: bool = False,
                        out_dtype=jnp.int32, pdl: bool = False):
    """Exact top-k (reference topk_clusters_exact semantics: indices,
    optionally values) on the default backend — ``jax.lax.top_k`` by
    measurement (the bisection kernel loses ~37x at 128k vocab, banked
    2026-07-31); ``FLASHINFER_TPU_TOPK_BACKEND=threshold`` opts the
    sorting-free kernel back in for set-semantics consumers."""
    vals, idx = top_k_values_indices(logits, top_k_, backend="auto")
    idx = idx.astype(out_dtype)
    return (idx, vals) if output_values else idx


def topk_clusters_page_table_transform(logits, seq_lens, src_page_table,
                                       top_k_: int, pdl: bool = False,
                                       page_size: Optional[int] = None):
    """Clusters-exact page-table transform -> the fused transform on the
    DEFAULT backend (reference topk.py:439).

    The sparse-MLA selection feeder.  Default routing is ``"auto"`` —
    ``jax.lax.top_k`` unless ``FLASHINFER_TPU_TOPK_BACKEND=threshold``
    opts the bisection kernel back in: the banked v5e A/B has the kernel
    at 40.7 ms vs 1.08 ms for the sort at the flagship shape (bs=64,
    128k vocab, VERDICT weak #8), and the consumer
    (``BatchMLAPagedAttentionWrapper.run_sparse``) treats the rows as a
    SET, so the backends are interchangeable (A/B-pinned by
    tests/test_topk.py::test_page_table_transform_backend_ab_parity).

    ``page_size`` defaults to ``max_kv / max_pages``, which is only valid
    when the table is exactly sized (``max_kv == max_pages * page_size``);
    over-allocated tables must pass ``page_size`` explicitly or the
    inferred value silently misindexes cache rows."""
    if page_size is None:
        if logits.shape[1] % src_page_table.shape[1] != 0:
            raise ValueError(
                f"cannot infer page_size: max_kv={logits.shape[1]} is not a "
                f"multiple of max_pages={src_page_table.shape[1]}; pass "
                "page_size explicitly"
            )
        page_size = logits.shape[1] // src_page_table.shape[1]
    rows, _ = top_k_page_table_transform(
        logits, src_page_table, seq_lens, top_k_, page_size,
        backend="auto",
    )
    return rows


def topk_clusters_ragged_transform(logits, seq_lens, offsets, top_k_: int,
                                   pdl: bool = False):
    """Clusters-exact ragged transform (reference topk.py:470) -> the
    compat ragged transform on the default backend (same measured
    sort-first routing and set-semantics rationale as
    :func:`topk_clusters_page_table_transform`; env
    ``FLASHINFER_TPU_TOPK_BACKEND=threshold`` opts the kernel back in)."""
    from flashinfer_tpu.compat import top_k_ragged_transform

    off = jnp.asarray(offsets, jnp.int32).reshape(-1)
    # real [B+1] indptr (last entry = end of the last segment), honoring
    # top_k_ragged_transform's documented contract
    indptr = jnp.concatenate(
        [off, off[-1:] + jnp.asarray(seq_lens, jnp.int32).reshape(-1)[-1:]]
    )
    rows, _ = top_k_ragged_transform(
        logits, indptr, seq_lens, top_k_, backend="auto"
    )
    return rows


def get_shared_bytes_per_block_optin(device=None) -> int:
    """Reference: max opt-in CUDA shared memory per block.  The analogous
    on-chip working memory on TPU is VMEM (~128 MB v5e)."""
    return 128 * 1024 * 1024
