"""Mixtral-style MoE decoder wired to the library.

The second integration model (reference keeps MoE serving in its
consumers and ships the fused-MoE blocks — ``flashinfer/fused_moe/``;
SURVEY §2.3): the llama attention sublayer (paged decode + RoPE + fused
AR) with the MLP replaced by the routed ``fused_moe`` expert block.

Entry points mirror ``models/llama.py``:

- ``mixtral_decode_step`` — single device, jittable.
- ``make_ep_sharded_decode_step`` — shard_map over a dp x ep mesh:
  attention weights replicated per dp shard, experts contiguously
  sharded over the ep axis with ``fused_moe_ep`` (allgather dispatch for
  decode's small token counts; the capacity-bucketed all_to_all mode is
  one kwarg away for prefill-sized batches).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flashinfer_tpu.comm.mapping import Mapping
from flashinfer_tpu.fused_moe import fused_moe, fused_moe_ep, route_renormalize
from flashinfer_tpu.models.llama import (
    LlamaConfig,
    _attn_decode,
    _mm,
)
from flashinfer_tpu.norm import rmsnorm
from flashinfer_tpu.utils import is_tpu, jax_shard_map


@dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2

    @staticmethod
    def tiny(**over) -> "MixtralConfig":
        d = dict(
            vocab_size=512, hidden_size=128, intermediate_size=128,
            num_layers=2, num_qo_heads=8, num_kv_heads=4, head_dim=32,
            num_experts=4, top_k=2,
        )
        d.update(over)
        return MixtralConfig(**d)


def init_mixtral_params(key: jax.Array, cfg: MixtralConfig) -> Dict:
    """Random-init pytree: llama attention weights + per-layer router and
    stacked expert weights ([E, hidden, 2*inter] / [E, inter, hidden])."""
    h, qh, kvh, hd = (
        cfg.hidden_size, cfg.num_qo_heads, cfg.num_kv_heads, cfg.head_dim,
    )
    E, inter = cfg.num_experts, cfg.intermediate_size
    keys = iter(jax.random.split(key, 4 + 8 * cfg.num_layers))

    def w(shape, scale=0.02):
        return (
            jax.random.normal(next(keys), shape, jnp.float32) * scale
        ).astype(cfg.dtype)

    layers = []
    for _ in range(cfg.num_layers):
        layers.append(
            dict(
                input_norm=jnp.ones((h,), cfg.dtype),
                q_proj=w((h, qh * hd)),
                k_proj=w((h, kvh * hd)),
                v_proj=w((h, kvh * hd)),
                o_proj=w((qh * hd, h)),
                post_norm=jnp.ones((h,), cfg.dtype),
                router=w((h, E), scale=0.1).astype(jnp.float32),
                w_gate_up=w((E, h, 2 * inter)),
                w_down=w((E, inter, h)),
            )
        )
    return dict(
        embed=w((cfg.vocab_size, h)),
        final_norm=jnp.ones((h,), cfg.dtype),
        lm_head=w((h, cfg.vocab_size)),
        layers=layers,
    )


def _moe_block(h, layer, cfg: MixtralConfig, moe_fn=fused_moe):
    """Route + expert compute; ``moe_fn`` swaps the single-device kernel
    for an EP-sharded one (keeps routing in ONE place for both steps)."""
    logits = h.astype(jnp.float32) @ layer["router"]
    wts, ids = route_renormalize(logits, cfg.top_k)
    return moe_fn(
        h, layer["w_gate_up"], layer["w_down"], wts, ids, cfg.num_experts
    ).astype(h.dtype)


def mixtral_decode_step(
    params: Dict,
    cfg: MixtralConfig,
    tokens: jax.Array,  # [B] int32
    positions: jax.Array,  # [B]
    kv_caches: List[Tuple[jax.Array, jax.Array]],
    page_table: jax.Array,  # [B, P]
    kv_lens: jax.Array,  # [B]
    use_pallas: bool = True,
) -> Tuple[jax.Array, List[Tuple[jax.Array, jax.Array]]]:
    """Single-device batched decode step -> (logits [B, vocab], caches)."""
    x = params["embed"][tokens].astype(cfg.dtype)
    new_caches = []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
        attn, cache = _attn_decode(
            h, layer, cfg, kv_caches[li], page_table, kv_lens, positions,
            cfg.num_qo_heads, cfg.num_kv_heads, use_pallas,
        )
        new_caches.append(cache)
        x = x + _mm(attn, layer, "o_proj").astype(cfg.dtype)
        h = rmsnorm(x, layer["post_norm"], cfg.rms_eps)
        x = x + _moe_block(h, layer, cfg)
    x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
    logits = _mm(x, params, "lm_head").astype(jnp.float32)
    return logits, new_caches


def make_ep_sharded_decode_step(
    mapping: Mapping, cfg: MixtralConfig, mesh=None,
):
    """dp x ep sharded Mixtral decode step via shard_map.

    The batch shards over the FLATTENED (dp, ep) axes — every chip holds
    its own token slice — and experts shard contiguously over the ep
    axis (``Mapping.AXIS_TP`` doubles as the expert axis, the ep_experts
    partition).  Attention weights are replicated so the attention
    sublayer runs collective-free on local tokens; ``fused_moe_ep``'s
    allgather dispatch + psum_scatter combine over the ep group is the
    only cross-chip traffic.

    Returns (step_fn, mesh, specs)."""
    mesh = mesh or mapping.make_mesh()
    ep_ax, dp = Mapping.AXIS_TP, Mapping.AXIS_DP
    ep = mapping.tp_size
    assert cfg.num_experts % ep == 0, (cfg.num_experts, ep)

    layer_spec = dict(
        input_norm=P(None),
        q_proj=P(None, None), k_proj=P(None, None), v_proj=P(None, None),
        o_proj=P(None, None),
        post_norm=P(None),
        router=P(None, None),
        w_gate_up=P(ep_ax, None, None),  # experts contiguously sharded
        w_down=P(ep_ax, None, None),
    )
    param_specs = dict(
        embed=P(None, None), final_norm=P(None), lm_head=P(None, None),
        layers=[layer_spec for _ in range(cfg.num_layers)],
    )
    b = P((dp, ep_ax))  # batch over ALL chips
    cache_spec = [
        (P((dp, ep_ax), None, None, None, None),
         P((dp, ep_ax), None, None, None, None))
        for _ in range(cfg.num_layers)
    ]
    in_specs = (
        param_specs, b, b, cache_spec, P((dp, ep_ax), None), b,
    )
    out_specs = (b, cache_spec)

    def step(params, tokens, positions, kv_caches, page_table, kv_lens):
        x = params["embed"][tokens].astype(cfg.dtype)
        new_caches = []
        use_pallas = is_tpu()
        for li, layer in enumerate(params["layers"]):
            h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
            attn, cache = _attn_decode(
                h, layer, cfg,
                (kv_caches[li][0][0], kv_caches[li][1][0]),
                page_table, kv_lens, positions,
                cfg.num_qo_heads, cfg.num_kv_heads, use_pallas,
            )
            new_caches.append((cache[0][None], cache[1][None]))
            x = x + _mm(attn, layer, "o_proj").astype(cfg.dtype)
            h = rmsnorm(x, layer["post_norm"], cfg.rms_eps)
            x = x + _moe_block(
                h, layer, cfg,
                moe_fn=functools.partial(
                    fused_moe_ep, axis=ep_ax, dispatch="allgather"
                ),
            )
        x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
        logits = _mm(x, params, "lm_head").astype(jnp.float32)
        return logits, new_caches

    sharded = jax.jit(
        jax_shard_map(
            step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    )
    return sharded, mesh, dict(params=param_specs, cache=cache_spec)
