"""Flagship integration model: Llama-3-style decoder wired to the library.

The reference keeps models in its consumers and ships integration blocks
(``examples/pytorch/flashinfer_modules.py`` — FlashInferAttentionDispatcher /
Linear / RMSNorm / FFN); this module is the TPU equivalent *and* the
end-to-end proof for the minimum slice (SURVEY §7 step 2): a paged-KV batch
decode step built entirely from flashinfer_tpu ops:

    rmsnorm -> qkv proj -> RoPE -> append_paged_kv_cache ->
    paged_decode_attention -> o proj -> fused allreduce+add+rmsnorm (TP) ->
    silu_and_mul MLP -> logits

Two entry points: ``llama_decode_step`` (single device, jittable) and
``make_sharded_decode_step`` (shard_map over a Mapping mesh with dp x tp
sharding; TP allreduces ride ICI via the comm layer).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flashinfer_tpu.activation import silu_and_mul
from flashinfer_tpu.comm.allreduce import allreduce_fusion
from flashinfer_tpu.comm.mapping import Mapping
from flashinfer_tpu.norm import rmsnorm
from flashinfer_tpu.ops.paged_decode import paged_decode_attention
from flashinfer_tpu.ops.xla_ref import xla_paged_decode
from flashinfer_tpu.rope import apply_rope_pos_ids
from flashinfer_tpu.utils import is_tpu, jax_shard_map


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_qo_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 5e5
    rms_eps: float = 1e-5
    dtype: object = jnp.bfloat16
    # int8 KV serving path: allocate caches as int8 and set the static
    # dequant scales (high_precision = int8 * scale); halves KV HBM traffic
    # and benches ~29% faster than bf16 decode on v5e (test_quant_kv.py,
    # .chip_probe measurements). bf16 caches ignore the scales.
    kv_k_scale: float = 0.05
    kv_v_scale: float = 0.05

    @staticmethod
    def llama3_8b(**over) -> "LlamaConfig":
        return LlamaConfig(**over)

    @staticmethod
    def tiny(**over) -> "LlamaConfig":
        """Small config for tests/dryruns."""
        d = dict(
            vocab_size=512, hidden_size=256, intermediate_size=512,
            num_layers=2, num_qo_heads=8, num_kv_heads=4, head_dim=32,
        )
        d.update(over)
        return LlamaConfig(**d)


def init_llama_params(key: jax.Array, cfg: LlamaConfig) -> Dict:
    """Random-initialized parameter pytree (layout mirrors HF llama naming)."""
    h, qh, kvh, hd = cfg.hidden_size, cfg.num_qo_heads, cfg.num_kv_heads, cfg.head_dim
    keys = iter(jax.random.split(key, 4 + 8 * cfg.num_layers))

    def w(shape, scale=0.02):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(
            cfg.dtype
        )

    layers = []
    for _ in range(cfg.num_layers):
        layers.append(
            dict(
                input_norm=jnp.ones((h,), cfg.dtype),
                q_proj=w((h, qh * hd)),
                k_proj=w((h, kvh * hd)),
                v_proj=w((h, kvh * hd)),
                o_proj=w((qh * hd, h)),
                post_norm=jnp.ones((h,), cfg.dtype),
                gate_proj=w((h, cfg.intermediate_size)),
                up_proj=w((h, cfg.intermediate_size)),
                down_proj=w((cfg.intermediate_size, h)),
            )
        )
    return dict(
        embed=w((cfg.vocab_size, h)),
        final_norm=jnp.ones((h,), cfg.dtype),
        lm_head=w((h, cfg.vocab_size)),
        layers=layers,
    )


_LINEAR_NAMES = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)


def quantize_llama_weights(params: Dict, include_lm_head: bool = True) -> Dict:
    """Per-output-channel symmetric int8 quantization of every linear
    weight -> params pytree with int8 weights + ``<name>_scale`` entries.

    The int8-weight serving mode (reference analogue: the fp8/int8 weight
    paths of trtllm-gen GEMMs): weights cross HBM at half width and every
    projection runs on the native int8 MXU (``gemm.mm_int8``) with dynamic
    per-row activation quantization.  Embedding stays high-precision (it
    is a gather, not a GEMM)."""
    from flashinfer_tpu.quantization import quantize_int8

    def q(w):
        wq, ws = quantize_int8(w.astype(jnp.float32), axis=0)  # [1, out]
        return wq, ws

    out = dict(params)
    new_layers = []
    for layer in params["layers"]:
        nl = dict(layer)
        for name in _LINEAR_NAMES:
            nl[name], nl[name + "_scale"] = q(layer[name])
        new_layers.append(nl)
    out["layers"] = new_layers
    if include_lm_head:
        out["lm_head"], out["lm_head_scale"] = q(params["lm_head"])
    return out


def _pre_quant(x, store, name="q_proj"):
    """Quantize an activation ONCE for reuse across the projections that
    share it (q/k/v, gate/up) — returns None on the bf16 path."""
    if store[name].dtype != jnp.int8:
        return None
    from flashinfer_tpu.quantization import quantize_int8

    return quantize_int8(x)


def _mm(x, store, name, pre=None):
    """Linear projection dispatching on the stored weight dtype: bf16
    einsum, or int8 MXU with folded activation/weight scales.  ``pre``
    is an optional pre-quantized ``(xq, xs)`` of ``x`` (``_pre_quant``)."""
    w = store[name]
    if w.dtype == jnp.int8:
        from flashinfer_tpu.gemm import mm_int8
        from flashinfer_tpu.quantization import quantize_int8

        xq, xs = pre if pre is not None else quantize_int8(x)
        return mm_int8(xq, w, xs, store[name + "_scale"], out_dtype=x.dtype)
    return x @ w


def _attn_decode(
    x, layer, cfg: LlamaConfig, kv_cache, page_table, kv_lens, positions,
    num_qo_heads: int, num_kv_heads: int, use_pallas: bool,
):
    """One decode-attention sublayer over local (possibly TP-sharded) heads.

    Returns (o_partial [B, qh*hd], updated kv_cache).  Cache layout HND:
    [num_pages, kvh, page_size, hd] (TPU-preferred, ops/paged_decode.py)."""
    B = x.shape[0]
    hd = cfg.head_dim
    pre = _pre_quant(x, layer)
    q = _mm(x, layer, "q_proj", pre).reshape(B, num_qo_heads, hd)
    k = _mm(x, layer, "k_proj", pre).reshape(B, num_kv_heads, hd)
    v = _mm(x, layer, "v_proj", pre).reshape(B, num_kv_heads, hd)
    q, k = apply_rope_pos_ids(q, k, positions, rope_theta=cfg.rope_theta)

    # append this step's K/V: page_table row lookup at the write position
    k_cache, v_cache = kv_cache
    page_size = k_cache.shape[2]
    page_in_req = positions // page_size
    slot = positions % page_size
    page_id = page_table[jnp.arange(B), page_in_req]
    int8_kv = k_cache.dtype == jnp.int8
    if int8_kv:
        from flashinfer_tpu.quantization import quantize_symmetric_int8

        k_w = quantize_symmetric_int8(k, cfg.kv_k_scale)
        v_w = quantize_symmetric_int8(v, cfg.kv_v_scale)
    else:
        k_w, v_w = k.astype(k_cache.dtype), v.astype(v_cache.dtype)
    # scatter [B, kvh, hd] rows into [pages, kvh, page_size, hd]
    k_cache = k_cache.at[page_id, :, slot, :].set(k_w)
    v_cache = v_cache.at[page_id, :, slot, :].set(v_w)

    kv_lens_inc = jnp.maximum(kv_lens, positions + 1)
    sm_scale = 1.0 / float(hd) ** 0.5
    if int8_kv:
        sm_scale = sm_scale * cfg.kv_k_scale
    fn = paged_decode_attention if use_pallas else xla_paged_decode
    kw = {}
    if use_pallas:
        # same tactic cache the decode wrapper consults (measured default
        # "static", scripts/exp_decode_prefetch.py: hides the per-request
        # cold-start chunk DMA with static slot indices); a banked "off"
        # for this shape reaches the model path too
        from flashinfer_tpu.autotuner import AutoTuner
        from flashinfer_tpu.ops.paged_decode import decode_tactic_key

        pf = AutoTuner.get().lookup(
            "paged_decode.prefetch",
            decode_tactic_key(B, page_table.shape[1], num_qo_heads,
                              num_kv_heads, hd, page_size, q.dtype),
            default="static",
        )
        kw["cross_step_prefetch"] = "static" if pf == "static" else False
    o = fn(
        q, k_cache, v_cache, page_table, kv_lens_inc,
        sm_scale=sm_scale, kv_layout="HND", **kw,
    )
    if int8_kv:
        o = (o.astype(jnp.float32) * cfg.kv_v_scale).astype(q.dtype)
    return o.reshape(B, num_qo_heads * hd), (k_cache, v_cache)


def llama_decode_step(
    params: Dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B] int32
    positions: jax.Array,  # [B] int32 (position of the new token)
    kv_caches: List[Tuple[jax.Array, jax.Array]],  # per layer, HND paged
    page_table: jax.Array,  # [B, P]
    kv_lens: jax.Array,  # [B] lens BEFORE this step
    use_pallas: bool = True,
) -> Tuple[jax.Array, List[Tuple[jax.Array, jax.Array]]]:
    """Single-device batched decode step -> (logits [B, vocab], new caches)."""
    x = params["embed"][tokens].astype(cfg.dtype)
    new_caches = []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
        attn, cache = _attn_decode(
            h, layer, cfg, kv_caches[li], page_table, kv_lens, positions,
            cfg.num_qo_heads, cfg.num_kv_heads, use_pallas,
        )
        new_caches.append(cache)
        x = x + _mm(attn, layer, "o_proj").astype(cfg.dtype)
        h = rmsnorm(x, layer["post_norm"], cfg.rms_eps)
        pre2 = _pre_quant(h, layer, "gate_proj")
        mlp_in = jnp.concatenate(
            [_mm(h, layer, "gate_proj", pre2),
             _mm(h, layer, "up_proj", pre2)], -1
        )
        x = x + _mm(silu_and_mul(mlp_in), layer, "down_proj").astype(cfg.dtype)
    x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
    logits = _mm(x, params, "lm_head").astype(jnp.float32)
    return logits, new_caches



def _tp_param_specs(cfg: LlamaConfig, tp: str, layer_leading=None,
                    quantized: bool = False):
    """Shared TP weight-sharding spec table (column-shard q/k/v/gate/up,
    row-shard o/down); ``layer_leading`` prepends an axis (pp layer stacks).
    With ``quantized``, each linear's [1, out] scale shards with the
    weight's out axis (tp for column-sharded, replicated for row-sharded
    whose out dim is full-width)."""
    def lp(*axes):
        return P(layer_leading, *axes) if layer_leading else P(*axes)

    layer = dict(
        input_norm=lp(None),
        q_proj=lp(None, tp), k_proj=lp(None, tp), v_proj=lp(None, tp),
        o_proj=lp(tp, None),
        post_norm=lp(None),
        gate_proj=lp(None, tp), up_proj=lp(None, tp),
        down_proj=lp(tp, None),
    )
    if quantized:
        for name in ("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj"):
            layer[name + "_scale"] = lp(None, tp)
        for name in ("o_proj", "down_proj"):
            layer[name + "_scale"] = lp(None, None)
    return layer


def _check_head_divisibility(cfg: LlamaConfig, tp_size: int) -> None:
    assert cfg.num_qo_heads % tp_size == 0, (
        f"num_qo_heads {cfg.num_qo_heads} not divisible by tp {tp_size}"
    )
    assert cfg.num_kv_heads % tp_size == 0, (
        f"num_kv_heads {cfg.num_kv_heads} not divisible by tp {tp_size}"
    )


def make_sharded_decode_step(mapping: Mapping, cfg: LlamaConfig, mesh=None,
                             quantized: bool = False):
    """Build a jitted dp x tp sharded decode step via shard_map.

    Weight sharding: q/k/v/gate/up column-sharded over tp, o/down
    row-sharded; attention runs on local kv heads; the o_proj and down_proj
    partial sums are combined with the fused allreduce(+residual+RMSNorm)
    from the comm layer — the reference's AR+norm fusion pattern
    (trtllm_allreduce_fusion) expressed as a compiled ICI collective.

    Returns (step_fn, mesh, specs) where specs maps each argument to its
    PartitionSpec."""
    mesh = mesh or mapping.make_mesh()
    tp, dp = Mapping.AXIS_TP, Mapping.AXIS_DP
    _check_head_divisibility(cfg, mapping.tp_size)
    qh_l = cfg.num_qo_heads // mapping.tp_size
    kvh_l = cfg.num_kv_heads // mapping.tp_size

    param_specs = dict(
        embed=P(None, None),
        final_norm=P(None),
        lm_head=P(None, tp),
        layers=[
            _tp_param_specs(cfg, tp, quantized=quantized)
            for _ in range(cfg.num_layers)
        ],
    )
    if quantized:
        param_specs["lm_head_scale"] = P(None, tp)
    cache_spec = [(P(dp, None, tp, None, None), P(dp, None, tp, None, None))
                  for _ in range(cfg.num_layers)]
    in_specs = (
        param_specs,
        P(dp),  # tokens [B]
        P(dp),  # positions [B]
        cache_spec,  # per layer (k, v): [dp, pages, kvh, page_size, hd]
        P(dp, None),  # page_table [B, P]
        P(dp),  # kv_lens [B]
    )
    out_specs = (P(dp, tp), cache_spec)

    def step(params, tokens, positions, kv_caches, page_table, kv_lens):
        page_table_l = page_table
        kv_lens_l = kv_lens
        x = params["embed"][tokens].astype(cfg.dtype)
        new_caches = []
        use_pallas = is_tpu()
        for li, layer in enumerate(params["layers"]):
            h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
            attn, cache = _attn_decode(
                h, layer, cfg, (kv_caches[li][0][0], kv_caches[li][1][0]),
                page_table_l, kv_lens_l, positions, qh_l, kvh_l, use_pallas,
            )
            new_caches.append((cache[0][None], cache[1][None]))
            # fused AR + residual-add + post-attention RMSNorm
            o_partial = _mm(attn, layer, "o_proj")
            h, x = allreduce_fusion(
                o_partial, residual=x, rms_weight=layer["post_norm"],
                eps=cfg.rms_eps, axis=tp,
            )
            h = h.astype(cfg.dtype)
            pre2 = _pre_quant(h, layer, "gate_proj")
            mlp_in = jnp.concatenate(
                [_mm(h, layer, "gate_proj", pre2),
                 _mm(h, layer, "up_proj", pre2)], -1
            )
            d_partial = _mm(silu_and_mul(mlp_in), layer, "down_proj")
            # MLP residual uses plain AR + add (next layer norms it)
            (x,) = allreduce_fusion(d_partial, residual=x, axis=tp)
        x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
        logits = _mm(x, params, "lm_head").astype(jnp.float32)  # [B, vocab/tp]
        return logits, new_caches

    sharded = jax.jit(
        jax_shard_map(
            step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    )
    return sharded, mesh, dict(params=param_specs, cache=cache_spec)


def stack_layer_params(params: Dict) -> Dict:
    """Stack the per-layer weight dicts into leading-layer-dim arrays
    (required for pipeline sharding: the layer dim shards over pp)."""
    layers = params["layers"]
    stacked = {
        k: jnp.stack([l[k] for l in layers]) for k in layers[0]
    }
    out = dict(params)
    out["layers"] = stacked
    return out


def make_cp_prefill_step(mapping: Mapping, cfg: LlamaConfig, mesh=None):
    """dp x cp x tp sharded PREFILL step: sequence sharded over the
    context-parallel axis with **ring attention** (parallel/attention.py),
    heads sharded over tp with fused-AR collectives — the long-context
    configuration (SURVEY §5: ring/Ulysses SP is first-class).

    Processes a [B, S] token block; returns sequence-sharded logits and the
    per-layer K/V (for cache append by the caller).  Causal over the global
    sequence (ring handles cross-chunk masking via positions).
    """
    mesh = mesh or mapping.make_mesh()
    tp, dp, cp = Mapping.AXIS_TP, Mapping.AXIS_DP, Mapping.AXIS_CP
    _check_head_divisibility(cfg, mapping.tp_size)
    qh_l = cfg.num_qo_heads // mapping.tp_size
    kvh_l = cfg.num_kv_heads // mapping.tp_size

    param_specs = dict(
        embed=P(None, None),
        final_norm=P(None),
        lm_head=P(None, tp),
        layers=[_tp_param_specs(cfg, tp) for _ in range(cfg.num_layers)],
    )
    in_specs = (param_specs, P(dp, cp))  # tokens [B, S] seq-sharded over cp
    kv_spec = [(P(dp, cp, tp, None), P(dp, cp, tp, None))
               for _ in range(cfg.num_layers)]
    out_specs = (P(dp, cp, tp), kv_spec)

    from flashinfer_tpu.parallel.attention import ring_attention

    def step(params, tokens):
        B, S_local = tokens.shape
        me = jax.lax.axis_index(cp)
        pos = (me * S_local + jnp.arange(S_local, dtype=jnp.int32))
        x = params["embed"][tokens].astype(cfg.dtype)  # [B, S_local, h]
        kvs = []
        for layer in params["layers"]:
            h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
            pre = _pre_quant(h, layer)
            q = _mm(h, layer, "q_proj", pre).reshape(B, S_local, qh_l, cfg.head_dim)
            k = _mm(h, layer, "k_proj", pre).reshape(B, S_local, kvh_l, cfg.head_dim)
            v = _mm(h, layer, "v_proj", pre).reshape(B, S_local, kvh_l, cfg.head_dim)
            qr, kr = jax.vmap(
                lambda qq, kk: apply_rope_pos_ids(
                    qq, kk, pos, rope_theta=cfg.rope_theta
                )
            )(q, k)
            attn = jax.vmap(
                lambda qq, kk, vv: ring_attention(
                    qq, kk, vv, axis=cp, causal=True,
                    sm_scale=1.0 / float(cfg.head_dim) ** 0.5,
                )
            )(qr, kr, v)
            kvs.append((kr, v))
            o_partial = _mm(attn.reshape(B, S_local, qh_l * cfg.head_dim), layer, "o_proj")
            h2, x = allreduce_fusion(
                o_partial, residual=x, rms_weight=layer["post_norm"],
                eps=cfg.rms_eps, axis=tp,
            )
            h2 = h2.astype(cfg.dtype)
            _pq2 = _pre_quant(h2, layer, "gate_proj")
            mlp_in = jnp.concatenate(
                [_mm(h2, layer, "gate_proj", _pq2),
                 _mm(h2, layer, "up_proj", _pq2)], -1
            )
            d_partial = _mm(silu_and_mul(mlp_in), layer, "down_proj")
            (x,) = allreduce_fusion(d_partial, residual=x, axis=tp)
        x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
        logits = _mm(x, params, "lm_head").astype(jnp.float32)
        return logits, kvs

    sharded = jax.jit(
        jax_shard_map(
            step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    )
    return sharded, mesh, dict(
        params=param_specs, tokens=P(dp, cp), kv=kv_spec
    )


def _make_pp_layer_runner(cfg, qh_l, kvh_l, tp):
    """Per-stage layer scan shared by the sequential and microbatched pp
    steps (one definition — a numerics fix must reach both schedules)."""

    def run_local_layers(layers, x, caches, page_table, kv_lens, positions):
        use_pallas = is_tpu()

        def body(x, inp):
            layer, kc, vc = inp
            h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
            attn, (kc2, vc2) = _attn_decode(
                h, layer, cfg, (kc, vc), page_table, kv_lens, positions,
                qh_l, kvh_l, use_pallas,
            )
            o_partial = _mm(attn, layer, "o_proj")
            h2, x2 = allreduce_fusion(
                o_partial, residual=x, rms_weight=layer["post_norm"],
                eps=cfg.rms_eps, axis=tp,
            )
            h2 = h2.astype(cfg.dtype)
            _pq2 = _pre_quant(h2, layer, "gate_proj")
            mlp_in = jnp.concatenate(
                [_mm(h2, layer, "gate_proj", _pq2),
                 _mm(h2, layer, "up_proj", _pq2)], -1
            )
            d_partial = _mm(silu_and_mul(mlp_in), layer, "down_proj")
            (x3,) = allreduce_fusion(d_partial, residual=x2, axis=tp)
            return x3, (kc2, vc2)

        kcs, vcs = caches
        x, (kcs2, vcs2) = jax.lax.scan(body, x, (layers, kcs, vcs))
        return x, (kcs2, vcs2)

    return run_local_layers


def make_pp_sharded_decode_step(mapping: Mapping, cfg: LlamaConfig, mesh=None):
    """dp x tp x pp sharded decode step.

    Pipeline parallelism the TPU way: layers stack along a leading dim
    sharded over the ``pp`` mesh axis (Mapping.pp_layers partition);
    activations traverse stages via ``lax.ppermute`` ring hops, and the
    final stage's logits are broadcast back with a masked psum.  Single
    token-batch decode runs the stages sequentially (microbatch overlap is
    a scheduling refinement on top of the same wiring).  TP within each
    stage works exactly as in make_sharded_decode_step (fused AR+norm).

    Expects ``stack_layer_params``-formatted params and per-layer-stacked
    caches ``(k, v) [L, dp, pages, kvh, ps, hd]``.
    """
    mesh = mesh or mapping.make_mesh()
    tp, dp, pp = Mapping.AXIS_TP, Mapping.AXIS_DP, Mapping.AXIS_PP
    assert cfg.num_layers % mapping.pp_size == 0
    _check_head_divisibility(cfg, mapping.tp_size)
    qh_l = cfg.num_qo_heads // mapping.tp_size
    kvh_l = cfg.num_kv_heads // mapping.tp_size
    pp_size = mapping.pp_size

    layer_specs = _tp_param_specs(cfg, tp, layer_leading=pp)
    param_specs = dict(
        embed=P(None, None), final_norm=P(None), lm_head=P(None, tp),
        layers=layer_specs,
    )
    cache_spec = (
        P(pp, dp, None, tp, None, None),
        P(pp, dp, None, tp, None, None),
    )
    in_specs = (param_specs, P(dp), P(dp), cache_spec, P(dp, None), P(dp))
    out_specs = (P(dp, tp), cache_spec)

    run_local_layers = _make_pp_layer_runner(cfg, qh_l, kvh_l, tp)

    def step(params, tokens, positions, kv_caches, page_table, kv_lens):
        my_stage = jax.lax.axis_index(pp)
        x = params["embed"][tokens].astype(cfg.dtype)
        # drop the sharded leading dims: layers [L_local, ...], cache
        # [L_local, 1(dp), pages, kvh_l, ps, hd]
        kcs = kv_caches[0][:, 0]
        vcs = kv_caches[1][:, 0]
        perm = [(i, (i + 1) % pp_size) for i in range(pp_size)]

        def stage_iter(s, carry):
            x, kcs, vcs = carry
            is_mine = my_stage == s
            x2, (kcs2, vcs2) = run_local_layers(
                params["layers"], x, (kcs, vcs), page_table, kv_lens, positions
            )
            # only the active stage advances the activation/caches
            x = jnp.where(is_mine, x2, x)
            kcs = jnp.where(is_mine, kcs2, kcs)
            vcs = jnp.where(is_mine, vcs2, vcs)
            # hand the activation to the next stage
            x = jax.lax.ppermute(x, pp, perm)
            return (x, kcs, vcs)

        x, kcs, vcs = jax.lax.fori_loop(
            0, pp_size, stage_iter, (x, kcs, vcs)
        )
        # after pp_size ring hops the fully-processed activation is back at
        # every rank in turn; it now sits on stage 0 — broadcast via psum
        x = jax.lax.psum(jnp.where(my_stage == 0, x, 0.0), pp)
        x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
        logits = _mm(x, params, "lm_head").astype(jnp.float32)
        return logits, (kcs[:, None], vcs[:, None])

    sharded = jax.jit(
        jax_shard_map(
            step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    )
    return sharded, mesh, dict(params=param_specs, cache=cache_spec)


def make_pp_microbatch_decode_step(
    mapping: Mapping, cfg: LlamaConfig, num_microbatches: int, mesh=None,
):
    """GPipe-style microbatched dp x tp x pp decode step.

    ``make_pp_sharded_decode_step`` runs the stages sequentially — at any
    tick pp_size - 1 stages idle.  Here each dp shard's token batch
    splits into M microbatches that flow through the stage ring as a
    (M + pp_size - 1)-tick software pipeline: stage s runs microbatch m
    at tick s + m, so in steady state every stage computes while the
    ring ppermutes activations one hop per tick (all inside one jitted
    fori_loop — no host threads, uniform control flow, masked commits).

    Reference analogue: Mapping.pp_layers layer partitioning
    (/root/reference/flashinfer/comm/mapping.py:442); the schedule is
    TPU-native.  Same params/cache/spec layout as the sequential step.
    """
    mesh = mesh or mapping.make_mesh()
    tp, dp, pp = Mapping.AXIS_TP, Mapping.AXIS_DP, Mapping.AXIS_PP
    assert cfg.num_layers % mapping.pp_size == 0
    _check_head_divisibility(cfg, mapping.tp_size)
    qh_l = cfg.num_qo_heads // mapping.tp_size
    kvh_l = cfg.num_kv_heads // mapping.tp_size
    pp_size = mapping.pp_size
    M = int(num_microbatches)

    layer_specs = _tp_param_specs(cfg, tp, layer_leading=pp)
    param_specs = dict(
        embed=P(None, None), final_norm=P(None), lm_head=P(None, tp),
        layers=layer_specs,
    )
    cache_spec = (
        P(pp, dp, None, tp, None, None),
        P(pp, dp, None, tp, None, None),
    )
    in_specs = (param_specs, P(dp), P(dp), cache_spec, P(dp, None), P(dp))
    out_specs = (P(dp, tp), cache_spec)

    run_local_layers = _make_pp_layer_runner(cfg, qh_l, kvh_l, tp)

    def step(params, tokens, positions, kv_caches, page_table, kv_lens):
        my_stage = jax.lax.axis_index(pp)
        b_local = tokens.shape[0]
        assert b_local % M == 0, (
            f"per-dp-shard batch {b_local} must divide into "
            f"{M} microbatches"
        )
        mbs = b_local // M
        x_all = params["embed"][tokens].astype(cfg.dtype)
        kcs = kv_caches[0][:, 0]
        vcs = kv_caches[1][:, 0]
        perm = [(i, (i + 1) % pp_size) for i in range(pp_size)]
        # the final stage BUFFERS its finished activations per tick and
        # the vocab projection (decode's largest matmul at 128k vocab)
        # runs ONCE over the whole batch after the loop — not per tick
        # per stage
        xfin_buf = jnp.zeros((b_local, x_all.shape[1]), cfg.dtype)
        act = jnp.zeros((mbs, x_all.shape[1]), cfg.dtype)

        def tick(t, carry):
            act, kcs, vcs, xfin_buf = carry
            mb_idx = t - my_stage
            active = (mb_idx >= 0) & (mb_idx < M)
            safe = jnp.clip(mb_idx, 0, M - 1)
            row0 = safe * mbs
            # stage 0 ingests a fresh microbatch; later stages use the
            # activation the ring delivered last tick
            fresh = jax.lax.dynamic_slice_in_dim(x_all, row0, mbs, 0)
            inp = jnp.where(my_stage == 0, fresh, act)
            pt_mb = jax.lax.dynamic_slice_in_dim(page_table, row0, mbs, 0)
            lens_mb = jax.lax.dynamic_slice_in_dim(kv_lens, row0, mbs, 0)
            pos_mb = jax.lax.dynamic_slice_in_dim(positions, row0, mbs, 0)
            x2, (kcs2, vcs2) = run_local_layers(
                params["layers"], inp, (kcs, vcs), pt_mb, lens_mb, pos_mb
            )
            # only active ticks commit state (bubbles pass through)
            out_act = jnp.where(active, x2, inp)
            kcs = jnp.where(active, kcs2, kcs)
            vcs = jnp.where(active, vcs2, vcs)
            # final stage banks this microbatch's finished activation
            cur = jax.lax.dynamic_slice_in_dim(xfin_buf, row0, mbs, 0)
            emit = active & (my_stage == pp_size - 1)
            xfin_buf = jax.lax.dynamic_update_slice_in_dim(
                xfin_buf, jnp.where(emit, x2, cur), row0, 0
            )
            act = jax.lax.ppermute(out_act, pp, perm)
            return (act, kcs, vcs, xfin_buf)

        act, kcs, vcs, xfin_buf = jax.lax.fori_loop(
            0, M + pp_size - 1, tick, (act, kcs, vcs, xfin_buf)
        )
        # finished activations live on the last stage; broadcast, then
        # one final-norm + lm_head over the whole batch
        xfin = jax.lax.psum(
            jnp.where(my_stage == pp_size - 1,
                      xfin_buf.astype(jnp.float32), 0.0), pp
        ).astype(cfg.dtype)
        xf = rmsnorm(xfin, params["final_norm"], cfg.rms_eps)
        logits = _mm(xf, params, "lm_head").astype(jnp.float32)
        return logits, (kcs[:, None], vcs[:, None])

    sharded = jax.jit(
        jax_shard_map(
            step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    )
    return sharded, mesh, dict(params=param_specs, cache=cache_spec)
