"""Flagship integration model: Llama-3-style decoder wired to the library.

The reference keeps models in its consumers and ships integration blocks
(``examples/pytorch/flashinfer_modules.py`` — FlashInferAttentionDispatcher /
Linear / RMSNorm / FFN); this module is the TPU equivalent *and* the
end-to-end proof for the minimum slice (SURVEY §7 step 2): a paged-KV batch
decode step built entirely from flashinfer_tpu ops:

    rmsnorm -> qkv proj -> RoPE -> append_paged_kv_cache ->
    paged_decode_attention -> o proj -> fused allreduce+add+rmsnorm (TP) ->
    silu_and_mul MLP -> logits

Two entry points: ``llama_decode_step`` (single device, jittable) and
``make_sharded_decode_step`` (shard_map over a Mapping mesh with dp x tp
sharding; TP allreduces ride ICI via the comm layer).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flashinfer_tpu.activation import silu_and_mul
from flashinfer_tpu.comm.allreduce import allreduce_fusion
from flashinfer_tpu.comm.mapping import Mapping
from flashinfer_tpu.norm import rmsnorm
from flashinfer_tpu.ops.paged_decode import paged_decode_attention
from flashinfer_tpu.ops.xla_ref import xla_paged_decode
from flashinfer_tpu.rope import apply_rope_pos_ids
from flashinfer_tpu.utils import is_tpu


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_qo_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 5e5
    rms_eps: float = 1e-5
    dtype: object = jnp.bfloat16

    @staticmethod
    def llama3_8b(**over) -> "LlamaConfig":
        return LlamaConfig(**over)

    @staticmethod
    def tiny(**over) -> "LlamaConfig":
        """Small config for tests/dryruns."""
        d = dict(
            vocab_size=512, hidden_size=256, intermediate_size=512,
            num_layers=2, num_qo_heads=8, num_kv_heads=4, head_dim=32,
        )
        d.update(over)
        return LlamaConfig(**d)


def init_llama_params(key: jax.Array, cfg: LlamaConfig) -> Dict:
    """Random-initialized parameter pytree (layout mirrors HF llama naming)."""
    h, qh, kvh, hd = cfg.hidden_size, cfg.num_qo_heads, cfg.num_kv_heads, cfg.head_dim
    keys = iter(jax.random.split(key, 4 + 8 * cfg.num_layers))

    def w(shape, scale=0.02):
        return (jax.random.normal(next(keys), shape, jnp.float32) * scale).astype(
            cfg.dtype
        )

    layers = []
    for _ in range(cfg.num_layers):
        layers.append(
            dict(
                input_norm=jnp.ones((h,), cfg.dtype),
                q_proj=w((h, qh * hd)),
                k_proj=w((h, kvh * hd)),
                v_proj=w((h, kvh * hd)),
                o_proj=w((qh * hd, h)),
                post_norm=jnp.ones((h,), cfg.dtype),
                gate_proj=w((h, cfg.intermediate_size)),
                up_proj=w((h, cfg.intermediate_size)),
                down_proj=w((cfg.intermediate_size, h)),
            )
        )
    return dict(
        embed=w((cfg.vocab_size, h)),
        final_norm=jnp.ones((h,), cfg.dtype),
        lm_head=w((h, cfg.vocab_size)),
        layers=layers,
    )


def _attn_decode(
    x, layer, cfg: LlamaConfig, kv_cache, page_table, kv_lens, positions,
    num_qo_heads: int, num_kv_heads: int, use_pallas: bool,
):
    """One decode-attention sublayer over local (possibly TP-sharded) heads.

    Returns (o_partial [B, qh*hd], updated kv_cache).  Cache layout HND:
    [num_pages, kvh, page_size, hd] (TPU-preferred, ops/paged_decode.py)."""
    B = x.shape[0]
    hd = cfg.head_dim
    q = (x @ layer["q_proj"]).reshape(B, num_qo_heads, hd)
    k = (x @ layer["k_proj"]).reshape(B, num_kv_heads, hd)
    v = (x @ layer["v_proj"]).reshape(B, num_kv_heads, hd)
    q, k = apply_rope_pos_ids(q, k, positions, rope_theta=cfg.rope_theta)

    # append this step's K/V: page_table row lookup at the write position
    k_cache, v_cache = kv_cache
    page_size = k_cache.shape[2]
    page_in_req = positions // page_size
    slot = positions % page_size
    page_id = page_table[jnp.arange(B), page_in_req]
    # scatter [B, kvh, hd] rows into [pages, kvh, page_size, hd]
    k_cache = k_cache.at[page_id, :, slot, :].set(k.astype(k_cache.dtype))
    v_cache = v_cache.at[page_id, :, slot, :].set(v.astype(v_cache.dtype))

    kv_lens_inc = jnp.maximum(kv_lens, positions + 1)
    sm_scale = 1.0 / float(hd) ** 0.5
    if use_pallas:
        o = paged_decode_attention(
            q, k_cache, v_cache, page_table, kv_lens_inc,
            sm_scale=sm_scale, kv_layout="HND",
        )
    else:
        o = xla_paged_decode(
            q, k_cache, v_cache, page_table, kv_lens_inc,
            sm_scale=sm_scale, kv_layout="HND",
        )
    return o.reshape(B, num_qo_heads * hd), (k_cache, v_cache)


def llama_decode_step(
    params: Dict,
    cfg: LlamaConfig,
    tokens: jax.Array,  # [B] int32
    positions: jax.Array,  # [B] int32 (position of the new token)
    kv_caches: List[Tuple[jax.Array, jax.Array]],  # per layer, HND paged
    page_table: jax.Array,  # [B, P]
    kv_lens: jax.Array,  # [B] lens BEFORE this step
    use_pallas: bool = True,
) -> Tuple[jax.Array, List[Tuple[jax.Array, jax.Array]]]:
    """Single-device batched decode step -> (logits [B, vocab], new caches)."""
    x = params["embed"][tokens].astype(cfg.dtype)
    new_caches = []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
        attn, cache = _attn_decode(
            h, layer, cfg, kv_caches[li], page_table, kv_lens, positions,
            cfg.num_qo_heads, cfg.num_kv_heads, use_pallas,
        )
        new_caches.append(cache)
        x = x + (attn @ layer["o_proj"]).astype(cfg.dtype)
        h = rmsnorm(x, layer["post_norm"], cfg.rms_eps)
        mlp_in = jnp.concatenate([h @ layer["gate_proj"], h @ layer["up_proj"]], -1)
        x = x + (silu_and_mul(mlp_in) @ layer["down_proj"]).astype(cfg.dtype)
    x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, new_caches


def make_sharded_decode_step(mapping: Mapping, cfg: LlamaConfig, mesh=None):
    """Build a jitted dp x tp sharded decode step via shard_map.

    Weight sharding: q/k/v/gate/up column-sharded over tp, o/down
    row-sharded; attention runs on local kv heads; the o_proj and down_proj
    partial sums are combined with the fused allreduce(+residual+RMSNorm)
    from the comm layer — the reference's AR+norm fusion pattern
    (trtllm_allreduce_fusion) expressed as a compiled ICI collective.

    Returns (step_fn, mesh, specs) where specs maps each argument to its
    PartitionSpec."""
    mesh = mesh or mapping.make_mesh()
    tp, dp = Mapping.AXIS_TP, Mapping.AXIS_DP
    assert cfg.num_kv_heads % mapping.tp_size == 0
    qh_l = cfg.num_qo_heads // mapping.tp_size
    kvh_l = cfg.num_kv_heads // mapping.tp_size

    param_specs = dict(
        embed=P(None, None),
        final_norm=P(None),
        lm_head=P(None, tp),
        layers=[
            dict(
                input_norm=P(None),
                q_proj=P(None, tp), k_proj=P(None, tp), v_proj=P(None, tp),
                o_proj=P(tp, None),
                post_norm=P(None),
                gate_proj=P(None, tp), up_proj=P(None, tp),
                down_proj=P(tp, None),
            )
            for _ in range(cfg.num_layers)
        ],
    )
    cache_spec = [(P(dp, None, tp, None, None), P(dp, None, tp, None, None))
                  for _ in range(cfg.num_layers)]
    in_specs = (
        param_specs,
        P(dp),  # tokens [B]
        P(dp),  # positions [B]
        cache_spec,  # per layer (k, v): [dp, pages, kvh, page_size, hd]
        P(dp, None),  # page_table [B, P]
        P(dp),  # kv_lens [B]
    )
    out_specs = (P(dp, tp), cache_spec)

    def step(params, tokens, positions, kv_caches, page_table, kv_lens):
        page_table_l = page_table
        kv_lens_l = kv_lens
        x = params["embed"][tokens].astype(cfg.dtype)
        new_caches = []
        use_pallas = is_tpu()
        for li, layer in enumerate(params["layers"]):
            h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
            attn, cache = _attn_decode(
                h, layer, cfg, (kv_caches[li][0][0], kv_caches[li][1][0]),
                page_table_l, kv_lens_l, positions, qh_l, kvh_l, use_pallas,
            )
            new_caches.append((cache[0][None], cache[1][None]))
            # fused AR + residual-add + post-attention RMSNorm
            o_partial = attn @ layer["o_proj"]
            h, x = allreduce_fusion(
                o_partial, residual=x, rms_weight=layer["post_norm"],
                eps=cfg.rms_eps, axis=tp,
            )
            h = h.astype(cfg.dtype)
            mlp_in = jnp.concatenate(
                [h @ layer["gate_proj"], h @ layer["up_proj"]], -1
            )
            d_partial = silu_and_mul(mlp_in) @ layer["down_proj"]
            # MLP residual uses plain AR + add (next layer norms it)
            (x,) = allreduce_fusion(d_partial, residual=x, axis=tp)
        x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
        logits = (x @ params["lm_head"]).astype(jnp.float32)  # [B, vocab/tp]
        return logits, new_caches

    sharded = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    )
    return sharded, mesh, dict(params=param_specs, cache=cache_spec)
