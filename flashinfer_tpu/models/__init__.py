from flashinfer_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    init_llama_params,
    llama_decode_step,
    make_cp_prefill_step,
    make_pp_microbatch_decode_step,
    make_pp_sharded_decode_step,
    make_sharded_decode_step,
    quantize_llama_weights,
    stack_layer_params,
)
from flashinfer_tpu.models.mixtral import (  # noqa: F401
    MixtralConfig,
    init_mixtral_params,
    make_ep_sharded_decode_step,
    mixtral_decode_step,
)
from flashinfer_tpu.models.deepseek import (  # noqa: F401
    DeepseekConfig,
    deepseek_decode_step,
    deepseek_prefill,
    init_deepseek_params,
    make_ep_sharded_decode_step as make_deepseek_ep_decode_step,
)
