from flashinfer_tpu.models.llama import (  # noqa: F401
    LlamaConfig,
    init_llama_params,
    llama_decode_step,
    make_cp_prefill_step,
    make_pp_sharded_decode_step,
    make_sharded_decode_step,
    stack_layer_params,
)
