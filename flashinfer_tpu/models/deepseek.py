"""DeepSeek-V3-style MLA + routed-MoE decoder wired to the library.

The third integration model family (reference serves this architecture
through its MLA + fused-MoE + DSv3-routing blocks: ``flashinfer/mla/``,
``flashinfer/fused_moe/``, ``noAuxTcKernels``; benchmarks
``bench_deepseek_mla.py``).  What it exercises end-to-end that llama/
mixtral do not:

- **MLA decode with weight absorption**: queries live in the compressed
  latent space — ``q_nope`` is absorbed through ``w_kc`` into the ckv
  dimension BEFORE attention, so the paged cache stores only the latent
  ``ckv`` (kv_lora_rank) plus the shared rope key ``kpe``; attention
  runs on ``ops/mla_decode`` and the output is un-absorbed through
  ``w_vc``.  The kpe cache uses the TPU-native lane-padded-128 layout.
- **DeepSeek-V3 no-aux routing**: sigmoid scores + correction bias,
  group-limited top-k (``route_deepseek_v3``), router logits via
  ``dsv3_ops.router_gemm``, plus a SHARED expert alongside the routed
  block and dense first-k layers — the real DSv3 layer plan.

Entry points mirror ``models/mixtral.py``:

- ``deepseek_decode_step`` — single device, jittable.
- ``make_ep_sharded_decode_step`` — shard_map over dp x ep: attention +
  shared expert replicated per chip on its local batch rows, routed
  experts contiguously sharded over ep via ``fused_moe_ep``.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flashinfer_tpu.activation import silu_and_mul
from flashinfer_tpu.comm.mapping import Mapping
from flashinfer_tpu.dsv3_ops import router_gemm
from flashinfer_tpu.fused_moe import fused_moe, fused_moe_ep
from flashinfer_tpu.fused_moe.routing import route_deepseek_v3
from flashinfer_tpu.norm import rmsnorm
from flashinfer_tpu.ops.mla_decode import (
    mla_paged_decode_attention,
    xla_mla_paged_decode,
)
from flashinfer_tpu.rope import apply_rope_pos_ids
from flashinfer_tpu.utils import is_tpu


@dataclass(frozen=True)
class DeepseekConfig:
    # defaults are DeepSeek-V3 671B scale (config.json of the released
    # model); use .tiny() for test shapes
    vocab_size: int = 129280
    hidden_size: int = 7168
    num_layers: int = 61
    num_heads: int = 128
    q_lora_rank: int = 1536
    kv_lora_rank: int = 512  # ckv latent dim
    head_dim_nope: int = 128  # per-head latent-query dim
    head_dim_kpe: int = 64  # shared rope dim
    # MoE
    num_experts: int = 256
    top_k: int = 8
    n_group: int = 8
    topk_group: int = 4
    routed_scaling_factor: float = 2.5
    moe_intermediate_size: int = 2048
    shared_intermediate_size: int = 2048
    first_k_dense: int = 3  # leading dense-MLP layers
    dense_intermediate_size: int = 18432
    rope_theta: float = 1e4
    rms_eps: float = 1e-6
    dtype: object = jnp.float32

    @staticmethod
    def tiny(**over) -> "DeepseekConfig":
        d = dict(
            vocab_size=512, hidden_size=256, num_layers=2, num_heads=4,
            q_lora_rank=96, kv_lora_rank=128, head_dim_nope=32,
            head_dim_kpe=64, num_experts=8, top_k=2, n_group=4,
            topk_group=2, routed_scaling_factor=1.0,
            moe_intermediate_size=64, shared_intermediate_size=64,
            first_k_dense=1, dense_intermediate_size=128,
        )
        d.update(over)
        return DeepseekConfig(**d)


def init_deepseek_params(key: jax.Array, cfg: DeepseekConfig) -> Dict:
    h, H = cfg.hidden_size, cfg.num_heads
    nope, kpe, ckv = cfg.head_dim_nope, cfg.head_dim_kpe, cfg.kv_lora_rank
    keys = iter(jax.random.split(key, 4 + 16 * cfg.num_layers))

    def w(shape, scale=0.02):
        return (
            jax.random.normal(next(keys), shape, jnp.float32) * scale
        ).astype(cfg.dtype)

    layers = []
    for li in range(cfg.num_layers):
        layer = dict(
            input_norm=jnp.ones((h,), cfg.dtype),
            q_a=w((h, cfg.q_lora_rank)),
            q_a_norm=jnp.ones((cfg.q_lora_rank,), cfg.dtype),
            q_b=w((cfg.q_lora_rank, H * (nope + kpe))),
            kv_a=w((h, ckv + kpe)),
            kv_a_norm=jnp.ones((ckv,), cfg.dtype),
            # absorption weights (reference k_b/v_b projections reshaped
            # per head): scores and outputs stay in the ckv latent space
            w_kc=w((H, nope, ckv)),
            w_vc=w((H, ckv, nope)),
            o_proj=w((H * nope, h)),
            post_norm=jnp.ones((h,), cfg.dtype),
        )
        if li < cfg.first_k_dense:
            di = cfg.dense_intermediate_size
            layer.update(
                gate_up=w((h, 2 * di)),
                down=w((di, h)),
            )
        else:
            E, I = cfg.num_experts, cfg.moe_intermediate_size
            Is = cfg.shared_intermediate_size
            layer.update(
                router=w((h, E), scale=0.1).astype(jnp.float32),
                e_bias=jnp.zeros((E,), jnp.float32),
                w_gate_up=w((E, h, 2 * I)),
                w_down=w((E, I, h)),
                shared_gate_up=w((h, 2 * Is)),
                shared_down=w((Is, h)),
            )
        layers.append(layer)
    return dict(
        embed=w((cfg.vocab_size, h)),
        final_norm=jnp.ones((h,), cfg.dtype),
        lm_head=w((h, cfg.vocab_size)),
        layers=layers,
    )


def _project_latents(x, layer, cfg: DeepseekConfig, positions):
    """Shared per-token MLA projections on a FLAT token axis: returns
    (q_nope [N, H, nope], roped q_pe [N, H, kpe], ckv [N, ckv],
    roped kpe [N, kpe]) — one definition for the decode step and the
    prefill path (their cache-sharing contract depends on identical
    latent math)."""
    H, nope, kpe = cfg.num_heads, cfg.head_dim_nope, cfg.head_dim_kpe
    ckv_dim = cfg.kv_lora_rank
    N = x.shape[0]
    q_lat = rmsnorm(x @ layer["q_a"], layer["q_a_norm"], cfg.rms_eps)
    q = (q_lat @ layer["q_b"]).reshape(N, H, nope + kpe)
    q_nope, q_pe = q[..., :nope], q[..., nope:]
    kv = x @ layer["kv_a"]  # [N, ckv + kpe]
    ckv = rmsnorm(kv[:, :ckv_dim], layer["kv_a_norm"], cfg.rms_eps)
    kpe_k = kv[:, None, ckv_dim:]  # [N, 1, kpe] — shared across heads
    q_pe, kpe_k = apply_rope_pos_ids(
        q_pe, kpe_k, positions, rope_theta=cfg.rope_theta
    )
    return q_nope, q_pe, ckv, kpe_k[:, 0]


def _append_latents(cache, rows, ckv, kpe_vec, kpe_dim: int):
    """Write per-token latents into the paged (ckv, lane-padded kpe)
    caches at flat ``rows`` — the ONE cache-append definition."""
    ckv_cache, kpe_cache = cache
    cflat = ckv_cache.reshape(-1, ckv_cache.shape[-1])
    pflat = kpe_cache.reshape(-1, kpe_cache.shape[-1])
    cflat = cflat.at[rows].set(ckv.astype(cflat.dtype))
    pflat = pflat.at[rows, :kpe_dim].set(kpe_vec.astype(pflat.dtype))
    return cflat.reshape(ckv_cache.shape), pflat.reshape(kpe_cache.shape)


def _mla_attn_decode(
    x, layer, cfg: DeepseekConfig, cache, page_table, kv_lens, positions,
    use_pallas: bool,
):
    """Absorbed MLA decode sublayer -> (o [B, H*nope], new (ckv, kpe)).

    Score identity: ``q_nope_abs . ckv_j == q_nope . (w_kc ckv_j)`` — the
    unabsorbed per-head key — so softmax scale is 1/sqrt(nope + kpe),
    the reference's qk_head_dim scale."""
    B = x.shape[0]
    H, nope, kpe = cfg.num_heads, cfg.head_dim_nope, cfg.head_dim_kpe

    q_nope, q_pe, ckv_new, kpe_new = _project_latents(
        x, layer, cfg, positions
    )

    # absorb the nope query into the latent space: [B, H, ckv]
    q_abs = jnp.einsum(
        "bhn,hnc->bhc", q_nope.astype(jnp.float32),
        layer["w_kc"].astype(jnp.float32),
    ).astype(x.dtype)

    # append this token's (ckv, kpe) into the paged caches
    ps = cache[0].shape[1]
    page_id = page_table[jnp.arange(B), positions // ps]
    rows = page_id * ps + positions % ps
    ckv_cache, kpe_cache = _append_latents(
        cache, rows, ckv_new, kpe_new, kpe
    )

    kv_lens_inc = jnp.maximum(kv_lens, positions + 1)
    sm_scale = 1.0 / float(nope + kpe) ** 0.5
    fn = mla_paged_decode_attention if use_pallas else xla_mla_paged_decode
    out = fn(
        q_abs, q_pe, ckv_cache, kpe_cache, page_table, kv_lens_inc,
        sm_scale=sm_scale,
    )  # [B, H, ckv]

    # un-absorb: latent outputs back to per-head nope dims
    o = jnp.einsum(
        "bhc,hcn->bhn", out.astype(jnp.float32),
        layer["w_vc"].astype(jnp.float32),
    ).astype(x.dtype)
    return o.reshape(B, H * nope), (ckv_cache, kpe_cache)


def _dsv3_moe_block(h, layer, cfg: DeepseekConfig, moe_fn=fused_moe):
    """DSv3 layer MLP: no-aux-routed experts + the always-on shared
    expert.  ``moe_fn`` swaps in the EP-sharded kernel (routing stays in
    ONE place for both step builders)."""
    logits = router_gemm(h, layer["router"])
    wts, ids = route_deepseek_v3(
        logits, layer["e_bias"], cfg.top_k, cfg.n_group, cfg.topk_group,
        cfg.routed_scaling_factor,
    )
    routed = moe_fn(
        h, layer["w_gate_up"], layer["w_down"], wts, ids, cfg.num_experts
    )
    shared = silu_and_mul(h @ layer["shared_gate_up"]) @ layer["shared_down"]
    return (routed + shared.astype(jnp.float32)).astype(h.dtype)


def _layer_mlp(h, layer, cfg: DeepseekConfig, moe_fn=fused_moe):
    if "router" in layer:
        return _dsv3_moe_block(h, layer, cfg, moe_fn)
    return (silu_and_mul(h @ layer["gate_up"]) @ layer["down"]).astype(
        h.dtype
    )


def deepseek_prefill(
    params: Dict,
    cfg: DeepseekConfig,
    tokens: jax.Array,  # [B, L] int32 prompt tokens
    caches: List[Tuple[jax.Array, jax.Array]],  # per layer (ckv, kpe)
    page_table: jax.Array,  # [B, max_pages]
):
    """Batched prefill -> (logits [B, L, vocab], caches).

    MLA prefill runs UNABSORBED (the reference's prefill regime: at long
    q the per-head materialization amortizes, and the fmha path wants
    standard per-head K/V): explicit per-head keys ``k_nope = w_kc ckv``
    and values ``v = w_vc^T ckv`` run through the library's STREAMING
    segment-flash attention (asymmetric qk/vo head dims; scores never
    materialize as [L, L] per head), while the paged cache still stores
    only the LATENT (ckv, lane-padded kpe) — so decode continues
    ABSORBED from the same cache.  The absorption identity makes the two
    regimes numerically interchangeable (tested against a pure
    stepwise-decode consumption)."""
    from flashinfer_tpu.ops.flash_attention import flash_attention
    from flashinfer_tpu.ops.xla_ref import xla_ragged_attention
    from flashinfer_tpu.utils import is_tpu

    B, L = tokens.shape
    H, nope, kpe = cfg.num_heads, cfg.head_dim_nope, cfg.head_dim_kpe
    N = B * L
    sm = 1.0 / float(nope + kpe) ** 0.5
    positions = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32), (B, L))
    pos_flat = positions.reshape(-1)
    seg = jnp.repeat(jnp.arange(B, dtype=jnp.int32), L)
    attn_fn = flash_attention if is_tpu() else xla_ragged_attention

    x = params["embed"][tokens].astype(cfg.dtype).reshape(N, -1)
    new_caches = []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
        q_nope, q_pe, ckv, kpe_vec = _project_latents(
            h, layer, cfg, pos_flat
        )
        # append the latents into the paged cache (decode reads these)
        ps = caches[li][0].shape[1]
        page_id = jnp.take_along_axis(page_table, positions // ps, axis=1)
        rows = (page_id * ps + positions % ps).reshape(-1)
        new_caches.append(
            _append_latents(caches[li], rows, ckv, kpe_vec, kpe)
        )

        # unabsorbed per-head K/V from the latent; attention streams
        # through the segment-flash kernel (qk dim nope+kpe, vo dim nope)
        k_nope = jnp.einsum(
            "nc,hdc->nhd", ckv.astype(jnp.float32),
            layer["w_kc"].astype(jnp.float32),
        )
        v = jnp.einsum(
            "nc,hcd->nhd", ckv.astype(jnp.float32),
            layer["w_vc"].astype(jnp.float32),
        )
        k = jnp.concatenate(
            [k_nope, jnp.broadcast_to(kpe_vec[:, None, :].astype(
                jnp.float32), (N, H, kpe))],
            axis=-1,
        ).astype(cfg.dtype)
        q = jnp.concatenate(
            [q_nope.astype(jnp.float32), q_pe.astype(jnp.float32)], -1
        ).astype(cfg.dtype)
        attn = attn_fn(
            q, k, v.astype(cfg.dtype), seg, seg, pos_flat, pos_flat,
            causal=True, sm_scale=sm,
        )  # [N, H, nope]
        x = x + (
            attn.reshape(N, H * nope).astype(cfg.dtype) @ layer["o_proj"]
        ).astype(cfg.dtype)
        h = rmsnorm(x, layer["post_norm"], cfg.rms_eps)
        x = x + _layer_mlp(h, layer, cfg)
    x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits.reshape(B, L, -1), new_caches


def deepseek_decode_step(
    params: Dict,
    cfg: DeepseekConfig,
    tokens: jax.Array,  # [B] int32
    positions: jax.Array,  # [B] write positions
    caches: List[Tuple[jax.Array, jax.Array]],  # per layer (ckv, kpe)
    page_table: jax.Array,  # [B, max_pages]
    kv_lens: jax.Array,  # [B]
    use_pallas: Optional[bool] = None,
):
    """Single-device batched decode step -> (logits [B, vocab], caches).

    ``use_pallas`` defaults to the platform (``is_tpu()``) — on a real
    chip the paged MLA kernel runs, off-chip the XLA dense-gather
    reference; pass explicitly to pin either."""
    if use_pallas is None:
        use_pallas = is_tpu()
    x = params["embed"][tokens].astype(cfg.dtype)
    new_caches = []
    for li, layer in enumerate(params["layers"]):
        h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
        attn, cache = _mla_attn_decode(
            h, layer, cfg, caches[li], page_table, kv_lens, positions,
            use_pallas,
        )
        new_caches.append(cache)
        x = x + (attn @ layer["o_proj"]).astype(cfg.dtype)
        h = rmsnorm(x, layer["post_norm"], cfg.rms_eps)
        x = x + _layer_mlp(h, layer, cfg)
    x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
    return (x @ params["lm_head"]).astype(jnp.float32), new_caches


def make_ep_sharded_decode_step(
    mapping: Mapping, cfg: DeepseekConfig, mesh=None,
):
    """dp x ep sharded DeepSeek decode step via shard_map.

    Batch shards over the FLATTENED (dp, ep) axes; routed experts shard
    contiguously over ep (``Mapping.AXIS_TP`` doubles as the expert
    axis).  Attention + shared expert are replicated and run
    collective-free on local rows; ``fused_moe_ep``'s allgather dispatch
    + psum_scatter combine is the only cross-chip traffic.  Dense
    first-k layers stay fully local.

    Returns (step_fn, mesh, specs)."""
    from flashinfer_tpu.utils import jax_shard_map as shard_map

    mesh = mesh or mapping.make_mesh()
    ep_ax, dp = Mapping.AXIS_TP, Mapping.AXIS_DP
    ep = mapping.tp_size
    assert cfg.num_experts % ep == 0, (cfg.num_experts, ep)

    # layer param structure is fully determined by cfg (first_k_dense),
    # so specs build from cfg at construction time and the shard_map is
    # wrapped + jitted ONCE (mixtral/llama builder pattern — the decode
    # loop must replay a compiled step, not re-trace per token)
    def layer_spec(li: int):
        names = ["input_norm", "q_a", "q_a_norm", "q_b", "kv_a",
                 "kv_a_norm", "w_kc", "w_vc", "o_proj", "post_norm"]
        ndims = dict(input_norm=1, q_a=2, q_a_norm=1, q_b=2, kv_a=2,
                     kv_a_norm=1, w_kc=3, w_vc=3, o_proj=2, post_norm=1)
        if li < cfg.first_k_dense:
            names += ["gate_up", "down"]
            ndims.update(gate_up=2, down=2)
        else:
            names += ["router", "e_bias", "w_gate_up", "w_down",
                      "shared_gate_up", "shared_down"]
            ndims.update(router=2, e_bias=1, w_gate_up=3, w_down=3,
                         shared_gate_up=2, shared_down=2)
        spec = {k: P(*([None] * ndims[k])) for k in names}
        if li >= cfg.first_k_dense:
            spec["w_gate_up"] = P(ep_ax, None, None)
            spec["w_down"] = P(ep_ax, None, None)
        return spec

    b = P((dp, ep_ax))  # batch over ALL chips
    cache_spec = [
        (P((dp, ep_ax), None, None, None), P((dp, ep_ax), None, None, None))
        for _ in range(cfg.num_layers)
    ]
    param_specs = dict(
        embed=P(None, None), final_norm=P(None), lm_head=P(None, None),
        layers=[layer_spec(li) for li in range(cfg.num_layers)],
    )

    def step(params, tokens, positions, caches, page_table, kv_lens):
        x = params["embed"][tokens].astype(cfg.dtype)
        new_caches = []
        use_pallas = is_tpu()
        ep_moe = functools.partial(
            fused_moe_ep, axis=ep_ax, dispatch="allgather"
        )
        for li, layer in enumerate(params["layers"]):
            h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
            attn, cache = _mla_attn_decode(
                h, layer, cfg,
                (caches[li][0][0], caches[li][1][0]),
                page_table, kv_lens, positions, use_pallas,
            )
            new_caches.append((cache[0][None], cache[1][None]))
            x = x + (attn @ layer["o_proj"]).astype(cfg.dtype)
            h = rmsnorm(x, layer["post_norm"], cfg.rms_eps)
            x = x + _layer_mlp(h, layer, cfg, moe_fn=ep_moe)
        x = rmsnorm(x, params["final_norm"], cfg.rms_eps)
        return (x @ params["lm_head"]).astype(jnp.float32), new_caches

    sharded = jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(param_specs, b, b, cache_spec, P((dp, ep_ax), None), b),
            out_specs=(b, cache_spec),
            check_vma=False,
        )
    )
    return sharded, mesh, dict(params=param_specs, cache=cache_spec)
