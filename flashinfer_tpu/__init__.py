"""flashinfer-tpu: TPU-native LLM inference kernel library.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
flashinfer-ai/flashinfer (reference ``flashinfer/__init__.py:1-262`` public
surface): attention (single/batch prefill & decode, paged KV, cascade,
sparse, MLA), paged-KV management, sampling, RoPE, norm, activation, GEMM,
MoE, and mesh collectives — built on Pallas Mosaic kernels + XLA, with
host-side plan()/run() scheduling and shard_map parallelism.
"""

from flashinfer_tpu.version import __version__  # noqa: F401

from flashinfer_tpu.cascade import (  # noqa: F401
    MultiLevelCascadeAttentionWrapper,
    merge_state,
    merge_state_in_place,
    merge_states,
    variable_length_merge_states,
)
from flashinfer_tpu.decode import (  # noqa: F401
    BatchDecodeWithPagedKVCacheWrapper,
    single_decode_with_kv_cache,
)
from flashinfer_tpu.prefill import (  # noqa: F401
    BatchPrefillWithPagedKVCacheWrapper,
    BatchPrefillWithRaggedKVCacheWrapper,
    build_multi_item_mask,
    single_prefill_with_kv_cache,
)
from flashinfer_tpu.gemm import (  # noqa: F401
    SegmentGEMMWrapper,
    group_gemm_fp4,
    group_gemm_fp8_nt_groupwise,
    group_gemm_int8,
    grouped_gemm,
    mm_fp4,
    mm_fp8_groupwise,
    mm_int8,
)
# mm_bf16 / bmm_bf16 / mm_fp8 / bmm_fp8 arrive via the compat star-import
# below as REFERENCE-signature adapters (compat_calls.py); the TPU-native
# forms live on flashinfer_tpu.gemm for internal/positional callers
from flashinfer_tpu.quantization import (  # noqa: F401
    dequantize_fp4,
    dequantize_fp8,
    packbits,
    quantize_fp4,
    quantize_fp8_per_channel,
    quantize_fp8_per_tensor,
    quantize_int8,
    segment_packbits,
)
from flashinfer_tpu.sparse import (  # noqa: F401
    BlockSparseAttentionWrapper,
    VariableBlockSparseAttentionWrapper,
)
from flashinfer_tpu.attention import (  # noqa: F401
    BatchAttention,
    BatchAttentionWithAttentionSinkWrapper,
    PODWithPagedKVCacheWrapper,
    apply_attention_sink,
)
from flashinfer_tpu.mla import (  # noqa: F401
    BatchDecodeMlaWithPagedKVCacheWrapper,
    BatchMLAPagedAttentionWrapper,
)
from flashinfer_tpu.topk import (  # noqa: F401
    top_k_indices,
    top_k_mask,
    top_k_page_table_transform,
    top_k_values_indices,
)

from flashinfer_tpu.activation import (  # noqa: F401
    gelu_and_mul,
    gelu_tanh_and_mul,
    silu_and_mul,
    silu_and_mul_quant_fp8,
)
from flashinfer_tpu.aliases import (  # noqa: F401
    cudnn_batch_decode_with_kv_cache,
    cudnn_batch_prefill_with_kv_cache,
    fast_decode_plan,
    trtllm_batch_context_with_kv_cache,
    trtllm_batch_decode_sparse_mla_dsv4,
    trtllm_batch_decode_trace_dispatch,
    trtllm_batch_decode_with_kv_cache,
    trtllm_batch_decode_with_kv_cache_mla,
    xqa_batch_decode_with_kv_cache,
    xqa_batch_decode_with_kv_cache_mla,
)
from flashinfer_tpu.msa_ops import (  # noqa: F401
    msa_proxy_score,
    msa_proxy_score_per_token,
    msa_sparse_attention,
    msa_topk_select,
    msa_topk_select_per_token,
)
from flashinfer_tpu.norm import (  # noqa: F401
    fused_add_rmsnorm,
    fused_add_rmsnorm_quant_fp8,
    gate_residual,
    gemma_fused_add_rmsnorm,
    gemma_rmsnorm,
    layernorm,
    layernorm_scale_shift,
    qk_rmsnorm,
    rmsnorm,
    rmsnorm_quant_fp8,
    rmsnorm_silu,
)
from flashinfer_tpu.concat_ops import concat_mla_k, concat_mla_q  # noqa: F401
from flashinfer_tpu.gdn import (  # noqa: F401
    gdn_chunk_prefill,
    gdn_decode_mtp,
    gdn_decode_step,
    gdn_prefill,
    kda_chunk_prefill,
    kda_decode_mtp,
    kda_decode_step,
    kda_prefill,
)
from flashinfer_tpu.mamba import (  # noqa: F401
    checkpointing_ssu,
    selective_scan,
    selective_state_update,
    selective_state_update_mtp,
)
from flashinfer_tpu.mhc import (  # noqa: F401
    mhc_dynamic_weights,
    mhc_post_mix,
    mhc_pre_mix,
)
from flashinfer_tpu.page import (  # noqa: F401
    append_paged_kv_cache,
    append_paged_kv_cache_quant_fp8,
    append_paged_kv_cache_quant_int8,
    append_paged_mla_kv_cache,
    get_batch_indices_positions,
    get_seq_lens,
)
from flashinfer_tpu.rope import (  # noqa: F401
    apply_llama31_rope,
    apply_llama31_rope_pos_ids,
    apply_rope,
    apply_rope_pos_ids,
    apply_rope_with_cos_sin_cache,
    generate_cos_sin_cache,
)
from flashinfer_tpu.autotuner import AutoTuner, autotune  # noqa: F401
from flashinfer_tpu.profiler import (  # noqa: F401
    annotate,
    kernel_profiler,
    start_timeline,
    stop_timeline,
    timeline,
)
from flashinfer_tpu.sampling import (  # noqa: F401
    chain_speculative_sampling,
    min_p_sampling_from_probs,
    sampling_from_logits,
    sampling_from_probs,
    softmax,
    top_k_mask_logits,
    top_k_renorm_probs,
    top_k_sampling_from_probs,
    top_k_top_p_sampling_from_logits,
    top_k_top_p_sampling_from_probs,
    top_p_renorm_probs,
    top_p_sampling_from_probs,
)
from flashinfer_tpu.compat import *  # noqa: F401,F403  (reference
#   top-level name parity — see compat.py)
from flashinfer_tpu.compat import __git_version__  # noqa: F401
