"""LogitsPipe: validate -> legalize -> fuse -> jit.

Mirrors the reference pipeline semantics (``flashinfer/logits_processor``):

- Type flow: the stream starts as LOGITS; ``Softmax`` moves it to PROBS;
  ``Sample`` consumes either and ends the pipe.
- Legalization: ``TopK`` on LOGITS -> mask-logits kernel, on PROBS ->
  renorm-probs kernel; ``TopP``/``MinP`` are PROBS-only (validation error on
  logits, matching the reference's legalization rules).
- Fusion: the chain is composed into one Python function and jitted whole —
  XLA fuses the sort/cumsum/mask chain the way the reference fuses CUDA
  kernels via its fusion rules.

Runtime parameters (temperature, top_k, top_p, min_p, key) are call-time
arguments, so one compiled pipe serves any parameter values.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from flashinfer_tpu import sampling as S

LOGITS, PROBS, TOKENS = "logits", "probs", "tokens"


class _Op:
    name: str = "op"
    needs: Sequence[str] = (LOGITS, PROBS)
    params: Sequence[str] = ()

    def apply(self, state: str, x, params: Dict[str, Any], key):
        raise NotImplementedError

    def out_state(self, state: str) -> str:
        return state


class Temperature(_Op):
    name = "temperature"
    needs = (LOGITS,)
    params = ("temperature",)

    def apply(self, state, x, params, key):
        t = jnp.asarray(params["temperature"], jnp.float32)
        t = jnp.maximum(t, 1e-6)
        if t.ndim == 1:
            t = t[:, None]
        return x / t


class Softmax(_Op):
    name = "softmax"
    needs = (LOGITS,)

    def apply(self, state, x, params, key):
        return jax.nn.softmax(x.astype(jnp.float32), axis=-1)

    def out_state(self, state):
        return PROBS


class TopK(_Op):
    name = "top_k"
    needs = (LOGITS, PROBS)
    params = ("top_k",)

    def apply(self, state, x, params, key):
        if state == LOGITS:
            return S.top_k_mask_logits(x, params["top_k"])
        return S.top_k_renorm_probs(x, params["top_k"])


class TopP(_Op):
    name = "top_p"
    needs = (PROBS,)
    params = ("top_p",)

    def apply(self, state, x, params, key):
        return S.top_p_renorm_probs(x, params["top_p"])


class MinP(_Op):
    name = "min_p"
    needs = (PROBS,)
    params = ("min_p",)

    def apply(self, state, x, params, key):
        p = x.astype(jnp.float32)
        mp = jnp.asarray(params["min_p"], jnp.float32)
        if mp.ndim == 1:
            mp = mp[:, None]
        thresh = mp * jnp.max(p, axis=-1, keepdims=True)
        kept = jnp.where(p >= thresh, p, 0.0)
        return kept / jnp.sum(kept, axis=-1, keepdims=True)


class Sample(_Op):
    name = "sample"
    needs = (LOGITS, PROBS)
    params = ()

    def apply(self, state, x, params, key):
        if key is None:
            raise ValueError("Sample requires a PRNG key at call time")
        if state == LOGITS:
            return S.sampling_from_logits(x, key)
        return S.sampling_from_probs(x, key)

    def out_state(self, state):
        return TOKENS


class LogitsPipe:
    """Compile a processor chain into one jitted function.

    >>> pipe = LogitsPipe([Temperature(), Softmax(), TopP(), Sample()])
    >>> tokens = pipe(logits, temperature=0.8, top_p=0.9, key=key)
    """

    def __init__(self, ops: Sequence[_Op], compile: bool = True,
                 input_type: Optional[str] = None):
        """``compile=`` mirrors the reference flag: True jits the fused
        chain (the default — on TPU compilation IS the fusion), False
        runs the ops eagerly (debugging).  ``input_type`` sets the
        starting stream type (reference TensorType.PROBS pipes start
        mid-stream, e.g. LogitsPipe([TopK()], input_type=PROBS))."""
        self.ops = list(ops)
        self._compile = bool(compile)
        self._input_state = input_type if input_type is not None else LOGITS
        if self._input_state not in (LOGITS, PROBS):
            raise ValueError(
                f"input_type must be LOGITS or PROBS, got "
                f"{self._input_state!r}")
        self._validate()
        self._param_names = [p for op in self.ops for p in op.params]
        self._compiled = None

    # call-time knobs that are pure scheduling on TPU (the reference's
    # deterministic-kernel switch; XLA reductions are deterministic)
    _INERT_PARAMS = frozenset({"is_deterministic", "deterministic"})

    def _validate(self) -> None:
        state = self._input_state
        for i, op in enumerate(self.ops):
            if state == TOKENS:
                raise ValueError(
                    f"op {op.name!r} at position {i} after Sample — the pipe "
                    "already ended"
                )
            if state not in op.needs:
                # the Softmax hint only helps when the stream can still
                # MOVE to what the op needs (LOGITS -> PROBS; a PROBS
                # stream cannot become logits again)
                hint = (" (insert Softmax() before it?)"
                        if state == LOGITS and PROBS in op.needs else "")
                raise ValueError(
                    f"op {op.name!r} at position {i} requires "
                    f"{'/'.join(op.needs)} input but the stream is "
                    f"{state}{hint}"
                )
            state = op.out_state(state)
        self.final_state = state
        # keep the public legalizer in lockstep (same walk; it raises
        # LegalizationError, a ValueError subclass, where the reference
        # would) — one more guard against the two drifting
        legalize_processors(self.ops, self._input_state)

    def _run(self, x, key, **params):
        state = self._input_state
        for op in self.ops:
            x = op.apply(state, x, params, key)
            state = op.out_state(state)
        return x

    def __call__(self, logits: jax.Array, key: Optional[jax.Array] = None,
                 **params):
        if params.get("generator") is not None:
            raise ValueError(
                "torch generators have no TPU meaning — pass an explicit "
                "jax.random.PRNGKey as key="
            )
        params.pop("generator", None)  # a forwarded default None is inert
        params = {k: v for k, v in params.items()
                  if k not in self._INERT_PARAMS}
        missing = [p for p in self._param_names if p not in params]
        if missing:
            raise ValueError(f"missing runtime params: {missing}")
        extra = [p for p in params if p not in self._param_names]
        if extra:
            raise ValueError(
                f"unknown params {extra}; this pipe takes {self._param_names}"
            )
        if not self._compile:
            return self._run(logits, key, **params)
        if self._compiled is None:
            self._compiled = jax.jit(
                functools.partial(self._run)
            )
        return self._compiled(logits, key, **params)


# ---------------------------------------------------------------------------
# Reference compiler-surface names (flashinfer/logits_processor: compiler.py,
# types.py, op.py).  The TPU pipe IS the compiler — validate -> legalize ->
# fuse happens in LogitsPipe — so these expose its pieces under the
# reference names.
# ---------------------------------------------------------------------------

Op = _Op
LogitsProcessor = _Op  # reference processor base class
ParameterizedOp = _Op  # parameterized ops read call-time params


class TensorType:
    """Reference stream-type enum (types.py): the pipe's LOGITS -> PROBS
    -> TOKENS flow, as string states here."""

    LOGITS = LOGITS
    PROBS = PROBS
    TOKENS = TOKENS


class TaggedTensor:
    """A (tensor, stream-type) pair (reference types.TaggedTensor)."""

    def __init__(self, tensor, type: str = LOGITS):  # noqa: A002
        self.tensor = tensor
        self.type = type

    @staticmethod
    def logits(t):
        return TaggedTensor(t, LOGITS)

    @staticmethod
    def probs(t):
        return TaggedTensor(t, PROBS)


class CompileError(ValueError):
    """Pipeline failed validation/compilation (reference compiler.py)."""


class LegalizationError(CompileError):
    """An op has no kernel for its input stream type."""


class FusionRule:
    """A fusion-rule record (reference fusion_rules.py).  XLA performs
    the actual fusion when the pipe jits; the record exists for
    introspection parity."""

    def __init__(self, pattern=(), name: str = "xla_fused"):
        self.pattern = tuple(pattern)
        self.name = name


def legalize_processors(ops, initial_state: str = LOGITS):
    """Validate + legalize a processor chain (reference
    legalization.py): returns the ops unchanged on success — each op's
    ``apply`` already dispatches on the stream state (the TPU form of
    kernel selection) — and raises :class:`LegalizationError` where the
    reference would."""
    state = initial_state
    for i, op in enumerate(ops):
        if state == TOKENS:
            raise LegalizationError(
                f"op {op.name!r} at position {i} after Sample"
            )
        if state not in op.needs:
            raise LegalizationError(
                f"op {op.name!r} at position {i} requires "
                f"{'/'.join(op.needs)}, stream is {state}"
            )
        state = op.out_state(state)
    return list(ops)


def compile_pipeline(processors, **_unused):
    """Compile a processor chain (reference compiler.compile_pipeline)
    -> a :class:`LogitsPipe` (validated, legalized, jitted whole)."""
    try:
        return LogitsPipe(processors)
    except ValueError as e:
        raise CompileError(str(e)) from e


class Compiler:
    """Reference compiler object: ``compile()`` == compile_pipeline."""

    def compile(self, processors, **kw):
        return compile_pipeline(processors, **kw)
