"""Declarative logits-processing pipeline compiler.

TPU re-design of ``flashinfer/logits_processor/`` (LogitsPipe pipeline.py,
compile/legalize/fuse compiler.py + fusion_rules.py + legalization.py): a
declarative chain of processors (Temperature/TopK/TopP/MinP/Softmax/Sample)
is validated (logits-vs-probs type flow), legalized (each op picks its
logits- or probs-domain kernel), and compiled into ONE jitted function —
the XLA analogue of the reference's fused-kernel selection.
"""

from flashinfer_tpu.logits_processor.pipeline import (  # noqa: F401
    LogitsPipe,
    MinP,
    Sample,
    Softmax,
    Temperature,
    TopK,
    TopP,
)
