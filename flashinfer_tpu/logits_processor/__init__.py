"""Declarative logits-processing pipeline compiler.

TPU re-design of ``flashinfer/logits_processor/`` (LogitsPipe pipeline.py,
compile/legalize/fuse compiler.py + fusion_rules.py + legalization.py): a
declarative chain of processors (Temperature/TopK/TopP/MinP/Softmax/Sample)
is validated (logits-vs-probs type flow), legalized (each op picks its
logits- or probs-domain kernel), and compiled into ONE jitted function —
the XLA analogue of the reference's fused-kernel selection.
"""

from flashinfer_tpu.logits_processor.pipeline import (  # noqa: F401
    CompileError,
    Compiler,
    FusionRule,
    LegalizationError,
    LogitsPipe,
    LogitsProcessor,
    MinP,
    Op,
    ParameterizedOp,
    Sample,
    Softmax,
    TaggedTensor,
    Temperature,
    TensorType,
    TopK,
    TopP,
    compile_pipeline,
    legalize_processors,
)
