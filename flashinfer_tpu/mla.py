"""MLA (DeepSeek Multi-Latent Attention) wrapper.

TPU re-design of ``flashinfer/mla/_core.py:1397``
(``BatchMLAPagedAttentionWrapper``, plan :1568 / run :1742): paged attention
over compressed KV (ckv head_dim 512 + kpe head_dim 64) with MQA-shaped
sharing across query heads.

Two execution paths, selected by the planned qo lengths:
- all qo_len == 1 -> the MLA decode Pallas kernel (ops/mla_decode.py);
- otherwise (speculative multi-token / chunked prefill) -> gather the
  planned pages into flattened ragged K/V and run the segment flash kernel
  with q = [q_nope | q_pe], k = [ckv | kpe], v = ckv (prefill is
  compute-bound; the gather pass is the documented v1 trade-off, as for
  paged batch prefill).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flashinfer_tpu.ops.flash_attention import flash_attention
from flashinfer_tpu.ops.mla_decode import (
    mla_paged_decode_attention,
    xla_mla_paged_decode,
)
from flashinfer_tpu.ops.xla_ref import xla_ragged_attention
from flashinfer_tpu.utils import next_power_of_two, resolve_backend


@dataclass(frozen=True)
class _MLAPlan:
    decode_mode: bool
    causal: bool
    sm_scale: float
    num_heads: int
    head_dim_ckv: int
    head_dim_kpe: int
    page_size: int
    batch_size: int
    # decode-mode arrays
    page_table: Optional[jax.Array] = None
    kv_lens: Optional[jax.Array] = None
    # ragged-mode arrays
    q_seg: Optional[jax.Array] = None
    q_pos: Optional[jax.Array] = None
    kv_seg: Optional[jax.Array] = None
    kv_pos: Optional[jax.Array] = None
    kv_rows: Optional[jax.Array] = None
    total_q: int = 0
    tq_pad: int = 0


class BatchMLAPagedAttentionWrapper:
    """plan/run MLA attention (reference mla/_core.py:1397)."""

    def __init__(self, float_workspace_buffer=None, backend: str = "auto",
                 **_unused):
        self._backend = backend
        self._plan: Optional[_MLAPlan] = None

    def plan(
        self,
        qo_indptr,  # [B+1]
        kv_indptr,  # [B+1] page-table offsets
        kv_indices,  # [total_pages]
        kv_len_arr,  # [B] kv token lengths
        num_heads: int,
        head_dim_ckv: int,
        head_dim_kpe: int,
        page_size: int,
        causal: bool = False,
        sm_scale: Optional[float] = None,
        q_data_type=jnp.bfloat16,
        kv_data_type=None,
        use_profiler: bool = False,
        **_unused,
    ) -> None:
        qo_indptr = np.asarray(qo_indptr)
        kv_indptr = np.asarray(kv_indptr)
        kv_indices = np.asarray(kv_indices)
        kv_len = np.asarray(kv_len_arr).astype(np.int64)
        batch = len(qo_indptr) - 1
        qo_lens = qo_indptr[1:] - qo_indptr[:-1]
        if sm_scale is None:
            sm_scale = 1.0 / float(head_dim_ckv + head_dim_kpe) ** 0.5

        if (qo_lens == 1).all():
            from flashinfer_tpu import native

            pages_per_req = kv_indptr[1:] - kv_indptr[:-1]
            p_bucket = max(next_power_of_two(int(pages_per_req.max(initial=1))), 8)
            b_bucket = max(next_power_of_two(batch), 8)
            # decode_plan builds the padded table; token lengths come from
            # the caller's kv_len_arr directly (last_page_len arg unused for
            # lengths here, so pass a valid placeholder)
            table, lens = native.decode_plan(
                kv_indptr, kv_indices, np.ones(batch, np.int32), page_size,
                b_bucket, p_bucket,
            )
            lens[:batch] = kv_len
            self._plan = _MLAPlan(
                decode_mode=True, causal=causal, sm_scale=float(sm_scale),
                num_heads=num_heads, head_dim_ckv=head_dim_ckv,
                head_dim_kpe=head_dim_kpe, page_size=page_size,
                batch_size=batch,
                page_table=jnp.asarray(table), kv_lens=jnp.asarray(lens),
            )
            return

        # ragged mode: flatten tokens with segments (same scheme as prefill),
        # built by the native planner
        from flashinfer_tpu import native

        total_q = int(qo_indptr[-1])
        kv_tok_indptr = np.concatenate([[0], np.cumsum(kv_len)])
        total_kv = int(kv_tok_indptr[-1])
        tq_pad = max(next_power_of_two(total_q), 128)
        tkv_pad = max(next_power_of_two(total_kv), 128)
        q_seg, q_pos = native.token_axis_plan(
            qo_indptr, kv_len - qo_lens, tq_pad, -1
        )
        kv_seg, kv_pos = native.token_axis_plan(
            kv_tok_indptr, np.zeros(batch, np.int64), tkv_pad, -2
        )
        rows = native.paged_gather_plan(
            kv_tok_indptr, kv_indptr, kv_indices, page_size, tkv_pad
        )
        self._plan = _MLAPlan(
            decode_mode=False, causal=causal, sm_scale=float(sm_scale),
            num_heads=num_heads, head_dim_ckv=head_dim_ckv,
            head_dim_kpe=head_dim_kpe, page_size=page_size, batch_size=batch,
            q_seg=jnp.asarray(q_seg), q_pos=jnp.asarray(q_pos),
            kv_seg=jnp.asarray(kv_seg), kv_pos=jnp.asarray(kv_pos),
            kv_rows=jnp.asarray(rows, dtype=jnp.int32),
            total_q=total_q, tq_pad=tq_pad,
        )

    def run(
        self,
        q_nope: jax.Array,  # [total_q, num_heads, head_dim_ckv]
        q_pe: jax.Array,  # [total_q, num_heads, head_dim_kpe]
        ckv_cache: jax.Array,  # [num_pages, page_size, head_dim_ckv]
        kpe_cache: jax.Array,  # [num_pages, page_size, head_dim_kpe]
        *,
        return_lse: bool = False,
    ):
        plan = self._plan
        if plan is None:
            raise RuntimeError("plan() must be called before run()")
        backend = resolve_backend(self._backend, "batch_mla")
        if ckv_cache.shape[0] == 0:
            # empty cache (every request has kv_len == 0): attention over
            # the empty set — zero output, lse = log(0) (the reference
            # kernel returns zeros and its tests skip the lse check,
            # test_deepseek_mla.py:630)
            n = plan.batch_size if plan.decode_mode else plan.total_q
            out = jnp.zeros((n, plan.num_heads, plan.head_dim_ckv),
                            q_nope.dtype)
            if return_lse:
                return out, jnp.full((n, plan.num_heads), -jnp.inf,
                                     jnp.float32)
            return out
        if plan.decode_mode:
            b_pad = plan.page_table.shape[0]
            if q_nope.shape[0] != b_pad:
                pad = b_pad - q_nope.shape[0]
                q_nope = jnp.pad(q_nope, ((0, pad), (0, 0), (0, 0)))
                q_pe = jnp.pad(q_pe, ((0, pad), (0, 0), (0, 0)))
            if backend == "pallas":
                # autotuned scratch layout: "split" (two buffers, two
                # score dots — the hardware-validated default) vs
                # "packed" (one [chunk, 640] buffer, one concatenated
                # dot; same DMA queue depth).  Shipped-config/default
                # outside an autotune() context, like decode's ppc.
                from flashinfer_tpu.autotuner import AutoTuner
                from flashinfer_tpu.ops import mla_decode as _mla_module

                key = (
                    plan.page_table.shape[0], plan.page_table.shape[1],
                    plan.num_heads, plan.head_dim_ckv, plan.head_dim_kpe,
                    plan.page_size, str(q_nope.dtype),
                )

                def _run(layout_):
                    return mla_paged_decode_attention(
                        q_nope, q_pe, ckv_cache, kpe_cache,
                        plan.page_table, plan.kv_lens,
                        sm_scale=plan.sm_scale, return_lse=return_lse,
                        layout=layout_,
                    )

                layout = AutoTuner.get().choose_one(
                    "mla_decode.layout", key, ["split", "packed"],
                    lambda c: (lambda: _run(c)),
                    default="split",
                    module=_mla_module,
                )
                out = _run(str(layout))
            else:
                out = xla_mla_paged_decode(
                    q_nope, q_pe, ckv_cache, kpe_cache, plan.page_table,
                    plan.kv_lens, sm_scale=plan.sm_scale,
                    return_lse=return_lse,
                )
            if return_lse:
                return out[0][: plan.batch_size], out[1][: plan.batch_size]
            return out[: plan.batch_size]

        # ragged path: gather + segment flash with asymmetric head dims
        ckv_rows = ckv_cache.reshape(-1, plan.head_dim_ckv)[plan.kv_rows]
        # kpe cache may be lane-padded to 128 (TPU-native layout): slice back
        kpe_rows = kpe_cache.reshape(-1, kpe_cache.shape[-1])[plan.kv_rows][
            :, : plan.head_dim_kpe
        ]
        k = jnp.concatenate([ckv_rows, kpe_rows], axis=-1)[:, None, :]  # MQA
        v = ckv_rows[:, None, :]
        q = jnp.concatenate(
            [q_nope.astype(jnp.float32), q_pe.astype(jnp.float32)], axis=-1
        ).astype(q_nope.dtype)
        if q.shape[0] != plan.tq_pad:
            q = jnp.pad(q, ((0, plan.tq_pad - q.shape[0]), (0, 0), (0, 0)))
        fn = flash_attention if backend == "pallas" else xla_ragged_attention
        out = fn(
            q, k, v, plan.q_seg, plan.kv_seg, plan.q_pos, plan.kv_pos,
            causal=plan.causal, sm_scale=plan.sm_scale, return_lse=return_lse,
        )
        if return_lse:
            return out[0][: plan.total_q], out[1][: plan.total_q]
        return out[: plan.total_q]

    def run_sparse(
        self,
        q_nope: jax.Array,  # [batch, num_heads, head_dim_ckv]
        q_pe: jax.Array,  # [batch, num_heads, head_dim_kpe]
        ckv_cache: jax.Array,
        kpe_cache: jax.Array,
        sparse_rows: jax.Array,  # [batch, k] flat cache rows (from
        # topk.top_k_page_table_transform), padded entries < 0
        *,
        sm_scale: Optional[float] = None,
        return_lse: bool = False,
    ):
        """Top-k sparse MLA decode (the DSv3.2 sparse-MLA path, reference
        ``flashinfer/mla/_sparse_mla_sm120.py`` + sparse_mla bindings):
        attention restricted to the top-k selected KV tokens per request.
        Selection comes from ``flashinfer_tpu.topk.top_k_page_table_transform``
        over per-token proxy scores; rows < 0 are masked padding."""
        d_ckv = ckv_cache.shape[-1]
        if sm_scale is None:
            sm_scale = 1.0 / float(d_ckv + q_pe.shape[-1]) ** 0.5
        return _sparse_mla_decode(
            q_nope, q_pe, ckv_cache, kpe_cache, sparse_rows,
            sm_scale=float(sm_scale), return_lse=return_lse,
        )

    forward = run

    def end_forward(self) -> None:
        pass


# legacy alias kept by the reference for its earlier MLA API generation
BatchDecodeMlaWithPagedKVCacheWrapper = BatchMLAPagedAttentionWrapper


@functools.partial(jax.jit, static_argnames=("sm_scale", "return_lse"))
def _sparse_mla_decode(
    q_nope, q_pe, ckv_cache, kpe_cache, sparse_rows,
    *, sm_scale: float, return_lse: bool,
):
    """Gather the selected latent rows and run dense MQA attention over the
    k tokens — with k in the hundreds this is one small MXU matmul per
    request, the shape the sparse path exists to produce."""
    batch, H, d_ckv = q_nope.shape
    rows = jnp.maximum(sparse_rows, 0)
    valid = sparse_rows >= 0  # [batch, k]
    ckv = ckv_cache.reshape(-1, d_ckv)[rows].astype(jnp.float32)  # [B,k,d]
    kpe = kpe_cache.reshape(-1, kpe_cache.shape[-1])[rows].astype(jnp.float32)
    kpe = kpe[..., : q_pe.shape[-1]]  # drop TPU lane padding if present
    s = (
        jnp.einsum("bhd,bkd->bhk", q_nope.astype(jnp.float32), ckv)
        + jnp.einsum("bhd,bkd->bhk", q_pe.astype(jnp.float32), kpe)
    ) * sm_scale
    s = jnp.where(valid[:, None], s, -1e30)
    m = jnp.max(s, -1, keepdims=True)
    p = jnp.where(valid[:, None], jnp.exp(s - m), 0.0)
    l = jnp.sum(p, -1, keepdims=True)
    out = jnp.einsum("bhk,bkd->bhd", p / jnp.where(l > 0, l, 1.0), ckv)
    out = out.astype(q_nope.dtype)
    if return_lse:
        lse = jnp.where(l[..., 0] > 0, m[..., 0] + jnp.log(l[..., 0]), -1e30)
        return out, lse
    return out
