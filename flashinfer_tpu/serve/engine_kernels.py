"""Pallas work-unit lowering of the serving engine's attention step.

The graduation ROADMAP item 1 names: the continuous-batching engine
(serve/engine.py) schedules mixed decode + chunked-prefill tokens on one
flat axis with a two-level cascade decomposition, and PR 11 deliberately
ran that attention in the dense XLA reference form so the bitwise
no-sharing contract could be proved on CPU.  This module lowers the
SAME per-step schedule onto the proven work-unit kernels instead:

- :func:`build_engine_work_units` — the host-side planner.  It takes
  the engine's flat-row schedule segments (tokens/positions, group page
  runs, per-token window bounds, the cascade split) and lowers them
  into the existing plan-array forms:

  * **level 0** (the shared-prefix span, gathered once per prefix
    GROUP) and **level 1 chunked prefill** (each request's suffix
    window, ``q_len >= 1``) both become
    :func:`~flashinfer_tpu.ops.paged_prefill.build_prefill_work_units`
    plans — level 0 with a per-token custom mask encoding the
    causal-by-global-position rule ``kv_row <= pos`` over the group
    run, level 1 with the planner's native causal rule (suffix-local
    positions, negative ``qpos0`` rows inside the shared span attend
    nothing and emit the empty-state sentinel);
  * **decode tokens** (``q_len == 1``) become
    :func:`~flashinfer_tpu.ops.paged_decode.build_decode_split_units`
    units over their suffix page runs (PR 6's split-KV partials +
    ``merge_states`` reduction).

- :func:`engine_kernel_attention` — the in-jit composition: the three
  kernel launches produce per-level ``(out, lse)`` states and fold
  through the SAME :func:`~flashinfer_tpu.cascade.compose_cascade_levels`
  merge operator the reference backend uses, so the cascade
  decomposition — and the shared-run HBM dedup it exists for — is
  identical across backends.

Retrace contract (the engine's rung ladder): every plan-array shape is
a pure function of the RUNG, never the schedule values — prefill plans
pad to :meth:`EngineKernelGeom.prefill_unit_cap` via the planner's
``num_units_pad``, the decode plan is ``max_batch x num_splits`` units
over a fixed-width page table, and the level-0 custom-mask operand is
always present (all-ones windows demote to the maskless PARTIAL code
in-plan, so steady-state decode pays no expansion).  One rung == one
trace, the same <= 9-trace budget the reference backend pins.

Every flat row — scheduled or rung padding — is covered by a plan
segment (padding rides trailing ``kv_len = 0`` segments), so both
levels emit defined ``(0, -inf)`` empty states for unused rows instead
of uninitialized HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import numpy as np

from flashinfer_tpu.utils import cdiv, next_power_of_two, round_up


@dataclasses.dataclass(frozen=True)
class SchedSeg:
    """One scheduled (request, chunk) of the engine's step, in flat-row
    order: rows ``[row0, row0 + n)`` of the rung-padded token axis.

    ``pages`` is the request's full page run, ``split`` the page-aligned
    cascade boundary frozen at first admission, ``kv_after`` the
    request's KV length AFTER this step's append (the last row's
    position + 1).  ``group`` is the step-local shared-prefix group id —
    the engine orders segments so equal groups are adjacent, which is
    what lets level 0 gather each shared run once per contiguous span."""

    row0: int
    n: int
    pages: Tuple[int, ...]
    split: int
    kv_after: int
    decoding: bool
    slot: int
    group: int


@dataclasses.dataclass(frozen=True)
class EngineKernelGeom:
    """The kernel backend's frozen launch geometry — every static the
    three launches need, derived ONCE from the engine config so plan
    shapes (and therefore traces) are functions of the rung alone."""

    page_size: int
    pages_per_req: int
    max_batch: int
    block_q: int
    prefill_ppc: int     # kv pages per DMA chunk, both prefill levels
    decode_ppc: int      # split-decode chunk pages (split_pages_per_chunk)
    num_splits: int      # decode split factor (1 = unsplit degenerate)
    single_chunk: bool   # config-level certificate: every decode unit
    #                      is at most one DMA chunk for EVERY schedule
    dec_width: int       # fixed decode page-table width (chunk-aligned)
    # ISSUE 14: the fused-ingest adoption static (the resolved
    # prefill.fused_ingest knob).  When True every plan bundle carries
    # an ``ingest`` sub-plan (rung-stable shapes; degenerate when the
    # step's schedule is not a from-scratch prefill) and the body
    # dispatches on the bundle's ``ingest_on`` VALUE via lax.cond — so
    # the one-trace-per-rung budget is untouched
    fused_ingest: bool = False

    @staticmethod
    def build(*, page_size: int, pages_per_req: int, max_batch: int,
              max_rung: int, num_kv_heads: int, head_dim: int,
              kv_itemsize: int, num_splits: int = 1,
              fused_ingest: bool = False) -> "EngineKernelGeom":
        from flashinfer_tpu.ops.paged_decode import split_pages_per_chunk
        from flashinfer_tpu.ops.paged_prefill import (
            ingest_block_q, ingest_pages_per_chunk)

        block_q = ingest_block_q(max_rung)
        prefill_ppc = ingest_pages_per_chunk(page_size)
        decode_ppc = split_pages_per_chunk(
            page_size, num_kv_heads, head_dim, kv_itemsize)
        per_unit_pages = round_up(cdiv(pages_per_req, num_splits),
                                  decode_ppc)
        return EngineKernelGeom(
            page_size=page_size,
            pages_per_req=pages_per_req,
            max_batch=max_batch,
            block_q=block_q,
            prefill_ppc=prefill_ppc,
            decode_ppc=decode_ppc,
            num_splits=num_splits,
            single_chunk=cdiv(pages_per_req, num_splits) <= decode_ppc,
            dec_width=max(round_up(pages_per_req, decode_ppc),
                          per_unit_pages * num_splits, decode_ppc),
            fused_ingest=bool(fused_ingest),
        )

    @property
    def max_prefill_chunks(self) -> int:
        return max(cdiv(self.pages_per_req, self.prefill_ppc), 1)

    def prefill_unit_cap(self, rung: int) -> int:
        """Worst-case work units either prefill-level plan can need at
        one rung: each of the <= max_batch scheduled segments (plus the
        trailing padding segment) overlaps at most ``cdiv(n, block_q) +
        1`` qo tiles, each (tile, segment) span walks at most
        ``max_prefill_chunks`` KV chunks, and pruned spans keep one
        zero-write fallback unit.  The cap is a RUNG static, so a rung's
        plan shape can never vary step to step."""
        spans = cdiv(rung, self.block_q) + 2 * (self.max_batch + 1)
        return max(next_power_of_two(spans * self.max_prefill_chunks), 8)


# plan-dict keys that ride into the jitted engine body (arrays only —
# statics and stats stay host-side so the traced pytree is rung-stable)
PREFILL_ARRAY_KEYS = ("qstart", "rowlo", "rowhi", "qpos0", "kvstart",
                      "kvlen", "first", "wout", "qslot", "code", "pages",
                      "mask_bytes")
DECODE_ARRAY_KEYS = ("pages", "kvlen", "wu_req", "wu_page0", "wu_kvlen")
# the ingest plan's traced arrays (fused_ingest geoms only): the base
# work-unit arrays plus the ingest extras, no mask (from-scratch causal)
INGEST_ARRAY_KEYS = ("qstart", "rowlo", "rowhi", "qpos0", "kvstart",
                     "kvlen", "first", "wout", "qslot", "code", "pages",
                     "kvbase", "posoff", "wkv")


def build_engine_work_units(
    segs: Sequence[SchedSeg],
    *,
    rung: int,
    geom: EngineKernelGeom,
):
    """Lower one engine step's schedule into the three plan-array forms.

    ``segs`` must tile ``[0, total)`` of the flat axis contiguously and
    keep equal ``group`` ids adjacent (the engine's group-sorted
    schedule).  Returns a dict::

        prefill0  — level-0 shared-prefix plan (custom mask, causal by
                    global position; one qo segment per contiguous
                    group span, so the run's pages stream once per
                    (tile, chunk) for ALL riders — the cascade dedup)
        prefill1  — level-1 suffix plan (causal; decode rows and
                    fully-in-span chunks ride kv_len=0 segments that
                    emit the empty state)
        decode    — split-KV decode plan over per-slot suffix tables
        dec_rows  — [max_batch] flat row of each decode lane's token
                    (== rung for idle slots: gathers clip to a harmless
                    row, scatters drop)
        stats     — launched-vs-effective unit accounting for the
                    ``engine_step`` cost family (padding INCLUDED in
                    launched work: pad prefill units still stream their
                    scratch-page chunk and run the masked MXU update)

    Every array shape depends only on ``rung`` and ``geom`` — the
    engine's compile-once contract.
    """
    from flashinfer_tpu.ops.paged_decode import build_decode_split_units
    from flashinfer_tpu.ops.paged_prefill import build_prefill_work_units

    ps = geom.page_size
    total = segs[-1].row0 + segs[-1].n if segs else 0
    if total > rung:
        raise ValueError(f"schedule has {total} rows > rung {rung}")
    row = 0
    for s in segs:
        if s.row0 != row:
            raise ValueError("schedule segments must tile the flat axis "
                             f"contiguously (row {row} != seg.row0 "
                             f"{s.row0})")
        row += s.n
    U = geom.prefill_unit_cap(rung)

    # ---- level 0: shared-prefix groups, causal-by-global-position ----
    qo0 = [0]
    kv0_lens: List[int] = []
    pi0 = [0]
    pages0: List[int] = []
    mask_parts: List[np.ndarray] = []

    def _close_seg0(n_rows, kv_len, pages, pos):
        qo0.append(qo0[-1] + n_rows)
        kv0_lens.append(kv_len)
        pages0.extend(pages)
        pi0.append(len(pages0))
        if kv_len > 0:
            cols = np.arange(kv_len)
            mask_parts.append(
                (cols[None, :] <= pos[:, None]).reshape(-1))

    i = 0
    while i < len(segs):
        s = segs[i]
        run = s.pages[:s.split // ps]
        j = i
        rows = 0
        pos: List[int] = []
        while j < len(segs) and segs[j].group == s.group:
            e = segs[j]
            pos.extend(range(e.kv_after - e.n, e.kv_after))
            rows += e.n
            j += 1
        if run:
            _close_seg0(rows, s.split, run, np.asarray(pos, np.int64))
        else:
            # no shared span (split == 0): level 0 is empty for these
            # rows — a kv_len=0 segment emits the (0, -inf) pass-through
            _close_seg0(rows, 0, (), np.zeros(0, np.int64))
        i = j
    if total < rung:  # rung padding rows: defined zeros, empty state
        _close_seg0(rung - total, 0, (), np.zeros(0, np.int64))
    mask0 = (np.concatenate(mask_parts) if mask_parts
             else np.zeros(0, bool))
    plan0 = build_prefill_work_units(
        np.asarray(qo0, np.int64), np.asarray(pi0, np.int64),
        np.asarray(pages0, np.int64), np.asarray(kv0_lens, np.int64),
        geom.block_q, geom.prefill_ppc, ps,
        mask_flat=mask0, causal=False, window_left=-1,
        pack_tiles=True, prune=True, num_units_pad=U,
    )

    # ---- level 1: per-segment suffix windows, native causal rule ----
    qo1 = [0]
    kv1_lens: List[int] = []
    pi1 = [0]
    pages1: List[int] = []
    for s in segs:
        qo1.append(qo1[-1] + s.n)
        suffix = s.kv_after - s.split
        if s.decoding or suffix <= 0:
            kv1_lens.append(0)
            pi1.append(len(pages1))
            continue
        kv1_lens.append(suffix)
        pages1.extend(s.pages[s.split // ps: cdiv(s.kv_after, ps)])
        pi1.append(len(pages1))
    if total < rung:
        qo1.append(rung)
        kv1_lens.append(0)
        pi1.append(len(pages1))
    plan1 = build_prefill_work_units(
        np.asarray(qo1, np.int64), np.asarray(pi1, np.int64),
        np.asarray(pages1, np.int64), np.asarray(kv1_lens, np.int64),
        geom.block_q, geom.prefill_ppc, ps,
        causal=True, window_left=-1,
        pack_tiles=True, prune=True, num_units_pad=U,
    )

    # ---- decode lanes: split-KV units over per-slot suffix tables ----
    dec_table = np.zeros((geom.max_batch, geom.dec_width), np.int32)
    dec_lens = np.zeros(geom.max_batch, np.int64)
    dec_rows = np.full(geom.max_batch, rung, np.int32)  # rung == idle
    for s in segs:
        if not s.decoding:
            continue
        suffix_pages = s.pages[s.split // ps: cdiv(s.kv_after, ps)]
        dec_table[s.slot, :len(suffix_pages)] = suffix_pages
        dec_lens[s.slot] = s.kv_after - s.split
        dec_rows[s.slot] = s.row0
    dplan = build_decode_split_units(
        dec_table, dec_lens, num_splits=geom.num_splits,
        page_size=ps, pages_per_chunk=geom.decode_ppc,
    )
    assert dplan["pages"].shape == (geom.max_batch, geom.dec_width), \
        (dplan["pages"].shape, geom.dec_width)
    assert not geom.single_chunk or dplan["single_chunk"]

    # ---- fused-ingest plan (ISSUE 14): rung-stable EXTRA plan -------
    # Present on every bundle of a fused_ingest geom so the traced
    # pytree never changes shape.  A step qualifies when its WHOLE
    # schedule is a from-scratch prefill (kv_before == 0, i.e.
    # kv_after == n, no decode lanes): every attended KV row is one of
    # THIS step's raw rows, so the ingest launch can rotate +
    # quantize-append + attend them in one pass.  The cascade split is
    # irrelevant here — it merely PARTITIONS the causal attention the
    # single launch computes whole (merge is associative), and no
    # cross-request page is shared on a from-scratch step (fresh pages
    # per request).  Non-qualifying steps carry the padding-only
    # degenerate plan with ``ingest_on = 0`` — the body's lax.cond
    # keeps the composed tier.
    ingest_plan = None
    ingest_on = 0
    if geom.fused_ingest:
        from flashinfer_tpu.ops.paged_prefill import (
            build_prefill_ingest_units)

        eligible = bool(segs) and all(
            s.kv_after == s.n and not s.decoding for s in segs)
        if eligible:
            qoI = [0]
            kvI: List[int] = []
            piI = [0]
            pagesI: List[int] = []
            basesI: List[int] = []
            for s in segs:
                qoI.append(qoI[-1] + s.n)
                kvI.append(s.kv_after)
                pagesI.extend(s.pages[: cdiv(s.kv_after, ps)])
                piI.append(len(pagesI))
                basesI.append(s.row0)
            if total < rung:  # rung padding rows: empty-state segment
                qoI.append(rung)
                kvI.append(0)
                piI.append(len(pagesI))
                basesI.append(total)
            ingest_plan = build_prefill_ingest_units(
                np.asarray(qoI, np.int64), np.asarray(piI, np.int64),
                np.asarray(pagesI, np.int64), np.asarray(kvI, np.int64),
                geom.block_q, geom.prefill_ppc, ps,
                causal=True, window_left=-1,
                pack_tiles=True, prune=True, num_units_pad=U,
                fused_ingest={"kv_bases": np.asarray(basesI, np.int64)},
            )
            ingest_on = 1
        else:
            ingest_plan = build_prefill_ingest_units(
                np.asarray([0, rung], np.int64),
                np.asarray([0, 0], np.int64),
                np.zeros(0, np.int64), np.asarray([0], np.int64),
                geom.block_q, geom.prefill_ppc, ps,
                causal=True, window_left=-1,
                pack_tiles=True, prune=True, num_units_pad=U,
            )
        # the rung contract: the cap must hold for the ingest plan too
        # (causal from-scratch geometry never emits write-only units —
        # the last tile of each request keeps every chunk)
        assert ingest_plan["qstart"].shape[0] == U, \
            (ingest_plan["qstart"].shape, U)
        assert ingest_plan["stats"].get("ingest_write_only_units",
                                        0) == 0

    chunk_tokens = geom.prefill_ppc * ps
    stats = {
        # launched work counts the PADDED unit grid: pad units still
        # DMA their scratch-page chunk and run the masked MXU update,
        # which is exactly the waste effective_pct_roofline exposes
        "prefill_units": plan0["stats"]["units"] + plan1["stats"]["units"],
        "prefill_units_launched": 2 * U,
        "prefill_cells_launched": 2.0 * U * geom.block_q * chunk_tokens,
        "prefill_cells_valid": float(plan0["stats"]["mxu_cells_valid"]
                                     + plan1["stats"]["mxu_cells_valid"]),
        "prefill_rows_launched": 2.0 * U * chunk_tokens,
        "decode_pages_real": dplan["stats"]["pages_real"],
        "decode_pages_launched": dplan["stats"]["pages_launched"],
        "decode_rows_launched": float(
            dplan["stats"]["pages_launched"] * ps),
        "decode_cells_launched": float(
            dplan["stats"]["pages_launched"] * ps),
        "decode_cells_valid": float(dec_lens.sum()),
    }
    out = dict(prefill0=plan0, prefill1=plan1, decode=dplan,
               dec_rows=dec_rows, stats=stats)
    if ingest_plan is not None:
        out["ingest"] = ingest_plan
        out["ingest_on"] = ingest_on
        stats["ingest_on"] = ingest_on
        stats["ingest_chunks"] = (
            ingest_plan["stats"].get("ingest_chunks", 0) if ingest_on
            else 0)
    return out


def plans_to_device(plans: Dict) -> Dict:
    """The rung-stable traced pytree of a plan bundle: array leaves
    only (statics/stats stripped), in a FIXED key layout so the jitted
    body never sees a structure change."""
    import jax.numpy as jnp

    out = dict(
        prefill0={k: jnp.asarray(plans["prefill0"][k])
                  for k in PREFILL_ARRAY_KEYS},
        prefill1={k: jnp.asarray(plans["prefill1"][k])
                  for k in PREFILL_ARRAY_KEYS if k != "mask_bytes"},
        decode={k: jnp.asarray(plans["decode"][k])
                for k in DECODE_ARRAY_KEYS},
        dec_rows=jnp.asarray(plans["dec_rows"]),
    )
    if "ingest" in plans:  # fused_ingest geoms: structurally ALWAYS on
        out["ingest"] = {k: jnp.asarray(plans["ingest"][k])
                         for k in INGEST_ARRAY_KEYS}
        out["ingest_on"] = jnp.asarray(plans["ingest_on"], jnp.int32)
    return out


def engine_kernel_attention(q, k_cache, v_cache, kplans, *,
                            geom: EngineKernelGeom, sm_scale: float):
    """One layer's engine attention on the Pallas work units (traced
    inside the engine's jitted body): level-0 + level-1 prefill
    launches, the split-KV decode launch scattered into level 1, and
    the cascade merge fold.  Returns f32 ``[T, H, D]`` (the same
    contract as the reference backend's compose output; int8-KV
    v_scale is applied by the caller after the merge, which is exact
    because merging is linear in V)."""
    from flashinfer_tpu.cascade import compose_cascade_levels
    from flashinfer_tpu.ops.paged_decode import paged_decode_attention_split
    from flashinfer_tpu.ops.paged_prefill import fused_paged_prefill

    p0, p1, pd = kplans["prefill0"], kplans["prefill1"], kplans["decode"]
    o0, lse0 = fused_paged_prefill(
        q, k_cache, v_cache, p0,
        num_units=p0["qstart"].shape[0], block_q=geom.block_q,
        pages_per_chunk=geom.prefill_ppc, sm_scale=sm_scale,
        causal=False, return_lse=True)
    o1, lse1 = fused_paged_prefill(
        q, k_cache, v_cache, p1,
        num_units=p1["qstart"].shape[0], block_q=geom.block_q,
        pages_per_chunk=geom.prefill_ppc, sm_scale=sm_scale,
        causal=True, return_lse=True)
    dec_rows = kplans["dec_rows"]
    # idle lanes carry row == rung: the gather clips to a real row whose
    # q is then attended against a kv_len=0 table (empty state), and
    # the scatter back drops out-of-bounds lanes entirely
    qd = q[dec_rows]
    od, lsed = paged_decode_attention_split(
        qd, k_cache, v_cache, pd,
        num_units=pd["wu_req"].shape[0], num_splits=geom.num_splits,
        single_chunk=geom.single_chunk,
        pages_per_chunk=geom.decode_ppc, sm_scale=sm_scale,
        return_lse=True)
    o1 = o1.at[dec_rows].set(od.astype(o1.dtype), mode="drop")
    lse1 = lse1.at[dec_rows].set(lsed.astype(lse1.dtype), mode="drop")
    out, _ = compose_cascade_levels([(o0, lse0), (o1, lse1)])
    return out


def engine_kernel_ingest_attention(q, k, v, k_cache, v_cache, kplans, *,
                                   geom: EngineKernelGeom,
                                   sm_scale: float, rope_theta: float,
                                   kv_quant: str, k_scale: float,
                                   v_scale: float):
    """The fused-ingest form of one layer's engine attention (ISSUE
    14, traced inside the engine body's ``lax.cond`` TRUE branch): the
    step's RAW pre-RoPE q/k/v rows ride ONE
    :func:`~flashinfer_tpu.ops.paged_prefill.fused_paged_prefill_ingest`
    launch that rotates in-register, quantize-appends the finished
    pages, and attends the in-register values — replacing the
    rope -> scatter-append -> (level-0 + level-1 + decode + merge)
    composition for the from-scratch prefill step the plan bundle
    certified (``ingest_on``; level 0 and decode are structurally
    empty there, so the cascade fold is the identity).

    ``sm_scale`` is the PLAIN softmax scale — the launcher owns the
    quantized-cache scale folding, so the output lands already
    v-scaled in ``q.dtype`` (matching the composed tier's
    ``(compose * v_scale).astype`` epilogue bit-for-bit for int8, see
    tests/test_prefill_ingest.py).  Returns ``(attn, k_cache,
    v_cache)`` with the caches updated by the launch."""
    from flashinfer_tpu.ops.paged_prefill import fused_paged_prefill_ingest

    plan = kplans["ingest"]
    attn, (kc, vc) = fused_paged_prefill_ingest(
        q, k, v, k_cache, v_cache, plan,
        num_units=plan["qstart"].shape[0], block_q=geom.block_q,
        pages_per_chunk=geom.prefill_ppc, sm_scale=sm_scale,
        causal=True, rope_theta=float(rope_theta),
        kv_quant=kv_quant, k_scale=float(k_scale),
        v_scale=float(v_scale),
    )
    return attn, kc, vc
