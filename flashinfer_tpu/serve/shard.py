"""The int8-weight 70B-shard serving step, fused and per-op forms.

The exact pipeline bench.py's ``serving`` phase measures (one v5e chip
holding the tp=8 per-chip shard of Llama-3-70B: int8 weights + int8 KV,
fused-wqkv projections) lifted out of the bench into a library surface
so the ``serving_fused`` A/B phase can run BOTH serving-loop shapes
over the same math:

- :func:`build_fused_step` — the whole decode step (rmsnorm -> fused
  int8 qkv -> RoPE -> **paged int8-KV append** -> int8-KV paged decode
  attention -> o/mlp int8 GEMMs -> lm_head shard -> top-k sampling)
  as ONE jitted program with the KV caches, page table, lens, and
  sampling key donated: per step, one dispatch, zero buffer copies.
- :func:`build_per_op_step` — the SAME math as the per-phase jitted
  micro-loop serving flow (each layer and the head+sampling epilogue
  its own jitted call, caches donated per call): the dispatch
  structure of the pre-fused serving loop, numerics identical.

The A/B difference between the two is pure host scheduling — the
dispatch residual the ``overhead_decomposition`` row attributed but
could not remove (VERDICT weak #2), now deleted by donation + fusion.

Scale conventions (sm_scale*k_scale folding, output *v_scale) follow
the models/llama.py int8-KV contract and tests/test_quant_kv.py.

TWIN NOTE: bench.py ``phase_serving`` carries its own inline ``_layer``
copy of this pipeline (with profiler scopes and an append toggle) whose
banked slope/e2e rows were measured on hardware under that exact code —
it is deliberately NOT rewired through this module until the fused
phase has its own on-chip proof, so a numerics edit here must be
mirrored there (and vice versa).  The ``serving_fused`` A/B uses THIS
module for both of its variants, so the A/B itself cannot drift.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Int8ShardSpec:
    """Frozen statics of the int8 shard pipeline (the quantization
    mode, page geometry, and sampling config of the fused step)."""

    bs: int
    hidden: int
    hq: int
    hkv: int
    hd: int
    inter: int
    vocab_shard: int
    page_size: int = 16
    k_scale: float = 0.05
    v_scale: float = 0.05
    top_k: int = 40
    # attention backend, resolved EAGERLY when the spec is built
    # (pass flashinfer_tpu.utils.is_tpu()): the step closure reads no
    # environment at trace time (L003 staticness)
    use_pallas: bool = False

    @property
    def qdim(self) -> int:
        return self.hq * self.hd

    @property
    def kvdim(self) -> int:
        return self.hkv * self.hd


def shard_layer(x, w, kcl, vcl, pt, lens, spec: Int8ShardSpec):
    """One decoder layer of the int8 shard pipeline, INCLUDING the
    per-step paged KV append (quantize + scatter of the new token's
    K/V — the real serving write path; the fused step never excludes
    it).  ``w`` is the per-layer weight tuple
    ``(wqkv, sqkv, wo, so, wgu, sgu, wd, sd, n1, n2)``."""
    from flashinfer_tpu.activation import silu_and_mul
    from flashinfer_tpu.gemm import mm_int8
    from flashinfer_tpu.norm import rmsnorm
    from flashinfer_tpu.ops import paged_decode_attention
    from flashinfer_tpu.ops.xla_ref import xla_paged_decode
    from flashinfer_tpu.quantization import quantize_int8
    from flashinfer_tpu.rope import apply_rope_pos_ids

    bs, qdim, kvdim = spec.bs, spec.qdim, spec.kvdim
    PS = spec.page_size
    wqkv, sqkv, wo, so, wgu, sgu, wd, sd, n1, n2 = w
    h = rmsnorm(x, n1.astype(x.dtype))
    hq8, hs = quantize_int8(h)
    qkv = mm_int8(hq8, wqkv, hs, sqkv)
    q = qkv[:, :qdim].reshape(bs, spec.hq, spec.hd)
    k = qkv[:, qdim:qdim + kvdim].reshape(bs, spec.hkv, spec.hd)
    q, k = apply_rope_pos_ids(q, k, lens)
    v = qkv[:, qdim + kvdim:].reshape(bs, spec.hkv, spec.hd)
    pages = jnp.take_along_axis(pt, lens[:, None] // PS, axis=1)[:, 0]
    slots = lens % PS
    k8 = jnp.clip(jnp.round(k.astype(jnp.float32) / spec.k_scale),
                  -127, 127).astype(jnp.int8)
    v8 = jnp.clip(jnp.round(v.astype(jnp.float32) / spec.v_scale),
                  -127, 127).astype(jnp.int8)
    kcl = kcl.at[pages, :, slots, :].set(k8)
    vcl = vcl.at[pages, :, slots, :].set(v8)
    attn_fn = paged_decode_attention if spec.use_pallas \
        else xla_paged_decode
    attn = attn_fn(
        q.astype(jnp.bfloat16), kcl, vcl, pt, lens + 1,
        sm_scale=spec.hd ** -0.5 * spec.k_scale, kv_layout="HND",
    ) * spec.v_scale
    a8, as_ = quantize_int8(attn.reshape(bs, qdim).astype(x.dtype))
    x = x + mm_int8(a8, wo, as_, so)
    h2 = rmsnorm(x, n2.astype(x.dtype))
    g8, gs = quantize_int8(h2)
    mlp = silu_and_mul(mm_int8(g8, wgu, gs, sgu))
    m8, ms = quantize_int8(mlp)
    x = (x + mm_int8(m8, wd, ms, sd)).astype(x.dtype)
    return x, kcl, vcl


def head_and_sample(x, head, head_s, skey, spec: Int8ShardSpec):
    """The lm_head shard + top-k sampling epilogue; the sampled token
    folds into the PRNG key so consecutive steps chain without an
    embedding matrix (the shard pipeline has none)."""
    from flashinfer_tpu.gemm import mm_int8
    from flashinfer_tpu.norm import rmsnorm
    from flashinfer_tpu.quantization import quantize_int8
    from flashinfer_tpu.sampling import (sampling_from_logits,
                                         top_k_mask_logits)

    hq8, hs = quantize_int8(
        rmsnorm(x, jnp.ones((spec.hidden,), x.dtype)))
    logits = mm_int8(hq8, head, hs, head_s, out_dtype=jnp.float32)
    tok = sampling_from_logits(top_k_mask_logits(logits, spec.top_k),
                               skey)
    return tok, jax.random.fold_in(skey, tok[0])


def build_fused_step(spec: Int8ShardSpec, *, donate: bool = True):
    """The compile-once fused shard step: ONE jitted program per
    serving session, KV caches / page table / lens / PRNG key donated.

    Signature: ``step(x0, layer_ws, caches, head, head_s, pt, lens,
    skey) -> (tok, caches, pt, lens, skey)`` where ``layer_ws`` is a
    list of per-layer weight tuples and ``caches`` a matching list of
    ``(k, v)`` int8 HND pages.  ``lens`` passes through unchanged
    (each step overwrites the same slot — the shape-identical
    steady-state step the bench measures; a serving engine advances
    lens between plans)."""

    def _body(x0, layer_ws, caches, head, head_s, pt, lens, skey):
        x = x0
        new_caches = []
        for w, (kcl, vcl) in zip(layer_ws, caches):
            x, kcl, vcl = shard_layer(x, w, kcl, vcl, pt, lens, spec)
            new_caches.append((kcl, vcl))
        tok, skey = head_and_sample(x, head, head_s, skey, spec)
        return tok, new_caches, pt, lens, skey

    donate_argnums = (2, 5, 6, 7) if donate else ()
    return jax.jit(_body, donate_argnums=donate_argnums)


def build_per_op_step(spec: Int8ShardSpec, *, donate: bool = True):
    """The SAME step as :func:`build_fused_step` in the pre-fused
    serving-loop dispatch structure: one jitted call PER LAYER plus a
    jitted head+sampling epilogue, chained by a host Python loop.
    Returns ``step(x0, layer_ws, caches, head, head_s, pt, lens,
    skey)`` with the fused step's signature — the A/B twin differs
    only in how many XLA programs one serving step dispatches
    (layers + 1 here, 1 there)."""
    layer_fn = jax.jit(
        lambda x, w, kcl, vcl, pt, lens: shard_layer(
            x, w, kcl, vcl, pt, lens, spec),
        donate_argnums=(2, 3) if donate else (),
    )
    epilogue_fn = jax.jit(
        lambda x, head, head_s, skey: head_and_sample(
            x, head, head_s, skey, spec))

    def step(x0, layer_ws, caches, head, head_s, pt, lens, skey):
        x = x0
        new_caches = []
        for w, (kcl, vcl) in zip(layer_ws, caches):
            x, kcl, vcl = layer_fn(x, w, kcl, vcl, pt, lens)
            new_caches.append((kcl, vcl))
        tok, skey = epilogue_fn(x, head, head_s, skey)
        return tok, new_caches, pt, lens, skey

    return step
