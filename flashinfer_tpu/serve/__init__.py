"""flashinfer_tpu.serve — the compile-once serving layer.

The serving engine's fused-step substrate (ROADMAP items 1 and 3): a
decode (or mixed chunked-prefill + decode) step compiled ONCE into a
single donated-buffer XLA program, replacing the Python-loop-over-ops
serving flow whose per-step host dispatch tax PR 2's
``overhead_decomposition`` measured at 1.13-1.31x e2e-vs-slope.

Reference analogue: ``fast_decode_plan`` + CUDAGraph capture (frozen
shapes, replayed launches) and the persistent mixed-batch kernel
(``persistent.cuh:682``) that runs a whole decode step as one launch.
The TPU form: plan() freezes every static (shapes, page geometry,
quantization mode, sampling config, backend) host-side, and run() is
one ``jax.jit`` call with ``donate_argnums`` on the KV caches, page
tables, and sampling key so XLA aliases the serving state in place and
the step never retraces across tokens.

- :mod:`~flashinfer_tpu.serve.step` — :class:`ServingStep` (decode
  mega-step over the Llama model family), :class:`MixedServingStep`
  (chunked prefill + decode in ONE step over the holistic
  BatchAttention plan arrays), :class:`SamplingConfig`;
- :mod:`~flashinfer_tpu.serve.shard` — the int8-weight 70B-shard step
  pipeline bench.py's ``serving``/``serving_fused`` phases measure;
- :mod:`~flashinfer_tpu.serve.engine` — the continuous-batching
  serving ENGINE above the steps: ref-counted paged-KV block pool,
  prefix-cache reuse via the cascade merge operator, and SLO-aware
  scheduling on a pre-compiled rung ladder (:class:`ServingEngine`,
  :class:`EngineConfig`, :class:`EngineRequest`, :class:`BlockPool`,
  :class:`PrefixCache`);
- :mod:`~flashinfer_tpu.serve.kv_tier` — the TIERED KV subsystem:
  :class:`HostKVStore` (host-RAM offload below the block pool —
  spill/restore with bit-exact resume, so effective KV capacity
  exceeds the chip's HBM GiB) and :class:`DisaggServing`
  (prefill-pool → decode-pool disaggregation joined by the
  ICI-priced ``kv_migrate`` handoff; docs/serving.md §"Tiered KV &
  disaggregation");
- :mod:`~flashinfer_tpu.serve.engine_kernels` — the engine's KERNEL
  attention tier (``EngineConfig.attention_backend="kernel"``): the
  host planner that lowers each step's schedule onto the work-unit
  prefill mainloop + split-KV decode plan arrays, and the in-jit
  cascade-merged composition (docs/performance.md §"Engine kernel
  graduation").

See docs/performance.md ("Compile-once serving step") for the step
lifecycle and donation contract, and docs/serving.md for the engine.
"""

from flashinfer_tpu.serve.engine import (
    BlockPool,
    EngineConfig,
    EngineRequest,
    PrefixCache,
    ServingEngine,
)
from flashinfer_tpu.serve.kv_tier import (
    DisaggServing,
    HostKVStore,
    migrate_request,
)
from flashinfer_tpu.serve.step import (
    MixedServingStep,
    SamplingConfig,
    ServingStep,
    ServingStepPlan,
    mixed_chunk_tokens,
    sample_next_tokens,
)

__all__ = [
    "BlockPool",
    "DisaggServing",
    "EngineConfig",
    "EngineRequest",
    "HostKVStore",
    "MixedServingStep",
    "PrefixCache",
    "SamplingConfig",
    "ServingEngine",
    "ServingStep",
    "ServingStepPlan",
    "migrate_request",
    "mixed_chunk_tokens",
    "sample_next_tokens",
]
