"""Continuous-batching serving engine on the compile-once steps.

The layer that serves *many concurrent requests* — what vLLM's
PagedAttention block pool and SGLang's RadixAttention prefix reuse
provide above a kernel library like the reference (SURVEY: "the
'model' layer lives in its consumers").  Three parts:

- :class:`BlockPool` — a paged-KV **block pool** over the existing
  page-table geometry: allocation, free list, and eviction with
  REF-COUNTED block sharing, so N requests holding the same prompt
  prefix point at one physical page run.
- :class:`PrefixCache` — a **prefix trie** keyed on token-block hashes
  (one node per full KV page, hash chained through the parent so equal
  blocks at different depths never collide).  Hits skip prefill for the
  shared span entirely; the engine composes the shared-prefix attention
  level with the per-request suffix level through the cascade merge
  operator (:func:`flashinfer_tpu.cascade.compose_cascade_levels`,
  reference ``cascade.cuh:45-471``).  Hit/miss traffic is metered as
  ``engine.prefix_{hit,miss}_tokens``.
- :class:`ServingEngine` — the **scheduler**: request admission with
  priority/SLO-aware ordering, chunked-prefill token budgeting that
  packs decode + prefill chunks onto ONE flat token axis, and
  preemption-by-eviction with recompute-on-resume.  Admission chunk
  sizing is priced by ``obs.costmodel.predict_step_seconds`` over the
  analytic ``engine_step`` cost family — not by heuristics (the
  ROADMAP item 5 direction).

Compile-once contract (the retrace-budget story ``obs trace
--selftest`` gates): the engine never re-plans per scheduling decision.
The jitted step body takes the per-step schedule — flat tokens,
positions, scatter targets, per-token window bounds, group page runs —
as ARGUMENTS with rung-padded shapes, so schedule *values* change
freely without retracing.  Padded shapes come from a small LADDER of
token-axis sizes (:attr:`EngineConfig.ladder`); each rung traces
exactly once and steady state replays compiled programs, keeping a
whole serving session inside the 9-trace budget.

Bitwise-reproducibility contract (the test anchor): attention uses
per-request KV windows whose row offset of KV position ``j`` is always
``j`` — POSITION-determined, never packing-determined.  Padding lanes
contribute exact zeros (masked ``p = 0``), and ``x + 0.0`` is exact,
so a token's attention state is bit-identical regardless of which
other requests share its step.  That makes engine output with prefix
sharing ON bitwise-equal to the no-sharing oracle (same requests, full
per-request prefill) — pinned across f32 and int8-KV caches in
tests/test_serve_engine.py.

Attention backends (``EngineConfig.attention_backend``, the
``engine.attention_backend`` autotuner knob):

- ``"reference"`` — the dense XLA windowed form above.  This is the
  engine's INTERPRET-MODE ORACLE TIER: O(T x K) through masked lanes,
  but provably bitwise vs the no-sharing oracle on CPU, so it anchors
  every correctness claim the kernel tier is measured against.
- ``"kernel"`` — the graduated path (ROADMAP item 1): the per-step
  schedule lowers through
  :func:`~flashinfer_tpu.serve.engine_kernels.build_engine_work_units`
  onto the PR 3 work-unit prefill mainloop and the PR 6 split-KV
  decode units, composed by the same cascade merge fold.  Plan arrays
  ride as rung-padded ARGUMENTS (shapes are rung statics), so the
  kernel tier keeps the compile-once ladder; it skips the masked-lane
  HBM/FLOP waste the reference tier pays.  Tokens are pinned equal to
  the reference tier (tests/test_engine_kernels.py), and interpret
  mode makes the whole path CPU-testable before the first on-chip
  session (``bench.py --only serving_engine`` A/Bs the two).

See docs/serving.md for lifecycle, pool invariants, prefix-cache
semantics, scheduler knobs, and the retrace-budget contract.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flashinfer_tpu.api_logging import flashinfer_api
from flashinfer_tpu.serve.step import SamplingConfig

_NEG_INF = -1e30  # matches ops/merge.py and ops/xla_ref.py


# ---------------------------------------------------------------------------
# Block pool
# ---------------------------------------------------------------------------


class BlockPool:
    """Ref-counted paged-KV block pool.

    Physical pages are integer ids into the engine's cache arrays
    ``[num_pages, Hkv, page_size, Hd]``.  Page 0 is the SCRATCH page:
    padding lanes of every step scatter into it and it is never
    allocated, so pad writes can never clobber live KV.

    Invariants (stress-pinned in tests/test_serve_engine.py):

    - a page is in the free list iff its refcount is 0;
    - ``alloc`` never returns a page whose refcount is non-zero;
    - ``decref`` below zero raises (double-free is a bug, not a state).
    """

    SCRATCH_PAGE = 0

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("BlockPool needs >= 2 pages (page 0 is "
                             "the reserved scratch page)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._ref = np.zeros(num_pages, np.int32)
        # LIFO free list: recently-freed pages are re-used first (their
        # cache lines are warm and stale contents are masked anyway)
        self._free = list(range(num_pages - 1, 0, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def ref(self, page: int) -> int:
        return int(self._ref[page])

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` free pages at refcount 1, or None (caller evicts /
        preempts and retries — partial allocations never escape)."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            assert self._ref[p] == 0, f"free-list page {p} has refs"
            self._ref[p] = 1
        return pages

    def incref(self, pages: Sequence[int]) -> None:
        for p in pages:
            if self._ref[p] <= 0:
                raise ValueError(f"incref on unowned page {p}")
            self._ref[p] += 1

    def decref(self, pages: Sequence[int]) -> int:
        """Drop one reference per page; pages reaching 0 return to the
        free list.  Returns how many pages were freed."""
        freed = 0
        for p in pages:
            if self._ref[p] <= 0:
                raise ValueError(f"decref on free page {p}")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)
                freed += 1
        return freed


# ---------------------------------------------------------------------------
# Prefix cache (trie keyed on token-block hashes)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _TrieNode:
    key: Tuple[int, int]  # (parent node id, block hash)
    page: int
    node_id: int
    parent: Optional[int]
    children: int = 0
    last_use: int = 0


def _block_hash(parent_hash: int, tokens: Sequence[int]) -> int:
    """Chained token-block hash: equal token blocks under different
    prefixes hash differently (the RadixAttention/vLLM block-hash
    scheme), so a trie edge fully identifies prefix CONTENT."""
    h = parent_hash
    for t in tokens:
        h = (h * 1000003 + int(t) + 1) & 0x7FFFFFFFFFFFFFFF
    return h


class PrefixCache:
    """Prefix trie over full KV pages; holds one pool reference per
    cached page (the "cache ownership" ref), so a cached page survives
    the requests that built it and is evictable exactly when only the
    cache still references it (refcount == 1)."""

    def __init__(self, pool: BlockPool):
        self._pool = pool
        self._nodes: Dict[Tuple[int, int], _TrieNode] = {}
        self._by_id: Dict[int, _TrieNode] = {}
        self._next_id = 1
        self._clock = 0

    @property
    def num_pages(self) -> int:
        return len(self._nodes)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def lookup(self, prompt: Sequence[int], max_pages: int
               ) -> Tuple[List[int], int]:
        """Longest cached page-run for ``prompt`` (full pages only,
        capped at ``max_pages``) -> (pages, hit_tokens).  Bumps LRU
        clocks; takes NO references — the caller increfs the pages it
        actually adopts."""
        ps = self._pool.page_size
        pages: List[int] = []
        parent, phash = 0, 0
        now = self._tick()
        for i in range(max_pages):
            blk = prompt[i * ps:(i + 1) * ps]
            if len(blk) < ps:
                break
            phash = _block_hash(phash, blk)
            node = self._nodes.get((parent, phash))
            if node is None:
                break
            node.last_use = now
            pages.append(node.page)
            parent = node.node_id
        return pages, len(pages) * ps

    def insert(self, prompt: Sequence[int], pages: Sequence[int],
               upto_pages: int) -> int:
        """Register the first ``upto_pages`` full pages of ``prompt``.
        Pages already cached (same content) keep the EXISTING node —
        a concurrent private copy stays private.  Newly-adopted pages
        get one cache-ownership incref.  Returns pages adopted."""
        ps = self._pool.page_size
        parent, phash = 0, 0
        now = self._tick()
        adopted = 0
        for i in range(upto_pages):
            blk = prompt[i * ps:(i + 1) * ps]
            if len(blk) < ps:
                break
            phash = _block_hash(phash, blk)
            node = self._nodes.get((parent, phash))
            if node is None:
                node = _TrieNode(key=(parent, phash), page=int(pages[i]),
                                 node_id=self._next_id, parent=parent,
                                 last_use=now)
                self._next_id += 1
                self._nodes[node.key] = node
                self._by_id[node.node_id] = node
                if parent:
                    self._by_id[parent].children += 1
                self._pool.incref([node.page])
                adopted += 1
            else:
                node.last_use = now
            parent = node.node_id
        return adopted

    def evict(self, pages_needed: int) -> int:
        """LRU-evict leaf nodes whose page only the cache references
        (pool refcount == 1) until ``pages_needed`` pages are freed or
        no candidate remains.  Returns pages actually freed.

        One scan gathers ALL current candidates sorted by LRU and
        drains them in order (admission hot path: O(nodes log nodes)
        per trie LEVEL, not O(nodes) per page); evicting a leaf can
        expose its parent as a new candidate, so the outer loop
        re-scans only when a full candidate batch was not enough."""
        from flashinfer_tpu import obs

        freed = 0
        while freed < pages_needed:
            candidates = sorted(
                (n for n in self._nodes.values()
                 if not n.children and self._pool.ref(n.page) == 1),
                key=lambda n: n.last_use)
            if not candidates:
                break
            for victim in candidates:
                del self._nodes[victim.key]
                del self._by_id[victim.node_id]
                if victim.parent:
                    self._by_id[victim.parent].children -= 1
                freed += self._pool.decref([victim.page])
                obs.counter_inc("engine.evictions")
                if freed >= pages_needed:
                    break
        return freed


# ---------------------------------------------------------------------------
# Requests + engine config
# ---------------------------------------------------------------------------

_WAITING, _RUNNING, _FINISHED = "waiting", "running", "finished"


@dataclasses.dataclass
class EngineRequest:
    """One serving request.  ``priority`` orders admission (lower is
    more urgent); ``slo_ttft_s`` turns into an admission deadline so
    SLO-pressed requests overtake equal-priority peers."""

    rid: str
    prompt: List[int]
    max_new_tokens: int = 8
    priority: int = 0
    slo_ttft_s: Optional[float] = None

    # -- runtime state (engine-owned) --
    state: str = _WAITING
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    slot: int = -1
    pages: List[int] = dataclasses.field(default_factory=list)
    kv_len: int = 0          # tokens whose KV is materialized
    split: int = -1          # cascade level boundary (page-aligned);
    #                          frozen at FIRST admission and preserved
    #                          across preemptions, so the two-level
    #                          decomposition — and therefore every
    #                          logit bit — is identical whether or not
    #                          the request was ever preempted
    hit_tokens: int = 0      # prefix-cache tokens adopted at admission
    inserted_pages: int = 0  # full pages registered in the trie so far
    folded_out: int = 0      # out tokens folded into prompt on preempt
    vacated: bool = False    # left the device mid-flight (preempt /
    #                          spill / idle offload) and not yet
    #                          re-admitted — the recompute-vs-restore
    #                          miss attribution keys on this, so even a
    #                          zero-token mid-prefill vacate counts
    arrival: int = -1
    enqueue_t: float = 0.0
    deadline: float = float("inf")
    preemptions: int = 0

    def seq(self) -> List[int]:
        """The token sequence as the model sees it: prompt (including
        any generated tokens folded back by a preemption) plus the
        not-yet-folded generated tail."""
        return self.prompt + self.out_tokens[self.folded_out:]

    def total_len(self) -> int:
        return len(self.prompt) + len(self.out_tokens) - self.folded_out


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Frozen engine statics.  ``block_size`` / ``prefill_budget_tokens``
    / ``max_batch`` / ``attention_backend`` are autotuner knobs
    (``engine.*`` in KNOWN_KNOBS, shape-keyed on the model's
    (hidden, hq, hkv, hd)); ``from_knobs`` resolves them through the
    per-chip-gen tuning configs."""

    num_pages: int                  # physical pages incl. scratch page 0
    page_size: int = 16             # engine.block_size
    max_batch: int = 8              # engine.max_batch (batch slots)
    prefill_budget_tokens: int = 64  # engine.prefill_budget_tokens
    max_seq_tokens: int = 256       # per-request context bound
    ladder: Tuple[int, ...] = ()    # rung token sizes; () = derived
    kv_dtype: Optional[object] = None   # default model cfg dtype
    sampling: SamplingConfig = SamplingConfig()
    enable_prefix_cache: bool = True
    slo_step_seconds: Optional[float] = None  # predicted-step-time cap
    donate: bool = True
    seed: int = 0
    # engine.attention_backend: "reference" = the dense XLA oracle
    # tier (bitwise-provable on CPU), "kernel" = the Pallas work-unit
    # lowering (serve/engine_kernels.py; interpret-mode off-TPU)
    attention_backend: str = "reference"
    decode_num_splits: int = 1      # kernel tier's split-KV factor
    # prefill.fused_ingest (ISSUE 14): "on" = kernel-tier from-scratch
    # prefill steps ride the fused RoPE + quantize-append + attention
    # ingest launch (serve/engine_kernels.engine_kernel_ingest_
    # attention; value-level lax.cond dispatch, so one-trace-per-rung
    # holds); "off" = the composed rope -> scatter -> cascade tier.
    # Ignored under the reference backend (the oracle tier stays
    # composed by contract).  from_knobs resolves absent entries via
    # the costmodel.predict_prefill_ingest_win chooser.
    fused_ingest: str = "off"
    # tiered-KV statics (serve/kv_tier.py): the engine's ROLE in a
    # disaggregated pair ("prefill" keeps finished KV pages alive for
    # the kv_migrate handoff; "decode" accepts migrated continuations;
    # "unified" = the single-pool default), the host-RAM offload tier
    # (engine.kv_offload: "host" attaches a HostKVStore of
    # engine.host_gib GiB), and the preemption policy
    # (engine.spill_policy: "recompute" = PR 11's fold + re-prefill,
    # "spill" = always offload, "auto" = cost-model comparison of
    # restore bytes vs recompute FLOPs per victim)
    role: str = "unified"
    kv_offload: str = "off"         # engine.kv_offload: off | host
    spill_policy: str = "recompute"  # engine.spill_policy
    host_gib: float = 4.0           # engine.host_gib (host tier GiB)

    @staticmethod
    def from_knobs(model_cfg, *, num_pages: int, max_seq_tokens: int = 256,
                   **over) -> "EngineConfig":
        """Resolve the tunable statics through ``autotuner.KNOWN_KNOBS``
        (engine.block_size / engine.prefill_budget_tokens /
        engine.max_batch / engine.attention_backend, plus the tiered-KV
        knobs engine.kv_offload / engine.spill_policy /
        engine.host_gib), shape-keyed on the model geometry so each
        chip generation ships its own scheduler shape ladder,
        attention tier, and KV tiering."""
        from flashinfer_tpu.autotuner import AutoTuner

        t = AutoTuner.get()
        key = (model_cfg.hidden_size, model_cfg.num_qo_heads,
               model_cfg.num_kv_heads, model_cfg.head_dim)
        knobs = dict(
            page_size=int(t.lookup("engine.block_size", key, default=16)),
            prefill_budget_tokens=int(t.lookup(
                "engine.prefill_budget_tokens", key, default=64)),
            max_batch=int(t.lookup("engine.max_batch", key, default=8)),
            attention_backend=str(t.lookup(
                "engine.attention_backend", key, default="reference")),
            kv_offload=str(t.lookup(
                "engine.kv_offload", key, default="off")),
            spill_policy=str(t.lookup(
                "engine.spill_policy", key, default="recompute")),
            host_gib=float(t.lookup("engine.host_gib", key, default=4)),
        )
        knobs.update(over)
        cfg = EngineConfig(num_pages=num_pages,
                           max_seq_tokens=max_seq_tokens, **knobs)
        if "fused_ingest" not in over \
                and cfg.attention_backend == "kernel":
            # shape-key the ingest knob the way the prefill wrapper
            # does (batch, tq_pad, H, Hkv, D, page_size) at the
            # ladder's top rung — the from-scratch prefill step the
            # fusion serves; resolve_prefill_ingest is the shared
            # knob -> cost-model-chooser resolution point
            from flashinfer_tpu.prefill import resolve_prefill_ingest

            top = max(cfg.rungs())
            kv_bytes = jnp.dtype(
                cfg.kv_dtype if cfg.kv_dtype is not None
                else model_cfg.dtype).itemsize
            use = resolve_prefill_ingest(
                (cfg.max_batch, top, model_cfg.num_qo_heads,
                 model_cfg.num_kv_heads, model_cfg.head_dim,
                 cfg.page_size),
                total_q=top, total_kv=top,
                num_qo_heads=model_cfg.num_qo_heads,
                num_kv_heads=model_cfg.num_kv_heads,
                head_dim=model_cfg.head_dim,
                cache_bytes=int(kv_bytes))
            cfg = dataclasses.replace(
                cfg, fused_ingest="on" if use else "off")
        return cfg

    def pages_per_req(self) -> int:
        return -(-self.max_seq_tokens // self.page_size)

    def rungs(self) -> Tuple[int, ...]:
        """The shape ladder: power-of-two token-axis sizes from the
        decode floor (max_batch) up to the full mixed budget.  Each
        rung is one trace — the ladder is deliberately small (<= 8
        rungs fits the 9-trace budget with room for a warmup)."""
        if self.ladder:
            return tuple(sorted(set(int(r) for r in self.ladder)))
        lo = 1
        while lo < self.max_batch:
            lo *= 2
        hi = lo
        top = self.max_batch + self.prefill_budget_tokens
        rungs = [lo]
        while hi < top:
            hi *= 2
            rungs.append(hi)
        return tuple(rungs[:8])


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------


class ServingEngine:
    """Continuous-batching engine over one jitted donated step body.

    >>> eng = ServingEngine(cfg, params, EngineConfig(num_pages=65))
    >>> eng.submit(EngineRequest("r0", prompt, max_new_tokens=8))
    >>> results = eng.run()          # {rid: [token, ...]}

    One ``jax.jit`` body serves every step; the per-step schedule rides
    as rung-padded ARGUMENTS (values change per step, shapes only per
    rung), and the KV caches are donated back into the engine state.
    ``num_traces`` counts compiles: steady state equals the number of
    distinct rungs exercised, and any trace beyond a rung's first is
    counted in ``serve.step_retraces`` + cause-attributed through the
    flight recorder (the PR 10 machinery)."""

    _STATE_NAMES = ("params", "flat_tokens", "positions", "tok_req",
                    "token_page", "token_slot", "page_table", "grp_pages",
                    "tok_grp", "split", "last_rows", "sample_seeds",
                    "kplans", "caches")

    def __init__(self, model_cfg, params, config: EngineConfig):
        if config.attention_backend not in ("reference", "kernel"):
            raise ValueError(
                f"attention_backend must be 'reference' or 'kernel', "
                f"got {config.attention_backend!r}")
        if config.role not in ("prefill", "decode", "unified"):
            raise ValueError(f"role must be prefill|decode|unified, "
                             f"got {config.role!r}")
        if config.fused_ingest not in ("off", "on"):
            raise ValueError(f"fused_ingest must be 'off' or 'on', "
                             f"got {config.fused_ingest!r}")
        if config.kv_offload not in ("off", "host"):
            raise ValueError(f"kv_offload must be off|host, "
                             f"got {config.kv_offload!r}")
        if config.spill_policy not in ("recompute", "spill", "auto"):
            raise ValueError(
                f"spill_policy must be recompute|spill|auto, "
                f"got {config.spill_policy!r}")
        if config.spill_policy != "recompute" \
                and config.kv_offload == "off":
            raise ValueError(
                f"spill_policy {config.spill_policy!r} needs a host "
                "tier — set kv_offload='host' (engine.kv_offload)")
        self.cfg = model_cfg
        self.params = params
        self.config = config
        self.pool = BlockPool(config.num_pages, config.page_size)
        self.prefix_cache = PrefixCache(self.pool)
        self._waiting: List[EngineRequest] = []
        self._running: List[EngineRequest] = []
        self._finished: Dict[str, EngineRequest] = {}
        self._arrivals = 0
        self._slots: List[Optional[EngineRequest]] = \
            [None] * config.max_batch
        self._traces = 0
        self._rung_traced: Dict[int, int] = {}  # rung tokens -> traces
        self._last_sig: Dict[int, object] = {}
        self._steps = 0
        self.idle_steps = 0  # empty-schedule polls (no dispatch)
        self.flops_avoided = 0.0  # prefill FLOPs skipped via prefix hits
        # tiered KV (serve/kv_tier.py): the host-RAM store below the
        # block pool, the in-flight migration staging of a decode-role
        # pool, and per-engine movement totals (what the serving_disagg
        # bench rows read without the metrics gate)
        self.host_store = None
        if config.kv_offload == "host":
            from flashinfer_tpu.serve.kv_tier import HostKVStore

            self.host_store = HostKVStore(
                int(config.host_gib * (1 << 30)))
        self._migrated: Dict[str, object] = {}  # rid -> HostKVEntry
        self.kv_tier_stats = {
            "spills": 0, "restores": 0, "recomputes": 0,
            "migrations": 0, "spill_bytes": 0.0, "restore_bytes": 0.0,
            "migrate_bytes": 0.0,
        }
        # aggregate work accounting for roofline stamping
        # (costmodel.engine_step over these totals == the run's cost):
        self.tokens_total = 0     # scheduled tokens (padding excluded)
        self.sampled_total = 0    # lm_head + sampling lanes paid
        self.kv_pairs_total = 0.0  # attended (q, kv) pairs (FLOPs term)
        self.kv_rows_total = 0.0   # KV rows streamed, shared-prefix
        #                            group gathers counted ONCE (bytes)
        kv_dtype = (jnp.dtype(config.kv_dtype)
                    if config.kv_dtype is not None
                    else jnp.dtype(model_cfg.dtype))
        self.kv_dtype = kv_dtype
        self._int8_kv = kv_dtype == jnp.int8
        ps, ppr = config.page_size, config.pages_per_req()
        self.caches = [
            (jnp.zeros((config.num_pages, model_cfg.num_kv_heads, ps,
                        model_cfg.head_dim), kv_dtype),
             jnp.zeros((config.num_pages, model_cfg.num_kv_heads, ps,
                        model_cfg.head_dim), kv_dtype))
            for _ in range(model_cfg.num_layers)
        ]
        self._ppr = ppr
        self._kernel_backend = config.attention_backend == "kernel"
        self._geom = None
        # launched-vs-effective unit accounting (kernel tier): what the
        # padded work-unit grids actually execute vs the attended pairs
        # — costmodel.engine_step prices the bench A/B from these
        self.unit_stats = {
            "prefill_units": 0, "prefill_units_launched": 0,
            "prefill_cells_launched": 0.0, "prefill_cells_valid": 0.0,
            "decode_pages_real": 0, "decode_pages_launched": 0,
            "kv_pairs_launched": 0.0, "kv_rows_launched": 0.0,
        }
        # fused ingest is a kernel-tier concept: the reference backend
        # is the composed oracle by contract, so "on" there is inert
        self._ingest = (self._kernel_backend
                        and config.fused_ingest == "on")
        if self._kernel_backend:
            from flashinfer_tpu.serve.engine_kernels import EngineKernelGeom

            self._geom = EngineKernelGeom.build(
                page_size=ps, pages_per_req=ppr,
                max_batch=config.max_batch,
                max_rung=max(config.rungs()),
                num_kv_heads=model_cfg.num_kv_heads,
                head_dim=model_cfg.head_dim,
                kv_itemsize=kv_dtype.itemsize,
                num_splits=config.decode_num_splits,
                fused_ingest=self._ingest,
            )
        self._build_step()

    # -- public surface ---------------------------------------------------

    @property
    def num_traces(self) -> int:
        return self._traces

    @property
    def steps(self) -> int:
        return self._steps

    def submit(self, req: EngineRequest) -> None:
        from flashinfer_tpu import obs

        if not req.prompt:
            raise ValueError("empty prompt")
        if req.total_len() + req.max_new_tokens > self.config.max_seq_tokens:
            raise ValueError(
                f"request {req.rid}: prompt+generation "
                f"{len(req.prompt) + req.max_new_tokens} exceeds "
                f"max_seq_tokens {self.config.max_seq_tokens}")
        pages = -(-(len(req.prompt) + req.max_new_tokens)
                  // self.config.page_size)
        usable = self.config.num_pages - 1  # page 0 is scratch
        if pages > usable:
            # reject HERE, not at admission: an unadmittable request
            # would otherwise preempt every lower-priority running
            # request (discarding their KV) before discovering it can
            # never fit, then abort the whole run
            raise ValueError(
                f"request {req.rid}: needs {pages} pages but the pool "
                f"has {usable} usable — grow num_pages or shrink the "
                "request")
        req.arrival = self._arrivals
        self._arrivals += 1
        req.enqueue_t = time.perf_counter()
        if req.slo_ttft_s is not None:
            req.deadline = req.enqueue_t + req.slo_ttft_s
        req.state = _WAITING
        self._waiting.append(req)
        obs.request_begin(req.rid)
        obs.counter_inc("engine.requests")

    def has_work(self) -> bool:
        return bool(self._waiting or self._running)

    def run(self, max_steps: int = 100000) -> Dict[str, List[int]]:
        """Drive steps until every submitted request finished (or the
        step cap trips — a scheduler deadlock guard, not a limiter)."""
        while self.has_work():
            if self._steps >= max_steps:
                raise RuntimeError(
                    f"engine exceeded {max_steps} steps with work left "
                    f"({len(self._waiting)} waiting, "
                    f"{len(self._running)} running)")
            self.step()
        return {rid: list(r.out_tokens)
                for rid, r in self._finished.items()}

    def aggregate_cost(self):
        """The whole run's work as one ``costmodel.engine_step`` Cost
        over the accumulated totals (the formula is linear in each
        term) — what bench.py's ``serving_engine`` phase stamps its
        rows with, shared-prefix KV dedup included via kv_rows.

        On the KERNEL backend the launched terms come from the REAL
        work-unit stats (padded unit grids, scratch-page chunk DMAs and
        all — ``ServingEngine.unit_stats``) while the effective terms
        stay the exact attended-pair accounting, so the stamped
        ``flops`` vs ``flops_effective`` gap is the tier's true
        padding/pruning waste, not the dense-window fiction the
        reference tier pays."""
        from flashinfer_tpu.obs import costmodel

        launched = {}
        if self._kernel_backend:
            launched = dict(
                kv_pairs_launched=self.unit_stats["kv_pairs_launched"],
                kv_rows_launched=self.unit_stats["kv_rows_launched"])
        return costmodel.engine_step(
            num_tokens=self.tokens_total, batch=max(self.sampled_total, 1),
            layers=self.cfg.num_layers, hidden=self.cfg.hidden_size,
            inter=self.cfg.intermediate_size, hq=self.cfg.num_qo_heads,
            hkv=self.cfg.num_kv_heads, hd=self.cfg.head_dim,
            vocab=self.cfg.vocab_size, kv_tokens=self.kv_pairs_total,
            kv_rows=self.kv_rows_total,
            kv_bytes=1 if self._int8_kv else 2, **launched)

    # -- admission + scheduling -------------------------------------------

    def _order_key(self, r: EngineRequest):
        return (r.priority, r.deadline, r.arrival)

    def _pages_needed(self, r: EngineRequest) -> int:
        return -(-(r.total_len() + self._remaining_new(r))
                 // self.config.page_size)

    def _remaining_new(self, r: EngineRequest) -> int:
        return r.max_new_tokens - len(r.out_tokens)

    def _release(self, r: EngineRequest) -> None:
        """Drop every pool reference the request holds and vacate its
        batch slot (finish and preemption share this path)."""
        if r.pages:
            self.pool.decref(r.pages)
            r.pages = []
        if r.slot >= 0:
            self._slots[r.slot] = None
            r.slot = -1

    def _fold_and_requeue(self, r: EngineRequest) -> None:
        """Release a running request's device state and requeue it for
        resume — THE one site enforcing the unconditional-fold
        invariant: generated tokens fold into the resume prompt on
        EVERY vacate-the-device path (preemption, spill, idle
        offload), because a spilled host entry can be LRU-evicted
        before resume and the fallback recompute must see the full
        sequence.  A path that skipped the fold would silently drop
        every mid-sequence generated token on that fallback (the
        tiered-KV regression tests/test_kv_tier.py pins: spill-restore
        == recompute-on-resume == never-preempted, bitwise)."""
        self._release(r)
        r.prompt = r.prompt + r.out_tokens[r.folded_out:]
        r.folded_out = len(r.out_tokens)
        r.kv_len = 0
        r.hit_tokens = 0
        r.inserted_pages = 0
        r.vacated = True
        r.state = _WAITING
        self._waiting.append(r)

    def _preempt(self, victim: EngineRequest) -> None:
        """Preemption-by-eviction: spill-on-preempt when the tier +
        policy allow (serve/kv_tier.py; resume then RESTORES the exact
        KV bits), recompute-on-resume otherwise — either way the
        victim folds + requeues through :meth:`_fold_and_requeue`.
        Deterministic per-token sampling seeds make the continuation
        reproducible under every policy."""
        from flashinfer_tpu import obs

        self._running.remove(victim)
        if self.host_store is not None \
                and self.config.spill_policy != "recompute":
            from flashinfer_tpu.serve import kv_tier

            if self.config.spill_policy == "spill" \
                    or kv_tier.spill_beats_recompute(self, victim):
                # copy to host BEFORE the release frees the pages
                kv_tier.spill_request(self, victim)
        self._fold_and_requeue(victim)
        victim.preemptions += 1
        obs.counter_inc("engine.preemptions")

    def _try_admit_one(self, r: EngineRequest) -> bool:
        from flashinfer_tpu import obs

        cfg = self.config
        if r.slot < 0:
            free_slots = [i for i, s in enumerate(self._slots) if s is None]
            if not free_slots:
                return False
            slot = free_slots[0]
        else:  # pragma: no cover - slots are assigned here only
            slot = r.slot
        P = len(r.prompt)
        # the shareable span: full pages of the prompt, capped so the
        # LAST prompt token always prefills (its logits seed token 0).
        # Frozen on FIRST admission: a resume prompt is longer (the
        # generated tokens folded in), and recomputing the boundary
        # would change the cascade decomposition — correct numerically,
        # but no longer BIT-identical to the never-preempted run
        if r.split < 0:
            r.split = ((P - 1) // cfg.page_size) * cfg.page_size
        split = r.split
        # a staged restore source (host-tier spill, or an in-flight
        # kv_migrate handoff on a decode-role pool) supersedes the
        # prefix cache: the entry already holds the request's OWN KV
        # bits up to its spilled kv_len — at least the shareable span
        from flashinfer_tpu.serve import kv_tier

        staged = kv_tier.staged_entry(self, r.rid)
        hit_pages: List[int] = []
        hit_tokens = 0
        if staged is None and cfg.enable_prefix_cache:
            hit_pages, hit_tokens = self.prefix_cache.lookup(
                r.prompt, split // cfg.page_size)
        # adopt the shared run BEFORE any eviction: the hit pages must
        # not be evictable while we make room (refcount >= 2 fences
        # them out of evict()'s cache-only candidate set — otherwise an
        # eviction pass could free a hit page and alloc() could hand it
        # back as a "fresh" page, aliasing the request's own table)
        self.pool.incref(hit_pages)
        need = self._pages_needed(r) - len(hit_pages)
        if need > self.pool.free_pages:
            self.prefix_cache.evict(need - self.pool.free_pages)
        if need > self.pool.free_pages:
            self.pool.decref(hit_pages)  # admission failed: un-adopt
            return False
        fresh = self.pool.alloc(need)
        assert fresh is not None
        r.pages = hit_pages + fresh
        r.slot = slot
        self._slots[slot] = r
        if staged is not None:
            # restore path: copy the staged KV bits into the fresh
            # pages and resume from the spilled kv_len — neither a
            # prefix hit nor a miss (no prefill happens for the span)
            kv_tier.restore_request(self, r)
            r.hit_tokens = 0
            r.inserted_pages = 0
        else:
            if r.vacated:
                # resume WITHOUT a restore source: PR 11's
                # recompute-on-resume (spill disabled, the policy
                # chose recompute, or the host store evicted the
                # entry) — counted so a spill-policy bench can assert
                # the tier absorbed every resume.  The flag (set by
                # _fold_and_requeue) covers zero-token mid-prefill
                # vacates too, where preemptions/folded_out can't
                self.kv_tier_stats["recomputes"] += 1
                obs.counter_inc("engine.kv_tier.recomputes")
            r.kv_len = hit_tokens
            r.hit_tokens = hit_tokens
            r.inserted_pages = len(hit_pages)
            obs.counter_inc("engine.prefix_hit_tokens", hit_tokens)
            obs.counter_inc("engine.prefix_miss_tokens", P - hit_tokens)
        r.vacated = False
        r.state = _RUNNING
        self._running.append(r)
        if hit_tokens:
            self.flops_avoided += self._prefill_cost_flops(r, hit_tokens)
        return True

    def _admit(self) -> None:
        """Admit waiting requests in (priority, deadline, arrival)
        order.  A request that cannot fit may PREEMPT strictly
        lower-priority running requests (recompute-on-resume) — at most
        down to the point where preemption stops helping."""
        self._waiting.sort(key=self._order_key)
        admitted: List[EngineRequest] = []
        for r in list(self._waiting):
            if self._try_admit_one(r):
                admitted.append(r)
                continue
            # eviction alone was not enough: preempt strictly-worse
            # running requests while that can still free the shortfall
            while True:
                victims = [v for v in self._running
                           if v.priority > r.priority]
                if not victims:
                    break
                victims.sort(key=self._order_key)
                self._preempt(victims[-1])
                if self._try_admit_one(r):
                    admitted.append(r)
                    break
            if r.state != _RUNNING:
                break  # head-of-line blocking: keep FIFO fairness
        for r in admitted:
            self._waiting.remove(r)

    def _prefill_cost_flops(self, r: EngineRequest, tokens: int) -> float:
        """Prefill FLOPs the prefix hit avoided.

        Reference backend: the analytic ``engine_step`` formula over
        the skipped span (its dense attention IS the formula).  Kernel
        backend: the attention term comes from the REAL planner —
        ``build_prefill_work_units`` is run for the skipped span and
        its launched MXU-cell stats price the work the kernel tier
        would actually have executed (bench.py's
        ``prefill_flops_avoided`` is therefore unit-stats-derived, not
        a dense-window estimate)."""
        from flashinfer_tpu.obs import costmodel

        kv_pairs = tokens * (tokens + 1) // 2
        launched = {}
        if self._kernel_backend:
            from flashinfer_tpu.ops.paged_prefill import (
                build_prefill_work_units)

            g = self._geom
            pages = np.asarray(
                r.pages[:-(-tokens // g.page_size)], np.int64)
            plan = build_prefill_work_units(
                np.asarray([0, tokens], np.int64),
                np.asarray([0, len(pages)], np.int64), pages,
                np.asarray([tokens], np.int64),
                g.block_q, g.prefill_ppc, g.page_size,
                causal=True, window_left=-1, pack_tiles=True, prune=True)
            # REAL units only (plan["stats"]["units"]): the skipped
            # span's work is priced at what its units would execute,
            # not at the pow2 padding of a standalone plan — padding
            # waste belongs to the steps that actually launch it
            real_units = plan["stats"]["units"]
            chunk = g.prefill_ppc * g.page_size
            launched = dict(
                kv_pairs_launched=float(real_units * g.block_q * chunk),
                kv_rows_launched=float(real_units * chunk))
        cost = costmodel.engine_step(
            num_tokens=tokens, batch=1, layers=self.cfg.num_layers,
            hidden=self.cfg.hidden_size, inter=self.cfg.intermediate_size,
            hq=self.cfg.num_qo_heads, hkv=self.cfg.num_kv_heads,
            hd=self.cfg.head_dim, vocab=self.cfg.vocab_size,
            kv_tokens=kv_pairs,
            kv_bytes=1 if self._int8_kv else 2, **launched,
        )
        return cost.flops

    def _predict_step_seconds(self, num_tokens: int, kv_tokens: int,
                              batch: int) -> float:
        from flashinfer_tpu.obs import costmodel, hwspec

        spec = hwspec.current_spec()
        cost = costmodel.engine_step(
            num_tokens=num_tokens, batch=max(batch, 1),
            layers=self.cfg.num_layers, hidden=self.cfg.hidden_size,
            inter=self.cfg.intermediate_size, hq=self.cfg.num_qo_heads,
            hkv=self.cfg.num_kv_heads, hd=self.cfg.head_dim,
            vocab=self.cfg.vocab_size, kv_tokens=kv_tokens,
            kv_bytes=1 if self._int8_kv else 2,
        )
        return costmodel.predict_step_seconds(
            cost, hbm_tbps=spec.hbm_tbps,
            peak_tflops=spec.peak_tflops(str(self.kv_dtype)),
            ici_gbps=0.0)

    def _schedule(self) -> List[Tuple[EngineRequest, int]]:
        """Pack this step: every decoding request advances 1 token;
        prefilling requests get chunks under the token budget, with the
        marginal chunk PRICED by ``predict_step_seconds`` against the
        SLO step-latency cap (``slo_step_seconds``) instead of a
        heuristic cutoff."""
        cfg = self.config
        sched: List[Tuple[EngineRequest, int]] = []
        total = 0
        kv_tokens = 0
        decoding = [r for r in self._running
                    if r.kv_len >= len(r.prompt)]
        prefilling = [r for r in self._running
                      if r.kv_len < len(r.prompt)]
        for r in decoding:
            sched.append((r, 1))
            total += 1
            kv_tokens += r.kv_len + 1
        rung_cap = max(self.config.rungs())
        budget = cfg.prefill_budget_tokens
        prefilling.sort(key=self._order_key)
        for r in prefilling:
            room = min(budget, rung_cap - total)
            if room <= 0:
                break
            chunk = min(len(r.prompt) - r.kv_len, room)
            # cost-model-priced admission: shrink the chunk until the
            # predicted step latency clears the SLO cap (never below 0;
            # decode lanes always run)
            if cfg.slo_step_seconds is not None:
                while chunk > 0:
                    attended = chunk * r.kv_len + chunk * (chunk + 1) // 2
                    pred = self._predict_step_seconds(
                        total + chunk, kv_tokens + attended,
                        len(self._running))
                    if pred <= cfg.slo_step_seconds:
                        break
                    chunk //= 2
            if chunk <= 0:
                continue
            sched.append((r, chunk))
            total += chunk
            budget -= chunk
            kv_tokens += chunk * r.kv_len + chunk * (chunk + 1) // 2
        if not sched and prefilling:
            # forced-progress floor: an SLO cap tighter than the
            # smallest possible step must not starve prefill forever —
            # one token of the most urgent request always runs
            sched.append((prefilling[0], 1))
        return sched

    # -- the jitted step body ---------------------------------------------

    def _build_step(self):
        cfg, mcfg = self.config, self.cfg
        ps, ppr = cfg.page_size, self._ppr
        K = ppr * ps          # per-request KV window rows
        int8_kv = self._int8_kv
        sm_scale = (1.0 / float(mcfg.head_dim) ** 0.5
                    * (mcfg.kv_k_scale if int8_kv else 1.0))
        sampling = cfg.sampling
        base_key = jax.random.PRNGKey(cfg.seed)
        engine_self = self

        def _window(c, table):
            # [pages, Hkv, PS, D] -> [rows, K, Hkv, D]: the row offset
            # of KV position j is ALWAYS j (position-determined — the
            # bitwise-reproducibility contract in the module doc)
            w = c[table]  # [rows, ppr, Hkv, PS, D]
            n = w.shape[0]
            return jnp.swapaxes(w, 2, 3).reshape(
                n, K, mcfg.num_kv_heads, mcfg.head_dim)

        def _attend(q, kw, vw, lo, hi):
            # per-token windowed attention: q [T, H, D], kw/vw
            # [T, K, Hkv, D], valid rows j in [lo, hi] per token.
            # Masked lanes contribute exact zeros, so window CONTENT
            # beyond the mask (stale pages, scratch) never perturbs a
            # bit.  Returns (out f32 [T, H, D], lse f32 [T, H]).
            T = q.shape[0]
            G = mcfg.num_qo_heads // mcfg.num_kv_heads
            qg = q.reshape(T, mcfg.num_kv_heads, G,
                           mcfg.head_dim).astype(jnp.float32)
            kf = kw.astype(jnp.float32)
            vf = vw.astype(jnp.float32)
            s = jnp.einsum("tngd,tknd->tngk", qg, kf) * sm_scale
            j = jnp.arange(kw.shape[1])
            valid = (j[None, :] >= lo[:, None]) & (j[None, :] <= hi[:, None])
            vm = valid[:, None, None, :]
            s = jnp.where(vm, s, _NEG_INF)
            m = jnp.max(s, axis=-1, keepdims=True)
            p = jnp.where(vm, jnp.exp(s - m), 0.0)
            l = jnp.sum(p, axis=-1)
            out = jnp.einsum(
                "tngk,tknd->tngd", p / jnp.where(l > 0, l, 1.0)[..., None],
                vf)
            lse = jnp.where(l > 0, m[..., 0] + jnp.log(l), _NEG_INF)
            H = mcfg.num_qo_heads
            return out.reshape(T, H, mcfg.head_dim), lse.reshape(T, H)

        kernel_backend = self._kernel_backend
        geom = self._geom
        use_ingest = self._ingest
        sm_plain = 1.0 / float(mcfg.head_dim) ** 0.5

        def _body(params, flat_tokens, positions, tok_req, token_page,
                  token_slot, page_table, grp_pages, tok_grp, split,
                  last_rows, sample_seeds, kplans, caches):
            from flashinfer_tpu.activation import silu_and_mul
            from flashinfer_tpu.cascade import compose_cascade_levels
            from flashinfer_tpu.models.llama import _mm, _pre_quant
            from flashinfer_tpu.norm import rmsnorm
            from flashinfer_tpu.rope import apply_rope_pos_ids

            engine_self._traces += 1
            T = flat_tokens.shape[0]
            x = params["embed"][flat_tokens].astype(mcfg.dtype)
            new_caches = []
            for li, layer in enumerate(params["layers"]):
                h = rmsnorm(x, layer["input_norm"], mcfg.rms_eps)
                pre = _pre_quant(h, layer)
                q = _mm(h, layer, "q_proj", pre).reshape(
                    T, mcfg.num_qo_heads, mcfg.head_dim)
                k = _mm(h, layer, "k_proj", pre).reshape(
                    T, mcfg.num_kv_heads, mcfg.head_dim)
                v = _mm(h, layer, "v_proj", pre).reshape(
                    T, mcfg.num_kv_heads, mcfg.head_dim)
                kc, vc = caches[li]

                def _composed_attn(q, k, v, kc, vc):
                    """The ONE composed sequence — rope -> quantize ->
                    scatter-append -> attend -> v-scale epilogue.  The
                    non-ingest path and the ingest cond's false branch
                    run exactly this function, so a fix to the
                    quantize/scale/scatter logic can never reach one
                    fused_ingest setting and miss the other."""
                    q, k = apply_rope_pos_ids(q, k, positions,
                                              rope_theta=mcfg.rope_theta)
                    if int8_kv:
                        from flashinfer_tpu.quantization import (
                            quantize_symmetric_int8)

                        k_w = quantize_symmetric_int8(k, mcfg.kv_k_scale)
                        v_w = quantize_symmetric_int8(v, mcfg.kv_v_scale)
                    else:
                        k_w = k.astype(kc.dtype)
                        v_w = v.astype(vc.dtype)
                    # pad lanes scatter into the scratch page (pool
                    # page 0)
                    kc = kc.at[token_page, :, token_slot, :].set(k_w)
                    vc = vc.at[token_page, :, token_slot, :].set(v_w)
                    if kernel_backend:
                        # the graduated path: the same two-level
                        # cascade, but level 1 rides the work-unit
                        # prefill mainloop + split-KV decode units and
                        # level 0 the group-masked prefill plan — all
                        # composed by the same merge fold
                        # (serve/engine_kernels.py)
                        from flashinfer_tpu.serve.engine_kernels import (
                            engine_kernel_attention)

                        o = engine_kernel_attention(
                            q, kc, vc, kplans, geom=geom,
                            sm_scale=sm_scale)
                    else:
                        # the dense XLA oracle tier (interpret-mode
                        # reference): position-determined windows
                        # attended through masked lanes — O(T x K) but
                        # bitwise-provable vs the no-sharing oracle on
                        # CPU
                        # level 1: the request's own window, rows
                        # [split, pos]
                        k1 = _window(kc, page_table)[tok_req]
                        v1 = _window(vc, page_table)[tok_req]
                        o1, lse1 = _attend(q, k1, v1, split, positions)
                        # level 0: the SHARED prefix run, gathered once
                        # per group slot, rows [0, min(split, pos + 1))
                        # — causal by position so a leader mid-prefill
                        # never sees ahead
                        k0 = _window(kc, grp_pages)[tok_grp]
                        v0 = _window(vc, grp_pages)[tok_grp]
                        hi0 = jnp.minimum(split - 1, positions)
                        o0, lse0 = _attend(q, k0, v0,
                                           jnp.zeros_like(split), hi0)
                        # cascade composition (reference cascade.cuh
                        # merge): empty levels pass through exactly via
                        # the lse guard
                        o, _ = compose_cascade_levels([(o0, lse0),
                                                       (o1, lse1)])
                    if int8_kv:
                        o = o * mcfg.kv_v_scale
                    return o.astype(mcfg.dtype), kc, vc

                if use_ingest:
                    # ISSUE 14: per-step VALUE dispatch between the
                    # fused-ingest launch and the composed cascade —
                    # lax.cond, so both branches live in the SAME
                    # per-rung program and the one-trace-per-rung
                    # budget is untouched.  ingest_on certifies a
                    # from-scratch prefill schedule (level 0 + decode
                    # structurally empty) at plan-build time.
                    from flashinfer_tpu.serve.engine_kernels import (
                        engine_kernel_ingest_attention)

                    def _ingest_branch(q, k, v, kc, vc):
                        return engine_kernel_ingest_attention(
                            q, k, v, kc, vc, kplans, geom=geom,
                            sm_scale=sm_plain,
                            rope_theta=mcfg.rope_theta,
                            kv_quant="int8" if int8_kv else "none",
                            k_scale=mcfg.kv_k_scale if int8_kv else 1.0,
                            v_scale=mcfg.kv_v_scale if int8_kv else 1.0)

                    attn, kc, vc = jax.lax.cond(
                        kplans["ingest_on"] > 0, _ingest_branch,
                        _composed_attn, q, k, v, kc, vc)
                else:
                    attn, kc, vc = _composed_attn(q, k, v, kc, vc)
                new_caches.append((kc, vc))
                x = x + _mm(attn.reshape(T, -1), layer,
                            "o_proj").astype(mcfg.dtype)
                h2 = rmsnorm(x, layer["post_norm"], mcfg.rms_eps)
                pre2 = _pre_quant(h2, layer, "gate_proj")
                mlp = jnp.concatenate(
                    [_mm(h2, layer, "gate_proj", pre2),
                     _mm(h2, layer, "up_proj", pre2)], -1)
                x = x + _mm(silu_and_mul(mlp), layer,
                            "down_proj").astype(mcfg.dtype)
            x_last = x[last_rows]
            xf = rmsnorm(x_last, params["final_norm"], mcfg.rms_eps)
            logits = _mm(xf, params, "lm_head").astype(jnp.float32)
            # per-lane deterministic sampling: the key depends only on
            # (request arrival id, token index), never on scheduling —
            # the same request samples the same stream under any
            # packing, preemption, or sharing mode
            t = jnp.maximum(jnp.asarray(sampling.temperature, jnp.float32),
                            1e-6)
            probs = jax.nn.softmax((logits / t).astype(jnp.float32), -1)
            if sampling.top_k:
                from flashinfer_tpu import sampling as S

                probs = S.top_k_renorm_probs(probs, sampling.top_k)
            if sampling.top_p < 1.0:
                from flashinfer_tpu import sampling as S

                probs = S.top_p_renorm_probs(probs, sampling.top_p)
            keys = jax.vmap(lambda s: jax.random.fold_in(base_key, s))(
                sample_seeds)
            tokens = jax.vmap(
                lambda p, kk: jax.random.categorical(
                    kk, jnp.log(jnp.maximum(p, 1e-30))))(probs, keys)
            return tokens.astype(jnp.int32), new_caches

        donate = (13,) if cfg.donate else ()
        self._step = jax.jit(_body, donate_argnums=donate)

    # -- step construction + execution ------------------------------------

    def _sample_seed(self, r: EngineRequest, token_index: int) -> int:
        return (r.arrival * 131071 + token_index) & 0x7FFFFFFF

    def _rung_for(self, tokens: int) -> int:
        for rung in self.config.rungs():
            if tokens <= rung:
                return rung
        raise RuntimeError(
            f"scheduled {tokens} tokens > largest rung "
            f"{max(self.config.rungs())} — scheduler bug")

    @flashinfer_api(name="engine.step")
    def step(self) -> dict:
        """One engine step: admit, schedule, run the compiled rung,
        scatter results.  Returns step facts (rung, tokens scheduled,
        requests sampled/finished)."""
        from flashinfer_tpu import obs

        tick = obs.steploop_begin("ServingEngine")
        self._admit()
        if tick is not None:
            tick.mark("admit")
        sched = self._schedule()
        if tick is not None:
            tick.mark("schedule")
        if not sched:
            if self._waiting and not self._running:
                r = min(self._waiting, key=self._order_key)
                raise RuntimeError(
                    f"request {r.rid} can never be admitted: needs "
                    f"{self._pages_needed(r)} pages, pool has "
                    f"{self.pool.num_pages - 1} (evictable cache pages "
                    "included) — grow num_pages or shrink the request")
            # explicit idle tick: nothing runnable, no dispatch — count
            # it so host-gap math and step accounting never read an
            # idle poll as device time (previously a silent return)
            self.idle_steps += 1
            obs.counter_inc("engine.idle_steps")
            if tick is not None:
                tick.commit(idle=True)
            return {"rung": 0, "tokens": 0, "sampled": 0, "finished": 0}
        cfg, mcfg = self.config, self.cfg
        ps, ppr = cfg.page_size, self._ppr
        Bpad = cfg.max_batch
        total = sum(n for _, n in sched)
        rung = self._rung_for(total)
        kv_pairs_before = self.kv_pairs_total

        flat = np.zeros(rung, np.int32)
        pos = np.zeros(rung, np.int32)
        tok_req = np.zeros(rung, np.int32)
        token_page = np.zeros(rung, np.int32)  # scratch page 0 for pads
        token_slot = np.zeros(rung, np.int32)
        split = np.zeros(rung, np.int32)
        tok_grp = np.zeros(rung, np.int32)
        page_table = np.zeros((Bpad, ppr), np.int32)
        grp_pages = np.zeros((Bpad, ppr), np.int32)
        last_rows = np.zeros(Bpad, np.int32)
        sample_seeds = np.zeros(Bpad, np.int32)
        samplers: List[EngineRequest] = []

        # group slots: one per distinct shared page-run prefix this
        # step (sharing mode: every full hit of one cached run lands in
        # ONE group, so the run's pages are gathered once — the cascade
        # HBM dedup; oracle mode degenerates to one group per request)
        groups: Dict[Tuple[int, ...], int] = {}
        for r in self._running:
            page_table[r.slot, :len(r.pages)] = r.pages

        def _grp_key(r):
            run = tuple(r.pages[:r.split // ps])
            return run or (-1 - r.slot,)

        for r, _n in sched:
            key = _grp_key(r)
            if key not in groups:
                g = len(groups)
                groups[key] = g
                run = tuple(r.pages[:r.split // ps])
                grp_pages[g, :len(run)] = run
        # same-group requests pack ADJACENT flat rows (stable sort, so
        # within-group order is the scheduler's): the kernel backend's
        # level-0 plan gathers each shared run once per contiguous
        # group span, and tokens are packing-invariant bitwise (the
        # module-doc contract), so the reference backend is unmoved
        sched.sort(key=lambda e: groups[_grp_key(e[0])])
        segs = []
        row = 0
        for r, n in sched:
            g = groups[_grp_key(r)]
            decoding = r.kv_len >= len(r.prompt)
            if self._kernel_backend:
                from flashinfer_tpu.serve.engine_kernels import SchedSeg

                segs.append(SchedSeg(
                    row0=row, n=n, pages=tuple(r.pages), split=r.split,
                    kv_after=r.kv_len + n, decoding=decoding,
                    slot=r.slot, group=g))
            seq = r.seq()
            for i in range(n):
                p = r.kv_len + i
                flat[row] = seq[p]
                pos[row] = p
                tok_req[row] = r.slot
                token_page[row] = r.pages[p // ps]
                token_slot[row] = p % ps
                split[row] = r.split
                tok_grp[row] = g
                row += 1
                # work accounting: every token attends [0, p] (pairs);
                # its level-1 rows [split, p] stream per request, its
                # level-0 rows are charged once per GROUP below
                self.kv_pairs_total += p + 1
                self.kv_rows_total += max(p + 1 - r.split, 0)
            r.kv_len += n
            if decoding or r.kv_len >= len(r.prompt):
                last_rows[r.slot] = row - 1
                sample_seeds[r.slot] = self._sample_seed(
                    r, len(r.out_tokens))
                samplers.append(r)
            if not decoding:
                obs.prefill_chunk(r.rid, n)
        # level-0 group gathers: one stream of each shared page run per
        # step regardless of how many requests ride it — the cascade
        # HBM dedup the cost model surfaces via kv_rows
        for run_key in groups:
            if run_key and run_key[0] >= 0:  # real runs, not sentinels
                self.kv_rows_total += len(run_key) * ps
        self.tokens_total += total
        self.sampled_total += len(samplers)
        if tick is not None:
            tick.mark("assemble")

        kplans: dict = {}
        if self._kernel_backend:
            from flashinfer_tpu.serve import engine_kernels as _ek

            plans = _ek.build_engine_work_units(segs, rung=rung,
                                                geom=self._geom)
            st = plans["stats"]
            us = self.unit_stats
            us["prefill_units"] += st["prefill_units"]
            us["prefill_units_launched"] += st["prefill_units_launched"]
            us["prefill_cells_launched"] += st["prefill_cells_launched"]
            us["prefill_cells_valid"] += st["prefill_cells_valid"]
            us["decode_pages_real"] += st["decode_pages_real"]
            us["decode_pages_launched"] += st["decode_pages_launched"]
            us["kv_pairs_launched"] += (st["prefill_cells_launched"]
                                        + st["decode_cells_launched"])
            us["kv_rows_launched"] += (st["prefill_rows_launched"]
                                       + st["decode_rows_launched"])
            kplans = _ek.plans_to_device(plans)
        if tick is not None:
            tick.mark("lower")

        full_args = (self.params, jnp.asarray(flat), jnp.asarray(pos),
                     jnp.asarray(tok_req), jnp.asarray(token_page),
                     jnp.asarray(token_slot), jnp.asarray(page_table),
                     jnp.asarray(grp_pages), jnp.asarray(tok_grp),
                     jnp.asarray(split), jnp.asarray(last_rows),
                     jnp.asarray(sample_seeds), kplans, self.caches)
        sig = obs.state_signature(full_args, names=self._STATE_NAMES)
        seen = self._rung_traced.get(rung, 0)
        before = self._traces
        t0 = time.perf_counter() if sig is not None else 0.0
        tokens_dev, self.caches = self._step(*full_args)
        if tick is not None:
            tick.dispatched()
        if self._traces > before:
            self._rung_traced[rung] = seen + 1
            if sig is not None:
                obs.record_span("ServingEngine.trace_and_compile",
                                "compile", t0, time.perf_counter(),
                                wrapper="ServingEngine", rung=rung,
                                trace_index=self._traces)
            if seen:
                # a rung that already compiled traced AGAIN: the
                # compile-once contract broke — count + attribute
                obs.counter_inc("serve.step_retraces",
                                wrapper="ServingEngine")
                if sig is not None:
                    obs.record_retrace(
                        "ServingEngine",
                        obs.diff_state_sigs(self._last_sig.get(rung),
                                            sig, full_args))
        if sig is not None:
            self._last_sig[rung] = sig
        tokens = np.asarray(tokens_dev)
        if tick is not None:
            # np.asarray above IS the completion probe (tokens cross to
            # host); join the predicted step time online — the drift
            # histogram ROADMAP items 1/7 wanted automated
            tick.done()
            tick.commit(
                tokens=total, rung=rung,
                predicted_s=self._predict_step_seconds(
                    total, self.kv_pairs_total - kv_pairs_before,
                    len(self._running)))

        # register freshly-completed full pages of each shareable span
        # FIRST (post-run: the page KV is materialized now, and a
        # request finishing this very step must still donate its span
        # to the cache before its own references are released)
        if cfg.enable_prefix_cache:
            for r, _ in sched:
                upto = min(r.kv_len, r.split) // ps
                if upto > r.inserted_pages:
                    self.prefix_cache.insert(r.prompt, r.pages, upto)
                    r.inserted_pages = upto
        finished = 0
        for r in samplers:
            tok = int(tokens[r.slot])
            r.out_tokens.append(tok)
            obs.decode_step(r.rid)
            if len(r.out_tokens) >= r.max_new_tokens:
                self._finish(r)
                finished += 1
        self._steps += 1
        obs.counter_inc("engine.steps")
        obs.counter_inc("engine.step_tokens", total)
        obs.gauge_set("engine.pool_pages_in_use", self.pool.used_pages)
        obs.gauge_set("engine.pool_pages_free", self.pool.free_pages)
        return {"rung": rung, "tokens": total, "sampled": len(samplers),
                "finished": finished}

    def _finish(self, r: EngineRequest) -> None:
        from flashinfer_tpu import obs

        self._running.remove(r)
        if self.config.role == "prefill":
            # disaggregated prefill pool: the finished KV pages stay
            # alive for the kv_migrate handoff (the coordinator owns
            # releasing them via kv_tier.migrate_request); only the
            # batch slot frees
            if r.slot >= 0:
                self._slots[r.slot] = None
                r.slot = -1
        else:
            self._release(r)
        if self.host_store is not None:
            self.host_store.drop(r.rid)  # a stale spill is dead weight
        r.state = _FINISHED
        self._finished[r.rid] = r
        obs.request_finish(r.rid)
        obs.counter_inc("engine.finished")

    # -- tiered-KV surface (serve/kv_tier.py) ------------------------------

    def harvest_finished(self) -> List[EngineRequest]:
        """Drain the finished set — the disaggregation coordinator's
        hook: on a prefill-role engine the drained requests still hold
        their KV pages (the caller owns releasing them, normally via
        ``kv_tier.migrate_request``)."""
        out = list(self._finished.values())
        self._finished.clear()
        return out

    def adopt_migrated(self, req: EngineRequest, entry) -> None:
        """Accept a migrated continuation (the prefill→decode
        handoff): the request queues for admission and its KV entry
        stages for the restore path — same machinery as a host-tier
        resume.  The request keeps its ORIGINAL ``arrival`` (the
        sampling-seed stream) and frozen cascade ``split``."""
        from flashinfer_tpu import obs

        if self.config.role == "prefill":
            raise ValueError("a prefill-role pool cannot adopt "
                             "migrated requests")
        if req.rid in self._migrated:
            raise ValueError(f"double migration: {req.rid!r} already "
                             "staged on this pool")
        if req.arrival < 0:
            raise ValueError("migrated request must carry its source "
                             "arrival (the sampling-seed identity)")
        total = req.total_len() + req.max_new_tokens - len(req.out_tokens)
        if total > self.config.max_seq_tokens:
            raise ValueError(
                f"migrated request {req.rid}: {total} tokens exceed "
                f"this pool's max_seq_tokens "
                f"{self.config.max_seq_tokens} (the per-request KV "
                "window bound)")
        pages = -(-total // self.config.page_size)
        if pages > self.config.num_pages - 1:
            raise ValueError(
                f"migrated request {req.rid}: needs {pages} pages but "
                f"the decode pool has {self.config.num_pages - 1} "
                "usable")
        self._migrated[req.rid] = entry
        req.enqueue_t = time.perf_counter()
        if req.slo_ttft_s is not None:
            req.deadline = req.enqueue_t + req.slo_ttft_s
        req.state = _WAITING
        self._waiting.append(req)
        obs.request_begin(req.rid)
        obs.counter_inc("engine.requests")

    def offload_idle(self, rid: str) -> None:
        """Voluntarily spill a RUNNING request's KV to the host tier
        (the idle-request path: a conversation between turns frees its
        device pages now and restores bit-exactly when it next
        schedules).  The request re-queues as waiting; admission
        pressure decides when it returns."""
        if self.host_store is None:
            raise ValueError("offload_idle needs kv_offload='host'")
        r = next((x for x in self._running if x.rid == rid), None)
        if r is None:
            raise ValueError(f"offload_idle: {rid!r} is not running")
        from flashinfer_tpu.serve import kv_tier

        if r.kv_len <= 0 or not r.pages:
            raise ValueError(
                f"offload_idle: {rid!r} has no materialized KV to "
                "spill yet (admitted but not stepped)")
        if not kv_tier.spill_request(self, r):
            raise RuntimeError(
                f"offload_idle: host store rejected {rid!r} "
                f"({-(-r.kv_len // self.config.page_size)} pages "
                "exceed its capacity — grow engine.host_gib)")
        self._running.remove(r)
        self._fold_and_requeue(r)
