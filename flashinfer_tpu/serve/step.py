"""Compile-once donated-buffer serving step (plan/run lifecycle).

The fast_decode_plan / CUDAGraph analog (SURVEY: plan/run lifecycle,
amortized host scheduling): the serving loop's whole decode step —
rmsnorm -> qkv projections -> RoPE -> **paged KV append** -> paged
decode attention (int8-KV scale folding included) -> o/mlp GEMMs ->
lm_head -> sampling — compiled ONCE into a single XLA program with
``donate_argnums`` on the KV caches, page tables, and sampling key.
XLA's input/output aliasing then updates every serving buffer in
place, and the step never retraces across tokens (pinned by the
trace-counter test): per-step host cost collapses to ONE dispatch,
the honest fix for the 1.13-1.31x e2e-vs-slope overhead tax PR 2's
``overhead_decomposition`` measured on the per-op loop.

Two step shapes:

- :class:`ServingStep` — the decode mega-step over the Llama model
  family (``models/llama.py`` pytrees, bf16 or int8 weights, bf16 or
  int8 KV caches).  Numerics are the per-op loop's exactly: the traced
  body *is* ``llama_decode_step`` plus the fused sampling epilogue, so
  fused-vs-unfused bit-parity is structural, not approximate.
- :class:`MixedServingStep` — chunked prefill + decode in ONE step
  over the holistic BatchAttention machinery (the reference's
  ``TwoStageHolisticPlan`` / persistent mixed-batch kernel shape,
  ``persistent.cuh:682``): requests with ``qo_len > 1`` advance a
  prompt chunk, requests with ``qo_len == 1`` decode — one flattened
  token axis, one launch.  plan() closes the wrapper's frozen gather
  plan arrays (``BatchPrefillWithPagedKVCacheWrapper.plan_arrays``)
  into the step.

plan() freezes ALL statics host-side — layer count, shapes, page
geometry, quantization mode, sampling config, and the attention
backend (resolved EAGERLY, never inside the trace: the L003
staticness contract) — so the jitted body reads no configuration at
trace time beyond the frozen plan.  Donated state is threaded as an
explicit tuple; after ``run()`` the previous state's buffers are
invalid (aliased into the new state), exactly like the reference's
CUDAGraph-owned workspace.

See docs/performance.md ("Compile-once serving step") for lifecycle,
donation contract, and the retrace conditions.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flashinfer_tpu.api_logging import flashinfer_api


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Frozen sampling statics of a serving step.

    Mirrors the ``LogitsPipe([Temperature(), Softmax(), TopK(),
    TopP(), Sample()])`` chain op for op (``logits_processor``): the
    fused epilogue applies exactly the stages this config enables, so
    a pipe built with the same stages and parameters samples the SAME
    token from the same key (the examples/generate.py parity assert).

    ``top_k=0`` disables the top-k stage, ``top_p>=1.0`` the top-p
    stage; temperature always applies (division by 1.0 is exact)."""

    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0


def sample_next_tokens(logits: jax.Array, key: jax.Array,
                       config: SamplingConfig) -> jax.Array:
    """The fused sampling epilogue: logits [B, V] -> tokens [B].

    Op-for-op the LogitsPipe chain (Temperature -> Softmax -> TopK ->
    TopP -> Sample) with the config's enabled stages, built from the
    same ``flashinfer_tpu.sampling`` kernels the pipe legalizes to —
    bit-parity with a matching pipe is the test contract
    (tests/test_serve_step.py)."""
    from flashinfer_tpu import sampling as S

    t = jnp.maximum(jnp.asarray(config.temperature, jnp.float32), 1e-6)
    probs = jax.nn.softmax((logits / t).astype(jnp.float32), axis=-1)
    if config.top_k:
        probs = S.top_k_renorm_probs(probs, config.top_k)
    if config.top_p < 1.0:
        probs = S.top_p_renorm_probs(probs, config.top_p)
    return S.sampling_from_probs(probs, key)


@dataclasses.dataclass(frozen=True)
class ServingStepPlan:
    """Every static the fused step closes over (the TPU analogue of
    the frozen ``DecodePlanInfo`` + CUDAGraph shape set): model
    geometry, page geometry, quantization mode, sampling config, and
    the eagerly-resolved backend flag.  A live plan never changes —
    re-planning builds a fresh jitted step (counted as a replan)."""

    cfg: object  # models.llama.LlamaConfig (frozen dataclass)
    batch_size: int
    num_pages: int
    pages_per_req: int
    page_size: int
    kv_dtype: str
    weights_int8: bool
    sampling: SamplingConfig
    use_pallas: bool
    donate: bool
    # mesh identity of a sharded plan ("" = single-device; "dp2.tp4" =
    # compiled under parallel.plan.ShardingPlan shardings) — rides onto
    # bench rows as configuration, never a measurement
    mesh_axes: str = ""


def _validate_state_geometry(plan: ServingStepPlan, caches, page_table,
                             kv_lens) -> None:
    cfg = plan.cfg
    if len(caches) != cfg.num_layers:
        raise ValueError(
            f"state has {len(caches)} layer caches; plan froze "
            f"{cfg.num_layers} layers")
    kc0 = caches[0][0]
    expect = (cfg.num_kv_heads, plan.page_size, cfg.head_dim)
    if tuple(kc0.shape[1:]) != expect or kc0.shape[0] < plan.num_pages:
        raise ValueError(
            f"KV cache shape {tuple(kc0.shape)} != planned HND "
            f"geometry (>={plan.num_pages}, {', '.join(map(str, expect))})")
    if str(kc0.dtype) != plan.kv_dtype:
        raise ValueError(
            f"KV cache dtype {kc0.dtype} != planned {plan.kv_dtype} "
            "(quantization mode is a frozen plan static)")
    if tuple(page_table.shape) != (plan.batch_size, plan.pages_per_req):
        raise ValueError(
            f"page_table shape {tuple(page_table.shape)} != planned "
            f"({plan.batch_size}, {plan.pages_per_req})")
    if kv_lens.shape != (plan.batch_size,):
        raise ValueError(
            f"kv_lens shape {kv_lens.shape} != ({plan.batch_size},)")


def _validate_logits_batch(plan: ServingStepPlan, logits) -> None:
    if logits.shape[0] != plan.batch_size:
        raise ValueError(
            f"logits batch {logits.shape[0]} != planned batch "
            f"{plan.batch_size}")


class ServingStep:
    """Compile-once fused decode step over the Llama model family.

    Lifecycle mirrors the batch wrappers (plan host-side once per
    geometry, run per token):

    >>> step = ServingStep()
    >>> step.plan(cfg, page_table=pt, kv_lens=lens,
    ...           sampling=SamplingConfig(0.8, 40, 0.95))
    >>> state = step.make_state(caches, pt, lens, prefill_logits, key)
    >>> for _ in range(n):
    ...     tokens, state = step.run(params, state)

    One jitted program serves every step: the state tuple ``(logits,
    kv_caches, page_table, kv_lens, key)`` is DONATED (KV caches, page
    table, lens, and PRNG key alias in place; re-using a consumed
    state raises jax's deleted-buffer error — thread the returned
    one).  ``num_traces`` exposes the compile count; the
    ``serve.step_retraces`` counter (obs catalog) records any trace
    beyond the first — with a stable plan there is exactly one.

    plan() can also seed its geometry straight from a planned
    ``BatchDecodeWithPagedKVCacheWrapper`` via ``decode_wrapper=``
    (the wrapper's ``plan_arrays`` export): the serving engine plans
    attention once and the fused step inherits the same frozen padded
    table."""

    # signed-argument component names for the flight recorder's trace
    # signature: a retrace-cause diff reports e.g. "logits" or
    # "caches[0][0]" instead of an opaque pytree path
    _STATE_NAMES = ("params", "logits", "caches", "page_table",
                    "kv_lens", "key")

    def __init__(self):
        self._plan: Optional[ServingStepPlan] = None
        self._step = None
        self._traces = 0
        self._last_sig = None  # obs.spans state signature of last run

    @property
    def num_traces(self) -> int:
        """How many times the live step function has traced (1 after
        the first run; still 1 after N steps — the compile-once pin)."""
        return self._traces

    @property
    def plan_statics(self) -> Optional[ServingStepPlan]:
        return self._plan

    def plan(
        self,
        cfg,  # models.llama.LlamaConfig
        *,
        page_table=None,  # [B, pages_per_req] int32
        kv_lens=None,  # [B] int32 (seeds; evolve in the donated state)
        decode_wrapper=None,  # planned BatchDecodeWithPagedKVCacheWrapper
        num_pages: Optional[int] = None,
        kv_dtype=None,  # cache dtype; default cfg.dtype
        weights_int8: Optional[bool] = None,
        sampling: SamplingConfig = SamplingConfig(),
        donate: bool = True,
        use_pallas: Optional[bool] = None,
        sharding_plan=None,  # parallel.plan.ShardingPlan
    ) -> None:
        """Freeze the step statics and build the jitted donated step.

        Backend resolution (``use_pallas``) happens HERE, eagerly —
        the traced body reads no environment (L003: the step closure
        is static).  ``decode_wrapper=`` imports the frozen attention
        plan (``plan_arrays``) instead of raw ``page_table``/
        ``kv_lens``; geometry mismatches against ``cfg`` raise.

        ``sharding_plan=`` compiles the SAME body under a mesh with
        explicit in/out shardings for every state leaf
        (``parallel.plan.llama_step_shardings``: TP weight table, dp
        batch state, dp-pages x tp-heads caches) — one sharded XLA
        program per step, donation preserved.  dp-only plans are
        tokens-bitwise with the unsharded step; tp>1 reorders the split
        f32 contractions (documented tolerance,
        tests/test_sharded_step.py)."""
        from flashinfer_tpu import obs
        from flashinfer_tpu.models.llama import llama_decode_step
        from flashinfer_tpu.utils import is_tpu

        replan = self._plan is not None
        if decode_wrapper is not None:
            arrays = decode_wrapper.plan_arrays
            if arrays["kv_layout"] != "HND":
                raise ValueError(
                    "ServingStep serves the HND paged layout only "
                    f"(wrapper planned {arrays['kv_layout']!r})")
            if (arrays["num_qo_heads"] != cfg.num_qo_heads
                    or arrays["num_kv_heads"] != cfg.num_kv_heads
                    or arrays["head_dim"] != cfg.head_dim):
                raise ValueError(
                    f"decode wrapper plan heads/dim "
                    f"({arrays['num_qo_heads']}, {arrays['num_kv_heads']}, "
                    f"{arrays['head_dim']}) != model cfg "
                    f"({cfg.num_qo_heads}, {cfg.num_kv_heads}, "
                    f"{cfg.head_dim})")
            if arrays["batch_size"] != arrays["page_table"].shape[0]:
                # the wrapper pow2-buckets its batch axis; the fused
                # step runs UNPADDED state tensors, so a padded plan
                # would crash at trace time with an opaque broadcast
                # error — fail here, with the fix in the message
                raise ValueError(
                    f"decode wrapper planned batch "
                    f"{arrays['batch_size']} padded to bucket "
                    f"{arrays['page_table'].shape[0]}; the fused step "
                    "needs a bucket-aligned batch — plan the wrapper "
                    "at a power-of-two batch (>= 8), or pass raw "
                    "page_table=/kv_lens= at the actual batch instead")
            page_table = arrays["page_table"]
            kv_lens = arrays["kv_lens"]
            page_size = arrays["page_size"]
        else:
            if page_table is None or kv_lens is None:
                raise ValueError(
                    "plan() needs page_table+kv_lens or decode_wrapper=")
            page_size = None  # derived from the cache at make_state
        page_table = jnp.asarray(page_table, jnp.int32)
        batch, pages_per_req = page_table.shape
        if use_pallas is None:
            use_pallas = is_tpu()  # resolved once, never in the trace
        kv_dtype = jnp.dtype(kv_dtype) if kv_dtype is not None \
            else jnp.dtype(cfg.dtype)
        self._plan = ServingStepPlan(
            cfg=cfg, batch_size=int(batch),
            num_pages=int(num_pages) if num_pages is not None
            else int(np.asarray(jnp.max(page_table)) + 1),
            pages_per_req=int(pages_per_req),
            page_size=int(page_size) if page_size else 0,
            kv_dtype=str(kv_dtype),
            weights_int8=bool(weights_int8) if weights_int8 is not None
            else False,
            sampling=sampling, use_pallas=bool(use_pallas),
            donate=bool(donate),
            mesh_axes=sharding_plan.mesh_axes if sharding_plan is not None
            else "",
        )
        plan = self._plan
        self._traces = 0
        step_self = self
        # the sampling chain must run REPLICATED under a mesh: this
        # jax's threefry is not partitionable, so random bits generated
        # over a sharded operand differ from the single-device stream.
        # Pinning the logits alone is not enough — GSPMD BACK-propagates
        # the embed-gather's dp sharding through the sampled tokens into
        # the RNG — so the tokens are pinned too, fencing the sampler
        # off from both sides (cost: one [B, vocab] f32 gather per step)
        sample_sharding = (sharding_plan.replicated
                           if sharding_plan is not None else None)

        def _body(params, logits, caches, page_table, kv_lens, key):
            # runs at TRACE time only: with a stable plan this counter
            # advances exactly once across the whole serving session
            step_self._traces += 1
            key, sk = jax.random.split(key)
            if sample_sharding is not None:
                logits = jax.lax.with_sharding_constraint(
                    logits, sample_sharding)
            tokens = sample_next_tokens(logits, sk, plan.sampling)
            if sample_sharding is not None:
                tokens = jax.lax.with_sharding_constraint(
                    tokens, sample_sharding)
            new_logits, new_caches = llama_decode_step(
                params, plan.cfg, tokens, kv_lens, caches, page_table,
                kv_lens, use_pallas=plan.use_pallas,
            )
            return (tokens, new_logits, new_caches, page_table,
                    kv_lens + 1, key)

        # donation: KV caches (2), page table (3), kv_lens (4), PRNG
        # key (5) alias input->output in place; params and logits stay
        # caller-owned (weights are shared across steps, logits feed
        # external parity/telemetry readers)
        donate_argnums = (2, 3, 4, 5) if donate else ()
        if sharding_plan is not None:
            from flashinfer_tpu.parallel.plan import (
                compile_step_with_plan, llama_step_shardings)

            in_sh, out_sh = llama_step_shardings(
                sharding_plan, cfg, weights_int8=self._plan.weights_int8)
            # out structure is (tokens, logits, caches, pt, lens, key);
            # llama_step_shardings' out matches positionally
            self._step = compile_step_with_plan(
                _body, sharding_plan, in_shardings=in_sh,
                out_shardings=out_sh, donate_argnums=donate_argnums)
        else:
            self._step = jax.jit(_body, donate_argnums=donate_argnums)
        self._last_sig = None  # a fresh plan resets run-state tracking
        # statics= hands the frozen plan to the flight recorder: a
        # replan whose statics moved records the retrace cause
        # (plan.retrace_cause{wrapper,key}) before the next run pays
        # it.  page_size stays OUT of the plan signature: raw-geometry
        # plans carry the 0 "derived at make_state" sentinel, so
        # signing it would diff sentinel-vs-frozen across replans of
        # identical geometry (a phantom cause); a REAL page-size move
        # is still attributed — it changes every cache's shape, which
        # the run-state signature covers
        statics = {f.name: getattr(self._plan, f.name)
                   for f in dataclasses.fields(self._plan)
                   if f.name != "page_size"}
        obs.record_plan(self, replan=replan, statics=statics)

    def make_state(self, kv_caches: List[Tuple[jax.Array, jax.Array]],
                   page_table: jax.Array, kv_lens: jax.Array,
                   logits: jax.Array, key: jax.Array):
        """Assemble (and geometry-check) the donated state tuple from
        the post-prefill serving buffers."""
        plan = self._plan
        if plan is None:
            raise RuntimeError("plan() must be called before make_state")
        page_table = jnp.asarray(page_table, jnp.int32)
        kv_lens = jnp.asarray(kv_lens, jnp.int32)
        if not plan.page_size:
            # raw-array plan: the page size is whatever the cache
            # carries; freeze it on first state assembly (the flight
            # recorder's plan signature deliberately excludes
            # page_size, so this late freeze never skews replan diffs)
            plan = dataclasses.replace(
                plan, page_size=int(kv_caches[0][0].shape[2]))
            self._plan = plan
        elif kv_caches[0][0].shape[2] != plan.page_size:
            raise ValueError(
                f"cache page_size {kv_caches[0][0].shape[2]} != planned "
                f"{plan.page_size}")
        _validate_state_geometry(plan, kv_caches, page_table, kv_lens)
        logits = jnp.asarray(logits)
        _validate_logits_batch(plan, logits)
        return (logits, list(kv_caches), page_table, kv_lens, key)

    @flashinfer_api(name="serve.step")
    def run(self, params, state):
        """One fused serving step: sample the carried logits, decode
        the sampled tokens, advance the donated state.  Returns
        ``(tokens, new_state)``; the input state's donated buffers are
        consumed."""
        from flashinfer_tpu import obs

        if self._step is None:
            raise RuntimeError("plan() must be called before run()")
        tick = obs.steploop_begin("ServingStep")
        logits, caches, page_table, kv_lens, key = state
        # flight recorder (FLASHINFER_TPU_SPANS): the trace signature
        # over EVERY jitted argument — params included, so a swapped
        # weight dtype/pytree attributes too (shape/dtype/structure
        # only, raw tuples: never a device transfer, no string work on
        # the hot path)
        signed = (params, logits, caches, page_table, kv_lens, key)
        sig = obs.state_signature(signed, names=self._STATE_NAMES)
        if tick is not None:
            tick.mark("signature")
        before = self._traces
        t0 = time.perf_counter() if sig is not None else 0.0
        out = self._step(params, logits, caches, page_table, kv_lens, key)
        if tick is not None:
            tick.dispatched()
        if self._traces > before:
            if sig is not None:
                # this dispatch paid a trace + XLA compile: give the
                # flight recorder the phase span (first trace is the
                # planned one; later ones are the retraces below)
                obs.record_span(f"{type(self).__name__}.trace_and_compile",
                                "compile", t0, time.perf_counter(),
                                wrapper=type(self).__name__,
                                trace_index=self._traces)
            if self._traces > 1:
                # a retrace under a live plan means a state pytree/
                # shape/dtype moved — the compile-once contract broke
                obs.counter_inc("serve.step_retraces",
                                wrapper=type(self).__name__)
                if sig is not None:
                    obs.record_retrace(
                        type(self).__name__,
                        obs.diff_state_sigs(self._last_sig, sig, signed))
        if sig is not None:
            self._last_sig = sig
        tokens, new_logits, new_caches, pt, lens, new_key = out
        if tick is not None:
            # completion probe (gate-ON measurement tax: one device
            # sync per step) — the OUTPUT blocks, never a donated input
            jax.block_until_ready(tokens)
            tick.done()
            tick.commit(tokens=int(tokens.shape[0]))
        return tokens, (new_logits, new_caches, pt, lens, new_key)


def mixed_chunk_tokens(batch_size: int, page_size: int, *,
                       default: int = 64) -> int:
    """Plan-time chunked-prefill chunk size (tokens advanced per mixed
    step by each prefilling request): the ``serve.mixed_chunk``
    autotune knob (KNOWN_KNOBS), shape-keyed on (batch, page_size).
    Larger chunks amortize the step launch over more prompt tokens;
    smaller chunks bound decode-request latency interference — the
    classic chunked-prefill trade, measured per chip generation."""
    from flashinfer_tpu.autotuner import AutoTuner

    return int(AutoTuner.get().lookup(
        "serve.mixed_chunk", (int(batch_size), int(page_size)),
        default=int(default)))


@dataclasses.dataclass(frozen=True)
class _MixedPlan:
    """Frozen statics + closed arrays of a mixed step (one chunk
    geometry; re-plan per scheduling decision, run per layer-sweep)."""

    cfg: object
    batch_size: int
    total_q: int
    page_size: int
    kv_dtype: str
    sampling: SamplingConfig
    donate: bool
    backend: str  # eagerly-resolved attention backend ("pallas"|"xla")
    # ISSUE 14: True = the step's per-layer RoPE + KV-quantize-append +
    # attention ride ONE fused-ingest launch (from-scratch prefill
    # steps only — every request at kv_before == 0); False = the
    # rope -> scatter-append -> gather-attend composition
    fused_ingest: bool = False


class MixedServingStep:
    """Chunked-prefill + decode in ONE jitted donated step.

    The holistic mixed-batch shape (reference ``TwoStageHolisticPlan``
    / ``persistent.cuh:682``): plan() takes per-request ``qo_lens``
    (prompt-chunk sizes; 1 for decoding requests) and the paged-KV
    geometry, builds the flattened token axis + per-token append
    targets host-side, plans the holistic attention through
    ``BatchAttention`` and closes its exported gather-plan arrays
    (``plan_arrays``) into the traced body.  run() embeds the flat
    token batch, appends every new K/V into the paged cache, attends
    causally over the post-append cache, and samples each request's
    last-token logits — one launch for the whole mixed batch.

    ``run_unfused`` executes the identical body eagerly (no jit, no
    donation) — the bit-parity oracle for the fused program."""

    _STATE_NAMES = ("params", "flat_tokens", "caches", "key")

    def __init__(self):
        self._plan: Optional[_MixedPlan] = None
        self._body = None
        self._step = None
        self._traces = 0
        self._last_sig = None

    @property
    def num_traces(self) -> int:
        return self._traces

    def plan(
        self,
        cfg,  # models.llama.LlamaConfig
        qo_lens,  # [B] host ints: tokens each request advances (>=1)
        kv_lens_before,  # [B] host ints: cache lens before this step
        kv_page_indptr,  # [B+1] host ints
        kv_page_indices,  # [total_pages] host ints
        page_size: int,
        *,
        kv_dtype=None,
        sampling: SamplingConfig = SamplingConfig(),
        donate: bool = True,
        backend: str = "auto",
        fused_ingest: Optional[bool] = None,
    ) -> None:
        from flashinfer_tpu import obs
        from flashinfer_tpu.attention import BatchAttention
        from flashinfer_tpu.utils import resolve_backend

        replan = self._plan is not None
        qo_lens = np.asarray(qo_lens, np.int64)
        kv0 = np.asarray(kv_lens_before, np.int64)
        if np.any(qo_lens < 1):
            raise ValueError("every request advances >= 1 token per "
                             "mixed step")
        # ISSUE 14 fused-ingest adoption: eligible iff this step is a
        # from-scratch prefill (every request at kv_before == 0 — the
        # first mixed step of a batch, where prefill cost concentrates);
        # None resolves via the prefill.fused_ingest knob -> cost-model
        # chooser (THE shared resolution point, prefill.py)
        ingest_eligible = bool(np.all(kv0 == 0)) and len(qo_lens) > 0
        if fused_ingest and not ingest_eligible:
            raise ValueError(
                "fused_ingest=True needs a from-scratch prefill step "
                "(every kv_lens_before == 0): chunked continuations "
                "attend cached prefixes the ingest kernel does not "
                "re-read — keep the composed step for them")
        B = len(qo_lens)
        qo_indptr = np.concatenate([[0], np.cumsum(qo_lens)]) \
            .astype(np.int32)
        total_q = int(qo_indptr[-1])
        seq_after = (kv0 + qo_lens).astype(np.int32)
        # per-token positions + paged append scatter targets (host math
        # once; frozen into the closure)
        tok_req = np.repeat(np.arange(B), qo_lens)
        tok_off = np.concatenate([np.arange(n) for n in qo_lens])
        positions = (kv0[tok_req] + tok_off).astype(np.int32)
        kvp_indptr = np.asarray(kv_page_indptr, np.int64)
        kvp_idx = np.asarray(kv_page_indices, np.int64)
        page_in_req = positions // page_size
        token_page = kvp_idx[kvp_indptr[tok_req] + page_in_req] \
            .astype(np.int32)
        token_slot = (positions % page_size).astype(np.int32)

        # the holistic attention plan over the POST-append cache; its
        # exported gather arrays are the closed attention schedule
        attn = BatchAttention(kv_layout="HND")
        attn.plan(
            qo_indptr, kv_page_indptr, kv_page_indices, seq_after,
            cfg.num_qo_heads, cfg.num_kv_heads, cfg.head_dim,
            cfg.head_dim, page_size, causal=True,
        )
        arrays = attn.plan_arrays
        last_idx = (qo_indptr[1:] - 1).astype(np.int32)

        # attention backend resolved EAGERLY (L003: the closure is
        # static); the gather + segment-flash form serves both — the
        # plan arrays ARE the gather schedule
        resolved = resolve_backend(
            "pallas" if backend == "pallas_fused" else backend,
            "batch_prefill_paged")
        if resolved == "pallas":
            from flashinfer_tpu.prefill import _tuned_flash as attn_fn
        else:
            from flashinfer_tpu.ops.xla_ref import (
                xla_ragged_attention as attn_fn)

        kv_dtype = jnp.dtype(kv_dtype) if kv_dtype is not None \
            else jnp.dtype(cfg.dtype)
        int8_kv = kv_dtype == jnp.int8
        sm_plain = float(arrays["sm_scale"])
        sm_scale = arrays["sm_scale"] * (cfg.kv_k_scale if int8_kv
                                         else 1.0)
        # fused-ingest resolution (ISSUE 14): an explicit request wins
        # (but must be eligible AND on the pallas tier — the ingest
        # kernel IS the work-unit mainloop); None routes through the
        # prefill.fused_ingest knob -> cost-model chooser, the same
        # single resolution point the wrapper uses (prefill.py)
        if fused_ingest is None:
            use_ingest = False
            if ingest_eligible and resolved == "pallas":
                from flashinfer_tpu.prefill import resolve_prefill_ingest

                fkey = (B, int(arrays["tq_pad"]), cfg.num_qo_heads,
                        cfg.num_kv_heads, cfg.head_dim, int(page_size))
                use_ingest = resolve_prefill_ingest(
                    fkey, total_q=total_q,
                    total_kv=int(seq_after.sum()),
                    num_qo_heads=cfg.num_qo_heads,
                    num_kv_heads=cfg.num_kv_heads,
                    head_dim=cfg.head_dim,
                    cache_bytes=int(kv_dtype.itemsize))
        else:
            use_ingest = bool(fused_ingest)
            if use_ingest and resolved != "pallas":
                raise ValueError(
                    "fused_ingest=True needs the pallas attention tier "
                    f"(backend resolved to {resolved!r}) — the ingest "
                    "kernel is the work-unit prefill mainloop")
        ingest_plan = None
        ingest_statics = None
        if use_ingest:
            from flashinfer_tpu.ops.paged_prefill import (
                build_prefill_ingest_units, ingest_block_q,
                ingest_pages_per_chunk)

            ibq = ingest_block_q(total_q)
            ippc = ingest_pages_per_chunk(page_size)
            iplan_np = build_prefill_ingest_units(
                qo_indptr.astype(np.int64), kvp_indptr, kvp_idx,
                seq_after.astype(np.int64),
                block_q=ibq, pages_per_chunk=ippc,
                page_size=int(page_size), causal=True,
            )
            ingest_statics = dict(
                num_units=iplan_np.pop("num_units"),
                block_q=iplan_np.pop("block_q"),
                pages_per_chunk=iplan_np.pop("pages_per_chunk"),
            )
            iplan_np.pop("stats")
            ingest_plan = {k2: jnp.asarray(v2)
                           for k2, v2 in iplan_np.items()}
        self._plan = _MixedPlan(
            cfg=cfg, batch_size=B, total_q=total_q,
            page_size=int(page_size), kv_dtype=str(kv_dtype),
            sampling=sampling, donate=bool(donate), backend=resolved,
            fused_ingest=use_ingest,
        )
        plan = self._plan
        self._traces = 0
        step_self = self

        # closed device constants (small plan arrays only; caches ride
        # as donated ARGUMENTS — closure-captured arrays embed as HLO
        # constants, fine at plan-array scale, fatal at cache scale)
        j_positions = jnp.asarray(positions)
        j_token_page = jnp.asarray(token_page)
        j_token_slot = jnp.asarray(token_slot)
        j_last_idx = jnp.asarray(last_idx)
        q_seg, q_pos = arrays["q_seg"], arrays["q_pos"]
        kv_seg, kv_pos = arrays["kv_seg"], arrays["kv_pos"]
        gather_rows = arrays["kv_gather_rows"]
        tq_pad, causal = arrays["tq_pad"], arrays["causal"]
        window_left = arrays["window_left"]
        soft_cap = arrays["logits_soft_cap"]

        def _attend(q, kc, vc):
            # HND [pages, Hkv, PS, D] -> flat NHD rows -> planned gather
            kg = jnp.swapaxes(kc, 1, 2).reshape(
                -1, cfg.num_kv_heads, cfg.head_dim)[gather_rows]
            vg = jnp.swapaxes(vc, 1, 2).reshape(
                -1, cfg.num_kv_heads, cfg.head_dim)[gather_rows]
            if int8_kv:  # raw codes attend; scales folded (sm/v_scale)
                kg = kg.astype(q.dtype)
                vg = vg.astype(q.dtype)
            qp = jnp.pad(q, ((0, tq_pad - total_q), (0, 0), (0, 0))) \
                if total_q != tq_pad else q
            out = attn_fn(
                qp, kg, vg, q_seg, kv_seg, q_pos, kv_pos,
                causal=causal, sm_scale=sm_scale,
                logits_soft_cap=soft_cap, window_left=window_left,
                return_lse=False,
            )
            out = out[:total_q]
            if int8_kv:
                out = (out.astype(jnp.float32)
                       * cfg.kv_v_scale).astype(q.dtype)
            return out

        def _body(params, flat_tokens, caches, key):
            from flashinfer_tpu.activation import silu_and_mul
            from flashinfer_tpu.models.llama import _mm, _pre_quant
            from flashinfer_tpu.norm import rmsnorm
            from flashinfer_tpu.rope import apply_rope_pos_ids

            step_self._traces += 1
            x = params["embed"][flat_tokens].astype(cfg.dtype)
            new_caches = []
            for li, layer in enumerate(params["layers"]):
                h = rmsnorm(x, layer["input_norm"], cfg.rms_eps)
                pre = _pre_quant(h, layer)
                q = _mm(h, layer, "q_proj", pre).reshape(
                    total_q, cfg.num_qo_heads, cfg.head_dim)
                k = _mm(h, layer, "k_proj", pre).reshape(
                    total_q, cfg.num_kv_heads, cfg.head_dim)
                v = _mm(h, layer, "v_proj", pre).reshape(
                    total_q, cfg.num_kv_heads, cfg.head_dim)
                kc, vc = caches[li]
                if use_ingest:
                    # ISSUE 14 fused ingest: RoPE + quantize-append +
                    # attention in ONE work-unit launch over the RAW
                    # q/k/v — the scatter-append and the cache re-read
                    # below disappear.  The launcher owns the int8
                    # scale folding (k into sm, v on the output), so
                    # it gets the PLAIN sm_scale and the raw scales
                    from flashinfer_tpu.ops.paged_prefill import (
                        fused_paged_prefill_ingest)

                    attn, (kc, vc) = fused_paged_prefill_ingest(
                        q, k, v, kc, vc, ingest_plan,
                        sm_scale=sm_plain, causal=True,
                        rope_theta=float(cfg.rope_theta),
                        kv_quant="int8" if int8_kv else "none",
                        k_scale=float(cfg.kv_k_scale) if int8_kv
                        else 1.0,
                        v_scale=float(cfg.kv_v_scale) if int8_kv
                        else 1.0,
                        **ingest_statics,
                    )
                else:
                    q, k = apply_rope_pos_ids(q, k, j_positions,
                                              rope_theta=cfg.rope_theta)
                    if int8_kv:
                        from flashinfer_tpu.quantization import (
                            quantize_symmetric_int8)

                        k_w = quantize_symmetric_int8(k, cfg.kv_k_scale)
                        v_w = quantize_symmetric_int8(v, cfg.kv_v_scale)
                    else:
                        k_w = k.astype(kc.dtype)
                        v_w = v.astype(vc.dtype)
                    kc = kc.at[j_token_page, :, j_token_slot, :].set(k_w)
                    vc = vc.at[j_token_page, :, j_token_slot, :].set(v_w)
                    attn = _attend(q, kc, vc)
                new_caches.append((kc, vc))
                x = x + _mm(attn.reshape(total_q, -1), layer,
                            "o_proj").astype(cfg.dtype)
                h2 = rmsnorm(x, layer["post_norm"], cfg.rms_eps)
                pre2 = _pre_quant(h2, layer, "gate_proj")
                mlp = jnp.concatenate(
                    [_mm(h2, layer, "gate_proj", pre2),
                     _mm(h2, layer, "up_proj", pre2)], -1)
                x = x + _mm(silu_and_mul(mlp), layer,
                            "down_proj").astype(cfg.dtype)
            x_last = x[j_last_idx]
            xf = rmsnorm(x_last, params["final_norm"], cfg.rms_eps)
            logits = _mm(xf, params, "lm_head").astype(jnp.float32)
            key, sk = jax.random.split(key)
            tokens = sample_next_tokens(logits, sk, plan.sampling)
            return tokens, logits, new_caches, key

        self._body = _body
        donate_argnums = (2, 3) if donate else ()  # caches + key
        self._step = jax.jit(_body, donate_argnums=donate_argnums)
        self._last_sig = None
        obs.record_plan(self, replan=replan, statics=self._plan)

    @flashinfer_api(name="serve.mixed_step")
    def run(self, params, flat_tokens, caches, key):
        """One fused mixed step -> ``(tokens [B], last_logits [B, V],
        new_caches, new_key)``.  ``caches`` and ``key`` are donated."""
        from flashinfer_tpu import obs

        if self._step is None:
            raise RuntimeError("plan() must be called before run()")
        tick = obs.steploop_begin("MixedServingStep")
        flat_tokens = jnp.asarray(flat_tokens, jnp.int32)
        signed = (params, flat_tokens, caches, key)
        sig = obs.state_signature(signed, names=self._STATE_NAMES)
        if tick is not None:
            tick.mark("signature")
        before = self._traces
        t0 = time.perf_counter() if sig is not None else 0.0
        out = self._step(params, flat_tokens, caches, key)
        if tick is not None:
            tick.dispatched()
        if self._traces > before:
            if sig is not None:
                obs.record_span(f"{type(self).__name__}.trace_and_compile",
                                "compile", t0, time.perf_counter(),
                                wrapper=type(self).__name__,
                                trace_index=self._traces)
            if self._traces > 1:
                obs.counter_inc("serve.step_retraces",
                                wrapper=type(self).__name__)
                if sig is not None:
                    obs.record_retrace(
                        type(self).__name__,
                        obs.diff_state_sigs(self._last_sig, sig, signed))
        if sig is not None:
            self._last_sig = sig
        if tick is not None:
            jax.block_until_ready(out[0])  # completion probe (gate-ON)
            tick.done()
            tick.commit(tokens=int(flat_tokens.shape[0]))
        return out

    def run_unfused(self, params, flat_tokens, caches, key):
        """The identical body, eager (no jit / no donation): the
        bit-parity oracle for the fused program — inputs stay valid.
        The trace counter is restored afterwards (an eager oracle run
        is not a trace of the compiled step)."""
        if self._body is None:
            raise RuntimeError("plan() must be called before run_unfused()")
        traces = self._traces
        try:
            return self._body(params, jnp.asarray(flat_tokens, jnp.int32),
                              caches, key)
        finally:
            self._traces = traces
