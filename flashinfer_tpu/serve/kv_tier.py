"""Tiered KV subsystem: host-RAM offload + disaggregated prefill→decode.

The KVCache-centric serving pattern the reference's production
consumers converged on (Mooncake-style disaggregation layered on the
PagedAttention block pool), built on three legs:

- :class:`HostKVStore` — the tier BELOW the device
  :class:`~flashinfer_tpu.serve.engine.BlockPool`: preempted and idle
  requests spill their materialized KV page runs to host RAM and
  restore on resume, so the engine's effective cache capacity exceeds
  the chip's ``hwspec.hbm_gib`` budget.  Spilled pages are stored at
  the CACHE'S OWN STORAGE DTYPE — for int8/fp8-KV engines that IS the
  compressed host format (1 byte/element, the existing KV quant
  appends already produced the quantized bits), and dtype preservation
  is what makes restore BIT-exact: restore-path tokens are pinned
  bitwise-equal to both recompute-on-resume and the never-preempted
  run (tests/test_kv_tier.py).
- **spill/restore/migrate ops** — :func:`spill_request`,
  :func:`restore_request`, :func:`migrate_request`: the decorated
  public movements (``engine.kv_spill`` / ``engine.kv_restore`` /
  ``engine.kv_migrate``), each priced by its ``obs.costmodel`` family
  (``kv_page_io`` pure-bandwidth page runs; ``kv_migrate`` adds the
  point-to-point ICI wire leg) and metered as ``engine.kv_tier.*``
  counters + flight-recorder spans.
- :class:`DisaggServing` — prefill/decode DISAGGREGATION: two
  :class:`~flashinfer_tpu.serve.engine.ServingEngine` instances with
  ``EngineConfig.role`` ``"prefill"`` and ``"decode"``.  The prefill
  pool runs each request through admission → chunked prefill → FIRST
  token, keeps the finished KV pages alive, and the coordinator hands
  them to the decode pool via :func:`migrate_request` — the handoff
  rides the same staging/restore machinery as the host tier, so one
  restore path serves both legs.  Decode continues from token 1 with
  the migrated request's original ``arrival`` (the per-lane sampling
  seeds are ``fold_in(base, arrival*K + token_index)``), which is why
  disaggregated tokens are BITWISE-equal to the unified engine's:
  same KV bits, same seeds, same position-determined windows —
  packing/scheduling differences cannot move a bit (the engine's
  module-doc contract).

Spill-vs-recompute policy (``EngineConfig.spill_policy``, the
``engine.spill_policy`` knob): ``"recompute"`` keeps PR 11's
recompute-on-resume; ``"spill"`` always offloads; ``"auto"`` compares
the cost model's two floors per victim — restore bytes over the HBM
roofline (:func:`~flashinfer_tpu.obs.costmodel.kv_page_io`) against
the recompute prefill's ``predict_step_seconds`` — and spills exactly
when moving bytes is cheaper than recomputing FLOPs
(:func:`spill_beats_recompute`).

The fold contract (the PR 11 regression this module fixes forward):
EVERY preemption — spill or recompute — folds the generated tokens
into the resume prompt (``ServingEngine._preempt``).  A spilled entry
can be LRU-evicted from the host store under capacity pressure, and
the fallback is recompute over ``req.prompt``; if the spill path
skipped the fold, that fallback would recompute the ORIGINAL prompt
only and silently drop every generated token mid-sequence.  With the
fold unconditional, a restore resumes from the spilled ``kv_len`` and
a host-evicted entry degrades to exactly the pinned recompute path —
both bitwise-equal to never-preempted (the satellite regression in
tests/test_kv_tier.py pins all three across f32 and int8-KV with real
sampling configs).

See docs/serving.md §"Tiered KV & disaggregation" for the tier
diagram, knobs, and the bitwise contract; docs/observability.md for
the ``engine.kv_tier.*`` catalog rows and the perf/3
``serving_disagg`` join.
"""

from __future__ import annotations

import dataclasses
import time
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

import numpy as np

from flashinfer_tpu.api_logging import flashinfer_api

if TYPE_CHECKING:  # import cycle: engine.py calls into this module
    from flashinfer_tpu.serve.engine import EngineRequest, ServingEngine


# ---------------------------------------------------------------------------
# Host-RAM tier
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class HostKVEntry:
    """One spilled (or in-flight migrated) request's KV pages in host
    RAM: per layer, the K and V page planes ``[pages, Hkv, ps, hd]`` at
    the cache's storage dtype (bit-exact restore), plus the
    ``kv_len`` the resume continues from."""

    rid: str
    kv_len: int
    layers: List[Tuple[np.ndarray, np.ndarray]]
    nbytes: int
    last_use: int = 0

    @property
    def num_pages(self) -> int:
        return int(self.layers[0][0].shape[0]) if self.layers else 0


class HostKVStore:
    """LRU host-RAM store of spilled KV page runs — the tier below the
    device block pool.

    Invariants (stress-pinned in tests/test_kv_tier.py):

    - one entry per request id; a second ``put`` for a live entry
      raises (double-spill is a bug, not a state — the engine released
      the device pages exactly once);
    - ``pop`` of an absent id raises (restoring pages nobody spilled
      would hand the engine fabricated KV);
    - ``bytes_used`` equals the sum of live entry payloads at all
      times; admission over ``capacity_bytes`` LRU-evicts other
      entries first (leaf == entry here: entries are flat) and rejects
      the put only when the entry alone exceeds the capacity.

    An evicted entry's request falls back to PR 11's
    recompute-on-resume — correct (the fold already happened), just
    slower; the eviction is counted (``engine.kv_tier.host_evictions``)
    so a thrashing store is visible, never silent.
    """

    def __init__(self, capacity_bytes: int):
        if capacity_bytes <= 0:
            raise ValueError("HostKVStore needs a positive capacity")
        self.capacity_bytes = int(capacity_bytes)
        self.bytes_used = 0
        self.evictions = 0
        self._entries: Dict[str, HostKVEntry] = {}
        self._clock = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def pages_used(self) -> int:
        return sum(e.num_pages for e in self._entries.values())

    def has(self, rid: str) -> bool:
        return rid in self._entries

    def peek(self, rid: str) -> Optional[HostKVEntry]:
        """The entry without removing it (bumps its LRU clock)."""
        e = self._entries.get(rid)
        if e is not None:
            self._clock += 1
            e.last_use = self._clock
        return e

    def put(self, rid: str, layers: List[Tuple[np.ndarray, np.ndarray]],
            kv_len: int) -> Optional[HostKVEntry]:
        """Admit one spilled page run.  Returns the entry, or None when
        it cannot fit even after evicting everything else (the caller
        falls back to recompute-on-resume).  Raises on double-spill."""
        from flashinfer_tpu import obs

        if rid in self._entries:
            raise ValueError(f"double spill: {rid!r} already has a "
                             "live host entry")
        nbytes = int(sum(k.nbytes + v.nbytes for k, v in layers))
        if nbytes > self.capacity_bytes:
            return None
        while self.bytes_used + nbytes > self.capacity_bytes:
            victim = min(self._entries.values(),
                         key=lambda e: e.last_use)
            self._drop(victim.rid)
            self.evictions += 1
            obs.counter_inc("engine.kv_tier.host_evictions")
        self._clock += 1
        entry = HostKVEntry(rid=rid, kv_len=int(kv_len), layers=layers,
                            nbytes=nbytes, last_use=self._clock)
        self._entries[rid] = entry
        self.bytes_used += nbytes
        return entry

    def pop(self, rid: str) -> HostKVEntry:
        """Remove and return the entry for restore; raises KeyError on
        an absent id (a restore nobody spilled)."""
        if rid not in self._entries:
            raise KeyError(f"no host KV entry for {rid!r} — restore of "
                           "pages that were never spilled")
        return self._drop(rid)

    def drop(self, rid: str) -> None:
        """Discard an entry if present (request finished elsewhere)."""
        if rid in self._entries:
            self._drop(rid)

    def _drop(self, rid: str) -> HostKVEntry:
        entry = self._entries.pop(rid)
        self.bytes_used -= entry.nbytes
        return entry


# ---------------------------------------------------------------------------
# page movement helpers (engine <-> host <-> peer pool)
# ---------------------------------------------------------------------------


def _extract_pages(engine: "ServingEngine", req: "EngineRequest"
                   ) -> Tuple[List[Tuple[np.ndarray, np.ndarray]], int]:
    """Copy the request's MATERIALIZED page run out of the engine's
    device caches: pages [0, ceil(kv_len/ps)) hold KV positions
    [0, kv_len) (the position-determined layout).  Returns
    (per-layer (k, v) host arrays, payload bytes)."""
    import jax.numpy as jnp

    ps = engine.config.page_size
    n = -(-req.kv_len // ps)
    idx = jnp.asarray(np.asarray(req.pages[:n], np.int32))
    layers: List[Tuple[np.ndarray, np.ndarray]] = []
    nbytes = 0
    for kc, vc in engine.caches:
        hk = np.asarray(kc[idx])
        hv = np.asarray(vc[idx])
        layers.append((hk, hv))
        nbytes += hk.nbytes + hv.nbytes
    return layers, nbytes


def _inject_pages(engine: "ServingEngine", req: "EngineRequest",
                  entry: HostKVEntry) -> None:
    """Write a host entry's page planes into the request's freshly
    allocated device pages — the bit-exact inverse of
    :func:`_extract_pages` (same dtype, same page-major layout)."""
    import jax.numpy as jnp

    n = entry.num_pages
    if n > len(req.pages):
        raise ValueError(
            f"restore of {req.rid!r}: entry holds {n} pages but the "
            f"request allocated only {len(req.pages)}")
    kv_dtype = engine.caches[0][0].dtype
    if entry.layers and entry.layers[0][0].dtype != kv_dtype:
        raise ValueError(
            f"restore of {req.rid!r}: host entry dtype "
            f"{entry.layers[0][0].dtype} != cache dtype {kv_dtype} — "
            "the bit-exact tier contract forbids a converting restore")
    idx = jnp.asarray(np.asarray(req.pages[:n], np.int32))
    new_caches = []
    for (kc, vc), (hk, hv) in zip(engine.caches, entry.layers):
        kc = kc.at[idx].set(jnp.asarray(hk))
        vc = vc.at[idx].set(jnp.asarray(hv))
        new_caches.append((kc, vc))
    engine.caches = new_caches


@flashinfer_api(name="engine.kv_spill")
def spill_request(engine: "ServingEngine", req: "EngineRequest") -> bool:
    """Spill a request's materialized KV pages to the engine's host
    store (``engine.kv_offload="host"``).  Called by the engine's
    preemption path (and :meth:`ServingEngine.offload_idle`) BEFORE the
    device pages are released.  Returns False when the run has nothing
    materialized or the host store rejected the payload — the caller
    then falls back to recompute-on-resume."""
    from flashinfer_tpu import obs

    if engine.host_store is None:
        raise ValueError("spill_request on an engine without a host "
                         "tier (EngineConfig.kv_offload is 'off')")
    if req.kv_len <= 0 or not req.pages:
        return False
    t0 = time.perf_counter()
    layers, nbytes = _extract_pages(engine, req)
    entry = engine.host_store.put(req.rid, layers, kv_len=req.kv_len)
    if entry is None:
        return False
    t1 = time.perf_counter()
    st = engine.kv_tier_stats
    st["spills"] += 1
    st["spill_bytes"] += nbytes
    obs.counter_inc("engine.kv_tier.spills")
    obs.counter_inc("engine.kv_tier.spill_bytes", nbytes)
    obs.gauge_set("engine.kv_tier.host_bytes",
                  engine.host_store.bytes_used)
    obs.gauge_set("engine.kv_tier.host_pages",
                  engine.host_store.pages_used)
    obs.record_span("engine.kv_spill", "host", t0, t1, rid=req.rid,
                    bytes=nbytes, pages=entry.num_pages,
                    kv_len=req.kv_len)
    return True


@flashinfer_api(name="engine.kv_restore")
def restore_request(engine: "ServingEngine", req: "EngineRequest") -> int:
    """Restore a staged entry (host-tier spill OR in-flight migration)
    into the request's freshly allocated device pages at admission.
    Sets ``req.kv_len`` to the spilled length and returns it.  Raises
    when no entry is staged — the admission path must only call this
    after :func:`staged_entry` said one exists."""
    from flashinfer_tpu import obs

    t0 = time.perf_counter()
    if req.rid in engine._migrated:
        entry = engine._migrated.pop(req.rid)
    elif engine.host_store is not None:
        entry = engine.host_store.pop(req.rid)
    else:
        raise KeyError(f"no staged KV entry for {req.rid!r}")
    _inject_pages(engine, req, entry)
    req.kv_len = entry.kv_len
    t1 = time.perf_counter()
    st = engine.kv_tier_stats
    st["restores"] += 1
    st["restore_bytes"] += entry.nbytes
    obs.counter_inc("engine.kv_tier.restores")
    obs.counter_inc("engine.kv_tier.restore_bytes", entry.nbytes)
    if engine.host_store is not None:
        obs.gauge_set("engine.kv_tier.host_bytes",
                      engine.host_store.bytes_used)
        obs.gauge_set("engine.kv_tier.host_pages",
                      engine.host_store.pages_used)
    obs.record_span("engine.kv_restore", "host", t0, t1, rid=req.rid,
                    bytes=entry.nbytes, pages=entry.num_pages,
                    kv_len=entry.kv_len)
    return entry.kv_len


def staged_entry(engine: "ServingEngine", rid: str
                 ) -> Optional[HostKVEntry]:
    """The restore source for ``rid`` if one is staged: an in-flight
    migration first (the disagg handoff), else the host spill store."""
    e = engine._migrated.get(rid)
    if e is not None:
        return e
    if engine.host_store is not None:
        return engine.host_store.peek(rid)
    return None


def spill_beats_recompute(engine: "ServingEngine",
                          req: "EngineRequest") -> bool:
    """The ``spill_policy="auto"`` decision: restore the spilled bytes
    (spill read + restore write over the HBM roofline) vs recompute
    the prefill (``costmodel.engine_step`` over the folded span through
    ``predict_step_seconds``).  Same physics ``obs perf`` attributes
    with, used forward — the PR 6 ``choose_decode_splits`` pattern."""
    from flashinfer_tpu.obs import costmodel, hwspec

    cfg, mcfg = engine.config, engine.cfg
    spec = hwspec.current_spec()
    pages = -(-req.kv_len // cfg.page_size)
    if pages <= 0:
        return False
    kv_bytes = engine.kv_dtype.itemsize
    io = costmodel.kv_page_bytes(
        pages, page_size=cfg.page_size,
        num_kv_heads=mcfg.num_kv_heads, head_dim=mcfg.head_dim,
        layers=mcfg.num_layers, kv_bytes=kv_bytes)
    restore_s = 2.0 * io / (spec.hbm_tbps * 1e12)
    tokens = req.kv_len
    recompute = costmodel.engine_step(
        num_tokens=tokens, batch=1, layers=mcfg.num_layers,
        hidden=mcfg.hidden_size, inter=mcfg.intermediate_size,
        hq=mcfg.num_qo_heads, hkv=mcfg.num_kv_heads, hd=mcfg.head_dim,
        vocab=mcfg.vocab_size, kv_tokens=tokens * (tokens + 1) / 2,
        kv_bytes=kv_bytes)
    recompute_s = costmodel.predict_step_seconds(
        recompute, hbm_tbps=spec.hbm_tbps,
        peak_tflops=spec.peak_tflops(str(engine.kv_dtype)),
        ici_gbps=0.0)
    return restore_s < recompute_s


# ---------------------------------------------------------------------------
# disaggregated prefill -> decode handoff
# ---------------------------------------------------------------------------


@flashinfer_api(name="engine.kv_migrate")
def migrate_request(src: "ServingEngine", dst: "ServingEngine",
                    req: "EngineRequest", *,
                    max_new_tokens: Optional[int] = None) -> dict:
    """Hand one finished-prefill request from the prefill pool to the
    decode pool: extract its KV page run from ``src`` (the prefill-role
    engine kept the pages alive past finish), release the source
    pages, and stage the run + a continuation request on ``dst`` —
    the decode engine's admission restores it through the same
    :func:`restore_request` path the host tier uses.

    The continuation carries the ORIGINAL ``arrival`` and the frozen
    cascade ``split``, so the decode pool samples the same seed stream
    from the same KV bits the unified engine would — disaggregated
    tokens are bitwise-equal to unified serving (pinned).

    Returns the handoff facts: ``bytes`` moved, ``pages``, and the
    ``kv_migrate`` cost (ICI wire + both HBM legs) priced by the
    model — what the ``serving_disagg`` bench phase aggregates and
    stamps."""
    from flashinfer_tpu import obs
    from flashinfer_tpu.obs import costmodel
    from flashinfer_tpu.serve.engine import EngineRequest

    if src.config.role != "prefill":
        raise ValueError("migrate_request source must be a "
                         "prefill-role engine")
    if not req.pages or req.kv_len <= 0:
        raise ValueError(f"migrate_request: {req.rid!r} has no "
                         "materialized KV to hand off")
    t0 = time.perf_counter()
    layers, nbytes = _extract_pages(src, req)
    n_pages = -(-req.kv_len // src.config.page_size)
    cost = costmodel.kv_migrate(
        pages=n_pages, page_size=src.config.page_size,
        num_kv_heads=src.cfg.num_kv_heads, head_dim=src.cfg.head_dim,
        layers=src.cfg.num_layers,
        kv_bytes=src.kv_dtype.itemsize)
    cont = EngineRequest(
        rid=req.rid, prompt=list(req.prompt),
        max_new_tokens=(req.max_new_tokens if max_new_tokens is None
                        else int(max_new_tokens)),
        priority=req.priority, slo_ttft_s=req.slo_ttft_s)
    cont.out_tokens = list(req.out_tokens)
    cont.arrival = req.arrival
    cont.split = req.split
    entry = HostKVEntry(rid=req.rid, kv_len=req.kv_len, layers=layers,
                        nbytes=nbytes)
    # adopt BEFORE releasing the source pages: a decode pool that
    # rejects the continuation (capacity / max_seq bounds) must leave
    # the request fully intact on the prefill side — the destructive
    # decref happens only once the handoff is committed
    dst.adopt_migrated(cont, entry)
    src.pool.decref(req.pages)
    req.pages = []
    t1 = time.perf_counter()
    for eng in (src, dst):
        eng.kv_tier_stats["migrations"] += 1
        eng.kv_tier_stats["migrate_bytes"] += nbytes
    obs.counter_inc("engine.kv_tier.migrations")
    obs.counter_inc("engine.kv_tier.migrate_bytes", nbytes)
    obs.record_span("engine.kv_migrate", "host", t0, t1, rid=req.rid,
                    bytes=nbytes, pages=n_pages, kv_len=req.kv_len)
    return {"rid": req.rid, "bytes": nbytes, "pages": n_pages,
            "kv_len": req.kv_len, "seconds": t1 - t0, "cost": cost}


class DisaggServing:
    """Disaggregated serving: a prefill-pool engine and a decode-pool
    engine joined by the :func:`migrate_request` handoff.

    >>> disagg = DisaggServing(cfg, params, EngineConfig(num_pages=65))
    >>> disagg.submit(EngineRequest("r0", prompt, max_new_tokens=8))
    >>> results = disagg.run()   # bitwise == the unified engine's

    Each submitted request runs on the prefill pool with
    ``max_new_tokens=1`` (admission, prefix-cache reuse, chunked
    prefill, the FIRST token), then its KV pages migrate to the decode
    pool, which decodes the remaining tokens.  ``migration_stats``
    aggregates the handoff traffic (count, bytes, wall seconds, and
    the summed ``kv_migrate`` cost) for the ``serving_disagg`` bench
    rows; :meth:`aggregate_cost` is both pools' ``engine_step`` work
    plus the migration cost — one stampable Cost for the whole
    disaggregated run."""

    def __init__(self, model_cfg, params, config, *, decode_config=None):
        from flashinfer_tpu.serve.engine import ServingEngine

        pcfg = dataclasses.replace(config, role="prefill")
        dcfg = dataclasses.replace(decode_config or config, role="decode")
        if dcfg.sampling != pcfg.sampling or dcfg.seed != pcfg.seed:
            raise ValueError(
                "prefill and decode pools must share the sampling "
                "config and seed — the per-lane seed stream is the "
                "bitwise handoff contract")
        self.prefill = ServingEngine(model_cfg, params, pcfg)
        self.decode = ServingEngine(model_cfg, params, dcfg)
        self._max_new: Dict[str, int] = {}
        self._prefill_only: Dict[str, List[int]] = {}
        self.migration_stats = {
            "migrations": 0, "bytes": 0.0, "seconds": 0.0,
            "ici_bytes": 0.0,
        }
        self._migration_cost = None

    def submit(self, req: "EngineRequest") -> None:
        """Enqueue on the prefill pool (capped at the first token; the
        original ``max_new_tokens`` rides the migration)."""
        from flashinfer_tpu.serve.engine import EngineRequest

        self._max_new[req.rid] = req.max_new_tokens
        self.prefill.submit(EngineRequest(
            rid=req.rid, prompt=list(req.prompt), max_new_tokens=1,
            priority=req.priority, slo_ttft_s=req.slo_ttft_s))

    def has_work(self) -> bool:
        return (self.prefill.has_work() or self.decode.has_work()
                or bool(self.prefill._finished))

    def step(self) -> None:
        """One coordinator tick: advance the prefill pool, migrate
        every freshly finished prefill, advance the decode pool."""
        if self.prefill.has_work():
            self.prefill.step()
        for req in self.prefill.harvest_finished():
            if self._max_new[req.rid] <= 1:
                # single-token request: the prefill pool already
                # produced everything; release its kept pages
                if req.pages:
                    self.prefill.pool.decref(req.pages)
                    req.pages = []
                self._prefill_only[req.rid] = list(req.out_tokens)
                continue
            facts = migrate_request(self.prefill, self.decode, req,
                                    max_new_tokens=self._max_new[req.rid])
            ms = self.migration_stats
            ms["migrations"] += 1
            ms["bytes"] += facts["bytes"]
            ms["seconds"] += facts["seconds"]
            ms["ici_bytes"] += facts["cost"].ici_bytes
            self._migration_cost = (
                facts["cost"] if self._migration_cost is None
                else self._migration_cost + facts["cost"])
        if self.decode.has_work():
            self.decode.step()

    def run(self, max_steps: int = 100000) -> Dict[str, List[int]]:
        steps = 0
        while self.has_work():
            if steps >= max_steps:
                raise RuntimeError(
                    f"disagg coordinator exceeded {max_steps} ticks "
                    "with work left")
            self.step()
            steps += 1
        results = dict(self._prefill_only)
        results.update({rid: list(r.out_tokens)
                        for rid, r in self.decode._finished.items()})
        return results

    def aggregate_cost(self):
        """Both pools' run-aggregate ``engine_step`` cost plus the
        summed ``kv_migrate`` handoff cost — the one Cost the
        ``serving_disagg`` bench row stamps (its ``ici_bytes`` make
        the migration traffic visible to ``obs perf``)."""
        total = (self.prefill.aggregate_cost()
                 + self.decode.aggregate_cost())
        if self._migration_cost is not None:
            total = total + self._migration_cost
        return dataclasses.replace(total, op="serving_disagg")
