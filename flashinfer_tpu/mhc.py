"""Manifold hyper-connections (mHC) mixing ops.

TPU re-design of the reference mHC ops (``flashinfer/mhc.py``,
``csrc/mhc/`` — HC=4 hyper-connection pre/post mixes): the model keeps
``n`` parallel residual streams; each layer reads a weighted combination
(pre-mix) and writes back through a depth gate + a stream-mixing matrix
(post-mix).  Dynamic variants derive the mix weights from the input via a
small projection.  Pure-XLA: these are small fused einsums.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@jax.jit
def mhc_pre_mix(
    streams: jax.Array,  # [tokens, n, hidden]
    w_pre: jax.Array,  # [n] static or [tokens, n] dynamic weights
) -> jax.Array:
    """Combine the n residual streams into the layer input."""
    wf = w_pre.astype(jnp.float32)
    sf = streams.astype(jnp.float32)
    if wf.ndim == 1:
        out = jnp.einsum("tnh,n->th", sf, wf)
    else:
        out = jnp.einsum("tnh,tn->th", sf, wf)
    return out.astype(streams.dtype)


@jax.jit
def mhc_post_mix(
    streams: jax.Array,  # [tokens, n, hidden]
    layer_out: jax.Array,  # [tokens, hidden]
    w_depth: jax.Array,  # [n] or [tokens, n]: how much layer_out each stream gets
    w_width: jax.Array,  # [n, n] or [tokens, n, n]: stream mixing matrix
) -> jax.Array:
    """streams' = w_width @ streams + w_depth (outer) layer_out."""
    sf = streams.astype(jnp.float32)
    of = layer_out.astype(jnp.float32)
    dd = w_depth.astype(jnp.float32)
    ww = w_width.astype(jnp.float32)
    mixed = (
        jnp.einsum("nm,tmh->tnh", ww, sf)
        if ww.ndim == 2
        else jnp.einsum("tnm,tmh->tnh", ww, sf)
    )
    inject = (
        dd[None, :, None] * of[:, None, :]
        if dd.ndim == 1
        else dd[:, :, None] * of[:, None, :]
    )
    return (mixed + inject).astype(streams.dtype)


@functools.partial(jax.jit, static_argnames=("n",))
def mhc_dynamic_weights(
    x: jax.Array,  # [tokens, hidden] pre-mix input source (e.g. stream mean)
    w_proj: jax.Array,  # [hidden, n + n + n*n]
    b_proj: Optional[jax.Array] = None,
    n: int = 4,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Project x to dynamic (w_pre [t,n], w_depth [t,n], w_width [t,n,n]);
    width matrix passes through tanh for stability (mHC convention)."""
    h = x.astype(jnp.float32) @ w_proj.astype(jnp.float32)
    if b_proj is not None:
        h = h + b_proj.astype(jnp.float32)
    t = x.shape[0]
    w_pre = h[:, :n]
    w_depth = h[:, n : 2 * n]
    w_width = jnp.tanh(h[:, 2 * n :].reshape(t, n, n))
    return w_pre, w_depth, w_width
