"""Manifold hyper-connections (mHC) mixing ops.

TPU re-design of the reference mHC ops (``flashinfer/mhc.py``,
``csrc/mhc/`` — HC=4 hyper-connection pre/post mixes): the model keeps
``n`` parallel residual streams; each layer reads a weighted combination
(pre-mix) and writes back through a depth gate + a stream-mixing matrix
(post-mix).  Dynamic variants derive the mix weights from the input via a
small projection.  Pure-XLA: these are small fused einsums.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@jax.jit
def mhc_pre_mix(
    streams: jax.Array,  # [tokens, n, hidden]
    w_pre: jax.Array,  # [n] static or [tokens, n] dynamic weights
) -> jax.Array:
    """Combine the n residual streams into the layer input."""
    wf = w_pre.astype(jnp.float32)
    sf = streams.astype(jnp.float32)
    if wf.ndim == 1:
        out = jnp.einsum("tnh,n->th", sf, wf)
    else:
        out = jnp.einsum("tnh,tn->th", sf, wf)
    return out.astype(streams.dtype)


@jax.jit
def mhc_post_mix(
    streams: jax.Array,  # [tokens, n, hidden]
    layer_out: jax.Array,  # [tokens, hidden]
    w_depth: jax.Array,  # [n] or [tokens, n]: how much layer_out each stream gets
    w_width: jax.Array,  # [n, n] or [tokens, n, n]: stream mixing matrix
) -> jax.Array:
    """streams' = w_width @ streams + w_depth (outer) layer_out."""
    sf = streams.astype(jnp.float32)
    of = layer_out.astype(jnp.float32)
    dd = w_depth.astype(jnp.float32)
    ww = w_width.astype(jnp.float32)
    mixed = (
        jnp.einsum("nm,tmh->tnh", ww, sf)
        if ww.ndim == 2
        else jnp.einsum("tnm,tmh->tnh", ww, sf)
    )
    inject = (
        dd[None, :, None] * of[:, None, :]
        if dd.ndim == 1
        else dd[:, :, None] * of[:, None, :]
    )
    return (mixed + inject).astype(streams.dtype)


@functools.partial(jax.jit, static_argnames=("n",))
def mhc_dynamic_weights(
    x: jax.Array,  # [tokens, hidden] pre-mix input source (e.g. stream mean)
    w_proj: jax.Array,  # [hidden, n + n + n*n]
    b_proj: Optional[jax.Array] = None,
    n: int = 4,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Project x to dynamic (w_pre [t,n], w_depth [t,n], w_width [t,n,n]);
    width matrix passes through tanh for stability (mHC convention)."""
    h = x.astype(jnp.float32) @ w_proj.astype(jnp.float32)
    if b_proj is not None:
        h = h + b_proj.astype(jnp.float32)
    t = x.shape[0]
    w_pre = h[:, :n]
    w_depth = h[:, n : 2 * n]
    w_width = jnp.tanh(h[:, 2 * n :].reshape(t, n, n))
    return w_pre, w_depth, w_width


# ---------------------------------------------------------------------------
# Reference HC=4 fused entry points (flashinfer/mhc.py:76,176,334 backed by
# csrc/mhc/mhc_post.cu, mhc_pre_big_fuse.cu) — exact math transcribed from
# the kernels, vectorized over tokens.
# ---------------------------------------------------------------------------

_HC = 4
_MIX = 2 * _HC + _HC * _HC  # 24 = pre(4) + post(4) + comb(16)


@jax.jit
def mhc_post(
    x: jax.Array,  # [..., H]
    residual: jax.Array,  # [..., HC=4, H]
    post_layer_mix: jax.Array,  # [..., HC] (trailing 1 squeezed if present)
    comb_res_mix: jax.Array,  # [..., HC, HC]
) -> jax.Array:
    """mHC post mapping (reference mhc.py:76 / mhc_post.cu):
    ``out[.., n, h] = x[.., h] * post[.., n]
    + sum_o residual[.., o, h] * comb[.., o, n]``."""
    if post_layer_mix.shape[-1] == 1 and post_layer_mix.ndim == x.ndim + 1:
        post_layer_mix = post_layer_mix[..., 0]
    xf = x.astype(jnp.float32)
    out = (
        xf[..., None, :] * post_layer_mix.astype(jnp.float32)[..., :, None]
        + jnp.einsum(
            "...oh,...on->...nh", residual.astype(jnp.float32),
            comb_res_mix.astype(jnp.float32),
        )
    )
    return out.astype(residual.dtype)


def _sinkhorn_hc4(cm: jax.Array, eps: float, repeat: int) -> jax.Array:
    """The kernel's comb normalization: row softmax (+eps), then column
    normalize; then (repeat-1) x (row divide by rowsum+eps inside the
    loop, column divide by colsum+eps).  cm: [..., HC(row), HC(col)]."""
    cm = jax.nn.softmax(cm, axis=-1) + eps
    cm = cm / (jnp.sum(cm, axis=-2, keepdims=True) + eps)
    def body(_, m):
        m = m / (jnp.sum(m, axis=-1, keepdims=True) + eps)
        return m / (jnp.sum(m, axis=-2, keepdims=True) + eps)
    return jax.lax.fori_loop(1, repeat, body, cm)


@functools.partial(
    jax.jit,
    static_argnames=("k", "rms_eps", "mhc_pre_eps", "mhc_sinkhorn_eps",
                     "mhc_post_mult_value", "sinkhorn_repeat", "num_splits"),
)
def mhc_pre_big_fuse(
    dot_mix: jax.Array,  # [..., 24] or [num_splits, ..., 24]
    sqrsum: jax.Array,  # [...] or [num_splits, ...]
    residual: jax.Array,  # [..., HC=4, H]
    mhc_scale: jax.Array,  # [3] (pre, post, comb) scales
    mhc_base: jax.Array,  # [24] biases
    k: int,
    rms_eps: float = 1e-6,
    mhc_pre_eps: float = 1e-6,
    mhc_sinkhorn_eps: float = 1e-6,
    mhc_post_mult_value: float = 1.0,
    sinkhorn_repeat: int = 20,
    num_splits: int = 1,
    block_size: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """mHC pre-map big fuse (reference mhc.py:176 / mhc_pre_big_fuse.cu):
    RMS-normalize the raw projection logits, derive sigmoid pre/post
    gates and the Sinkhorn-normalized 4x4 comb matrix, and build the
    layer input ``sum_j pre[j] * residual[j]``.  Returns
    ``(post_mix [..., 4, 1], comb_mix [..., 4, 4], layer_input [..., H])``.
    ``num_splits > 1`` leading split axes of dot_mix/sqrsum are reduced
    here (the kernel reduces them internally)."""
    if num_splits not in (1, 2, 4, 8, 16):
        raise ValueError("num_splits must be one of {1, 2, 4, 8, 16}")
    if num_splits > 1:
        dot_mix = jnp.sum(dot_mix.astype(jnp.float32), axis=0)
        sqrsum = jnp.sum(sqrsum.astype(jnp.float32), axis=0)
    y = dot_mix.astype(jnp.float32)
    rstd = jax.lax.rsqrt(
        sqrsum.astype(jnp.float32) / float(k) + rms_eps
    )[..., None]
    scale = mhc_scale.astype(jnp.float32)
    base = mhc_base.astype(jnp.float32)
    pre = jax.nn.sigmoid(
        y[..., :_HC] * rstd * scale[0] + base[:_HC]
    ) + mhc_pre_eps
    post = jax.nn.sigmoid(
        y[..., _HC:2 * _HC] * rstd * scale[1] + base[_HC:2 * _HC]
    ) * mhc_post_mult_value
    cm = (
        y[..., 2 * _HC:] * rstd * scale[2] + base[2 * _HC:]
    ).reshape(*y.shape[:-1], _HC, _HC)
    comb = _sinkhorn_hc4(cm, mhc_sinkhorn_eps, sinkhorn_repeat)
    layer_input = jnp.einsum(
        "...n,...nh->...h", pre, residual.astype(jnp.float32)
    ).astype(residual.dtype)
    return post[..., None], comb, layer_input


@functools.partial(
    jax.jit,
    static_argnames=("rms_eps", "mhc_pre_eps", "mhc_sinkhorn_eps",
                     "mhc_post_mult_value", "sinkhorn_repeat"),
)
def mhc_pre_big_fuse_with_prenorm(
    dot_mix: jax.Array,  # [..., 24] (or [1, ..., 24])
    residual: jax.Array,  # [..., HC=4, H]
    mhc_scale: jax.Array,
    mhc_base: jax.Array,
    rms_eps: float = 1e-6,
    mhc_pre_eps: float = 1e-6,
    mhc_sinkhorn_eps: float = 1e-6,
    mhc_post_mult_value: float = 1.0,
    sinkhorn_repeat: int = 20,
    block_size: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The prenorm variant (reference mhc.py:334): ``sqrsum`` is computed
    from ``residual`` here (sum of squares over the [HC, H] block,
    normalized by K = HC * H)."""
    if dot_mix.ndim == residual.ndim:  # leading [1, ...] split axis
        dot_mix = dot_mix[0]
    rf = residual.astype(jnp.float32)
    sqrsum = jnp.sum(rf * rf, axis=(-2, -1))
    k = residual.shape[-2] * residual.shape[-1]
    return mhc_pre_big_fuse(
        dot_mix, sqrsum, residual, mhc_scale, mhc_base, k,
        rms_eps, mhc_pre_eps, mhc_sinkhorn_eps, mhc_post_mult_value,
        sinkhorn_repeat,
    )
