"""Gated activation ops.

TPU-native equivalents of the reference's activation family
(``flashinfer/activation.py``, ``include/flashinfer/activation.cuh``):
``silu_and_mul``, ``gelu_and_mul``, ``gelu_tanh_and_mul``.

Input convention matches the reference: the last dimension is ``2*d`` holding
``[gate, up]`` halves; output has last dimension ``d`` computed as
``act(gate) * up``.  These are single-pass bandwidth-bound ops that XLA fuses
optimally under jit, so the primary backend is pure-XLA (a Pallas kernel adds
nothing here — documented design decision, SURVEY §7 "let XLA fuse").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from flashinfer_tpu.api_logging import flashinfer_api
from flashinfer_tpu.norm import _norm_parity_kw as _act_parity_kw


def _split_gate_up(x: jax.Array):
    d = x.shape[-1] // 2
    return x[..., :d], x[..., d:]


@jax.jit
def _silu_and_mul(x: jax.Array) -> jax.Array:
    gate, up = _split_gate_up(x)
    gf = gate.astype(jnp.float32)
    return (jax.nn.silu(gf) * up.astype(jnp.float32)).astype(x.dtype)


@flashinfer_api
def silu_and_mul(x: jax.Array, out=None, enable_pdl=None) -> jax.Array:
    """``silu(x[..., :d]) * x[..., d:]`` (reference flashinfer/activation.py)."""
    _act_parity_kw("silu_and_mul", out, enable_pdl)
    return _silu_and_mul(x)


@jax.jit
def _gelu_and_mul(x: jax.Array) -> jax.Array:
    gate, up = _split_gate_up(x)
    gf = gate.astype(jnp.float32)
    return (jax.nn.gelu(gf, approximate=False) * up.astype(jnp.float32)).astype(x.dtype)


@flashinfer_api
def gelu_and_mul(x: jax.Array, out=None, enable_pdl=None) -> jax.Array:
    """Exact-erf GeLU gated multiply."""
    _act_parity_kw("gelu_and_mul", out, enable_pdl)
    return _gelu_and_mul(x)


@jax.jit
def _gelu_tanh_and_mul(x: jax.Array) -> jax.Array:
    gate, up = _split_gate_up(x)
    gf = gate.astype(jnp.float32)
    return (jax.nn.gelu(gf, approximate=True) * up.astype(jnp.float32)).astype(x.dtype)


@flashinfer_api
def gelu_tanh_and_mul(x: jax.Array, out=None, enable_pdl=None) -> jax.Array:
    """tanh-approximated GeLU gated multiply."""
    _act_parity_kw("gelu_tanh_and_mul", out, enable_pdl)
    return _gelu_tanh_and_mul(x)


@functools.partial(jax.jit, static_argnames=("quant_dtype",))
def silu_and_mul_quant_fp8(x: jax.Array, quant_dtype=jnp.float8_e4m3fn):
    """Fused gated-SiLU + per-tensor fp8 quantize (reference's
    SiLU-fused quantizing activation variants, flashinfer/quantization/).
    Returns (values, scale)."""
    gate, up = _split_gate_up(x)
    y = jax.nn.silu(gate.astype(jnp.float32)) * up.astype(jnp.float32)
    finfo = jnp.finfo(quant_dtype)
    amax = jnp.max(jnp.abs(y))
    scale = jnp.maximum(amax / float(finfo.max), 1e-12)
    q = jnp.clip(y / scale, float(finfo.min), float(finfo.max)).astype(quant_dtype)
    return q, scale.astype(jnp.float32)
