"""Gated activation ops.

TPU-native equivalents of the reference's activation family
(``flashinfer/activation.py``, ``include/flashinfer/activation.cuh``):
``silu_and_mul``, ``gelu_and_mul``, ``gelu_tanh_and_mul``.

Input convention matches the reference: the last dimension is ``2*d`` holding
``[gate, up]`` halves; output has last dimension ``d`` computed as
``act(gate) * up``.  These are single-pass bandwidth-bound ops that XLA fuses
optimally under jit, so the primary backend is pure-XLA (a Pallas kernel adds
nothing here — documented design decision, SURVEY §7 "let XLA fuse").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _split_gate_up(x: jax.Array):
    d = x.shape[-1] // 2
    return x[..., :d], x[..., d:]


@jax.jit
def silu_and_mul(x: jax.Array) -> jax.Array:
    """``silu(x[..., :d]) * x[..., d:]`` (reference flashinfer/activation.py)."""
    gate, up = _split_gate_up(x)
    gf = gate.astype(jnp.float32)
    return (jax.nn.silu(gf) * up.astype(jnp.float32)).astype(x.dtype)


@jax.jit
def gelu_and_mul(x: jax.Array) -> jax.Array:
    """Exact-erf GeLU gated multiply."""
    gate, up = _split_gate_up(x)
    gf = gate.astype(jnp.float32)
    return (jax.nn.gelu(gf, approximate=False) * up.astype(jnp.float32)).astype(x.dtype)


@jax.jit
def gelu_tanh_and_mul(x: jax.Array) -> jax.Array:
    """tanh-approximated GeLU gated multiply."""
    gate, up = _split_gate_up(x)
    gf = gate.astype(jnp.float32)
    return (jax.nn.gelu(gf, approximate=True) * up.astype(jnp.float32)).astype(x.dtype)
