"""Communication & parallelism layer.

TPU-native re-design of the reference comm layer (``flashinfer/comm/``,
SURVEY §2.4).  The reference hand-rolls device-side collectives over CUDA
IPC / NVLink / MNNVL / NVSHMEM with MPI/torch.distributed bootstrap; the
TPU equivalents are XLA collectives over ICI/DCN inside ``shard_map`` —
one-shot vs two-shot strategy selection, IPC workspaces, Lamport buffers
and fabric-handle exchange all disappear into the compiler.  What remains
(and lives here) is:

- ``Mapping``: rank topology math (tp/pp/cp/dp/moe_tp/moe_ep) — same
  bookkeeping role as ``flashinfer/comm/mapping.py:21``.
- ``allreduce`` facade: ``allreduce`` / ``allreduce_fusion`` (residual +
  RMSNorm [+ quant] epilogues) mirroring the reference's unified API
  (``flashinfer/comm/allreduce.py``), implemented as jit-fusable psum
  compositions to be used inside shard_map.
- ``all_to_all`` helpers for EP dispatch/combine and DCP decode.
"""

from flashinfer_tpu.comm.mapping import Mapping  # noqa: F401
from flashinfer_tpu.comm.allreduce import (  # noqa: F401
    allreduce,
    allreduce_fusion,
    allgather,
    reducescatter,
)
from flashinfer_tpu.comm.compat import *  # noqa: F401,F403  (reference
# comm name surface: AR strategies/workspaces, vLLM AR, MoE a2a, DCP a2a)
from flashinfer_tpu.comm import compat as _compat  # noqa: F401
