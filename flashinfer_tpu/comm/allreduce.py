"""Unified allreduce / fused-allreduce API over mesh axes.

TPU re-design of the reference's unified allreduce surface
(``flashinfer/comm/allreduce.py:107-525`` facade over TRTLLM/MNNVL IPC
kernels, fusion patterns ``AllReduceFusionPattern`` trtllm_ar.py:68-100).

On TPU there is no workspace creation, no one-shot/two-shot strategy choice
and no Lamport buffers: ``jax.lax.psum`` inside ``shard_map`` compiles to
the optimal ICI collective.  What this module preserves is the *fusion
surface*: allreduce + residual-add + RMSNorm (+ FP8 quantize) as one jitted
composition so XLA fuses the epilogue into the collective's output pass —
the same latency motivation as the reference's fused kernels.

All functions here must be called **inside shard_map** with the named axis
present.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _record_allreduce_bytes(x: jax.Array, axis: str) -> None:
    """Count the payload bytes of one allreduce call site.

    Shapes are static even under a shard_map/jit trace, so this runs
    host-side at TRACE time: the counter reads as the per-call traffic
    of each compiled program (obs catalog ``comm.allreduce_bytes``) —
    the measured-side join for the cost model's predicted ICI bytes.
    Zero-overhead when the metrics gate is off (default, pinned)."""
    from flashinfer_tpu import obs

    if obs.metrics_enabled():
        obs.counter_inc("comm.allreduce_bytes",
                        int(x.size) * x.dtype.itemsize, axis=axis)


def allreduce(x: jax.Array, axis: str = "tp") -> jax.Array:
    """Plain sum-allreduce over a mesh axis (reference
    ``allreduce``/trtllm_custom_all_reduce)."""
    _record_allreduce_bytes(x, axis)
    return jax.lax.psum(x, axis)


def allgather(x: jax.Array, axis: str = "tp", *, tiled: bool = True) -> jax.Array:
    return jax.lax.all_gather(x, axis, tiled=tiled)


def reducescatter(x: jax.Array, axis: str = "tp") -> jax.Array:
    return jax.lax.psum_scatter(x, axis, tiled=True)


def allreduce_fusion(
    x: jax.Array,  # [tokens, hidden] partial sums (e.g. o_proj shard output)
    residual: Optional[jax.Array] = None,  # [tokens, hidden]
    rms_weight: Optional[jax.Array] = None,  # [hidden]
    eps: float = 1e-6,
    axis: str = "tp",
    quant_dtype=None,  # e.g. jnp.float8_e4m3fn for AR+norm+quant patterns
) -> Tuple[jax.Array, ...]:
    """Allreduce with fused residual-add + RMSNorm (+ quantize) epilogue.

    Pattern table mirrors ``AllReduceFusionPattern`` (trtllm_ar.py:68):
    - residual=None, rms_weight=None    -> kAllReduce: returns (sum,)
    - residual, rms_weight              -> kARResidualRMSNorm:
          returns (normed, new_residual)
    - + quant_dtype                     -> kARResidualRMSNormFP8Quant:
          returns (quantized, scale, new_residual)
    """
    _record_allreduce_bytes(x, axis)
    s = jax.lax.psum(x, axis)
    if residual is None and rms_weight is None:
        return (s,)
    sf = s.astype(jnp.float32)
    if residual is not None:
        sf = sf + residual.astype(jnp.float32)
    new_residual = sf.astype(x.dtype)
    if rms_weight is None:
        return (new_residual,)
    var = jnp.mean(sf * sf, axis=-1, keepdims=True)
    normed = sf * jax.lax.rsqrt(var + eps) * rms_weight.astype(jnp.float32)
    if quant_dtype is None:
        return normed.astype(x.dtype), new_residual
    amax = jnp.max(jnp.abs(normed))
    finfo = jnp.finfo(quant_dtype)
    scale = jnp.maximum(amax / float(finfo.max), 1e-12)
    q = jnp.clip(normed / scale, float(finfo.min), float(finfo.max)).astype(
        quant_dtype
    )
    return q, scale.astype(jnp.float32), new_residual
