"""Rank topology math: the TPU ``Mapping``.

Re-design of the reference ``Mapping`` (``flashinfer/comm/mapping.py:21-461``):
the same tp/pp/cp/dp/moe_tp/moe_ep bookkeeping, but instead of deriving
*process group rank lists* for NCCL it derives **mesh axis layouts** for
``jax.sharding.Mesh`` — on TPU the collectives are compiled, not brokered,
so the Mapping's job is to build the mesh and name the axes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class Mapping:
    """Topology descriptor over ``world_size`` devices.

    Axes (any may be 1): ``dp`` (data/batch), ``cp`` (context/sequence),
    ``tp`` (tensor), ``pp`` (pipeline); MoE sub-axes ``moe_tp``/``moe_ep``
    factor the tp axis for expert layers (reference mapping.py moe_cluster
    semantics collapse into this factoring).
    """

    world_size: int = 1
    tp_size: int = 1
    pp_size: int = 1
    cp_size: int = 1
    dp_size: int = 1
    moe_tp_size: int = 1
    moe_ep_size: int = 1
    num_slices: int = 1  # multi-slice (DCN) deployments; dp crosses slices

    def __post_init__(self):
        if self.dp_size * self.cp_size * self.tp_size * self.pp_size != self.world_size:
            raise ValueError(
                f"dp*cp*tp*pp = "
                f"{self.dp_size * self.cp_size * self.tp_size * self.pp_size}"
                f" != world_size {self.world_size}"
            )
        if self.moe_tp_size * self.moe_ep_size not in (1, self.tp_size):
            raise ValueError(
                "moe_tp_size * moe_ep_size must equal tp_size (or both be 1): "
                f"{self.moe_tp_size}*{self.moe_ep_size} vs tp {self.tp_size}"
            )
        if self.num_slices > 1:
            if self.dp_size % self.num_slices:
                raise ValueError(
                    "multi-slice topologies put DCN parallelism on the dp "
                    f"axis: dp_size {self.dp_size} must be a multiple of "
                    f"num_slices {self.num_slices} (tp/cp/pp collectives "
                    "must stay inside a slice's ICI)"
                )
            if self.world_size % self.num_slices:
                raise ValueError(
                    f"world_size {self.world_size} not divisible by "
                    f"num_slices {self.num_slices}"
                )

    # ---- axis names -------------------------------------------------------
    AXIS_DP = "dp"
    AXIS_CP = "cp"
    AXIS_TP = "tp"
    AXIS_PP = "pp"

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return (self.AXIS_DP, self.AXIS_CP, self.AXIS_TP, self.AXIS_PP)

    @property
    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.dp_size, self.cp_size, self.tp_size, self.pp_size)

    def make_mesh(self, devices: Optional[Sequence] = None):
        """Build the ``jax.sharding.Mesh`` for this topology.

        Multi-slice (``num_slices > 1``): the mesh is laid out so the
        OUTER part of the dp axis crosses slices (DCN) and every inner
        axis (cp/tp/pp and the within-slice part of dp) stays inside one
        slice's ICI — the scaling-book recipe: only gradient/batch-style
        traffic rides DCN, bandwidth-hungry tp/cp collectives never
        leave a slice.  On real multi-slice TPU the devices are grouped
        by ``slice_index`` (the jax device attribute
        ``mesh_utils.create_hybrid_device_mesh`` keys on); hosts without
        slice metadata (CPU dryruns, single slice) use flat order, which
        has the same contiguous-blocks-per-slice layout.

        Reference analogue: multi-node rank groups over NCCL/MNNVL
        (comm/mapping.py:21-461 + mnnvl.py); here the DCN/ICI split is a
        device-ordering concern and XLA compiles the right collectives.
        """
        import jax
        from jax.sharding import Mesh

        devices = list(devices if devices is not None else jax.devices())
        if len(devices) < self.world_size:
            raise ValueError(
                f"need {self.world_size} devices, have {len(devices)}"
            )
        devices = devices[: self.world_size]
        if self.num_slices > 1:
            per_slice = self.world_size // self.num_slices
            slice_ids = [getattr(d, "slice_index", None) for d in devices]
            if all(s is not None for s in slice_ids):
                # real multi-slice: the population must be exactly
                # num_slices slices of per_slice devices each — anything
                # else would put a tp/cp collective block across two
                # slices' DCN boundary silently
                from collections import Counter

                counts = Counter(slice_ids)
                if len(counts) != self.num_slices \
                        or set(counts.values()) != {per_slice}:
                    raise ValueError(
                        f"multi-slice mesh needs {self.num_slices} slices "
                        f"x {per_slice} devices; got slice populations "
                        f"{dict(counts)} — a contiguous block would span "
                        "slices and its ICI collectives would ride DCN"
                    )
                # group by slice so each outer-dp block is one slice
                # (one contiguous ICI domain); stable within a slice
                devices = [d for _, d in sorted(
                    zip(slice_ids, devices), key=lambda t: (t[0],)
                )]
            # devices without slice metadata (CPU dryruns): flat order
            # already yields contiguous per-slice blocks
        arr = np.array(devices).reshape(self.axis_sizes)
        return Mesh(arr, self.axis_names)

    @property
    def dcn_axis_name(self) -> Optional[str]:
        """The mesh axis whose collectives cross DCN (None when single
        slice).  Always ``dp`` by construction — batch-parallel traffic
        is the only traffic cheap enough for DCN."""
        return self.AXIS_DP if self.num_slices > 1 else None

    # ---- rank coordinate math (parity with reference rank accessors) ------
    def coords(self, rank: int) -> Tuple[int, int, int, int]:
        """(dp, cp, tp, pp) coordinates of a flat rank."""
        sizes = self.axis_sizes
        out = []
        rem = rank
        for s in sizes[::-1]:
            out.append(rem % s)
            rem //= s
        return tuple(out[::-1])

    def tp_rank(self, rank: int) -> int:
        return self.coords(rank)[2]

    def pp_rank(self, rank: int) -> int:
        return self.coords(rank)[3]

    def cp_rank(self, rank: int) -> int:
        return self.coords(rank)[1]

    def dp_rank(self, rank: int) -> int:
        return self.coords(rank)[0]

    def moe_ep_rank(self, rank: int) -> int:
        return self.tp_rank(rank) % self.moe_ep_size

    def moe_tp_rank(self, rank: int) -> int:
        return self.tp_rank(rank) // self.moe_ep_size

    def pp_layers(self, num_layers: int) -> List[List[int]]:
        """Contiguous layer partition per pipeline stage (reference
        ``Mapping.pp_layers``, mapping.py:442)."""
        base = num_layers // self.pp_size
        extra = num_layers % self.pp_size
        out, start = [], 0
        for s in range(self.pp_size):
            n = base + (1 if s < extra else 0)
            out.append(list(range(start, start + n)))
            start += n
        return out

    def ep_experts(self, num_experts: int) -> List[List[int]]:
        """Expert partition per EP rank (reference ``Mapping.ep_experts``)."""
        base = num_experts // self.moe_ep_size
        extra = num_experts % self.moe_ep_size
        out, start = [], 0
        for s in range(self.moe_ep_size):
            n = base + (1 if s < extra else 0)
            out.append(list(range(start, start + n)))
            start += n
        return out
