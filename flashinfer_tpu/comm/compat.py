"""Reference ``flashinfer.comm`` name surface on the mesh model.

The reference comm package exports ~40 CUDA-fabric entry points: IPC
workspace lifecycles (trtllm/vLLM custom all-reduce), Lamport buffer
initialization, MNNVL fabric handles, and the MoE all-to-all runtime.
Under XLA every one of these concerns is owned by the compiler — a
collective is an op inside ``shard_map``, its buffers are XLA's, and
there is no out-of-band workspace to create, register, or destroy.

Three binding classes here (same policy as the package-level compat):

- **mapped**: all-reduce/all-to-all entry points route to the real
  collectives (``allreduce_fusion``, ``lax.all_to_all``);
- **inert lifecycle**: workspace create/destroy/register return
  lightweight handle records and accept them back — engine plumbing
  runs unchanged, and the handles document that XLA owns the buffers;
- **honest absence**: fabric probes report what this hardware has.

Cited: /root/reference/flashinfer/comm/__init__.py (name surface),
trtllm_allreduce.py, vllm_allreduce.py, moe_alltoall.py.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp

from flashinfer_tpu.utils import lax_axis_size

from flashinfer_tpu.comm.allreduce import allreduce, allreduce_fusion

__all__ = [
    "AllReduceFusionOp", "AllReduceFusionPattern",
    "AllReduceFusionWorkspace", "AllReduceStrategyConfig",
    "AllReduceStrategyType", "MNNVLAllReduceFusionWorkspace",
    "MoeAlltoAll", "QuantizationSFLayout",
    "TRTLLMAllReduceFusionWorkspace",
    "compute_fp4_swizzled_layout_sf_size",
    "create_allreduce_fusion_workspace", "create_shared_buffer",
    "decode_cp_a2a_allocate_mnnvl_workspace", "decode_cp_a2a_alltoall",
    "decode_cp_a2a_init_workspace", "decode_cp_a2a_workspace_size",
    "free_shared_buffer", "moe_a2a_active_rank_mask", "moe_a2a_combine",
    "moe_a2a_dispatch", "moe_a2a_get_workspace_size_per_rank",
    "moe_a2a_initialize", "moe_a2a_sanitize_expert_ids",
    "moe_a2a_wrap_payload_tensor_in_workspace", "pack_strided_memory",
    "trtllm_allreduce_fusion",
    "trtllm_create_ipc_workspace_for_all_reduce",
    "trtllm_create_ipc_workspace_for_all_reduce_fusion",
    "trtllm_custom_all_reduce",
    "trtllm_destroy_ipc_workspace_for_all_reduce",
    "trtllm_destroy_ipc_workspace_for_all_reduce_fusion",
    "trtllm_lamport_initialize", "trtllm_lamport_initialize_all",
    "trtllm_moe_allreduce_fusion", "trtllm_moe_finalize_allreduce_fusion",
    "vllm_all_reduce", "vllm_dispose", "vllm_get_graph_buffer_ipc_meta",
    "vllm_init_custom_ar", "vllm_meta_size", "vllm_register_buffer",
    "vllm_register_graph_buffers",
]


# ---------------------------------------------------------------------------
# enums + strategy records (reference trtllm_allreduce.py)
# ---------------------------------------------------------------------------


class AllReduceStrategyType(enum.IntEnum):
    """Reference kernel-strategy selector (one-shot/two-shot/NCCL...).
    XLA picks the collective algorithm; AUTO is the only meaningful
    member and the others are accepted as hints."""

    NCCL = 0
    ONESHOT = 1
    TWOSHOT = 2
    AUTO = 3
    LOWPRECISION = 4
    MNNVL = 5


class AllReduceStrategyConfig(enum.IntEnum):
    USE_MEMCPY = 0
    PUSH_MODE = 1


class AllReduceFusionOp(enum.IntEnum):
    """Fusion epilogue selector — maps onto allreduce_fusion's pattern
    table (residual + RMSNorm [+ quant])."""

    NONE = 0
    RESIDUAL_RMS_NORM = 1
    LAST_PROCESS_FOR_UB = 2
    RESIDUAL_RMS_PREPOST_NORM = 3
    RESIDUAL_RMS_NORM_QUANT_FP8 = 4
    RESIDUAL_RMS_NORM_QUANT_NVFP4 = 5


class AllReduceFusionPattern(enum.IntEnum):
    kAllReduce = 0
    kARResidualRMSNorm = 1
    kARResidualRMSNormFP8Quant = 2
    kARResidualRMSNormFP4Quant = 3


class QuantizationSFLayout(enum.IntEnum):
    """Scale-factor layout for quantizing fusions: XLA owns layout, so
    row-major is the one (and identity-correct) member."""

    ROW_MAJOR = 0
    SWIZZLED_128x4 = 0
    SWIZZLED_8x4 = 0


def compute_fp4_swizzled_layout_sf_size(rows: int, cols: int,
                                        sf_vec_size: int = 16) -> int:
    """Reference sizes the swizzled fp4 scale buffer; row-major here."""
    return rows * (cols // sf_vec_size)


# ---------------------------------------------------------------------------
# workspace lifecycle -> inert handle records (XLA owns the buffers)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AllReduceFusionWorkspace:
    """Inert workspace handle: the reference allocates IPC-mapped Lamport
    buffers; XLA collectives need none.  Carried so engine code that
    creates/passes/destroys workspaces runs unchanged."""

    tp_size: int = 1
    max_token_num: int = 0
    hidden_dim: int = 0


TRTLLMAllReduceFusionWorkspace = AllReduceFusionWorkspace
MNNVLAllReduceFusionWorkspace = AllReduceFusionWorkspace


def create_allreduce_fusion_workspace(tp_size: int = 1,
                                      max_token_num: int = 0,
                                      hidden_dim: int = 0, **_):
    return AllReduceFusionWorkspace(tp_size, max_token_num, hidden_dim)


def trtllm_create_ipc_workspace_for_all_reduce(*_, **__):
    return AllReduceFusionWorkspace()


def trtllm_create_ipc_workspace_for_all_reduce_fusion(*_, **__):
    return AllReduceFusionWorkspace()


def trtllm_destroy_ipc_workspace_for_all_reduce(*_, **__):
    return None


def trtllm_destroy_ipc_workspace_for_all_reduce_fusion(*_, **__):
    return None


def trtllm_lamport_initialize(*_, **__):
    """Lamport flag buffers synchronize the reference's one-shot kernels;
    XLA collectives carry their own synchronization."""
    return None


def trtllm_lamport_initialize_all(*_, **__):
    return None


def create_shared_buffer(*_, **__):
    """CUDA IPC shared buffers have no TPU analogue; arrays passed to
    collectives are already device-resident and mesh-addressable."""
    return None


def free_shared_buffer(*_, **__):
    return None


def pack_strided_memory(tensor, *_, **__):
    """Reference packs strided CUDA memory for IPC transport; identity
    (XLA owns layout and transport)."""
    return tensor


# ---------------------------------------------------------------------------
# all-reduce entry points -> the real collectives
# ---------------------------------------------------------------------------


def trtllm_custom_all_reduce(inp, axis: str = "tp", *,
                             strategy=AllReduceStrategyType.AUTO,
                             workspace=None, **_unused):
    """Reference one-shot/two-shot custom AR -> ``psum`` over the mesh
    axis (call inside shard_map)."""
    return allreduce(inp, axis=axis)


def trtllm_allreduce_fusion(
    allreduce_in, residual_in=None, rms_gamma=None, axis: str = "tp",
    *, pattern=AllReduceFusionPattern.kARResidualRMSNorm, eps: float = 1e-6,
    workspace=None, scale_factor=None, layout_code=None, **_unused,
):
    """Reference fused AR(+residual+RMSNorm[+quant]) -> the
    allreduce_fusion pattern table."""
    quant = None
    if pattern == AllReduceFusionPattern.kARResidualRMSNormFP8Quant:
        quant = jnp.float8_e4m3fn
    elif pattern == AllReduceFusionPattern.kARResidualRMSNormFP4Quant:
        raise ValueError(
            "TPU backend: the FP4-quantizing AR fusion is not implemented "
            "(the quantizing epilogue here is fp8/int8); use "
            "kARResidualRMSNormFP8Quant or quantize after the fusion"
        )
    if pattern == AllReduceFusionPattern.kAllReduce or residual_in is None:
        return allreduce(allreduce_in, axis=axis)
    return allreduce_fusion(
        allreduce_in, residual_in, rms_gamma, axis=axis, eps=eps,
        quant_dtype=quant,
    )


def trtllm_moe_allreduce_fusion(token_input, residual=None, gamma=None,
                                axis: str = "tp", **kw):
    """MoE-combined AR fusion -> the same fused pattern."""
    return trtllm_allreduce_fusion(token_input, residual, gamma, axis, **kw)


def trtllm_moe_finalize_allreduce_fusion(expert_output, expert_weights=None,
                                         residual=None, gamma=None,
                                         axis: str = "tp", **kw):
    """Finalize (weighted expert combine) + AR fusion: the weighted sum
    happens in fused_moe's finalize; the AR rides here."""
    out = expert_output
    if expert_weights is not None:
        out = (out.astype(jnp.float32)
               * expert_weights.astype(jnp.float32)[..., None]).sum(-2)
        out = out.astype(expert_output.dtype)
    return trtllm_allreduce_fusion(out, residual, gamma, axis, **kw)


# vLLM custom-AR surface: registration is a no-op (no graph buffers to
# exchange), the reduce is the collective
def vllm_init_custom_ar(*_, **__):
    return AllReduceFusionWorkspace()


def vllm_all_reduce(inp, axis: str = "tp", **_unused):
    return allreduce(inp, axis=axis)


def vllm_dispose(*_, **__):
    return None


def vllm_meta_size() -> int:
    return 0


def vllm_register_buffer(*_, **__):
    return None


def vllm_register_graph_buffers(*_, **__):
    return None


def vllm_get_graph_buffer_ipc_meta(*_, **__):
    return (b"", [])


# ---------------------------------------------------------------------------
# MoE all-to-all runtime (reference moe_alltoall.py) -> lax.all_to_all
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _MoeA2AWorkspace:
    ep_size: int = 1
    max_tokens: int = 0


def moe_a2a_get_workspace_size_per_rank(*_, **__) -> int:
    return 0


def moe_a2a_initialize(ep_size: int = 1, max_tokens: int = 0, **_):
    return _MoeA2AWorkspace(ep_size, max_tokens)


def moe_a2a_wrap_payload_tensor_in_workspace(payload, *_, **__):
    return payload


def moe_a2a_sanitize_expert_ids(expert_ids, num_experts: int,
                                pad_id: int = -1):
    """Clamp out-of-range expert ids to the pad id (reference sanitize)."""
    ids = jnp.asarray(expert_ids)
    ok = (ids >= 0) & (ids < num_experts)
    return jnp.where(ok, ids, pad_id)


def moe_a2a_active_rank_mask(expert_ids, num_experts: int, ep_size: int):
    """[ep_size] bool: which ranks receive any of this rank's routes."""
    ids = jnp.asarray(expert_ids).reshape(-1)
    e_local = num_experts // ep_size
    dst = jnp.where(ids >= 0, ids // e_local, ep_size)
    return (
        jnp.zeros((ep_size + 1,), jnp.int32).at[dst].add(1)[:ep_size] > 0
    )


def moe_a2a_dispatch(hidden, topk_ids, topk_weights, num_experts: int,
                     axis: str = "tp", workspace=None,
                     capacity_factor: float = 2.0, **_unused):
    """Standalone dispatch half (reference moe_a2a_dispatch): the fused
    path keeps dispatch inside ``fused_moe_ep``; this explicit form
    performs the capacity-bucketed exchange and returns the received
    (tokens, expert_ids, validity) — call inside shard_map.

    DEVIATION (ADVICE r4): this is CAPACITY-DROP dispatch — routes
    beyond ``capacity_factor`` x fair-share per expert contribute zero,
    while the reference runtime (comm/moe_alltoall.py) delivers every
    routed token.  For exact no-drop semantics use
    ``fused_moe_ep(..., dispatch="alltoall_exact")`` (rounds-based
    exchange)
    or raise capacity_factor.  See docs/migration.md deviation table."""
    from flashinfer_tpu.fused_moe.core import _route_buckets

    ep = lax_axis_size(axis)
    e_local = num_experts // ep
    T, K = topk_ids.shape
    H = hidden.shape[1]
    cap, order, sd, stok, eid, within = _route_buckets(
        topk_ids, e_local, ep, capacity_factor
    )
    send_x = jnp.zeros((ep, cap, H), hidden.dtype).at[sd, within].set(
        hidden[stok], mode="drop")
    send_eid = jnp.full((ep, cap), -1, jnp.int32).at[sd, within].set(
        eid, mode="drop")
    recv_x = jax.lax.all_to_all(send_x, axis, 0, 0)
    recv_eid = jax.lax.all_to_all(send_eid, axis, 0, 0)
    return recv_x, recv_eid, recv_eid >= 0


def moe_a2a_combine(expert_output, topk_ids, topk_weights,
                    num_experts: int, axis: str = "tp", workspace=None,
                    capacity_factor: float = 2.0, **_unused):
    """Standalone combine half: route expert outputs back and weight-sum
    per source token (inverse of :func:`moe_a2a_dispatch`).  Same
    capacity-drop deviation as dispatch — dropped routes contribute
    zero to the weighted sum (docs/migration.md)."""
    from flashinfer_tpu.fused_moe.core import _route_buckets

    ep = lax_axis_size(axis)
    e_local = num_experts // ep
    T, K = topk_ids.shape
    H = expert_output.shape[-1]
    cap, order, sd, stok, eid, within = _route_buckets(
        topk_ids, e_local, ep, capacity_factor
    )
    back = jax.lax.all_to_all(
        expert_output.reshape(ep, cap, H), axis, 0, 0
    )
    kept = (within < cap)[:, None].astype(jnp.float32)
    gathered = back[sd, jnp.minimum(within, cap - 1)] * kept
    contrib = jnp.zeros((T * K, H), jnp.float32).at[order].set(
        gathered.astype(jnp.float32))
    return (
        contrib.reshape(T, K, H)
        * topk_weights.astype(jnp.float32)[..., None]
    ).sum(1).astype(expert_output.dtype)


class MoeAlltoAll:
    """Object form of the a2a runtime (reference MoeAlltoAll): holds the
    geometry; dispatch/combine call the functions above."""

    def __init__(self, ep_size: int = 1, num_experts: int = 1,
                 axis: str = "tp", capacity_factor: float = 2.0, **_):
        self.ep_size = ep_size
        self.num_experts = num_experts
        self.axis = axis
        self.capacity_factor = capacity_factor

    def dispatch(self, hidden, topk_ids, topk_weights, **kw):
        return moe_a2a_dispatch(
            hidden, topk_ids, topk_weights, self.num_experts, self.axis,
            capacity_factor=self.capacity_factor, **kw)

    def combine(self, expert_output, topk_ids, topk_weights, **kw):
        return moe_a2a_combine(
            expert_output, topk_ids, topk_weights, self.num_experts,
            self.axis, capacity_factor=self.capacity_factor, **kw)


# ---------------------------------------------------------------------------
# decode-CP all-to-all (reference decode_cp_a2a) -> parallel/dcp
# ---------------------------------------------------------------------------


def decode_cp_a2a_workspace_size(*_, **__) -> int:
    return 0


def decode_cp_a2a_init_workspace(*_, **__):
    return None


def decode_cp_a2a_allocate_mnnvl_workspace(*_, **__):
    return None


def decode_cp_a2a_alltoall(x, axis: str = "cp", split_axis: int = 0,
                           concat_axis: int = 0, **_unused):
    """Decode context-parallel all-to-all (reference decode_cp_a2a):
    the DCP head/kv exchange — ``lax.all_to_all`` over the cp axis
    (``parallel.dcp_decode`` is the full fused form)."""
    return jax.lax.all_to_all(x, axis, split_axis, concat_axis, tiled=True)
