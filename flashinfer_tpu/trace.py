"""Workload trace capture + zero-code kernel substitution.

TPU re-design of the reference's fi_trace / trace_apply pair
(``flashinfer/fi_trace.py:15-75`` TraceTemplate -> flashinfer-bench JSON;
``flashinfer/trace_apply/apply.py:15-28`` monkey-patch substitution):

- ``FLASHINFER_TPU_TRACE_DUMP=1``: every decorated public-API call appends
  a JSON definition line (op, shapes, dtypes, static params) to
  ``<dump_dir>/trace.jsonl`` — the workload-capture format benchmark
  tooling consumes.
- ``register_solution(op, match, fn)`` + ``FLASHINFER_TPU_TRACE_APPLY=1``:
  calls whose static axes match a registered solution are routed to the
  substitute implementation, without touching call sites (the reference's
  tuned-kernel swap-in mechanism).

These hooks ride the ``@flashinfer_api`` decorator (api_logging.py) that
already wraps the public APIs — op names in traces/solutions are the public
function names (e.g. ``"rmsnorm"``).  ``@traced_api`` remains for adding
the hooks to functions outside the logged API surface.
"""

from __future__ import annotations

import functools
import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from flashinfer_tpu import env

_lock = threading.Lock()
_solutions: Dict[str, List[Tuple[Dict[str, Any], Callable]]] = {}


def _trace_enabled() -> bool:
    return os.environ.get("FLASHINFER_TPU_TRACE_DUMP", "0") == "1"


def _apply_enabled() -> bool:
    return os.environ.get("FLASHINFER_TPU_TRACE_APPLY", "0") == "1"


def _axes_of(args, kwargs) -> Dict[str, Any]:
    axes: Dict[str, Any] = {}
    for i, a in enumerate(args):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            axes[f"arg{i}"] = {"shape": list(a.shape), "dtype": str(a.dtype)}
        elif isinstance(a, (int, float, str, bool)):
            axes[f"arg{i}"] = a
    for k, v in kwargs.items():
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            axes[k] = {"shape": list(v.shape), "dtype": str(v.dtype)}
        elif isinstance(v, (int, float, str, bool)):
            axes[k] = v
    return axes


def _dump_trace(op: str, axes: Dict[str, Any]) -> None:
    d = env.dump_dir()
    d.mkdir(parents=True, exist_ok=True)
    with _lock, open(d / "trace.jsonl", "a") as f:
        f.write(json.dumps({"op": op, "axes": axes}) + "\n")


def register_solution(op: str, match: Dict[str, Any], fn: Callable) -> None:
    """Register a substitute implementation for ``op`` when the call's
    static axes contain ``match`` (subset match, reference const-axes
    semantics)."""
    _solutions.setdefault(op, []).append((match, fn))


def clear_solutions() -> None:
    _solutions.clear()


def _find_solution(op: str, axes: Dict[str, Any]) -> Optional[Callable]:
    for match, fn in _solutions.get(op, []):
        if all(axes.get(k) == v for k, v in match.items()):
            return fn
    return None


def traced_api(fn: Callable = None, *, name: str = None) -> Callable:
    """Decorator adding trace-dump and solution-substitution hooks."""

    def deco(f):
        op = name or f.__qualname__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            if not (_trace_enabled() or _apply_enabled()):
                return f(*args, **kwargs)
            axes = _axes_of(args, kwargs)
            if _trace_enabled():
                _dump_trace(op, axes)
            if _apply_enabled():
                sub = _find_solution(op, axes)
                # substitution hit/miss metrics (same wiring as the
                # @flashinfer_api path, api_logging._instrumented_call)
                from flashinfer_tpu import obs

                obs.counter_inc(
                    "trace.solution_hits" if sub is not None
                    else "trace.solution_misses", op=op)
                if sub is not None:
                    return sub(*args, **kwargs)
            return f(*args, **kwargs)

        return wrapper

    if fn is not None:
        return deco(fn)
    return deco


def build_fi_trace_fn(op_name: str, reference_fn: Callable = None, **_):
    """Reference fi_trace.build_fi_trace_fn: builds the traced wrapper
    for an op from its TraceTemplate.  Here tracing is the
    :func:`traced_api` decorator, so this returns it applied."""
    if reference_fn is None:
        return lambda fn: traced_api(fn, name=op_name)
    return traced_api(reference_fn, name=op_name)
