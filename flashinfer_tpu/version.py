"""Version of flashinfer-tpu.

Mirrors the reference's ``version.txt`` single-source-of-truth
(/root/reference/version.txt) but tracked in-package.
"""

__version__ = "0.1.0"
