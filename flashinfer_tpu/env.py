"""Environment/flag system for flashinfer-tpu.

The reference configures itself purely through environment variables read at
import/call time (survey of ``flashinfer/jit/env.py:58-110``,
``flashinfer/api_logging.py:47-66``).  We keep the same design: a small,
documented set of ``FLASHINFER_TPU_*`` env vars, read lazily so tests can
monkeypatch them.

Principal flags
---------------
FLASHINFER_TPU_LOGLEVEL       int 0-10, api-call logging verbosity (default 0)
FLASHINFER_TPU_BACKEND        "auto" | "pallas" | "xla" — global backend override
FLASHINFER_TPU_INTERPRET      "1" forces Pallas interpret mode (CPU debugging)
FLASHINFER_TPU_CACHE_DIR      XLA persistent compilation cache directory
                              (the TPU analogue of the reference JIT cache,
                              ``flashinfer/jit/env.py:148-163``)
FLASHINFER_TPU_DUMP_DIR       directory for api-logging tensor dumps
"""

from __future__ import annotations

import os
from pathlib import Path


def _env(name: str, default: str) -> str:
    return os.environ.get(name, default)


def log_level() -> int:
    try:
        return int(_env("FLASHINFER_TPU_LOGLEVEL", "0"))
    except ValueError:
        return 0


def backend_override() -> str:
    """Global backend selector: "auto" (default), "pallas", or "xla"."""
    v = _env("FLASHINFER_TPU_BACKEND", "auto").lower()
    if v not in ("auto", "pallas", "xla"):
        raise ValueError(f"FLASHINFER_TPU_BACKEND must be auto|pallas|xla, got {v!r}")
    return v


def force_interpret() -> bool:
    return _env("FLASHINFER_TPU_INTERPRET", "0") == "1"


def cache_dir() -> Path:
    d = _env(
        "FLASHINFER_TPU_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "flashinfer_tpu"),
    )
    return Path(d)


def dump_dir() -> Path:
    return Path(_env("FLASHINFER_TPU_DUMP_DIR", str(cache_dir() / "dumps")))


_CACHE_ENABLED = False


def enable_compilation_cache() -> None:
    """Enable the XLA persistent compilation cache.

    TPU analogue of the reference's on-disk JIT cache + cubin artifactory
    (``flashinfer/jit/core.py:225-321``, ``flashinfer/artifacts.py``): compiled
    executables are persisted under :func:`cache_dir` and re-loaded with no
    recompile on subsequent processes.
    """
    global _CACHE_ENABLED
    if _CACHE_ENABLED:
        return
    import jax

    d = cache_dir() / "xla_cache"
    d.mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(d))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    _CACHE_ENABLED = True


def apply_platform_from_env() -> None:
    """Honor ``JAX_PLATFORMS`` even under the axon sitecustomize.

    The axon image pre-registers its PJRT plugin at interpreter start, so
    the env var alone does not move a script off the TPU tunnel (repo
    memory ``axon-env-gotchas``) — standalone scripts that want CPU must
    force it via config BEFORE any device use.  CPU also implies Pallas
    interpret mode (Mosaic cannot target CPU).
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax

    jax.config.update("jax_platforms", plat)
    if plat == "cpu":
        os.environ.setdefault("FLASHINFER_TPU_INTERPRET", "1")
