"""Testing & perf-measurement utilities.

TPU re-design of the reference's ``flashinfer/testing/utils.py`` — the eager
reference attention used by every correctness test, tolerance helpers, the
FLOPs/bytes calculators (testing/utils.py:456-751), and a device-time
benchmark timer (testing/utils.py:774-1546; cold-L2 rotation is replaced by
buffer donation + ``block_until_ready`` median timing, the TPU-appropriate
protocol per BASELINE.md).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# Reference attention (pure jnp, fp32 accumulation)
# ---------------------------------------------------------------------------


def attention_ref(
    q: jax.Array,  # [qo_len, num_qo_heads, head_dim]
    k: jax.Array,  # [kv_len, num_kv_heads, head_dim]
    v: jax.Array,  # [kv_len, num_kv_heads, head_dim_vo]
    causal: bool = False,
    sm_scale: Optional[float] = None,
    logits_soft_cap: float = 0.0,
    window_left: int = -1,
    custom_mask: Optional[jax.Array] = None,  # [qo_len, kv_len] bool
    return_lse: bool = False,
):
    """Eager attention reference with GQA head-group broadcast.

    Matches the semantics of the reference's test helper attention
    (e.g. tests/attention/test_batch_prefill_kernels.py): causal alignment is
    bottom-right (query i attends to kv <= kv_len - qo_len + i), ALiBi and
    soft-cap applied pre-softmax, LSE returned in natural log units.
    """
    qo_len, num_qo_heads, head_dim = q.shape
    kv_len, num_kv_heads, _ = k.shape
    group = num_qo_heads // num_kv_heads
    if sm_scale is None:
        sm_scale = 1.0 / float(head_dim) ** 0.5

    qf = q.astype(jnp.float32)
    kf = jnp.repeat(k.astype(jnp.float32), group, axis=1)
    vf = jnp.repeat(v.astype(jnp.float32), group, axis=1)

    # [heads, qo, kv]
    logits = jnp.einsum("qhd,khd->hqk", qf, kf) * sm_scale
    if logits_soft_cap > 0.0:
        logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)

    mask = jnp.ones((qo_len, kv_len), dtype=bool)
    if causal:
        qi = jnp.arange(qo_len)[:, None]
        ki = jnp.arange(kv_len)[None, :]
        mask = mask & (ki <= qi + (kv_len - qo_len))
    if window_left >= 0:
        qi = jnp.arange(qo_len)[:, None]
        ki = jnp.arange(kv_len)[None, :]
        mask = mask & (ki >= qi + (kv_len - qo_len) - window_left)
    if custom_mask is not None:
        mask = mask & custom_mask

    logits = jnp.where(mask[None], logits, -jnp.inf)
    lse = jax.nn.logsumexp(logits, axis=-1)  # [heads, qo]
    out = jnp.einsum("hqk,khd->qhd", jax.nn.softmax(logits, axis=-1), vf)
    out = out.astype(q.dtype)
    if return_lse:
        return out, jnp.transpose(lse)  # [qo, heads]
    return out


def assert_close(actual, expected, rtol=1e-3, atol=1e-3, name=""):
    np.testing.assert_allclose(
        np.asarray(actual, dtype=np.float32),
        np.asarray(expected, dtype=np.float32),
        rtol=rtol,
        atol=atol,
        err_msg=name,
    )


# ---------------------------------------------------------------------------
# FLOPs / bytes calculators (reference testing/utils.py:456-751)
# ---------------------------------------------------------------------------


def attention_flops(
    qo_len: int, kv_len: int, num_qo_heads: int, head_dim_qk: int,
    head_dim_vo: int, causal: bool = False,
) -> float:
    """Total attention FLOPs (QK^T + PV) for one request."""
    if causal and qo_len > 1:
        # each query i sees kv_len - qo_len + i + 1 keys
        attended = qo_len * (kv_len - qo_len) + (qo_len * (qo_len + 1)) // 2
    else:
        attended = qo_len * kv_len
    return 2.0 * attended * num_qo_heads * (head_dim_qk + head_dim_vo)


def attention_bytes(
    qo_len: int, kv_len: int, num_qo_heads: int, num_kv_heads: int,
    head_dim_qk: int, head_dim_vo: int, dtype_bytes: int = 2,
) -> float:
    """HBM bytes moved by one attention call (q+k+v+o), decode-bound metric."""
    q = qo_len * num_qo_heads * head_dim_qk
    k = kv_len * num_kv_heads * head_dim_qk
    v = kv_len * num_kv_heads * head_dim_vo
    o = qo_len * num_qo_heads * head_dim_vo
    return float((q + k + v + o) * dtype_bytes)


# ---------------------------------------------------------------------------
# Benchmark timer
# ---------------------------------------------------------------------------


def bench_fn(
    fn: Callable,
    *args,
    warmup: int = 3,
    iters: int = 20,
    **kwargs,
) -> float:
    """Median wall time per call in seconds, device-synchronized.

    TPU analogue of ``bench_gpu_time`` (reference testing/utils.py:774):
    compile+warm first, then time each iteration with ``block_until_ready``.

    .. warning:: Under a remote/tunneled device runtime (e.g. the axon TPU
       tunnel) ``block_until_ready`` can return before device execution
       finishes, and per-call dispatch overhead (~ms) dwarfs kernel time.
       Use :func:`bench_fn_device` for hardware-honest numbers there.
    """
    out = fn(*args, **kwargs)  # compile
    for _ in range(max(warmup - 1, 0)):
        out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def bench_fn_device(
    fn: Callable,
    x: jax.Array,
    *rest,
    iters_low: int = 8,
    iters_high: int = 40,
    repeats: int = 3,
) -> float:
    """Device-honest per-call time via an in-jit iteration loop + slope fit.

    Runs ``fn`` ``iters`` times inside one jitted ``lax.fori_loop``, with a
    data-dependency chain that defeats both loop hoisting and dead-code
    elimination:

    * the input ``x`` is perturbed by ``carry * 1e-30`` so iteration *i*
      depends on iteration *i-1* (no cross-iteration parallelism / CSE);
    * the carry is a full-output reduction, so XLA must compute every
      element of ``fn``'s output (slicing the carry from one element lets
      XLA dead-code-eliminate the rest of the computation).

    Per-iteration time is the **slope** ``(t(iters_high) - t(iters_low)) /
    (iters_high - iters_low)``, which cancels fixed dispatch/transfer
    overhead exactly — required on tunneled devices where per-call overhead
    is ~4-5 ms and ``block_until_ready`` is not a reliable execution fence.
    Validated on v5e: 8192-cube bf16 matmul measures 189 TFLOP/s (96% of
    peak) and a fused streaming read measures 102% of the 819 GB/s HBM spec.

    ``fn`` takes the (perturbed) ``x`` plus ``rest`` and returns an array or
    pytree.  Every large operand MUST be passed through ``rest`` (or ``x``),
    never closed over: jit embeds closure-captured device arrays as HLO
    constants, and a GB-scale KV cache serialized into the HLO blows up the
    (remote) compile.  Reduction traffic is fused and adds no HBM round-trip
    for the dominant input reads.
    """
    @jax.jit
    def _timed(n, x, *rest):
        # n is TRACED: one compiled while-loop serves every iteration
        # count (one remote compile per bench call instead of two per
        # escalation stage), and the lo/hi measurements of a pair are
        # guaranteed to run the SAME executable
        def body(i, carry):
            # cast keeps x's dtype (bf16 + f32 would silently promote
            # and benchmark an f32 kernel variant)
            out = fn(x + (carry * 1e-30).astype(x.dtype), *rest)
            leaves = jax.tree_util.tree_leaves(out)
            return sum(
                jnp.sum(leaf.astype(jnp.float32)) for leaf in leaves
            ) * 1e-30
        return jax.lax.fori_loop(0, n, body, jnp.float32(0))

    # Measurement reality on the axon tunnel (characterized 2026-07-31,
    # scripts/exp_decode_step.py): per-call dispatch is ~80 ms with
    # +-3-5 ms jitter, and multi-second DEGRADED WINDOWS exist in which
    # every invocation runs ~100x slower per iteration (a ~1.8 ms phantom
    # op cost that poisoned whole median-of-repeats measurements and
    # migrated between variants across runs).  Two defenses:
    #
    # 1. ESCALATION: the slope numerator (t_hi - t_lo) must clear the
    #    dispatch jitter by a wide margin; for microsecond ops 32 extra
    #    iterations (~0.5 ms) is far below the +-5 ms floor, so iteration
    #    counts escalate x8 until the numerator >= 25 ms or the cap.
    # 2. FLOORS + CROSS-SCALE CONFIRMATION: within a stage, mins over
    #    1 + `repeats` (lo, hi) cycles reject stalls shorter than the
    #    stage; a degraded window swallowing a WHOLE low stage is caught
    #    by re-measuring at the next scale up and keeping the smaller
    #    positive slope (the true slope is scale-invariant and the
    #    poison is positive-only).
    _MIN_NUMERATOR_S = 0.025
    _SCALES = (1, 8, 64, 512, 4096)

    def _time_once(n):
        t0 = time.perf_counter()
        float(_timed(n, x, *rest))
        return time.perf_counter() - t0

    def _stage(scale, cycles):
        """Floored (t_lo, t_hi, slope) over `cycles` (lo, hi) pairs."""
        n_lo, n_hi = iters_low * scale, iters_high * scale
        t_lo_min = float("inf")
        t_hi_min = float("inf")
        for _ in range(cycles):
            t_lo_min = min(t_lo_min, _time_once(n_lo))
            t_hi_min = min(t_hi_min, _time_once(n_hi))
        return t_lo_min, t_hi_min, (t_hi_min - t_lo_min) / (n_hi - n_lo)

    float(_timed(iters_low, x, *rest))  # the one compile, before timing
    for idx, scale in enumerate(_SCALES):
        # probe: one (lo, hi) cycle decides whether this scale's delta
        # can clear the jitter floor at all
        t_lo_min, t_hi_min, slope = _stage(scale, 1)
        if (t_hi_min - t_lo_min) < _MIN_NUMERATOR_S and scale != _SCALES[-1]:
            continue
        # full measurement: floors over `repeats` more cycles (a min is
        # immune to positive stalls); acceptance is judged on the FLOORED
        # numerator, so a stall inflating the probe alone cannot lock in
        # an under-escalated scale
        t_lo2, t_hi2, slope = _stage(scale, repeats)
        t_lo_min, t_hi_min = min(t_lo_min, t_lo2), min(t_hi_min, t_hi2)
        slope = (t_hi_min - t_lo_min) / (iters_high - iters_low) / scale
        if (t_hi_min - t_lo_min) >= _MIN_NUMERATOR_S or scale == _SCALES[-1]:
            # CROSS-SCALE CONFIRMATION: a degraded window spanning this
            # whole stage (~1 s at low scales, shorter than the observed
            # windows) would pass the floored check with a ~100x-inflated
            # slope.  The true slope is scale-invariant and the poison is
            # positive-only, so re-measure once at the next scale up and
            # keep the smaller positive slope — a window rarely spans
            # both stages, and floors at each stage reject stalls within
            # it.
            if scale != _SCALES[-1] and slope > 0:
                _, _, slope_c = _stage(_SCALES[idx + 1], max(repeats // 2, 1))
                if 0 < slope_c < slope:
                    slope = slope_c
            break
    if slope <= 0:
        # kernel faster than dispatch jitter even at the escalation cap:
        # report the amortized upper bound rather than nonsense throughput
        return t_hi_min / (iters_high * scale)
    return slope


def bench_steps_device(
    make_loop: Callable,
    *args,
    iters_low: int = 4,
    iters_high: int = 12,
    repeats: int = 3,
) -> float:
    """Slope-timed per-step cost of a loop that CARRIES its state.

    ``make_loop(n)`` must return a jitted callable running ``n`` dependent
    steps with mutable state threaded through a ``lax.scan`` /
    ``while_loop`` carry and a scalar-reducible output.  Use this instead
    of :func:`bench_fn_device` for stateful step benchmarks (serving
    loops with KV caches): ``bench_fn_device`` re-feeds identical inputs
    every iteration, so any buffer the step updates is loop-invariant and
    the update degenerates into a full-buffer copy per iteration — an
    artifact a donation-based serving loop never pays.  A carry lets
    XLA's while-body input/output aliasing update the state in place, so
    the measured step includes only the writes the real loop performs.

    Per-step time is the ``(t(hi) - t(lo)) / (hi - lo)`` slope, which
    cancels fixed dispatch/compile-cache/transfer overhead (see
    :func:`bench_fn_device`; ``float()`` on the result is the execution
    fence — ``block_until_ready`` is unreliable over tunneled devices).
    """
    lo, hi = make_loop(iters_low), make_loop(iters_high)
    float(lo(*args))  # compile both before timing
    float(hi(*args))
    slopes = []
    t_hi_min = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(lo(*args))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(hi(*args))
        t_hi = time.perf_counter() - t0
        t_hi_min = min(t_hi_min, t_hi)
        slopes.append((t_hi - t_lo) / (iters_high - iters_low))
    slope = float(np.median(slopes))
    if slope <= 0:
        return t_hi_min / iters_high
    return slope
