from flashinfer_tpu.testing.utils import (  # noqa: F401
    assert_close,
    attention_ref,
    bench_fn,
    bench_fn_device,
    bench_steps_device,
    attention_flops,
    attention_bytes,
)
