"""Production autotuning runbook: ``flashinfer_tpu tune``.

The reference ships per-arch tuned configs produced by an offline tuning
run (``flashinfer/tuning_configs/`` + the autotuner harness); this module
is the TPU analogue as a CLI command rather than a scratch script
(VERDICT r3 #9: the config-production path must be invokable by the
recovery watchdog with no manual merge step).

Ordering follows the chip-health discipline from the wedge history:
cheap/known-good kernel families first, flash-kernel block variants LAST,
so a late Mosaic hang still leaves a mergeable config on disk after every
completed stage (``merge_into_shipped`` runs incrementally).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional


def _shipped_path(stem: str) -> Path:
    return Path(__file__).parent / "tuning_configs" / f"{stem}.json"


def merge_into_shipped(stem: Optional[str] = None) -> Path:
    """Merge the live AutoTuner cache into ``tuning_configs/<stem>.json``.

    Freshly profiled tactics override shipped entries of the same key;
    everything else is preserved.  Returns the written path."""
    from flashinfer_tpu.autotuner import AutoTuner, _device_config_key
    from flashinfer_tpu.utils import atomic_write_text

    stem = stem or _device_config_key()
    if stem is None:
        raise RuntimeError(
            "cannot map this device_kind to a tuning-config stem; pass one "
            "explicitly (e.g. 'v5e')"
        )
    tuner = AutoTuner.get()
    tuner._load()
    path = _shipped_path(stem)
    try:
        shipped = json.loads(path.read_text())
    except Exception:
        shipped = {
            "comment": f"Pre-tuned tactics for TPU {stem} "
                       "(reference analogue: flashinfer/tuning_configs/).",
            "tactics": {},
        }
    shipped.setdefault("tactics", {}).update(tuner._cache)
    atomic_write_text(path, json.dumps(shipped, indent=1))
    return path


def run_tuning_workload(stages: Optional[list] = None,
                        merge_stem: Optional[str] = None,
                        log=print) -> Path:
    """Profile the serving-critical op families on the live chip and write
    the shipped config after EVERY stage (a late wedge keeps earlier
    stages' tactics).  Returns the config path."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    import flashinfer_tpu as fi
    from flashinfer_tpu.autotuner import autotune

    H, HQ, HKV, D, PS = 4096, 32, 8, 128, 16  # Llama-3-8B shapes

    def stage_norm():
        w = jnp.ones((H,), jnp.bfloat16)
        for t in (1024, 4096, 8192):
            x = jnp.asarray(np.random.randn(t, H), jnp.bfloat16)
            fi.rmsnorm(x, w)
            fi.fused_add_rmsnorm(x, x, w)
            log(f"norm tuned t={t}")

    def stage_decode():
        for bs, ctx in ((64, 4096), (16, 4096), (64, 8192), (256, 2048)):
            pages_per_req = ctx // PS
            npages = bs * pages_per_req + 1
            k_cache = jnp.asarray(
                np.random.randn(npages, HKV, PS, D) / 8, jnp.bfloat16)
            v_cache = jnp.asarray(
                np.random.randn(npages, HKV, PS, D) / 8, jnp.bfloat16)
            wrap = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="HND")
            wrap.plan(
                np.arange(bs + 1) * pages_per_req,
                np.arange(bs * pages_per_req),
                np.full((bs,), PS),
                HQ, HKV, D, PS, q_data_type=jnp.bfloat16,
            )
            q = jnp.asarray(np.random.randn(bs, HQ, D), jnp.bfloat16)
            wrap.run(q, (k_cache, v_cache))
            log(f"decode tuned bs={bs} ctx={ctx}")

    def stage_prefill():
        # shapes match bench.py's prefill sweep exactly (a tuned tactic
        # only helps a measured row if the tactic KEY covers that shape)
        for bs, qlen, ctx in ((4, 1024, 4096), (8, 512, 4096),
                              (1, 8192, 8192), (2, 2048, 8192),
                              (16, 256, 2048)):
            pages_per_req = ctx // PS
            npages = bs * pages_per_req + 1
            k_cache = jnp.asarray(
                np.random.randn(npages, HKV, PS, D) / 8, jnp.bfloat16)
            v_cache = jnp.asarray(
                np.random.randn(npages, HKV, PS, D) / 8, jnp.bfloat16)
            wrap = fi.BatchPrefillWithPagedKVCacheWrapper(kv_layout="HND")
            wrap.plan(
                np.arange(bs + 1) * qlen,
                np.arange(bs + 1) * pages_per_req,
                np.arange(bs * pages_per_req),
                np.full((bs,), PS),
                HQ, HKV, D, PS, causal=True,
            )
            q = jnp.asarray(np.random.randn(bs * qlen, HQ, D), jnp.bfloat16)
            wrap.run(q, (k_cache, v_cache))
            log(f"fused prefill tuned bs={bs} qlen={qlen}")

    def stage_flash():
        # LAST: the most first-compiles — a hang here keeps prior stages
        for t in (2048, 4096, 8192):
            q = jnp.asarray(np.random.randn(t, HQ, D), jnp.bfloat16)
            k = jnp.asarray(np.random.randn(t, HKV, D), jnp.bfloat16)
            v = jnp.asarray(np.random.randn(t, HKV, D), jnp.bfloat16)
            fi.single_prefill_with_kv_cache(q, k, v, causal=True)
            log(f"flash tuned t={t}")

    def stage_moe():
        # Mixtral-8x7B geometry at serving token counts; fused_moe's tile
        # resolution profiles per-GEMM candidates under autotune() (see
        # ops/moe_gmm.tune_tiles) and the decode-side prefetch tactic is
        # covered by stage_decode's wrapper runs
        from flashinfer_tpu import fused_moe as moe_pkg
        from flashinfer_tpu.quantization import quantize_int8

        E, I, K = 8, 14336, 2
        w1 = jnp.asarray(
            np.random.randn(E, H, 2 * I) * 0.02, jnp.bfloat16)
        w2 = jnp.asarray(
            np.random.randn(E, I, H) * 0.02, jnp.bfloat16)
        w1q, w1s = quantize_int8(w1, axis=1)
        w2q, w2s = quantize_int8(w2, axis=1)
        for t in (64, 256, 1024):
            x = jnp.asarray(np.random.randn(t, H), jnp.bfloat16)
            logits = jnp.asarray(np.random.randn(t, E), jnp.float32)
            wts, ids = moe_pkg.route_renormalize(logits, K)
            moe_pkg.fused_moe(x, w1, w2, wts, ids, E, backend="gmm",
                              gather_variant="sorted")
            moe_pkg.fused_moe(x, w1q, w2q, wts, ids, E, w1_scale=w1s,
                              w2_scale=w2s, backend="gmm",
                              gather_variant="sorted")
            log(f"moe tiles tuned T={t}")

    def stage_mla():
        # DeepSeek-V3 absorbed-MLA decode at the bench shape; profiles the
        # mla_decode.layout tactic (split vs packed scratch)
        DC, DP, MPS, MH = 512, 64, 16, 128
        for bs, ctx in ((64, 4096), (16, 4096)):
            ppr = ctx // MPS
            npages = bs * ppr + 1
            ckv = jnp.asarray(
                np.random.randn(npages, MPS, DC) / 8, jnp.bfloat16)
            kpe = jnp.asarray(
                np.pad(np.random.randn(npages, MPS, DP) / 8,
                       ((0, 0), (0, 0), (0, 128 - DP))), jnp.bfloat16)
            wrap = fi.mla.BatchMLAPagedAttentionWrapper()
            wrap.plan(
                np.arange(bs + 1, dtype=np.int32),
                np.arange(bs + 1, dtype=np.int32) * ppr,
                np.arange(bs * ppr, dtype=np.int32),
                np.full((bs,), ctx, np.int32),
                MH, DC, DP, MPS, False, 1.0 / (DC + DP) ** 0.5,
                jnp.bfloat16, jnp.bfloat16,
            )
            qn = jnp.asarray(np.random.randn(bs, MH, DC) / 8, jnp.bfloat16)
            qp = jnp.asarray(np.random.randn(bs, MH, DP) / 8, jnp.bfloat16)
            wrap.run(qn, qp, ckv, kpe)
            log(f"mla layout tuned bs={bs} ctx={ctx}")

    all_stages = [
        ("norm", stage_norm),
        ("decode", stage_decode),
        ("prefill", stage_prefill),
        ("moe", stage_moe),
        # mla after moe: the packed-layout candidate is a first Mosaic
        # compile (wedge-ordering discipline — risky compiles late, so a
        # hang cannot cost the proven stages' tactics); flash stays last
        ("mla", stage_mla),
        ("flash", stage_flash),
    ]
    selected = (
        [s for s in all_stages if s[0] in stages] if stages else all_stages
    )
    log(f"device: {jax.devices()[0].device_kind}")
    path = None
    with autotune():
        for name, fn in selected:
            fn()
            path = merge_into_shipped(merge_stem)
            log(f"stage {name} merged -> {path}")
    return path
