"""Quantization ops: packbits + fp8/int8 quantize/dequantize.

TPU re-design of the reference quantization layer
(``flashinfer/quantization/`` packbits.py + fp8_quantization.py;
``include/flashinfer/quantization.cuh``).  NVFP4/MXFP4 block formats have no
v5 hardware path; the supported low-precision surface here is fp8 (storage)
and int8 (storage + native MXU), with per-tensor and per-channel scaling.
Block-scaled int4 packing mirrors the NVFP4 role and lands in a later round.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("bitorder",))
def packbits(x: jax.Array, bitorder: str = "big") -> jax.Array:
    """Pack a boolean/0-1 int array into uint8, 8 elements per byte
    (reference ``flashinfer.quantization.packbits``, quantization.cuh)."""
    x = x.reshape(-1).astype(jnp.uint8)
    pad = (-x.shape[0]) % 8
    x = jnp.pad(x, (0, pad))
    x = x.reshape(-1, 8)
    if bitorder == "big":
        weights = jnp.array([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    else:
        weights = jnp.array([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)
    return jnp.sum(x * weights[None, :], axis=1).astype(jnp.uint8)


def segment_packbits(
    x: jax.Array, indptr: jax.Array, bitorder: str = "big"
) -> Tuple[jax.Array, jax.Array]:
    """Per-segment packbits (reference ``segment_packbits``): each segment
    is packed independently so segment boundaries stay byte-aligned.
    Returns (packed, new_indptr)."""
    import numpy as np

    indptr_np = np.asarray(indptr)
    segs = []
    new_indptr = [0]
    for r in range(len(indptr_np) - 1):
        seg = x[int(indptr_np[r]) : int(indptr_np[r + 1])]
        packed = packbits(seg, bitorder)
        segs.append(packed)
        new_indptr.append(new_indptr[-1] + packed.shape[0])
    return jnp.concatenate(segs), jnp.asarray(new_indptr, dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("dtype",))
def quantize_fp8_per_tensor(
    x: jax.Array, dtype=jnp.float8_e4m3fn
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-tensor fp8 quantization -> (values, scale) with
    ``x ~= values * scale``."""
    finfo = jnp.finfo(dtype)
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    scale = jnp.maximum(amax / float(finfo.max), 1e-12)
    q = jnp.clip(
        x.astype(jnp.float32) / scale, float(finfo.min), float(finfo.max)
    ).astype(dtype)
    return q, scale.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("dtype", "axis"))
def quantize_fp8_per_channel(
    x: jax.Array, dtype=jnp.float8_e4m3fn, axis: int = -1
) -> Tuple[jax.Array, jax.Array]:
    finfo = jnp.finfo(dtype)
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / float(finfo.max), 1e-12)
    q = jnp.clip(
        x.astype(jnp.float32) / scale, float(finfo.min), float(finfo.max)
    ).astype(dtype)
    return q, scale.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def dequantize_fp8(q: jax.Array, scale: jax.Array, out_dtype=jnp.bfloat16):
    return (q.astype(jnp.float32) * scale).astype(out_dtype)


def quantize_symmetric_int8(x, scale):
    """Fixed-scale symmetric int8 quantize: round(x/scale) saturated to
    [-127, 127].  The single definition of the int8 KV-cache value format —
    quantizing appends and model-level cache writes must all match the
    decode kernel's dequant (int8 * scale)."""
    return jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("axis",))
def quantize_int8(
    x: jax.Array, axis: int = -1
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-channel int8 quantization -> (values, scale)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return q, scale.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Block-int4 ("fp4-class") format: the TPU mapping of NVFP4/MXFP4
# (reference flashinfer/quantization/fp4_quantization.py).  4-bit symmetric
# values in 16-element blocks with an fp32 scale per block, two nibbles
# packed per int8 byte — same storage footprint as NVFP4 (0.5 B/elem +
# scales), dequantized in-register to bf16 for the MXU.
# ---------------------------------------------------------------------------

FP4_BLOCK = 16


@functools.partial(jax.jit, static_argnames=("block_size",))
def quantize_fp4(
    x: jax.Array,  # [..., K] with K % (2 and block_size) == 0
    block_size: int = FP4_BLOCK,
) -> Tuple[jax.Array, jax.Array]:
    """Block-scaled 4-bit quantization -> (packed [..., K//2] int8,
    scales [..., K//block_size] f32)."""
    shape = x.shape
    K = shape[-1]
    xf = x.astype(jnp.float32).reshape(*shape[:-1], K // block_size, block_size)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 7.0, 1e-12)
    q = jnp.clip(jnp.round(xf / scale), -7, 7).astype(jnp.int8)
    q = q.reshape(*shape[:-1], K)
    lo = q[..., 0::2] & 0x0F
    hi = (q[..., 1::2] & 0x0F) << 4
    packed = (lo | hi).astype(jnp.int8)
    return packed, scale[..., 0].astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("block_size", "out_dtype"))
def dequantize_fp4(
    packed: jax.Array,  # [..., K//2] int8
    scales: jax.Array,  # [..., K//block_size] f32
    block_size: int = FP4_BLOCK,
    out_dtype=jnp.bfloat16,
) -> jax.Array:
    lo = (packed << 4) >> 4  # sign-extend low nibble (arithmetic shift)
    hi = packed >> 4
    K = packed.shape[-1] * 2
    q = jnp.stack([lo, hi], axis=-1).reshape(*packed.shape[:-1], K)
    qf = q.astype(jnp.float32).reshape(
        *packed.shape[:-1], K // block_size, block_size
    )
    out = qf * scales[..., None]
    return out.reshape(*packed.shape[:-1], K).astype(out_dtype)
