"""Gated Delta Net (GDN) and Kimi Delta Attention (KDA) recurrences.

TPU re-design of the reference's linear-attention families:
- GDN (Qwen3-Next; reference ``flashinfer/gdn_decode.py`` /
  ``gdn_prefill.py`` / ``gdn_kernels/``): gated delta rule over a matrix
  state ``S [dk, dv]`` per head — decay first, then delta-correct against
  the *decayed* state (standard Gated DeltaNet form):
      S~   = alpha_t * S_{t-1}
      S_t  = S~ + beta_t * k_t (v_t - S~^T k_t)^T
      o_t  = S_t^T q_t
  with scalar-per-head decay ``alpha`` and update gate ``beta``.
- KDA (Kimi; reference ``flashinfer/kda_decode.py`` /
  ``kda_kernels/recurrent_kda.py``): same delta rule with *per-channel*
  decay ``alpha_t [dk]`` (finer-grained forgetting).

Decode-step ops + lax.scan prefill forms; the reference's chunked
Blackwell-DSL kernels map to a future Pallas chunked scan — these are the
semantics oracles and the serving decode path (one small einsum per step,
XLA-fused).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@jax.jit
def gdn_decode_step(
    state: jax.Array,  # [B, H, dk, dv]
    q: jax.Array,  # [B, H, dk]
    k: jax.Array,  # [B, H, dk]
    v: jax.Array,  # [B, H, dv]
    alpha: jax.Array,  # [B, H] decay gate in [0, 1]
    beta: jax.Array,  # [B, H] update gate
) -> Tuple[jax.Array, jax.Array]:
    """One GDN decode step -> (o [B, H, dv], new_state)."""
    s = state.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    a = alpha.astype(jnp.float32)[..., None, None]
    b = beta.astype(jnp.float32)[..., None, None]
    s = a * s
    # delta rule: write (v - S^T k) at key k
    pred = jnp.einsum("bhkv,bhk->bhv", s, kf)
    s = s + b * jnp.einsum("bhk,bhv->bhkv", kf, vf - pred)
    o = jnp.einsum("bhkv,bhk->bhv", s, q.astype(jnp.float32))
    return o.astype(q.dtype), s.astype(state.dtype)


@jax.jit
def gdn_prefill(
    q: jax.Array,  # [B, L, H, dk]
    k: jax.Array,  # [B, L, H, dk]
    v: jax.Array,  # [B, L, H, dv]
    alpha: jax.Array,  # [B, L, H]
    beta: jax.Array,  # [B, L, H]
    initial_state: Optional[jax.Array] = None,  # [B, H, dk, dv]
) -> Tuple[jax.Array, jax.Array]:
    """Sequential GDN scan -> (o [B, L, H, dv], final_state)."""
    B, L, H, dk = q.shape
    dv = v.shape[-1]
    if initial_state is None:
        initial_state = jnp.zeros((B, H, dk, dv), jnp.float32)

    def step(s, inp):
        qt, kt, vt, at, bt = inp
        o, s = gdn_decode_step(s, qt, kt, vt, at, bt)
        return s, o

    final, ys = jax.lax.scan(
        step, initial_state.astype(jnp.float32),
        tuple(jnp.moveaxis(t, 1, 0) for t in (q, k, v, alpha, beta)),
    )
    return jnp.moveaxis(ys, 0, 1), final


@jax.jit
def kda_decode_step(
    state: jax.Array,  # [B, H, dk, dv]
    q: jax.Array,  # [B, H, dk]
    k: jax.Array,  # [B, H, dk]
    v: jax.Array,  # [B, H, dv]
    alpha: jax.Array,  # [B, H, dk] per-channel decay
    beta: jax.Array,  # [B, H] update gate
) -> Tuple[jax.Array, jax.Array]:
    """One KDA decode step (per-channel decay delta rule)."""
    s = state.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    a = alpha.astype(jnp.float32)[..., None]  # [B, H, dk, 1]
    b = beta.astype(jnp.float32)[..., None, None]
    s = a * s
    pred = jnp.einsum("bhkv,bhk->bhv", s, kf)
    s = s + b * jnp.einsum("bhk,bhv->bhkv", kf, vf - pred)
    o = jnp.einsum("bhkv,bhk->bhv", s, q.astype(jnp.float32))
    return o.astype(q.dtype), s.astype(state.dtype)


@jax.jit
def kda_prefill(
    q: jax.Array,  # [B, L, H, dk]
    k: jax.Array,
    v: jax.Array,  # [B, L, H, dv]
    alpha: jax.Array,  # [B, L, H, dk]
    beta: jax.Array,  # [B, L, H]
    initial_state: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    B, L, H, dk = q.shape
    dv = v.shape[-1]
    if initial_state is None:
        initial_state = jnp.zeros((B, H, dk, dv), jnp.float32)

    def step(s, inp):
        qt, kt, vt, at, bt = inp
        o, s = kda_decode_step(s, qt, kt, vt, at, bt)
        return s, o

    final, ys = jax.lax.scan(
        step, initial_state.astype(jnp.float32),
        tuple(jnp.moveaxis(t, 1, 0) for t in (q, k, v, alpha, beta)),
    )
    return jnp.moveaxis(ys, 0, 1), final
