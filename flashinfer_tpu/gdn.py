"""Gated Delta Net (GDN) and Kimi Delta Attention (KDA) recurrences.

TPU re-design of the reference's linear-attention families:
- GDN (Qwen3-Next; reference ``flashinfer/gdn_decode.py`` /
  ``gdn_prefill.py`` / ``gdn_kernels/``): gated delta rule over a matrix
  state ``S [dk, dv]`` per head — decay first, then delta-correct against
  the *decayed* state (standard Gated DeltaNet form):
      S~   = alpha_t * S_{t-1}
      S_t  = S~ + beta_t * k_t (v_t - S~^T k_t)^T
      o_t  = S_t^T q_t
  with scalar-per-head decay ``alpha`` and update gate ``beta``.
- KDA (Kimi; reference ``flashinfer/kda_decode.py`` /
  ``kda_kernels/recurrent_kda.py``): same delta rule with *per-channel*
  decay ``alpha_t [dk]`` (finer-grained forgetting).

Decode-step ops + lax.scan prefill forms; the reference's chunked
Blackwell-DSL kernels map to a future Pallas chunked scan — these are the
semantics oracles and the serving decode path (one small einsum per step,
XLA-fused).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@jax.jit
def gdn_decode_step(
    state: jax.Array,  # [B, H, dk, dv]
    q: jax.Array,  # [B, H, dk]
    k: jax.Array,  # [B, H, dk]
    v: jax.Array,  # [B, H, dv]
    alpha: jax.Array,  # [B, H] decay gate in [0, 1]
    beta: jax.Array,  # [B, H] update gate
) -> Tuple[jax.Array, jax.Array]:
    """One GDN decode step -> (o [B, H, dv], new_state)."""
    s = state.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    a = alpha.astype(jnp.float32)[..., None, None]
    b = beta.astype(jnp.float32)[..., None, None]
    s = a * s
    # delta rule: write (v - S^T k) at key k
    pred = jnp.einsum("bhkv,bhk->bhv", s, kf)
    s = s + b * jnp.einsum("bhk,bhv->bhkv", kf, vf - pred)
    o = jnp.einsum("bhkv,bhk->bhv", s, q.astype(jnp.float32))
    return o.astype(q.dtype), s.astype(state.dtype)


@jax.jit
def gdn_prefill(
    q: jax.Array,  # [B, L, H, dk]
    k: jax.Array,  # [B, L, H, dk]
    v: jax.Array,  # [B, L, H, dv]
    alpha: jax.Array,  # [B, L, H]
    beta: jax.Array,  # [B, L, H]
    initial_state: Optional[jax.Array] = None,  # [B, H, dk, dv]
) -> Tuple[jax.Array, jax.Array]:
    """Sequential GDN scan -> (o [B, L, H, dv], final_state)."""
    B, L, H, dk = q.shape
    dv = v.shape[-1]
    if initial_state is None:
        initial_state = jnp.zeros((B, H, dk, dv), jnp.float32)

    def step(s, inp):
        qt, kt, vt, at, bt = inp
        o, s = gdn_decode_step(s, qt, kt, vt, at, bt)
        return s, o

    final, ys = jax.lax.scan(
        step, initial_state.astype(jnp.float32),
        tuple(jnp.moveaxis(t, 1, 0) for t in (q, k, v, alpha, beta)),
    )
    return jnp.moveaxis(ys, 0, 1), final


def gdn_chunk_prefill(
    q: jax.Array,  # [B, L, H, dk]
    k: jax.Array,
    v: jax.Array,  # [B, L, H, dv]
    alpha: jax.Array,  # [B, L, H] decay in (0, 1]
    beta: jax.Array,  # [B, L, H] update gate
    chunk_size: int = 64,
    initial_state: Optional[jax.Array] = None,  # [B, H, dk, dv]
    backend: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Chunked gated-delta-rule prefill (the WY/UT-transform form the
    reference's Blackwell GDN kernels implement, flashinfer/gdn_kernels/).

    Within a chunk, the sequentially-dependent written values
    ``u_i = beta_i (v_i - (alpha_i S_{i-1})^T k_i)`` satisfy a unit-lower-
    triangular system ``(I + C) U = rhs`` with
    ``C[i,j] = beta_i (D_i/D_j) (k_j . k_i)`` (D = in-chunk decay products),
    solved with one triangular solve per (batch, head, chunk); outputs and
    boundary states are then plain matmuls — O(L*chunk) FLOPs on the MXU
    with O(L/chunk) sequential depth.  Matches ``gdn_prefill`` exactly
    (same recurrence), requires ``L % chunk_size == 0``.

    ``backend="pallas"`` (or env ``FLASHINFER_TPU_GDN_BACKEND=pallas``)
    routes to the fully-fused VMEM-resident kernel
    (``ops/gdn_kernel.py``; chunk 128, 128-aligned dims, normalized-key
    stability domain — see its docstring).  ``"auto"`` resolves to the
    kernel on eligible shapes: the banked v5e A/B (BENCH_BANKED.md
    2026-07-31, B=4 L=4096 H=16 128x128) measured gdn_prefill_pallas at
    7121 us vs 10049 us XLA — 1.41x — so the kernel is the default where
    it applies; ineligible shapes fall back to this XLA form.

    **Numerical domain of the auto default**: the kernel's Neumann-series
    solve assumes the delta rule's operating regime — L2-NORMALIZED KEYS
    (the QK-norm every GDN model applies before this op).  Unnormalized
    keys with coupling magnitudes >> 1 make the underlying recurrence
    itself chaotic AND can overflow the kernel's intermediate power
    matrices; such callers (outside any trained-model regime) must pass
    ``backend="xla"`` explicitly for the back-substituting solve.

    **Primal-only**: the kernel defines no AD rule (this is an inference
    library, matching the reference's inference-only kernel scope);
    differentiating callers must pass ``backend="xla"``.
    """
    from_env = False
    if backend == "auto":
        import os

        backend = os.environ.get("FLASHINFER_TPU_GDN_BACKEND", "pallas")
        from_env = True
    if backend == "pallas":
        from flashinfer_tpu.ops import gdn_kernel

        if gdn_kernel.eligible(q, v):
            # the kernel runs its own fixed chunk (128) — a different
            # explicit chunk_size changes only the internal blocking, not
            # the result, so it is legal to override here
            return gdn_kernel.gdn_chunk_prefill_pallas(
                q, k, v, alpha, beta, initial_state=initial_state
            )
        if not from_env:
            raise ValueError(
                "backend='pallas' needs L % 128 == 0 and 128-aligned "
                f"dk/dv, got L={q.shape[1]} dk={q.shape[-1]} "
                f"dv={v.shape[-1]}"
            )
        backend = "xla"  # env-selected: ineligible shapes fall back
    if backend != "xla":
        raise ValueError(f"unknown gdn backend {backend!r}")
    return _gdn_chunk_prefill_xla(
        q, k, v, alpha, beta, chunk_size, initial_state
    )


@functools.partial(jax.jit, static_argnames=("chunk_size",))
def _gdn_chunk_prefill_xla(q, k, v, alpha, beta, chunk_size=64,
                           initial_state=None):
    B, L, H, dk = q.shape
    dv = v.shape[-1]
    Q = chunk_size
    assert L % Q == 0, "pad L to a chunk multiple"
    nC = L // Q
    if initial_state is None:
        initial_state = jnp.zeros((B, H, dk, dv), jnp.float32)

    qf = q.astype(jnp.float32).reshape(B, nC, Q, H, dk)
    kf = k.astype(jnp.float32).reshape(B, nC, Q, H, dk)
    vf = v.astype(jnp.float32).reshape(B, nC, Q, H, dv)
    af = alpha.astype(jnp.float32).reshape(B, nC, Q, H)
    bf = beta.astype(jnp.float32).reshape(B, nC, Q, H)
    loga = jnp.log(jnp.maximum(af, 1e-30))
    acum = jnp.cumsum(loga, axis=2)  # [B,nC,Q,H] log D_i
    D = jnp.exp(acum)
    Dtot = jnp.exp(acum[:, :, -1])  # [B,nC,H]

    # decay ratio matrix R[i,j] = D_i / D_j (i >= j), computed in log space
    # (linear-space D_j underflows fp32 for strong decay over long chunks);
    # the used (lower) triangle has non-positive log-diffs, so clamping at 0
    # removes the masked upper triangle's overflow (and its NaN under grad)
    R = jnp.exp(
        jnp.minimum(acum[:, :, :, None, :] - acum[:, :, None, :, :], 0.0)
    )  # [B,nC,Q,Q,H]
    kk = jnp.einsum("bnjhd,bnihd->bnijh", kf, kf)  # k_j . k_i at [i,j]
    strict = jnp.tril(jnp.ones((Q, Q), bool), -1)
    C = jnp.where(
        strict[None, None, :, :, None],
        bf[:, :, :, None, :] * R * kk,
        0.0,
    )  # [B,nC,Q(i),Q(j),H]

    # rhs_i = beta_i (v_i - D_i S0^T k_i); S0 enters via the chunk scan, so
    # split U into a part independent of S0 and a part linear in S0:
    #   U = U_v - U_s(S0) with (I+C) U_v = B V, (I+C) Us = B (D K) -> then
    #   U = U_v - Us @ S0 (matrix in dk) applied per chunk inside the scan.
    eye = jnp.eye(Q)
    A_mat = eye[None, None, :, :, None] + C  # unit lower-triangular
    A_mat = jnp.moveaxis(A_mat, -1, 2)  # [B,nC,H,Q,Q]

    import jax.scipy.linalg as jsl

    rhs_v = jnp.moveaxis(bf[..., None] * vf, 3, 2)  # [B,nC,H,Q,dv]
    rhs_s = jnp.moveaxis(
        (bf * D)[..., None] * kf, 3, 2
    )  # [B,nC,H,Q,dk]  (coefficients multiplying S0^T k -> S0)
    Uv = jsl.solve_triangular(A_mat, rhs_v, lower=True, unit_diagonal=True)
    Us = jsl.solve_triangular(A_mat, rhs_s, lower=True, unit_diagonal=True)
    # [B,nC,Q,H,*]
    Uv = jnp.moveaxis(Uv, 2, 3)
    Us = jnp.moveaxis(Us, 2, 3)

    # per-chunk constant tensors for the boundary-state scan; the ratio
    # Dtot/D_j is exp(acum_Q - acum_j) in log space (underflow-safe)
    ratio = jnp.exp(acum[:, :, -1:, :] - acum)  # [B,nC,Q,H] = Dtot/D_j
    wk = ratio[..., None] * kf  # (Dtot/D_j) k_j
    # S_chunk_v = sum_j (Dtot/D_j) k_j Uv_j^T ; transition uses Us likewise
    Sv = jnp.einsum("bnjhd,bnjhe->bnhde", wk, Uv)
    Sm = jnp.einsum("bnjhd,bnjhe->bnhde", wk, Us)
    # q-side attention pieces
    qk = jnp.einsum("bnjhd,bnihd->bnijh", kf, qf)  # k_j . q_i at [i,j]
    causal = jnp.tril(jnp.ones((Q, Q), bool))
    P = jnp.where(causal[None, None, :, :, None], R * qk, 0.0)

    def scan_body(S0, inp):
        Sv_c, Sm_c, q_c, D_c, Dtot_c, P_c, Uv_c, Us_c = inp
        # outputs: o_i = D_i S0^T q_i + sum_{j<=i} P[i,j] u_j
        # with u_j = Uv_j - Us_j @ S0  (Us_j in dk -> contract with S0)
        u = Uv_c - jnp.einsum("bjhd,bhde->bjhe", Us_c, S0)
        o = (
            jnp.einsum("bhde,bihd->bihe", S0, q_c * D_c[..., None])
            + jnp.einsum("bijh,bjhe->bihe", P_c, u)
        )
        # state: S_Q = Dtot S0 + sum_j (Dtot/D_j) k_j u_j^T
        S = (
            Dtot_c[:, :, None, None] * S0
            + Sv_c
            - jnp.einsum("bhdf,bhfe->bhde", Sm_c, S0)
        )
        return S, o

    seq = lambda x: jnp.moveaxis(x, 1, 0)
    final, outs = jax.lax.scan(
        scan_body, initial_state.astype(jnp.float32),
        (seq(Sv), seq(Sm), seq(qf), seq(D), seq(Dtot), seq(P), seq(Uv), seq(Us)),
    )
    o = jnp.moveaxis(outs, 0, 1).reshape(B, L, H, dv)
    return o.astype(q.dtype), final


@jax.jit
def kda_decode_step(
    state: jax.Array,  # [B, H, dk, dv]
    q: jax.Array,  # [B, H, dk]
    k: jax.Array,  # [B, H, dk]
    v: jax.Array,  # [B, H, dv]
    alpha: jax.Array,  # [B, H, dk] per-channel decay
    beta: jax.Array,  # [B, H] update gate
) -> Tuple[jax.Array, jax.Array]:
    """One KDA decode step (per-channel decay delta rule)."""
    s = state.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    a = alpha.astype(jnp.float32)[..., None]  # [B, H, dk, 1]
    b = beta.astype(jnp.float32)[..., None, None]
    s = a * s
    pred = jnp.einsum("bhkv,bhk->bhv", s, kf)
    s = s + b * jnp.einsum("bhk,bhv->bhkv", kf, vf - pred)
    o = jnp.einsum("bhkv,bhk->bhv", s, q.astype(jnp.float32))
    return o.astype(q.dtype), s.astype(state.dtype)


def _mtp_scan(step_fn, state, seqs):
    """Scan a per-token decode step over a small T (MTP) axis at dim 1."""
    def body(st, inp):
        o, st = step_fn(st, *inp)
        return st, o

    final, os = jax.lax.scan(
        body, state, tuple(jnp.moveaxis(a, 1, 0) for a in seqs)
    )
    return jnp.moveaxis(os, 0, 1), final


@jax.jit
def gdn_decode_mtp(
    state: jax.Array,  # [B, H, dk, dv]
    q: jax.Array,  # [B, T, H, dk] — T draft/MTP tokens
    k: jax.Array,
    v: jax.Array,  # [B, T, H, dv]
    alpha: jax.Array,  # [B, T, H]
    beta: jax.Array,  # [B, T, H]
) -> Tuple[jax.Array, jax.Array]:
    """Multi-token GDN decode -> (o [B, T, H, dv], new_state): the
    reference's MTP decode kernel surface (gdn_kernels
    ``gated_delta_rule_mtp`` / ``run_mtp_decode``, T >= 1).  On TPU the
    T-step recurrence scans the single-token step — XLA keeps the state
    on-chip across the scan; T is the small speculative window."""
    return _mtp_scan(gdn_decode_step, state, (q, k, v, alpha, beta))


@jax.jit
def kda_decode_mtp(
    state: jax.Array,  # [B, H, dk, dv]
    q: jax.Array,  # [B, T, H, dk]
    k: jax.Array,
    v: jax.Array,  # [B, T, H, dv]
    alpha: jax.Array,  # [B, T, H, dk] per-channel decay
    beta: jax.Array,  # [B, T, H]
) -> Tuple[jax.Array, jax.Array]:
    """Multi-token KDA decode (per-channel-decay twin of
    :func:`gdn_decode_mtp`)."""
    return _mtp_scan(kda_decode_step, state, (q, k, v, alpha, beta))


def kda_chunk_prefill(
    q: jax.Array,  # [B, L, H, dk]
    k: jax.Array,
    v: jax.Array,  # [B, L, H, dv]
    alpha: jax.Array,  # [B, L, H, dk] per-channel decay in (0, 1]
    beta: jax.Array,  # [B, L, H]
    chunk_size: int = 32,
    initial_state: Optional[jax.Array] = None,  # [B, H, dk, dv]
    backend: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Chunked KDA prefill: the gdn_chunk_prefill WY form generalized to
    per-channel decay.  The score couplings become per-channel-weighted
    inner products, factorized around the chunk-midpoint decay (numerically
    valid while each channel's half-chunk decay stays within fp32 range —
    per-channel log-decay * chunk_size/2 > -60; chunk_size=32 covers
    alpha >= ~0.02, far below trained-gate ranges).  Boundary-state terms
    use one-sided non-positive exponents (always safe).

    ``backend="pallas"`` routes to the fused VMEM-resident kernel
    (``ops/gdn_kernel.kda_chunk_prefill_pallas``, chunk 128).  Its
    pair scores assemble from 16-row blocks with boundary-referenced
    history factors (safe at any decay) and midpoint diagonal blocks, so
    the usable per-token decay domain is alpha >= ~0.011 — wider than
    this chunk-32 XLA form's ~0.02 and far below trained-gate ranges.
    ``"auto"`` resolves to the kernel on eligible shapes: the banked v5e
    A/B (BENCH_BANKED.md 2026-07-31, B=4 L=4096 H=16 128x128) measured
    kda_prefill_pallas at 8652 us vs 10210 us XLA — 1.18x — and its
    decay domain is the wider of the two; ineligible shapes fall back to
    this XLA form.  Primal-only like GDN's kernel: differentiating
    callers must pass ``backend="xla"``."""
    from_env = False
    if backend == "auto":
        import os

        backend = os.environ.get("FLASHINFER_TPU_KDA_BACKEND", "pallas")
        from_env = True
    if backend == "pallas":
        from flashinfer_tpu.ops import gdn_kernel

        if gdn_kernel.eligible(q, v):
            return gdn_kernel.kda_chunk_prefill_pallas(
                q, k, v, alpha, beta, initial_state=initial_state
            )
        if not from_env:
            raise ValueError(
                "backend='pallas' needs L % 128 == 0 and 128-aligned "
                f"dk/dv, got L={q.shape[1]} dk={q.shape[-1]} "
                f"dv={v.shape[-1]}"
            )
        backend = "xla"  # env-selected: ineligible shapes fall back
    if backend != "xla":
        raise ValueError(f"unknown kda backend {backend!r}")
    return _kda_chunk_prefill_xla(
        q, k, v, alpha, beta, chunk_size, initial_state
    )


@functools.partial(jax.jit, static_argnames=("chunk_size",))
def _kda_chunk_prefill_xla(q, k, v, alpha, beta, chunk_size=32,
                           initial_state=None):
    B, L, H, dk = q.shape
    dv = v.shape[-1]
    Q = chunk_size
    assert L % Q == 0, "pad L to a chunk multiple"
    nC = L // Q
    if initial_state is None:
        initial_state = jnp.zeros((B, H, dk, dv), jnp.float32)

    qf = q.astype(jnp.float32).reshape(B, nC, Q, H, dk)
    kf = k.astype(jnp.float32).reshape(B, nC, Q, H, dk)
    vf = v.astype(jnp.float32).reshape(B, nC, Q, H, dv)
    af = alpha.astype(jnp.float32).reshape(B, nC, Q, H, dk)
    bf = beta.astype(jnp.float32).reshape(B, nC, Q, H)
    loga = jnp.log(jnp.maximum(af, 1e-30))
    acum = jnp.cumsum(loga, axis=2)  # [B,nC,Q,H,dk]
    D = jnp.exp(acum)  # <= 1 elementwise
    # midpoint-shifted two-sided factors for the quadratic couplings
    m = acum[:, :, Q // 2 : Q // 2 + 1]  # [B,nC,1,H,dk]
    f = jnp.exp(acum - m)  # decays to the right of midpoint
    g = jnp.exp(m - acum)  # grows to the left of midpoint

    # C[i,j] = beta_i sum_d k_i f_i k_j g_j   (j < i)
    kq_f = kf * f
    k_g = kf * g
    strict = jnp.tril(jnp.ones((Q, Q), bool), -1)
    C = jnp.where(
        strict[None, None, :, :, None],
        bf[:, :, :, None, :]
        * jnp.einsum("bnihd,bnjhd->bnijh", kq_f, k_g),
        0.0,
    )
    eye = jnp.eye(Q)
    A_mat = jnp.moveaxis(eye[None, None, :, :, None] + C, -1, 2)

    import jax.scipy.linalg as jsl

    rhs_v = jnp.moveaxis(bf[..., None] * vf, 3, 2)  # [B,nC,H,Q,dv]
    rhs_s = jnp.moveaxis(bf[..., None] * (D * kf), 3, 2)  # [B,nC,H,Q,dk]
    Uv = jnp.moveaxis(
        jsl.solve_triangular(A_mat, rhs_v, lower=True, unit_diagonal=True), 2, 3
    )
    Us = jnp.moveaxis(
        jsl.solve_triangular(A_mat, rhs_s, lower=True, unit_diagonal=True), 2, 3
    )

    # boundary-state pieces (one-sided, exponents <= 0)
    wk = jnp.exp(acum[:, :, -1:] - acum) * kf  # (D_Q/D_j) o k_j
    Sv = jnp.einsum("bnjhd,bnjhe->bnhde", wk, Uv)
    Sm = jnp.einsum("bnjhd,bnjhe->bnhde", wk, Us)
    Dtot = jnp.exp(acum[:, :, -1])  # [B,nC,H,dk]

    # P[i,j] = (q_i f_i) . (k_j g_j), causal inclusive
    causal = jnp.tril(jnp.ones((Q, Q), bool))
    P = jnp.where(
        causal[None, None, :, :, None],
        jnp.einsum("bnihd,bnjhd->bnijh", qf * f, k_g),
        0.0,
    )

    def scan_body(S0, inp):
        Sv_c, Sm_c, qD_c, Dtot_c, P_c, Uv_c, Us_c = inp
        u = Uv_c - jnp.einsum("bjhd,bhde->bjhe", Us_c, S0)
        o = (
            jnp.einsum("bhde,bihd->bihe", S0, qD_c)
            + jnp.einsum("bijh,bjhe->bihe", P_c, u)
        )
        S = (
            Dtot_c[:, :, :, None] * S0
            + Sv_c
            - jnp.einsum("bhdf,bhfe->bhde", Sm_c, S0)
        )
        return S, o

    seq = lambda x: jnp.moveaxis(x, 1, 0)
    final, outs = jax.lax.scan(
        scan_body, initial_state.astype(jnp.float32),
        (seq(Sv), seq(Sm), seq(qf * D), seq(Dtot), seq(P), seq(Uv), seq(Us)),
    )
    o = jnp.moveaxis(outs, 0, 1).reshape(B, L, H, dv)
    return o.astype(q.dtype), final


@jax.jit
def kda_prefill(
    q: jax.Array,  # [B, L, H, dk]
    k: jax.Array,
    v: jax.Array,  # [B, L, H, dv]
    alpha: jax.Array,  # [B, L, H, dk]
    beta: jax.Array,  # [B, L, H]
    initial_state: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    B, L, H, dk = q.shape
    dv = v.shape[-1]
    if initial_state is None:
        initial_state = jnp.zeros((B, H, dk, dv), jnp.float32)

    def step(s, inp):
        qt, kt, vt, at, bt = inp
        o, s = kda_decode_step(s, qt, kt, vt, at, bt)
        return s, o

    final, ys = jax.lax.scan(
        step, initial_state.astype(jnp.float32),
        tuple(jnp.moveaxis(t, 1, 0) for t in (q, k, v, alpha, beta)),
    )
    return jnp.moveaxis(ys, 0, 1), final
