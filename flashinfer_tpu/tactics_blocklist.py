"""Known-bad tactic exclusion list.

TPU re-design of the reference's tactics blocklist
(``flashinfer/tactics_blocklist.py`` + generator): a JSON list of
(op_name, tactic) pairs the autotuner must never select — the escape hatch
for kernel parameters that compile but miscompute or hang on specific
hardware.  Ships with built-in entries; extendable via
``FLASHINFER_TPU_TACTICS_BLOCKLIST`` (path to a JSON file of
``[{"op": ..., "tactic": ...}, ...]``).  A malformed file logs a warning
(never silently disables the safety net).

A third source is the bring-up quarantine (``bringup_quarantine.json``,
written by ``obs bringup`` when a smoke-ladder rung wedges the chip):
entries carrying both ``op`` and ``tactic`` join the blocklist, so the
autotuner resolver and the choosers skip wedge-proven tactics without any
extra plumbing at the call sites.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, List, Optional, Sequence, Tuple

# Built-in entries: (op_name, tactic) in json-normalized form.  Populated as
# hardware regressions are found with reproduced evidence.
_BUILTIN: List[Tuple[str, Any]] = []

_ext_cache: Optional[Tuple[str, List[Tuple[str, Any]]]] = None  # (path, entries)


def _normalize(tactic: Any) -> Any:
    """Canonical comparison form: json round-trip turns nested tuples into
    nested lists so Python tactics match file entries."""
    return json.loads(json.dumps(tactic))


def _load_external() -> List[Tuple[str, Any]]:
    global _ext_cache
    path = os.environ.get("FLASHINFER_TPU_TACTICS_BLOCKLIST")
    if not path:
        return []
    if _ext_cache is not None and _ext_cache[0] == path:
        return _ext_cache[1]
    entries: List[Tuple[str, Any]] = []
    try:
        data = json.loads(open(path).read())
        entries = [(e["op"], _normalize(e["tactic"])) for e in data]
    except Exception as e:
        logging.getLogger("flashinfer_tpu").warning(
            "FLASHINFER_TPU_TACTICS_BLOCKLIST %r unreadable (%r) — "
            "blocklist entries from this file are NOT active", path, e,
        )
    _ext_cache = (path, entries)
    return entries


# (path, mtime, raw entries) — quarantine reads are on chooser hot paths,
# so cache by mtime and never let a broken file raise
_bringup_cache: Optional[Tuple[str, float, List[dict]]] = None


def bringup_quarantine_path() -> str:
    """Where ``obs bringup`` writes wedge attributions.  Defined here (not
    in obs/) so the blocklist can consult it without importing obs."""
    p = os.environ.get("FLASHINFER_TPU_BRINGUP_QUARANTINE")
    if p:
        return p
    from flashinfer_tpu import env

    return str(env.cache_dir() / "bringup_quarantine.json")


def bringup_entries() -> List[dict]:
    """Raw quarantine entries (``[]`` when absent/unreadable).  Each is a
    dict with at least ``rung_id``/``reason``; knob rungs also carry
    ``op``/``tactic`` (consulted by :func:`blocked`) and ``bench_phases``
    (consulted by bench.py's orchestrator)."""
    global _bringup_cache
    path = bringup_quarantine_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return []
    if _bringup_cache is not None and _bringup_cache[:2] == (path, mtime):
        return _bringup_cache[2]
    entries: List[dict] = []
    try:
        data = json.loads(open(path).read())
        entries = [e for e in data if isinstance(e, dict)]
    except Exception as e:
        logging.getLogger("flashinfer_tpu").warning(
            "bring-up quarantine %r unreadable (%r) — wedge-proven "
            "tactics from it are NOT being skipped", path, e,
        )
    _bringup_cache = (path, mtime, entries)
    return entries


def _bringup_pairs() -> List[Tuple[str, Any]]:
    return [(e["op"], _normalize(e["tactic"])) for e in bringup_entries()
            if e.get("op") is not None and "tactic" in e]


def blocked(op_name: str, tactic: Any) -> bool:
    """True if (op, tactic) is blocklisted."""
    t = _normalize(tactic)
    for bop, btac in _BUILTIN + _load_external() + _bringup_pairs():
        if bop == op_name and btac == t:
            return True
    return False


def filter_candidates(op_name: str, candidates: Sequence[Any]) -> List[Any]:
    """Drop blocklisted candidates (keeps at least one)."""
    kept = [c for c in candidates if not blocked(op_name, c)]
    return kept or list(candidates[:1])
