"""Expert-parallel MoE runtime namespace (reference ``flashinfer/moe_ep``).

The reference's moe_ep subsystem is an NCCL/NIXL *fleet* runtime:
bootstrap a communicator world, allocate RDMA buffers, then run
dispatch -> expert GEMMs -> combine through split- or mega-fused layers.
On TPU every one of those concerns maps onto the mesh model:

- fleet/bootstrap -> ``jax.distributed`` + a ``Mapping``/``Mesh`` axis
  (the ICI/DCN fabric needs no per-op communicator objects);
- dispatch/combine -> ``fused_moe_ep``'s allgather or all_to_all modes
  (``alltoall_exact`` is the no-drop split-layer equivalent);
- RDMA buffer sizing / QP knobs -> absent by construction (XLA owns
  collective buffering); the knob classes survive as inert records so
  configuration code imports and constructs them unchanged;
- arch/backends probes answer honestly for this hardware: there is ONE
  backend ("xla-collective"), and NCCL/NIXL are not it.

Cited: /root/reference/flashinfer/moe_ep/__init__.py (name surface),
modes/split_layer.py (split semantics; the no-drop delivery contract
fused_moe_ep's exact mode reproduces).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp

from flashinfer_tpu.fused_moe import fused_moe_ep


class MoEEpNotBuiltError(RuntimeError):
    """Reference: raised when the NCCL/NIXL extension is not built.  On
    TPU the collective backend is always present, so this is raised only
    by explicit requests for the CUDA fabrics."""


class MoEEpArchError(RuntimeError):
    """Unsupported arch/backend combination."""


class MoEEpConfigError(ValueError):
    """Invalid EP configuration."""


# ---------------------------------------------------------------------------
# enums + config records
# ---------------------------------------------------------------------------


class EpAlgorithm(enum.Enum):
    """Dispatch/combine algorithm (reference EpAlgorithm) -> the
    fused_moe_ep dispatch modes."""

    ALLGATHER = "allgather"
    ALLTOALL = "alltoall"
    ALLTOALL_EXACT = "alltoall_exact"  # the no-drop split-layer contract


class EpLayout(enum.Enum):
    """Expert placement (reference EpLayout): experts shard contiguously
    over the ep axis here (Mapping.ep_experts)."""

    CONTIGUOUS = "contiguous"


class QuantType(enum.Enum):
    """EP-path activation quantization (reference QuantType): the TPU
    low-precision story is int8 (native MXU); fp8 is storage-only."""

    NONE = "none"
    INT8 = "int8"
    FP8 = "fp8"


@dataclasses.dataclass
class AlgoKnob:
    """Base knob record (reference AlgoKnob family).  The CUDA knobs
    tune RDMA channel/QP/buffer geometry, which has no TPU analogue —
    they are carried as inert records so config code runs unchanged."""

    name: str = ""
    value: Any = None


class FleetAlgoKnobAllocator(AlgoKnob):
    pass


class FleetAlgoKnobNumChannelsPerRank(AlgoKnob):
    pass


class FleetAlgoKnobNumQpsPerRank(AlgoKnob):
    pass


class FleetAlgoKnobQuantization(AlgoKnob):
    pass


class FleetAlgoKnobRdmaBufferSize(AlgoKnob):
    pass


class FleetAlgoKnobTopologyCapacity(AlgoKnob):
    pass


class HandleAlgoKnobNumReceivedTokens(AlgoKnob):
    pass


class HandleAlgoKnobSplitOperation(AlgoKnob):
    pass


class HandleAlgoKnobTopKWeights(AlgoKnob):
    pass


class HandleAlgoKnobUserStream(AlgoKnob):
    pass


@dataclasses.dataclass
class BootstrapConfig:
    """World-bootstrap parameters (reference BootstrapConfig) — the
    jax.distributed coordinates."""

    world_size: int = 1
    rank: int = 0
    coordinator_address: Optional[str] = None


@dataclasses.dataclass
class FleetParams:
    """Fleet geometry (reference FleetParams): on TPU this is the mesh
    axis the experts shard over."""

    ep_size: int = 1
    num_experts: int = 1
    axis: str = "tp"
    algorithm: EpAlgorithm = EpAlgorithm.ALLGATHER
    capacity_factor: float = 2.0
    knobs: Tuple[AlgoKnob, ...] = ()


@dataclasses.dataclass
class HandleParams:
    """Per-forward handle parameters (reference HandleParams)."""

    top_k: int = 2
    quant: QuantType = QuantType.NONE
    knobs: Tuple[AlgoKnob, ...] = ()


@dataclasses.dataclass
class DispatchInputParams:
    hidden_states: Any = None
    topk_ids: Any = None
    topk_weights: Any = None


@dataclasses.dataclass
class DispatchOutput:
    """Dispatch result (reference DispatchOutput).  The fused TPU path
    never materializes the routed intermediate outside the op, so this
    record is produced only by the explicit two-phase API below."""

    hidden_states: Any = None
    handle: Any = None


@dataclasses.dataclass
class CombineInputParams:
    expert_output: Any = None
    handle: Any = None


@dataclasses.dataclass
class CombineOutput:
    hidden_states: Any = None


# mega-mode weight preprocessing: the reference fuses all experts' GEMMs
# into one mega kernel over preprocessed (shuffled/quantized) weights;
# XLA owns layout, so preprocessing is identity and the configs are
# records only
@dataclasses.dataclass
class DeepGemmMegaMoeConfig:
    num_experts: int = 1
    hidden_size: int = 0
    intermediate_size: int = 0


Mxfp8CutedslMegaMoeConfig = DeepGemmMegaMoeConfig
Nvfp4CutedslMegaMoeConfig = DeepGemmMegaMoeConfig


def preprocess_mega_weights(weights, *_, **__):
    """Identity: mega-kernel weight shuffles are CUDA layout prep."""
    return weights


preprocess_mxfp8_cutedsl_mega_weights = preprocess_mega_weights
preprocess_nvfp4_cutedsl_mega_weights = preprocess_mega_weights


@dataclasses.dataclass
class FusedMoeKernelConfig:
    activation: str = "silu"


class IdentityConfig:
    """No-quant kernel config (reference IdentityConfig)."""


@dataclasses.dataclass
class SplitConfig:
    """Split-layer kernel config (reference SplitConfig)."""

    algorithm: EpAlgorithm = EpAlgorithm.ALLTOALL_EXACT
    capacity_factor: float = 2.0


MegaConfig = SplitConfig
NCCLEPConfig = SplitConfig
NcclEpConfig = SplitConfig
NvepConfig = SplitConfig


@dataclasses.dataclass
class SplitKernelContext:
    params: FleetParams = dataclasses.field(default_factory=FleetParams)


@dataclasses.dataclass
class MoEEpTensors:
    """The EP layer's tensor bundle (reference MoEEpTensors)."""

    w_gate_up: Any = None
    w_down: Any = None
    w1_scale: Any = None
    w2_scale: Any = None


@dataclasses.dataclass
class MoEWeightPack:
    """Expert weight pack (reference MoEWeightPack)."""

    gemm1: Any = None
    gemm2: Any = None


def dummy_moe_weights(num_experts: int, hidden: int, inter: int,
                      dtype=jnp.bfloat16, seed: int = 0) -> MoEWeightPack:
    """Random weight pack for tests/benches (reference dummy_moe_weights)."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return MoEWeightPack(
        gemm1=(jax.random.normal(k1, (num_experts, hidden, 2 * inter),
                                 jnp.float32) * 0.02).astype(dtype),
        gemm2=(jax.random.normal(k2, (num_experts, inter, hidden),
                                 jnp.float32) * 0.02).astype(dtype),
    )


def kernel_requires_weights(config) -> bool:
    """Reference predicate: every TPU kernel config takes weights."""
    return True


# ---------------------------------------------------------------------------
# bootstrap / fleet lifecycle -> jax.distributed + Mesh
# ---------------------------------------------------------------------------


def bootstrap_comm_group(config: Optional[BootstrapConfig] = None, **kw):
    """Initialize the multi-host world (reference bootstrap_comm_group ->
    ``jax.distributed.initialize``).  Single-process worlds are a no-op."""
    cfg = config or BootstrapConfig(**kw)
    if cfg.world_size > 1 and cfg.coordinator_address:
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.world_size,
            process_id=cfg.rank,
        )
    return cfg


def bootstrap_ep_rank_world() -> Tuple[int, int]:
    """(rank, world) of this process (reference bootstrap_ep_rank_world)."""
    return jax.process_index(), jax.process_count()


def bootstrap_ep_world_size() -> int:
    return jax.process_count()


def bootstrap_moe_ep_runtime(*args, **kw):
    """Reference: loads the NCCL/NIXL extension.  The XLA collective
    runtime is always resident; returns the bootstrap config."""
    return bootstrap_comm_group(*args, **kw) if (args or kw) else None


def ensure_moe_ep_cuda_device(*_, **__):
    """Reference pins the CUDA device; device placement is XLA's on TPU."""
    return None


def finalize_moe_ep_runtime(*_, **__):
    return None


class Handle:
    """Per-forward routing handle (reference Handle): carries what the
    combine needs.  The fused path keeps this inside the op; the
    two-phase API below threads it explicitly."""

    def __init__(self, params: HandleParams, wts, ids):
        self.params = params
        self.topk_weights = wts
        self.topk_ids = ids


class Fleet:
    """EP communicator + expert placement (reference Fleet).  On TPU a
    fleet IS a mesh axis: construct inside ``shard_map`` (or pass the
    axis name) and call :meth:`run_split` per layer."""

    def __init__(self, params: FleetParams):
        validate_fleet_params(params)
        self.params = params

    def make_handle(self, wts, ids,
                    params: Optional[HandleParams] = None) -> Handle:
        return Handle(params or HandleParams(), wts, ids)

    def run_split(self, hidden, tensors: MoEEpTensors, handle: Handle,
                  activation: str = "silu", return_dropped: bool = False):
        """The split-layer forward (reference MoEEpSplitLayer.forward /
        modes/split_layer.py): dispatch -> expert GEMMs -> combine over
        the fleet's axis, delivering every routed token when the
        algorithm is ALLTOALL_EXACT."""
        return fused_moe_ep(
            hidden, tensors.w_gate_up, tensors.w_down,
            handle.topk_weights, handle.topk_ids,
            self.params.num_experts, axis=self.params.axis,
            activation=activation,
            dispatch=self.params.algorithm.value,
            capacity_factor=self.params.capacity_factor,
            return_dropped=return_dropped,
        )


def create_fleet(params: FleetParams) -> Fleet:
    return Fleet(params)


class MoEEpLayer:
    """Layer-object form (reference MoEEpLayer): binds a fleet + weights."""

    def __init__(self, fleet: Fleet, tensors: MoEEpTensors,
                 config: Optional[SplitConfig] = None):
        self.fleet = fleet
        self.tensors = tensors
        self.config = config or SplitConfig()

    def forward(self, hidden, topk_weights, topk_ids, **kw):
        return self.fleet.run_split(
            hidden, self.tensors,
            self.fleet.make_handle(topk_weights, topk_ids), **kw
        )

    __call__ = forward


MoEEpSplitLayer = MoEEpLayer


class MoEEpMegaLayer(MoEEpLayer):
    """Mega mode fuses dispatch+GEMMs+combine into one kernel chain; the
    TPU split path is already one jitted program, so mega == split."""


def run_split_kernel(ctx: SplitKernelContext, hidden, tensors, handle,
                     **kw):
    """Free-function split forward (reference run_split_kernel)."""
    return Fleet(ctx.params).run_split(hidden, tensors, handle, **kw)


def record_dropped_tokens(dropped, algorithm=EpAlgorithm.ALLTOALL) -> int:
    """Host-side obs wiring for the capacity-drop counts.

    Inside ``shard_map`` the ``return_dropped=True`` count is a tracer,
    so ``fused_moe_ep`` cannot feed the registry itself there; the loop
    that pulls the concrete per-rank counts out of the sharded call
    hands them to this helper (``obs.catalog`` ``moe.dropped_tokens``).
    Returns the total recorded (0 when the metrics gate is off).
    """
    from flashinfer_tpu import obs

    alg = algorithm.value if isinstance(algorithm, EpAlgorithm) else \
        str(algorithm)
    return obs.record_dropped_tokens(dropped, alg)


# ---------------------------------------------------------------------------
# validation (reference validation.py family) — TPU-meaningful checks
# ---------------------------------------------------------------------------


def validate_fleet_params(params: FleetParams) -> None:
    if params.ep_size < 1:
        raise MoEEpConfigError(f"ep_size must be >= 1, got {params.ep_size}")
    if params.num_experts % max(params.ep_size, 1):
        raise MoEEpConfigError(
            f"num_experts ({params.num_experts}) must divide over ep_size "
            f"({params.ep_size}) — experts shard contiguously"
        )
    if not isinstance(params.algorithm, EpAlgorithm):
        raise MoEEpConfigError(f"unknown algorithm {params.algorithm!r}")


def validate_fleet_weights(tensors: MoEEpTensors) -> None:
    w1, w2 = tensors.w_gate_up, tensors.w_down
    if w1 is None or w2 is None:
        raise MoEEpConfigError("fleet weights missing")
    if w1.ndim != 3 or w2.ndim != 3 or w1.shape[0] != w2.shape[0]:
        raise MoEEpConfigError(
            f"expert weights must be [E_local, ...] stacks, got "
            f"{getattr(w1, 'shape', None)} / {getattr(w2, 'shape', None)}"
        )


def validate_mega_fleet_params(params: FleetParams) -> None:
    validate_fleet_params(params)


def validate_mega_arch(*_, **__) -> None:
    return None  # one arch: the mesh


def validate_arch_for_backend(backend: str = "xla-collective") -> None:
    if backend not in ("xla-collective", "auto"):
        raise MoEEpArchError(
            f"backend {backend!r} is a CUDA fabric; this hardware runs "
            "XLA collectives over ICI/DCN"
        )


def validate_bootstrap_world_size(world_size: int) -> None:
    if world_size < 1:
        raise MoEEpConfigError("world_size must be >= 1")


def validate_bootstrap_process_group_ready() -> bool:
    return True  # XLA collectives need no separate process group


def ensure_bootstrap_dist_validated(*_, **__) -> None:
    return None


def validate_split_forward_inputs(hidden, topk_weights, topk_ids) -> None:
    if hidden.ndim != 2 or topk_ids.ndim != 2:
        raise MoEEpConfigError(
            f"split forward takes hidden [T, H] and topk_ids [T, K]; got "
            f"{hidden.shape} / {topk_ids.shape}"
        )
    if topk_weights.shape != topk_ids.shape:
        raise MoEEpConfigError("topk_weights/topk_ids shape mismatch")


def validate_mega_forward_inputs(hidden, topk_weights, topk_ids) -> None:
    validate_split_forward_inputs(hidden, topk_weights, topk_ids)


# ---------------------------------------------------------------------------
# backend probes — honest answers for this hardware
# ---------------------------------------------------------------------------


def have_nccl_ep() -> bool:
    """NCCL-EP is a CUDA fabric; not this hardware's backend."""
    return False


def have_nixl_ep() -> bool:
    return False


def available_backends() -> List[str]:
    return ["xla-collective"]
