"""L010 — accumulator-init races and input/output alias bounds.

The Pallas accumulation idiom is a scratch ref initialized on the
FIRST grid step and read-modified on every step::

    @pl.when(k_i == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
    acc_ref[...] += partial_product        # every step

The race this pass encodes: an accumulator whose every write sits
under a guard that provably EXCLUDES the first step (``step != 0``,
``step > 0``) with no step-0 initialization write anywhere.  Scratch
VMEM is not zeroed between grid steps — the first read sees whatever
the previous launch left there: silently wrong numerics on-chip,
often-correct zeros in interpret mode, which is exactly the split that
makes this bug survive CPU CI.

Guard classification is deliberately narrow: a condition only counts
as "excludes step 0" when its subject is a *grid-step name* — a value
assigned from ``pl.program_id(...)`` in the kernel — compared against
a nonzero bound.  Conditions on plan values (``num_chunks > 0``) are
neither init nor exclusion; they gate whole-kernel work, not steps.

Second check: ``input_output_aliases`` literal dicts must stay in
bounds — each input index below ``num_scalar_prefetch + len(in_specs)``
(aliasing a scalar-prefetch operand is also flagged: prefetch operands
live in SMEM and cannot alias an output buffer) and each output index
below ``len(out_specs)``.  Non-literal alias dicts are skipped.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from flashinfer_tpu.analysis.core import (Finding, PallasCallSite,
                                          Project, SourceFile,
                                          expr_basename)

CODE = "L010"

_FIRST_NAME_RE = re.compile(r"first", re.IGNORECASE)


def _program_id_names(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and expr_basename(n.value.func) == "program_id":
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _subject_name(expr: ast.expr) -> Optional[str]:
    """Bare name, or the base name of a constant-indexed subscript
    (``first_ref[u]`` -> ``first_ref``)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Subscript) and isinstance(expr.value, ast.Name):
        return expr.value.id
    return None


_INIT, _EXCLUDING, _OTHER = "init", "excluding", "other"


def _classify_guard(cond: ast.expr, pid_names: Set[str]) -> str:
    """What a ``pl.when`` condition says about the first grid step."""
    if not (isinstance(cond, ast.Compare) and len(cond.ops) == 1):
        return _OTHER
    op = cond.ops[0]
    left, right = cond.left, cond.comparators[0]
    # normalize constant-on-the-left
    if isinstance(left, ast.Constant):
        left, right = right, left
        flip = {ast.Gt: ast.Lt, ast.Lt: ast.Gt,
                ast.GtE: ast.LtE, ast.LtE: ast.GtE}
        op = flip.get(type(op), type(op))() if type(op) in flip else op
    subject = _subject_name(left)
    rconst = right.value if isinstance(right, ast.Constant) else None
    if subject is None:
        return _OTHER
    if subject in pid_names:
        if rconst == 0:
            if isinstance(op, ast.Eq) or isinstance(op, ast.LtE):
                return _INIT
            if isinstance(op, (ast.NotEq, ast.Gt)):
                return _EXCLUDING
        if isinstance(rconst, int) and rconst >= 1:
            if isinstance(op, (ast.Eq, ast.GtE)):
                return _EXCLUDING
    elif _FIRST_NAME_RE.search(subject):
        # the plan-encoded first-of-tile flag idiom: first_ref[u] == 1
        if rconst == 1 and isinstance(op, ast.Eq):
            return _INIT
    return _OTHER


@dataclasses.dataclass
class _RefUse:
    reads: List[int] = dataclasses.field(default_factory=list)
    # write line -> guard class
    writes: List[Tuple[int, str]] = dataclasses.field(default_factory=list)


def _check_kernel(sf: SourceFile, fn: ast.FunctionDef,
                  findings: List[Finding]) -> None:
    pid_names = _program_id_names(fn)
    params = {a.arg for a in fn.args.posonlyargs + fn.args.args}
    if fn.args.vararg:
        params.add(fn.args.vararg.arg)
    # ref-like locals: unpacked from params (refs[i:] destructuring)
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            src_names = {s.id for s in ast.walk(n.value)
                         if isinstance(s, ast.Name)}
            if src_names & params:
                for t in n.targets:
                    elts = (t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t])
                    for e in elts:
                        e = e.value if isinstance(e, ast.Starred) else e
                        if isinstance(e, ast.Name):
                            params.add(e.id)

    uses: Dict[str, _RefUse] = {}
    # guard classes of local helper defs, resolved from call sites
    helper_guards: Dict[str, List[str]] = {}

    def _guard_of(def_node) -> Optional[ast.expr]:
        for d in def_node.decorator_list:
            if isinstance(d, ast.Call) \
                    and expr_basename(d.func) == "when" and d.args:
                return d.args[0]
        return None

    def _scan_exprs(node: ast.AST, guard_class: str,
                    helper: Optional[str]) -> None:
        for n in ast.walk(node):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Name) \
                    and n.func.id in helper_guards:
                helper_guards[n.func.id].append(
                    helper if helper is not None else guard_class)
            if isinstance(n, ast.Subscript) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id in params:
                use = uses.setdefault(n.value.id, _RefUse())
                if isinstance(n.ctx, ast.Store):
                    use.writes.append(
                        (n.lineno,
                         helper if helper is not None else guard_class))
                elif isinstance(n.ctx, ast.Load):
                    use.reads.append(n.lineno)

    def _walk(stmts, guard_class: str, helper: Optional[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                g = _guard_of(stmt)
                if g is not None:
                    cls = _classify_guard(g, pid_names)
                    # nested guards: an excluding inner guard wins; an
                    # init outer guard keeps init
                    eff = (_EXCLUDING if _EXCLUDING in (guard_class, cls)
                           else _INIT if _INIT in (guard_class, cls)
                           else _OTHER)
                    _walk(stmt.body, eff, helper)
                else:
                    # un-guarded local helper: its writes classify by
                    # the guards of its call sites (resolved below)
                    helper_guards.setdefault(stmt.name, [])
                    _walk(stmt.body, guard_class, stmt.name)
            elif isinstance(stmt, (ast.If, ast.While)):
                _scan_exprs(stmt.test, guard_class, helper)
                _walk(stmt.body, guard_class, helper)
                _walk(stmt.orelse, guard_class, helper)
            elif isinstance(stmt, ast.For):
                _scan_exprs(stmt.iter, guard_class, helper)
                _walk(stmt.body, guard_class, helper)
                _walk(stmt.orelse, guard_class, helper)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    _scan_exprs(item.context_expr, guard_class, helper)
                _walk(stmt.body, guard_class, helper)
            else:
                _scan_exprs(stmt, guard_class, helper)

    # two passes: helper defs may be called before/after their bodies
    # register, and helper call sites must exist before classification
    _walk(fn.body, _OTHER, None)
    uses.clear()
    for k in helper_guards:
        helper_guards[k] = []
    _walk(fn.body, _OTHER, None)

    def _resolve(cls_or_helper: str, depth: int = 0) -> List[str]:
        if cls_or_helper in (_INIT, _EXCLUDING, _OTHER):
            return [cls_or_helper]
        if depth > 4:
            return [_OTHER]
        sites = helper_guards.get(cls_or_helper, [])
        out: List[str] = []
        for s in sites:
            out.extend(_resolve(s, depth + 1))
        return out or [_OTHER]

    for ref, use in sorted(uses.items()):
        if not use.reads or not use.writes:
            continue
        classes: List[Tuple[int, str]] = []
        for line, raw in use.writes:
            for c in _resolve(raw):
                classes.append((line, c))
        has_init = any(c in (_INIT, _OTHER) for _, c in classes)
        excluding = [line for line, c in classes if c == _EXCLUDING]
        if excluding and not has_init:
            findings.append(Finding(
                CODE, sf.path, excluding[0], fn.name,
                f"ref '{ref}' is read in this kernel but every write is "
                "guarded to EXCLUDE the first grid step (pl.when(step != "
                "0)-shaped) with no step-0 initialization write — scratch "
                "VMEM is not zeroed between steps, so the first read sees "
                "stale data from the previous launch (wrong numerics "
                "on-chip; interpret mode often hides it)"))


def _check_io_aliases(site: PallasCallSite,
                      findings: List[Finding]) -> None:
    expr = site.io_aliases_expr
    if not isinstance(expr, ast.Dict):
        return
    n_in = (len(site.in_spec_exprs)
            if site.in_spec_exprs is not None else None)
    nsp = site.num_scalar_prefetch if site.is_prefetch_spec else 0
    n_out = (len(site.out_spec_exprs)
             if site.out_spec_exprs is not None else None)
    func = site.enclosing.name if site.enclosing else "<module>"
    for k, v in zip(expr.keys, expr.values):
        ki = k.value if isinstance(k, ast.Constant) \
            and isinstance(k.value, int) else None
        vi = v.value if isinstance(v, ast.Constant) \
            and isinstance(v.value, int) else None
        if ki is not None and nsp is not None and ki < nsp:
            findings.append(Finding(
                CODE, site.file.path, expr.lineno, func,
                f"input_output_aliases key {ki} names a scalar-prefetch "
                f"operand (num_scalar_prefetch={nsp}) — prefetch "
                "operands live in SMEM and cannot alias an output "
                "buffer"))
        elif ki is not None and nsp is not None and n_in is not None \
                and ki >= nsp + n_in:
            findings.append(Finding(
                CODE, site.file.path, expr.lineno, func,
                f"input_output_aliases key {ki} is out of range: the "
                f"launch has {nsp} scalar-prefetch + {n_in} array "
                "input(s)"))
        if vi is not None and n_out is not None and vi >= n_out:
            findings.append(Finding(
                CODE, site.file.path, expr.lineno, func,
                f"input_output_aliases value {vi} is out of range: the "
                f"launch has {n_out} output(s)"))


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    seen = set()
    for site in project.pallas_sites:
        _check_io_aliases(site, findings)
        k = site.kernel
        if k is None:
            continue
        key = (k.file.path, k.node.lineno)
        if key in seen:
            continue
        seen.add(key)
        _check_kernel(k.file, k.node, findings)
    return findings
