"""L013 — registry completeness: the silent-skip extension points closed.

PR 4 documented two deliberate soft spots: an autotuner knob with no
``KNOB_LAUNCHES`` binding silently skips the L009 VMEM proof, and a
planner/kernel pair missing from ``PLANNER_KERNELS`` silently skips the
L007 plan-array contract.  Both were fine while the registries were
young; by the PR 13/14 era (``engine.*`` tier knobs,
``prefill.fused_ingest``) "silently skipped" is indistinguishable from
"checked and clean" at review time.  This pass turns coverage itself
into a lint invariant:

1. **Knob coverage.**  Every knob in ``autotuner.KNOWN_KNOBS`` must
   have a ``vmem_budget.KNOB_LAUNCHES`` binding or an explicit
   ``vmem_budget.KNOB_WAIVERS`` entry with a reason (host-side /
   scheduler-only knobs have no VMEM launch by design — the waiver
   SAYS so, reviewably).  A reasonless waiver, a waiver shadowing a
   real binding, and a waiver for an unregistered knob are all
   findings.  Findings anchor to the knob's ``register_knob(...)``
   call (or the stale waiver's registry), so the fix site is one
   click away.
2. **Planner coverage.**  A ``PrefetchScalarGridSpec`` launch whose
   leading operands are ``plan["key"]`` subscripts is consuming a
   host planner's plan arrays; if its resolved kernel is bound to no
   ``PLANNER_KERNELS`` entry, the whole L007 planner contract skips
   it.  Additionally any statically-resolvable ``build_*`` project
   function whose emitted keys cover the consumed set must itself be
   registered — matched only for launches consuming >= 3 plan keys (a
   deliberate noise floor: one- or two-key overlaps with a generic
   ``build_*`` helper are coincidence, not a planner relationship).
3. **Obs registry coverage.**  The scattered ``obs doctor`` checks —
   ``catalog.SERVING_OPS`` vs ``spans.SPAN_CATEGORIES`` (every serving
   op opens a span), ``catalog.API_OPS`` vs ``costmodel.API_OP_COSTS``
   (every public op roofline-attributes) — unify HERE as the one
   implementation; the doctor delegates to
   :func:`unspanned_serving_ops` / :func:`uncovered_api_ops` and its
   output is unchanged.  Stale entries (a span category or cost family
   for an op the catalog no longer lists, an invalid span category)
   are findings too — a stale registry silently shrinks the observed
   surface.

Registry checks are gated on the project actually containing the
defining module (``register_knob`` calls for 1, ``obs/spans.py`` /
``obs/costmodel.py`` for 3), so synthetic test projects and
``--changed-only`` subsets can only under-report, never false-fail.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from flashinfer_tpu.analysis.core import (Finding, Project,
                                          expr_basename, project_relpath)

CODE = "L013"


# -- live-registry views (the ONE implementation obs doctor delegates to) --


def unspanned_serving_ops() -> List[str]:
    """Serving ops that declare no flight-recorder span category — the
    ``obs doctor`` ``spans.unspanned_serving_ops`` field (must stay
    empty; the L005 ships-observed rule extended to the span layer)."""
    from flashinfer_tpu.obs.catalog import SERVING_OPS
    from flashinfer_tpu.obs.spans import SPAN_CATEGORIES

    return sorted(SERVING_OPS - set(SPAN_CATEGORIES))


def uncovered_api_ops() -> Tuple[str, ...]:
    """Decorated public ops with no cost-model family — the ``obs
    doctor`` ``costmodel.uncovered_api_ops`` field (must stay empty)."""
    from flashinfer_tpu.obs.catalog import API_OPS
    from flashinfer_tpu.obs.costmodel import API_OP_COSTS

    return tuple(sorted(API_OPS - set(API_OP_COSTS)))


def unbound_knobs(knobs: Optional[Dict] = None,
                  launches: Optional[Dict] = None,
                  waivers: Optional[Dict] = None) -> List[str]:
    """Registered knobs with neither a KNOB_LAUNCHES binding nor an
    explicit waiver — the gaps check 1 reports (must stay empty)."""
    if knobs is None:
        from flashinfer_tpu.autotuner import KNOWN_KNOBS as knobs
    if launches is None:
        from flashinfer_tpu.analysis.vmem_budget import \
            KNOB_LAUNCHES as launches
    if waivers is None:
        from flashinfer_tpu.analysis.vmem_budget import \
            KNOB_WAIVERS as waivers
    return sorted(set(knobs) - set(launches) - set(waivers))


# -- finding anchors ------------------------------------------------------


def _register_knob_lines(project: Project) -> Dict[str, Tuple[str, int]]:
    """knob name -> (file, line) of its ``register_knob("name", ...)``
    call in the analyzed set; empty when the registry module is not in
    the project (subset runs skip check 1)."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in project.files:
        if sf.tree is None:
            continue
        for n in ast.walk(sf.tree):
            if isinstance(n, ast.Call) \
                    and expr_basename(n.func) == "register_knob" \
                    and n.args and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                out[n.args[0].value] = (sf.path, n.lineno)
    return out


def _assign_line(project: Project, relpath: str,
                 name: str) -> Optional[Tuple[str, int]]:
    """(file, line) of the top-level ``name = ...`` / ``name: T = ...``
    assignment in the project file at `relpath`, if analyzed."""
    for sf in project.files:
        if sf.tree is None or project_relpath(sf.path) != relpath:
            continue
        for n in sf.tree.body:
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, ast.AnnAssign):
                targets = [n.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return sf.path, n.lineno
    return None


def _waiver_call_lines(project: Project) -> Dict[str, Tuple[str, int]]:
    out: Dict[str, Tuple[str, int]] = {}
    for sf in project.files:
        if sf.tree is None:
            continue
        for n in ast.walk(sf.tree):
            if isinstance(n, ast.Call) \
                    and expr_basename(n.func) == "waive_knob_launch" \
                    and n.args and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                out[n.args[0].value] = (sf.path, n.lineno)
    return out


# -- check 1: knob coverage ----------------------------------------------


def _check_knobs(project: Project, findings: List[Finding],
                 knobs: Optional[Dict], launches: Optional[Dict],
                 waivers: Optional[Dict]) -> None:
    anchors = _register_knob_lines(project)
    if not anchors:
        return  # registry module not analyzed: skip, never guess
    if knobs is None:
        from flashinfer_tpu.autotuner import KNOWN_KNOBS as knobs
    if launches is None:
        from flashinfer_tpu.analysis.vmem_budget import \
            KNOB_LAUNCHES as launches
    if waivers is None:
        from flashinfer_tpu.analysis.vmem_budget import \
            KNOB_WAIVERS as waivers
    waiver_anchors = _waiver_call_lines(project)
    for knob in unbound_knobs(knobs, launches, waivers):
        path, line = anchors.get(knob, next(iter(anchors.values())))
        findings.append(Finding(
            CODE, path, line, knob,
            f"knob '{knob}' is registered in KNOWN_KNOBS but has "
            "neither a KNOB_LAUNCHES binding (the L009 VMEM proof) nor "
            "an explicit KNOB_WAIVERS entry — an unbound knob's config "
            "entries are silently skipped by the feasibility check; "
            "bind the launcher or waive it with a reason "
            "(analysis/vmem_budget.py)"))
    for knob, reason in sorted(waivers.items()):
        anchor = waiver_anchors.get(knob) \
            or anchors.get(knob, next(iter(anchors.values())))
        path, line = anchor
        if not str(reason).strip():
            findings.append(Finding(
                CODE, path, line, knob,
                f"KNOB_WAIVERS entry for '{knob}' has no reason — an "
                "unreviewable waiver is worse than the gap it hides "
                "(the L000 rule, applied to registries)"))
        if knob in launches:
            findings.append(Finding(
                CODE, path, line, knob,
                f"knob '{knob}' is BOTH bound in KNOB_LAUNCHES and "
                "waived in KNOB_WAIVERS — delete the stale waiver so "
                "the binding's proof visibly owns the knob"))
        if knob not in knobs:
            findings.append(Finding(
                CODE, path, line, knob,
                f"KNOB_WAIVERS entry for '{knob}' names no registered "
                "knob — a renamed/retired knob left a stale waiver; "
                "prune it"))


# -- check 2: planner coverage -------------------------------------------


def _check_planners(project: Project, findings: List[Finding],
                    planner_kernels: Optional[Dict]) -> None:
    from flashinfer_tpu.analysis.pallas_contract import (
        _leading_plan_keys, _planner_emitted_keys)

    if planner_kernels is None:
        from flashinfer_tpu.analysis.pallas_contract import \
            PLANNER_KERNELS as planner_kernels
    covered_kernels = set(planner_kernels.values())
    # (kernel name, consumed keyset) per covered launch — collected
    # once so the build_* sweep below runs ONCE per planner, not per
    # site (a planner feeding several launches must flag exactly once
    # or the count-keyed baseline goes brittle)
    consumed: List[Tuple[str, Set[str]]] = []
    for site in project.pallas_sites:
        if site.kernel is None or not site.is_prefetch_spec:
            continue
        keys = _leading_plan_keys(site)
        if not keys:
            continue
        func = site.enclosing.name if site.enclosing else "<module>"
        if site.kernel.name not in covered_kernels:
            findings.append(Finding(
                CODE, site.file.path,
                site.invocation.lineno if site.invocation else site.line,
                func,
                f"kernel '{site.kernel.name}' consumes plan array(s) "
                f"({', '.join(keys[:4])}{', …' if len(keys) > 4 else ''}) "
                "but no PLANNER_KERNELS entry binds it — the L007 "
                "planner contract silently skips this launch; register "
                "the planner→kernel pair "
                "(analysis/pallas_contract.py)"))
            continue
        if len(keys) >= 3:
            consumed.append((site.kernel.name, set(keys)))
    # a resolvable build_* planner whose emission covers the consumed
    # keys must itself be registered (the engine lowering precedent:
    # transitively-enforced planners still get entries)
    for name, infos in sorted(project.function_index.items()):
        if not name.startswith("build_") or name in planner_kernels:
            continue
        for info in infos:
            emitted = _planner_emitted_keys(info)
            if emitted is None:
                continue
            hit = next((kname for kname, keyset in consumed
                        if keyset <= emitted), None)
            if hit is not None:
                findings.append(Finding(
                    CODE, info.file.path, info.node.lineno, name,
                    f"planner '{name}' emits every plan key the "
                    f"'{hit}' launch consumes but is "
                    "not in PLANNER_KERNELS — its plan-schema "
                    "changes would skip the L007 contract; "
                    "register the pair"))
                break


# -- check 3: obs registry coverage --------------------------------------

_SPANS_RELPATH = "flashinfer_tpu/obs/spans.py"
_COSTMODEL_RELPATH = "flashinfer_tpu/obs/costmodel.py"


def _check_obs_registries(project: Project,
                          findings: List[Finding]) -> None:
    spans_anchor = _assign_line(project, _SPANS_RELPATH,
                                "SPAN_CATEGORIES")
    costs_anchor = _assign_line(project, _COSTMODEL_RELPATH,
                                "API_OP_COSTS")
    if spans_anchor is not None:
        try:
            from flashinfer_tpu.obs.catalog import SERVING_OPS
            from flashinfer_tpu.obs.spans import (SPAN_CATEGORIES,
                                                  SPAN_CATEGORIES_VALID)
        except Exception:
            # broken spans tree: L999/import errors own THIS block;
            # the independent costmodel check below still runs
            spans_anchor = None
    if spans_anchor is not None:
        path, line = spans_anchor
        for op in unspanned_serving_ops():
            findings.append(Finding(
                CODE, path, line, op,
                f"serving op '{op}' (catalog.SERVING_OPS) has no "
                "spans.SPAN_CATEGORIES entry — it would serve whole "
                "steps the flight recorder cannot trace; declare its "
                "span category"))
        for op, cat in sorted(SPAN_CATEGORIES.items()):
            if op not in SERVING_OPS:
                findings.append(Finding(
                    CODE, path, line, op,
                    f"spans.SPAN_CATEGORIES names '{op}' which is not "
                    "in catalog.SERVING_OPS — a renamed/retired op "
                    "left a stale span declaration; prune it"))
            if cat not in SPAN_CATEGORIES_VALID:
                findings.append(Finding(
                    CODE, path, line, op,
                    f"span category {cat!r} for '{op}' is not in "
                    "SPAN_CATEGORIES_VALID — the chrome-trace export "
                    "would carry an undeclared category"))
    if costs_anchor is not None:
        try:
            from flashinfer_tpu.obs.catalog import API_OPS
            from flashinfer_tpu.obs.costmodel import API_OP_COSTS
        except Exception:
            return  # broken costmodel tree: L999/import errors own it
        path, line = costs_anchor
        for op in uncovered_api_ops():
            findings.append(Finding(
                CODE, path, line, op,
                f"public op '{op}' (catalog.API_OPS) has no "
                "costmodel.API_OP_COSTS family — it can bench but "
                "never roofline-attribute; map it to a cost family"))
        for op in sorted(set(API_OP_COSTS) - set(API_OPS)):
            findings.append(Finding(
                CODE, path, line, op,
                f"costmodel.API_OP_COSTS names '{op}' which is not in "
                "catalog.API_OPS — a renamed/retired op left a stale "
                "cost mapping; prune it"))


def run(project: Project, *, knobs: Optional[Dict] = None,
        launches: Optional[Dict] = None,
        waivers: Optional[Dict] = None,
        planner_kernels: Optional[Dict] = None) -> List[Finding]:
    findings: List[Finding] = []
    _check_knobs(project, findings, knobs, launches, waivers)
    _check_planners(project, findings, planner_kernels)
    _check_obs_registries(project, findings)
    return findings
