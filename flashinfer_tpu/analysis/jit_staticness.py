"""L003 — trace-time configuration reads inside jit boundaries.

``jax.jit`` runs the Python body ONCE per cache key and bakes every
Python-level value into the trace.  An ``os.environ`` / ``os.getenv``
read (or a read of a mutated module-level dict/list) inside a jitted
function is therefore resolved at first trace and pinned by the jit
cache — later env changes are silently ignored for that shape.  The
motivating true positive (ADVICE.md round 5, item 4): compat's
``_top_k_large_ties`` was jitted with ``backend`` static, so
``backend="auto"`` resolved ``FLASHINFER_TPU_TOPK_BACKEND`` inside the
trace, contradicting topk.py's documented eager per-call resolution.

Detection:

- a function is *jitted* if decorated with ``jit``/``jax.jit``/
  ``pjit`` (bare, called, or via ``functools.partial(jax.jit, ...)``),
  or wrapped at assignment (``f = jax.jit(g)`` marks ``g``);
- a function is *env-reading* if its body touches ``os.environ`` /
  ``environ`` or calls ``getenv``, or loads a module-level dict/list/
  set that the SAME module mutates somewhere (a mutated global read at
  trace time is the same staleness bug; never-mutated constant tables
  are exempt);
- taint propagates through calls by callee basename across the whole
  analyzed file set (cross-module: compat's jitted helper calling
  ``topk.top_k_values_indices`` → ``_resolve_backend`` → env read).

Findings anchor at the env-read line (direct) or the call line inside
the jitted function (transitive).  Fix: resolve the configuration
EAGERLY in the un-jitted caller and pass the concrete value through —
then suppress any remaining transitive-reachability report with
``# graft-lint: ok <why the value is already concrete>``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from flashinfer_tpu.analysis.core import Finding, Project, SourceFile

CODE = "L003"

_JIT_NAMES = {"jit", "pjit"}
_PARTIAL_NAMES = {"partial"}
_MUTATOR_METHODS = {"append", "extend", "add", "update", "pop", "popitem",
                    "setdefault", "clear", "insert", "remove", "discard"}


def _basename(expr: ast.expr) -> str:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _is_jit_expr(expr: ast.expr) -> bool:
    """`jax.jit`, `jit`, `pjit`, `jax.jit(...)`, or
    `functools.partial(jax.jit, ...)`."""
    if _basename(expr) in _JIT_NAMES:
        return True
    if isinstance(expr, ast.Call):
        if _basename(expr.func) in _JIT_NAMES:
            return True
        if _basename(expr.func) in _PARTIAL_NAMES and expr.args \
                and _is_jit_expr(expr.args[0]):
            return True
    return False


@dataclasses.dataclass
class FnInfo:
    name: str
    file: SourceFile
    node: ast.FunctionDef
    jitted: bool
    env_reads: List[int]            # lines with direct env reads
    global_reads: List[Tuple[int, str]]  # (line, mutated-global name)
    # (callee basename, line, root): root is None for bare-name calls,
    # else the leftmost Name of the attribute chain ("topk" for
    # topk.top_k_values_indices, "jax" for jax.lax.top_k, "self" for
    # method calls) — taint only follows project-internal roots, so an
    # external library sharing a function name cannot false-positive
    calls: List[Tuple[str, int, Optional[str]]]


def _mutated_globals(tree: ast.Module) -> Set[str]:
    """Module-level names bound to a dict/list/set literal (or
    constructor call) that the module also mutates somewhere."""
    candidates: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            v = node.value
            mutable = isinstance(v, (ast.Dict, ast.List, ast.Set,
                                     ast.DictComp, ast.ListComp,
                                     ast.SetComp)) or (
                isinstance(v, ast.Call)
                and _basename(v.func) in ("dict", "list", "set",
                                          "defaultdict", "OrderedDict"))
            if mutable:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        candidates.add(t.id)
    if not candidates:
        return set()
    mutated: Set[str] = set()
    for node in ast.walk(tree):
        # d[key] = ... / del d[key] / d[key] += ...
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [getattr(node, "target", None)]
                       if not isinstance(node, ast.Delete)
                       else node.targets)
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in candidates:
                    mutated.add(t.value.id)
        # d.update(...) / l.append(...)
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATOR_METHODS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in candidates:
            mutated.add(node.func.value.id)
    return mutated


def _collect_functions(sf: SourceFile) -> List[FnInfo]:
    if sf.tree is None:
        return []
    mutated = _mutated_globals(sf.tree)

    def _callable_names(expr: ast.expr) -> Set[str]:
        """Bare Names that plausibly name the traced callable inside a
        jit argument — the name itself, or the FIRST positional arg of
        a composing call, recursively (`jax.jit(jax_shard_map(step,
        ...))` traces `step`; `jax.jit(partial(f, x))` traces `f`).
        Later positional args are data/callback operands, not the
        traced body — marking them too would false-positive L003 on
        any module function sharing such an argument's name."""
        names: Set[str] = set()
        if isinstance(expr, ast.Name):
            names.add(expr.id)
        elif isinstance(expr, ast.Call) and expr.args:
            names |= _callable_names(expr.args[0])
        return names

    # names marked jitted via call wrapping: g = jax.jit(f), a bare
    # jax.jit(shard_map(step, ...)) in a return, etc.
    wrapped: Set[str] = set()
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_jit_expr(node.func):
            args = node.args
        elif _basename(node.func) in _PARTIAL_NAMES and node.args \
                and _is_jit_expr(node.args[0]):
            args = node.args[1:]
        else:
            continue
        for a in args:
            wrapped |= _callable_names(a)

    infos: List[FnInfo] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        jitted = node.name in wrapped or any(
            _is_jit_expr(d) for d in node.decorator_list)
        env_reads: List[int] = []
        global_reads: List[Tuple[int, str]] = []
        calls: List[Tuple[str, int, Optional[str]]] = []
        # locals shadow module globals; a parameter named like a global
        # is not a global read
        local_names = {a.arg for a in (
            node.args.posonlyargs + node.args.args + node.args.kwonlyargs
            + ([node.args.vararg] if node.args.vararg else [])
            + ([node.args.kwarg] if node.args.kwarg else []))}
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and n.attr == "environ":
                env_reads.append(n.lineno)
            elif isinstance(n, ast.Name) and n.id == "environ" \
                    and isinstance(n.ctx, ast.Load):
                env_reads.append(n.lineno)
            elif isinstance(n, ast.Call):
                base = _basename(n.func)
                if base == "getenv":
                    env_reads.append(n.lineno)
                elif base:
                    root: Optional[str] = None
                    if isinstance(n.func, ast.Attribute):
                        head = n.func.value
                        while isinstance(head, ast.Attribute):
                            head = head.value
                        root = head.id if isinstance(head, ast.Name) \
                            else ""
                    calls.append((base, n.lineno, root))
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in mutated and n.id not in local_names:
                global_reads.append((n.lineno, n.id))
        infos.append(FnInfo(node.name, sf, node, jitted,
                            sorted(set(env_reads)), global_reads, calls))
    return infos


def run(project: Project) -> List[Finding]:
    all_fns: List[FnInfo] = []
    for sf in project.files:
        all_fns.extend(_collect_functions(sf))

    by_name: Dict[str, List[FnInfo]] = {}
    for fn in all_fns:
        by_name.setdefault(fn.name, []).append(fn)

    # roots taint may follow: bare names (None), methods on self/cls,
    # and attribute access on a project-internal module name
    internal_roots: Set[str] = {"self", "cls"}
    for sf in project.files:
        internal_roots.add(os.path.splitext(sf.basename)[0])
        parent = os.path.basename(os.path.dirname(
            os.path.abspath(sf.path)))
        if parent:
            internal_roots.add(parent)

    def _follows(call: Tuple[str, int, Optional[str]]) -> bool:
        _callee, _line, root = call
        return root is None or root in internal_roots

    # fixpoint: taint = reads trace-time-pinned state directly (env OR
    # a mutated module global), or calls a tainted name
    tainted: Set[str] = {fn.name for fn in all_fns
                         if fn.env_reads or fn.global_reads}
    changed = True
    while changed:
        changed = False
        for fn in all_fns:
            if fn.name in tainted:
                continue
            if any(_follows(c) and c[0] in tainted for c in fn.calls):
                tainted.add(fn.name)
                changed = True

    findings: List[Finding] = []
    for fn in all_fns:
        if not fn.jitted:
            continue
        for line in fn.env_reads:
            findings.append(Finding(
                CODE, fn.file.path, line, fn.name,
                "os.environ/getenv read inside a jit-traced function is "
                "resolved ONCE at trace time and pinned by the jit cache "
                "(the _top_k_large_ties backend-pinning bug) — resolve "
                "the value eagerly outside the jit and pass it in"))
        for line, gname in fn.global_reads:
            findings.append(Finding(
                CODE, fn.file.path, line, fn.name,
                f"read of mutated module-level '{gname}' inside a "
                "jit-traced function is baked in at trace time — later "
                "mutations are silently ignored for cached shapes; pass "
                "the value as an argument instead"))
        seen_callees: Set[str] = set()
        for callee, line, root in fn.calls:
            if callee in tainted and callee not in seen_callees \
                    and _follows((callee, line, root)) \
                    and not any(f.jitted
                                for f in by_name.get(callee, [])):
                seen_callees.add(callee)
                findings.append(Finding(
                    CODE, fn.file.path, line, fn.name,
                    f"call to '{callee}', which (transitively) reads "
                    "process env or a mutated module global — inside "
                    "this jit boundary the read happens at trace time "
                    "and is pinned by the jit cache; hoist the "
                    "resolution out of the jit or suppress with the "
                    "eager-resolution reason if the value is already "
                    "concrete here"))
    return findings
