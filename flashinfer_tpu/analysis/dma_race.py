"""L014 dma_race — DMA/semaphore happens-before checking inside Pallas
kernel bodies.

The repo's committed-speed backlog lives in hand-rolled double-buffered
DMA mainloops, and the chip wedged for two bench rounds (BENCH_r04/r05
``wedged: true``) on exactly the hang an unbalanced semaphore produces.
This pass executes each resolved kernel body in a tiny concrete model
— the FINAL grid axis runs sequentially for ``N_STEPS`` model steps,
scalar-prefetch loads become opaque terms, unknown guards fork the
world with a memoized truth per canonicalized condition atom — and
checks, per surviving world:

(a) reads of a DMA destination ref not dominated by the matching wait,
(b) overwrite of a buffer slot or copy source while a copy on that
    slot may still be in flight (the double-buffer slot-parity /
    cross-unit-prefetch anti-dependency),
(c) start/wait imbalance on any semaphore along any path — a wait with
    nothing in flight, or copies still in flight after the last grid
    step (the static wedge-prevention proof), and
(d) cross-grid-iteration carries (start in step *i*, wait in *i+1*)
    whose slot is touched in between — reported through (a)/(b) with
    the carry step called out.

Soundness stance (the L007 rule): a kernel the interpreter cannot
execute SKIPS — never false-reports — and skips are counted
(``stats()`` feeds ``obs doctor``).  Conflict decisions use MUST
semantics both ways: a finding needs must-overlap (structurally equal
or concretely intersecting index terms), and a wait retires any
may-matching in-flight copy silently, so an unknown term never turns
into a report.  World forks that a guard's memo cannot distinguish are
merged back as soon as their DMA state (in-flight multiset + ref
stores + kernel-scope env) re-converges, which keeps the
mask/online-update guard combinatorics of the real fused-prefill
mainloops flat.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from flashinfer_tpu.analysis.core import (Finding, FnLocals, FunctionInfo,
                                          PallasCallSite, Project,
                                          const_int, expr_basename,
                                          expr_root)

# model sizes: the final grid axis runs N_STEPS sequential steps (two
# steps exercise a double-buffer handoff, three exercise slot reuse);
# unknown fori_loop bounds enumerate trip counts 0..MAX_TRIP.
N_STEPS = 3
MAX_TRIP = 2
MAX_UNROLL = 8          # concrete fori/range unroll ceiling
MAX_WORLDS = 768        # live worlds after merging, per site
MAX_STMT_PATHS = 4096   # fork paths within one top-level statement
MAX_OPS = 4_000_000     # interpreter fuel per site
_MODEL_INT = 2          # model value for unresolvable static loop bounds


class KernelSkip(Exception):
    """Kernel not statically executable — count, never guess."""


class _NeedChoice(Exception):
    def __init__(self, key, options):
        super().__init__(key)
        self.key = key
        self.options = options


class _DeadWorld(Exception):
    """Binding contradicted an already-memoized guard: path infeasible."""


class _Return(Exception):
    def __init__(self, value):
        super().__init__("return")
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


# -- values ---------------------------------------------------------------
# Terms are nested tuples (hashable, structurally comparable):
#   ("sym", name)                 opaque scalar (non-final program_id, ...)
#   ("static", name)              unresolved partial-bound kernel param
#   ("load", refkey, idx)         value read from a ref
#   ("op", opname, *args)         uninterpreted arithmetic
#   ("cmp", op, a, b)             comparison (array mask until guarded)
#   ("and"/"or"/"not", ...)       logical combination
#   ("call", name, *args)         uninterpreted call
#   ("attr", value, name)         attribute of an opaque value
# Concrete ints/bools/floats/strings pass through as themselves.


class Ref:
    """A Pallas ref (kernel param, scratch slot, or vararg element)."""

    def __init__(self, key: str):
        self.key = key
        self.label = key

    def __eq__(self, other):
        return isinstance(other, Ref) and other.key == self.key

    def __hash__(self):
        return hash(self.key)

    def __repr__(self):
        return f"Ref({self.label})"


@dataclasses.dataclass(frozen=True)
class DS:
    """pl.ds(start, size)."""
    start: object
    size: object


_FULL = ("fullslice",)
_ELL = ("ellipsis",)


@dataclasses.dataclass(frozen=True)
class View:
    """ref[idx...] as a copy operand / access region."""
    ref: Ref
    idx: tuple

    def describe(self) -> str:
        return self.ref.label


class AtProxy:
    def __init__(self, ref: Ref):
        self.ref = ref


class Copy:
    def __init__(self, src: View, dst: View, sem: View, line: int):
        self.src = src
        self.dst = dst
        self.sem = sem
        self.line = line

    def key(self):
        return (_view_key(self.src), _view_key(self.dst),
                _view_key(self.sem), self.line)


class BoundMethod:
    def __init__(self, recv, name: str):
        self.recv = recv
        self.name = name


class WhenPred:
    def __init__(self, cond):
        self.cond = cond


class Varargs:
    """The kernel's *refs tuple; elements materialize lazily so the
    boolean-static ref layout (has_mask, return_lse, ...) needs no
    launch-side operand count."""

    def __init__(self, name: str):
        self.name = name
        self._refs: Dict[int, Ref] = {}

    def get(self, i: int) -> Ref:
        if i not in self._refs:
            self._refs[i] = Ref(f"*{self.name}[{i}]")
        return self._refs[i]


@dataclasses.dataclass(frozen=True)
class VarargTail:
    base: object  # Varargs
    start: int


class Closure:
    def __init__(self, node, env):
        self.node = node
        self.env = env


class RangeVal:
    def __init__(self, lo: int, hi: int):
        self.lo = lo
        self.hi = hi

    def items(self):
        return list(range(self.lo, self.hi))


def _view_key(v: View):
    return (v.ref.key, tuple(_idx_key(i) for i in v.idx))


def _idx_key(i):
    if isinstance(i, DS):
        return ("ds", _idx_key(i.start), _idx_key(i.size))
    return i


def _value_key(v, cache: Optional[dict] = None):
    """Stable fingerprint for world merging.  ``cache`` (id -> key)
    memoizes container fingerprints within one merge: cloned worlds
    share term DAGs, and uninterpreted-arithmetic chains alias their
    subterms heavily, so an uncached walk is quadratic-and-worse in
    model time.  Safe because every keyed object is held alive by a
    world for the duration of the merge."""
    if isinstance(v, (list, tuple)) and cache is not None:
        hit = cache.get(id(v))
        if hit is not None:
            return hit
    if isinstance(v, Ref):
        return ("ref", v.key)
    elif isinstance(v, View):
        return ("view", _view_key(v))
    elif isinstance(v, AtProxy):
        return ("at", v.ref.key)
    elif isinstance(v, DS):
        return ("ds", _value_key(v.start, cache), _value_key(v.size, cache))
    elif isinstance(v, Copy):
        return ("copy", v.key())
    elif isinstance(v, Closure):
        return ("closure", id(v.node))
    elif isinstance(v, BoundMethod):
        return ("bm", _value_key(v.recv, cache), v.name)
    elif isinstance(v, WhenPred):
        return ("when", _value_key(v.cond, cache))
    elif isinstance(v, Varargs):
        return ("varargs", v.name)
    elif isinstance(v, VarargTail):
        return ("vtail", v.base.name, v.start)
    elif isinstance(v, RangeVal):
        return ("range", v.lo, v.hi)
    elif isinstance(v, (list, tuple)):
        out = ("seq", tuple(_value_key(x, cache) for x in v))
        if cache is not None:
            cache[id(v)] = out
        return out
    elif isinstance(v, (int, float, bool, str)) or v is None:
        return v
    else:
        return ("opaque", repr(v))


# -- environments ---------------------------------------------------------


_MISSING = object()


class ModuleEnv:
    """Module-level constants + helper defs of the kernel's file,
    resolved lazily and shared by every world (values are constant)."""

    def __init__(self, project: Project, file):
        self.project = project
        self.file = file
        self._locals = FnLocals(file.tree) if file.tree else None
        self._cache: Dict[str, object] = {}

    def lookup(self, name: str, world: "World"):
        if name in self._cache:
            return self._cache[name]
        val = _MISSING
        if self._locals is not None:
            expr = self._locals.value_of(name)
            if expr is not None:
                c = const_int(expr)
                if c is not None:
                    val = c
                elif isinstance(expr, ast.Constant) and isinstance(
                        expr.value, (str, float, bool)):
                    val = expr.value
                elif isinstance(expr, ast.UnaryOp) \
                        and isinstance(expr.op, ast.USub) \
                        and isinstance(expr.operand, ast.Constant) \
                        and isinstance(expr.operand.value,
                                       (int, float)) \
                        and not isinstance(expr.operand.value, bool):
                    # `_NEG_INF = -1e30`: the sentinel-constant idiom
                    val = -expr.operand.value
        if val is _MISSING:
            fi = self.project.resolve_function(name, prefer_file=self.file)
            if fi is not None and fi.file is self.file \
                    and "." not in fi.qualname:
                val = Closure(fi.node, self)
        self._cache[name] = val
        return val

    def assign(self, name, value, world):  # pragma: no cover - defensive
        raise KernelSkip("assignment into module scope")


class WorldEnv:
    """The kernel-body scope: storage lives ON the world so closures
    defined before a fork read the forked world's bindings."""

    def __init__(self, parent: ModuleEnv):
        self.parent = parent

    def lookup(self, name: str, world: "World"):
        if name in world.kenv:
            return world.kenv[name]
        return self.parent.lookup(name, world)

    def assign(self, name, value, world):
        world.kenv[name] = value


class LocalEnv:
    """A call-frame scope (helper invocation / guarded-body execution);
    lives within one top-level statement, so a plain dict is safe."""

    def __init__(self, parent):
        self.parent = parent
        self.vars: Dict[str, object] = {}

    def lookup(self, name: str, world: "World"):
        if name in self.vars:
            return self.vars[name]
        return self.parent.lookup(name, world)

    def assign(self, name, value, world):
        self.vars[name] = value


# -- the world ------------------------------------------------------------


@dataclasses.dataclass
class _InFlight:
    copy: Copy
    step: int


class World:
    def __init__(self):
        self.kenv: Dict[str, object] = {}
        self.memo: Dict[tuple, bool] = {}
        self.bindings: Dict[tuple, int] = {}
        self.in_flight: List[_InFlight] = []
        self.stores: Dict[tuple, object] = {}
        self.findings: Set[tuple] = set()  # (line, tag, msg)
        self.activity = 0  # start/wait operations executed so far
        # per-copy records appended by an installed `on_copy_start`
        # hook (L016 byte accounting).  Like `stores`, EXCLUDED from
        # state_key: the merge keeps the higher-activity (max-DMA)
        # representative, so the surviving traffic log is a feasible
        # world's full copy stream, never a mix.
        self.traffic: List[tuple] = []

    def clone(self) -> "World":
        w = World.__new__(World)
        w.kenv = dict(self.kenv)
        w.memo = dict(self.memo)
        w.bindings = dict(self.bindings)
        w.in_flight = list(self.in_flight)
        w.stores = dict(self.stores)
        w.findings = set(self.findings)
        w.activity = self.activity
        w.traffic = list(self.traffic)
        return w

    def state_key(self, _cache: Optional[dict] = None):
        # Deliberately EXCLUDES `stores` and `memo`: stores are a value
        # cache (hazard checks consult only `in_flight`), and memo-only
        # divergence means the guard outcome changed nothing DMA-visible
        # — so worlds forked on compute-only guards (mask codes, causal
        # windows, dequant paths) collapse right after each statement.
        # The merged world keeps one representative's memo/stores: any
        # finding it reports is real for that feasible world; the cost
        # is possible (documented) under-exploration of the dropped
        # polarity, never a false report.
        flight: Dict[tuple, int] = {}
        for e in self.in_flight:
            fk = (e.copy.key(), e.step)
            flight[fk] = flight.get(fk, 0) + 1
        return (
            frozenset(flight.items()),
            frozenset(self.bindings.items()),
            frozenset((k, _value_key(v, _cache))
                      for k, v in self.kenv.items()),
        )

    def seed(self, key, option):
        kind = key[0]
        if kind == "memo":
            self.memo[key[1]] = option
        else:  # ("bind", termkey)
            self.bindings[key[1]] = option
            self._recheck_memo()

    def _recheck_memo(self):
        for atom, val in self.memo.items():
            decided = _fold_atom(atom, self.bindings)
            if decided is not None and decided != val:
                raise _DeadWorld()


# -- term algebra ---------------------------------------------------------


def _is_concrete(v) -> bool:
    return isinstance(v, (int, float, bool, str)) or v is None


_FOLD_OPS = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "floordiv": lambda a, b: a // b if b else None,
    "mod": lambda a, b: a % b if b else None,
    "min": min,
    "max": max,
    "cdiv": lambda a, b: -(-a // b) if b else None,
}


def _mk_op(name, a, b):
    if isinstance(a, (int, bool)) and isinstance(b, (int, bool)) \
            and name in _FOLD_OPS:
        v = _FOLD_OPS[name](int(a), int(b))
        if v is not None:
            return v
    # identity simplifications keep structural term equality useful
    if name == "add":
        if a == 0:
            return b
        if b == 0:
            return a
    if name == "sub" and b == 0:
        return a
    if name == "mul":
        if a == 0 or b == 0:
            return 0
        if a == 1:
            return b
        if b == 1:
            return a
    if name in ("min", "max") and a == b:
        return a
    return ("op", name, a, b)


def _subst(term, bindings):
    if not bindings or _is_concrete(term) or not isinstance(term, tuple):
        return term
    if term in bindings:
        return bindings[term]
    if term and term[0] in ("op", "cmp", "and", "or", "not", "call"):
        head = term[:2] if term[0] in ("op", "cmp", "call") else term[:1]
        args = [_subst(t, bindings) for t in term[len(head):]]
        if term[0] == "op" and len(args) == 2:
            return _mk_op(term[1], args[0], args[1])
        return head + tuple(args)
    return term


def _min_bound(term) -> Optional[int]:
    """PROVABLE lower bound of an integer term, None when unknown:
    lets ``fori_loop(0, jnp.maximum(n, 1), ...)`` skip the infeasible
    zero-trip world — the real cross-step-prefetch decode kernel relies
    on exactly that clamp to keep its predecessor's DMA consumed."""
    if isinstance(term, (int, bool)):
        return int(term)
    if isinstance(term, tuple) and term[:2] == ("op", "max"):
        bounds = [b for b in (_min_bound(term[2]), _min_bound(term[3]))
                  if b is not None]
        return max(bounds) if bounds else None
    return None


_CMP_CANON = {
    "lt": ("lt", False, False),   # a < b  -> lt(a,b)
    "gt": ("lt", False, True),    # a > b  -> lt(b,a)
    "gte": ("lt", True, False),   # a >= b -> not lt(a,b)
    "lte": ("lt", True, True),    # a <= b -> not lt(b,a)
    "eq": ("eq", False, False),
    "ne": ("eq", True, False),
    "is": ("is", False, False),
    "isnot": ("is", True, False),
}


def _canon_cmp(op, a, b) -> Tuple[tuple, bool]:
    base, neg, swap = _CMP_CANON[op]
    if swap:
        a, b = b, a
    if base in ("eq", "is") and repr(a) > repr(b):
        a, b = b, a
    return (base, a, b), neg


def _fold_atom(atom, bindings) -> Optional[bool]:
    kind = atom[0]
    if kind in ("lt", "eq"):
        a, b = _subst(atom[1], bindings), _subst(atom[2], bindings)
        if isinstance(a, (int, bool)) and isinstance(b, (int, bool)):
            return (a < b) if kind == "lt" else (a == b)
        if kind == "eq" and isinstance(a, str) and isinstance(b, str):
            return a == b
        if kind == "eq" and a == b and not _is_concrete(a):
            return True
        if kind == "lt" and isinstance(b, (int, bool)):
            mb = _min_bound(a)
            if mb is not None and mb >= int(b):
                return False  # a >= mb >= b, so a < b is impossible
        return None
    if kind == "is":
        a, b = _subst(atom[1], bindings), _subst(atom[2], bindings)
        if _is_concrete(a) and _is_concrete(b):
            return type(a) is type(b) and a == b
        if a == b:
            return True  # one term is identical to itself
        return None
    if kind == "truthy":
        v = _subst(atom[1], bindings)
        if isinstance(v, (int, bool)):
            return bool(v)
        return None
    return None


# -- overlap / matching ---------------------------------------------------


def _bounds(i) -> Optional[Tuple[int, int]]:
    """Concrete [lo, hi) interval of one index element, else None."""
    if isinstance(i, (int, bool)):
        return (int(i), int(i) + 1)
    if isinstance(i, DS) and isinstance(i.start, int) \
            and isinstance(i.size, int):
        return (i.start, i.start + i.size)
    return None


def _dim_rel(a, b) -> str:
    """'eq' | 'overlap' | 'disjoint' | 'unknown' for one dim pair."""
    if a == b and (a == _FULL or a == _ELL):
        return "eq"
    if a == _FULL or b == _FULL or a == _ELL or b == _ELL:
        return "overlap"
    ba, bb = _bounds(a), _bounds(b)
    if ba is not None and bb is not None:
        if ba == bb:
            return "eq"
        return "overlap" if ba[0] < bb[1] and bb[0] < ba[1] \
            else "disjoint"
    if a == b:
        return "eq"
    if isinstance(a, DS) and isinstance(b, DS) and a.start == b.start:
        return "overlap"  # same (possibly opaque) start, sizes >= 1
    if isinstance(a, DS) and a.start == b:
        return "overlap"
    if isinstance(b, DS) and b.start == a:
        return "overlap"
    return "unknown"


def _must_overlap(va: View, vb: View) -> bool:
    """True only when the two regions PROVABLY intersect: equal ref and
    every common-prefix dim structurally equal or concretely
    intersecting (a shorter tuple covers the longer's remainder)."""
    if va.ref != vb.ref:
        return False
    for a, b in zip(va.idx, vb.idx):
        if a == _ELL or b == _ELL:
            return True
        if _dim_rel(a, b) in ("disjoint", "unknown"):
            return False
    return True


def _sem_eq(va: View, vb: View) -> bool:
    if va.ref != vb.ref or len(va.idx) != len(vb.idx):
        return False
    return all(_dim_rel(a, b) == "eq" for a, b in zip(va.idx, vb.idx))


def _sem_must_differ(va: View, vb: View) -> bool:
    if va.ref != vb.ref:
        return True
    return any(_dim_rel(a, b) == "disjoint"
               for a, b in zip(va.idx, vb.idx))


# -- the interpreter ------------------------------------------------------

_MODULE_NAMES = frozenset({"jnp", "jax", "np", "pl", "pltpu", "lax",
                           "functools", "math", "partial"})

# pl/pltpu primitives the simulator cannot model yet: raw semaphore
# signalling and scoped scratch.  Encountering one is a SKIP (counted),
# never a guess.
_SKIP_CALLS = frozenset({"semaphore_signal", "semaphore_wait",
                         "semaphore_read", "run_scoped",
                         "make_async_remote_copy"})


class _Sim:
    def __init__(self, project: Project, site: PallasCallSite,
                 kernel: FunctionInfo, final_axis: int):
        self.project = project
        self.site = site
        self.kernel = kernel
        self.final_axis = final_axis
        self.module_env = ModuleEnv(project, kernel.file)
        self.kernel_env = WorldEnv(self.module_env)
        self.ops = 0
        self.step = 0
        # extension points for the L016 cost walk (cost_parity), which
        # re-runs this simulator under a concrete binding scenario:
        # `on_copy_start(world, copy, line)` observes every DMA issue;
        # `load_seed(refname, idx)` supplies concrete scalar-prefetch
        # values (else loads stay symbolic terms); `static_overrides`
        # replaces OPAQUE `_static_env` entries with scenario constants;
        # `max_unroll` is raised so real chunk loops aren't modeled
        # short (the `hi = lo + _MODEL_INT` clamp would silently drop
        # bytes).  All default to L014's exact behavior.
        self.on_copy_start = None
        self.load_seed = None
        self.static_overrides: Dict[str, object] = {}
        self.max_unroll = MAX_UNROLL

    def _fuel(self):
        self.ops += 1
        if self.ops > MAX_OPS:
            raise KernelSkip("interpreter fuel exhausted")

    # -- findings ---------------------------------------------------------

    def _note(self, world: World, line: int, tag: str, msg: str):
        world.findings.add((line, tag, msg))

    def _carry(self, ent: _InFlight) -> str:
        if ent.step != self.step:
            return (f" (cross-grid-iteration carry: started in step "
                    f"{ent.step}, still in flight in step {self.step})")
        return ""

    def _label(self, world: World, ref: Ref) -> str:
        """World-local name for a ref: forked worlds share Ref objects
        (and therefore `label` mutations) across diverged vararg
        layouts, so name lookup must go through THIS world's env."""
        for name, v in world.kenv.items():
            if isinstance(v, Ref) and v.key == ref.key:
                return name
        return ref.label

    def _check_read(self, world: World, view: View, line: int):
        for ent in world.in_flight:
            if _must_overlap(view, ent.copy.dst):
                self._note(
                    world, line, "read-before-wait",
                    f"read of `{self._label(world, view.ref)}` overlaps "
                    f"the destination of the DMA started at line "
                    f"{ent.copy.line} with no dominating wait"
                    + self._carry(ent))

    def _check_write(self, world: World, view: View, line: int):
        for ent in world.in_flight:
            if _must_overlap(view, ent.copy.dst):
                self._note(
                    world, line, "slot-overwrite",
                    f"write to `{self._label(world, view.ref)}` overlaps "
                    f"the destination of the in-flight DMA started at "
                    f"line {ent.copy.line}" + self._carry(ent))
            elif _must_overlap(view, ent.copy.src):
                self._note(
                    world, line, "source-overwrite",
                    f"write to `{self._label(world, view.ref)}` overlaps "
                    f"the SOURCE of the in-flight DMA started at line "
                    f"{ent.copy.line}" + self._carry(ent))

    def _do_start(self, world: World, copy: Copy, line: int):
        self._check_read(world, copy.src, line)
        self._check_write(world, copy.dst, line)
        world.in_flight.append(_InFlight(copy, self.step))
        world.activity += 1
        if self.on_copy_start is not None:
            self.on_copy_start(world, copy, line)

    def _do_wait(self, world: World, copy: Copy, line: int):
        world.activity += 1
        for i, ent in enumerate(world.in_flight):
            if _sem_eq(ent.copy.sem, copy.sem):
                del world.in_flight[i]
                return
        maybes = [i for i, ent in enumerate(world.in_flight)
                  if not _sem_must_differ(ent.copy.sem, copy.sem)]
        if maybes:
            # a may-match retires silently: unknown terms never report
            del world.in_flight[maybes[0]]
            return
        self._note(
            world, line, "wait-imbalance",
            f"wait on semaphore `{self._label(world, copy.sem.ref)}` "
            f"with no copy in flight on any matching slot along this "
            f"path — start/wait imbalance (the BENCH_r04/r05 wedge "
            f"shape)")

    # -- guards -----------------------------------------------------------

    def _resolve_bool(self, v, world: World) -> bool:
        self._fuel()
        v = _subst(v, world.bindings)
        if isinstance(v, (bool, int, float)):
            return bool(v)
        if isinstance(v, tuple):
            if v[0] == "and":
                return self._resolve_bool(v[1], world) \
                    and self._resolve_bool(v[2], world)
            if v[0] == "or":
                return self._resolve_bool(v[1], world) \
                    or self._resolve_bool(v[2], world)
            if v[0] == "not":
                return not self._resolve_bool(v[1], world)
            if v[0] == "cmp":
                atom, neg = _canon_cmp(v[1], v[2], v[3])
                known = _fold_atom(atom, world.bindings)
                if known is not None:
                    return known != neg
                if atom in world.memo:
                    return world.memo[atom] != neg
                raise _NeedChoice(("memo", atom), [True, False])
        atom = ("truthy", v)
        known = _fold_atom(atom, world.bindings)
        if known is not None:
            return known
        if atom in world.memo:
            return world.memo[atom]
        raise _NeedChoice(("memo", atom), [True, False])

    def _bind_int(self, term, world: World, options: List[int]) -> int:
        term = _subst(term, world.bindings)
        if isinstance(term, (int, bool)):
            return int(term)
        key = ("bind", term)
        if term in world.bindings:
            return world.bindings[term]
        raise _NeedChoice(key, options)

    # -- index helpers ----------------------------------------------------

    def _eval_index(self, node: ast.expr, env, world) -> tuple:
        elts = node.elts if isinstance(node, ast.Tuple) else [node]
        out = []
        for e in elts:
            if isinstance(e, ast.Slice):
                if e.lower is None and e.upper is None and e.step is None:
                    out.append(_FULL)
                else:
                    out.append((
                        "slice",
                        None if e.lower is None
                        else self.eval(e.lower, env, world),
                        None if e.upper is None
                        else self.eval(e.upper, env, world),
                        None if e.step is None
                        else self.eval(e.step, env, world)))
            elif isinstance(e, ast.Constant) and e.value is Ellipsis:
                out.append(_ELL)
            else:
                out.append(self.eval(e, env, world))
        return tuple(out)

    # -- expression evaluation -------------------------------------------

    _BINOPS = {ast.Add: "add", ast.Sub: "sub", ast.Mult: "mul",
               ast.FloorDiv: "floordiv", ast.Mod: "mod",
               ast.Div: "div", ast.Pow: "pow", ast.BitAnd: "and",
               ast.BitOr: "or", ast.BitXor: "xor",
               ast.LShift: "shl", ast.RShift: "shr",
               ast.MatMult: "matmul"}
    _CMPOPS = {ast.Lt: "lt", ast.Gt: "gt", ast.LtE: "lte",
               ast.GtE: "gte", ast.Eq: "eq", ast.NotEq: "ne",
               ast.Is: "is", ast.IsNot: "isnot"}

    def eval(self, node: ast.expr, env, world: World):
        self._fuel()
        if isinstance(node, ast.Constant):
            if node.value is Ellipsis:
                return _ELL
            return node.value
        if isinstance(node, ast.Name):
            v = env.lookup(node.id, world)
            if v is _MISSING:
                if node.id in _MODULE_NAMES:
                    return ("mod", node.id)
                return ("sym", node.id)
            return v
        if isinstance(node, ast.Attribute):
            if node.attr == "at":
                base = self.eval(node.value, env, world)
                if isinstance(base, Ref):
                    return AtProxy(base)
                return ("attr", _as_term(base), "at")
            base = self.eval(node.value, env, world)
            if isinstance(base, Copy) and node.attr in ("start", "wait"):
                return BoundMethod(base, node.attr)
            return ("attr", _as_term(base), node.attr)
        if isinstance(node, ast.BinOp):
            a = self.eval(node.left, env, world)
            b = self.eval(node.right, env, world)
            opname = self._BINOPS.get(type(node.op))
            if opname is None:
                return ("op", "unknown", _as_term(a), _as_term(b))
            if opname in _FOLD_OPS or opname in ("add", "sub", "mul",
                                                 "floordiv", "mod"):
                return _mk_op(opname, _as_term(a), _as_term(b))
            if isinstance(a, (int, bool)) and isinstance(b, (int, bool)):
                if opname == "shl":
                    return int(a) << int(b)
                if opname == "shr":
                    return int(a) >> int(b)
                if opname == "and":
                    return int(a) & int(b)
                if opname == "or":
                    return int(a) | int(b)
                if opname == "xor":
                    return int(a) ^ int(b)
            if opname == "and":
                return ("and", _as_term(a), _as_term(b))
            if opname == "or":
                return ("or", _as_term(a), _as_term(b))
            return ("op", opname, _as_term(a), _as_term(b))
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand, env, world)
            if isinstance(node.op, ast.USub):
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    return -v
                return _mk_op("sub", 0, _as_term(v))
            if isinstance(node.op, ast.Not):
                if _is_concrete(v):
                    return not v
                return ("not", _as_term(v))
            if isinstance(node.op, ast.Invert):
                # `~mask` is THE jax boolean-not idiom — losing it to an
                # opaque term decorrelates `~prev_prefetched` from
                # `prev_prefetched`, and the infeasible both-true world
                # re-runs a warmup over its predecessor's in-flight
                # prefetch (a false slot-overwrite on the static
                # cross-step decode variant)
                if isinstance(v, bool):
                    return not v
                if isinstance(v, int):
                    return ~v
                return ("not", _as_term(v))
            return ("op", "unary", _as_term(v), 0)
        if isinstance(node, ast.Compare):
            if len(node.ops) != 1:
                return ("sym", "<chained-compare>")
            a = self.eval(node.left, env, world)
            b = self.eval(node.comparators[0], env, world)
            opname = self._CMPOPS.get(type(node.ops[0]))
            if opname is None:
                return ("sym", "<compare>")
            if opname in ("is", "isnot"):
                # the enum-dispatch idiom: `cross_step_prefetch is True`
                # vs `== "static"` vs falsy.  Identity over the model's
                # value domain (interned literals) is type-and-value
                # equality; anything symbolic stays a per-TERM atom so
                # repeated tests of one static stay correlated instead
                # of collapsing into a single shared <compare> symbol.
                if _is_concrete(a) and _is_concrete(b):
                    same = type(a) is type(b) and a == b
                    return same if opname == "is" else not same
                return ("cmp", opname, _as_term(a), _as_term(b))
            if _is_concrete(a) and _is_concrete(b) \
                    and type(a) is type(b):
                return {"lt": a < b, "gt": a > b, "lte": a <= b,
                        "gte": a >= b, "eq": a == b,
                        "ne": a != b}[opname]
            if isinstance(a, (int, bool)) and isinstance(b, (int, bool)):
                a, b = int(a), int(b)
                return {"lt": a < b, "gt": a > b, "lte": a <= b,
                        "gte": a >= b, "eq": a == b,
                        "ne": a != b}[opname]
            return ("cmp", opname, _as_term(a), _as_term(b))
        if isinstance(node, ast.BoolOp):
            terms = [_as_term(self.eval(v, env, world))
                     for v in node.values]
            out = terms[0]
            kind = "and" if isinstance(node.op, ast.And) else "or"
            for t in terms[1:]:
                out = (kind, out, t)
            return out
        if isinstance(node, ast.IfExp):
            # pure two-branch values with an unknown test fork via the
            # shared memo, so `i += 1 if has_mask else 0` stays concrete
            b = self._resolve_bool(
                _as_term(self.eval(node.test, env, world)), world)
            return self.eval(node.body if b else node.orelse, env, world)
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node, env, world)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env, world)
        if isinstance(node, (ast.Tuple, ast.List)):
            return [self.eval(e, env, world) for e in node.elts]
        if isinstance(node, ast.Lambda):
            return Closure(node, env)
        if isinstance(node, ast.JoinedStr):
            return ("sym", "<fstring>")
        if isinstance(node, ast.Starred):
            raise KernelSkip("starred expression")
        return ("sym", f"<{type(node).__name__}>")

    def _eval_subscript(self, node: ast.Subscript, env, world: World):
        base = self.eval(node.value, env, world)
        if isinstance(base, Varargs):
            sl = node.slice
            if isinstance(sl, ast.Slice):
                if sl.upper is not None or sl.step is not None:
                    raise KernelSkip("vararg slice with upper bound")
                lo = self.eval(sl.lower, env, world) if sl.lower else 0
                if not isinstance(lo, int):
                    raise KernelSkip("vararg slice at unknown offset")
                return VarargTail(base, lo)
            i = self.eval(sl, env, world)
            if not isinstance(i, int):
                raise KernelSkip("vararg indexed by unknown value")
            return base.get(i)
        if isinstance(base, AtProxy):
            return View(base.ref, self._eval_index(node.slice, env, world))
        if isinstance(base, Ref):
            idx = self._eval_index(node.slice, env, world)
            view = View(base, idx)
            self._check_read(world, view, node.lineno)
            skey = (base.key, tuple(_idx_key(i) for i in idx))
            if skey in world.stores:
                return world.stores[skey]
            if self.load_seed is not None:
                seeded = self.load_seed(self._label(world, base), idx)
                if seeded is not None:
                    return seeded
            return ("load", base.key, tuple(_idx_key(i) for i in idx))
        if isinstance(base, (list, tuple)):
            sl = node.slice
            if isinstance(sl, ast.Slice):
                lo = self.eval(sl.lower, env, world) if sl.lower else None
                hi = self.eval(sl.upper, env, world) if sl.upper else None
                if (lo is None or isinstance(lo, int)) \
                        and (hi is None or isinstance(hi, int)):
                    return list(base)[lo:hi]
                return ("sym", "<seq-slice>")
            i = self.eval(sl, env, world)
            if isinstance(i, int) and -len(base) <= i < len(base):
                return base[i]
            return ("sym", "<seq-index>")
        idx = self._eval_index(node.slice, env, world)
        return ("op", "index", _as_term(base),
                tuple(_idx_key(i) for i in idx))

    def _call_closure(self, clo: Closure, args: list, kwargs: dict,
                      world: World):
        self._fuel()
        frame = LocalEnv(clo.env)
        a = clo.node.args
        params = [p.arg for p in a.posonlyargs + a.args]
        defaults = a.defaults or []
        # positional params without a supplied arg take their default
        # (evaluated at call time in the defining scope — close enough
        # for the `def _(j=j)` capture idiom, whose default is a local)
        ndef = len(defaults)
        for i, p in enumerate(params):
            if i < len(args):
                frame.vars[p] = args[i]
            elif p in kwargs:
                frame.vars[p] = kwargs.pop(p)
            else:
                di = i - (len(params) - ndef)
                if 0 <= di < ndef:
                    frame.vars[p] = self.eval(defaults[di], clo.env, world)
                else:
                    frame.vars[p] = ("sym", p)
        for kw, kwd in zip(a.kwonlyargs, a.kw_defaults):
            if kw.arg in kwargs:
                frame.vars[kw.arg] = kwargs.pop(kw.arg)
            elif kwd is not None:
                frame.vars[kw.arg] = self.eval(kwd, clo.env, world)
            else:
                frame.vars[kw.arg] = ("sym", kw.arg)
        if a.vararg is not None:
            frame.vars[a.vararg.arg] = list(args[len(params):])
        if isinstance(clo.node, ast.Lambda):
            return self.eval(clo.node.body, frame, world)
        try:
            self.exec_body(clo.node.body, frame, world)
        except _Return as r:
            return r.value
        return None

    def _eval_call(self, node: ast.Call, env, world: World):
        func = node.func
        base = expr_basename(func)
        root = expr_root(func)

        if base in _SKIP_CALLS:
            raise KernelSkip(f"unmodeled primitive `{base}`")

        def _args():
            out = []
            for a in node.args:
                if isinstance(a, ast.Starred):
                    v = self.eval(a.value, env, world)
                    if not isinstance(v, (list, tuple)):
                        raise KernelSkip(
                            "star-unpack of unknown-length value")
                    out.extend(v)
                else:
                    out.append(self.eval(a, env, world))
            return out

        def _kwargs():
            return {k.arg: self.eval(k.value, env, world)
                    for k in node.keywords if k.arg}

        # list mutation: the real kv_dmas helpers build their copy
        # batches with `dmas.append(make_async_copy(...))`
        if isinstance(func, ast.Attribute) \
                and base in ("append", "extend"):
            recv = self.eval(func.value, env, world)
            if isinstance(recv, list):
                args = _args()
                if base == "append":
                    recv.append(args[0] if args else None)
                elif args and isinstance(args[0], (list, tuple)):
                    recv.extend(args[0])
                else:
                    raise KernelSkip("list.extend of unknown iterable")
                return None
        # method calls on evaluated receivers (copy.start()/.wait())
        if isinstance(func, ast.Attribute) \
                and base in ("start", "wait"):
            recv = self.eval(func.value, env, world)
            if isinstance(recv, Copy):
                if base == "start":
                    self._do_start(world, recv, node.lineno)
                else:
                    self._do_wait(world, recv, node.lineno)
                return None
            raise KernelSkip(f".{base}() on unresolved copy object")
        if base == "make_async_copy":
            args = _args()
            if len(args) != 3:
                raise KernelSkip("make_async_copy arity != 3")
            views = []
            for a in args:
                if isinstance(a, Ref):
                    a = View(a, (_ELL,))
                if not isinstance(a, View):
                    raise KernelSkip("make_async_copy operand is not a "
                                     "resolvable ref view")
                views.append(a)
            return Copy(views[0], views[1], views[2], node.lineno)
        if base == "when" and root in ("pl", None):
            args = _args()
            return WhenPred(_as_term(args[0]) if args else True)
        if base == "ds":
            args = _args()
            if len(args) == 1:
                return DS(0, _as_term(args[0]))
            return DS(_as_term(args[0]), _as_term(args[1]))
        if base == "program_id":
            axis = const_int(node.args[0]) if node.args else None
            if axis is not None and axis % max(self.grid_rank, 1) \
                    == self.final_axis:
                return self.step
            return ("sym", f"pid{axis}")
        if base == "num_programs":
            axis = const_int(node.args[0]) if node.args else None
            if axis is not None and axis % max(self.grid_rank, 1) \
                    == self.final_axis:
                return N_STEPS
            return ("sym", f"nprog{axis}")
        if base == "fori_loop":
            return self._eval_fori(node, env, world)
        if base == "cond" and root in ("lax", "jax"):
            return self._eval_lax_cond(node, env, world)
        if base in ("minimum", "maximum"):
            a, b = (_as_term(v) for v in _args()[:2])
            return _mk_op("min" if base == "minimum" else "max", a, b)
        if base == "where":
            args = _args()
            if len(args) == 3:
                # scalar select with a concrete predicate picks its
                # branch (`jnp.where(b == 0, 0, base_smem[0])` — the
                # cross-step slot-parity seed); a symbolic/array
                # predicate stays an uninterpreted call
                if isinstance(args[0], (bool, int)):
                    return args[1] if args[0] else args[2]
            return ("call", "where",
                    tuple(_as_term(a) for a in args))
        if base in ("rem", "remainder"):
            a, b = (_as_term(v) for v in _args()[:2])
            return _mk_op("mod", a, b)
        if base == "cdiv":
            a, b = (_as_term(v) for v in _args()[:2])
            return _mk_op("cdiv", a, b)
        if base == "logical_and":
            a, b = (_as_term(v) for v in _args()[:2])
            return ("and", a, b)
        if base == "logical_or":
            a, b = (_as_term(v) for v in _args()[:2])
            return ("or", a, b)
        if base == "logical_not":
            return ("not", _as_term(_args()[0]))
        if base == "range" and isinstance(func, ast.Name):
            args = [_as_term(v) for v in _args()]
            if len(args) == 1:
                lo, hi = 0, args[0]
            elif len(args) >= 2:
                lo, hi = args[0], args[1]
            else:
                raise KernelSkip("range() without bounds")
            if not isinstance(lo, int):
                raise KernelSkip("range() with unknown start")
            if not isinstance(hi, int):
                hi = self._bind_int(hi, world, [_MODEL_INT])
            if hi - lo > self.max_unroll:
                if self.on_copy_start is not None:
                    # a short model silently DROPS bytes — in a cost
                    # walk that is a guess, so it must be a skip
                    raise KernelSkip(
                        f"range({hi - lo}) exceeds the cost-walk "
                        f"unroll ceiling {self.max_unroll}")
                hi = lo + _MODEL_INT  # model a long static loop short
            return RangeVal(lo, hi)
        if base == "len" and isinstance(func, ast.Name):
            args = _args()
            if args and isinstance(args[0], (list, tuple)):
                return len(args[0])
            return ("sym", "<len>")
        if base in ("int", "bool", "abs", "float") \
                and isinstance(func, ast.Name):
            args = _args()
            if args and _is_concrete(args[0]):
                try:
                    return {"int": int, "bool": bool, "abs": abs,
                            "float": float}[base](args[0])
                except (TypeError, ValueError):
                    pass
            return ("call", base, _as_term(args[0]) if args else 0)

        # user value in function position: closures, when-predicates
        callee = None
        if isinstance(func, ast.Name):
            callee = env.lookup(func.id, world)
        elif isinstance(func, ast.Call):
            callee = self.eval(func, env, world)
        if isinstance(callee, WhenPred):
            args = _args()
            if len(args) == 1 and isinstance(args[0], Closure):
                if self._resolve_bool(callee.cond, world):
                    return self._call_closure(args[0], [], {}, world)
                return None
            raise KernelSkip("pl.when(...) applied to a non-closure")
        if isinstance(callee, Closure):
            return self._call_closure(callee, _args(), _kwargs(), world)
        if isinstance(callee, BoundMethod):
            if callee.name == "start":
                self._do_start(world, callee.recv, node.lineno)
            else:
                self._do_wait(world, callee.recv, node.lineno)
            return None

        # anything else: uninterpreted.  Refs passed whole count as
        # reads (zeros_like(ref) et al touch at most the metadata, but
        # MUST semantics keeps that from ever reporting falsely).  The
        # receiver of a method call (`qbuf[qslot].reshape(...)`) is a
        # read too — evaluate it so its subscripts get checked.
        args = _args()
        _kwargs()
        if isinstance(func, ast.Attribute) \
                and not isinstance(func.value, ast.Name):
            recv = self.eval(func.value, env, world)
            if isinstance(recv, Ref):
                self._check_read(world, View(recv, (_ELL,)), node.lineno)
            if isinstance(recv, Copy):
                raise KernelSkip("copy object escapes into an "
                                 "uninterpreted method call")
        for a in args:
            if isinstance(a, Ref):
                self._check_read(world, View(a, (_ELL,)), node.lineno)
            if isinstance(a, Copy):
                raise KernelSkip("copy object escapes into an "
                                 "uninterpreted call")
        return ("call", base or "<expr>",
                tuple(_as_term(a) for a in args))

    def _eval_fori(self, node: ast.Call, env, world: World):
        if len(node.args) < 4:
            raise KernelSkip("fori_loop arity < 4")
        lo = self.eval(node.args[0], env, world)
        hi = _as_term(self.eval(node.args[1], env, world))
        body = self.eval(node.args[2], env, world)
        carry = self.eval(node.args[3], env, world)
        if not isinstance(lo, int):
            raise KernelSkip("fori_loop with unknown lower bound")
        if not isinstance(body, Closure):
            raise KernelSkip("fori_loop body is not a local function")
        hi = _subst(hi, world.bindings)
        if isinstance(hi, (int, bool)):
            trips = int(hi) - lo
            if trips > self.max_unroll:
                raise KernelSkip(
                    f"fori_loop with {trips} static iterations")
        else:
            mb = max(0, _min_bound(hi) or 0)
            if mb > MAX_TRIP:
                raise KernelSkip("fori_loop bound too large to model")
            trips = self._bind_int(
                hi, world, list(range(mb, MAX_TRIP + 1))) - lo
        for it in range(lo, lo + max(0, trips)):
            carry = self._call_closure(body, [it, carry], {}, world)
        return carry

    def _eval_lax_cond(self, node: ast.Call, env, world: World):
        pred = _as_term(self.eval(node.args[0], env, world))
        branches = [self.eval(a, env, world) for a in node.args[1:3]]
        operands = [self.eval(a, env, world) for a in node.args[3:]]
        b = self._resolve_bool(pred, world)
        chosen = branches[0] if b else branches[1]
        if not isinstance(chosen, Closure):
            raise KernelSkip("lax.cond branch is not a local function")
        return self._call_closure(chosen, operands, {}, world)

    # -- statements -------------------------------------------------------

    def exec_body(self, stmts: List[ast.stmt], env, world: World):
        for s in stmts:
            self.exec_stmt(s, env, world)

    def _assign_target(self, target: ast.expr, value, env, world: World):
        if isinstance(target, ast.Name):
            env.assign(target.id, value, world)
            if isinstance(value, Ref) and value.label == value.key:
                value.label = target.id
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            if isinstance(value, Varargs):
                value = VarargTail(value, 0)
            if isinstance(value, VarargTail):
                if any(isinstance(e, ast.Starred) for e in elts):
                    raise KernelSkip("starred unpack of kernel varargs")
                value = [value.base.get(value.start + k)
                         for k in range(len(elts))]
            if not isinstance(value, (list, tuple)) \
                    or len(value) != len(elts):
                raise KernelSkip("tuple unpack of unknown-length value")
            for t, v in zip(elts, value):
                self._assign_target(t, v, env, world)
            return
        if isinstance(target, ast.Subscript):
            base = self.eval(target.value, env, world)
            if isinstance(base, AtProxy):
                base = base.ref
            if isinstance(base, Ref):
                idx = self._eval_index(target.slice, env, world)
                view = View(base, idx)
                self._check_write(world, view, target.lineno)
                skey = (base.key, tuple(_idx_key(i) for i in idx))
                world.stores[skey] = _as_term(value) \
                    if _is_concrete(value) or isinstance(value, tuple) \
                    else ("sym", "<stored>")
                return
            if isinstance(base, list):
                return  # python-list mutation: value not tracked
            raise KernelSkip("store through an unresolved subscript")
        if isinstance(target, ast.Starred):
            raise KernelSkip("starred assignment")
        if isinstance(target, ast.Attribute):
            raise KernelSkip("attribute assignment in kernel body")
        raise KernelSkip(f"unhandled assign target "
                         f"{type(target).__name__}")

    def exec_stmt(self, stmt: ast.stmt, env, world: World):
        self._fuel()
        if isinstance(stmt, ast.Expr):
            self.eval(stmt.value, env, world)
            return
        if isinstance(stmt, ast.Assign):
            value = self.eval(stmt.value, env, world)
            for t in stmt.targets:
                self._assign_target(t, value, env, world)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign_target(
                    stmt.target, self.eval(stmt.value, env, world),
                    env, world)
            return
        if isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                load: ast.expr = ast.Name(id=stmt.target.id,
                                          ctx=ast.Load())
            elif isinstance(stmt.target, ast.Subscript):
                # `acc_ref[...] += dot(...)` — the MXU accumulate
                # idiom: desugar to load, fold, store so the read is
                # hazard-checked and the stored term stays typed
                load = ast.Subscript(value=stmt.target.value,
                                     slice=stmt.target.slice,
                                     ctx=ast.Load())
            else:
                raise KernelSkip("augmented assign to non-name")
            cur = self.eval(ast.copy_location(load, stmt), env, world)
            rhs = self.eval(stmt.value, env, world)
            opname = self._BINOPS.get(type(stmt.op))
            if opname in _FOLD_OPS or opname in ("add", "sub", "mul",
                                                 "floordiv", "mod"):
                nv = _mk_op(opname, _as_term(cur), _as_term(rhs))
            else:
                nv = ("op", opname or "unknown", _as_term(cur),
                      _as_term(rhs))
            if isinstance(stmt.target, ast.Name):
                env.assign(stmt.target.id, nv, world)
            else:
                self._assign_target(stmt.target, nv, env, world)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            when_cond = None
            for dec in stmt.decorator_list:
                if isinstance(dec, ast.Call) \
                        and expr_basename(dec.func) == "when":
                    when_cond = _as_term(
                        self.eval(dec.args[0], env, world))
            clo = Closure(stmt, env)
            if when_cond is not None:
                if self._resolve_bool(when_cond, world):
                    self._call_closure(clo, [], {}, world)
                env.assign(stmt.name, None, world)
            else:
                env.assign(stmt.name, clo, world)
            return
        if isinstance(stmt, ast.If):
            b = self._resolve_bool(
                _as_term(self.eval(stmt.test, env, world)), world)
            self.exec_body(stmt.body if b else stmt.orelse, env, world)
            return
        if isinstance(stmt, ast.For):
            it = self.eval(stmt.iter, env, world)
            if isinstance(it, RangeVal):
                items = it.items()
            elif isinstance(it, (list, tuple)):
                items = list(it)
            else:
                if _mentions_dma(stmt):
                    raise KernelSkip("for-loop over unknown iterable "
                                     "containing DMA operations")
                return
            for item in items:
                try:
                    self._assign_target(stmt.target, item, env, world)
                    self.exec_body(stmt.body, env, world)
                except _Continue:
                    continue
                except _Break:
                    break
            else:
                self.exec_body(stmt.orelse, env, world)
            return
        if isinstance(stmt, ast.While):
            if _mentions_dma(stmt):
                raise KernelSkip("while-loop containing DMA operations")
            return
        if isinstance(stmt, ast.Return):
            raise _Return(None if stmt.value is None
                          else self.eval(stmt.value, env, world))
        if isinstance(stmt, ast.Break):
            raise _Break()
        if isinstance(stmt, ast.Continue):
            raise _Continue()
        if isinstance(stmt, (ast.Pass, ast.Global, ast.Nonlocal,
                             ast.Import, ast.ImportFrom)):
            return
        if isinstance(stmt, ast.Assert):
            self.eval(stmt.test, env, world)
            return
        if isinstance(stmt, (ast.Raise, ast.Try, ast.With,
                             ast.AsyncWith, ast.ClassDef)):
            if _mentions_dma(stmt):
                raise KernelSkip(
                    f"{type(stmt).__name__} containing DMA operations")
            return
        if isinstance(stmt, ast.Delete):
            return
        raise KernelSkip(f"unhandled statement {type(stmt).__name__}")

    # -- top-level driver -------------------------------------------------

    def _run_stmt_forked(self, stmt: ast.stmt,
                         world: World) -> List[World]:
        """Execute one top-level statement, forking on every fresh
        guard choice: each fork seeds the memo/binding and re-executes
        the statement on a clone of the pre-statement state."""
        queue = [world]
        done: List[World] = []
        paths = 0
        while queue:
            paths += 1
            if paths > MAX_STMT_PATHS:
                raise KernelSkip("guard fork explosion in one statement")
            w = queue.pop()
            w2 = w.clone()
            try:
                self.exec_stmt(stmt, self.kernel_env, w2)
                done.append(w2)
            except _NeedChoice as nc:
                for opt in nc.options:
                    w3 = w.clone()
                    try:
                        w3.seed(nc.key, opt)
                    except _DeadWorld:
                        continue
                    queue.append(w3)
            except _DeadWorld:
                continue
        return done

    @staticmethod
    def _merge(worlds: List[World]) -> List[World]:
        # On collision keep the HIGHER-ACTIVITY world: its memo is the
        # one that actually started/waited DMAs, and the grid repeats
        # the same statements next step — so a guard polarity that
        # fired a start this step (e.g. an over-wide warmup that
        # re-fires every step and wedges) stays represented instead of
        # being shadowed by its idle twin.  The kept world is feasible,
        # so this can never create a false report.
        by_key: Dict[tuple, World] = {}
        cache: dict = {}
        for w in worlds:
            k = w.state_key(cache)
            kept = by_key.get(k)
            if kept is None:
                by_key[k] = w
            elif w.activity > kept.activity:
                w.findings |= kept.findings
                by_key[k] = w
            else:
                kept.findings |= w.findings
        return list(by_key.values())

    def _eval_test_forked(self, test: ast.expr,
                          world: World) -> List[Tuple[World, bool]]:
        """Resolve one `if` test on its own, forking only on the test."""
        queue = [world]
        out: List[Tuple[World, bool]] = []
        paths = 0
        while queue:
            paths += 1
            if paths > MAX_STMT_PATHS:
                raise KernelSkip("guard fork explosion in one test")
            w = queue.pop()
            w2 = w.clone()
            try:
                b = self._resolve_bool(
                    _as_term(self.eval(test, self.kernel_env, w2)), w2)
                out.append((w2, b))
            except _NeedChoice as nc:
                for opt in nc.options:
                    w3 = w.clone()
                    try:
                        w3.seed(nc.key, opt)
                    except _DeadWorld:
                        continue
                    queue.append(w3)
            except _DeadWorld:
                continue
        return out

    def _run_block_forked(self, stmts: List[ast.stmt],
                          worlds: List[World]) -> List[World]:
        """Run a statement block over a world set, merging after every
        statement.  Plain `if` statements recurse so each nested
        statement forks independently — without this, a module-sized
        ``if attend:`` block re-executes once per guard COMBINATION
        (exponential fuel) instead of once per guard."""
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                true_ws: List[World] = []
                false_ws: List[World] = []
                for w in worlds:
                    for w2, b in self._eval_test_forked(stmt.test, w):
                        (true_ws if b else false_ws).append(w2)
                nxt: List[World] = []
                if true_ws:
                    nxt.extend(self._run_block_forked(stmt.body, true_ws))
                if false_ws:
                    nxt.extend(
                        self._run_block_forked(stmt.orelse, false_ws)
                        if stmt.orelse else false_ws)
                worlds = self._merge(nxt)
            else:
                nxt = []
                for w in worlds:
                    nxt.extend(self._run_stmt_forked(stmt, w))
                worlds = self._merge(nxt)
            if not worlds:
                raise KernelSkip("every model world died "
                                 "(inconsistent guard model)")
            if len(worlds) > MAX_WORLDS:
                raise KernelSkip("model world explosion")
        return worlds

    def _run_worlds(self) -> List[World]:
        """The small-step walk itself: N_STEPS modeled grid steps over
        every surviving world, returned WITH their per-world state so
        subclasses (the L016 cost model) can read accumulated traffic
        before finding extraction."""
        node = self.kernel.node
        a = node.args
        self.grid_rank = self.site.grid_rank or 1
        statics = _static_env(self.site, self.kernel)
        for name, val in self.static_overrides.items():
            # scenario constants replace only OPAQUE statics: literal
            # binds and the final-grid-axis N_STEPS tie stay the model's
            cur = statics.get(name)
            if cur is None or (isinstance(cur, tuple)
                               and cur[:1] == ("static",)):
                statics[name] = val
        pos_params = [p.arg for p in a.posonlyargs + a.args]

        base = World()
        worlds = [base]
        for step in range(N_STEPS):
            self.step = step
            for w in worlds:
                w.kenv = {}
                for i, p in enumerate(pos_params):
                    if i < self.site.kernel_bound_posargs:
                        w.kenv[p] = statics.get(p, ("static", p))
                    else:
                        w.kenv[p] = Ref(p)
                for kw in a.kwonlyargs:
                    w.kenv[kw.arg] = statics.get(
                        kw.arg, ("static", kw.arg))
                if a.vararg is not None:
                    w.kenv[a.vararg.arg] = Varargs(a.vararg.arg)
            # Ref equality is by key (the param name), so re-creating
            # them per step above is identity-preserving per world.
            worlds = self._run_block_forked(node.body, worlds)
        return worlds

    def run(self) -> List[Finding]:
        worlds = self._run_worlds()
        findings: Set[tuple] = set()
        for w in worlds:
            for ent in w.in_flight:
                w.findings.add((
                    ent.copy.line, "dangling-dma",
                    f"DMA started at line {ent.copy.line} (step "
                    f"{ent.step}) is never waited along some path "
                    f"through the grid — start/wait imbalance that "
                    f"wedges the chip on teardown"))
            findings |= w.findings
        out: List[Finding] = []
        for line, tag, msg in sorted(findings):
            out.append(Finding(
                "L014", self.kernel.file.path, line,
                self.kernel.qualname, f"[{tag}] {msg}"))
        return out


def _as_term(v):
    """Coerce an evaluated value into a hashable term for memo keys and
    arithmetic; refs/views keep their identity keys."""
    if isinstance(v, Ref):
        return ("refval", v.key)
    if isinstance(v, View):
        return ("viewval", _view_key(v))
    if isinstance(v, DS):
        return v
    if isinstance(v, list):
        return tuple(_as_term(x) for x in v)
    if isinstance(v, (Copy, Closure, BoundMethod, WhenPred, Varargs,
                      VarargTail, AtProxy, RangeVal)):
        return ("objval", _value_key(v))
    return v


# -- static parameter seeding --------------------------------------------


def _static_env(site: PallasCallSite,
                kernel: FunctionInfo) -> Dict[str, object]:
    """Partial-bound kernel params evaluated in the launcher's scope:
    literals resolve concretely; a value expr that IS the final grid
    element ties to N_STEPS (the `num_units` coupling every
    cross-unit-prefetch guard needs); the rest stay opaque statics."""
    out: Dict[str, object] = {}
    grid_last = None
    if site.grid_exprs:
        grid_last = ast.dump(site.grid_exprs[-1])
    # trampoline forks carry bound exprs written in the CALLER's scope
    expr_locals = site.bound_expr_locals or site.locals_

    def _value(name: str, expr: ast.expr):
        if grid_last is not None and ast.dump(expr) == grid_last:
            return N_STEPS
        c = const_int(expr)
        if c is not None:
            return c
        if isinstance(expr, ast.Constant) and isinstance(
                expr.value, (str, float, bool)):
            return expr.value
        if isinstance(expr, ast.Name):
            v = expr_locals.value_of(expr.id)
            if v is not None:
                return _value(name, v)
        if isinstance(expr, ast.UnaryOp) \
                and isinstance(expr.op, ast.USub):
            c = const_int(expr)
            if c is not None:
                return c
        return ("static", name)

    a = kernel.node.args
    pos_params = [p.arg for p in a.posonlyargs + a.args]
    for i, expr in enumerate(site.kernel_bound_posarg_exprs):
        if i < len(pos_params):
            out[pos_params[i]] = _value(pos_params[i], expr)
    for name, expr in site.kernel_bound_kwarg_exprs.items():
        out[name] = _value(name, expr)
    return out


# -- DMA reachability scan ------------------------------------------------


def _mentions_dma(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) \
                and n.attr in ("make_async_copy", "start", "wait"):
            return True
        if isinstance(n, ast.Name) and n.id == "make_async_copy":
            return True
    return False


def _kernel_has_dma(project: Project, kernel: FunctionInfo,
                    _depth: int = 0,
                    _seen: Optional[Set[int]] = None) -> bool:
    """Transitive make_async_copy reachability: the kernel body plus
    same-project helpers it calls by name (one name-resolution hop per
    level, depth-capped)."""
    if _seen is None:
        _seen = set()
    if id(kernel.node) in _seen or _depth > 3:
        return False
    _seen.add(id(kernel.node))
    for n in ast.walk(kernel.node):
        if isinstance(n, (ast.Attribute, ast.Name)) \
                and (getattr(n, "attr", None) == "make_async_copy"
                     or getattr(n, "id", None) == "make_async_copy"):
            return True
    for n in ast.walk(kernel.node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
            fi = project.resolve_function(
                n.func.id, prefer_file=kernel.file)
            if fi is not None and _kernel_has_dma(
                    project, fi, _depth + 1, _seen):
                return True
    return False


# -- pass driver ----------------------------------------------------------

# The symbolic walk is the analyzer's one genuinely expensive pass
# (seconds over the package tree), and a build runs it several times
# over IDENTICAL sources — the driver, `obs doctor`'s coverage counts,
# and every whole-tree test each construct their own Project.  Memoize
# on file content, not Project identity, so all of them share one walk;
# a single mutated source (the skew tests) misses cleanly.
_MEMO_CAP = 32
_memo: "Dict[tuple, tuple]" = {}


def _memo_key(project: Project):
    return tuple(sorted((sf.path, hash(sf.src)) for sf in project.files))


def _analyze(project: Project):
    """-> (findings, stats) shared by run() and stats() — memoized on
    source content (see _memo above)."""
    key = _memo_key(project)
    hit = _memo.get(key)
    if hit is not None:
        return hit
    result = _analyze_uncached(project)
    if len(_memo) >= _MEMO_CAP:
        _memo.pop(next(iter(_memo)))
    _memo[key] = result
    return result


def _analyze_uncached(project: Project):
    findings: List[Finding] = []
    stats = {"kernels_analyzed": 0, "kernels_skipped": 0,
             "kernels_no_dma": 0, "sites_unresolved": 0,
             "skip_reasons": {}}
    seen: Set[tuple] = set()
    emitted: Set[tuple] = set()
    for site in project.pallas_sites:
        if site.kernel is None:
            stats["sites_unresolved"] += 1
            continue
        key = (id(site.kernel.node), site.call.lineno, site.file.path)
        if key in seen:
            continue
        seen.add(key)
        if not _kernel_has_dma(project, site.kernel):
            stats["kernels_no_dma"] += 1
            continue
        try:
            if site.grid_rank is None:
                raise KernelSkip("grid rank not statically visible")
            sim = _Sim(project, site, site.kernel,
                       final_axis=site.grid_rank - 1)
            for f in sim.run():
                fkey = (f.filename, f.line, f.message)
                if fkey not in emitted:
                    emitted.add(fkey)
                    findings.append(f)
            stats["kernels_analyzed"] += 1
        except KernelSkip as e:
            stats["kernels_skipped"] += 1
            reason = str(e) or "unexecutable kernel"
            stats["skip_reasons"][f"{site.kernel.qualname}"] = reason
    return findings, stats


def run(project: Project) -> List[Finding]:
    findings, _stats = _analyze(project)
    return list(findings)  # memoized — hand out a copy


def stats(project: Project) -> dict:
    """analyzed-vs-skipped kernel counts for ``obs doctor`` — the L013
    no-silent-skip rule applied to kernel bodies."""
    _findings, st = _analyze(project)
    return {**st, "skip_reasons": dict(st["skip_reasons"])}
