"""L005: every ``@flashinfer_api`` op must be in the obs metric catalog.

The obs layer's per-op metrics (``api.calls{op=}``, ``api.dispatch_us``)
are only as complete as the catalog that documents them: a new public
op decorated with ``@flashinfer_api`` but missing from
``flashinfer_tpu.obs.catalog.API_OPS`` would emit metrics nobody
documented, dashboarded, or audited — the "ships unobserved" failure
mode ISSUE 2's satellite list names.  This pass closes the loop
statically: the decorated surface and the catalog must agree.

Flags:

- a decorated function whose op name (the ``name=`` kwarg literal, or
  the function's qualname) is absent from ``API_OPS``;
- a decorated function whose ``name=`` is a non-literal expression —
  unverifiable statically, so it must be a literal.

Suppression: ``# graft-lint: ok <reason>`` on the ``def`` line (e.g.
for an intentionally-internal decorated helper).
"""

from __future__ import annotations

import ast
from typing import FrozenSet, List, Optional

from flashinfer_tpu.analysis.core import Finding, Project

CODE = "L005"


def _decorator_is_api(dec: ast.expr) -> Optional[ast.Call]:
    """The ``flashinfer_api`` decorator node, bare or called form;
    returns the Call node (or a sentinel None-args marker) when it IS
    the decorator, else None-ish False."""
    target = dec.func if isinstance(dec, ast.Call) else dec
    name = (target.attr if isinstance(target, ast.Attribute)
            else target.id if isinstance(target, ast.Name) else None)
    if name != "flashinfer_api":
        return None
    return dec if isinstance(dec, ast.Call) else ast.Call(
        func=target, args=[], keywords=[])


def _catalog_ops() -> FrozenSet[str]:
    from flashinfer_tpu.obs.catalog import API_OPS

    return API_OPS


def run(project: Project,
        ops: Optional[FrozenSet[str]] = None) -> List[Finding]:
    if ops is None:
        ops = _catalog_ops()
    findings: List[Finding] = []
    for sf in project.files:
        if sf.tree is None:
            continue

        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, prefix + child.name + ".")
                    continue
                if not isinstance(child,
                                  (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                qual = prefix + child.name
                for dec in child.decorator_list:
                    call = _decorator_is_api(dec)
                    if call is None:
                        continue
                    op = qual
                    dynamic = False
                    for kw in call.keywords:
                        if kw.arg == "name":
                            if isinstance(kw.value, ast.Constant) and \
                                    isinstance(kw.value.value, str):
                                op = kw.value.value
                            else:
                                dynamic = True
                    if dynamic:
                        findings.append(Finding(
                            CODE, sf.path, child.lineno, qual,
                            "@flashinfer_api name= is not a string "
                            "literal — the obs catalog check needs a "
                            "static op name"))
                    elif op not in ops:
                        findings.append(Finding(
                            CODE, sf.path, child.lineno, qual,
                            f"public op {op!r} is decorated with "
                            "@flashinfer_api but absent from "
                            "flashinfer_tpu.obs.catalog.API_OPS — add "
                            "it to the catalog (and to docs/"
                            "observability.md) so it cannot ship "
                            "unobserved"))
                # nested defs can also be decorated (factory-built APIs)
                visit(child, qual + ".")

        visit(sf.tree, "")
    return findings
