"""L002 — signature parity against the recorded reference call shapes.

The port's contract is that a VERBATIM reference call site either works
identically or fails loudly — it must never silently misbind.  The
classic violation (ADVICE.md round 5, item 2): ``BatchAttention.plan``
grew ``window_left`` positionally between ``logits_soft_cap`` and
``q_data_type``, so a reference caller passing the dtypes positionally
bound a dtype into ``window_left`` with no error.

The pass checks every symbol recorded in ``reference_signatures.json``
(the signature bank, seeded from the reference snapshot) against the
implementation's AST:

- each positional parameter (positional-only or positional-or-keyword,
  after self/cls) must match the reference's positional list name-for-
  name, in order — any insertion or reorder is a finding;
- parameters the implementation adds beyond the reference's positional
  arity must be keyword-only (after ``*``) so a reference positional
  call overflows loudly instead of misbinding.

An implementation may take FEWER parameters positionally than the
reference (the rest keyword-only): reference positional calls then
raise TypeError — loud, therefore acceptable and the recommended fix.
A bare ``*args`` vararg voids that loud-overflow guarantee and is
flagged unless the bank entry records ``allow_vararg`` with the
forwarding contract documented.

Bank format (``reference_signatures.json``)::

    {"symbols": {
        "flashinfer_tpu/attention.py:BatchAttention.plan": {
            "reference": "flashinfer/attention/_core.py:95",
            "positional": ["qo_indptr", ...],
            "note": "..."}}}

Keys are ``<project-relative path>:<qualname>`` (``project_relpath``
form, as the baseline uses — duplicate basenames cannot collide).
Regenerate / audit the bank
with ``python -m flashinfer_tpu.analysis --dump-signatures`` (prints
the CURRENT implementation shapes for every recorded symbol) and
docs/static_analysis.md's workflow.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional

from flashinfer_tpu.analysis.core import (Finding, Project, SourceFile,
                                          project_relpath)

CODE = "L002"

DEFAULT_BANK_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "reference_signatures.json")


def load_bank(path: Optional[str] = None) -> Dict[str, dict]:
    with open(path or DEFAULT_BANK_PATH) as f:
        return json.load(f)["symbols"]


def _qualname_defs(sf: SourceFile) -> Dict[str, ast.FunctionDef]:
    """Top-level functions and one-level class methods by qualname."""
    out: Dict[str, ast.FunctionDef] = {}
    if sf.tree is None:
        return out
    for node in sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if isinstance(stmt,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out[f"{node.name}.{stmt.name}"] = stmt
    return out


def positional_params(fn: ast.FunctionDef, *, method: bool) -> List[str]:
    """Names bindable by position, in order, self/cls dropped."""
    names = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    if method and names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


def run(project: Project, bank: Optional[Dict[str, dict]] = None
        ) -> List[Finding]:
    if bank is None:
        bank = load_bank()
    # keys carry project_relpath (the baseline's path keys), so
    # duplicate basenames (attention.py vs parallel/attention.py,
    # compat.py vs comm/compat.py) can never match the wrong file
    by_path: Dict[str, List[tuple]] = {}
    for key, spec in bank.items():
        path, _, qualname = key.partition(":")
        by_path.setdefault(path, []).append((key, qualname, spec))

    findings: List[Finding] = []
    for sf in project.files:
        entries = by_path.get(project_relpath(sf.path))
        if not entries:
            continue
        defs = _qualname_defs(sf)
        for key, qualname, spec in entries:
            fn = defs.get(qualname)
            if fn is None:
                # the FILE is under analysis but the recorded symbol is
                # gone: a rename/move would otherwise silently drop its
                # parity protection (bank entries whose file isn't in
                # the analyzed set stay quiet — the CLI may run on a
                # subset)
                findings.append(Finding(
                    CODE, sf.path, 1, key,
                    f"recorded reference symbol '{qualname}' not found "
                    f"in this file — its positional-parity protection "
                    f"({spec.get('reference', 'reference snapshot')}) "
                    f"is silently gone; update the bank key or restore "
                    f"the symbol"))
                continue
            findings.extend(_check_symbol(sf, key, qualname, fn, spec))
    return findings


def _check_symbol(sf: SourceFile, key: str, qualname: str,
                  fn: ast.FunctionDef, spec: dict) -> List[Finding]:
    ref: List[str] = spec["positional"]
    impl = positional_params(fn, method="." in qualname)
    src = spec.get("reference", "reference snapshot")
    if fn.args.vararg is not None and not spec.get("allow_vararg"):
        # a bare *args voids the "fewer positionals fail loudly"
        # guarantee: reference positionals past the declared prefix are
        # swallowed silently instead of raising TypeError
        return [Finding(
            CODE, sf.path, fn.lineno, key,
            f"'*{fn.args.vararg.arg}' vararg on a reference-parity "
            f"symbol: a verbatim reference call with more positionals "
            f"than the declared prefix is silently swallowed instead "
            f"of raising — enumerate the reference positionals "
            f"({src}) explicitly, or record allow_vararg in the bank "
            f"with the forwarding contract documented")]
    raw = fn.args.posonlyargs + fn.args.args
    offset = len(raw) - len(impl)  # 1 when self/cls was dropped
    for i, name in enumerate(impl):
        arg_node = raw[i + offset]
        if i >= len(ref):
            if spec.get("open_tail"):
                return []  # prefix matched; tail deviation is recorded
            return [Finding(
                CODE, sf.path, arg_node.lineno, key,
                f"positional parameter #{i + 1} '{name}' is beyond the "
                f"reference positional arity ({len(ref)}, {src}) — a "
                f"verbatim reference call cannot supply it; make it "
                f"keyword-only (after '*')")]
        if name != ref[i]:
            return [Finding(
                CODE, sf.path, arg_node.lineno, key,
                f"positional parameter #{i + 1} is '{name}' where the "
                f"reference ({src}) has '{ref[i]}' — a verbatim "
                f"reference positional call misbinds here; restore the "
                f"reference order or make '{name}' keyword-only")]
    return []
