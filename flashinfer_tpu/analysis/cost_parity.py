"""L016 ``cost_parity`` — physics parity between kernels and formulas.

The cost model (`obs/costmodel.py`) is load-bearing: ``choose_decode_
splits``, ``predict_prefill_ingest_win``, the engine's SLO chunk
budgeting and the perf/6 drift watchdog all trust its analytic
bytes/FLOPs.  Nothing else checks that those formulas match the DMA
traffic the Pallas kernels actually issue, so a kernel rewrite (PR 14's
fused ingest rewrote prefill traffic wholesale) can silently skew every
chooser and SLO decision downstream.  This pass is the static mirror of
the paper's plan-time cost accounting: it re-runs the L014 symbolic
small-step walk under a *concrete binding scenario* and accumulates

- **bytes read / written** from every modeled ``make_async_copy``
  (copy extent x declared dtype width, double-buffer warmup counted
  once), plus the BlockSpec pipeline's implicit operand traffic
  (block shape x index-map fetch count x grid trips), and
- **MXU FLOPs** from every ``dot`` / ``dot_general`` site at its block
  shapes (2 x batch x free_lhs x free_rhs x contract),

extrapolates the three modeled grid steps to the scenario's real trip
counts as ``t0 + t1*(T-2) + t2`` (warmup step + steady state + epilogue
step — the per-step guards key on ``program_id == 0`` and
``pid + 1 < num_programs``, which the model's N_STEPS tie reproduces
exactly), and compares against the registered ``costmodel`` family via
the ``COST_LAUNCH_BINDINGS`` adapter within the binding's declared
tolerance band.

A deviation beyond tolerance is a machine-proved cost-model drift:
**fixed, never baselined** (the code is in the driver's unbaselineable
set, like L014 races).  Anything the model cannot prove — unresolvable
copy extents, non-literal ``dimension_numbers``, disagreeing surviving
worlds, ``einsum`` — is a *counted skip* surfaced through ``obs
doctor``'s ``l016_kernels`` section, never a guess.

Two soundness rules inherited from L014 and sharpened here:

- the walk runs with a raised unroll ceiling and *skips* (rather than
  models-short) any loop longer than it, because a shortened loop
  silently drops bytes;
- the formulas are evaluated from the **project's own source snapshot**
  (the ``obs/costmodel.py`` file in the analyzed tree, executed in a
  scratch module), not the installed package — so the pass sees exactly
  the formula text it is vouching for, and the skew tests' mutated
  copies are diagnosed against themselves.

A third finding family, ``[binding-drift]``, cross-checks each
binding's declared ``vmem_shapes`` against the launch site's
``scratch_shapes`` exprs through the L009 evaluator: a registry whose
declared shapes disagree with the launch it prices would make the
parity proof vacuous.
"""

from __future__ import annotations

import ast
import sys
import types
from typing import Dict, List, Optional, Tuple

from .core import (Finding, FunctionInfo, PallasCallSite, Project,
                   eval_int_expr, expr_basename)
from . import dma_race
from .dma_race import (DS, KernelSkip, Ref, View, _ELL, _FULL, _Sim,
                       _as_term, _subst)
from .vmem_budget import _DTYPE_SIZES, _site_of

_COSTMODEL_SUFFIX = "obs/costmodel.py"
_TOL_EPS = 1e-9
_COST_UNROLL = 16   # real chunk loops must unroll, not model short

# calls whose result shape the walk must track so dot operands resolve.
# Method-style receivers are folded into the term (the base walk's
# uninterpreted fallthrough drops them, which would alias every
# `.astype(f32)` into one term).  Names the base walk special-cases
# (where/minimum/maximum/when/ds/...) are deliberately absent.
_SHAPE_CALLS = frozenset({
    "astype", "reshape", "transpose", "swapaxes", "repeat", "clip",
    "sum", "max", "min", "mean", "prod", "cumsum",
    "exp", "exp2", "tanh", "cos", "sin", "sqrt", "rsqrt", "log",
    "log2", "square", "negative", "erf", "sigmoid",
    "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "broadcast_to", "broadcasted_iota", "iota",
    "stack", "concatenate",
})
_ELEMWISE = frozenset({
    "astype", "clip", "exp", "exp2", "tanh", "cos", "sin", "sqrt",
    "rsqrt", "log", "log2", "square", "negative", "erf", "sigmoid",
    "cumsum", "copy",
})
_REDUCTIONS = frozenset({"sum", "max", "min", "mean", "prod"})
_LIKE_CTORS = frozenset({"zeros_like", "ones_like", "full_like"})
_SHAPE_CTORS = frozenset({"zeros", "ones", "empty"})

_CONFLICT = object()


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


class _CostSim(_Sim):
    """The L014 simulator re-targeted at byte/FLOP accounting.

    Scenario constants replace opaque statics, scalar-prefetch loads
    are seeded concrete, every DMA issue is logged into the world's
    traffic with its resolved extent, and ``dot``/``dot_general``
    sites contribute MXU FLOPs at shapes tracked through a small
    result-shape algebra.  Anything unresolvable raises KernelSkip.
    """

    def __init__(self, project: Project, site: PallasCallSite,
                 kernel: FunctionInfo, final_axis: int, binding):
        super().__init__(project, site, kernel, final_axis)
        self.binding = binding
        self.scenario: Dict[str, object] = dict(binding.scenario)
        self.vshapes: Dict[str, tuple] = {
            name: tuple(int(d) for d in shape)
            for name, shape in binding.vmem_shapes(self.scenario).items()}
        self.static_overrides = dict(binding.statics)
        self.max_unroll = _COST_UNROLL
        self._seeds = dict(binding.seeds)
        self._termshapes: Dict[tuple, object] = {}
        self.on_copy_start = self._record_copy
        self.load_seed = self._seed_load

    # -- seeding ----------------------------------------------------------

    def _seed_load(self, name: str, idx):
        return self._seeds.get(name)

    def _itemsize(self, name: str) -> int:
        return int(self.binding.itemsizes.get(
            name, self.binding.default_itemsize))

    def _conc(self, world, v) -> Optional[int]:
        t = _subst(_as_term(v), world.bindings)
        if isinstance(t, bool):
            return int(t)
        if isinstance(t, int):
            return t
        return None

    # -- traffic ----------------------------------------------------------

    def _record_copy(self, world, copy, line: int):
        src = self._label(world, copy.src.ref)
        dst = self._label(world, copy.dst.ref)
        src_v, dst_v = src in self.vshapes, dst in self.vshapes
        if src_v and dst_v:
            return  # VMEM-to-VMEM staging moves no HBM bytes
        if dst_v:
            world.traffic.append(
                (self.step, "r", self._view_bytes(world, dst,
                                                  copy.dst, line)))
        elif src_v:
            world.traffic.append(
                (self.step, "w", self._view_bytes(world, src,
                                                  copy.src, line)))
        else:
            raise KernelSkip(
                f"DMA at line {line}: neither `{src}` nor `{dst}` has "
                f"a declared VMEM shape — binding vmem_shapes "
                f"incomplete")

    def _view_bytes(self, world, name: str, view: View,
                    line: int) -> float:
        rshape = self._index_shape(world, self.vshapes[name], view.idx)
        if rshape is None:
            raise KernelSkip(
                f"copy extent on `{name}` at line {line} is not "
                f"concrete under the binding scenario")
        return float(_prod(rshape) * self._itemsize(name))

    # -- index / shape algebra -------------------------------------------

    def _index_shape(self, world, shape: tuple,
                     idx) -> Optional[tuple]:
        """Result shape of ``shape[idx]``; dropped scalar dims vanish,
        ``None`` (newaxis) inserts 1, so element count is the product."""
        idx = list(idx)
        ndim = len(shape)
        consumed = sum(1 for e in idx
                       if not (isinstance(e, tuple) and e == _ELL)
                       and e is not None)
        if any(isinstance(e, tuple) and e == _ELL for e in idx):
            flat = []
            for e in idx:
                if isinstance(e, tuple) and e == _ELL:
                    flat.extend([_FULL] * (ndim - consumed))
                else:
                    flat.append(e)
            idx = flat
        else:
            idx = idx + [_FULL] * (ndim - consumed)
        out: List[int] = []
        dims = list(shape)
        for e in idx:
            if e is None:
                out.append(1)
                continue
            if not dims:
                return None
            dim = dims.pop(0)
            if isinstance(e, tuple) and e == _FULL:
                out.append(dim)
            elif isinstance(e, DS) or (isinstance(e, tuple)
                                       and len(e) == 3
                                       and e[0] == "ds"):
                size = e.size if isinstance(e, DS) else e[2]
                sz = self._conc(world, size)
                if sz is None:
                    return None
                out.append(sz)
            elif isinstance(e, tuple) and len(e) == 4 \
                    and e[0] == "slice":
                if e[3] is not None:
                    return None
                lo = 0 if e[1] is None else self._conc(world, e[1])
                hi = dim if e[2] is None else self._conc(world, e[2])
                if lo is None or hi is None:
                    return None
                if lo < 0:
                    lo += dim
                if hi < 0:
                    hi += dim
                out.append(max(0, min(hi, dim) - max(0, lo)))
            else:
                pass  # scalar index (concrete or symbolic): dim drops
        if dims:
            return None
        return tuple(out)

    def _key_name(self, world, key: str) -> str:
        for name, v in world.kenv.items():
            if isinstance(v, Ref) and v.key == key:
                return name
        return key

    def _broadcast(self, *shapes) -> Optional[tuple]:
        if any(s is None or s is _CONFLICT for s in shapes):
            return None
        width = max(len(s) for s in shapes)
        out = []
        for i in range(width):
            m = 1
            for s in shapes:
                j = i - (width - len(s))
                if j < 0:
                    continue
                d = int(s[j])
                if d != 1 and m != 1 and d != m:
                    return None
                m = max(m, d)
            out.append(m)
        return tuple(out)

    def _shape_of(self, world, v) -> Optional[tuple]:
        t = _as_term(v)
        if isinstance(t, (int, float, bool)) or t is None \
                or isinstance(t, str):
            return ()
        if isinstance(t, DS):
            return ()
        if not isinstance(t, tuple):
            return None
        cached = self._termshapes.get(t)
        if cached is _CONFLICT:
            return None
        if cached is not None:
            return cached
        tag = t[0] if t else None
        if tag == "refval":
            return self.vshapes.get(self._key_name(world, t[1]))
        if tag == "viewval":
            sh = self.vshapes.get(self._key_name(world, t[1][0]))
            if sh is None:
                return None
            return self._index_shape(world, sh, t[1][1])
        if tag == "load":
            sh = self.vshapes.get(self._key_name(world, t[1]))
            if sh is None:
                return None
            return self._index_shape(world, sh, t[2])
        if tag == "op":
            if t[1] == "index":
                bs = self._shape_of(world, t[2])
                if bs is None:
                    return None
                return self._index_shape(world, bs, t[3])
            return self._broadcast(self._shape_of(world, t[2]),
                                   self._shape_of(world, t[3]))
        if tag in ("and", "or"):
            return self._broadcast(self._shape_of(world, t[1]),
                                   self._shape_of(world, t[2]))
        if tag == "not":
            return self._shape_of(world, t[1])
        if tag == "cmp":
            return self._broadcast(self._shape_of(world, t[2]),
                                   self._shape_of(world, t[3]))
        if tag == "call":
            if t[1] == "where" and isinstance(t[2], tuple) \
                    and len(t[2]) == 3:
                return self._broadcast(
                    *[self._shape_of(world, a) for a in t[2]])
            if t[1] in ("int", "bool", "abs", "float"):
                return ()
            return None
        if tag == "static":
            return ()
        return None

    def _reg_shape(self, term, shape):
        if shape is None:
            return
        old = self._termshapes.get(term)
        if old is None:
            self._termshapes[term] = shape
        elif old is not _CONFLICT and old != shape:
            self._termshapes[term] = _CONFLICT

    # -- evaluation overrides --------------------------------------------

    _EQ_OPS = (ast.Eq, ast.Is)
    _NE_OPS = (ast.NotEq, ast.IsNot)

    @staticmethod
    def _is_dtype_term(v) -> bool:
        return isinstance(v, tuple) and len(v) == 3 \
            and v[0] == "attr" and v[2] == "dtype"

    def eval(self, node: ast.expr, env, world):
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0],
                               self._EQ_OPS + self._NE_OPS):
            a = self.eval(node.left, env, world)
            b = self.eval(node.comparators[0], env, world)
            eq = None
            if self._is_dtype_term(a) or self._is_dtype_term(b):
                # dtype guards only pick shape-preserving cast
                # branches — traffic/FLOP neutral — and every binding
                # scenario computes at the storage dtype, so "equal"
                # is both the truth here and fork-free
                eq = True
            elif dma_race._is_concrete(a) \
                    and dma_race._is_concrete(b) \
                    and type(a) is not type(b):
                eq = bool(a == b)  # `False == "static"` enum dispatch
            if eq is not None:
                return eq if isinstance(node.ops[0], self._EQ_OPS) \
                    else not eq
            return super().eval(node, env, world)
        if isinstance(node, ast.Attribute) and node.attr == "shape":
            base = self.eval(node.value, env, world)
            if isinstance(base, Ref):
                sh = self.vshapes.get(self._label(world, base))
                if sh is not None:
                    return [int(d) for d in sh]
            else:
                sh = self._shape_of(world, base)
                if sh is not None:
                    return [int(d) for d in sh]
            return ("attr", _as_term(base), "shape")
        return super().eval(node, env, world)

    def _eval_subscript(self, node: ast.Subscript, env, world):
        # a symbolic TERM (tuple) is an array value here, not a python
        # sequence: `q[h]` on a loaded block must stay an indexed array
        # (the base walk's seq-index fallback would python-index the
        # term tuple itself)
        base = self.eval(node.value, env, world)
        if isinstance(base, tuple) and not isinstance(base, DS) \
                and base[:1] not in (("mod",), ("sym",)):
            idx = self._eval_index(node.slice, env, world)
            return ("op", "index", _as_term(base),
                    tuple(dma_race._idx_key(i) for i in idx))
        return super()._eval_subscript(node, env, world)

    def _eval_call(self, node: ast.Call, env, world):
        base = expr_basename(node.func)
        if base == "einsum":
            raise KernelSkip("einsum FLOPs not modeled")
        if base in ("dot", "dot_general"):
            return self._eval_dot(node, env, world, base)
        if base in _SHAPE_CALLS and isinstance(node.func, ast.Attribute):
            return self._eval_shape_call(node, base, env, world)
        val = super()._eval_call(node, env, world)
        return val

    def _eval_dot(self, node: ast.Call, env, world, base: str):
        args = [self.eval(a, env, world) for a in node.args]
        if len(args) < 2:
            raise KernelSkip(f"{base} with < 2 operands")
        sa = self._shape_of(world, args[0])
        sb = self._shape_of(world, args[1])
        if sa is None or sb is None:
            raise KernelSkip(
                f"{base} at line {node.lineno}: operand shape unknown "
                f"(lhs={sa} rhs={sb})")
        if base == "dot":
            if len(sa) != 2 or len(sb) != 2 or sa[1] != sb[0]:
                raise KernelSkip(
                    f"dot at line {node.lineno} on shapes {sa} x {sb}")
            flops = 2.0 * sa[0] * sa[1] * sb[1]
            out = (sa[0], sb[1])
        else:
            dn_node = node.args[2] if len(node.args) > 2 else next(
                (k.value for k in node.keywords
                 if k.arg == "dimension_numbers"), None)
            try:
                dn = ast.literal_eval(dn_node)
                (ca, cb), (ba, bb) = dn
            except Exception:
                raise KernelSkip(
                    f"dot_general at line {node.lineno}: "
                    f"dimension_numbers not a literal")
            ca, cb, ba, bb = (tuple(int(i) for i in d)
                              for d in (ca, cb, ba, bb))
            try:
                contract = [sa[i] for i in ca]
                batch = [sa[i] for i in ba]
                if contract != [sb[i] for i in cb] \
                        or batch != [sb[i] for i in bb]:
                    raise KernelSkip(
                        f"dot_general at line {node.lineno}: "
                        f"contraction shapes disagree ({sa} x {sb})")
            except IndexError:
                raise KernelSkip(
                    f"dot_general at line {node.lineno}: "
                    f"dimension_numbers out of range for {sa} x {sb}")
            free_a = [sa[i] for i in range(len(sa))
                      if i not in ca and i not in ba]
            free_b = [sb[i] for i in range(len(sb))
                      if i not in cb and i not in bb]
            flops = 2.0 * _prod(batch) * _prod(free_a) \
                * _prod(free_b) * _prod(contract)
            out = tuple(batch) + tuple(free_a) + tuple(free_b)
        world.traffic.append((self.step, "f", flops))
        term = ("call", base, tuple(_as_term(a) for a in args))
        self._reg_shape(term, out)
        return term

    def _eval_shape_call(self, node: ast.Call, base: str, env, world):
        """jnp/method calls whose result shape downstream dots need.
        The receiver joins the term so distinct `.astype(f32)` sites
        stay distinct; ref operands keep MUST-read checking."""
        recv = None
        if isinstance(node.func, ast.Attribute):
            rv = self.eval(node.func.value, env, world)
            if not (isinstance(rv, tuple) and rv[:1] == ("mod",)):
                recv = rv
        args = [self.eval(a, env, world) for a in node.args]
        kwargs = {k.arg: self.eval(k.value, env, world)
                  for k in node.keywords if k.arg}
        for v in [recv] + args:
            if isinstance(v, Ref):
                self._check_read(world, View(v, (_ELL,)), node.lineno)
        operands = ([recv] if recv is not None else []) + args
        shape = self._shape_call_shape(world, base, recv, args, kwargs)
        term = ("call", base, tuple(_as_term(v) for v in operands))
        self._reg_shape(term, shape)
        return term

    def _shape_call_shape(self, world, base: str, recv, args,
                          kwargs) -> Optional[tuple]:
        def first():
            return recv if recv is not None else (
                args[0] if args else None)

        def conc_tuple(v) -> Optional[tuple]:
            if isinstance(v, int):
                return (v,)
            if isinstance(v, (list, tuple)):
                out = []
                for e in v:
                    c = self._conc(world, e)
                    if c is None:
                        return None
                    out.append(c)
                return tuple(out)
            return None

        if base in _ELEMWISE:
            return self._shape_of(world, first())
        if base in _REDUCTIONS:
            src = self._shape_of(world, first())
            if src is None:
                return None
            axis = kwargs.get("axis")
            if axis is None and recv is None and len(args) > 1:
                axis = args[1]
            elif axis is None and recv is not None and args:
                axis = args[0]
            keep = bool(kwargs.get("keepdims", False))
            if axis is None:
                return (1,) * len(src) if keep else ()
            axes = [axis] if isinstance(axis, int) else \
                ([int(a) for a in axis]
                 if isinstance(axis, (list, tuple))
                 and all(isinstance(a, int) for a in axis) else None)
            if axes is None:
                return None
            axes = [a % len(src) for a in axes]
            return tuple(1 if i in axes else d
                         for i, d in enumerate(src)
                         if keep or i not in axes)
        if base == "reshape":
            new = args if recv is not None else args[1:]
            if len(new) == 1 and isinstance(new[0], (list, tuple)):
                new = list(new[0])
            dims = []
            for v in new:
                c = self._conc(world, v)
                if c is None:
                    return None
                dims.append(c)
            if dims.count(-1) == 1:
                src = self._shape_of(
                    world, recv if recv is not None else args[0])
                if src is None:
                    return None
                rest = _prod(d for d in dims if d != -1)
                dims[dims.index(-1)] = _prod(src) // max(rest, 1)
            elif -1 in dims:
                return None
            return tuple(dims)
        if base in ("transpose", "swapaxes"):
            src = self._shape_of(world, first())
            if src is None:
                return None
            if base == "swapaxes" and len(args) >= (2 if recv is None
                                                    else 2):
                ax = args[-2:] if recv is not None else args[1:3]
                a, b = (self._conc(world, ax[0]),
                        self._conc(world, ax[1]))
                if a is None or b is None:
                    return None
                out = list(src)
                out[a], out[b] = out[b], out[a]
                return tuple(out)
            perm = conc_tuple(args[0] if recv is not None and args
                              else (args[1] if len(args) > 1 else None))
            if perm is None:
                return tuple(reversed(src))
            return tuple(src[p] for p in perm)
        if base == "repeat":
            src = self._shape_of(world, first())
            n = self._conc(world, args[1] if recv is None else args[0])
            axis = self._conc(world, kwargs.get("axis"))
            if src is None or n is None or axis is None:
                return None
            out = list(src)
            out[axis % len(out)] *= n
            return tuple(out)
        if base in _LIKE_CTORS:
            return self._shape_of(world, first())
        if base in _SHAPE_CTORS or base == "full":
            return conc_tuple(args[0]) if args else None
        if base == "broadcast_to":
            return conc_tuple(args[1] if recv is None and len(args) > 1
                              else (args[0] if args else None))
        if base in ("broadcasted_iota", "iota"):
            for a in args:
                sh = conc_tuple(a) if isinstance(a, (list, tuple)) \
                    else None
                if sh is not None and len(sh) > 1:
                    return sh
            return conc_tuple(args[1]) if len(args) > 1 else None
        if base in ("stack", "concatenate"):
            seq = args[0] if args else None
            if not isinstance(seq, (list, tuple)) or not seq:
                return None
            shapes = [self._shape_of(world, e) for e in seq]
            if any(s is None for s in shapes) \
                    or len(set(shapes)) != 1:
                return None
            axis = self._conc(world, kwargs.get("axis"))
            if axis is None and recv is None and len(args) > 1:
                axis = self._conc(world, args[1])  # positional axis
            if axis is None:
                axis = 0
            if base == "stack":
                out = list(shapes[0])
                out.insert(axis % (len(out) + 1), len(seq))
                return tuple(out)
            out = list(shapes[0])
            out[axis % len(out)] *= len(seq)
            return tuple(out)
        return None


# -- implicit BlockSpec pipeline traffic ----------------------------------


def _spec_side_bytes(site: PallasCallSite, binding, trips: List[int],
                     which: str) -> float:
    """Operand bytes moved by the BlockSpec grid pipeline for one side
    (``in`` / ``out``): block elements x index-map fetch count.  A
    spec list the resolver cannot see (flag-conditional appends) falls
    back to the binding's declared ``implicit_fallback`` — declared,
    not guessed, and ignored whenever the machine CAN resolve."""
    exprs = site.in_spec_exprs if which == "in" else site.out_spec_exprs
    scenario = dict(binding.scenario)
    if exprs is None:
        fb = binding.implicit_fallback
        if fb is None:
            raise KernelSkip(
                f"{which}_specs not statically resolvable and the "
                f"binding declares no implicit_fallback")
        d = fb(scenario)
        return float(d.get("bytes_read" if which == "in"
                           else "bytes_written", 0.0))
    total = 0.0
    rank = site.grid_rank or 0
    for i, call in enumerate(exprs):
        if not isinstance(call, ast.Call):
            raise KernelSkip(f"{which}{i} spec is not a BlockSpec call")
        if any(k.arg == "memory_space" for k in call.keywords):
            continue  # ANY operand: its traffic is the modeled DMA
        if not call.args:
            raise KernelSkip(f"{which}{i} bare BlockSpec not modeled")
        shape_node = call.args[0]
        if not isinstance(shape_node, ast.Tuple):
            raise KernelSkip(
                f"{which}{i} block shape is not a literal tuple")
        elems = 1
        for d_ast in shape_node.elts:
            if isinstance(d_ast, ast.Constant) and d_ast.value is None:
                continue
            dv = eval_int_expr(d_ast, scenario, site.locals_)
            if dv is None:
                raise KernelSkip(
                    f"{which}{i} block dim not evaluable under the "
                    f"binding scenario")
            elems *= dv
        if len(call.args) > 1 and isinstance(call.args[1], ast.Lambda):
            params = [p.arg for p in call.args[1].args.args[:rank]]
            dmax = -1
            for n in ast.walk(call.args[1].body):
                if isinstance(n, ast.Name) and n.id in params:
                    dmax = max(dmax, params.index(n.id))
            fetches = _prod(trips[:dmax + 1]) if dmax >= 0 else 1
        else:
            fetches = _prod(trips)  # default index map visits the grid
        isz = int(binding.spec_itemsizes.get(
            f"{which}{i}", binding.default_itemsize))
        total += float(elems) * isz * fetches
    return total


# -- per-binding check ----------------------------------------------------


def _extrapolate(agg: Dict[tuple, float], kind: str, t_final: int,
                 outer: int) -> float:
    t = [agg.get((kind, s), 0.0) for s in range(dma_race.N_STEPS)]
    if t_final >= 3:
        total = t[0] + t[1] * (t_final - 2) + t[2]
    else:  # == 2: warmup step + epilogue step, no steady state
        total = t[0] + t[2]
    return total * outer


def _scratch_drift(site: PallasCallSite, fi: FunctionInfo,
                   binding) -> List[Finding]:
    """Declared vmem_shapes vs the launch's scratch_shapes exprs.

    The L009 evaluator is a deliberate LOWER bound (itemsize 1 for
    non-literal dtypes, min over IfExp) — good for fit proofs, wrong
    for an equality check.  Parity needs exactness, so dims go through
    ``eval_int_expr`` (exact or None) and the itemsize is compared
    only when the launch declares a literal dtype name."""
    out: List[Finding] = []
    scenario = dict(binding.scenario)
    sexprs = site.scratch_exprs
    if not binding.scratch_names or sexprs is None:
        return out
    shapes = binding.vmem_shapes(scenario)
    for name, idx in sorted(binding.scratch_names.items()):
        bad = name not in shapes or idx >= len(sexprs)
        expr = None if bad else sexprs[idx]
        if not bad and not (isinstance(expr, ast.Call)
                            and expr_basename(expr.func) == "VMEM"
                            and expr.args):
            bad = True  # index points at a semaphore / SMEM operand
        if bad:
            out.append(Finding(
                "L016", fi.file.path, site.line, fi.qualname,
                f"[binding-drift] COST_LAUNCH_BINDINGS"
                f"[{binding.launcher!r}].scratch_names[{name!r}] -> "
                f"{idx} does not name a VMEM scratch of the launch "
                f"(scratch arity {len(sexprs)})"))
            continue
        shape_node = expr.args[0]
        if not isinstance(shape_node, (ast.Tuple, ast.List)):
            continue
        elems, exact = 1, True
        for dim in shape_node.elts:
            if isinstance(dim, ast.Constant) and dim.value is None:
                continue
            dv = eval_int_expr(dim, scenario, site.locals_)
            if dv is None:
                exact = False
                break
            elems *= dv
        if exact and elems != _prod(shapes[name]):
            out.append(Finding(
                "L016", fi.file.path, site.line, fi.qualname,
                f"[binding-drift] `{name}`: the binding declares "
                f"{_prod(shapes[name])} elements but the launch's "
                f"scratch_shapes[{idx}] evaluates to {elems} under "
                f"the same scenario — the registry no longer "
                f"describes the kernel it prices"))
        if len(expr.args) > 1:
            sz = _DTYPE_SIZES.get(expr_basename(expr.args[1]))
            want_sz = int(binding.itemsizes.get(
                name, binding.default_itemsize))
            if sz is not None and sz != want_sz:
                out.append(Finding(
                    "L016", fi.file.path, site.line, fi.qualname,
                    f"[binding-drift] `{name}`: the binding prices "
                    f"{want_sz} bytes/element but the launch declares "
                    f"a {sz}-byte dtype"))
    return out


def _check_binding(project: Project, site: PallasCallSite,
                   fi: FunctionInfo,
                   binding) -> Tuple[List[Finding], float]:
    scenario = dict(binding.scenario)
    trips = site.resolve_trip_counts(scenario)
    if trips is None:
        raise KernelSkip("grid trip counts unresolved under scenario")
    if trips[-1] < 2:
        raise KernelSkip(
            "scenario must give >= 2 final-axis grid trips (warmup + "
            "epilogue must both be real steps)")
    outer = _prod(trips[:-1]) if len(trips) > 1 else 1

    sim = _CostSim(project, site, site.kernel,
                   final_axis=site.grid_rank - 1, binding=binding)
    worlds = sim._run_worlds()
    per: List[Dict[tuple, float]] = []
    for w in worlds:
        agg: Dict[tuple, float] = {}
        for (step, kind, amt) in w.traffic:
            agg[(kind, step)] = agg.get((kind, step), 0.0) + float(amt)
        per.append(agg)
    if len({tuple(sorted(a.items())) for a in per}) > 1:
        raise KernelSkip(
            "surviving model worlds disagree on per-step traffic "
            "totals (data-dependent DMA extent)")
    agg = per[0] if per else {}

    t_final = int(trips[-1])
    dma_r = _extrapolate(agg, "r", t_final, outer)
    dma_w = _extrapolate(agg, "w", t_final, outer)
    flops = _extrapolate(agg, "f", t_final, outer)
    imp_r = _spec_side_bytes(site, binding, trips, "in")
    imp_w = _spec_side_bytes(site, binding, trips, "out")
    model = {
        "bytes_read": dma_r + imp_r,
        "bytes_written": dma_w + imp_w,
        "bytes_total": dma_r + imp_r + dma_w + imp_w,
        "flops": flops,
    }

    try:
        expected = binding.adapter(scenario)
    except Exception as e:
        raise KernelSkip(f"cost adapter raised: {e!r}")

    findings = _scratch_drift(site, fi, binding)
    maxdev = 0.0
    for cat, tol in sorted(binding.compare.items()):
        if cat not in expected:
            findings.append(Finding(
                "L016", fi.file.path, site.line, fi.qualname,
                f"[binding-drift] adapter for family "
                f"`{binding.family}` returned no `{cat}` even though "
                f"the binding compares it"))
            continue
        exp = float(expected[cat])
        got = float(model[cat])
        dev = abs(got - exp) / max(abs(exp), 1.0)
        maxdev = max(maxdev, dev)
        if dev > float(tol) + _TOL_EPS:
            findings.append(Finding(
                "L016", fi.file.path, site.line, fi.qualname,
                f"[cost-drift] {binding.family}.{cat}: the kernel's "
                f"machine-derived {cat} is {got:,.0f} but the "
                f"costmodel family prices {exp:,.0f} (deviation "
                f"{dev:.2%} > tolerance {float(tol):.1%}) — either "
                f"the kernel's traffic changed without the formula "
                f"(update `{binding.family}`) or the formula drifted "
                f"from the kernel; fix one, never baseline this"))
    return findings, maxdev


# -- project costmodel snapshot -------------------------------------------


def _load_snapshot(project: Project):
    """Execute the PROJECT's obs/costmodel.py (pure-Python by its own
    import contract) in a scratch module and return it — the formulas
    checked are exactly the formula text in the analyzed tree, not
    whatever package happens to be installed.  Shared with L017, which
    checks the chooser registries of the same snapshot."""
    sf = None
    for f in project.files:
        if f.path.replace("\\", "/").endswith(_COSTMODEL_SUFFIX):
            sf = f
            break
    if sf is None:
        return None, None
    mod = types.ModuleType("_l016_costmodel_snapshot")
    mod.__file__ = sf.path
    # dataclass construction resolves cls.__module__ through
    # sys.modules, so the scratch module must be registered while the
    # snapshot executes
    sys.modules[mod.__name__] = mod
    try:
        exec(compile(sf.src, sf.path, "exec"), mod.__dict__)
    except Exception as e:
        return None, f"costmodel snapshot failed to execute: {e!r}"
    finally:
        sys.modules.pop(mod.__name__, None)
    return mod, None


def _load_bindings(project: Project):
    mod, err = _load_snapshot(project)
    if mod is None:
        return None, err
    return getattr(mod, "COST_LAUNCH_BINDINGS", {}), None


# -- pass driver ----------------------------------------------------------

_MEMO_CAP = 8
_memo: Dict[tuple, tuple] = {}


def _analyze(project: Project, bindings=None):
    if bindings is not None:
        return _analyze_uncached(project, bindings)
    key = tuple(sorted((sf.path, hash(sf.src)) for sf in project.files))
    hit = _memo.get(key)
    if hit is not None:
        return hit
    result = _analyze_uncached(project, None)
    if len(_memo) >= _MEMO_CAP:
        _memo.pop(next(iter(_memo)))
    _memo[key] = result
    return result


def _analyze_uncached(project: Project, bindings):
    findings: List[Finding] = []
    stats = {"families_total": 0, "families_checked": 0,
             "families_skipped": 0, "max_deviation": 0.0,
             "skip_reasons": {}}
    if bindings is None:
        bindings, err = _load_bindings(project)
        if bindings is None:
            if err is not None:
                stats["families_skipped"] = 1
                stats["skip_reasons"]["<costmodel>"] = err
            return findings, stats  # registry out of scope: pass gated
    for launcher in sorted(bindings):
        binding = bindings[launcher]
        stats["families_total"] += 1
        try:
            fi = project.resolve_function(launcher)
            if fi is None:
                raise KernelSkip("launcher not found in project")
            site = _site_of(project, fi)
            if site is None:
                raise KernelSkip("no pallas_call site inside launcher")
            if site.kernel is None:
                raise KernelSkip("kernel reference not resolved")
            if site.grid_rank is None:
                raise KernelSkip("grid rank not statically visible")
            fnds, dev = _check_binding(project, site, fi, binding)
            findings.extend(fnds)
            stats["families_checked"] += 1
            stats["max_deviation"] = max(stats["max_deviation"], dev)
        except KernelSkip as e:
            stats["families_skipped"] += 1
            stats["skip_reasons"][launcher] = str(e) or "unmodelable"
    return findings, stats


def run(project: Project, bindings=None) -> List[Finding]:
    findings, _stats = _analyze(project, bindings)
    return list(findings)


def stats(project: Project) -> dict:
    """families checked/skipped + max observed deviation for
    ``obs doctor`` — the no-silent-skip rule applied to cost parity."""
    _findings, st = _analyze(project)
    return {**st, "skip_reasons": dict(st["skip_reasons"])}
