"""SARIF 2.1.0 output for the analyzer — the GitHub code-scanning
surface.

One run, one tool (``graft-lint``), one rule per pass code, one result
per finding.  URIs are the same project-relative keys the baseline
uses (``project_relpath``), so annotations land on the right file in
any checkout regardless of where the CLI ran.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from flashinfer_tpu.analysis.core import Finding, project_relpath

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

# code -> (short description, help text) — the rule metadata the
# code-scanning UI shows; keep in sync with docs/static_analysis.md
RULE_DESCRIPTIONS: Dict[str, str] = {
    "L000": "graft-lint suppression without a reason",
    "L001": "class-level method alias skipping a subclass override",
    "L002": "positional-signature drift vs the reference bank",
    "L003": "trace-time env/global read pinned by the jit cache",
    "L004": "chip-wedging Mosaic pattern (wedge lint)",
    "L005": "@flashinfer_api op missing from the obs catalog",
    "L006": "stale/invalid tuning_configs tactic entry",
    "L007": "Pallas plan/kernel launch-contract mismatch",
    "L008": "traced value leaking into Python control flow",
    "L009": "tuning-config blocks exceeding the VMEM budget",
    "L010": "unguarded accumulator init / bad input_output_aliases",
    "L011": "donated-buffer lifetime violation at a compile-once step",
    "L012": "per-step schedule value flowing into a compile-once static",
    "L013": "incomplete knob/planner/obs registry coverage",
    "L014": "DMA/semaphore race inside a Pallas kernel body",
    "L015": "interpret-proven-only construct (Mosaic lowering risk)",
    "L016": "kernel traffic diverges from its registered cost family",
    "L017": "priced choice missing its VMEM prune or knob coverage",
    "L999": "unparseable source",
    "W000": "wedge-lint suppression without a reason",
    "W001": "strided-gather lowering wedge",
    "W002": "DMA queue-unroll wedge",
    "W003": "lane-dim repeat/reshape wedge",
    "W004": "unrolled-dot flags wedge",
    "W999": "wedge-lint internal error",
}


def to_sarif(findings: List[Finding],
             mosaic_risks: Optional[List[dict]] = None) -> dict:
    codes = sorted({f.code for f in findings})
    rules = [
        {
            "id": code,
            "name": code,
            "shortDescription": {
                "text": RULE_DESCRIPTIONS.get(code, "analyzer finding"),
            },
            # relative URI-reference: resolves inside whatever checkout
            # the SARIF was uploaded from (the upstream repo does not
            # carry this doc, so no absolute upstream link)
            "helpUri": "docs/static_analysis.md",
            "defaultConfiguration": {"level": "error"},
        }
        for code in codes
    ]
    rule_index = {code: i for i, code in enumerate(codes)}
    results = [
        {
            "ruleId": f.code,
            "ruleIndex": rule_index[f.code],
            "level": "error",
            "message": {"text": f"{f.func}: {f.message}"},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": project_relpath(f.filename),
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {"startLine": max(int(f.line), 1)},
                    },
                }
            ],
        }
        for f in findings
    ]
    run: dict = {
        "tool": {
            "driver": {
                "name": "graft-lint",
                "informationUri": (
                    "https://github.com/flashinfer-ai/flashinfer"),
                "rules": rules,
            },
        },
        "originalUriBaseIds": {
            "SRCROOT": {"description": {
                "text": "repository root"}},
        },
        "results": results,
    }
    if mosaic_risks is not None:
        # machine-readable hardware bring-up checklist: EVERY current
        # L015 finding (baselined/triaged ones included — "results"
        # above only carries the NEW ones), so the item-1 hardware
        # session reads one property bag instead of CHANGES.md
        run["properties"] = {"mosaic_risks": mosaic_risks}
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [run],
    }
