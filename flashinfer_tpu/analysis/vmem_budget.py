"""L009 — tuning-config block shapes that cannot fit in VMEM.

L006 checks that every ``tuning_configs/*.json`` entry names a
registered knob with a well-formed value; this pass extends it with
the SEMANTIC check: plug the knob's values into the launcher's own
``scratch_shapes`` / BlockSpec expressions, evaluate the VMEM bytes
symbolically, and flag entries whose blocks exceed the launch's VMEM
budget.  A config that cannot compile fails at the worst possible time
— a Mosaic error on the serving host when the shipped tactic table
finally matches a live shape — when the arithmetic was fully decidable
at lint time from (knob value, shape key, kernel source).

Per-knob launch bindings live in ``KNOB_LAUNCHES``: which launcher
function owns the pallas_call, which env names the knob's components
and the shape key's fields bind to.  The evaluator then walks the
launcher body executing simple arithmetic assignments
(``chunk_tokens = pages_per_chunk * page_size``, ``bq = min(block_q,
round_up(total_q, 16))``) and sizes every ``pltpu.VMEM`` scratch plus
every explicitly-shaped BlockSpec block (x2 for the pipeline's double
buffering).  Anything unevaluable contributes zero — the estimate is a
LOWER bound, so a finding is a proof, never a guess.

The budget is the launcher's own declared ``vmem_limit_bytes`` when
statically present (Mosaic enforces it on every platform), else the
per-generation ceiling in ``VMEM_CAPS``.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

from flashinfer_tpu.analysis.core import (Finding, FunctionInfo,
                                          PallasCallSite, Project,
                                          expr_basename)
from flashinfer_tpu.analysis.tuning_schema import (_config_paths,
                                                   _key_line, _tables)
from flashinfer_tpu.obs.hwspec import VMEM_CAPS

CODE = "L009"

# Per-generation VMEM ceilings (bytes) used when a launch declares no
# vmem_limit_bytes: imported from the chip-spec registry above
# (obs/hwspec.py is plain data with no env/backend reads at import, so
# this lint path stays accelerator-free).  Provenance lives with the
# specs — compile budgets, not datasheet capacities.
_DEFAULT_CAP = 128 * 1024 * 1024

_DTYPE_SIZES = {
    "float64": 8, "int64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1, "fp8": 1, "e4m3": 1,
}


@dataclasses.dataclass(frozen=True)
class KnobLaunch:
    """How one registered knob binds into its launcher's namespace.

    ``value_names``: env name for each component of the tactic value.
    ``shape_names``: env name for each ``_``-separated field of the
    config key's shape part (None = unused; ``"__dtype__"`` = the
    field is a dtype string setting the default element size).
    ``aliases``: extra env names copied from already-bound ones
    (launcher locals the shape key doesn't spell, e.g. head_dim_vo)."""

    knob: str
    launcher: str
    value_names: Sequence[str]
    shape_names: Sequence[Optional[str]]
    aliases: Dict[str, str] = dataclasses.field(default_factory=dict)


# Sibling registries, same no-silent-skip rule:
# pallas_contract.PLANNER_KERNELS (the L007 plan-array contract) and
# obs/costmodel.COST_LAUNCH_BINDINGS (the L016 parity scenario that
# proves a priced launcher's traffic against its cost family).  A
# launcher whose candidates this registry's L009 proof gates should
# also carry a parity binding — the proof says a tactic FITS, the
# binding says the model PRICING it is honest (L017 checks both).
KNOB_LAUNCHES: Dict[str, KnobLaunch] = {}


def register_knob_launch(kl: KnobLaunch) -> None:
    KNOB_LAUNCHES[kl.knob] = kl


# Knobs with NO KNOB_LAUNCHES binding, waived EXPLICITLY with a reason
# (L013 `registry_coverage`): a registered knob must either carry a
# VMEM-proof binding above or state here why none is needed — the PR 4
# silent-skip extension point closed.  A waiver with an empty reason is
# itself an L013 finding (the L000 rule, applied to registries).
KNOB_WAIVERS: Dict[str, str] = {}


def waive_knob_launch(knob: str, reason: str) -> None:
    KNOB_WAIVERS[knob] = reason


# host-side / scheduler-only knobs: no VMEM launch by design
waive_knob_launch(
    "serve.mixed_chunk",
    "host-side chunked-prefill scheduling quantum (tokens per mixed "
    "step) — no kernel launch of its own; the step's attention rides "
    "the flash/work-unit launchers whose own knobs carry the proofs")
waive_knob_launch(
    "parallel.dp",
    "mesh axis size — host-side sharding topology, no VMEM launch; "
    "plan_axes falls back on invalid combinations before a mesh exists")
waive_knob_launch(
    "parallel.tp",
    "mesh axis size — host-side sharding topology, no VMEM launch; "
    "plan_axes falls back on invalid combinations before a mesh exists")
waive_knob_launch(
    "parallel.ep",
    "mesh axis factor — host-side sharding topology, no VMEM launch; "
    "plan_axes falls back on invalid combinations before a mesh exists")
waive_knob_launch(
    "engine.block_size",
    "scheduler static (KV page size): feeds EngineKernelGeom, whose "
    "launches are proved via the fused_prefill.blocks / decode.splits "
    "bindings the geometry is clamped to")
waive_knob_launch(
    "engine.prefill_budget_tokens",
    "scheduler budget — host-side admission pricing via "
    "predict_step_seconds, no launch")
waive_knob_launch(
    "engine.max_batch",
    "scheduler static (batch slots / rung-ladder floor) — host-side, "
    "no launch of its own")
waive_knob_launch(
    "engine.kv_offload",
    "host-RAM tier attach switch — host-side page copies only, no "
    "VMEM launch by design")
waive_knob_launch(
    "engine.spill_policy",
    "preemption-resume policy enum — host-side decision, no VMEM "
    "launch by design")
waive_knob_launch(
    "engine.host_gib",
    "host-RAM capacity budget (HostKVStore LRU bound) — host-side, "
    "no VMEM launch by design")
# kernel knobs whose tactic can never launch an infeasible shape
waive_knob_launch(
    "rmsnorm.row_block",
    "scratchless row-block elementwise kernel; the resolver clamps "
    "the tactic to the operand's rows (norm.py _tuned_row_block), so "
    "an oversized entry is clamped, never launched")
waive_knob_launch(
    "fused_add_rmsnorm.row_block",
    "scratchless row-block elementwise kernel; the resolver clamps "
    "the tactic to the operand's rows (norm.py _tuned_row_block), so "
    "an oversized entry is clamped, never launched")
waive_knob_launch(
    "paged_decode.prefetch",
    "string mode knob (static/off cross-step prefetch) — no shape "
    "arithmetic, no VMEM-bearing value")
waive_knob_launch(
    "mla_decode.layout",
    "scratch-LAYOUT enum (split/packed) over a fixed scratch budget — "
    "the layout choice moves no bytes")


# fkey: (batch, tq_pad, num_qo_heads, num_kv_heads, head_dim,
# page_size) — prefill.py fused_key
register_knob_launch(KnobLaunch(
    knob="fused_prefill.blocks",
    launcher="fused_paged_prefill",
    value_names=("block_q", "pages_per_chunk"),
    shape_names=(None, "total_q", "H", "Hkv", "D", "page_size"),
))

# key: (tq_pow2, tkv_pow2, num_qo_heads, num_kv_heads, head_dim,
# dtype, causal) — prefill.py flash_block_key
register_knob_launch(KnobLaunch(
    knob="flash_attention.blocks",
    launcher="flash_attention",
    value_names=("block_q", "block_kv"),
    shape_names=("total_q", "total_kv", "num_qo_heads", "num_kv_heads",
                 "head_dim", "__dtype__", None),
    aliases={"head_dim_vo": "head_dim"},
))

# key: (batch, max_pages, num_qo_heads, num_kv_heads, head_dim,
# page_size, pages_per_chunk, dtype) — ops/paged_decode.py
# decode_split_tactic_key.  The tactic VALUE (num_splits) never enters
# the scratch arithmetic — the split kernel's VMEM footprint is the
# double-buffered (pages_per_chunk, Hkv, PS, D) chunk pair, which the
# key's own fields size — so this binding is the feasibility gate
# plan-time selection composes with (decode.py _split_vmem_feasible):
# a split tactic whose chunk scratch can't compile is pruned before it
# is ever considered.
register_knob_launch(KnobLaunch(
    knob="decode.splits",
    launcher="paged_decode_attention_split",
    value_names=("num_splits",),
    shape_names=("batch", "max_pages", "num_qo_heads", "num_kv_heads",
                 "head_dim", "page_size", "pages_per_chunk",
                 "__dtype__"),
))

# key: (batch, max_pages, num_qo_heads, num_kv_heads, head_dim,
# page_size, dtype) — ops/paged_decode.py decode_tactic_key.  The tactic
# VALUE is the pages-per-chunk itself, which directly sizes the
# double-buffered (2, ppc, Hkv, PS, D) K+V chunk-pair scratch of the
# head-fused HND launch — the unsplit twin of decode.splits (whose key
# carries ppc as a SHAPE field instead).  The launcher's runtime 8 MiB
# clamp lives in paged_decode_attention, upstream of this launch, so
# the proof evaluates the RAW shipped value: an entry this binding
# rejects would only ever run clamped, i.e. the tactic silently would
# not be what the config promised — exactly what L009 must say.
register_knob_launch(KnobLaunch(
    knob="paged_decode.pages_per_chunk",
    launcher="_paged_decode_hnd_launch",
    value_names=("pages_per_chunk",),
    shape_names=("batch", None, "num_qo_heads", "num_kv_heads",
                 "head_dim", "page_size", "__dtype__"),
))

# key: (m, k, n, dtype) — ops/moe_gmm.py tune_tiles / fused_moe.  The
# (tm, tn, tk) tactic sizes the lhs/rhs/out blocks and the f32/int32
# accumulator scratch of the one gmm pallas_call; the quantized-path
# extra scale blocks are tiny and branch-gated, so the evaluator's
# min-merge keeps the estimate a lower bound (L009 semantics).
register_knob_launch(KnobLaunch(
    knob="moe_gmm.tiles",
    launcher="gmm",
    value_names=("tm", "tn", "tk"),
    shape_names=("m", "k", "n", "__dtype__"),
))

# key: (hidden, hq, hkv, hd) — serve/engine.py EngineConfig.from_knobs.
# The engine's KERNEL attention tier launches through
# fused_paged_prefill (both cascade levels) and
# paged_decode_attention_split; the tactic value is the backend NAME
# (string), which never enters scratch arithmetic, and the engine's
# block_q/pages_per_chunk launch statics are derived at engine build
# (serve/engine_kernels.py EngineKernelGeom), so this binding registers
# the launch without a standalone VMEM proof — the compile-feasibility
# gate rides the fused_prefill.blocks and decode.splits bindings the
# engine's geometry is clamped to (the same 512-token chunk / 8 MiB
# double-buffer clamps those knobs' evaluations prove).
register_knob_launch(KnobLaunch(
    knob="engine.attention_backend",
    launcher="fused_paged_prefill",
    value_names=("attention_backend",),
    shape_names=("hidden", "H", "Hkv", "D"),
))

# key: (batch, tq_pad, num_qo_heads, num_kv_heads, head_dim, page_size)
# — prefill.py fused_key, shared with fused_prefill.blocks.  The tactic
# value is the mode STRING ("on"/"off"), which never enters scratch
# arithmetic, and the ingest launcher's block_q/pages_per_chunk arrive
# from the fused_prefill.blocks tactic for the same key — so this
# binding registers the launch (the ISSUE 14 satellite contract) while
# the compile-feasibility proof rides the fused_prefill.blocks
# evaluation of the shared chunk/tile shapes.
register_knob_launch(KnobLaunch(
    knob="prefill.fused_ingest",
    launcher="fused_paged_prefill_ingest",
    value_names=("fused_ingest",),
    shape_names=(None, "total_q", "H", "Hkv", "D", "page_size"),
))


class _Unevaluable(Exception):
    pass


class _Evaluator:
    """Tiny arithmetic interpreter over a known-int environment."""

    _FNS = {
        "min": min, "max": max, "abs": abs, "sum": sum, "int": int,
        "round_up": lambda x, m: -(-x // m) * m,
        "cdiv": lambda a, b: -(-a // b),
        "next_power_of_two": lambda x: 1 << max(int(x) - 1, 0).bit_length(),
    }

    def __init__(self, env: Dict[str, int], default_itemsize: int,
                 dtype_declared: bool = False):
        self.env = dict(env)
        self.default_itemsize = default_itemsize
        # blocks pipeline the operands the config key was looked up
        # with: when the key DECLARED their dtype the default is a
        # proof for them; otherwise (and for `.dtype`-attribute scratch
        # like an int8 KV cache) only 1 byte/element keeps the
        # estimate a lower bound
        self.block_itemsize = default_itemsize if dtype_declared else 1

    def eval(self, expr: ast.expr):
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, (int, float)) \
                    and not isinstance(expr.value, bool):
                return expr.value
            raise _Unevaluable
        if isinstance(expr, ast.Name):
            if expr.id in self.env and self.env[expr.id] is not None:
                return self.env[expr.id]
            raise _Unevaluable
        if isinstance(expr, ast.BinOp):
            lo, hi = self.eval(expr.left), self.eval(expr.right)
            ops = {ast.Add: lambda: lo + hi, ast.Sub: lambda: lo - hi,
                   ast.Mult: lambda: lo * hi,
                   ast.FloorDiv: lambda: lo // hi,
                   ast.Div: lambda: lo / hi, ast.Mod: lambda: lo % hi,
                   ast.Pow: lambda: lo ** hi,
                   ast.LShift: lambda: lo << hi,
                   ast.RShift: lambda: lo >> hi}
            fn = ops.get(type(expr.op))
            if fn is None or (type(expr.op) in (ast.FloorDiv, ast.Div,
                                                ast.Mod) and not hi):
                raise _Unevaluable
            return fn()
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
            return -self.eval(expr.operand)
        if isinstance(expr, ast.Call):
            fn = self._FNS.get(expr_basename(expr.func))
            if fn is None or expr.keywords:
                raise _Unevaluable
            try:
                return fn(*[self.eval(a) for a in expr.args])
            except (TypeError, ValueError, ZeroDivisionError):
                raise _Unevaluable
        if isinstance(expr, ast.IfExp):
            # undecidable test: the smaller branch keeps the total a
            # lower bound, so "cannot fit" stays a proof
            return min(self.eval(expr.body), self.eval(expr.orelse))
        raise _Unevaluable

    def itemsize(self, expr: Optional[ast.expr]) -> int:
        # anything not a literal dtype name charges the MINIMUM real
        # itemsize (1): `k_cache.dtype` may be the int8 low-precision
        # cache even when the op computes in bf16, and over-charging
        # would turn "cannot fit" from a proof into a guess
        if expr is None:
            return 1
        return _DTYPE_SIZES.get(expr_basename(expr), 1)

    def run_body(self, fn: ast.AST) -> None:
        """Execute evaluable straight-line assignments in source order.
        Writes under a conditional (If branch, loop body that may run
        zero times, Try) min-MERGE into the environment — which branch
        runs is undecidable here, and only the smallest value on any
        path keeps "cannot fit" a proof."""
        self._exec_block(fn.body, self.env)

    def _exec_block(self, stmts, env: Dict[str, int]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                saved, self.env = self.env, env
                try:
                    t = stmt.targets[0]
                    if isinstance(t, ast.Name):
                        try:
                            env[t.id] = self.eval(stmt.value)
                        except _Unevaluable:
                            pass
                    elif isinstance(t, ast.Tuple) and all(
                            isinstance(e, ast.Name) for e in t.elts) \
                            and isinstance(stmt.value, ast.Tuple) \
                            and len(stmt.value.elts) == len(t.elts):
                        for e, v in zip(t.elts, stmt.value.elts):
                            try:
                                env[e.id] = self.eval(v)
                            except _Unevaluable:
                                pass
                finally:
                    self.env = saved
            elif isinstance(stmt, (ast.If, ast.For, ast.While,
                                   ast.With, ast.Try)):
                outs = []
                for attr in ("body", "orelse", "finalbody"):
                    block = getattr(stmt, attr, []) or []
                    if block:
                        branch = dict(env)
                        self._exec_block(block, branch)
                        outs.append(branch)
                for branch in outs:
                    for k, v in branch.items():
                        env[k] = min(env[k], v) if k in env else v

    # -- buffer sizing ----------------------------------------------------

    def _shape_bytes(self, shape_expr: ast.expr, itemsize: int) -> int:
        if not isinstance(shape_expr, (ast.Tuple, ast.List)):
            raise _Unevaluable
        total = itemsize
        for dim in shape_expr.elts:
            if isinstance(dim, ast.Constant) and dim.value is None:
                continue  # squeezed block dim
            total *= int(self.eval(dim))
        return total

    def scratch_bytes(self, expr: ast.expr) -> int:
        """pltpu.VMEM((shape), dtype) -> bytes; semaphores/SMEM -> 0."""
        if not isinstance(expr, ast.Call):
            return 0
        base = expr_basename(expr.func)
        if base != "VMEM":
            return 0
        if not expr.args:
            return 0
        try:
            return self._shape_bytes(expr.args[0], self.itemsize(
                expr.args[1] if len(expr.args) > 1 else None))
        except _Unevaluable:
            return 0

    def block_bytes(self, spec: ast.expr) -> int:
        """BlockSpec((block shape), index_map) -> bytes (0 for ANY /
        unshaped specs), x2 for the grid pipeline's double buffering."""
        if not (isinstance(spec, ast.Call)
                and expr_basename(spec.func) == "BlockSpec"):
            return 0
        for k in spec.keywords:
            if k.arg == "memory_space":
                return 0  # ANY/SMEM residents are not VMEM blocks
        if not spec.args:
            return 0
        try:
            return 2 * self._shape_bytes(spec.args[0],
                                         self.block_itemsize)
        except _Unevaluable:
            return 0


def _site_of(project: Project,
             launcher: FunctionInfo) -> Optional[PallasCallSite]:
    for site in project.pallas_sites:
        if site.enclosing is not None \
                and site.enclosing.node is launcher.node:
            return site
    return None


def _estimate(project: Project, kl: KnobLaunch, value, shape_fields):
    """(bytes, budget, detail) for one config entry, or None when the
    launcher/launch cannot be resolved in the analyzed set."""
    launcher = project.resolve_function(kl.launcher)
    if launcher is None:
        return None
    site = _site_of(project, launcher)
    if site is None:
        return None
    env: Dict[str, int] = {}
    itemsize = 2  # bf16 default — the serving dtype
    dtype_declared = False
    vals = value if isinstance(value, (list, tuple)) else [value]
    if len(vals) != len(kl.value_names):
        return None  # arity errors are L006's finding
    for name, v in zip(kl.value_names, vals):
        if isinstance(v, int):
            env[name] = v
    for name, field in zip(kl.shape_names, shape_fields):
        if name is None:
            continue
        if name == "__dtype__":
            if field in _DTYPE_SIZES:
                itemsize = _DTYPE_SIZES[field]
                dtype_declared = True
        else:
            try:
                env[name] = int(field)
            except (TypeError, ValueError):
                pass
    ev = _Evaluator(env, itemsize, dtype_declared=dtype_declared)
    for dst, src in kl.aliases.items():
        if src is not None and src in ev.env:
            ev.env[dst] = ev.env[src]
    ev.run_body(launcher.node)
    total = 0
    for e in site.scratch_exprs or ():
        total += ev.scratch_bytes(e)
    for spec in list(site.in_spec_exprs or []) + list(
            site.out_spec_exprs or []):
        total += ev.block_bytes(spec)
    if total <= 0:
        return None
    budget = site.vmem_limit_bytes
    return total, budget, launcher


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for path in _config_paths(project):
        stem = os.path.splitext(os.path.basename(path))[0]
        platform_cap = VMEM_CAPS.get(stem, _DEFAULT_CAP)
        try:
            with open(path) as fh:
                src = fh.read()
            data = json.loads(src)
        except (OSError, json.JSONDecodeError):
            continue  # L006 already reports unreadable configs
        if not isinstance(data, dict):
            continue
        for _section, table in _tables(data).items():
            if not isinstance(table, dict):
                continue
            for key, value in table.items():
                op_name, sep, shape = key.partition("|")
                kl = KNOB_LAUNCHES.get(op_name)
                if kl is None or not sep:
                    continue
                est = _estimate(project, kl, value, shape.split("_"))
                if est is None:
                    continue
                total, declared, launcher = est
                budget = declared if declared is not None \
                    else platform_cap
                if total > budget:
                    findings.append(Finding(
                        CODE, path, _key_line(src, key), key,
                        f"blocks {value} need >= {total // 1024} KiB of "
                        f"VMEM in '{kl.launcher}' "
                        f"({launcher.file.basename}:"
                        f"{launcher.node.lineno}) but the launch "
                        + (f"declares vmem_limit_bytes="
                           f"{declared // (1024 * 1024)} MiB"
                           if declared is not None else
                           f"budget on {stem} is "
                           f"{budget // (1024 * 1024)} MiB")
                        + " — this tactic can never compile; shrink the "
                        "blocks or delete the entry"))
    return findings
