"""L015 mosaic_lowering — interpret-proven-only construct lint for
Pallas kernel bodies.

The kernels in this tree are developed and regression-tested under
``interpret=True`` (CPU); Mosaic — the actual TPU lowering — supports
a narrower set of shapes on the lane (last) axis, which it tiles in
128s.  PR 14's in-register rotation slices the lane dim at
``head_dim//2`` and interleaves with stride-2 slices, both annotated in
prose as "interpret-proven only, Mosaic support unknown".  Risks like
that must be machine-tracked findings, not notes a hardware session has
to rediscover, so this pass walks every resolved kernel body and flags:

``[lane-slice]``
    last-axis slicing whose bounds are not PROVABLY 0 mod 128
    (``x[:, half:]`` with ``half = head_dim // 2``), including
    ``pl.ds(start, size)`` in the last index slot with an unprovable
    start/size.  Full slices (``:``) and width-1 slices (``[:, :1]`` —
    the online-softmax running-stat idiom, a supported lane broadcast
    shape) are exempt.
``[strided-lane]``
    non-unit-stride last-axis slicing (``xf[:, 0::2]`` — the rotation
    interleave).
``[cast]``
    in-kernel dtype cast-to-match (``p.astype(v.dtype)``): the target
    dtype is data-dependent, so there is no single committed lowering
    to point at.  Casts to a LITERAL dtype (``jnp.float32``) are exempt
    — those lower through one fixed, testable path.
``[gather]``
    in-kernel ``jnp.take`` / ``take_along_axis`` — dynamic gather has
    no committed Mosaic proof at any shape in this tree.

Every finding is either ``# graft-lint: ok``-waived in place with a
reason, or triaged into the machine-readable ``mosaic_risks`` section
of the baseline (and echoed as a SARIF run property), so the hardware
bring-up session starts from a checklist instead of CHANGES.md
archaeology.  The pass is purely syntactic over RESOLVED kernels — it
executes nothing, so unlike L014 it has no skip path; unresolved
``pallas_call`` sites are counted (``stats()`` feeds ``obs doctor``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from flashinfer_tpu.analysis.core import (ChainLocals, Finding,
                                          FunctionInfo, Project,
                                          const_int, expr_basename)

LANE = 128  # Mosaic lane-dim tile width

# rule registry: tag -> one-line contract (docs/static_analysis.md and
# the SARIF rule description render from the pass docstring; this table
# is what `stats()` enumerates so a new rule cannot ship uncounted)
RULES: Dict[str, str] = {
    "lane-slice": "last-axis slice bounds not provably 0 mod 128",
    "strided-lane": "non-unit-stride last-axis slice",
    "cast": "dtype cast-to-match (data-dependent target dtype)",
    "gather": "dynamic gather (jnp.take / take_along_axis)",
}

_GATHER_NAMES = {"take", "take_along_axis"}
_DS_NAMES = {"ds", "dslice"}
_MAX_FOLD_DEPTH = 8


def _fold_int(expr: Optional[ast.expr], loc: ChainLocals,
              depth: int = 0) -> Optional[int]:
    """const_int extended one level: once-assigned local names resolve
    through the kernel's lexical scope chain (``half = head_dim // 2``
    resolves the ``// 2`` but stops at the ``head_dim`` param)."""
    if expr is None or depth > _MAX_FOLD_DEPTH:
        return None
    v = const_int(expr)
    if v is not None:
        return v
    if isinstance(expr, ast.Name):
        return _fold_int(loc.value_of(expr.id), loc, depth + 1)
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        v = _fold_int(expr.operand, loc, depth + 1)
        return -v if v is not None else None
    if isinstance(expr, ast.BinOp):
        lo = _fold_int(expr.left, loc, depth + 1)
        hi = _fold_int(expr.right, loc, depth + 1)
        if lo is None or hi is None:
            return None
        if isinstance(expr.op, ast.Add):
            return lo + hi
        if isinstance(expr.op, ast.Sub):
            return lo - hi
        if isinstance(expr.op, ast.Mult):
            return lo * hi
        if isinstance(expr.op, ast.FloorDiv):
            return lo // hi if hi else None
        if isinstance(expr.op, ast.LShift):
            return lo << hi
    return None


def _snippet(node: ast.AST, limit: int = 64) -> str:
    try:
        s = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.10
        s = f"<{type(node).__name__}>"
    return s if len(s) <= limit else s[: limit - 1] + "…"


def _aligned(v: Optional[int]) -> bool:
    return v is not None and v % LANE == 0


class _Linter:
    def __init__(self, kernel: FunctionInfo):
        self.kernel = kernel
        self.findings: List[Finding] = []

    def _emit(self, tag: str, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(
            "L015", self.kernel.file.path,
            getattr(node, "lineno", self.kernel.node.lineno),
            self.kernel.qualname, f"[{tag}] {msg}"))

    def run(self) -> List[Finding]:
        self._lint_scope(self.kernel.node, [self.kernel.node])
        return self.findings

    def _lint_scope(self, fn_node: ast.AST,
                    chain: List[ast.AST]) -> None:
        loc = ChainLocals(chain)
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested defs (the pl.when/_rot/_quant helpers) are part
                # of the kernel body but resolve names in their own
                # scope first — recurse with the extended chain
                self._lint_scope(n, [n] + chain)
                continue
            if isinstance(n, ast.Subscript):
                self._check_subscript(n, loc)
            elif isinstance(n, ast.Call):
                self._check_call(n, loc)
            stack.extend(ast.iter_child_nodes(n))

    # -- [lane-slice] / [strided-lane] ------------------------------------

    def _check_subscript(self, n: ast.Subscript,
                         loc: ChainLocals) -> None:
        # only multi-dim subscripts: a bare `seq[a:b]` is usually python
        # tuple/list plumbing (ref unpacking), not an array lane op, and
        # a 1-D array in a kernel is a sublane-only shape anyway
        idx = n.slice
        if not isinstance(idx, ast.Tuple) or not idx.elts:
            return
        # `.at[...]` builds a REF VIEW for make_async_copy — the DMA
        # engine copies arbitrary windows (alignment is a perf concern,
        # not a lowerability one), and on partially-indexed HBM refs the
        # tuple's last slot isn't the array's lane axis anyway.  Lane
        # tiling constrains VECTOR ops: plain subscripts on loaded
        # arrays.
        if isinstance(n.value, ast.Attribute) and n.value.attr == "at":
            return
        last = idx.elts[-1]
        if isinstance(last, ast.Slice):
            self._check_lane_slice(n, last, loc)
        elif isinstance(last, ast.Call) \
                and expr_basename(last.func) in _DS_NAMES:
            self._check_lane_ds(n, last, loc)

    def _check_lane_slice(self, sub: ast.Subscript, sl: ast.Slice,
                          loc: ChainLocals) -> None:
        step = _fold_int(sl.step, loc) if sl.step is not None else 1
        if step != 1:
            self._emit(
                "strided-lane", sub,
                f"strided last-axis slice `{_snippet(sub)}` "
                f"(step {_snippet(sl.step)}) — lane interleave is "
                f"interpret-proven only; no committed Mosaic lowering")
            return
        if sl.lower is None and sl.upper is None:
            return  # full slice: the lane-preserving identity
        lo = 0 if sl.lower is None else _fold_int(sl.lower, loc)
        hi = _fold_int(sl.upper, loc) if sl.upper is not None else None
        if lo is not None and hi is not None and hi - lo == 1:
            return  # width-1 ([:, :1]): supported lane-broadcast shape
        lo_ok = _aligned(lo)
        # an omitted upper bound is the array end — whatever the extent,
        # the slice START being lane-aligned is the testable obligation
        hi_ok = sl.upper is None or _aligned(hi)
        if lo_ok and hi_ok:
            return
        self._emit(
            "lane-slice", sub,
            f"last-axis slice `{_snippet(sub)}` has bound(s) not "
            f"provably 0 mod {LANE} — interpret-proven only; Mosaic "
            f"tiles the lane dim in {LANE}s")

    def _check_lane_ds(self, sub: ast.Subscript, ds: ast.Call,
                       loc: ChainLocals) -> None:
        args = [a for a in ds.args if not isinstance(a, ast.Starred)]
        start = _fold_int(args[0], loc) if args else None
        size = _fold_int(args[1], loc) if len(args) > 1 else None
        if _aligned(start) and (len(args) < 2 or _aligned(size)):
            return
        if size == 1 and start is not None:
            return  # width-1 dynamic slice: lane-broadcast shape
        self._emit(
            "lane-slice", sub,
            f"last-axis dynamic slice `{_snippet(sub)}` has "
            f"start/size not provably 0 mod {LANE} — interpret-proven "
            f"only; Mosaic tiles the lane dim in {LANE}s")

    # -- [cast] / [gather] -------------------------------------------------

    def _check_call(self, n: ast.Call, loc: ChainLocals) -> None:
        if isinstance(n.func, ast.Attribute) and n.func.attr == "astype" \
                and n.args:
            target = n.args[0]
            # cast-to-match: `.astype(<something>.dtype)` — the target
            # is whatever dtype the launch bound, so there is no single
            # lowering to test.  A literal (`jnp.float32`) is one fixed
            # path and exempt: Attribute whose attr is the dtype name,
            # not `.dtype`.
            if isinstance(target, ast.Attribute) \
                    and target.attr == "dtype":
                self._emit(
                    "cast", n,
                    f"in-kernel cast-to-match `{_snippet(n)}` — target "
                    f"dtype is data-dependent (bound at launch); no "
                    f"committed on-chip lowering proof per dtype pair")
            return
        if expr_basename(n.func) in _GATHER_NAMES:
            self._emit(
                "gather", n,
                f"in-kernel gather `{_snippet(n)}` — dynamic gather "
                f"has no committed Mosaic lowering proof")


# -- pass driver ----------------------------------------------------------


def _analyze(project: Project) -> Tuple[List[Finding], dict]:
    """-> (findings, stats) shared by run() and stats()."""
    findings: List[Finding] = []
    st = {"kernels_linted": 0, "sites_unresolved": 0,
          "findings_by_rule": {tag: 0 for tag in RULES}}
    seen: Set[int] = set()
    emitted: Set[tuple] = set()
    for site in project.pallas_sites:
        if site.kernel is None:
            st["sites_unresolved"] += 1
            continue
        if id(site.kernel.node) in seen:
            continue
        seen.add(id(site.kernel.node))
        st["kernels_linted"] += 1
        for f in _Linter(site.kernel).run():
            fkey = (f.filename, f.line, f.message)
            if fkey in emitted:
                continue
            emitted.add(fkey)
            findings.append(f)
            tag = f.message[1:].split("]", 1)[0]
            if tag in st["findings_by_rule"]:
                st["findings_by_rule"][tag] += 1
    return findings, st


def run(project: Project) -> List[Finding]:
    findings, _st = _analyze(project)
    return findings


def stats(project: Project) -> dict:
    """linted-vs-unresolved counts for ``obs doctor`` — the L013
    no-silent-skip rule applied to kernel bodies (L015 itself never
    skips a resolved kernel: the walk is total)."""
    _findings, st = _analyze(project)
    return st
