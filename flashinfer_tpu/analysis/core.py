"""Shared data model for the multi-pass static analyzer.

One ``Finding`` shape serves every pass (including the folded-in wedge
lint, whose ``Finding`` predates this package and fixed the field
names).  A ``Project`` is the unit of analysis: passes that need
cross-file resolution (L001 walks base classes defined in other
modules, L003 propagates env-read taint through cross-module calls)
consult the project-wide indexes instead of re-parsing.

Suppressions: ``# graft-lint: ok <reason>`` on the flagged line or on a
standalone comment line directly above it waives a finding.  A
suppression WITHOUT a reason still waives the finding but is itself
reported as L000 — the same contract the wedge lint proved with W000:
an unreviewable waiver is worse than the finding it hides.
"""

from __future__ import annotations

import ast
import copy
import dataclasses
import os
import re
from typing import Dict, List, Optional, Set, Tuple

GRAFT_SUPPRESS_RE = re.compile(r"#\s*graft-lint:\s*ok\b\s*(.*)")
# the wedge pass's historical spelling: waives only W-codes (scanned by
# the wedge lint itself), but the driver still audits it for reasonless
# comments — an unreviewable waiver is a finding in either spelling
WEDGE_SUPPRESS_RE = re.compile(r"#\s*wedge-lint:\s*ok\b\s*(.*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str
    filename: str
    line: int
    func: str
    message: str

    def __str__(self) -> str:
        return (f"{self.filename}:{self.line} [{self.code}] {self.func}: "
                f"{self.message}")


def project_relpath(path: str) -> str:
    """Stable path key for baselines: the path from the last known
    project-root component on, independent of the CWD the CLI ran in.
    The RIGHTMOST marker match wins across all markers — so a checkout
    directory that happens to be named ``flashinfer_tpu`` cannot hijack
    the key of a ``tests/`` or ``scripts/`` file nested inside it."""
    p = os.path.normpath(os.path.abspath(path)).replace(os.sep, "/")
    best = -1
    for marker in ("/flashinfer_tpu/", "/tests/", "/scripts/",
                   "/benchmarks/", "/examples/"):
        best = max(best, p.rfind(marker))
    if best >= 0:
        return p[best + 1:]
    return os.path.basename(p)


def iter_python_files(paths: List[str]) -> List[str]:
    """The ONE directory walk: every consumer (Project.from_paths, the
    CLI's full-tree/stale-baseline comparisons) must enumerate files
    identically or the comparisons silently diverge."""
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, _dirs, names in os.walk(path):
                for fn in sorted(names):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        else:
            out.append(path)
    return out


@dataclasses.dataclass
class SourceFile:
    path: str
    src: str
    tree: Optional[ast.Module]
    suppressions: Dict[int, str]  # graft: line -> reason ("" = reasonless)
    parse_finding: Optional[Finding] = None
    # wedge-spelled suppressions, recorded ONLY so the driver can audit
    # reasonless ones — they never waive L-codes
    wedge_suppressions: Dict[int, str] = dataclasses.field(
        default_factory=dict)

    @property
    def basename(self) -> str:
        return os.path.basename(self.path)

    def suppression_for(self, line: int) -> Optional[str]:
        """Reason string if `line` (or the comment line directly above)
        carries a graft-lint suppression; None otherwise."""
        for ln in (line, line - 1):
            if ln in self.suppressions:
                return self.suppressions[ln]
        return None


def load_source(src: str, path: str) -> SourceFile:
    suppressions: Dict[int, str] = {}
    wedge_suppressions: Dict[int, str] = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = GRAFT_SUPPRESS_RE.search(line)
        if m:
            suppressions[i] = m.group(1).strip()
        m = WEDGE_SUPPRESS_RE.search(line)
        if m:
            wedge_suppressions[i] = m.group(1).strip()
    try:
        tree = ast.parse(src, path)
        parse_finding = None
    except SyntaxError as e:  # analysis must never crash a build
        tree = None
        parse_finding = Finding("L999", path, e.lineno or 0, "<module>",
                                f"unparseable source: {e.msg}")
    return SourceFile(path, src, tree, suppressions, parse_finding,
                      wedge_suppressions)


def load_file(path: str) -> SourceFile:
    with open(path) as f:
        return load_source(f.read(), path)


class Project:
    """The set of files under analysis plus lazily-built cross-file
    indexes.  Passes receive the whole project so inheritance and call
    chains resolve across modules (within the analyzed set)."""

    def __init__(self, files: List[SourceFile]):
        self.files = files
        self._class_index: Optional[Dict[str, List["ClassInfo"]]] = None
        self._function_index: Optional[
            Dict[str, List["FunctionInfo"]]] = None
        self._pallas_sites: Optional[List["PallasCallSite"]] = None

    @classmethod
    def from_paths(cls, paths: List[str]) -> "Project":
        return cls([load_file(f) for f in iter_python_files(paths)])

    # -- class index (L001) ------------------------------------------------

    @property
    def class_index(self) -> Dict[str, List["ClassInfo"]]:
        if self._class_index is None:
            idx: Dict[str, List[ClassInfo]] = {}
            for sf in self.files:
                if sf.tree is None:
                    continue
                for node in ast.walk(sf.tree):
                    if isinstance(node, ast.ClassDef):
                        info = ClassInfo.from_node(sf, node)
                        idx.setdefault(info.name, []).append(info)
            self._class_index = idx
        return self._class_index

    def resolve_class(self, name: str) -> Optional["ClassInfo"]:
        hits = self.class_index.get(name)
        return hits[0] if hits else None

    # -- function index (the L007–L010 cross-module resolution layer) ------

    @property
    def function_index(self) -> Dict[str, List["FunctionInfo"]]:
        """Every def in the analyzed set, keyed by bare name — the
        project symbol index that lets a pass in one file see the
        planner/kernel defined in another (same resolution scope as the
        class index: name-level, within the analyzed files)."""
        if self._function_index is None:
            idx: Dict[str, List[FunctionInfo]] = {}
            for sf in self.files:
                if sf.tree is None:
                    continue
                for node, qualname in _walk_defs(sf.tree):
                    idx.setdefault(node.name, []).append(
                        FunctionInfo(node.name, qualname, sf, node))
            self._function_index = idx
        return self._function_index

    def resolve_function(
            self, name: str,
            prefer_file: Optional[SourceFile] = None
    ) -> Optional["FunctionInfo"]:
        """The def `name` resolves to: the one in `prefer_file` when it
        defines it (Python name resolution would find the local def
        first), else the unique project-wide def, else None (ambiguous
        names stay unresolved — no guessing across modules)."""
        hits = self.function_index.get(name)
        if not hits:
            return None
        if prefer_file is not None:
            local = [h for h in hits if h.file is prefer_file]
            if len(local) == 1:
                return local[0]
        return hits[0] if len(hits) == 1 else None

    @property
    def pallas_sites(self) -> List["PallasCallSite"]:
        """Every ``pl.pallas_call`` launch in the analyzed set, with its
        statically-resolved contract pieces (shared by L007–L010)."""
        if self._pallas_sites is None:
            self._pallas_sites = collect_pallas_sites(self)
        return self._pallas_sites

    def mro_chain(self, cls: "ClassInfo") -> List["ClassInfo"]:
        """Depth-first base-class chain starting at `cls` — an
        approximation of the MRO sufficient for single-inheritance
        wrapper stacks (name-resolved within the analyzed file set)."""
        chain: List[ClassInfo] = []
        seen = set()

        def _walk(c: ClassInfo) -> None:
            key = (c.file.path, c.name, c.node.lineno)
            if key in seen:
                return
            seen.add(key)
            chain.append(c)
            for base in c.base_names:
                b = self.resolve_class(base)
                if b is not None:
                    _walk(b)

        _walk(cls)
        return chain


def _base_name(expr: ast.expr) -> Optional[str]:
    """Last dotted component of a base-class expression."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Call):  # e.g. Generic[...] or a metaclass call
        return _base_name(expr.func)
    if isinstance(expr, ast.Subscript):  # Generic[T]
        return _base_name(expr.value)
    return None


@dataclasses.dataclass
class ClassInfo:
    name: str
    file: SourceFile
    node: ast.ClassDef
    base_names: List[str]
    # alias -> (target name, body index, line); LAST class-level
    # ``alias = target`` assignment wins, like the class body would.
    alias_binds: Dict[str, tuple]
    # method name -> (body index, line) of its LAST class-level def
    method_defs: Dict[str, tuple]

    @classmethod
    def from_node(cls, sf: SourceFile, node: ast.ClassDef) -> "ClassInfo":
        bases = [b for b in (_base_name(e) for e in node.bases) if b]
        aliases: Dict[str, tuple] = {}
        methods: Dict[str, tuple] = {}
        for i, stmt in enumerate(node.body):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[stmt.name] = (i, stmt.lineno)
            elif isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Name):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        aliases[t.id] = (stmt.value.id, i, stmt.lineno)
        return cls(node.name, sf, node, bases, aliases, methods)


# ---------------------------------------------------------------------------
# Cross-module resolution layer (L007–L010): function index, static
# expression helpers, and the shared Pallas launch-site scanner.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FunctionInfo:
    name: str
    qualname: str
    file: SourceFile
    node: ast.AST  # FunctionDef | AsyncFunctionDef

    @property
    def positional_params(self) -> List[str]:
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args]

    @property
    def has_vararg(self) -> bool:
        return self.node.args.vararg is not None


def _walk_defs(tree: ast.Module):
    """(def node, dotted qualname) for every function def, in source
    order, nesting encoded in the qualname (``outer.inner``)."""
    out = []

    def _walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((child, q))
                _walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                _walk(child, f"{prefix}{child.name}.")
            else:
                _walk(child, prefix)

    _walk(tree, "")
    return out


# basenames that compile a step body into a compile-once callable —
# the ONE registry the serving-contract passes (L011 donation_lifetime,
# L012 static_flow) share, so a future compile wrapper registered here
# is seen by both (registering it in only one pass would silently
# under-report in the other)
JIT_LIKE_NAMES = frozenset({"jit", "compile_step_with_plan"})


def expr_basename(expr: ast.expr) -> str:
    """Last dotted component: ``pltpu.PrefetchScalarGridSpec`` ->
    ``PrefetchScalarGridSpec``; bare names return themselves."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def expr_root(expr: ast.expr) -> Optional[str]:
    """Leftmost Name of a dotted chain (``np`` for ``np.sum``), the
    Name itself for bare names, None otherwise."""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def const_int(expr: ast.expr) -> Optional[int]:
    """Fold an integer-constant expression: literals, +,-,*,//,<<, and
    unary minus over them (``64 * 1024 * 1024`` resolves)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int) \
            and not isinstance(expr.value, bool):
        return expr.value
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        v = const_int(expr.operand)
        return -v if v is not None else None
    if isinstance(expr, ast.BinOp):
        lo, hi = const_int(expr.left), const_int(expr.right)
        if lo is None or hi is None:
            return None
        if isinstance(expr.op, ast.Add):
            return lo + hi
        if isinstance(expr.op, ast.Sub):
            return lo - hi
        if isinstance(expr.op, ast.Mult):
            return lo * hi
        if isinstance(expr.op, ast.FloorDiv):
            return lo // hi if hi else None
        if isinstance(expr.op, ast.LShift):
            return lo << hi
    return None


class FnLocals:
    """Single-assignment resolution for names local to one function:
    ``kernel = functools.partial(_k, ...)`` or ``in_specs = [...]``.
    A name counts as resolvable ONLY when it is assigned exactly once
    and never mutated in place (.append/.extend/.insert / augmented
    assignment) — conditional rebinds and list growth make the static
    count a guess, and a guessed contract check is worse than none."""

    _MUTATORS = {"append", "extend", "insert", "add", "update"}

    def __init__(self, fn_node: ast.AST):
        assigns: Dict[str, List[ast.expr]] = {}
        mutated: Set[str] = set()
        # the scope's own parameters are bindings with UNKNOWN values:
        # they must resolve to None (and block outer-scope fall-through
        # in ChainLocals), never to a shadowed outer assignment
        params: Set[str] = set()
        if isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
            a = fn_node.args
            params = {p.arg for p in (a.posonlyargs + a.args
                                      + a.kwonlyargs)}
            for va in (a.vararg, a.kwarg):
                if va is not None:
                    params.add(va.arg)
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        assigns.setdefault(t.id, []).append(n.value)
            elif isinstance(n, ast.AugAssign) and isinstance(
                    n.target, ast.Name):
                mutated.add(n.target.id)
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in self._MUTATORS \
                    and isinstance(n.func.value, ast.Name):
                mutated.add(n.func.value.id)
        self._assigns = assigns
        self._mutated = mutated
        self._params = params

    def value_of(self, name: str) -> Optional[ast.expr]:
        if name in self._params:
            return None
        vals = self._assigns.get(name)
        if vals is None or len(vals) != 1 or name in self._mutated:
            return None
        return vals[0]

    def values_of(self, name: str) -> List[ast.expr]:
        """Every assignment of an unmutated, non-param local — the
        both-arms-of-an-if selection (``kernel = partial(_a, ...)`` /
        ``kernel = partial(_b, ...)``) that ``value_of`` rightly refuses
        to pick a winner from.  Callers FORK one analysis per candidate
        instead of guessing (or skipping)."""
        if name in self._params or name in self._mutated:
            return []
        return list(self._assigns.get(name, ()))

    def seq_elements(self, expr: ast.expr,
                     _depth: int = 0) -> Optional[List[ast.expr]]:
        """Statically-known elements of a list/tuple expression: a
        literal, a concat of statics, or a once-assigned local name."""
        if _depth > 8:
            return None
        if isinstance(expr, (ast.List, ast.Tuple)):
            if any(isinstance(e, ast.Starred) for e in expr.elts):
                return None
            return list(expr.elts)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            lo = self.seq_elements(expr.left, _depth + 1)
            hi = self.seq_elements(expr.right, _depth + 1)
            if lo is None or hi is None:
                return None
            return lo + hi
        if isinstance(expr, ast.Name):
            v = self.value_of(expr.id)
            if v is not None:
                return self.seq_elements(v, _depth + 1)
        return None


_EVAL_INT_FNS = {
    "min": min, "max": max, "abs": abs, "int": int,
    "cdiv": lambda a, b: -(-a // b),
    "round_up": lambda x, m: -(-x // m) * m,
    "next_power_of_two": lambda x: 1 << max(int(x) - 1, 0).bit_length(),
}


def eval_int_expr(expr: Optional[ast.expr], env: Dict[str, int],
                  locals_: Optional[FnLocals] = None,
                  _depth: int = 0) -> Optional[int]:
    """Fold an expression to a concrete int under a known-int env:
    literals, env names (env wins — it carries the binding scenario),
    once-assigned locals, +-*//%min/max/cdiv/round_up.  None, never a
    guess, for anything else — the shared arithmetic behind grid
    trip-count resolution (L016) and chooser scenario plumbing."""
    if expr is None or _depth > 12:
        return None
    v = const_int(expr)
    if v is not None:
        return v
    if isinstance(expr, ast.Name):
        if expr.id in env and isinstance(env[expr.id], int) \
                and not isinstance(env[expr.id], bool):
            return env[expr.id]
        if locals_ is not None:
            return eval_int_expr(locals_.value_of(expr.id), env,
                                 locals_, _depth + 1)
        return None
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub):
        v = eval_int_expr(expr.operand, env, locals_, _depth + 1)
        return -v if v is not None else None
    if isinstance(expr, ast.BinOp):
        lo = eval_int_expr(expr.left, env, locals_, _depth + 1)
        hi = eval_int_expr(expr.right, env, locals_, _depth + 1)
        if lo is None or hi is None:
            return None
        if isinstance(expr.op, ast.Add):
            return lo + hi
        if isinstance(expr.op, ast.Sub):
            return lo - hi
        if isinstance(expr.op, ast.Mult):
            return lo * hi
        if isinstance(expr.op, ast.FloorDiv):
            return lo // hi if hi else None
        if isinstance(expr.op, ast.Mod):
            return lo % hi if hi else None
        if isinstance(expr.op, ast.LShift):
            return lo << hi
        return None
    if isinstance(expr, ast.Call) and not expr.keywords:
        fn = _EVAL_INT_FNS.get(expr_basename(expr.func))
        if fn is None:
            return None
        args = [eval_int_expr(a, env, locals_, _depth + 1)
                for a in expr.args]
        if any(a is None for a in args):
            return None
        try:
            return int(fn(*args))
        except (TypeError, ValueError, ZeroDivisionError):
            return None
    return None


_PALLAS_CALL_NAMES = {"pallas_call"}
_GRID_SPEC_NAMES = {"PrefetchScalarGridSpec", "GridSpec"}
_PARTIAL_CALL_NAMES = {"partial"}


def _unwrap_partial(
        expr: ast.expr, locals_: FnLocals, _depth: int = 0,
) -> Tuple[Optional[ast.expr], Set[str], int,
           Dict[str, ast.expr], List[ast.expr]]:
    """(innermost callable expr, keyword names bound along the partial
    chain, count of POSITIONALLY-bound partial args — they consume the
    kernel's leading params, kwarg name -> bound VALUE expr, positional
    bound value exprs in order).  Resolves through once-assigned local
    names.  The value exprs are what L014 evaluates (in the launcher's
    scope) to recover static kernel parameters; innermost partial wins
    a kwarg collision, matching functools semantics."""
    bound: Set[str] = set()
    npos = 0
    kw_exprs: Dict[str, ast.expr] = {}
    pos_exprs: List[ast.expr] = []
    while _depth < 8:
        _depth += 1
        if isinstance(expr, ast.Call) \
                and expr_basename(expr.func) in _PARTIAL_CALL_NAMES \
                and expr.args:
            bound |= {k.arg for k in expr.keywords if k.arg}
            for k in expr.keywords:
                if k.arg:  # inner partial (seen later) overrides
                    kw_exprs[k.arg] = k.value
            npos += len(expr.args) - 1
            pos_exprs = list(expr.args[1:]) + pos_exprs
            expr = expr.args[0]
            continue
        if isinstance(expr, ast.Name):
            v = locals_.value_of(expr.id)
            if v is not None and not isinstance(v, ast.Name):
                expr = v
                continue
        break
    return expr, bound, npos, kw_exprs, pos_exprs


@dataclasses.dataclass
class PallasCallSite:
    """One ``pl.pallas_call`` launch and everything about its contract
    that is statically decidable.  ``None`` fields mean "not statically
    countable here" — passes must skip, never guess."""

    file: SourceFile
    enclosing: Optional[FunctionInfo]  # the launcher def
    call: ast.Call                     # the pallas_call(...) itself
    invocation: Optional[ast.Call]     # pallas_call(...)(operands...)
    kernel: Optional[FunctionInfo]     # resolved kernel def
    kernel_bound_kwargs: Set[str]      # kwargs bound via functools.partial
    kernel_bound_posargs: int          # positional partial binds (leading)
    is_prefetch_spec: bool             # PrefetchScalarGridSpec launch
    num_scalar_prefetch: Optional[int]
    grid_rank: Optional[int]
    in_spec_exprs: Optional[List[ast.expr]]
    out_spec_exprs: Optional[List[ast.expr]]
    scratch_exprs: Optional[List[ast.expr]]
    io_aliases_expr: Optional[ast.expr]
    vmem_limit_bytes: Optional[int]
    locals_: FnLocals
    # the bound VALUE exprs behind kernel_bound_kwargs/posargs, and the
    # grid tuple's element exprs — both evaluated (in the launcher's
    # scope) by L014 to seed static kernel parameters.  None grid_exprs
    # mirrors grid_rank=None: not statically visible here.
    kernel_bound_kwarg_exprs: Dict[str, ast.expr] = dataclasses.field(
        default_factory=dict)
    kernel_bound_posarg_exprs: List[ast.expr] = dataclasses.field(
        default_factory=list)
    grid_exprs: Optional[List[ast.expr]] = None
    # when the kernel resolved through a CALLER of the launcher (the
    # trampoline shape: the kernel is a launcher parameter), the bound
    # value exprs live in the caller's scope — evaluate them there, not
    # against the launcher's locals.  None means `locals_` is correct.
    bound_expr_locals: Optional[FnLocals] = None

    @property
    def line(self) -> int:
        return self.call.lineno

    def resolve_trip_counts(
            self, env: Dict[str, int]) -> Optional[List[int]]:
        """Concrete per-axis grid trip counts under a known-int
        environment (a chooser/cost binding's shape scenario), or None
        when any axis stays symbolic.  Grid exprs are evaluated with
        ``eval_int_expr`` — env names win, then once-assigned launcher
        locals, then literal arithmetic; anything else is not a guess."""
        if not self.grid_exprs:
            return None
        trips: List[int] = []
        for e in self.grid_exprs:
            v = eval_int_expr(e, env, self.locals_)
            if v is None or v <= 0:
                return None
            trips.append(v)
        return trips


def _spec_list(expr: Optional[ast.expr],
               locals_: FnLocals) -> Optional[List[ast.expr]]:
    """Spec kwarg -> element list.  A bare BlockSpec/ShapeDtypeStruct
    call (the single-output shorthand) counts as a 1-element list."""
    if expr is None:
        return None
    elems = locals_.seq_elements(expr)
    if elems is not None:
        return elems
    resolved = expr
    if isinstance(expr, ast.Name):
        v = locals_.value_of(expr.id)
        if v is None:
            return None
        elems = locals_.seq_elements(v)
        if elems is not None:
            return elems
        resolved = v
    if isinstance(resolved, ast.Call):
        return [resolved]
    return None


def walk_own_scope(node: ast.AST):
    """Child nodes of `node` excluding the interiors of nested defs
    (the nested def node itself IS yielded)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(n))


class ChainLocals(FnLocals):
    """FnLocals over a lexical scope chain (innermost def first): a
    launch inside a closure still resolves names bound in the enclosing
    launcher — matching Python's own lookup order."""

    def __init__(self, scopes: List[ast.AST]):
        self._chain = [FnLocals(s) for s in scopes]

    def value_of(self, name: str) -> Optional[ast.expr]:
        for loc in self._chain:
            v = loc.value_of(name)
            if v is not None:
                return v
            # a name bound-but-unresolvable in an inner scope (param,
            # multi-assign, mutation) must not fall through to a stale
            # outer binding
            if name in loc._assigns or name in loc._mutated \
                    or name in loc._params:
                return None
        return None

    def values_of(self, name: str) -> List[ast.expr]:
        for loc in self._chain:
            vals = loc.values_of(name)
            if vals:
                return vals
            if name in loc._assigns or name in loc._mutated \
                    or name in loc._params:
                return []
        return []


def collect_pallas_sites(project: "Project") -> List[PallasCallSite]:
    sites: List[PallasCallSite] = []
    for sf in project.files:
        if sf.tree is None:
            continue

        def _scan(scope: ast.AST, chain: List[ast.AST],
                  qual_prefix: str) -> None:
            for node in walk_own_scope(scope):
                if isinstance(node,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                    _scan(node, [node] + chain,
                          f"{qual_prefix}{node.name}.")
                elif isinstance(node, ast.ClassDef):
                    _scan(node, chain, f"{qual_prefix}{node.name}.")
                elif isinstance(node, ast.Call) and expr_basename(
                        node.func) in _PALLAS_CALL_NAMES:
                    enclosing = None
                    for s in chain:
                        if isinstance(s, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                            enclosing = FunctionInfo(
                                s.name, s.name, sf, s)
                            break
                    sites.extend(_build_site(
                        project, sf, enclosing, node,
                        ChainLocals(chain or [sf.tree]),
                        chain[0] if chain else sf.tree))

        _scan(sf.tree, [], "")
    return sites


_MAX_KERNEL_FORKS = 4


def _param_arg_exprs(project: "Project", enclosing: FunctionInfo,
                     pname: str) -> List[Tuple[ast.expr, FnLocals]]:
    """Argument exprs bound to launcher parameter ``pname`` at every
    project-wide call of the launcher, each paired with the CALLER's
    scope locals (a partial chain unwraps in the scope that wrote it).
    Feeds the trampoline kernel shape: ``_launch(kernel, ...)`` where
    the real kernel arrives from the wrapper one frame up."""
    node = enclosing.node
    a = node.args
    pos_params = [p.arg for p in (a.posonlyargs + a.args)]
    idx = pos_params.index(pname) if pname in pos_params else None
    out: List[Tuple[ast.expr, FnLocals]] = []
    for f in project.files:
        if f.tree is None:
            continue

        def _scan(scope: ast.AST, chain: List[ast.AST],
                  tree: ast.AST) -> None:
            for n in walk_own_scope(scope):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if n is not node:  # the def itself is not a call site
                        _scan(n, [n] + chain, tree)
                elif isinstance(n, ast.Call) \
                        and expr_basename(n.func) == enclosing.name:
                    expr = None
                    for k in n.keywords:
                        if k.arg == pname:
                            expr = k.value
                    if expr is None and idx is not None \
                            and idx < len(n.args) \
                            and not any(isinstance(p, ast.Starred)
                                        for p in n.args[: idx + 1]):
                        expr = n.args[idx]
                    if expr is not None:
                        out.append((expr, ChainLocals(chain or [tree])))

        _scan(f.tree, [], f.tree)
    return out


def _kernel_candidates(
        project: "Project", sf: SourceFile,
        enclosing: Optional[FunctionInfo], call: ast.Call,
        locals_: FnLocals,
) -> List[Tuple[FunctionInfo, Set[str], int, Dict[str, ast.expr],
                List[ast.expr], Optional[FnLocals]]]:
    """Statically-possible kernels behind the pallas_call's first
    argument.  Beyond the single-resolution ``_unwrap_partial`` chase,
    two shapes FORK one candidate per possibility instead of failing:
    a name assigned once per branch of an if/else (the moe gather
    rowcache-vs-plain kernel select), and a name that is a PARAMETER of
    the enclosing launcher, resolved through every project-wide caller
    (the sampling bisect trampoline).  Each entry is (kernel info,
    bound kwarg names, bound posarg count, kwarg exprs, posarg exprs,
    expr locals or None when the launch scope is already correct)."""
    if not call.args:
        return []
    root = call.args[0]
    exprs: List[Tuple[ast.expr, Optional[FnLocals]]] = [(root, None)]
    if isinstance(root, ast.Name):
        multi = locals_.values_of(root.id)
        if len(multi) > 1:
            if len(multi) > _MAX_KERNEL_FORKS:
                return []  # too many rebinds: stay honestly unresolved
            exprs = [(v, None) for v in multi]
        elif not multi and enclosing is not None:
            callers = _param_arg_exprs(project, enclosing, root.id)
            if callers:
                if len(callers) > _MAX_KERNEL_FORKS:
                    return []
                exprs = [(v, loc) for v, loc in callers]
    out = []
    seen: Set[int] = set()
    for expr, expr_loc in exprs:
        target, bound, npos, kw_exprs, pos_exprs = _unwrap_partial(
            expr, expr_loc if expr_loc is not None else locals_)
        info = None
        if target is not None:
            base = expr_basename(target)
            if base:
                info = project.resolve_function(base, prefer_file=sf)
        if info is None or id(info.node) in seen:
            continue
        seen.add(id(info.node))
        out.append((info, bound, npos, kw_exprs, pos_exprs, expr_loc))
    return out


def _lambda_grid_elts(lam: ast.Lambda,
                      call: ast.Call) -> Optional[List[ast.expr]]:
    """Substitute a grid-builder lambda's call args for its params in
    its tuple body: ``grid = lambda nt: (nt, tiles_n, tiles_k)`` then
    ``grid=grid(num_tiles)`` yields ``(num_tiles, tiles_n, tiles_k)``.
    Positional-only and arity-exact; anything fancier returns None —
    the rank may still be known while the element exprs are not."""
    params = [p.arg for p in lam.args.args]
    if (lam.args.posonlyargs or lam.args.kwonlyargs or lam.args.vararg
            or lam.args.kwarg or lam.args.defaults or call.keywords
            or len(call.args) != len(params)):
        return None
    sub = {p: a for p, a in zip(params, call.args)}

    class _Subst(ast.NodeTransformer):
        def visit_Name(self, n: ast.Name) -> ast.expr:
            return sub.get(n.id, n)

    return [_Subst().visit(copy.deepcopy(e)) for e in lam.body.elts
            ] if isinstance(lam.body, ast.Tuple) else None


def _build_site(project: "Project", sf: SourceFile,
                enclosing: Optional[FunctionInfo], call: ast.Call,
                locals_: FnLocals,
                scope_node: ast.AST) -> List[PallasCallSite]:
    """Sites for one pallas_call — usually one; one per candidate when
    the kernel resolution legitimately forks (branch-selected kernel
    locals, trampoline launchers)."""
    kwargs = {k.arg: k.value for k in call.keywords if k.arg}

    # grid spec: inline call, once-assigned local, or direct kwargs
    spec_call = None
    gs = kwargs.get("grid_spec")
    if isinstance(gs, ast.Name):
        gs = locals_.value_of(gs.id)
    if isinstance(gs, ast.Call) \
            and expr_basename(gs.func) in _GRID_SPEC_NAMES:
        spec_call = gs
    spec_kwargs = ({k.arg: k.value for k in spec_call.keywords if k.arg}
                   if spec_call is not None else kwargs)
    is_prefetch = bool(
        spec_call is not None
        and expr_basename(spec_call.func) == "PrefetchScalarGridSpec")

    nsp = None
    if is_prefetch:
        nsp_expr = spec_kwargs.get("num_scalar_prefetch")
        nsp = const_int(nsp_expr) if nsp_expr is not None else 0
    grid_rank = None
    grid_exprs: Optional[List[ast.expr]] = None
    grid_expr = spec_kwargs.get("grid")
    if isinstance(grid_expr, ast.Name):
        grid_expr = locals_.value_of(grid_expr.id)
    if isinstance(grid_expr, (ast.Tuple, ast.List)):
        grid_rank = len(grid_expr.elts)
        grid_exprs = list(grid_expr.elts)
    elif grid_expr is not None and const_int(grid_expr) is not None:
        grid_rank = 1
        grid_exprs = [grid_expr]
    elif isinstance(grid_expr, ast.Call) \
            and isinstance(grid_expr.func, ast.Name):
        # grid built by a local helper lambda — ``grid = lambda nt:
        # (nt, tiles_n, tiles_k)`` then ``grid=grid(num_tiles)``.  The
        # rank is statically visible whenever EVERY candidate lambda
        # (branch-selected rebinds included) returns a tuple of the
        # same arity; the element exprs are kept only when the lambda
        # is unambiguous, since branch candidates may order the axes
        # differently and a guessed axis order is worse than none.
        builders = locals_.values_of(grid_expr.func.id)
        if builders and all(
                isinstance(b, ast.Lambda)
                and isinstance(b.body, ast.Tuple) for b in builders):
            ranks = {len(b.body.elts) for b in builders}
            if len(ranks) == 1:
                grid_rank = ranks.pop()
                if len(builders) == 1:
                    grid_exprs = _lambda_grid_elts(
                        builders[0], grid_expr)

    in_specs = _spec_list(spec_kwargs.get("in_specs"), locals_)
    out_specs = _spec_list(spec_kwargs.get("out_specs"), locals_)
    scratch = _spec_list(spec_kwargs.get("scratch_shapes"), locals_)
    if scratch is None and "scratch_shapes" not in spec_kwargs \
            and (spec_call is not None or "grid_spec" not in kwargs):
        # an omitted scratch_shapes is statically ZERO scratch refs —
        # leaving it "uncountable" would disable the kernel-arity check
        # for every plain launch; only an UNRESOLVED grid_spec (where
        # the real kwargs are invisible) keeps it unknown
        scratch = []

    # kernel: first positional arg, through partial chains, local
    # names (forking on branch-selected rebinds), and trampoline params
    cands = _kernel_candidates(project, sf, enclosing, call, locals_)
    if not cands:
        bound: Set[str] = set()
        bound_pos = 0
        bound_kw_exprs: Dict[str, ast.expr] = {}
        bound_pos_exprs: List[ast.expr] = []
        if call.args:
            (_target, bound, bound_pos, bound_kw_exprs,
             bound_pos_exprs) = _unwrap_partial(call.args[0], locals_)
        cands = [(None, bound, bound_pos, bound_kw_exprs,
                  bound_pos_exprs, None)]

    # the immediately-applied operand call, if any
    invocation = None
    for n in ast.walk(scope_node):
        if isinstance(n, ast.Call) and n.func is call:
            invocation = n
            break

    vmem = None
    cp = kwargs.get("compiler_params")
    if isinstance(cp, ast.Call):
        for k in cp.keywords:
            if k.arg == "vmem_limit_bytes":
                vmem = const_int(k.value)

    return [PallasCallSite(
        file=sf, enclosing=enclosing, call=call, invocation=invocation,
        kernel=kernel_info, kernel_bound_kwargs=bound,
        kernel_bound_posargs=bound_pos,
        is_prefetch_spec=is_prefetch, num_scalar_prefetch=nsp,
        grid_rank=grid_rank, in_spec_exprs=in_specs,
        out_spec_exprs=out_specs, scratch_exprs=scratch,
        io_aliases_expr=kwargs.get("input_output_aliases"),
        vmem_limit_bytes=vmem, locals_=locals_,
        kernel_bound_kwarg_exprs=bound_kw_exprs,
        kernel_bound_posarg_exprs=bound_pos_exprs,
        grid_exprs=grid_exprs, bound_expr_locals=expr_locals)
        for (kernel_info, bound, bound_pos, bound_kw_exprs,
             bound_pos_exprs, expr_locals) in cands]
