"""Shared data model for the multi-pass static analyzer.

One ``Finding`` shape serves every pass (including the folded-in wedge
lint, whose ``Finding`` predates this package and fixed the field
names).  A ``Project`` is the unit of analysis: passes that need
cross-file resolution (L001 walks base classes defined in other
modules, L003 propagates env-read taint through cross-module calls)
consult the project-wide indexes instead of re-parsing.

Suppressions: ``# graft-lint: ok <reason>`` on the flagged line or on a
standalone comment line directly above it waives a finding.  A
suppression WITHOUT a reason still waives the finding but is itself
reported as L000 — the same contract the wedge lint proved with W000:
an unreviewable waiver is worse than the finding it hides.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional

GRAFT_SUPPRESS_RE = re.compile(r"#\s*graft-lint:\s*ok\b\s*(.*)")
# the wedge pass's historical spelling: waives only W-codes (scanned by
# the wedge lint itself), but the driver still audits it for reasonless
# comments — an unreviewable waiver is a finding in either spelling
WEDGE_SUPPRESS_RE = re.compile(r"#\s*wedge-lint:\s*ok\b\s*(.*)")


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str
    filename: str
    line: int
    func: str
    message: str

    def __str__(self) -> str:
        return (f"{self.filename}:{self.line} [{self.code}] {self.func}: "
                f"{self.message}")


def project_relpath(path: str) -> str:
    """Stable path key for baselines: the path from the last known
    project-root component on, independent of the CWD the CLI ran in.
    The RIGHTMOST marker match wins across all markers — so a checkout
    directory that happens to be named ``flashinfer_tpu`` cannot hijack
    the key of a ``tests/`` or ``scripts/`` file nested inside it."""
    p = os.path.normpath(os.path.abspath(path)).replace(os.sep, "/")
    best = -1
    for marker in ("/flashinfer_tpu/", "/tests/", "/scripts/",
                   "/benchmarks/", "/examples/"):
        best = max(best, p.rfind(marker))
    if best >= 0:
        return p[best + 1:]
    return os.path.basename(p)


@dataclasses.dataclass
class SourceFile:
    path: str
    src: str
    tree: Optional[ast.Module]
    suppressions: Dict[int, str]  # graft: line -> reason ("" = reasonless)
    parse_finding: Optional[Finding] = None
    # wedge-spelled suppressions, recorded ONLY so the driver can audit
    # reasonless ones — they never waive L-codes
    wedge_suppressions: Dict[int, str] = dataclasses.field(
        default_factory=dict)

    @property
    def basename(self) -> str:
        return os.path.basename(self.path)

    def suppression_for(self, line: int) -> Optional[str]:
        """Reason string if `line` (or the comment line directly above)
        carries a graft-lint suppression; None otherwise."""
        for ln in (line, line - 1):
            if ln in self.suppressions:
                return self.suppressions[ln]
        return None


def load_source(src: str, path: str) -> SourceFile:
    suppressions: Dict[int, str] = {}
    wedge_suppressions: Dict[int, str] = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = GRAFT_SUPPRESS_RE.search(line)
        if m:
            suppressions[i] = m.group(1).strip()
        m = WEDGE_SUPPRESS_RE.search(line)
        if m:
            wedge_suppressions[i] = m.group(1).strip()
    try:
        tree = ast.parse(src, path)
        parse_finding = None
    except SyntaxError as e:  # analysis must never crash a build
        tree = None
        parse_finding = Finding("L999", path, e.lineno or 0, "<module>",
                                f"unparseable source: {e.msg}")
    return SourceFile(path, src, tree, suppressions, parse_finding,
                      wedge_suppressions)


def load_file(path: str) -> SourceFile:
    with open(path) as f:
        return load_source(f.read(), path)


class Project:
    """The set of files under analysis plus lazily-built cross-file
    indexes.  Passes receive the whole project so inheritance and call
    chains resolve across modules (within the analyzed set)."""

    def __init__(self, files: List[SourceFile]):
        self.files = files
        self._class_index: Optional[Dict[str, List["ClassInfo"]]] = None

    @classmethod
    def from_paths(cls, paths: List[str]) -> "Project":
        files: List[SourceFile] = []
        for path in paths:
            if os.path.isdir(path):
                for dirpath, _dirs, names in os.walk(path):
                    for fn in sorted(names):
                        if fn.endswith(".py"):
                            files.append(
                                load_file(os.path.join(dirpath, fn)))
            else:
                files.append(load_file(path))
        return cls(files)

    # -- class index (L001) ------------------------------------------------

    @property
    def class_index(self) -> Dict[str, List["ClassInfo"]]:
        if self._class_index is None:
            idx: Dict[str, List[ClassInfo]] = {}
            for sf in self.files:
                if sf.tree is None:
                    continue
                for node in ast.walk(sf.tree):
                    if isinstance(node, ast.ClassDef):
                        info = ClassInfo.from_node(sf, node)
                        idx.setdefault(info.name, []).append(info)
            self._class_index = idx
        return self._class_index

    def resolve_class(self, name: str) -> Optional["ClassInfo"]:
        hits = self.class_index.get(name)
        return hits[0] if hits else None

    def mro_chain(self, cls: "ClassInfo") -> List["ClassInfo"]:
        """Depth-first base-class chain starting at `cls` — an
        approximation of the MRO sufficient for single-inheritance
        wrapper stacks (name-resolved within the analyzed file set)."""
        chain: List[ClassInfo] = []
        seen = set()

        def _walk(c: ClassInfo) -> None:
            key = (c.file.path, c.name, c.node.lineno)
            if key in seen:
                return
            seen.add(key)
            chain.append(c)
            for base in c.base_names:
                b = self.resolve_class(base)
                if b is not None:
                    _walk(b)

        _walk(cls)
        return chain


def _base_name(expr: ast.expr) -> Optional[str]:
    """Last dotted component of a base-class expression."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Call):  # e.g. Generic[...] or a metaclass call
        return _base_name(expr.func)
    if isinstance(expr, ast.Subscript):  # Generic[T]
        return _base_name(expr.value)
    return None


@dataclasses.dataclass
class ClassInfo:
    name: str
    file: SourceFile
    node: ast.ClassDef
    base_names: List[str]
    # alias -> (target name, body index, line); LAST class-level
    # ``alias = target`` assignment wins, like the class body would.
    alias_binds: Dict[str, tuple]
    # method name -> (body index, line) of its LAST class-level def
    method_defs: Dict[str, tuple]

    @classmethod
    def from_node(cls, sf: SourceFile, node: ast.ClassDef) -> "ClassInfo":
        bases = [b for b in (_base_name(e) for e in node.bases) if b]
        aliases: Dict[str, tuple] = {}
        methods: Dict[str, tuple] = {}
        for i, stmt in enumerate(node.body):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[stmt.name] = (i, stmt.lineno)
            elif isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Name):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        aliases[t.id] = (stmt.value.id, i, stmt.lineno)
        return cls(node.name, sf, node, bases, aliases, methods)
