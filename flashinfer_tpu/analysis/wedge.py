"""L004 — the wedge-pattern lint, behind the shared analysis driver.

This is the implementation that used to live in
``flashinfer_tpu/wedge_lint.py`` (that shim is retired — importing it
raises ``ModuleNotFoundError``; ``compile_guard`` and every caller
import from here, docs/migration.md).  It encodes two real chip-wedge
incidents as AST heuristics; finding codes keep their original W
prefix because they are committed in suppressions and docs.

This project has twice wedged the shared TPU compile server with kernel
contents that HANG Mosaic (not fail cleanly): round 1 (flash-kernel
variants) and round 2 (``fp4_paged_decode_attention`` at
pages_per_chunk=16 — an unrolled body of 8 heads x 16 pages x 2
parities of small dots; the same kernel at ppc=8 compiled fine).  A
wedge takes out EVERY compile from every process, for hours to days.

Checks (kernel-like functions only — a parameter ending in ``_ref`` or
a name ending in ``_kernel``):

W001 unrolled-dot-explosion: statically-unrolled ``for`` nests whose
     bodies issue MXU dots; total dots > {DOT_UNROLL_LIMIT} hangs the
     scheduler (the round-2 wedge: 256 small dots).
W002 unrolled-dma-queue: literal-range loops issuing async copies with
     unroll > {DMA_UNROLL_LIMIT} (DMA queue depth) — per-row DMA loops
     must be chunked or double-buffered instead.
W003 lane-repeat: ``jnp.repeat``/``pltpu.repeat`` on the minor (lane)
     dim is an unsupported shape cast in Mosaic ("infer-vector-layout");
     use a selector-matrix matmul or ride the sublane dim
     (memory: mosaic-kernel-constraints).
W004 dynamic-unroll: a Python ``for`` over a NON-literal ``range`` in a
     kernel body is fully unrolled at trace time with a bound the lint
     cannot see — the round-2 wedge was exactly this (range(ppc) with
     ppc=16 from a closure).  Such loops containing dots or async
     copies must carry a suppression stating the clamp that bounds them
     (e.g. 'ok ppc clamped <= 8 at call site').

Suppression: append ``# wedge-lint: ok <reason>`` on the flagged line
(or the ``def`` line to waive a whole function).  A suppression without
a reason is itself a finding (W000).  The shared ``# graft-lint: ok``
form is honored identically on every path — driver, standalone lint,
and the runtime compile guard — so a CI-blessed suppression can never
diverge from what strict mode enforces on real TPU.

Wiring: ``compile_guard.guarded(..., module=m)`` lints ``m``'s source
once per process before the first hardware compile and refuses to
compile a flagged kernel unless FLASHINFER_TPU_WEDGE_LINT=off (or warn —
the default outside TPU).
"""

from __future__ import annotations

import ast
import inspect
import os
import re
from typing import List, Optional

from flashinfer_tpu.analysis.core import Finding, Project

CODE = "L004"  # driver registration; emitted findings keep W-codes

DOT_UNROLL_LIMIT = 64
DMA_UNROLL_LIMIT = 8

# both spellings are honored EVERYWHERE this lint runs — the analysis
# driver, lint_file/lint_tree, and the runtime compile guard
# (check_module).  If the runtime guard scanned only the wedge form, a
# CI-blessed '# graft-lint: ok' kernel suppression would still
# hard-block compilation on real TPU (strict mode raises every call).
_SUPPRESS_RE = re.compile(r"#\s*(?:wedge|graft)-lint:\s*ok\b\s*(.*)")

_DOT_NAMES = {"dot", "dot_general", "matmul", "einsum"}
_DMA_NAMES = {"make_async_copy", "make_async_remote_copy", "async_copy"}
_REPEAT_NAMES = {"repeat"}


def _literal_range_extent(node: ast.For) -> Optional[int]:
    """Static trip count of ``for _ in range(<int literal>)`` (or
    range(a, b) with both literal); None when dynamic."""
    it = node.iter
    if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id == "range"):
        return None
    vals = []
    for a in it.args:
        if isinstance(a, ast.Constant) and isinstance(a.value, int):
            vals.append(a.value)
        else:
            return None
    if len(vals) == 1:
        return max(vals[0], 0)
    if len(vals) >= 2:
        step = vals[2] if len(vals) > 2 and vals[2] else 1
        return max((vals[1] - vals[0] + (step - 1)) // step, 0) \
            if step > 0 else None
    return None


def _call_basename(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _is_kernel_like(fn: ast.FunctionDef) -> bool:
    if fn.name.endswith("_kernel"):
        return True
    args = fn.args
    every = (args.posonlyargs + args.args + args.kwonlyargs
             + ([args.vararg] if args.vararg else []))
    return any(a.arg.endswith("_ref") for a in every if a)


class _KernelVisitor(ast.NodeVisitor):
    """Walks one kernel-like function, tracking literal unroll products."""

    def __init__(self, filename: str, func: str, suppressed, findings):
        self.filename = filename
        self.func = func
        self.suppressed = suppressed  # {line: reason-or-""}
        self.findings: List[Finding] = findings
        self.unroll = 1            # product of enclosing literal ranges
        self.dot_count = 0         # unroll-weighted dots in this function
        self.dot_first_line = None
        self.dma_count = 0         # unroll-weighted async-copy starts
        self.dma_first_line = None

    def _suppress(self, line: int) -> bool:
        # a suppression may sit on the flagged line, on a standalone
        # comment line directly above it, or on the function's def line
        for ln in (line, line - 1, getattr(self, "_def_line", -1)):
            if ln in self.suppressed:
                if not self.suppressed[ln]:
                    self.findings.append(Finding(
                        "W000", self.filename, ln, self.func,
                        "wedge-lint suppression without a reason — state "
                        "why the pattern is safe (e.g. 'on-chip validated "
                        "YYYY-MM-DD at config ...')"))
                return True
        return False

    def visit_For(self, node: ast.For) -> None:
        extent = _literal_range_extent(node)
        if extent is None:
            is_range = (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range")
            risky = sum(
                1 for n in ast.walk(node)
                if (isinstance(n, ast.Call)
                    and _call_basename(n) in (_DOT_NAMES | _DMA_NAMES))
                or (isinstance(n, ast.BinOp)
                    and isinstance(n.op, ast.MatMult)))
            if is_range and risky and not self._suppress(node.lineno):
                self.findings.append(Finding(
                    "W004", self.filename, node.lineno, self.func,
                    "Python for over a non-literal range unrolls at "
                    f"trace time with an unbounded factor and contains "
                    f"{risky} dot/DMA call(s) — the round-2 wedge shape "
                    "(range(ppc), ppc=16). Clamp the bound and suppress "
                    "with the clamp stated, or use lax.fori_loop"))
            self.generic_visit(node)
            return
        # the W002 DMA count accrues unroll-weighted at each call site
        # (visit_Call), so nested literal loops multiply correctly
        self.unroll *= max(extent, 1)
        self.generic_visit(node)
        self.unroll //= max(extent, 1)

    def visit_Call(self, node: ast.Call) -> None:
        base = _call_basename(node)
        if base in _DOT_NAMES:
            self.dot_count += self.unroll
            if self.dot_first_line is None:
                self.dot_first_line = node.lineno
        if base in _DMA_NAMES:
            self.dma_count += self.unroll
            if self.dma_first_line is None:
                self.dma_first_line = node.lineno
        if base in _REPEAT_NAMES:
            def _const_axis(v):
                if isinstance(v, ast.Constant):
                    return v.value, True
                if (isinstance(v, ast.UnaryOp)
                        and isinstance(v.op, ast.USub)
                        and isinstance(v.operand, ast.Constant)):
                    return -v.operand.value, True
                return None, True  # non-constant expression: unknown

            axis = None
            has_axis = False
            for kw in node.keywords:
                if kw.arg == "axis":
                    axis, has_axis = _const_axis(kw.value)
            if not has_axis and len(node.args) >= 3:
                # positional axis form: jnp.repeat(x, reps, axis)
                axis, has_axis = _const_axis(node.args[2])
            # axis=-1 is definitely the lane dim; an unknown/omitted axis
            # flattens (jnp semantics) which also crosses the lane dim
            if (axis in (-1, None) or not has_axis) \
                    and not self._suppress(node.lineno):
                self.findings.append(Finding(
                    "W003", self.filename, node.lineno, self.func,
                    "repeat on (or possibly on) the minor/lane dim is an "
                    "unsupported Mosaic shape cast — use a selector-"
                    "matrix matmul or move the broadcast to the sublane "
                    "dim (mosaic-kernel-constraints)"))
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, ast.MatMult):
            self.dot_count += self.unroll
            if self.dot_first_line is None:
                self.dot_first_line = node.lineno
        self.generic_visit(node)


def lint_source(src: str, filename: str = "<string>",
                tree: Optional[ast.Module] = None) -> List[Finding]:
    findings: List[Finding] = []
    suppressed = {}
    for i, line in enumerate(src.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            suppressed[i] = m.group(1).strip()
    if tree is None:
        try:
            tree = ast.parse(src, filename)
        except SyntaxError as e:  # lint must never crash a build
            findings.append(Finding(
                "W999", filename, e.lineno or 0, "<module>",
                f"unparseable source: {e.msg}"))
            return findings
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and _is_kernel_like(node):
            v = _KernelVisitor(filename, node.name, suppressed, findings)
            v._def_line = node.lineno
            v.visit(node)
            if v.dot_count > DOT_UNROLL_LIMIT \
                    and not v._suppress(v.dot_first_line or node.lineno):
                findings.append(Finding(
                    "W001", filename, v.dot_first_line or node.lineno,
                    node.name,
                    f"~{v.dot_count} statically-unrolled MXU dots in one "
                    f"kernel body (> {DOT_UNROLL_LIMIT}) — the round-2 "
                    "wedge shape; hoist the loop into the grid or shrink "
                    "the unroll factor (tpu-wedge-history: ppc<=8)"))
            if v.dma_count > DMA_UNROLL_LIMIT \
                    and not v._suppress(v.dma_first_line or node.lineno):
                findings.append(Finding(
                    "W002", filename, v.dma_first_line or node.lineno,
                    node.name,
                    f"~{v.dma_count} statically-unrolled async-copy "
                    f"starts in one kernel body (> DMA queue depth "
                    f"{DMA_UNROLL_LIMIT}); chunk the loop nest or "
                    "double-buffer (wedge history: unrolled per-row DMA "
                    "loops)"))
    return findings


def lint_file(path: str) -> List[Finding]:
    with open(path) as f:
        return lint_source(f.read(), path)


def lint_tree(root: str) -> List[Finding]:
    out: List[Finding] = []
    for dirpath, _dirs, files in os.walk(root):
        for fn in sorted(files):
            if fn.endswith(".py"):
                out.extend(lint_file(os.path.join(dirpath, fn)))
    return out


def run(project: Project) -> List[Finding]:
    """Analysis-driver adapter: lint every project file, reusing the
    driver's already-parsed trees (no second ast.parse per file).
    Unparseable files are already reported as L999 by the driver; skip
    them here so they don't double-report as W999."""
    out: List[Finding] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        out.extend(lint_source(sf.src, sf.path, tree=sf.tree))
    return out


_module_findings: dict = {}  # {module key: cached findings}


def check_module(module) -> List[Finding]:
    """Lint a module's source (compile_guard hook).  The LINT runs once
    per module per process, but the cached FINDINGS are re-enforced on
    every call — in strict mode a flagged module raises every time, so
    a retry can never slip a known-wedging kernel through to a hardware
    compile.  Strict is the default on real TPU (a hang costs the
    chip); FLASHINFER_TPU_WEDGE_LINT=warn/off downgrades."""
    key = getattr(module, "__name__", id(module))
    if key not in _module_findings:
        try:
            src = inspect.getsource(module)
            path = inspect.getsourcefile(module) or str(key)
        except (OSError, TypeError):
            _module_findings[key] = []
            return []
        _module_findings[key] = lint_source(src, path)
    findings = _module_findings[key]
    if not findings:
        return findings
    mode = os.environ.get("FLASHINFER_TPU_WEDGE_LINT", "")
    if not mode:
        from flashinfer_tpu.utils import is_tpu

        mode = "strict" if is_tpu() else "warn"
    if mode == "off":
        return findings
    msg = "wedge-lint findings (patterns that have wedged this chip):\n" \
        + "\n".join(f"  {f}" for f in findings)
    if mode == "strict":
        raise WedgeLintError(
            msg + "\nFix the pattern, or suppress a verified-safe line "
            "with '# wedge-lint: ok <reason>' "
            "(FLASHINFER_TPU_WEDGE_LINT=warn/off to downgrade)")
    import logging

    logging.getLogger("flashinfer_tpu").warning(msg)
    return findings


class WedgeLintError(RuntimeError):
    """A kernel source matches a known chip-wedging Mosaic pattern."""
