"""``python -m flashinfer_tpu.analysis`` — see package docstring."""

from flashinfer_tpu.analysis import main

if __name__ == "__main__":
    raise SystemExit(main())
