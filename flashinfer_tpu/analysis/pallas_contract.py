"""L007 — plan/kernel launch-contract parity for Pallas calls.

The plan/run split is the port's deepest contract: host planners emit
scalar arrays that a device kernel consumes POSITIONALLY.  Nothing at
runtime ties the three parties together — the kernel's parameter list,
the grid spec's operand counts, and the planner's emitted plan arrays —
so a skew fails late (Mosaic compile error on-chip, or silently wrong
scalars read from the wrong prefetch slot).  PR 3's own commit note is
the motivating incident: "fused_prefill plan arrays changed … 11
scalar-prefetch operands — hw tier tests updated" was enforced by
nothing.  Every piece is statically decidable from the AST:

1. **Kernel arity.**  When ``num_scalar_prefetch``, ``in_specs``,
   ``out_specs`` and ``scratch_shapes`` are all statically countable,
   the kernel's positional parameter count must equal their sum (vararg
   kernels are checked as: named positional params must not exceed it).
2. **Scalar-prefetch params.**  A vararg kernel of a prefetch launch
   names its scalar refs individually (the tree-wide idiom:
   ``def k(a_ref, b_ref, *refs, ...)``); the named-positional count
   must equal ``num_scalar_prefetch``.  This is what catches the
   "11 operands" skew without countable in_specs.
3. **index_map arity.**  A ``BlockSpec`` index_map lambda receives the
   grid indices (plus the scalar-prefetch refs under
   ``PrefetchScalarGridSpec``): a non-vararg lambda must take exactly
   ``grid_rank (+ num_scalar_prefetch)`` params, a vararg lambda at
   most that many named ones.
4. **Planner registry.**  ``PLANNER_KERNELS`` maps a host planner to
   the kernel consuming its plan (resolved through the project symbol
   index, so they may live in different modules).  At the launch the
   plan arrays are spelled ``plan["key"]`` positionally: their count
   must equal ``num_scalar_prefetch`` and every consumed key must be a
   key the planner actually emits.  Seeded with the fused-prefill pair
   (``build_prefill_work_units`` -> ``_fused_prefill_kernel``,
   ops/paged_prefill.py's 11 scalar-prefetch operands).

Unresolvable pieces (dynamic ``len(prefetch)``, conditionally-built
spec lists) are SKIPPED, never guessed — a contract pass that guesses
trains people to ignore it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from flashinfer_tpu.analysis.core import (Finding, FunctionInfo,
                                          PallasCallSite, Project,
                                          expr_basename)

CODE = "L007"

# planner -> kernel pairs whose plan-array contract L007 enforces
# end-to-end (check 4).  Extend when a new build_* planner feeds a
# kernel's scalar-prefetch operands.  Sibling registries, same
# no-silent-skip rule: vmem_budget.KNOB_LAUNCHES (the L009 VMEM proof
# per knob) and obs/costmodel.COST_LAUNCH_BINDINGS (the L016
# kernel-vs-formula parity scenario per priced launcher) — a launcher
# registered in none of the three is invisible to the analyzer, which
# L013/L017 exist to flag.
PLANNER_KERNELS: Dict[str, str] = {
    "build_prefill_work_units": "_fused_prefill_kernel",
    # the ingest-mode pair (ISSUE 14): build_prefill_ingest_units is
    # the explicit-dict re-emission of build_prefill_work_units(
    # fused_ingest=...) so its 14-key emission is statically decidable
    "build_prefill_ingest_units": "_fused_prefill_ingest_kernel",
    "build_decode_split_units": "_decode_split_kernel_fused_heads",
    # the serving engine's schedule lowering (serve/engine_kernels.py)
    # feeds BOTH kernels above through their own planners, so its
    # plan-array contract is enforced transitively by the two entries
    # that precede it; this entry records the binding (planner lookup
    # is by-kernel and first-match, so the direct planners keep owning
    # the key checks) — see the PR 4 NOTE: unregistered planners are
    # silently skipped.
    "build_engine_work_units": "_fused_prefill_kernel",
}


def _lambda_of(spec: ast.expr) -> Optional[ast.Lambda]:
    """The index_map lambda of a BlockSpec(...) expression, if any."""
    if not (isinstance(spec, ast.Call)
            and expr_basename(spec.func) == "BlockSpec"):
        return None
    cands = list(spec.args) + [k.value for k in spec.keywords
                               if k.arg == "index_map"]
    for c in cands:
        if isinstance(c, ast.Lambda):
            return c
    return None


def _check_index_maps(site: PallasCallSite,
                      findings: List[Finding]) -> None:
    if site.grid_rank is None:
        return
    expected = site.grid_rank
    if site.is_prefetch_spec:
        if site.num_scalar_prefetch is None:
            return
        expected += site.num_scalar_prefetch
    for group in (site.in_spec_exprs, site.out_spec_exprs):
        for spec in group or ():
            lam = _lambda_of(spec)
            if lam is None:
                continue
            named = len(lam.args.posonlyargs) + len(lam.args.args)
            vararg = lam.args.vararg is not None
            bad = (named > expected) if vararg else (named != expected)
            if bad:
                findings.append(Finding(
                    CODE, site.file.path, lam.lineno,
                    site.enclosing.name if site.enclosing else "<module>",
                    f"BlockSpec index_map takes {named} parameter(s) but "
                    f"the launch passes {expected} (grid rank "
                    f"{site.grid_rank}"
                    + (f" + {site.num_scalar_prefetch} scalar-prefetch "
                       f"refs" if site.is_prefetch_spec else "")
                    + ") — the map would be called with a mismatched "
                    "argument list at trace time"))


def _kernel_positional(site: PallasCallSite) -> Optional[int]:
    """Named positional parameter count of the resolved kernel, with
    partial-bound names excluded: keyword binds by name, and each
    POSITIONAL partial arg consumes one leading param."""
    k = site.kernel
    if k is None:
        return None
    named = len([p for p in k.positional_params
                 if p not in site.kernel_bound_kwargs])
    return max(0, named - site.kernel_bound_posargs)


def _check_kernel_arity(site: PallasCallSite,
                        findings: List[Finding]) -> None:
    named = _kernel_positional(site)
    if named is None or site.kernel is None:
        return
    func = site.enclosing.name if site.enclosing else "<module>"
    counts = (site.num_scalar_prefetch if site.is_prefetch_spec else 0,
              site.in_spec_exprs, site.out_spec_exprs,
              site.scratch_exprs)
    if all(c is not None for c in counts):
        expected = counts[0] + sum(len(c) for c in counts[1:])
        if site.kernel.has_vararg:
            if named > expected:
                findings.append(Finding(
                    CODE, site.file.path, site.line, func,
                    f"kernel '{site.kernel.name}' names {named} "
                    f"positional ref(s) before its vararg but the launch "
                    f"only passes {expected} "
                    "(num_scalar_prefetch + in_specs + out_specs + "
                    "scratch_shapes) — the extra refs would bind nothing"))
        elif named != expected:
            findings.append(Finding(
                CODE, site.file.path, site.line, func,
                f"kernel '{site.kernel.name}' takes {named} positional "
                f"ref(s) but the launch passes {expected} "
                f"(num_scalar_prefetch={counts[0]} + "
                f"{len(counts[1])} in_specs + {len(counts[2])} out_specs "
                f"+ {len(counts[3])} scratch_shapes) — Mosaic fails this "
                "at compile time on-chip; fix it at review time"))


def _leading_plan_keys(site: PallasCallSite) -> Optional[List[str]]:
    """The ``plan["key"]`` operands spelled before the first starred
    operand at the launch invocation; None when the invocation is
    absent or its leading operands are not plan subscripts."""
    inv = site.invocation
    if inv is None:
        return None
    keys: List[str] = []
    base: Optional[str] = None
    for a in inv.args:
        if isinstance(a, ast.Starred):
            break
        is_key = (isinstance(a, ast.Subscript)
                  and isinstance(a.value, ast.Name)
                  and isinstance(a.slice, ast.Constant)
                  and isinstance(a.slice.value, str))
        if is_key and (base is None or a.value.id == base):
            base = a.value.id
            keys.append(a.slice.value)
        elif is_key and keys:
            # a key drawn from a DIFFERENT dict: the scalar prefix may
            # span several plan dicts — not countable here, skip rather
            # than report a truncated count
            return None
        else:
            return keys if keys else None
    return keys if keys else None


def _planner_emitted_keys(planner: FunctionInfo) -> Optional[Set[str]]:
    """String keys the planner's plan dict carries: ``dict(...)``
    keyword names, ``{"k": ...}`` literal keys, and ``name["k"] = ...``
    subscript stores anywhere in its body."""
    keys: Set[str] = set()
    found = False
    for n in ast.walk(planner.node):
        if isinstance(n, ast.Call) and expr_basename(n.func) == "dict":
            kw = {k.arg for k in n.keywords if k.arg}
            if kw:
                keys |= kw
                found = True
        elif isinstance(n, ast.Dict):
            for k in n.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
                    found = True
        elif isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.slice, ast.Constant) \
                        and isinstance(t.slice.value, str):
                    keys.add(t.slice.value)
                    found = True
    return keys if found else None


def _check_planner_contract(project: Project, site: PallasCallSite,
                            findings: List[Finding]) -> None:
    if site.kernel is None or not site.is_prefetch_spec:
        return
    planner_name = next(
        (p for p, k in PLANNER_KERNELS.items()
         if k == site.kernel.name), None)
    if planner_name is None:
        return
    func = site.enclosing.name if site.enclosing else "<module>"
    nsp = site.num_scalar_prefetch
    # a REGISTERED kernel follows the named-scalar-refs convention:
    # every scalar-prefetch ref is a named positional param before the
    # vararg — so the named count must equal num_scalar_prefetch (this
    # is what catches a skewed num_scalar_prefetch= literal)
    named = _kernel_positional(site)
    if named is not None and nsp is not None \
            and site.kernel.has_vararg and named != nsp:
        findings.append(Finding(
            CODE, site.file.path, site.line, func,
            f"kernel '{site.kernel.name}' names {named} scalar-prefetch "
            f"ref(s) before its vararg but the launch sets "
            f"num_scalar_prefetch={nsp} — scalar refs bind positionally, "
            "so every ref after the skew reads the WRONG plan array "
            "(silently wrong indices, not an error)"))
    keys = _leading_plan_keys(site)
    if keys is not None and nsp is not None and len(keys) != nsp:
        findings.append(Finding(
            CODE, site.file.path,
            site.invocation.lineno if site.invocation else site.line,
            func,
            f"launch passes {len(keys)} plan array(s) "
            f"({', '.join(keys)}) but num_scalar_prefetch={nsp} — the "
            f"'{planner_name}' plan and the kernel would skew; every "
            "scalar ref after the mismatch reads the wrong operand"))
    planner = project.resolve_function(planner_name,
                                       prefer_file=site.file)
    if planner is None:
        # not statically decidable here: a subset/--changed-only run may
        # simply not include the planner's module (and resolve_function
        # also returns None on ambiguity) — skip, never guess.  A truly
        # stale registry entry is caught by the whole-tree fixture
        # regressions, which require the planner checks to fire.
        return
    emitted = _planner_emitted_keys(planner)
    if emitted is None or keys is None:
        return
    missing = [k for k in keys if k not in emitted]
    if missing:
        findings.append(Finding(
            CODE, site.file.path,
            site.invocation.lineno if site.invocation else site.line,
            func,
            f"launch consumes plan key(s) {missing} that planner "
            f"'{planner_name}' ({planner.file.basename}:"
            f"{planner.node.lineno}) never emits — the KeyError fires "
            "at the first run() after the next plan-schema change"))


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for site in project.pallas_sites:
        _check_kernel_arity(site, findings)
        _check_index_maps(site, findings)
        _check_planner_contract(project, site, findings)
    return findings
