"""flashinfer_tpu.analysis — multi-pass static analyzer for the port's
deepest contracts.

Grown from ``wedge_lint.py`` (which proved that an AST pass wired into
CI pays for itself — it encodes two real chip-wedge incidents), this
package generalizes the approach to the three bug classes every
round-5 advisor finding fell into:

====  =====================  ==========================================
pass  module                 bug class (motivating incident)
====  =====================  ==========================================
L001  alias_rebind           class-level ``forward = run`` aliases that
                             skip subclass overrides (the attention-sink
                             forward() wrong-numerics bug)
L002  signature_parity       positional-arg drift against the recorded
                             reference signatures (the ``window_left``
                             dtype-misbinding bug)
L003  jit_staticness         env/mutable-global reads pinned at
                             ``jax.jit`` trace time (the top-k backend
                             env-override pinning bug)
L004  wedge                  the original wedge lint (W000–W004), now a
                             pass behind this driver; ``wedge_lint.py``
                             remains as a compat shim
L005  obs_coverage           ``@flashinfer_api`` ops missing from the
                             obs metric catalog (public ops shipping
                             unobserved — ISSUE 2 satellite)
L006  tuning_schema          ``tuning_configs/*.json`` entries naming
                             knobs the autotuner never registered, or
                             values the registered KnobSpec rejects
                             (stale shipped tactics silently falling
                             back to defaults — ISSUE 3 satellite)
====  =====================  ==========================================

CLI::

    python -m flashinfer_tpu.analysis [paths...]
        [--baseline FILE | --no-baseline] [--write-baseline]
        [--bank FILE] [--dump-signatures]

With no paths, analyzes the installed ``flashinfer_tpu`` package tree.
Exit status is 1 iff findings exist that are not in the committed
baseline (``flashinfer_tpu/analysis/baseline.json``).  Suppress a
reviewed-safe line with ``# graft-lint: ok <reason>`` — reasonless
suppressions are themselves findings (L000).  See
docs/static_analysis.md for the pass catalog and workflows.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from flashinfer_tpu.analysis import (alias_rebind, jit_staticness,
                                     obs_coverage, signature_parity,
                                     tuning_schema, wedge)
from flashinfer_tpu.analysis.core import (Finding, Project,  # noqa: F401
                                          SourceFile, load_file,
                                          load_source, project_relpath)

__all__ = [
    "Finding", "Project", "analyze_paths", "analyze_project",
    "load_baseline", "partition_against_baseline", "main",
    "DEFAULT_BASELINE_PATH", "PASSES",
]

PASSES = (alias_rebind, signature_parity, jit_staticness, wedge,
          obs_coverage, tuning_schema)

DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def analyze_project(project: Project,
                    bank: Optional[dict] = None) -> List[Finding]:
    """Run every pass over `project`, apply graft-lint suppressions,
    and emit L000 for reasonless suppression comments."""
    raw: List[Finding] = []
    for sf in project.files:
        if sf.parse_finding is not None:
            raw.append(sf.parse_finding)
    for p in PASSES:
        if p is signature_parity:
            raw.extend(p.run(project, bank))
        else:
            raw.extend(p.run(project))

    by_path: Dict[str, SourceFile] = {sf.path: sf for sf in project.files}
    findings: List[Finding] = []
    for f in raw:
        sf = by_path.get(f.filename)
        if sf is not None and f.code != "W000" \
                and sf.suppression_for(f.line) is not None:
            continue  # suppressed (reasonless ones add L000 below)
        findings.append(f)
    # every reasonless graft suppression is a finding, whether or not
    # it shielded anything — an unreviewable waiver is always a bug.
    # (One finding per line: the wedge pass already reports W000 when
    # the bare suppression shields a W-code on that line.)
    w000_lines = {(f.filename, f.line) for f in findings
                  if f.code == "W000"}
    for sf in project.files:
        for line, reason in sorted(sf.suppressions.items()):
            if not reason and (sf.path, line) not in w000_lines:
                findings.append(Finding(
                    "L000", sf.path, line, "<suppression>",
                    "graft-lint suppression without a reason — state "
                    "why the flagged pattern is safe"))
        # wedge-spelled suppressions never waive L-codes, but a
        # reasonless one is still an unreviewable waiver: the wedge
        # pass only reports W000 when it SHIELDS a W-finding, so an
        # orphan bare '# wedge-lint: ok' would otherwise pass silently
        # and mute any future W-finding landing on its line
        for line, reason in sorted(sf.wedge_suppressions.items()):
            if not reason and (sf.path, line) not in w000_lines \
                    and line not in sf.suppressions:
                findings.append(Finding(
                    "W000", sf.path, line, "<suppression>",
                    "wedge-lint suppression without a reason — state "
                    "why the pattern is safe (it currently shields "
                    "nothing, but would silently waive the next "
                    "W-finding on this line)"))
    findings.sort(key=lambda f: (f.filename, f.line, f.code))
    return findings


def analyze_paths(paths: List[str],
                  bank: Optional[dict] = None) -> List[Finding]:
    return analyze_project(Project.from_paths(paths), bank)


# -- baseline ------------------------------------------------------------


def _baseline_key(f: Finding) -> Tuple[str, str, str]:
    return (f.code, project_relpath(f.filename), f.func)


def load_baseline(path: Optional[str] = None) -> Dict[Tuple, int]:
    """{(code, relpath, func): allowed count}; {} if the file is absent."""
    path = path or DEFAULT_BASELINE_PATH
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out: Dict[Tuple, int] = {}
    for e in data.get("findings", []):
        if e["code"] in _UNBASELINEABLE:
            continue  # hand-edited in: still never honored
        key = (e["code"], e["path"], e["func"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def partition_against_baseline(
        findings: List[Finding], baseline: Dict[Tuple, int]
) -> Tuple[List[Finding], List[Finding], List[Tuple]]:
    """-> (new findings, baselined findings, stale baseline keys).

    Keys are (code, path, func) with a count, NOT line numbers — the
    baseline survives unrelated edits above a finding, and a fixed
    instance surfaces as a stale entry to prune rather than silently
    freeing budget for a new bug of the same shape elsewhere."""
    budget = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        key = _baseline_key(f)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = [k for k, n in sorted(budget.items()) if n > 0]
    return new, old, stale


# findings that may NEVER be baselined: a reasonless suppression is by
# definition un-triageable — the whole point of L000/W000 is that it
# must be fixed (add the reason), not accepted
_UNBASELINEABLE = frozenset({"L000", "W000"})


def write_baseline(findings: List[Finding], path: str) -> None:
    skipped = [f for f in findings if f.code in _UNBASELINEABLE]
    if skipped:
        for f in skipped:
            print(f"refusing to baseline (fix the suppression reason "
                  f"instead): {f}")
        findings = [f for f in findings if f.code not in _UNBASELINEABLE]
    counts: Dict[Tuple, int] = {}
    lines: Dict[Tuple, List[int]] = {}
    for f in findings:
        key = _baseline_key(f)
        counts[key] = counts.get(key, 0) + 1
        lines.setdefault(key, []).append(f.line)
    entries = [
        {"code": code, "path": path, "func": func,
         "count": counts[(code, path, func)],
         "lines_at_capture": lines[(code, path, func)]}
        for code, path, func in sorted(counts)]
    with open(path, "w") as f:
        json.dump({
            "comment": (
                "Accepted pre-existing findings. Keyed by (code, path, "
                "func) + count; lines_at_capture is informational only. "
                "Regenerate with `python -m flashinfer_tpu.analysis "
                "--write-baseline` AFTER triaging that every new entry "
                "is a documented deviation, not a bug "
                "(docs/static_analysis.md)."),
            "findings": entries,
        }, f, indent=1, sort_keys=False)
        f.write("\n")


# -- CLI -----------------------------------------------------------------


def _default_paths() -> List[str]:
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def _dump_signatures(paths: List[str], bank: dict) -> None:
    project = Project.from_paths(paths)
    out = {}
    for sf in project.files:
        defs = signature_parity._qualname_defs(sf)
        for key, spec in bank.items():
            path, _, qualname = key.partition(":")
            if path != project_relpath(sf.path) or qualname not in defs:
                continue
            fn = defs[qualname]
            kwonly = [a.arg for a in fn.args.kwonlyargs]
            out[key] = {
                "reference_positional": spec["positional"],
                "implementation_positional":
                    signature_parity.positional_params(
                        fn, method="." in qualname),
                "implementation_kwonly": kwonly,
                "has_vararg": fn.args.vararg is not None,
            }
    print(json.dumps(out, indent=1))


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m flashinfer_tpu.analysis",
        description="multi-pass static analyzer (lifecycle aliases, "
                    "signature parity, jit staticness, wedge patterns)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to analyze (default: the "
                        "flashinfer_tpu package tree)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default {DEFAULT_BASELINE_PATH})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept current findings into the baseline file")
    p.add_argument("--bank", default=None,
                   help="signature bank for L002 (default: the "
                        "committed reference_signatures.json)")
    p.add_argument("--dump-signatures", action="store_true",
                   help="print current implementation signatures for "
                        "every bank symbol, then exit")
    args = p.parse_args(argv)

    paths = args.paths or _default_paths()
    bank = signature_parity.load_bank(args.bank)
    if args.dump_signatures:
        _dump_signatures(paths, bank)
        return 0

    findings = analyze_paths(paths, bank)
    baseline_path = args.baseline or DEFAULT_BASELINE_PATH

    if args.write_baseline:
        write_baseline(findings, baseline_path)
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    if args.no_baseline:
        new, old, stale = findings, [], []
    else:
        new, old, stale = partition_against_baseline(
            findings, load_baseline(baseline_path))
    for f in new:
        print(f)
    for key in stale:
        print(f"stale baseline entry (no longer fires — prune it): "
              f"{key[1]} [{key[0]}] {key[2]}")
    print(f"{len(findings)} finding(s): {len(new)} new, "
          f"{len(old)} baselined, {len(stale)} stale baseline entr(ies)")
    return 1 if new else 0
