"""flashinfer_tpu.analysis — multi-pass static analyzer for the port's
deepest contracts.

Grown from ``wedge_lint.py`` (which proved that an AST pass wired into
CI pays for itself — it encodes two real chip-wedge incidents), this
package generalizes the approach to the three bug classes every
round-5 advisor finding fell into:

====  =====================  ==========================================
pass  module                 bug class (motivating incident)
====  =====================  ==========================================
L001  alias_rebind           class-level ``forward = run`` aliases that
                             skip subclass overrides (the attention-sink
                             forward() wrong-numerics bug)
L002  signature_parity       positional-arg drift against the recorded
                             reference signatures (the ``window_left``
                             dtype-misbinding bug)
L003  jit_staticness         env/mutable-global reads pinned at
                             ``jax.jit`` trace time (the top-k backend
                             env-override pinning bug)
L004  wedge                  the original wedge lint (W000–W004), now a
                             pass behind this driver; ``wedge_lint.py``
                             remains as a compat shim
L005  obs_coverage           ``@flashinfer_api`` ops missing from the
                             obs metric catalog (public ops shipping
                             unobserved — ISSUE 2 satellite)
L006  tuning_schema          ``tuning_configs/*.json`` entries naming
                             knobs the autotuner never registered, or
                             values the registered KnobSpec rejects
                             (stale shipped tactics silently falling
                             back to defaults — ISSUE 3 satellite)
L007  pallas_contract        plan/kernel launch-contract skew: kernel
                             arity vs specs, index_map arity vs grid
                             rank, and num_scalar_prefetch vs the plan
                             arrays the registered planner emits
                             (PR 3's "11 scalar-prefetch operands"
                             contract, previously enforced by nothing)
L008  tracer_leak            Python if/while/assert, int()/bool()/
                             float()/.item(), and np.* applied to
                             traced values inside jit bodies and
                             Pallas kernels
L009  vmem_budget            tuning-config block shapes whose scratch/
                             block VMEM provably exceeds the launch's
                             budget (extends L006 with the semantics)
L010  kernel_init_guard      accumulator refs written only under
                             first-step-EXCLUDING pl.when guards (no
                             step-0 init: stale-scratch numerics), and
                             out-of-range input_output_aliases
L011  donation_lifetime      donated-buffer lifetime violations at the
                             compile-once serving steps: use-after-
                             donate, donated args the jitted body also
                             closes over, and the both-or-neither
                             in/out-shardings contract (ISSUE 15)
L012  static_flow            per-step schedule values flowing into
                             compile-once statics (frozen plan fields,
                             plan-shape planner kwargs, jit
                             static_argnums/static_argnames,
                             trace-keying branches) —
                             the static complement of the PR 10
                             retrace-cause attribution
L013  registry_coverage      registry completeness: every KNOWN_KNOBS
                             knob bound in KNOB_LAUNCHES or explicitly
                             waived, every plan-consuming kernel's
                             planner in PLANNER_KERNELS, and the obs
                             span/cost-family catalogs complete (the
                             one implementation ``obs doctor``
                             delegates to)
L014  dma_race               DMA/semaphore happens-before inside kernel
                             bodies: read-before-wait, slot overwrite
                             while a copy may be in flight, start/wait
                             imbalance along any guard path (the
                             BENCH_r04/r05 wedge shape), and
                             cross-grid-iteration carry hazards
L015  mosaic_lowering        interpret-proven-only constructs in kernel
                             bodies: non-128-aligned or strided lane
                             (last-axis) slices, in-kernel cast-to-
                             match/gather — waived in place or triaged
                             into the baseline's ``mosaic_risks``
                             hardware bring-up checklist
L016  cost_parity            kernel-vs-costmodel physics parity: the
                             L014 symbolic walk re-run in cost mode
                             accumulates DMA bytes + MXU FLOPs per
                             grid step and must agree with the
                             registered cost family under each
                             COST_LAUNCH_BINDINGS scenario — proved
                             drift is fixed, never baselined
L017  chooser_coverage       priced-choice coverage: every chooser
                             prunes through the L009 VMEM evaluator
                             (structurally + wired at a call site),
                             every KNOWN_KNOBS surface priced or
                             reasonably waived, every parity binding's
                             family/adapter intact
====  =====================  ==========================================

L007–L017 are interprocedural: they resolve planners/kernels through
the project symbol index in ``core.py``, so the planner in one module
and the kernel in another are checked as one contract.

CLI::

    python -m flashinfer_tpu.analysis [paths...]
        [--baseline FILE | --no-baseline] [--write-baseline]
        [--bank FILE] [--dump-signatures]
        [--sarif FILE] [--changed-only] [--changed-base REF]

With no paths, analyzes the installed ``flashinfer_tpu`` package tree.
Exit status is 1 iff findings exist that are not in the committed
baseline (``flashinfer_tpu/analysis/baseline.json``).  ``--sarif``
additionally writes the non-baselined findings as SARIF 2.1.0 (GitHub
code-scanning).  ``--changed-only`` restricts analysis to files the
git working tree changed against ``--changed-base`` (default HEAD) —
the incremental pre-commit mode.  Suppress a reviewed-safe line with
``# graft-lint: ok <reason>`` — reasonless suppressions are themselves
findings (L000).  See docs/static_analysis.md for the pass catalog and
workflows.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Set, Tuple

from flashinfer_tpu.analysis import (alias_rebind, chooser_coverage,
                                     cost_parity, dma_race,
                                     donation_lifetime, jit_staticness,
                                     kernel_init_guard, mosaic_lowering,
                                     obs_coverage, pallas_contract,
                                     registry_coverage, signature_parity,
                                     static_flow, tracer_leak,
                                     tuning_schema, vmem_budget, wedge)
from flashinfer_tpu.analysis import sarif as sarif_mod
from flashinfer_tpu.analysis.core import (Finding, Project,  # noqa: F401
                                          SourceFile, iter_python_files,
                                          load_file, load_source,
                                          project_relpath)

__all__ = [
    "Finding", "Project", "analyze_paths", "analyze_project",
    "load_baseline", "partition_against_baseline", "main",
    "DEFAULT_BASELINE_PATH", "PASSES",
]

PASSES = (alias_rebind, signature_parity, jit_staticness, wedge,
          obs_coverage, tuning_schema, pallas_contract, tracer_leak,
          vmem_budget, kernel_init_guard, donation_lifetime,
          static_flow, registry_coverage, dma_race, mosaic_lowering,
          cost_parity, chooser_coverage)

DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def analyze_project(project: Project,
                    bank: Optional[dict] = None) -> List[Finding]:
    """Run every pass over `project`, apply graft-lint suppressions,
    and emit L000 for reasonless suppression comments."""
    raw: List[Finding] = []
    for sf in project.files:
        if sf.parse_finding is not None:
            raw.append(sf.parse_finding)
    for p in PASSES:
        if p is signature_parity:
            raw.extend(p.run(project, bank))
        else:
            raw.extend(p.run(project))

    by_path: Dict[str, SourceFile] = {sf.path: sf for sf in project.files}
    findings: List[Finding] = []
    for f in raw:
        sf = by_path.get(f.filename)
        if sf is not None and f.code != "W000" \
                and sf.suppression_for(f.line) is not None:
            continue  # suppressed (reasonless ones add L000 below)
        findings.append(f)
    # every reasonless graft suppression is a finding, whether or not
    # it shielded anything — an unreviewable waiver is always a bug.
    # (One finding per line: the wedge pass already reports W000 when
    # the bare suppression shields a W-code on that line.)
    w000_lines = {(f.filename, f.line) for f in findings
                  if f.code == "W000"}
    for sf in project.files:
        for line, reason in sorted(sf.suppressions.items()):
            if not reason and (sf.path, line) not in w000_lines:
                findings.append(Finding(
                    "L000", sf.path, line, "<suppression>",
                    "graft-lint suppression without a reason — state "
                    "why the flagged pattern is safe"))
        # wedge-spelled suppressions never waive L-codes, but a
        # reasonless one is still an unreviewable waiver: the wedge
        # pass only reports W000 when it SHIELDS a W-finding, so an
        # orphan bare '# wedge-lint: ok' would otherwise pass silently
        # and mute any future W-finding landing on its line
        for line, reason in sorted(sf.wedge_suppressions.items()):
            if not reason and (sf.path, line) not in w000_lines \
                    and line not in sf.suppressions:
                findings.append(Finding(
                    "W000", sf.path, line, "<suppression>",
                    "wedge-lint suppression without a reason — state "
                    "why the pattern is safe (it currently shields "
                    "nothing, but would silently waive the next "
                    "W-finding on this line)"))
    findings.sort(key=lambda f: (f.filename, f.line, f.code))
    return findings


def analyze_paths(paths: List[str],
                  bank: Optional[dict] = None) -> List[Finding]:
    return analyze_project(Project.from_paths(paths), bank)


# -- baseline ------------------------------------------------------------


def _baseline_key(f: Finding) -> Tuple[str, str, str]:
    return (f.code, project_relpath(f.filename), f.func)


def load_baseline(path: Optional[str] = None) -> Dict[Tuple, int]:
    """{(code, relpath, func): allowed count}; {} if the file is absent."""
    path = path or DEFAULT_BASELINE_PATH
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out: Dict[Tuple, int] = {}
    for e in data.get("findings", []):
        if e["code"] in _UNBASELINEABLE:
            continue  # hand-edited in: still never honored
        key = (e["code"], e["path"], e["func"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    # triaged Mosaic-lowering risks live in their own machine-readable
    # section (the hardware bring-up checklist) but budget exactly like
    # ordinary baselined findings — one L015 per counted instance
    for e in data.get("mosaic_risks", []):
        key = ("L015", e["path"], e["func"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def partition_against_baseline(
        findings: List[Finding], baseline: Dict[Tuple, int]
) -> Tuple[List[Finding], List[Finding], List[Tuple]]:
    """-> (new findings, baselined findings, stale baseline keys).

    Keys are (code, path, func) with a count, NOT line numbers — the
    baseline survives unrelated edits above a finding, and a fixed
    instance surfaces as a stale entry to prune rather than silently
    freeing budget for a new bug of the same shape elsewhere."""
    budget = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        key = _baseline_key(f)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = [k for k, n in sorted(budget.items()) if n > 0]
    return new, old, stale


# findings that may NEVER be baselined: a reasonless suppression is by
# definition un-triageable — the whole point of L000/W000 is that it
# must be fixed (add the reason), not accepted.  L016 joins them: a
# machine-proved kernel-vs-costmodel divergence means either the
# kernel's traffic changed without the formula or the formula drifted
# from the kernel; one of the two is wrong TODAY, and a baselined
# wrong cost model silently mis-prices every chooser race.
_UNBASELINEABLE = frozenset({"L000", "W000", "L016"})


def _l015_rule(f: Finding) -> str:
    """'[lane-slice] ...' -> 'lane-slice' (the mosaic_lowering tag)."""
    if f.message.startswith("["):
        return f.message[1:].split("]", 1)[0]
    return "unknown"


def write_baseline(findings: List[Finding], path: str) -> None:
    skipped = [f for f in findings if f.code in _UNBASELINEABLE]
    if skipped:
        for f in skipped:
            print(f"refusing to baseline (fix the suppression reason "
                  f"instead): {f}")
        findings = [f for f in findings if f.code not in _UNBASELINEABLE]
    # L015 findings route to the mosaic_risks section: same budget
    # semantics, but keyed one level finer ((path, func, rule)) and
    # carrying a human triage note that regeneration must preserve —
    # the note IS the hardware bring-up checklist entry
    notes: Dict[Tuple, str] = {}
    if os.path.exists(path):
        try:
            with open(path) as fh:
                prev = json.load(fh)
            for e in prev.get("mosaic_risks", []):
                notes[(e["path"], e["func"], e.get("rule", "unknown"))] \
                    = e.get("note", "")
        except (OSError, ValueError, KeyError):
            pass
    risks = [f for f in findings if f.code == "L015"]
    findings = [f for f in findings if f.code != "L015"]
    counts: Dict[Tuple, int] = {}
    lines: Dict[Tuple, List[int]] = {}
    for f in findings:
        key = _baseline_key(f)
        counts[key] = counts.get(key, 0) + 1
        lines.setdefault(key, []).append(f.line)
    entries = [
        {"code": code, "path": path_, "func": func,
         "count": counts[(code, path_, func)],
         "lines_at_capture": lines[(code, path_, func)]}
        for code, path_, func in sorted(counts)]
    rcounts: Dict[Tuple, int] = {}
    rlines: Dict[Tuple, List[int]] = {}
    for f in risks:
        key = (project_relpath(f.filename), f.func, _l015_rule(f))
        rcounts[key] = rcounts.get(key, 0) + 1
        rlines.setdefault(key, []).append(f.line)
    risk_entries = [
        {"rule": rule, "path": rpath, "func": func,
         "count": rcounts[(rpath, func, rule)],
         "lines_at_capture": sorted(rlines[(rpath, func, rule)]),
         "note": notes.get((rpath, func, rule), "TRIAGE PENDING")}
        for rpath, func, rule in sorted(rcounts)]
    with open(path, "w") as f:
        json.dump({
            "comment": (
                "Accepted pre-existing findings. Keyed by (code, path, "
                "func) + count; lines_at_capture is informational only. "
                "Regenerate with `python -m flashinfer_tpu.analysis "
                "--write-baseline` AFTER triaging that every new entry "
                "is a documented deviation, not a bug "
                "(docs/static_analysis.md).  mosaic_risks is the L015 "
                "section: the machine-readable hardware bring-up "
                "checklist — every entry's note must say what on-chip "
                "proof retires it; notes survive regeneration."),
            "findings": entries,
            "mosaic_risks": risk_entries,
        }, f, indent=1, sort_keys=False)
        f.write("\n")


# -- CLI -----------------------------------------------------------------


def _mosaic_risk_props(project: Project) -> List[dict]:
    """Current whole-tree L015 findings serialized for the SARIF run
    property (suppression-filtered like the driver, baseline NOT
    applied — triaged risks stay on the checklist by design)."""
    by_path = {sf.path: sf for sf in project.files}
    out = []
    for f in mosaic_lowering.run(project):
        sf = by_path.get(f.filename)
        if sf is not None and sf.suppression_for(f.line) is not None:
            continue
        out.append({"rule": _l015_rule(f),
                    "path": project_relpath(f.filename),
                    "line": f.line, "func": f.func,
                    "message": f.message})
    return out


def _default_paths() -> List[str]:
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


_iter_python_files = iter_python_files


def _git_changed_files(paths: List[str],
                       base: str) -> Optional[Set[str]]:
    """Absolute paths of files changed vs `base` (plus untracked) in
    every git repo owning one of `paths`; None when git is unusable —
    the caller falls back to full analysis with a warning rather than
    silently passing a broken tree."""
    roots: Set[str] = set()
    for p in paths:
        d = p if os.path.isdir(p) else os.path.dirname(os.path.abspath(p))
        try:
            top = subprocess.run(
                ["git", "-C", d, "rev-parse", "--show-toplevel"],
                capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        if top.returncode != 0:
            return None
        roots.add(top.stdout.strip())
    changed: Set[str] = set()
    for root in sorted(roots):
        try:
            # quotepath off: otherwise non-ASCII names print octal-
            # escaped and quoted, match nothing, and silently drop out
            diff = subprocess.run(
                ["git", "-C", root, "-c", "core.quotepath=false",
                 "diff", "--name-only", base, "--"],
                capture_output=True, text=True, timeout=30)
            untracked = subprocess.run(
                ["git", "-C", root, "-c", "core.quotepath=false",
                 "ls-files", "--others", "--exclude-standard"],
                capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        if diff.returncode != 0 or untracked.returncode != 0:
            return None
        for line in (diff.stdout + untracked.stdout).splitlines():
            if line.strip():
                # realpath: git reports the PHYSICAL toplevel, while the
                # analyzed paths may reach the repo through a symlink —
                # matching abspaths would silently intersect to nothing
                changed.add(os.path.realpath(
                    os.path.join(root, line.strip())))
    return changed


def _dump_signatures(paths: List[str], bank: dict) -> None:
    project = Project.from_paths(paths)
    out = {}
    for sf in project.files:
        defs = signature_parity._qualname_defs(sf)
        for key, spec in bank.items():
            path, _, qualname = key.partition(":")
            if path != project_relpath(sf.path) or qualname not in defs:
                continue
            fn = defs[qualname]
            kwonly = [a.arg for a in fn.args.kwonlyargs]
            out[key] = {
                "reference_positional": spec["positional"],
                "implementation_positional":
                    signature_parity.positional_params(
                        fn, method="." in qualname),
                "implementation_kwonly": kwonly,
                "has_vararg": fn.args.vararg is not None,
            }
    print(json.dumps(out, indent=1))


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m flashinfer_tpu.analysis",
        description="multi-pass static analyzer (lifecycle aliases, "
                    "signature parity, jit staticness, wedge patterns)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to analyze (default: the "
                        "flashinfer_tpu package tree)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default {DEFAULT_BASELINE_PATH})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept current findings into the baseline file")
    p.add_argument("--bank", default=None,
                   help="signature bank for L002 (default: the "
                        "committed reference_signatures.json)")
    p.add_argument("--dump-signatures", action="store_true",
                   help="print current implementation signatures for "
                        "every bank symbol, then exit")
    p.add_argument("--sarif", metavar="FILE", default=None,
                   help="also write the non-baselined findings as "
                        "SARIF 2.1.0 (GitHub code scanning)")
    p.add_argument("--changed-only", action="store_true",
                   help="analyze only files the git working tree "
                        "changed (incremental pre-commit mode)")
    p.add_argument("--changed-base", metavar="REF", default="HEAD",
                   help="git ref --changed-only diffs against "
                        "(default HEAD)")
    args = p.parse_args(argv)

    paths = args.paths or _default_paths()
    bank = signature_parity.load_bank(args.bank)
    if args.dump_signatures:
        _dump_signatures(paths, bank)
        return 0

    files = _iter_python_files(paths)
    if args.changed_only:
        changed = _git_changed_files(paths, args.changed_base)
        if changed is None:
            print("--changed-only: git unavailable for the analyzed "
                  "paths; falling back to full analysis",
                  file=sys.stderr)
        elif any(p.endswith(".json")
                 and os.path.basename(os.path.dirname(p))
                 == "tuning_configs" for p in changed):
            # a config-only diff has no changed .py file to anchor the
            # subset, but L006/L009 exist to lint exactly these JSONs —
            # run the full analysis so the edit is actually checked
            print("--changed-only: tuning_configs/*.json changed; "
                  "running full analysis (L006/L009 need the launch "
                  "modules)", file=sys.stderr)
        else:
            files = [f for f in files
                     if os.path.realpath(f) in changed]
            if not files:
                print("--changed-only: no analyzed files changed vs "
                      f"{args.changed_base}")
                if args.sarif:
                    # no changed files ≠ no current risks: the
                    # mosaic_risks checklist is a whole-tree property,
                    # so recompute it rather than emit an empty bag
                    risks = _mosaic_risk_props(
                        Project.from_paths(_default_paths()))
                    with open(args.sarif, "w") as fh:
                        json.dump(sarif_mod.to_sarif([], risks),
                                  fh, indent=1)
                return 0
    project = Project.from_paths(files)
    findings = analyze_project(project, bank)
    baseline_path = args.baseline or DEFAULT_BASELINE_PATH

    # interprocedural passes see less on a partial tree, so whole-tree
    # claims (baseline rewrites, stale-entry pruning) need the full
    # default file set analyzed.  Config JSONs discovered next to
    # analyzed modules count as analyzed (L006/L009).
    analyzed = {project_relpath(sf.path) for sf in project.files}
    analyzed |= {project_relpath(p)
                 for p in tuning_schema._config_paths(project)}
    saw_whole_tree = {project_relpath(f)
                      for f in _iter_python_files(_default_paths())
                      } <= analyzed

    if args.write_baseline:
        if not saw_whole_tree:
            print("--write-baseline requires a whole-tree run: a "
                  "subset (explicit paths or --changed-only) misses "
                  "cross-module findings and would truncate the "
                  "baseline", file=sys.stderr)
            return 2
        write_baseline(findings, baseline_path)
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    if args.no_baseline:
        new, old, stale = findings, [], []
    else:
        new, old, stale = partition_against_baseline(
            findings, load_baseline(baseline_path))
        # "no longer fires" is likewise only decidable when the run saw
        # the WHOLE tree (an L003 on norm.py fires through callees in
        # other modules) — a subset run re-checking a file with less
        # context must not demand pruning its entries.  A whole-tree
        # run keeps every stale key: one naming a no-longer-analyzed
        # path is the deleted/renamed-file case, exactly what needs
        # pruning.
        if not saw_whole_tree:
            stale = []
    if args.sarif:
        # run property = EVERY current L015 finding (baselined/triaged
        # included), not just the new ones in "results" — the hardware
        # session reads the full checklist from one artifact.  On a
        # subset run the subset's findings are all we saw; fall back to
        # a whole-tree scan only when the subset saw no kernels at all.
        risks = [{"rule": _l015_rule(f),
                  "path": project_relpath(f.filename),
                  "line": f.line, "func": f.func,
                  "message": f.message}
                 for f in findings if f.code == "L015"]
        if not saw_whole_tree and not risks:
            risks = _mosaic_risk_props(
                Project.from_paths(_default_paths()))
        with open(args.sarif, "w") as fh:
            json.dump(sarif_mod.to_sarif(new, risks), fh, indent=1)
            fh.write("\n")
        print(f"# sarif ({len(new)} result(s)) -> {args.sarif}",
              file=sys.stderr)
    for f in new:
        print(f)
    for key in stale:
        print(f"stale baseline entry (no longer fires — prune it): "
              f"{key[1]} [{key[0]}] {key[2]}")
    print(f"{len(findings)} finding(s): {len(new)} new, "
          f"{len(old)} baselined, {len(stale)} stale baseline entr(ies)")
    return 1 if new else 0
