"""L006: every ``tuning_configs/*.json`` entry must name a knob the
autotuner actually registers.

The shipped per-generation tactic tables (``flashinfer_tpu/
tuning_configs/v5e.json`` etc.) are string-keyed: ``"op.knob|shape"``.
Nothing ties those strings to the ``choose_one``/``lookup`` call sites —
a renamed knob, a typo'd key, or a malformed value silently orphans the
entry and the kernel quietly falls back to defaults (the stale-config
bug class ISSUE 3's autotune satellite names).  This pass closes the
loop: every key's op name must exist in
``flashinfer_tpu.autotuner.KNOWN_KNOBS`` and every value must satisfy
the registered :class:`~flashinfer_tpu.autotuner.KnobSpec` (arity for
block tuples, enum membership for string knobs).

Config discovery is project-relative: any ``tuning_configs`` directory
that sits next to an analyzed ``.py`` file is scanned, so synthetic
projects in tests see only the configs they stage.  Findings carry the
JSON file as the filename and the offending key's line; ``func`` is the
key itself so baselining stays per-entry.

Validated shapes per file: the flat top-level ``"tactics"`` table plus
every named section carrying its own ``"tactics"`` (the schema
``autotuner._flatten_config`` consumes).  An unparseable config file is
itself a finding — the runtime loader swallows it silently by design,
which is exactly when lint must speak up.

Provenance (ROADMAP item 5, ISSUE 15 satellite): every named section
must label where its entries came from — ``"provenance"`` in
:data:`VALID_PROVENANCE` (``seed`` = derived off-chip, ``measured`` =
banked on-chip winners, ``model-derived`` = cost-model-chosen).  The
shipped pre-provenance sections carry the legacy ``"seed": true`` flag
and are grandfathered (only the affirmative ``true`` counts — a
``"seed": false`` section has disclaimed the label);
NEW sections with neither are findings — an unlabeled tactic table
can't be audited against the 0.35x/1.05x poison rules or graduated by
the hardware session.

Graduation references (ISSUE 20): a ``"measured"`` section must carry
``journal_id`` (the ``obs bringup`` session that produced it) and
``banked_row`` (RowAuditor stamp(s) of the BENCH_BANKED.md rows that
measured it) — the rewrite ``obs bringup --graduate`` emits both, and
requiring them here makes a hand-edited seed→measured flip a lint
failure instead of an unfalsifiable claim.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

from flashinfer_tpu.analysis.core import Finding, Project

CODE = "L006"

# section provenance labels (ROADMAP item 5): where a tactic table's
# values came from, so the perf gates know what they may trust
VALID_PROVENANCE = ("seed", "measured", "model-derived")


def _config_paths(project: Project) -> List[str]:
    dirs = []
    seen = set()
    for sf in project.files:
        d = os.path.join(os.path.dirname(os.path.abspath(sf.path)),
                         "tuning_configs")
        if d not in seen:
            seen.add(d)
            if os.path.isdir(d):
                dirs.append(d)
    paths = []
    for d in sorted(dirs):
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".json"):
                paths.append(os.path.join(d, fn))
    return paths


def _key_line(src: str, key: str) -> int:
    """Line of the key's first occurrence (1-based; 1 if not found)."""
    needle = json.dumps(key)
    for i, line in enumerate(src.splitlines(), 1):
        if needle in line:
            return i
    return 1


def _tables(data: dict) -> Dict[str, dict]:
    """{section label: tactics table} in the loader's merge order."""
    out = {"tactics": data.get("tactics", {})}
    for key, sec in sorted(data.items()):
        if key != "tactics" and isinstance(sec, dict) \
                and isinstance(sec.get("tactics"), dict):
            out[key] = sec["tactics"]
    return out


def run(project: Project) -> List[Finding]:
    from flashinfer_tpu.autotuner import validate_tactic

    findings: List[Finding] = []
    for path in _config_paths(project):
        try:
            with open(path) as fh:
                src = fh.read()
            data = json.loads(src)
        except (OSError, json.JSONDecodeError) as e:
            findings.append(Finding(
                CODE, path, getattr(e, "lineno", 1) or 1, "<config>",
                f"unreadable tuning config: {e} — the runtime loader "
                "ignores broken files silently, so every shipped tactic "
                "in it is dead"))
            continue
        if not isinstance(data, dict):
            findings.append(Finding(
                CODE, path, 1, "<config>",
                "tuning config root must be a JSON object with a "
                "'tactics' table"))
            continue
        # section-level checks run on EVERY named dict section — not
        # just the ones _tables() admits — so a malformed tactics table
        # cannot shield a section from the provenance gate (the loader
        # drops such sections silently, which is exactly when lint must
        # speak up)
        for section in sorted(data):
            sec = data[section]
            if section == "tactics" or not isinstance(sec, dict):
                continue
            if not isinstance(sec.get("tactics"), dict):
                findings.append(Finding(
                    CODE, path, _key_line(src, section), section,
                    f"section {section!r} has no 'tactics' object — "
                    "the runtime loader drops the whole section "
                    "silently, so every entry in it is dead"))
            prov = sec.get("provenance")
            # only an affirmative `"seed": true` grandfathers — a
            # section declaring `"seed": false` has disclaimed the
            # legacy label and must carry real provenance
            legacy_seed = sec.get("seed") is True
            if prov is not None and prov not in VALID_PROVENANCE:
                findings.append(Finding(
                    CODE, path, _key_line(src, section), section,
                    f"section provenance {prov!r} is not one of "
                    f"{list(VALID_PROVENANCE)} — the perf gates "
                    "cannot classify what these tactics may be "
                    "trusted for"))
            elif prov is None and not legacy_seed:
                findings.append(Finding(
                    CODE, path, _key_line(src, section), section,
                    f"section {section!r} carries no provenance "
                    "label: add \"provenance\": "
                    "\"seed\"|\"measured\"|\"model-derived\" "
                    "(the shipped pre-provenance sections are "
                    "grandfathered via their \"seed\": true "
                    "flag) — unlabeled tactics cannot be audited "
                    "or graduated (ROADMAP item 5)"))
            # a "measured" claim must be auditable (ISSUE 20): the
            # graduation rewrite carries the session journal id and the
            # RowAuditor stamps of the banked rows that measured it —
            # a hand-edited flip without them is unfalsifiable
            if prov == "measured":
                jid = sec.get("journal_id")
                if not (isinstance(jid, str) and jid):
                    findings.append(Finding(
                        CODE, path, _key_line(src, section), section,
                        f"measured section {section!r} carries no "
                        "journal_id reference — a \"measured\" claim "
                        "must join to the bring-up session journal "
                        "that produced it (run `obs bringup "
                        "--graduate`, don't hand-edit provenance)"))
                br = sec.get("banked_row")
                ok_refs = (isinstance(br, str) and br) or (
                    isinstance(br, list) and br
                    and all(isinstance(r, str) and r for r in br))
                if not ok_refs:
                    findings.append(Finding(
                        CODE, path, _key_line(src, section), section,
                        f"measured section {section!r} carries no "
                        "banked_row reference(s) — a \"measured\" "
                        "claim must join to BENCH_BANKED.md rows by "
                        "their RowAuditor stamp (bench_audit."
                        "row_stamp)"))
        for section, table in _tables(data).items():
            if not isinstance(table, dict):
                findings.append(Finding(
                    CODE, path, _key_line(src, section), section,
                    "'tactics' must be a string-keyed object"))
                continue
            for key, value in table.items():
                op_name, sep, shape = key.partition("|")
                if not sep or not shape:
                    findings.append(Finding(
                        CODE, path, _key_line(src, key), key,
                        "tactic keys are 'op.knob|shape_key' — this one "
                        "has no shape part and can never be looked up"))
                    continue
                err = validate_tactic(op_name, value)
                if err is not None:
                    findings.append(Finding(
                        CODE, path, _key_line(src, key), key,
                        f"stale/invalid tuning entry in section "
                        f"{section!r}: {err} — the autotuner drops it at "
                        "load time and the kernel silently runs "
                        "defaults"))
    return findings
