"""L001 — stale class-level method aliases across inheritance.

A class-level alias like ``forward = run`` binds whatever ``run`` IS at
class-definition time.  A subclass that redefines ``run`` but inherits
the alias gets a ``forward()`` that silently calls the BASE class's
``run`` — skipping the subclass's epilogue with no error.  This is the
BatchAttentionWithAttentionSinkWrapper bug (ADVICE.md round 5, item 1):
``forward()`` skipped the sink epilogue and produced wrong numerics.
``sparse.py``'s VariableBlockSparseAttentionWrapper shows the fix
pattern — rebind ``forward = run`` after the subclass ``def run``.

Flagged shapes (for every alias ``A = T`` where ``T`` is defined as a
method somewhere in the base chain):

- a class redefines ``T`` but inherits ``A = T`` from an ancestor
  without rebinding it after its own ``def T``;
- a class binds ``A = T`` BEFORE its own ``def T`` in the same body
  (the alias captures the inherited ``T``, not the one defined below);
- a class inherits both a redefined ``T`` and an alias bound ABOVE the
  redefinition (the "inheriting a redefined run" case — its
  ``forward`` skips the override it actually inherits).

Fix: rebind ``A = T`` after the most-derived ``def T``, or replace the
alias with a ``def A`` that dispatches through ``self.T``.
"""

from __future__ import annotations

from typing import List

from flashinfer_tpu.analysis.core import ClassInfo, Finding, Project

CODE = "L001"


def _chain_pos(chain: List[ClassInfo], info: ClassInfo) -> int:
    for i, c in enumerate(chain):
        if c is info:
            return i
    return -1


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for name in sorted(project.class_index):
        for cls in project.class_index[name]:
            findings.extend(_check_class(project, cls))
    return findings


def _check_class(project: Project, cls: ClassInfo) -> List[Finding]:
    chain = project.mro_chain(cls)
    out: List[Finding] = []
    # every alias name visible on `cls`, resolved to its nearest binder
    seen_aliases = set()
    for c in chain:
        for alias in c.alias_binds:
            if alias in seen_aliases:
                continue
            seen_aliases.add(alias)
            binder = c
            target, bind_idx, bind_line = binder.alias_binds[alias]
            # nearest class in the chain that defines the target method
            definer = next(
                (d for d in chain if target in d.method_defs), None)
            if definer is None:
                continue  # not a method alias (constant, re-export, ...)
            def_idx, def_line = definer.method_defs[target]
            binder_pos = _chain_pos(chain, binder)
            definer_pos = _chain_pos(chain, definer)
            if definer_pos < binder_pos:
                # the method override is MORE derived than the alias
                # binding: alias dispatches to the stale base method
                if definer is cls:
                    line, func = def_line, f"{cls.name}.{target}"
                else:
                    line, func = cls.node.lineno, cls.name
                out.append(Finding(
                    CODE, cls.file.path, line, func,
                    f"class-level alias '{alias} = {target}' inherited "
                    f"from {binder.name} (line {bind_line}) was bound at "
                    f"class-definition time and skips the '{target}' "
                    f"override defined in {definer.name} (line {def_line})"
                    f" — {cls.name}.{alias}() silently calls the base "
                    f"'{target}'. Rebind '{alias} = {target}' after the "
                    f"override (sparse.py VariableBlockSparse pattern) or "
                    f"make '{alias}' a def dispatching via self.{target}"))
            elif definer is binder and binder is cls \
                    and bind_idx < def_idx:
                # same class, alias textually above the def: it captured
                # the inherited/previous target, not the one below
                out.append(Finding(
                    CODE, cls.file.path, bind_line,
                    f"{binder.name}.{alias}",
                    f"'{alias} = {target}' appears ABOVE 'def {target}' "
                    f"(line {def_line}) in the same class body — the "
                    f"alias captured the inherited '{target}', not the "
                    f"definition below it. Move the alias after the def"))
    return out
