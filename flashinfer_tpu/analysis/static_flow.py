"""L012 — per-step-varying values flowing into compile-once statics.

The serving engine's compile-once ladder (PR 11/12) rests on ONE rule:
the per-step schedule rides as ARGUMENTS, never as statics.  A schedule
value that reaches a trace-keying static — a frozen ``_*Plan``/``*Geom``
dataclass field, a planner kwarg that sizes the plan arrays, a jit
``static_argnums`` slot, or a Python branch inside a jitted body —
recompiles the step every time the value moves: the ≤9-trace budget
silently becomes one trace per step.  PR 10's flight recorder attributes
that failure at RUNTIME (retrace-cause diffs); this pass is the static
complement — the same bug class caught at review time, before a serving
host ever pays the compile.

Taint model (function-local, resolution via ``core.py``):

- **Sources** are seeded in :data:`SCHEDULE_SOURCES` (per-step-varying
  parameters of registered schedule-lowering functions, keyed by
  qualname — mirroring ``pallas_contract.PLANNER_KERNELS``) and
  :data:`SCHEDULE_SOURCE_CALLS` (calls that RETURN a per-step schedule,
  e.g. the engine scheduler's ``_schedule()``).  Request/token counts
  (``len()`` of a schedule list), attribute/subscript reads off tainted
  names, loop variables over tainted iterables, and arithmetic over any
  of those propagate.
- **Sinks**:

  1. a tainted value bound to a plan-shape static of a registered
     planner (``pallas_contract.PLANNER_KERNELS`` names, params in
     :data:`PLAN_SHAPE_STATICS`) — the plan-array SHAPES become
     schedule-dependent and every step retraces;
  2. a tainted value passed into a frozen ``_*Plan``/``*Geom``
     dataclass constructor (or ``dataclasses.replace`` on one) — a
     per-step value frozen into plan statics;
  3. a tainted value at a ``static_argnums``/``static_argnames``
     position of a jit-compiled callable — every distinct value is a
     distinct jit cache entry;
  4. a nested def that is jit-compiled AND branches (``if``/``while``)
     on a tainted closure — the branch keys the trace.

Deliberately NOT tainted: plan()-time parameters of the re-plan-per-
scheduling-decision steps (``MixedServingStep.plan`` replans by
design), the rung (the quantized ladder is the sanctioned static), and
anything outside registered source scopes — a taint pass that guesses
trains people to ignore it.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from flashinfer_tpu.analysis.core import (JIT_LIKE_NAMES, Finding,
                                          Project, SourceFile,
                                          expr_basename, walk_own_scope)

CODE = "L012"

# qualname -> parameter names that carry the per-step schedule into the
# function.  Registered per function (not per type): taint never leaks
# into unregistered scopes, so plan-time replanning stays unflagged.
SCHEDULE_SOURCES: Dict[str, Tuple[str, ...]] = {
    # the engine's schedule lowering: `segs` is THE per-step schedule
    # (SchedSeg rows); `rung` and `geom` are the sanctioned statics
    "build_engine_work_units": ("segs",),
    # the engine step itself sources its schedule via _schedule() (see
    # SCHEDULE_SOURCE_CALLS) but is registered so the pass walks it
    "ServingEngine.step": (),
}

# call basenames whose RETURN VALUE is a per-step schedule
SCHEDULE_SOURCE_CALLS: FrozenSet[str] = frozenset({"_schedule"})

# planner params that freeze plan-array SHAPES (the rung contract:
# "every array shape is a pure function of the rung, never the
# schedule").  `rung` itself is deliberately absent — the quantized
# ladder is the design.
PLAN_SHAPE_STATICS: FrozenSet[str] = frozenset({
    "num_units_pad", "block_q", "pages_per_chunk", "num_splits",
})

# frozen-static dataclass name patterns (the plan/geom record families)
_PLAN_CLASS_SUFFIXES = ("Plan", "Geom")



def _planner_names() -> FrozenSet[str]:
    from flashinfer_tpu.analysis.pallas_contract import PLANNER_KERNELS

    return frozenset(PLANNER_KERNELS)


def _is_plan_class(name: str) -> bool:
    return any(name.endswith(sfx) for sfx in _PLAN_CLASS_SUFFIXES)


class _Taint:
    """Fixpoint name-level taint over one function's own scope."""

    def __init__(self, fn: ast.AST, sources: Tuple[str, ...]):
        self.fn = fn
        self.tainted: Set[str] = set(sources)
        self._propagate()

    def _propagate(self) -> None:
        # true fixpoint: each round either grows the tainted set or
        # stops, and the set is bounded by the scope's names — so this
        # terminates without an arbitrary iteration cap (a capped loop
        # silently under-taints long forward assignment chains)
        while True:
            before = len(self.tainted)
            for n in walk_own_scope(self.fn):
                if isinstance(n, ast.Assign):
                    if self.expr_tainted(n.value):
                        for t in n.targets:
                            self._taint_target(t)
                elif isinstance(n, ast.AnnAssign):
                    # `n: int = len(segs)` — a type annotation must
                    # not dodge the taint an unannotated assign carries
                    if n.value is not None \
                            and self.expr_tainted(n.value):
                        self._taint_target(n.target)
                elif isinstance(n, ast.NamedExpr):
                    if self.expr_tainted(n.value):
                        self._taint_target(n.target)
                elif isinstance(n, ast.AugAssign):
                    if self.expr_tainted(n.value) and isinstance(
                            n.target, ast.Name):
                        self.tainted.add(n.target.id)
                elif isinstance(n, ast.For):
                    if self.expr_tainted(n.iter):
                        self._taint_target(n.target)
                elif isinstance(n, ast.withitem):
                    # `with tainted() as segs:` binds the schedule too
                    if n.optional_vars is not None \
                            and self.expr_tainted(n.context_expr):
                        self._taint_target(n.optional_vars)
            if len(self.tainted) == before:
                return

    def _taint_target(self, t: ast.expr) -> None:
        if isinstance(t, ast.Name):
            self.tainted.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._taint_target(e)
        elif isinstance(t, ast.Starred):
            # `first, *rest = segs` — the starred slice carries the
            # schedule too
            self._taint_target(t.value)

    def expr_tainted(self, expr: ast.expr) -> bool:
        """An expression carries schedule taint when any Name it reads
        is tainted or it calls a registered schedule source."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id in self.tainted:
                return True
            if isinstance(n, ast.Call) \
                    and expr_basename(n.func) in SCHEDULE_SOURCE_CALLS:
                return True
        return False


def _match_sources(qualname: str) -> Optional[Tuple[str, ...]]:
    if qualname in SCHEDULE_SOURCES:
        return SCHEDULE_SOURCES[qualname]
    return None


def _call_bound_args(call: ast.Call, params: List[str],
                     has_vararg: bool):
    """(param name, value expr) pairs a call binds, positionally and by
    keyword (starred operands end positional mapping)."""
    out = []
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(params):
            out.append((params[i], a))
        elif not has_vararg:
            break
    for k in call.keywords:
        if k.arg:
            out.append((k.arg, k.value))
    return out


def _frozen_plan_classes(project: Project) -> FrozenSet[str]:
    """Project classes that are frozen dataclasses with a Plan/Geom
    name — the records whose fields are compile-once statics."""
    out: Set[str] = set()
    for name, infos in project.class_index.items():
        if not _is_plan_class(name):
            continue
        for info in infos:
            for dec in info.node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if expr_basename(target) == "dataclass":
                    kws = (dec.keywords if isinstance(dec, ast.Call)
                           else [])
                    if any(k.arg == "frozen"
                           and isinstance(k.value, ast.Constant)
                           and k.value.value is True for k in kws):
                        out.add(name)
    return frozenset(out)


def _binds_plan_instance(fn: ast.AST, name: str,
                         plan_classes: FrozenSet[str],
                         _seen: Optional[Set[str]] = None) -> bool:
    """True when `name` is bound in `fn`'s own scope to a plan/geom
    CONSTRUCTION (`_StepPlan(...)`, `Geom.build(...)`, or a
    `dataclasses.replace` of one — the self-rebind
    `plan = replace(plan, ...)` resolves through the name's OTHER
    bindings) — the receiver test the replace sink needs so ordinary
    bookkeeping records never flag.  Unresolvable receivers return
    False: skip, never guess."""
    if _seen is None:
        _seen = set()
    if name in _seen:
        return False
    _seen.add(name)
    for n in walk_own_scope(fn):
        if not (isinstance(n, ast.Assign)
                and isinstance(n.value, ast.Call)):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in n.targets):
            continue
        v = n.value
        base = expr_basename(v.func)
        if base in plan_classes:
            return True
        if isinstance(v.func, ast.Attribute) and v.func.attr == "build" \
                and expr_basename(v.func.value) in plan_classes:
            return True
        if base == "replace" and v.args \
                and isinstance(v.args[0], ast.Name) \
                and _binds_plan_instance(fn, v.args[0].id, plan_classes,
                                         _seen):
            return True
    return False


def _static_positions(call: ast.Call) -> FrozenSet[int]:
    """static_argnums of a jit-like call (int/tuple literals only)."""
    for k in call.keywords:
        if k.arg == "static_argnums":
            v = k.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return frozenset({v.value})
            if isinstance(v, (ast.Tuple, ast.List)):
                out = set()
                for e in v.elts:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, int):
                        out.add(e.value)
                return frozenset(out)
    return frozenset()


def _static_names(call: ast.Call) -> FrozenSet[str]:
    """static_argnames of a jit-like call (str/tuple literals only) —
    the dominant spelling at this repo's jit sites."""
    for k in call.keywords:
        if k.arg == "static_argnames":
            v = k.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return frozenset({v.value})
            if isinstance(v, (ast.Tuple, ast.List)):
                out = set()
                for e in v.elts:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, str):
                        out.add(e.value)
                return frozenset(out)
    return frozenset()


def _body_positional_params(project: Project, sf: SourceFile,
                            fn: ast.AST, call: ast.Call) -> List[str]:
    """Positional params of the jit call's body function, so a
    positional call-site operand can map onto a static_argnames name —
    a same-scope nested def first, else the project index."""
    if not call.args:
        return []
    base = expr_basename(call.args[0])
    if not base:
        return []
    for n in walk_own_scope(fn):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n.name == base:
            a = n.args
            return [p.arg for p in a.posonlyargs + a.args]
    info = project.resolve_function(base, prefer_file=sf)
    if info is not None:
        return list(info.positional_params)
    return []


def _class_jit_statics(cls: ast.ClassDef, project: Project,
                       sf: SourceFile) -> Dict[
                           str, Tuple[FrozenSet[int], FrozenSet[str],
                                      List[str]]]:
    """``self.<attr> = jax.jit(..., static_argnums/argnames=...)``
    assignments anywhere in the class — the map a ``self.<attr>(...)``
    call site in a registered method resolves against (the repo's
    dominant compiled-step idiom compiles in plan()/__init__ and calls
    in step()/run()).  A leading ``self`` param of a method body is
    dropped so positional operands map onto the bound signature."""
    out: Dict[str, Tuple[FrozenSet[int], FrozenSet[str], List[str]]] = {}
    for stmt in cls.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in walk_own_scope(stmt):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.value, ast.Call)
                    and expr_basename(n.value.func) in JIT_LIKE_NAMES):
                continue
            t = n.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            statics = _static_positions(n.value)
            snames = _static_names(n.value)
            if not (statics or snames):
                continue
            params: List[str] = []
            if snames and n.value.args:
                base = expr_basename(n.value.args[0])
                for m in cls.body:
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                            and m.name == base:
                        a = m.args
                        params = [q.arg for q in a.posonlyargs + a.args]
                        break
                else:
                    params = _body_positional_params(project, sf, stmt,
                                                     n.value)
                if params and params[0] == "self":
                    params = params[1:]
            out[t.attr] = (statics, snames, params)
    return out


def _check_function(project: Project, sf: SourceFile, fn: ast.AST,
                    qualname: str, sources: Tuple[str, ...],
                    plan_classes: FrozenSet[str],
                    findings: List[Finding],
                    cls_statics: Optional[Dict] = None) -> None:
    taint = _Taint(fn, sources)
    planners = _planner_names()
    # names bound to a jit-compiled callable with static positions or
    # names (`step = jax.jit(body, static_argnums=...)` /
    # `static_argnames=...`) — collected up front so call sites
    # anywhere in the scope resolve; the body's positional params let
    # a positional operand map onto a named static
    jitted_statics: Dict[
        str, Tuple[FrozenSet[int], FrozenSet[str], List[str]]] = {}
    for node in walk_own_scope(fn):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and expr_basename(node.value.func) in JIT_LIKE_NAMES:
            statics = _static_positions(node.value)
            snames = _static_names(node.value)
            if statics or snames:
                params = _body_positional_params(project, sf, fn,
                                                 node.value) \
                    if snames else []
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jitted_statics[t.id] = (statics, snames, params)

    for node in walk_own_scope(fn):
        if not isinstance(node, ast.Call):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _check_jit_body_branches(sf, fn, node, qualname, taint,
                                         findings)
            continue
        base = expr_basename(node.func)

        # sink 1: plan-shape statics of registered planners
        if base in planners:
            info = project.resolve_function(base, prefer_file=sf)
            params = info.positional_params if info is not None else []
            vararg = info.has_vararg if info is not None else True
            for pname, val in _call_bound_args(node, params, vararg):
                if pname in PLAN_SHAPE_STATICS \
                        and taint.expr_tainted(val):
                    findings.append(Finding(
                        CODE, sf.path, val.lineno, qualname,
                        f"per-step schedule value reaches the plan-"
                        f"shape static '{pname}=' of planner "
                        f"'{base}': plan-array shapes must be a pure "
                        "function of the rung, never the schedule — "
                        "this retraces the step every time the "
                        "schedule moves (the compile-once ladder "
                        "silently becomes one trace per step)"))

        # sink 2: frozen plan/geom dataclass constructions
        ctor = base
        if ctor in plan_classes or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "build"
                and expr_basename(node.func.value) in plan_classes):
            cname = ctor if ctor in plan_classes \
                else expr_basename(node.func.value)
            for k in node.keywords:
                if k.arg and taint.expr_tainted(k.value):
                    findings.append(Finding(
                        CODE, sf.path, k.value.lineno, qualname,
                        f"per-step schedule value frozen into "
                        f"'{cname}.{k.arg}': frozen plan statics key "
                        "the jit cache, so a schedule-varying field "
                        "forces a replan+retrace every step — pass it "
                        "as a traced argument instead"))
            for a in node.args:
                if taint.expr_tainted(a):
                    findings.append(Finding(
                        CODE, sf.path, a.lineno, qualname,
                        f"per-step schedule value frozen into a "
                        f"'{cname}' plan static (positional): frozen "
                        "plan statics key the jit cache — pass it as "
                        "a traced argument instead"))
        elif base == "replace" and node.args \
                and isinstance(node.args[0], ast.Name) \
                and _binds_plan_instance(fn, node.args[0].id,
                                         plan_classes):
            # only a replace whose receiver RESOLVES to a plan/geom
            # construction is a plan sink — replace on ordinary
            # bookkeeping records must not flag (a taint pass that
            # guesses trains people to ignore it)
            for k in node.keywords:
                if k.arg and taint.expr_tainted(k.value):
                    findings.append(Finding(
                        CODE, sf.path, k.value.lineno, qualname,
                        f"per-step schedule value written into plan "
                        f"field '{k.arg}' via dataclasses.replace — "
                        "the replaced plan keys a fresh trace every "
                        "step"))

        # sink 3: tainted values at jit static positions/names — a
        # local `step(...)` or the class-attribute `self._step(...)`
        # idiom (compiled in plan()/__init__, called in step()/run())
        sink3 = None
        if isinstance(node.func, ast.Name) \
                and node.func.id in jitted_statics:
            sink3 = (node.func.id, jitted_statics[node.func.id])
        elif cls_statics and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self" \
                and node.func.attr in cls_statics:
            sink3 = ("self." + node.func.attr,
                     cls_statics[node.func.attr])
        if sink3 is not None:
            fname, (positions, snames, params) = sink3
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Starred):
                    break
                pname = params[i] if i < len(params) else None
                if not taint.expr_tainted(a):
                    continue
                if i in positions:
                    findings.append(Finding(
                        CODE, sf.path, a.lineno, qualname,
                        f"per-step schedule value passed at "
                        f"static_argnums position {i} of the jitted "
                        f"'{fname}': every distinct value is a "
                        "fresh trace — make it a traced argument or "
                        "quantize it onto the rung ladder"))
                elif pname is not None and pname in snames:
                    findings.append(Finding(
                        CODE, sf.path, a.lineno, qualname,
                        f"per-step schedule value passed at "
                        f"static_argnames param '{pname}' of the "
                        f"jitted '{fname}': every distinct "
                        "value is a fresh trace — make it a traced "
                        "argument or quantize it onto the rung ladder"))
            for k in node.keywords:
                if k.arg and k.arg in snames \
                        and taint.expr_tainted(k.value):
                    findings.append(Finding(
                        CODE, sf.path, k.value.lineno, qualname,
                        f"per-step schedule value passed at "
                        f"static_argnames param '{k.arg}' of the "
                        f"jitted '{fname}': every distinct "
                        "value is a fresh trace — make it a traced "
                        "argument or quantize it onto the rung ladder"))


def _check_jit_body_branches(sf: SourceFile, outer: ast.AST,
                             body: ast.AST, qualname: str,
                             taint: "_Taint",
                             findings: List[Finding]) -> None:
    """Sink 4: a nested def that is jit-compiled in this scope and
    branches on a tainted closure read."""
    compiled = False
    for n in walk_own_scope(outer):
        if isinstance(n, ast.Call) \
                and expr_basename(n.func) in JIT_LIKE_NAMES \
                and n.args and expr_basename(n.args[0]) == body.name:
            compiled = True
            break
    if not compiled:
        return
    a = body.args
    params = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    # a body-local that SHADOWS a tainted outer name is the body's own
    # binding, not a schedule closure — exclude stored names, like the
    # L011 capture check's _body_free_reads does
    stored = {n.id for n in ast.walk(body)
              if isinstance(n, ast.Name)
              and not isinstance(n.ctx, ast.Load)}
    for n in ast.walk(body):
        if isinstance(n, (ast.If, ast.While)):
            for m in ast.walk(n.test):
                if isinstance(m, ast.Name) and isinstance(m.ctx, ast.Load) \
                        and m.id in taint.tainted and m.id not in params \
                        and m.id not in stored:
                    findings.append(Finding(
                        CODE, sf.path, n.lineno, qualname,
                        f"jitted body '{body.name}' branches on "
                        f"per-step schedule closure '{m.id}': the "
                        "branch keys the trace, so every schedule "
                        "move recompiles — lower it to lax.cond on a "
                        "traced operand or hoist it to the host "
                        "planner"))
                    break


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    plan_classes = _frozen_plan_classes(project)
    for sf in project.files:
        if sf.tree is None:
            continue

        def _scan(scope: ast.AST, prefix: str,
                  cls_statics: Optional[Dict] = None) -> None:
            for node in walk_own_scope(scope):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    qual = prefix + node.name
                    sources = _match_sources(qual)
                    if sources is not None:
                        _check_function(project, sf, node, qual,
                                        sources, plan_classes, findings,
                                        cls_statics=cls_statics)
                    _scan(node, qual + ".", cls_statics)
                elif isinstance(node, ast.ClassDef):
                    _scan(node, prefix + node.name + ".",
                          _class_jit_statics(node, project, sf))

        _scan(sf.tree, "")
    return findings
