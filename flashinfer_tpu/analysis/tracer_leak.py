"""L008 — traced values leaking into Python-level control flow.

``jax.jit`` and Pallas kernels run their Python bodies ONCE, over
tracers.  A Python ``if``/``while``/``assert`` on a traced value either
raises ``TracerBoolConversionError`` at first trace (the loud case) or
— worse — silently specializes the whole trace on the first concrete
value when the operand happens to be concrete off-jit (the wrong-
numerics case).  ``int()``/``bool()``/``float()``/``.item()``
concretize a tracer the same way, and ``np.*`` calls materialize it
host-side at trace time, pinning the first value into the compiled
program.

Scope (deliberately precise, not maximal):

- **jit bodies**: functions decorated/wrapped with ``jax.jit``/``pjit``
  (bare, via ``functools.partial(jax.jit, ...)``, or assignment-
  wrapped).  Parameters named in ``static_argnames``/``static_argnums``
  are concrete and exempt.
- **Pallas kernels**: functions resolved as pallas_call targets
  (through the project symbol index, so a kernel launched from another
  module is still covered).  Positional params are refs/values in the
  traced world; keyword-only params are the partial-bound statics.

Taint is local and syntactic: a name assigned from a traced expression
is traced; ``.shape``/``.dtype``/``.ndim`` access, ``len()``, and
``is``/``is not`` comparisons yield static values (pytree structure is
static under jit) and break the chain.  Nested defs (the ``pl.when``
closure idiom) share the enclosing traced environment.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from flashinfer_tpu.analysis.core import (Finding, Project, SourceFile,
                                          expr_basename, expr_root)

CODE = "L008"

_JIT_NAMES = {"jit", "pjit"}
_PARTIAL_NAMES = {"partial"}
# attribute reads that are static under tracing (structure, not data)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding",
                 "itemsize", "weak_type"}
_STATIC_CALLS = {"len", "isinstance", "type", "range", "enumerate",
                 "hasattr", "id", "repr", "str", "format"}
_CONCRETIZERS = {"int", "bool", "float", "complex"}
_NP_ROOTS = {"np", "numpy"}


def _is_jit_expr(expr: ast.expr) -> bool:
    if expr_basename(expr) in _JIT_NAMES:
        return True
    if isinstance(expr, ast.Call):
        if expr_basename(expr.func) in _JIT_NAMES:
            return True
        if expr_basename(expr.func) in _PARTIAL_NAMES and expr.args \
                and _is_jit_expr(expr.args[0]):
            return True
    return False


def _static_names_of(call: ast.Call, fn: ast.FunctionDef) -> Set[str]:
    """Parameter names pinned static by a jit call's
    static_argnames/static_argnums literals."""
    out: Set[str] = set()
    pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for k in call.keywords:
        if k.arg == "static_argnames":
            vals = (k.value.elts
                    if isinstance(k.value, (ast.Tuple, ast.List))
                    else [k.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out.add(v.value)
        elif k.arg == "static_argnums":
            vals = (k.value.elts
                    if isinstance(k.value, (ast.Tuple, ast.List))
                    else [k.value])
            for v in vals:
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, int) \
                        and 0 <= v.value < len(pos):
                    out.add(pos[v.value])
    return out


def _jitted_defs(sf: SourceFile) -> List[Tuple[ast.FunctionDef, Set[str]]]:
    """(def, static param names) for every jitted function in `sf`."""
    if sf.tree is None:
        return []
    # assignment-wrapped: g = jax.jit(f, static_argnames=...)
    wrapped: Dict[str, ast.Call] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node.func) \
                and node.args and isinstance(node.args[0], ast.Name):
            wrapped[node.args[0].id] = node
    out = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        statics: Optional[Set[str]] = None
        for d in node.decorator_list:
            if _is_jit_expr(d):
                statics = set()
                if isinstance(d, ast.Call):
                    statics = _static_names_of(d, node)
                    # partial(jax.jit, static_argnames=...) carries the
                    # kwargs on the partial call itself
                break
        if statics is None and node.name in wrapped:
            statics = _static_names_of(wrapped[node.name], node)
        if statics is not None:
            out.append((node, statics))
    return out


class _Scope:
    """Taint environment for one traced body (shared by nested defs)."""

    def __init__(self, traced: Set[str]):
        self.traced = set(traced)

    # -- expression taint ------------------------------------------------

    def tainted(self, expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.traced
        if isinstance(expr, ast.Constant):
            return False
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return False
            return self.tainted(expr.value)
        if isinstance(expr, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
                return False  # None-ness / identity is pytree structure
            return self.tainted(expr.left) or any(
                self.tainted(c) for c in expr.comparators)
        if isinstance(expr, ast.Call):
            base = expr_basename(expr.func)
            if base in _STATIC_CALLS:
                return False
            args = list(expr.args) + [k.value for k in expr.keywords]
            arg_taint = any(self.tainted(a) for a in args)
            # a call ON a traced object (plan.get(...)) is traced too
            if isinstance(expr.func, ast.Attribute) \
                    and self.tainted(expr.func):
                return True
            return arg_taint
        if isinstance(expr, ast.Subscript):
            return self.tainted(expr.value)
        if isinstance(expr, (ast.BinOp,)):
            return self.tainted(expr.left) or self.tainted(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.tainted(expr.operand)
        if isinstance(expr, ast.BoolOp):
            return any(self.tainted(v) for v in expr.values)
        if isinstance(expr, ast.IfExp):
            return any(self.tainted(e)
                       for e in (expr.test, expr.body, expr.orelse))
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted(e) for e in expr.elts
                       if not isinstance(e, ast.Starred)) or any(
                self.tainted(e.value) for e in expr.elts
                if isinstance(e, ast.Starred))
        if isinstance(expr, ast.Starred):
            return self.tainted(expr.value)
        return False

    def bind(self, target: ast.expr, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.traced.add(target.id)
            else:
                self.traced.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for t in target.elts:
                self.bind(t.value if isinstance(t, ast.Starred) else t,
                          tainted)


def _check_body(fn: ast.FunctionDef, scope: _Scope, sf: SourceFile,
                kind: str, findings: List[Finding],
                fname: Optional[str] = None) -> None:
    fname = fname or fn.name

    def visit(stmts) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # the pl.when-closure idiom: nested defs trace in the
                # SAME environment; their own params shadow
                inner = _Scope(scope.traced)
                for a in (stmt.args.posonlyargs + stmt.args.args
                          + stmt.args.kwonlyargs):
                    inner.traced.discard(a.arg)
                _check_body(stmt, inner, sf, kind, findings, fname)
                continue
            if isinstance(stmt, (ast.If, ast.While)) \
                    and scope.tainted(stmt.test):
                findings.append(Finding(
                    CODE, sf.path, stmt.lineno, fname,
                    f"Python {'if' if isinstance(stmt, ast.If) else 'while'}"
                    f" on a traced value inside a {kind} — the branch is "
                    "resolved ONCE at trace time (or raises "
                    "TracerBoolConversionError); use jnp.where / "
                    "lax.cond / pl.when on the traced operand, or hoist "
                    "the decision to the host"))
            if isinstance(stmt, ast.Assert) and scope.tainted(stmt.test):
                findings.append(Finding(
                    CODE, sf.path, stmt.lineno, fname,
                    f"assert on a traced value inside a {kind} — it "
                    "cannot check runtime data (checkify is the traced "
                    "form); move the assert to the host-side planner"))
            # scan only THIS statement's own expressions — compound
            # statements' bodies are visited recursively below, and
            # scanning them here too would double-report
            if isinstance(stmt, (ast.If, ast.While)):
                own = ast.walk(stmt.test)
            elif isinstance(stmt, ast.For):
                own = ast.walk(stmt.iter)
            elif isinstance(stmt, ast.With):
                own = (n for item in stmt.items
                       for n in ast.walk(item.context_expr))
            elif isinstance(stmt, ast.Try):
                own = iter(())
            else:
                own = ast.walk(stmt)
            for expr in own:
                if not isinstance(expr, ast.Call):
                    continue
                base = expr_basename(expr.func)
                args = list(expr.args) + [k.value for k in expr.keywords]
                if base in _CONCRETIZERS and any(
                        scope.tainted(a) for a in args):
                    findings.append(Finding(
                        CODE, sf.path, expr.lineno, fname,
                        f"{base}() on a traced value inside a {kind} "
                        "concretizes it at trace time — the first "
                        "traced value is baked into every later call"))
                elif base == "item" \
                        and isinstance(expr.func, ast.Attribute) \
                        and scope.tainted(expr.func.value):
                    findings.append(Finding(
                        CODE, sf.path, expr.lineno, fname,
                        f".item() on a traced value inside a {kind} "
                        "forces a host round-trip at trace time — keep "
                        "the value on-device or compute it in the "
                        "host-side plan"))
                elif expr_root(expr.func) in _NP_ROOTS \
                        and isinstance(expr.func, ast.Attribute) \
                        and any(scope.tainted(a) for a in args):
                    findings.append(Finding(
                        CODE, sf.path, expr.lineno, fname,
                        f"np.{expr_basename(expr.func)}() applied to a "
                        f"traced value inside a {kind} materializes it "
                        "host-side at trace time and pins the result in "
                        "the jit cache — use the jnp equivalent"))
            # statement-level rebinds AFTER scanning the statement, so
            # `x = int(x)` still reports on the traced right-hand side
            if isinstance(stmt, ast.Assign):
                t = scope.tainted(stmt.value)
                for tgt in stmt.targets:
                    scope.bind(tgt, t)
            elif isinstance(stmt, ast.AugAssign):
                if scope.tainted(stmt.value) and isinstance(
                        stmt.target, ast.Name):
                    scope.traced.add(stmt.target.id)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                scope.bind(stmt.target, scope.tainted(stmt.value))
            elif isinstance(stmt, ast.For):
                # iterating a Python list built FROM traced values is
                # legal (the list is host-side); the loop var inherits
                # the iterable's taint for reads inside the body
                scope.bind(stmt.target, scope.tainted(stmt.iter))
                visit(stmt.body)
                visit(stmt.orelse)
                continue
            elif isinstance(stmt, ast.With):
                visit(stmt.body)
                continue
            elif isinstance(stmt, ast.Try):
                visit(stmt.body)
                for h in stmt.handlers:
                    visit(h.body)
                visit(stmt.orelse)
                visit(stmt.finalbody)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                visit(stmt.body)
                visit(stmt.orelse)

    visit(fn.body)


def _kernel_defs(project: Project) -> List[Tuple[SourceFile,
                                                 ast.FunctionDef,
                                                 Set[str]]]:
    """Unique kernels with the param names partial-bound at ANY launch
    site (keyword binds by name, positional binds the leading params) —
    those are compile-time statics, not traced refs."""
    agg: Dict[tuple, Tuple[SourceFile, ast.FunctionDef, Set[str]]] = {}
    for site in project.pallas_sites:
        k = site.kernel
        if k is None:
            continue
        key = (k.file.path, k.node.lineno)
        entry = agg.setdefault(key, (k.file, k.node, set()))
        bound = entry[2]
        bound |= site.kernel_bound_kwargs
        pos = [a.arg for a in (k.node.args.posonlyargs
                               + k.node.args.args)]
        bound |= set(pos[:site.kernel_bound_posargs])
    return list(agg.values())


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files:
        for fn, statics in _jitted_defs(sf):
            traced = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                      + fn.args.kwonlyargs)
                      if a.arg not in statics}
            if fn.args.vararg:
                traced.add(fn.args.vararg.arg)
            _check_body(fn, _Scope(traced), sf, "jit-traced body",
                        findings)
    for sf, fn, bound in _kernel_defs(project):
        # positional params are refs; keyword-only params and
        # partial-bound names (keyword OR leading positional) are the
        # launch statics
        traced = {a.arg for a in fn.args.posonlyargs + fn.args.args
                  if a.arg not in bound}
        if fn.args.vararg:
            traced.add(fn.args.vararg.arg)
        _check_body(fn, _Scope(traced), sf, "Pallas kernel", findings)
    return findings
