"""L017 — chooser coverage: every priced choice prunes through the proof.

The PR 6/14 chooser pattern (``choose_decode_splits``,
``predict_prefill_ingest_win``) races candidates through the analytic
cost model at plan time — but a candidate the compiler would reject is
not a candidate, so every chooser must first prune through the L009
VMEM-feasibility evaluator (decode.py ``_split_vmem_feasible``,
prefill.py ``_ingest_vmem_feasible``).  A chooser that prices without
pruning can select a tactic that fails to compile; a knob surface with
no chooser and no waiver silently reverts to hand-tuning.  Both are
exactly the "silently skipped == checked and clean" failure L013
closed for registries, applied to the choice layer:

1. **Chooser prune discipline.**  Every chooser named in
   ``costmodel.KNOB_CHOOSERS`` must resolve in the analyzed tree, take
   a ``feasible`` parameter, and guard its pricing loop with the
   ``feasible is not None and not feasible(...)`` prune.  The check is
   structural (AST), so deleting the prune — even while the signature
   keeps the parameter — is a finding.
2. **Call-site wiring.**  A prune parameter nobody passes is dead
   code: at least one project call site of each chooser must wire
   ``feasible=`` (advisory callers like roofline explainers may omit
   it; the PLAN path must not).  Gated on the project containing call
   sites at all, so ``--changed-only`` subsets under-report, never
   false-fail.
3. **Knob coverage.**  Every ``autotuner.KNOWN_KNOBS`` surface is
   either priced (``KNOB_CHOOSERS``) or carries a reasoned
   ``CHOOSER_WAIVERS`` entry saying WHY no pricing loop exists
   (measured-beats-modeled, geometry derivation, topology contract…).
   Reasonless waivers, waivers shadowing a real chooser, and
   waivers/choosers naming retired knobs are findings — the L013
   staleness rules verbatim.
4. **Binding-family integrity.**  Every ``COST_LAUNCH_BINDINGS`` entry
   (the L016 parity registry) must reference a family formula that
   exists in the costmodel snapshot, and its adapter must actually
   produce every category the binding's ``compare`` tolerances name —
   otherwise L016 "passes" by comparing against nothing.

Like L016, the registries are read from the PROJECT's
``obs/costmodel.py`` executed as a snapshot (cost_parity's loader), so
a mutated tree is judged against its own registrations, not the
installed package.  All checks are anchor-gated: no
``register_knob_chooser`` / ``register_knob`` calls in the analyzed
set means the registry module is out of scope and the check skips.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from flashinfer_tpu.analysis.core import (Finding, Project,
                                          expr_basename)
from flashinfer_tpu.analysis.cost_parity import _load_snapshot

CODE = "L017"


# -- anchors ---------------------------------------------------------------


def _call_lines(project: Project, fname: str,
                key_arg: int = 0) -> Dict[str, Tuple[str, int]]:
    """first-string-arg -> (file, line) for every ``fname("...", ...)``
    call in the analyzed set; the finding anchors land on the
    registration that needs editing."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in project.files:
        if sf.tree is None:
            continue
        for n in ast.walk(sf.tree):
            if isinstance(n, ast.Call) \
                    and expr_basename(n.func) == fname \
                    and len(n.args) > key_arg \
                    and isinstance(n.args[key_arg], ast.Constant) \
                    and isinstance(n.args[key_arg].value, str):
                out[n.args[key_arg].value] = (sf.path, n.lineno)
    return out


def _binding_lines(project: Project) -> Dict[str, Tuple[str, int]]:
    """launcher -> (file, line) of its ``CostLaunchBinding(launcher=…)``
    construction."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in project.files:
        if sf.tree is None:
            continue
        for n in ast.walk(sf.tree):
            if not (isinstance(n, ast.Call)
                    and expr_basename(n.func) == "CostLaunchBinding"):
                continue
            for kw in n.keywords:
                if kw.arg == "launcher" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    out[kw.value.value] = (sf.path, n.lineno)
    return out


# -- check 1+2: chooser prune discipline and wiring ------------------------


def _has_feasible_param(node) -> bool:
    a = node.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    return "feasible" in names


def _has_prune_guard(node) -> bool:
    """the structural signature of the prune: a ``feasible is not
    None`` comparison AND a ``feasible(...)`` call somewhere in the
    chooser body — deleting either half disarms the prune."""
    has_cmp = has_call = False
    for n in ast.walk(node):
        if isinstance(n, ast.Compare) and isinstance(n.left, ast.Name) \
                and n.left.id == "feasible" \
                and any(isinstance(op, (ast.IsNot, ast.NotEq))
                        for op in n.ops):
            has_cmp = True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "feasible":
            has_call = True
    return has_cmp and has_call


def _chooser_call_sites(project: Project,
                        chooser: str) -> List[Tuple[str, int, bool]]:
    """(file, line, passes_feasible) per project call of `chooser`,
    excluding its own definition module's registration line."""
    out: List[Tuple[str, int, bool]] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        for n in ast.walk(sf.tree):
            if isinstance(n, ast.Call) \
                    and expr_basename(n.func) == chooser:
                wired = any(kw.arg == "feasible" for kw in n.keywords)
                out.append((sf.path, n.lineno, wired))
    return out


def _check_choosers(project: Project, findings: List[Finding],
                    choosers: Dict[str, str]) -> None:
    anchors = _call_lines(project, "register_knob_chooser")
    if not anchors:
        return  # registry module not analyzed: skip, never guess
    for knob, chooser in sorted(choosers.items()):
        anchor = anchors.get(knob, next(iter(anchors.values())))
        fi = project.resolve_function(chooser)
        if fi is None:
            path, line = anchor
            findings.append(Finding(
                CODE, path, line, knob,
                f"KNOB_CHOOSERS binds '{knob}' to '{chooser}' but no "
                "such function exists in the analyzed tree — a renamed "
                "chooser left a dangling registration; re-point it"))
            continue
        if not _has_feasible_param(fi.node):
            findings.append(Finding(
                CODE, fi.file.path, fi.node.lineno, chooser,
                f"chooser '{chooser}' (knob '{knob}') takes no "
                "``feasible`` parameter — it prices candidates the "
                "L009 VMEM evaluator could reject; thread the prune "
                "through (the choose_decode_splits pattern)"))
            continue
        if not _has_prune_guard(fi.node):
            findings.append(Finding(
                CODE, fi.file.path, fi.node.lineno, chooser,
                f"chooser '{chooser}' (knob '{knob}') accepts "
                "``feasible`` but never prunes with it (no ``feasible "
                "is not None`` guard + ``feasible(...)`` call) — an "
                "uncompilable candidate can win the pricing race; "
                "restore the prune before pricing"))
            continue
        sites = _chooser_call_sites(project, chooser)
        if sites and not any(w for _, _, w in sites):
            path, line, _ = sites[0]
            findings.append(Finding(
                CODE, path, line, chooser,
                f"no call site of chooser '{chooser}' passes "
                "``feasible=`` — the VMEM prune is dead code and every "
                "plan prices unproven candidates; wire the evaluator "
                "at the plan-path call (decode.py "
                "_split_vmem_feasible / prefill.py "
                "_ingest_vmem_feasible precedent)"))


# -- check 3: knob coverage ------------------------------------------------


def _check_knob_coverage(project: Project, findings: List[Finding],
                         knobs: Optional[Dict],
                         choosers: Dict[str, str],
                         waivers: Dict[str, str]) -> None:
    knob_anchors = _call_lines(project, "register_knob")
    if not knob_anchors:
        return  # autotuner registry not analyzed: subset run
    if knobs is None:
        from flashinfer_tpu.autotuner import KNOWN_KNOBS as knobs
    chooser_anchors = _call_lines(project, "register_knob_chooser")
    waiver_anchors = _call_lines(project, "waive_chooser")
    if not (chooser_anchors or waiver_anchors):
        return  # chooser registry module not analyzed
    fallback = next(iter(knob_anchors.values()))
    for knob in sorted(set(knobs) - set(choosers) - set(waivers)):
        path, line = knob_anchors.get(knob, fallback)
        findings.append(Finding(
            CODE, path, line, knob,
            f"knob '{knob}' is registered in KNOWN_KNOBS but has "
            "neither a KNOB_CHOOSERS pricing chooser nor a "
            "CHOOSER_WAIVERS entry — an unpriced knob silently "
            "reverts to hand-tuning; register the chooser or waive "
            "with the reason pricing does not apply "
            "(obs/costmodel.py)"))
    for knob, reason in sorted(waivers.items()):
        path, line = waiver_anchors.get(
            knob, knob_anchors.get(knob, fallback))
        if not str(reason).strip():
            findings.append(Finding(
                CODE, path, line, knob,
                f"CHOOSER_WAIVERS entry for '{knob}' has no reason — "
                "an unreviewable waiver is worse than the gap it "
                "hides (the L000 rule, applied to the choice layer)"))
        if knob in choosers:
            findings.append(Finding(
                CODE, path, line, knob,
                f"knob '{knob}' is BOTH priced in KNOB_CHOOSERS and "
                "waived in CHOOSER_WAIVERS — delete the stale waiver "
                "so the chooser visibly owns the knob"))
        if knob not in knobs:
            findings.append(Finding(
                CODE, path, line, knob,
                f"CHOOSER_WAIVERS entry for '{knob}' names no "
                "registered knob — a renamed/retired knob left a "
                "stale waiver; prune it"))
    for knob in sorted(set(choosers) - set(knobs)):
        path, line = chooser_anchors.get(knob, fallback)
        findings.append(Finding(
            CODE, path, line, knob,
            f"KNOB_CHOOSERS entry for '{knob}' names no registered "
            "knob — a renamed/retired knob left a stale chooser "
            "registration; prune or re-point it"))


# -- check 4: binding-family integrity -------------------------------------


def _check_binding_families(project: Project, findings: List[Finding],
                            bindings: Dict, families_mod) -> None:
    anchors = _binding_lines(project)
    fallback = next(iter(anchors.values())) if anchors else None
    for launcher in sorted(bindings):
        b = bindings[launcher]
        anchor = anchors.get(launcher, fallback)
        if anchor is None:
            continue  # registration text not in the analyzed set
        path, line = anchor
        family = getattr(b, "family", None)
        if families_mod is not None \
                and not callable(getattr(families_mod, str(family),
                                         None)):
            findings.append(Finding(
                CODE, path, line, launcher,
                f"cost-launch binding for '{launcher}' prices against "
                f"family '{family}' which is not a callable in "
                "obs/costmodel.py — the L016 parity check would "
                "compare kernel traffic against nothing; fix the "
                "family name or add the formula"))
            continue
        try:
            expected = b.adapter(dict(b.scenario))
        except Exception as e:
            findings.append(Finding(
                CODE, path, line, launcher,
                f"cost-launch binding for '{launcher}': adapter "
                f"crashed on its own declared scenario ({e!r}) — "
                "the binding can never be evaluated; the scenario "
                "and the family signature drifted apart"))
            continue
        missing = sorted(set(getattr(b, "compare", {}) or {})
                         - set(expected or {}))
        if missing:
            findings.append(Finding(
                CODE, path, line, launcher,
                f"cost-launch binding for '{launcher}': adapter "
                f"omits compared categor{'ies' if len(missing) > 1 else 'y'} "
                f"{', '.join(missing)} — a tolerance with no expected "
                "value is a check that never runs; emit the category "
                "or drop it from `compare`"))


# -- pass driver -----------------------------------------------------------


def _registries(project: Project, choosers, waivers, bindings,
                families_mod):
    if choosers is not None and waivers is not None \
            and bindings is not None:
        return choosers, waivers, bindings, families_mod
    mod, _err = _load_snapshot(project)
    if mod is None:
        return (choosers or {}, waivers or {}, bindings or {},
                families_mod)
    return (choosers if choosers is not None
            else getattr(mod, "KNOB_CHOOSERS", {}),
            waivers if waivers is not None
            else getattr(mod, "CHOOSER_WAIVERS", {}),
            bindings if bindings is not None
            else getattr(mod, "COST_LAUNCH_BINDINGS", {}),
            families_mod if families_mod is not None else mod)


def run(project: Project, *, knobs: Optional[Dict] = None,
        choosers: Optional[Dict] = None,
        waivers: Optional[Dict] = None,
        bindings: Optional[Dict] = None,
        families_mod=None) -> List[Finding]:
    findings: List[Finding] = []
    choosers, waivers, bindings, families_mod = _registries(
        project, choosers, waivers, bindings, families_mod)
    _check_choosers(project, findings, choosers)
    _check_knob_coverage(project, findings, knobs, choosers, waivers)
    _check_binding_families(project, findings, bindings, families_mod)
    return findings


def stats(project: Project) -> dict:
    """counts for ``obs doctor`` — chooser/waiver surface + findings."""
    choosers, waivers, bindings, _mod = _registries(
        project, None, None, None, None)
    return {
        "choosers": len(choosers),
        "waivers": len(waivers),
        "bindings": len(bindings),
        "findings": len(run(project)),
    }
