"""L011 — donated-buffer lifetime violations at compile-once call sites.

The serving layer's deepest runtime contract (PR 7/8): the fused step
is compiled with ``donate_argnums`` so XLA aliases the KV caches, page
tables, and PRNG key in place — after a ``step(...)`` call the donated
INPUT buffers are dead.  At runtime the violation surfaces as jax's
deleted-buffer error *on the first run that actually reuses the
buffer* (often a cold path: telemetry, an error handler, a rarely-hit
branch).  Every piece is statically decidable from the AST, so this
pass proves the three lifetime contracts at lint time:

1. **Use-after-donate.**  At a call through a donation-compiled
   callable (``self._step = jax.jit(body, donate_argnums=...)`` and
   friends), any later read of a NAME that was passed at a donated
   position — without an intervening rebind — reads a dead buffer.
2. **Donated-and-captured.**  A donated argument whose name the jitted
   body ALSO reads as a closure is aliased on both sides of the
   donation: the traced constant and the donated operand race, and XLA
   may fold the closure copy into the program (a silent stale read,
   not an error).
3. **Both-or-neither shardings.**  The ``parallel/plan.py``
   ``compile_step_with_plan`` contract, statically: a ``jax.jit`` (or
   ``compile_step_with_plan``) call spelling exactly one of
   ``in_shardings``/``out_shardings`` lets the compiler re-derive the
   missing side and partition the program differently than the plan
   says.  ``compile_step_with_plan`` raises at runtime; this makes it
   a cannot-land review failure instead.

Resolution rides the ``core.py`` layer: donation-compiled callables
resolve through once-assigned locals (``step = jax.jit(...)``),
``self.<attr>`` assignments anywhere in the enclosing class, and
project functions whose single ``return`` is the jit call (the
``build_fused_step`` builder idiom).  Anything not statically
decidable — starred call args, multi-assigned names, dynamic
donate_argnums, reads the straight-line continuation of the call
cannot prove (past the ``if`` arm holding the call, after a
maybe-zero-iteration loop) — is SKIPPED, never guessed.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from flashinfer_tpu.analysis.core import (JIT_LIKE_NAMES, ChainLocals,
                                          Finding, FnLocals, Project,
                                          SourceFile, expr_basename,
                                          walk_own_scope)

CODE = "L011"

# callables that compile a step body with explicit donation semantics
# (the first positional argument is the body function in every
# spelling) live in the shared core registry, so L012 sees the same set


def _is_jit_like(call: ast.Call) -> bool:
    return expr_basename(call.func) in JIT_LIKE_NAMES


def _const_set(expr: ast.expr, locals_: Optional[FnLocals], pred,
               _depth: int = 0) -> Optional[FrozenSet]:
    """Statically-known elements of a donation expression: a literal
    accepted by `pred`, a tuple/list of them, a once-assigned local
    name, or a conditional between resolvable branches (the
    ``(2, 3) if donate else ()`` idiom — the union is taken: if EITHER
    branch donates, post-call reuse is a bug on that branch).  One
    resolver serves both spellings (donate_argnums ints and
    donate_argnames strs) so they can never diverge in what they
    resolve."""
    if _depth > 6:
        return None
    if isinstance(expr, ast.Constant):
        return frozenset({expr.value}) if pred(expr.value) else None
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: Set = set()
        for e in expr.elts:
            if not (isinstance(e, ast.Constant) and pred(e.value)):
                return None
            out.add(e.value)
        return frozenset(out)
    if isinstance(expr, ast.IfExp):
        lo = _const_set(expr.body, locals_, pred, _depth + 1)
        hi = _const_set(expr.orelse, locals_, pred, _depth + 1)
        if lo is None or hi is None:
            return None
        return lo | hi
    if isinstance(expr, ast.Name) and locals_ is not None:
        v = locals_.value_of(expr.id)
        if v is not None:
            return _const_set(v, locals_, pred, _depth + 1)
    return None


def _is_argnum(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _const_int_set(expr: ast.expr, locals_: Optional[FnLocals]
                   ) -> Optional[FrozenSet[int]]:
    return _const_set(expr, locals_, _is_argnum)


def _const_str_set(expr: ast.expr, locals_: Optional[FnLocals]
                   ) -> Optional[FrozenSet[str]]:
    return _const_set(expr, locals_,
                      lambda v: isinstance(v, str))


def _donated_positions(call: ast.Call,
                       locals_: Optional[FnLocals]
                       ) -> Optional[FrozenSet[int]]:
    """Donated argnums of a jit-like call, or None when absent or not
    statically resolvable (an unresolvable donation disables the
    lifetime checks for this callable — skip, never guess)."""
    for k in call.keywords:
        if k.arg == "donate_argnums":
            return _const_int_set(k.value, locals_)
    return None


def _donated_names_kw(call: ast.Call,
                      locals_: Optional[FnLocals]
                      ) -> Optional[FrozenSet[str]]:
    """Donated argnames of a jit-like call (the ``donate_argnames``
    spelling), or None when absent/unresolvable."""
    for k in call.keywords:
        if k.arg == "donate_argnames":
            return _const_str_set(k.value, locals_)
    return None


def _local_def(scope: ast.AST, name: str) -> Optional[ast.AST]:
    """A def named `name` in `scope`'s own body (not nested deeper)."""
    for n in walk_own_scope(scope):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n.name == name:
            return n
    return None


def _body_free_reads(fn: ast.AST) -> FrozenSet[str]:
    """Names the body READS but neither takes as parameters nor binds
    itself — the closure-capture surface the donated-and-captured
    check intersects with donated call-site names."""
    params: Set[str] = set()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        params.add(p.arg)
    for va in (a.vararg, a.kwarg):
        if va is not None:
            params.add(va.arg)
    loads: Set[str] = set()
    stores: Set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Name):
            if isinstance(n.ctx, ast.Load):
                loads.add(n.id)
            else:
                stores.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n is not fn:
            stores.add(n.name)
        elif isinstance(n, (ast.Import, ast.ImportFrom)):
            for alias in n.names:
                stores.add((alias.asname or alias.name).split(".")[0])
    return frozenset(loads - params - stores)


@dataclasses.dataclass(frozen=True)
class _DonatedCallable:
    """One donation-compiled callable: where it was compiled, which
    positions/param-names donate, the body's positional params (to map
    positional operands onto donate_argnames), and the jitted body's
    closure reads."""

    positions: FrozenSet[int]
    names: FrozenSet[str]
    params: Tuple[str, ...]
    body_free: FrozenSet[str]
    jit_line: int
    # True only when the jitted body's free names bind in the CALL
    # SITE's own scope (local `step = jax.jit(_body)` / inline-applied
    # jit): the donated-and-captured name comparison is meaningful
    # there and cross-scope name collisions (builder/class/module
    # bodies) are not — skip, never guess
    same_scope: bool = False


def _positional_params(fn: ast.AST) -> Tuple[str, ...]:
    a = fn.args
    return tuple(p.arg for p in a.posonlyargs + a.args)


def _resolve_jit_site(call: ast.Call, locals_: Optional[FnLocals],
                      scope: ast.AST,
                      same_scope: bool = False) -> Optional[_DonatedCallable]:
    """A jit-like Call -> its donation record (None when donations are
    absent/empty/unresolvable)."""
    pos = _donated_positions(call, locals_)
    names = _donated_names_kw(call, locals_)
    if not pos and not names:
        return None
    body_free: FrozenSet[str] = frozenset()
    params: Tuple[str, ...] = ()
    if call.args:
        base = expr_basename(call.args[0])
        if base:
            body = _local_def(scope, base)
            if body is not None:
                body_free = _body_free_reads(body)
                params = _positional_params(body)
    return _DonatedCallable(pos or frozenset(), names or frozenset(),
                            params, body_free, call.lineno,
                            same_scope=same_scope)


def _decorated_donations(fn_def: ast.AST) -> Optional[_DonatedCallable]:
    """Donation record of a def compiled by decorator —
    ``@functools.partial(jax.jit, donate_argnums=...)`` (the repo's
    dominant jit-decorator idiom); the def's own params are the
    donated positions."""
    for dec in fn_def.decorator_list:
        if not (isinstance(dec, ast.Call)
                and expr_basename(dec.func) == "partial"
                and dec.args
                and expr_basename(dec.args[0]) in JIT_LIKE_NAMES):
            continue
        pos = _donated_positions(dec, None)
        names = _donated_names_kw(dec, None)
        if not pos and not names:
            return None
        return _DonatedCallable(pos or frozenset(), names or frozenset(),
                                _positional_params(fn_def),
                                _body_free_reads(fn_def), dec.lineno)
    return None


def _builder_return_jit(project: Project, name: str,
                        sf: SourceFile) -> Optional[_DonatedCallable]:
    """Resolve ``step = build_x(...); step(...)`` through a project
    function whose single return value is a jit-like call (the
    serve/shard.py builder idiom)."""
    fn = project.resolve_function(name, prefer_file=sf)
    if fn is None:
        return None
    returns = [n for n in walk_own_scope(fn.node)
               if isinstance(n, ast.Return) and n.value is not None]
    if len(returns) != 1:
        return None
    fl = FnLocals(fn.node)
    val = returns[0].value
    if isinstance(val, ast.Name):
        v = fl.value_of(val.id)
        if v is not None:
            val = v
    if isinstance(val, ast.Call) and _is_jit_like(val):
        return _resolve_jit_site(val, fl, fn.node)
    return None


class _ClassDonations:
    """``self.<attr> = jax.jit(..., donate_argnums=...)`` assignments
    collected per class: the attribute map a ``self.<attr>(...)`` call
    site resolves against.  Multiple assignments to one attribute union
    their donations (step.py compiles the same body down either the
    sharded or plain branch with identical donations)."""

    def __init__(self, cls: ast.ClassDef):
        self.attrs: Dict[str, _DonatedCallable] = {}
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            fl = FnLocals(stmt)
            for n in walk_own_scope(stmt):
                if not (isinstance(n, ast.Assign) and len(n.targets) == 1):
                    continue
                t = n.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if not (isinstance(n.value, ast.Call)
                        and _is_jit_like(n.value)):
                    continue
                rec = _resolve_jit_site(n.value, fl, stmt)
                if rec is None:
                    continue
                prev = self.attrs.get(t.attr)
                if prev is not None:
                    rec = _DonatedCallable(
                        prev.positions | rec.positions,
                        prev.names | rec.names,
                        prev.params or rec.params,
                        prev.body_free | rec.body_free, prev.jit_line)
                self.attrs[t.attr] = rec


def _call_site_donations(project: Project, sf: SourceFile,
                         call: ast.Call, chain: List[ast.AST],
                         cls_map: Optional[_ClassDonations]
                         ) -> Optional[_DonatedCallable]:
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self" and cls_map is not None:
        return cls_map.attrs.get(f.attr)
    if isinstance(f, ast.Name):
        locals_ = ChainLocals(chain)
        v = locals_.value_of(f.id)
        if isinstance(v, ast.Call):
            if _is_jit_like(v):
                return _resolve_jit_site(v, locals_,
                                         chain[0] if chain else sf.tree,
                                         same_scope=True)
            base = expr_basename(v.func)
            if base:
                return _builder_return_jit(project, base, sf)
        # the decorator spelling: a def compiled in place by
        # @functools.partial(jax.jit, donate_argnums=...), called by
        # its own name
        fn_def = None
        for scope in list(chain) + [sf.tree]:
            fn_def = _local_def(scope, f.id)
            if fn_def is not None:
                break
        if fn_def is None:
            info = project.resolve_function(f.id, prefer_file=sf)
            fn_def = info.node if info is not None else None
        if fn_def is not None:
            return _decorated_donations(fn_def)
    if isinstance(f, ast.Call) and _is_jit_like(f):
        # jax.jit(body, donate_argnums=...)(operands) applied inline
        locals_ = ChainLocals(chain)
        return _resolve_jit_site(f, locals_,
                                 chain[0] if chain else sf.tree,
                                 same_scope=True)
    return None


def _donated_arg_names(call: ast.Call,
                       rec: _DonatedCallable
                       ) -> Optional[List[Tuple[int, str, str]]]:
    """(position, name, donating kwarg) for donated args that are bare
    Names — positional operands at donate_argnums positions or at
    positions whose param is in donate_argnames, plus keyword operands
    matching a donated name (position -1); the kwarg records WHICH
    spelling donated, so the finding's fix guidance names a keyword
    that actually exists at the jit site.  None when the call's
    positional layout is not statically mappable (starred operands)."""
    if any(isinstance(a, ast.Starred) for a in call.args):
        return None
    out = []
    for i, a in enumerate(call.args):
        pname = rec.params[i] if i < len(rec.params) else None
        if not isinstance(a, ast.Name):
            continue
        if i in rec.positions:
            out.append((i, a.id, "donate_argnums"))
        elif pname is not None and pname in rec.names:
            out.append((i, a.id, "donate_argnames"))
    for k in call.keywords:
        if k.arg and k.arg in rec.names and isinstance(k.value, ast.Name):
            out.append((-1, k.value.id, "donate_argnames"))
    return out


def _name_events(fn: ast.AST, skip_subtree: ast.AST):
    """(lineno, is_store, name, node) Name events in `fn`'s own scope,
    excluding the donated call's own subtree (its args are loads too)
    and DEFERRED closures (lambda / generator-expression bodies are
    late-binding: they run after any later rebind, so their reads are
    not straight-line reads — skip, never guess; eager list/set/dict
    comprehensions stay in)."""
    skip = {id(n) for n in ast.walk(skip_subtree)}
    for n in walk_own_scope(fn):
        if isinstance(n, (ast.Lambda, ast.GeneratorExp)):
            skip.update(id(x) for x in ast.walk(n))
    events = []
    for n in walk_own_scope(fn):
        if isinstance(n, ast.Name) and id(n) not in skip:
            events.append((n.lineno, not isinstance(n.ctx, ast.Load),
                           n.id, n))
    return events


def _parent_map(root: ast.AST) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
    return out


_BLOCK_FIELDS = ("body", "orelse", "finalbody")


def _stmt_of(node: ast.AST, parents: Dict[int, ast.AST]) -> ast.AST:
    """The statement holding `node`: the first ancestor (or node
    itself) sitting in a block list of its parent — the unit whose
    RHS-before-LHS evaluation order the revival check must respect."""
    cur = node
    while True:
        p = parents.get(id(cur))
        if p is None:
            return cur
        for field in _BLOCK_FIELDS:
            stmts = getattr(p, field, None)
            if isinstance(stmts, list) and cur in stmts:
                return cur
        cur = p


def _post_call_region(enclosing: ast.AST, call: ast.Call,
                      parents: Dict[int, ast.AST]) -> Set[int]:
    """ids of nodes PROVABLY executed after the donating call ran: the
    suffix of the call statement's own block, ascending only through
    always-executed containers (``with`` bodies, ``try`` finalbodies).
    A read past an ``if`` arm holding the call, past a
    maybe-zero-iteration loop, or in a sibling except handler cannot
    be proven to follow the donation — skip, never guess (the
    fast-path/fallback idiom must stay clean)."""
    region: Set[int] = set()
    cur: ast.AST = call
    while cur is not enclosing:
        p = parents.get(id(cur))
        if p is None:
            break
        in_block = False
        through = False
        for field in _BLOCK_FIELDS:
            stmts = getattr(p, field, None)
            if isinstance(stmts, list) and cur in stmts:
                for s in stmts[stmts.index(cur) + 1:]:
                    region.update(id(x) for x in ast.walk(s))
                in_block = True
                if isinstance(p, (ast.With, ast.AsyncWith, ast.Module,
                                  ast.FunctionDef,
                                  ast.AsyncFunctionDef)) \
                        or (isinstance(p, ast.Try)
                            and (field == "finalbody"
                                 or not p.handlers)):
                    through = True
        if in_block and not through:
            break  # conditional container: later siblings can't prove
        cur = p
    return region


def _block_chain(node: ast.AST, parents: Dict[int, ast.AST]
                 ) -> List[Tuple[int, int, bool]]:
    """(block id, statement index, always-executes) triples from the
    outermost block down to `node`'s own statement — the structured-
    code position a dominance comparison needs.  ``always-executes``
    marks blocks that run unconditionally once their container
    statement is reached — ``with`` bodies, ``try`` finalbodies, and
    handler-less ``try`` bodies (an exception would propagate past any
    later read too) — so a rebind inside one still dominates reads
    past it."""
    chain: List[Tuple[int, int, bool]] = []
    cur = node
    while True:
        p = parents.get(id(cur))
        if p is None:
            break
        for field in _BLOCK_FIELDS:
            stmts = getattr(p, field, None)
            if isinstance(stmts, list) and cur in stmts:
                always = isinstance(p, (ast.With, ast.AsyncWith)) \
                    or (isinstance(p, ast.Try)
                        and (field == "finalbody"
                             or not p.handlers))
                chain.append((id(stmts), stmts.index(cur), always))
        cur = p
    return list(reversed(chain))


def _dominates(store_chain: List[Tuple[int, int, bool]],
               read_chain: List[Tuple[int, int, bool]]) -> bool:
    """True when the store's statement is GUARANTEED to have executed
    by the time control reaches the read: the chains diverge inside a
    shared block with the store earlier, and every level BELOW the
    divergence on the store's side always executes (``with`` bodies).
    A store inside an `if` arm the read is not part of does NOT
    dominate — on the arm-not-taken path the read still sees the dead
    buffer (the cold-path scenario this pass exists to catch)."""
    for d in range(min(len(store_chain), len(read_chain))):
        s_blk, s_idx, _s_alw = store_chain[d]
        r_blk, r_idx, _r_alw = read_chain[d]
        if s_blk != r_blk or s_idx > r_idx:
            return False
        if s_idx == r_idx:
            continue  # nested under the same statement: go deeper
        return all(alw for _b, _i, alw in store_chain[d + 1:])
    return False


def _target_stores(name: str, target: ast.expr) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               and not isinstance(n.ctx, ast.Load)
               for n in ast.walk(target))


def _definitely_stores(name: str, stmt: ast.stmt) -> bool:
    """True when executing `stmt` UNCONDITIONALLY rebinds `name`: a
    top-level assignment target, a ``with`` body that definitely
    stores, an if/elif/else chain storing on every path, or a ``try``
    whose finalbody does.  A store nested under a further condition
    (or inside a nested def — a local binding, not a rebind) does NOT
    count: on the path around it the donated buffer is still dead."""
    if isinstance(stmt, ast.Assign):
        return any(_target_stores(name, t) for t in stmt.targets)
    if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        return _target_stores(name, stmt.target)
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return _block_definitely_stores(name, stmt.body)
    if isinstance(stmt, ast.If):
        return _all_paths_store(stmt, name)
    if isinstance(stmt, ast.Try):
        if _block_definitely_stores(name, stmt.finalbody):
            return True
        # with no except handler, an exception propagates past the
        # read too — so a definite try-body/orelse store counts
        return not stmt.handlers and (
            _block_definitely_stores(name, stmt.body)
            or _block_definitely_stores(name, stmt.orelse))
    return False


def _block_definitely_stores(name: str, stmts: List[ast.stmt]) -> bool:
    return any(_definitely_stores(name, s) for s in stmts)


def _all_paths_store(if_node: ast.If, name: str) -> bool:
    """True when EVERY path through the if/elif/else chain rebinds
    `name`: body definitely stores AND the orelse — recursing through
    an elif — stores on all of ITS paths.  A chain with no final
    ``else`` has a fall-through path that rebinds nothing, so it
    never revives."""
    if not _block_definitely_stores(name, if_node.body):
        return False
    if not if_node.orelse:
        return False
    if len(if_node.orelse) == 1 and isinstance(if_node.orelse[0], ast.If):
        return _all_paths_store(if_node.orelse[0], name)
    return _block_definitely_stores(name, if_node.orelse)


def _check_use_after_donate(sf: SourceFile, enclosing: ast.AST,
                            func_label: str, call: ast.Call,
                            donated: List[Tuple[int, str, str]],
                            findings: List[Finding]) -> None:
    end = call.end_lineno or call.lineno
    events = _name_events(enclosing, call)
    parents = _parent_map(enclosing)
    region = _post_call_region(enclosing, call, parents)
    # a comprehension target binds nothing at function scope — never a
    # rebind; a for-loop target binds only while the loop runs, so it
    # revives reads INSIDE the loop body (chained as the body's first
    # binding) but not past a maybe-zero-iteration loop
    comp_targets: Set[int] = set()
    for_target_owner: Dict[int, ast.AST] = {}
    # an augmented assignment READS its target first: `kv_lens += 1`
    # on a donated name is itself a dead-buffer read (the rebind it
    # performs still revives LATER reads, but cannot excuse its own)
    aug_targets: Set[int] = set()
    for n in ast.walk(enclosing):
        if isinstance(n, (ast.For, ast.AsyncFor)):
            for x in ast.walk(n.target):
                for_target_owner[id(x)] = n
        elif isinstance(n, ast.comprehension):
            comp_targets.update(id(x) for x in ast.walk(n.target))
        elif isinstance(n, ast.AugAssign) \
                and isinstance(n.target, ast.Name):
            aug_targets.add(id(n.target))
    for _pos, name, via in donated:
        # a rebind DOMINATING a later read revives the name — the call
        # statement's own assign target (`x, kcl, vcl = step(...)`) and
        # a straight-line `name = ...` both count; a rebind on only ONE
        # arm of a branch does not (the arm-not-taken path still reads
        # the dead buffer — the rarely-hit-branch scenario), and a
        # store on the READ's own statement does not revive that read
        # (`caches = f(caches)` evaluates its dead RHS first).
        store_chains = []
        for ln, is_store, n, node in events:
            if not (is_store and n == name and ln >= call.lineno):
                continue
            if id(node) in comp_targets:
                continue
            s_stmt = _stmt_of(node, parents)
            owner_for = for_target_owner.get(id(node))
            if owner_for is not None:
                chain = _block_chain(owner_for, parents) \
                    + [(id(owner_for.body), -1, False)]
            else:
                chain = _block_chain(s_stmt, parents)
            store_chains.append((chain, s_stmt))
        # an if/elif/else chain rebinding the name on EVERY path
        # revives everything past it, even though no single arm's
        # store dominates alone (a chain without a final else has a
        # fall-through path and never revives)
        both_arm_ifs = [
            n for n in walk_own_scope(enclosing)
            if isinstance(n, ast.If) and n.lineno >= call.lineno
            and _all_paths_store(n, name)]
        both_arm_chains = [_block_chain(n, parents) for n in both_arm_ifs]
        for lineno, is_store, n, node in sorted(
                events, key=lambda e: (e[0], e[1], e[2])):
            is_aug_read = is_store and id(node) in aug_targets
            if (is_store and not is_aug_read) or n != name \
                    or lineno <= end:
                continue
            if id(node) not in region:
                continue  # not provably after the call: skip
            rstmt = _stmt_of(node, parents)
            rchain = _block_chain(rstmt, parents)
            if any(s_stmt is not rstmt and _dominates(schain, rchain)
                   for schain, s_stmt in store_chains):
                continue  # rebound on every path before this read
            if any(_dominates(ichain, rchain)
                   for ichain in both_arm_chains):
                continue  # both-arm rebind ahead of the read
            findings.append(Finding(
                CODE, sf.path, lineno, func_label,
                f"'{name}' was DONATED at the compile-once step call on "
                f"line {call.lineno} ({via}) and is read again "
                "here: the buffer is dead after the call — thread the "
                "returned state instead, or drop the argument from "
                f"{via}"))
            break  # one finding per donated name keeps baselines stable


def _check_captured(sf: SourceFile, func_label: str, call: ast.Call,
                    donated: List[Tuple[int, str, str]],
                    rec: _DonatedCallable,
                    findings: List[Finding]) -> None:
    if not rec.same_scope:
        return  # cross-scope name comparison would be a guess
    for _pos, name, _via in donated:
        if name in rec.body_free:
            findings.append(Finding(
                CODE, sf.path, call.lineno, func_label,
                f"'{name}' is passed at a donated position but the "
                f"jitted body (compiled at line {rec.jit_line}) ALSO "
                "closes over it: the traced closure constant aliases "
                "the donated operand — pass it as an argument only, or "
                "stop donating it"))


def _check_sharding_contract(sf: SourceFile, findings: List[Finding]
                             ) -> None:
    for n in ast.walk(sf.tree):
        if not (isinstance(n, ast.Call) and _is_jit_like(n)):
            continue
        kw = {k.arg for k in n.keywords
              if k.arg in ("in_shardings", "out_shardings")
              and not (isinstance(k.value, ast.Constant)
                       and k.value.value is None)}
        if len(kw) == 1:
            present = kw.pop()
            missing = ("out_shardings" if present == "in_shardings"
                       else "in_shardings")
            findings.append(Finding(
                CODE, sf.path, n.lineno, expr_basename(n.func),
                f"step compiled with {present}= but no {missing}= — "
                "the both-or-neither contract (parallel/plan.py "
                "compile_step_with_plan): a half-specified sharding "
                "set lets the compiler re-derive the missing side and "
                "partition the program differently than the plan says"))


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for sf in project.files:
        if sf.tree is None:
            continue
        _check_sharding_contract(sf, findings)

        def _scan(scope: ast.AST, chain: List[ast.AST],
                  cls_map: Optional[_ClassDonations],
                  label: str) -> None:
            for node in walk_own_scope(scope):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    _scan(node, [node] + chain, cls_map, node.name)
                elif isinstance(node, ast.ClassDef):
                    _scan(node, chain, _ClassDonations(node), label)
                elif isinstance(node, ast.Call):
                    rec = _call_site_donations(project, sf, node, chain,
                                               cls_map)
                    if rec is None:
                        continue
                    donated = _donated_arg_names(node, rec)
                    if not donated:
                        continue  # starred/keyword layout: skip
                    enclosing = chain[0] if chain else sf.tree
                    _check_use_after_donate(sf, enclosing, label, node,
                                            donated, findings)
                    _check_captured(sf, label, node, donated, rec,
                                    findings)

        _scan(sf.tree, [], None, "<module>")
    return findings
