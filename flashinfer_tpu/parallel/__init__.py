"""Sequence/context parallelism: Ulysses and Ring attention.

TPU-native re-design of the reference's ``flashinfer/parallel_attention/``
(ParallelAttention parallel_attention.py:12-62; all-to-all wrapper
parallel_wrapper.py:10; ring P2P parallel_wrapper.py:216-242) and of the
decode-context-parallel path (``flashinfer/comm/dcp_alltoall.py``).
"""

from flashinfer_tpu.parallel.attention import (  # noqa: F401
    ParallelAttention,
    ring_attention,
    ulysses_attention,
)
from flashinfer_tpu.parallel.dcp import dcp_decode  # noqa: F401
