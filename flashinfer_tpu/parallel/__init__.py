"""Sequence/context parallelism + the mesh-aware sharded serving step.

TPU-native re-design of the reference's ``flashinfer/parallel_attention/``
(ParallelAttention parallel_attention.py:12-62; all-to-all wrapper
parallel_wrapper.py:10; ring P2P parallel_wrapper.py:216-242) and of the
decode-context-parallel path (``flashinfer/comm/dcp_alltoall.py``).

``parallel/plan.py`` adds the compile-once SHARDED serving step
(ROADMAP item 3): :class:`ShardingPlan` (mesh + dp/tp/ep axes ->
NamedShardings per serving-state leaf), :func:`compile_step_with_plan`
(explicit shardings + donation), and the sharded fused / per-op step
builders with a shard_map fallback.
"""

from flashinfer_tpu.parallel.attention import (  # noqa: F401
    ParallelAttention,
    ring_attention,
    ulysses_attention,
)
from flashinfer_tpu.parallel.dcp import dcp_decode  # noqa: F401
from flashinfer_tpu.parallel.plan import (  # noqa: F401
    ShardedServingStep,
    ShardingPlan,
    build_sharded_fused_step,
    build_sharded_per_op_step,
    compile_step_with_plan,
    llama_step_shardings,
    make_serving_mesh,
    plan_axes,
    sharded_step_body,
    split_shard_weights_for_spec,
    validate_dp_page_table,
)
