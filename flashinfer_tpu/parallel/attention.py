"""Ulysses (all-to-all over heads) and Ring (KV-rotation) attention.

Re-design of the reference SP layer (``flashinfer/parallel_attention/``):

- **Ulysses** (parallel_wrapper.py:10 ``all_to_all``): sequence-sharded
  activations are all-to-all'd so each rank holds *all* tokens of a *subset
  of heads*, attention runs locally, then the inverse all-to-all restores
  sequence sharding.  The reference builds this from NCCL all-to-all; here
  it is ``jax.lax.all_to_all`` over a mesh axis — XLA lowers it onto ICI.

- **Ring** (parallel_wrapper.py:216-242): KV chunks rotate around the ring
  (``jax.lax.ppermute``) while each rank accumulates partial attention
  states, merged with the online-softmax LSE algebra from ops/merge.py —
  the same attention-state math the reference uses
  (recursive_attention.rst).  O(seq) memory per rank; the long-context
  workhorse.

Both are *per-shard* functions to call inside ``shard_map`` with the
context-parallel axis in scope; ``ParallelAttention`` packages the
shard_map for convenience (mirroring the reference's wrapper class).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flashinfer_tpu.ops.flash_attention import flash_attention
from flashinfer_tpu.ops.merge import merge_state
from flashinfer_tpu.ops.xla_ref import xla_ragged_attention
from flashinfer_tpu.utils import (get_sm_scale, is_tpu, jax_shard_map,
                                  lax_axis_size)


def _attn(q, k, v, q_pos, kv_pos, *, causal, sm_scale, use_pallas):
    """Local attention chunk -> (out, lse); positions carry global offsets."""
    T, S = q.shape[0], k.shape[0]
    seg_q = jnp.zeros((T,), jnp.int32)
    seg_kv = jnp.zeros((S,), jnp.int32)
    fn = flash_attention if use_pallas else xla_ragged_attention
    return fn(
        q, k, v, seg_q, seg_kv, q_pos, kv_pos,
        causal=causal, sm_scale=sm_scale, return_lse=True,
    )


def ulysses_attention(
    q: jax.Array,  # [seq_local, num_qo_heads, head_dim]
    k: jax.Array,  # [seq_local, num_kv_heads, head_dim]
    v: jax.Array,
    axis: str = "cp",
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """All-to-all sequence parallel attention (call inside shard_map).

    Requires num heads divisible by the axis size."""
    cp = lax_axis_size(axis)
    if q.shape[1] % cp or k.shape[1] % cp:
        raise ValueError(
            f"ulysses needs qo heads ({q.shape[1]}) and kv heads "
            f"({k.shape[1]}) divisible by the {axis!r} axis size {cp}; "
            "use ring attention (mode='ring') for GQA head counts below "
            "the axis size"
        )
    sm_scale = get_sm_scale(q.shape[-1], sm_scale)
    # [seq/cp, H, D] -> [seq, H/cp, D]
    qg = jax.lax.all_to_all(q, axis, split_axis=1, concat_axis=0, tiled=True)
    kg = jax.lax.all_to_all(k, axis, split_axis=1, concat_axis=0, tiled=True)
    vg = jax.lax.all_to_all(v, axis, split_axis=1, concat_axis=0, tiled=True)
    seq = qg.shape[0]
    pos = jnp.arange(seq, dtype=jnp.int32)
    fn = flash_attention if is_tpu() else xla_ragged_attention
    out = fn(
        qg, kg, vg,
        jnp.zeros((seq,), jnp.int32), jnp.zeros((seq,), jnp.int32), pos, pos,
        causal=causal, sm_scale=sm_scale,
    )
    # [seq, H/cp, D] -> [seq/cp, H, D]
    return jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=1, tiled=True)


def ring_attention(
    q: jax.Array,  # [chunk, num_qo_heads, head_dim]  (this rank's seq chunk)
    k: jax.Array,  # [chunk, num_kv_heads, head_dim]
    v: jax.Array,
    axis: str = "cp",
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention with LSE-merged partials (call inside shard_map).

    Sequence is chunked contiguously: rank r holds tokens
    ``[r*chunk, (r+1)*chunk)``.  Each of the cp steps computes a partial
    against the currently-held KV chunk and rotates KV to the next rank
    (bidirectional-bandwidth zigzag scheduling is a later optimization)."""
    cp = lax_axis_size(axis)
    me = jax.lax.axis_index(axis)
    chunk = q.shape[0]
    sm_scale = get_sm_scale(q.shape[-1], sm_scale)
    use_pallas = is_tpu()
    q_pos = me * chunk + jnp.arange(chunk, dtype=jnp.int32)

    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def step(carry, i):
        k_cur, v_cur, acc, lse = carry
        src = jax.lax.rem(me - i + cp, cp)  # owner of the current kv chunk
        kv_pos = src * chunk + jnp.arange(chunk, dtype=jnp.int32)
        o_i, lse_i = _attn(
            q, k_cur, v_cur, q_pos, kv_pos,
            causal=causal, sm_scale=sm_scale, use_pallas=use_pallas,
        )
        acc, lse = merge_state(acc, lse, o_i, lse_i)
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return (k_nxt, v_nxt, acc, lse), None

    # carry the merge accumulator in f32 across all cp steps (casting to
    # q.dtype per step would re-round to bf16 each rotation and degrade
    # precision with cp size); single cast on exit
    acc0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full((chunk, q.shape[1]), -1e30, jnp.float32)
    (k_f, v_f, acc, lse), _ = jax.lax.scan(
        step, (k, v, acc0, lse0), jnp.arange(cp)
    )
    return acc.astype(q.dtype)


class ParallelAttention:
    """Mesh-packaged SP attention (mirrors reference ``ParallelAttention``,
    parallel_attention.py:12): pick ``mode="ulysses"`` or ``"ring"``, get a
    jitted callable over sequence-sharded [seq, H, D] global arrays."""

    def __init__(
        self,
        mesh,
        axis: str = "cp",
        mode: str = "ulysses",
        causal: bool = False,
        sm_scale: Optional[float] = None,
    ):
        if mode not in ("ulysses", "ring"):
            raise ValueError(f"unknown parallel attention mode {mode!r}")
        self.mesh = mesh
        self.axis = axis
        self.mode = mode
        fn = ulysses_attention if mode == "ulysses" else ring_attention

        def local(q, k, v):
            return fn(q, k, v, axis, causal=causal, sm_scale=sm_scale)

        spec = P(axis, None, None)
        self._call = jax.jit(
            jax_shard_map(
                local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False,
            )
        )

    def run(self, q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        return self._call(q, k, v)

    __call__ = run
