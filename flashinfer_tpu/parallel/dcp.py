"""Decode context parallelism (DCP): KV sharded across ranks at decode time.

Re-design of the reference DCP path (``flashinfer/comm/dcp_alltoall.py:67-227``
+ ``csrc/trtllm_dcp_alltoall.cu``): each rank holds a shard of every
request's KV pages, computes a partial decode attention with LSE, and the
partials are combined.  The reference exchanges partials with a custom
all-to-all over MNNVL; here the combine is an ``all_gather`` of the
(state, lse) pair over the cp axis followed by the merge-states reduction —
XLA turns this into one fused ICI collective + elementwise pass.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from flashinfer_tpu.ops.merge import merge_states
from flashinfer_tpu.ops.paged_decode import paged_decode_attention
from flashinfer_tpu.ops.xla_ref import xla_paged_decode
from flashinfer_tpu.utils import get_sm_scale, is_tpu


def dcp_decode(
    q: jax.Array,  # [batch, num_qo_heads, head_dim] (replicated over cp)
    k_cache: jax.Array,  # this rank's page shard
    v_cache: jax.Array,
    page_table: jax.Array,  # [batch, P_local] this rank's pages per request
    kv_lens: jax.Array,  # [batch] this rank's share of each request's kv len
    axis: str = "cp",
    *,
    sm_scale: Optional[float] = None,
    kv_layout: str = "HND",
) -> jax.Array:
    """Per-shard decode + cross-rank LSE merge (call inside shard_map)."""
    sm_scale = get_sm_scale(q.shape[-1], sm_scale)
    fn = paged_decode_attention if is_tpu() else xla_paged_decode
    out, lse = fn(
        q, k_cache, v_cache, page_table, kv_lens,
        sm_scale=sm_scale, kv_layout=kv_layout, return_lse=True,
    )
    # gather all ranks' partial states: [cp, batch, H, D] / [cp, batch, H]
    outs = jax.lax.all_gather(out, axis)
    lses = jax.lax.all_gather(lse, axis)
    # merge over the cp axis per (batch, head)
    merged, _ = merge_states(
        jnp.moveaxis(outs, 0, 1), jnp.moveaxis(lses, 0, 1)
    )
    return merged.astype(q.dtype)
