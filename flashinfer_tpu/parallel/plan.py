"""Mesh-aware compile-once SHARDED serving step (pjit/shard_map unification).

The multi-chip half of the plan/run story (ROADMAP item 3; reference
analogue: one captured multi-GPU program instead of a Python loop over
per-op sharded calls).  ``serve/`` compiled the whole serving step into
ONE donated XLA program on one chip (PR 7); the per-op parallel layer
(``parallel/attention.py`` Ulysses/Ring, ``fused_moe_ep``, ``comm/``
fusions) already speaks mesh axes — what was missing is the Titanax
``compile_step_with_plan`` pattern (SNIPPETS.md [2]): a :class:`ShardingPlan`
that derives explicit ``NamedSharding``s for every serving-state leaf,
and one compile entry that lowers the WHOLE sharded step under the mesh
with explicit in/out shardings and donated KV buffers.

Components:

- :class:`ShardingPlan` — a ``jax.sharding.Mesh`` plus named (dp, tp,
  ep) axes, and the sharding table for every serving-state leaf:
  replicated small state (norms, scales of row-sharded linears, PRNG
  key), TP-sharded weights/heads (column-shard q/k/v/gate/up/lm_head,
  row-shard o/down, KV-head axis of the paged caches), DP-sharded batch
  state (activations, page tables, kv_lens, the page axis of the caches).
- :func:`compile_step_with_plan` — explicit in/out shardings + donation
  in one ``jax.jit``; both-or-neither sharding contract (the Titanax
  rule), degenerating to a plain donated jit when no shardings are given.
- :class:`ShardedServingStep` / :func:`build_sharded_fused_step` — the
  bench 70B-shard int8 pipeline (``serve/shard.py``) at GLOBAL model
  dims compiled ONCE under the mesh: ``mode="pjit"`` traces the global
  math and lets GSPMD partition it along the plan's shardings;
  ``mode="shard_map"`` is the explicit-collective fallback (per-device
  body, int32-psum TP reductions, pmax-amax activation quantization,
  logits all-gather epilogue) that is parity-tested against pjit.
- :func:`build_sharded_per_op_step` — the SAME math as per-layer jitted
  sharded calls chained by a host loop: the pre-fused dispatch
  structure, the A/B twin ``bench.py phase_serving_sharded`` measures.
- :func:`llama_step_shardings` — the sharding table for
  ``serve/step.py``'s :class:`~flashinfer_tpu.serve.step.ServingStep`
  state (the Llama pytree), so ``ServingStep.plan(sharding_plan=...)``
  compiles the model-family mega-step under a mesh too.

Numerics contract (pinned by tests/test_sharded_step.py): the int8
shard pipeline's TP reductions accumulate in int32 (order-free), so
fused-sharded, per-op-sharded, shard_map, and the unsharded
``serve/shard.py`` step sample token-for-token identical sequences.
The bf16 :class:`ServingStep` under a tp>1 plan reorders f32 partial
sums across the contraction split (documented tolerance); dp-only
sharding never moves a contraction and stays tokens-bitwise.

DP paged-KV contract: the page axis of every cache shards over dp, so
all pages of a request must live in its dp block —
``page_table[b] // (num_pages // dp) == b // (batch // dp)`` for every
entry (:func:`validate_dp_page_table`; the per-replica block-pool
layout a dp-sharded serving engine allocates naturally).

Everything here is testable off-hardware on a CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``); the
predicted multi-chip performance story lives in the ICI-aware cost
model (``obs/costmodel.py`` collective family + ``obs perf``'s
tp1->tp8 scaling curve).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from flashinfer_tpu.api_logging import flashinfer_api
from flashinfer_tpu.serve.shard import Int8ShardSpec


# ---------------------------------------------------------------------------
# The plan: mesh + named axes -> NamedShardings per serving-state leaf
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """A mesh plus the serving axis names.

    ``dp`` shards the batch (requests, page pools), ``tp`` the heads /
    hidden projections / vocab, ``ep`` (optional; MoE steps) the expert
    axis.  Axes named here must exist in the mesh; absent ``ep`` means
    experts replicate (or fold into tp, the ``fused_moe_ep`` default)."""

    mesh: Mesh
    dp: str = "dp"
    tp: str = "tp"
    ep: Optional[str] = None

    def __post_init__(self):
        names = tuple(self.mesh.axis_names)
        for axis in (self.dp, self.tp) + ((self.ep,) if self.ep else ()):
            if axis not in names:
                raise ValueError(
                    f"axis {axis!r} not in mesh axes {names}; a "
                    "ShardingPlan names only axes its mesh carries")

    # ---- sizes / identity -----------------------------------------------
    def _axis_size(self, axis: Optional[str]) -> int:
        if axis is None:
            return 1
        return self.mesh.shape[axis]

    @property
    def dp_size(self) -> int:
        return self._axis_size(self.dp)

    @property
    def tp_size(self) -> int:
        return self._axis_size(self.tp)

    @property
    def ep_size(self) -> int:
        return self._axis_size(self.ep)

    @property
    def mesh_axes(self) -> str:
        """The row-identity string bench rows carry (``"dp2.tp4"``):
        mesh SHAPE is configuration, so a tp8 row must never compete
        with tp1 history in the quality audit (obs.bench_audit)."""
        parts = [f"dp{self.dp_size}", f"tp{self.tp_size}"]
        if self.ep:
            parts.append(f"ep{self.ep_size}")
        return ".".join(parts)

    # ---- shardings -------------------------------------------------------
    def named(self, *axes) -> NamedSharding:
        """NamedSharding over this plan's mesh (axes as in a
        PartitionSpec: strings, None, or nothing for replicated)."""
        return NamedSharding(self.mesh, P(*axes))

    @property
    def replicated(self) -> NamedSharding:
        return self.named()

    def cache_sharding(self) -> NamedSharding:
        """Paged KV cache [num_pages, kv_heads, page_size, head_dim]:
        page pool over dp (per-replica block pools), KV heads over tp."""
        return self.named(self.dp, self.tp, None, None)

    def shard_layer_shardings(self) -> Dict[str, NamedSharding]:
        """Sharding per leaf of one decoder layer's weight dict (the
        :func:`split_shard_weights` format): column-shard q/k/v/gate/up
        (+ their per-output-channel scales), row-shard o/down (whose
        scales span the full out dim and replicate), replicate norms."""
        col = self.named(None, self.tp)
        row = self.named(self.tp, None)
        repl2 = self.named(None, None)
        repl1 = self.named(None)
        return dict(
            q_proj=col, q_scale=col, k_proj=col, k_scale=col,
            v_proj=col, v_scale=col,
            o_proj=row, o_scale=repl2,
            gate_proj=col, gate_scale=col, up_proj=col, up_scale=col,
            down_proj=row, down_scale=repl2,
            input_norm=repl1, post_norm=repl1,
        )

    def shard_step_shardings(self, num_layers: int):
        """(in_shardings, out_shardings) for the sharded shard-pipeline
        step signature ``(x0, layer_ws, caches, head, head_s, pt, lens,
        skey) -> (tok, caches, pt, lens, skey)``.  Sampled tokens come
        back REPLICATED (the epilogue gathers the vocab-sharded logits
        so every device samples the same tokens)."""
        layer = self.shard_layer_shardings()
        cache = self.cache_sharding()
        in_sh = (
            self.named(self.dp, None),            # x0 [bs, hidden]
            [dict(layer) for _ in range(num_layers)],
            [(cache, cache) for _ in range(num_layers)],
            self.named(None, self.tp),            # head [hidden, vocab]
            self.named(None, self.tp),            # head_s [1, vocab]
            self.named(self.dp, None),            # page_table [bs, ppr]
            self.named(self.dp),                  # kv_lens [bs]
            self.replicated,                      # PRNG key
        )
        out_sh = (
            self.replicated,                      # tokens [bs]
            [(cache, cache) for _ in range(num_layers)],
            self.named(self.dp, None),
            self.named(self.dp),
            self.replicated,
        )
        return in_sh, out_sh

    def spec_tree(self, shardings):
        """The PartitionSpec pytree of a NamedSharding pytree (the
        shard_map in_specs/out_specs form of the same table)."""
        return jax.tree_util.tree_map(
            lambda s: s.spec, shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding))


def shard_check(spec: Int8ShardSpec, plan: ShardingPlan) -> None:
    """Divisibility contract of the GLOBAL-dims spec against the mesh:
    heads/inter/vocab over tp, batch over dp."""
    bad = []
    if spec.hq % plan.tp_size:
        bad.append(f"hq {spec.hq} % tp {plan.tp_size}")
    if spec.hkv % plan.tp_size:
        bad.append(f"hkv {spec.hkv} % tp {plan.tp_size}")
    if spec.inter % plan.tp_size:
        bad.append(f"inter {spec.inter} % tp {plan.tp_size}")
    if spec.vocab_shard % plan.tp_size:
        bad.append(f"vocab {spec.vocab_shard} % tp {plan.tp_size}")
    if spec.bs % plan.dp_size:
        bad.append(f"bs {spec.bs} % dp {plan.dp_size}")
    if bad:
        raise ValueError(
            "spec does not tile the mesh: " + ", ".join(bad))


def validate_dp_page_table(page_table, num_pages: int,
                           plan: ShardingPlan) -> None:
    """Host-side check of the DP paged-KV contract: request b's pages
    must all live in b's dp block of the page pool (each dp replica owns
    a contiguous ``num_pages // dp`` slab).  Raises with the offending
    request; a violated contract would silently read another replica's
    pages under ``mode="shard_map"``."""
    pt = np.asarray(page_table)
    dp = plan.dp_size
    if dp == 1:
        return
    bs = pt.shape[0]
    if bs % dp or num_pages % dp:
        raise ValueError(
            f"batch {bs} / num_pages {num_pages} must divide dp {dp}")
    bs_l, pages_l = bs // dp, num_pages // dp
    blocks = pt // pages_l
    want = np.repeat(np.arange(dp), bs_l)[:, None]
    if not np.array_equal(blocks, np.broadcast_to(want, pt.shape)):
        b = int(np.argwhere(blocks != want)[0][0])
        raise ValueError(
            f"request {b}'s pages leave its dp block (block ids "
            f"{sorted(set(blocks[b].tolist()))}, expected "
            f"{int(want[b, 0])}): a dp-sharded page pool allocates each "
            "replica's requests from its own page slab")


def split_shard_weights_for_spec(layer_ws,
                                 spec: Int8ShardSpec
                                 ) -> List[Dict[str, jax.Array]]:
    """Convert ``serve/shard.py``'s fused per-layer 10-tuples
    ``(wqkv, sqkv, wo, so, wgu, sgu, wd, sd, n1, n2)`` into the named
    per-projection dict the sharded step shards (the spec names the
    column boundaries: fused [q | k | v] and [gate | up] blocks split
    apart so each projection's columns tile over tp as whole heads).
    Column-exact: ``mm(x, concat(a, b)) == concat(mm(x, a), mm(x, b))``,
    so the split changes no numerics."""
    qdim, kvdim, inter = spec.qdim, spec.kvdim, spec.inter
    out = []
    for wqkv, sqkv, wo, so, wgu, sgu, wd, sd, n1, n2 in layer_ws:
        out.append(dict(
            q_proj=wqkv[:, :qdim], q_scale=sqkv[:, :qdim],
            k_proj=wqkv[:, qdim:qdim + kvdim],
            k_scale=sqkv[:, qdim:qdim + kvdim],
            v_proj=wqkv[:, qdim + kvdim:],
            v_scale=sqkv[:, qdim + kvdim:],
            o_proj=wo, o_scale=so,
            gate_proj=wgu[:, :inter], gate_scale=sgu[:, :inter],
            up_proj=wgu[:, inter:], up_scale=sgu[:, inter:],
            down_proj=wd, down_scale=sd,
            input_norm=n1, post_norm=n2,
        ))
    return out


# ---------------------------------------------------------------------------
# compile_step_with_plan: the Titanax entry
# ---------------------------------------------------------------------------


def compile_step_with_plan(fn, plan: Optional[ShardingPlan] = None, *,
                           in_shardings=None, out_shardings=None,
                           donate_argnums=(), static_argnums=()):
    """Compile one serving-step body under explicit shardings + donation.

    The SNIPPETS.md [2] contract: ``in_shardings`` and ``out_shardings``
    come together or not at all — a half-specified sharding set silently
    compiles a differently-partitioned program, so it raises instead.
    With both absent the step compiles as a plain donated ``jax.jit``
    (the single-device degenerate; ``plan`` may be None there).  The
    shard_map fallback is not spelled here — it needs a per-device body
    with explicit collectives, which :func:`build_sharded_fused_step`
    provides via ``mode="shard_map"``."""
    if (in_shardings is None) != (out_shardings is None):
        raise ValueError(
            "compile_step_with_plan needs BOTH in_shardings and "
            "out_shardings (or neither, for the single-device jit): a "
            "half-specified set would let the compiler re-derive the "
            "missing side and split the program differently than the "
            "plan says")
    kw = dict(donate_argnums=donate_argnums, static_argnums=static_argnums)
    if in_shardings is None:
        return jax.jit(fn, **kw)
    return jax.jit(fn, in_shardings=in_shardings,
                   out_shardings=out_shardings, **kw)


# ---------------------------------------------------------------------------
# The sharded shard-pipeline step bodies
# ---------------------------------------------------------------------------


class _GlobalComm:
    """Global-math strategy (the pjit path): no explicit collectives —
    the traced body is the whole-model math and GSPMD partitions it
    along the plan's shardings."""

    def __init__(self, spec: Int8ShardSpec,
                 plan: Optional[ShardingPlan] = None):
        self.spec = spec
        self.plan = plan
        self.hq_l, self.hkv_l = spec.hq, spec.hkv
        self.qdim_l = spec.qdim

    def local_pages(self, pt, kcl):
        return pt

    def quantize_tp(self, x):
        from flashinfer_tpu.quantization import quantize_int8

        return quantize_int8(x)

    def mm_row(self, a8, w, name, scale_name, a_scale):
        from flashinfer_tpu.gemm import mm_int8

        return mm_int8(a8, w[name], a_scale, w[scale_name])

    def gather_logits(self, logits):
        # replicate BEFORE sampling: this jax's threefry is not
        # partitionable (jax_threefry_partitionable=False), so random
        # bits generated over a sharded operand differ from the
        # unsharded stream — the constraint forces the gather here
        # (where the shard_map fallback gathers anyway) and keeps every
        # step shape tokens-identical with the single-chip pipeline
        if self.plan is not None:
            return jax.lax.with_sharding_constraint(
                logits, self.plan.replicated)
        return logits

    def pin_tokens(self, tok):
        # fence the sampler from the BACK side too: a sharded consumer
        # of the tokens would let GSPMD back-propagate its sharding
        # into the RNG (the serve/step.py threefry note)
        if self.plan is not None:
            return jax.lax.with_sharding_constraint(
                tok, self.plan.replicated)
        return tok

    def first_token(self, tok):
        return tok[0]


class _ShardMapComm:
    """Per-device strategy (the shard_map fallback): explicit
    collectives spelled to land bit-identically with the partitioned
    global program — TP matmul reductions psum in int32 BEFORE the f32
    scale multiply (integer addition is order-free), activation
    quantization pmaxes the local amax so every shard applies the
    global scale, and the sampling epilogue all-gathers the
    vocab/batch-sharded logits so every device samples the same
    tokens."""

    def __init__(self, spec: Int8ShardSpec, plan: ShardingPlan):
        self.spec = spec
        self.plan = plan
        self.hq_l = spec.hq // plan.tp_size
        self.hkv_l = spec.hkv // plan.tp_size
        self.qdim_l = self.hq_l * spec.hd

    def local_pages(self, pt, kcl):
        # global page ids -> this dp shard's slab-local ids (the
        # validate_dp_page_table contract); kcl is the LOCAL cache
        # shard, so its page axis is the slab length
        if self.plan.dp_size == 1:
            return pt
        rank = jax.lax.axis_index(self.plan.dp)
        return pt - rank * kcl.shape[0]

    def quantize_tp(self, x):
        amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        amax = jax.lax.pmax(amax, self.plan.tp)
        scale = jnp.maximum(amax / 127.0, 1e-12)
        q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                     -127, 127).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    def mm_row(self, a8, w, name, scale_name, a_scale):
        acc = jnp.dot(a8, w[name], preferred_element_type=jnp.int32)
        acc = jax.lax.psum(acc, self.plan.tp)
        out = (acc.astype(jnp.float32) * a_scale
               * jnp.asarray(w[scale_name], jnp.float32))
        return out.astype(jnp.bfloat16)

    def gather_logits(self, logits):
        full = jax.lax.all_gather(logits, self.plan.tp, axis=1, tiled=True)
        if self.plan.dp_size > 1:
            full = jax.lax.all_gather(full, self.plan.dp, axis=0,
                                      tiled=True)
        return full

    def pin_tokens(self, tok):
        return tok  # per-device body: the gather already replicated

    def first_token(self, tok):
        return tok[0]


def _sharded_layer(x, w: Dict[str, jax.Array], kcl, vcl, pt, lens,
                   spec: Int8ShardSpec, comm):
    """One decoder layer of the int8 shard pipeline over split-named
    weights — the same math as ``serve/shard.py shard_layer`` (paged
    int8-KV append included), with the TP-sensitive steps routed
    through the `comm` strategy."""
    from flashinfer_tpu.activation import silu_and_mul
    from flashinfer_tpu.gemm import mm_int8
    from flashinfer_tpu.norm import rmsnorm
    from flashinfer_tpu.ops import paged_decode_attention
    from flashinfer_tpu.ops.xla_ref import xla_paged_decode
    from flashinfer_tpu.quantization import quantize_int8
    from flashinfer_tpu.rope import apply_rope_pos_ids

    bs = x.shape[0]
    PS = spec.page_size
    h = rmsnorm(x, w["input_norm"].astype(x.dtype))
    hq8, hs = quantize_int8(h)  # rows span the (unsharded) hidden axis
    q = mm_int8(hq8, w["q_proj"], hs, w["q_scale"]) \
        .reshape(bs, comm.hq_l, spec.hd)
    k = mm_int8(hq8, w["k_proj"], hs, w["k_scale"]) \
        .reshape(bs, comm.hkv_l, spec.hd)
    v = mm_int8(hq8, w["v_proj"], hs, w["v_scale"]) \
        .reshape(bs, comm.hkv_l, spec.hd)
    q, k = apply_rope_pos_ids(q, k, lens)
    pt_l = comm.local_pages(pt, kcl)
    pages = jnp.take_along_axis(pt_l, lens[:, None] // PS, axis=1)[:, 0]
    slots = lens % PS
    k8 = jnp.clip(jnp.round(k.astype(jnp.float32) / spec.k_scale),
                  -127, 127).astype(jnp.int8)
    v8 = jnp.clip(jnp.round(v.astype(jnp.float32) / spec.v_scale),
                  -127, 127).astype(jnp.int8)
    kcl = kcl.at[pages, :, slots, :].set(k8)
    vcl = vcl.at[pages, :, slots, :].set(v8)
    attn_fn = paged_decode_attention if spec.use_pallas \
        else xla_paged_decode
    attn = attn_fn(
        q.astype(jnp.bfloat16), kcl, vcl, pt_l, lens + 1,
        sm_scale=spec.hd ** -0.5 * spec.k_scale, kv_layout="HND",
    ) * spec.v_scale
    a8, as_ = comm.quantize_tp(
        attn.reshape(bs, comm.qdim_l).astype(x.dtype))
    x = x + comm.mm_row(a8, w, "o_proj", "o_scale", as_)
    h2 = rmsnorm(x, w["post_norm"].astype(x.dtype))
    g8, gs = quantize_int8(h2)
    mlp = silu_and_mul(jnp.concatenate(
        [mm_int8(g8, w["gate_proj"], gs, w["gate_scale"]),
         mm_int8(g8, w["up_proj"], gs, w["up_scale"])], -1))
    m8, ms = comm.quantize_tp(mlp)
    x = (x + comm.mm_row(m8, w, "down_proj", "down_scale", ms)) \
        .astype(x.dtype)
    return x, kcl, vcl


def _sharded_epilogue(x, head, head_s, skey, spec: Int8ShardSpec, comm):
    """lm_head shard + top-k sampling over the gathered logits — the
    ``serve/shard.py head_and_sample`` math; every device ends with the
    same tokens and the same folded key."""
    from flashinfer_tpu.gemm import mm_int8
    from flashinfer_tpu.norm import rmsnorm
    from flashinfer_tpu.quantization import quantize_int8
    from flashinfer_tpu.sampling import (sampling_from_logits,
                                         top_k_mask_logits)

    hq8, hs = quantize_int8(
        rmsnorm(x, jnp.ones((spec.hidden,), x.dtype)))
    logits = mm_int8(hq8, head, hs, head_s, out_dtype=jnp.float32)
    logits = comm.gather_logits(logits)
    tok = sampling_from_logits(top_k_mask_logits(logits, spec.top_k),
                               skey)
    tok = comm.pin_tokens(tok)
    return tok, jax.random.fold_in(skey, comm.first_token(tok))


def _step_math(x0, layer_ws, caches, head, head_s, pt, lens, skey,
               spec: Int8ShardSpec, comm):
    """One whole serving step (layers + sampling epilogue) — the body
    every builder here compiles (fused, per-op chains per-layer slices
    of it, and the bench's in-jit scan slope floor)."""
    x = x0
    new_caches = []
    for w, (kcl, vcl) in zip(layer_ws, caches):
        x, kcl, vcl = _sharded_layer(x, w, kcl, vcl, pt, lens, spec,
                                     comm)
        new_caches.append((kcl, vcl))
    tok, skey = _sharded_epilogue(x, head, head_s, skey, spec, comm)
    return tok, new_caches, pt, lens, skey


def sharded_step_body(spec: Int8ShardSpec, plan: ShardingPlan):
    """The UNJITTED global-math step body ``(x0, layer_ws, caches,
    head, head_s, pt, lens, skey) -> (tok, caches, pt, lens, skey)`` —
    for custom compositions like bench.py's in-jit ``lax.scan`` slope
    floor (the zero-host-dispatch steady state both A/B variants
    chase).  :func:`build_sharded_fused_step` compiles exactly this
    math."""
    comm = _GlobalComm(spec, plan)

    def body(x0, layer_ws, caches, head, head_s, pt, lens, skey):
        return _step_math(x0, layer_ws, caches, head, head_s, pt, lens,
                          skey, spec, comm)

    return body


class _CountingStep:
    """A compiled step that counts its own traces (the compile-once
    pin's instrument; mirrors serve/step.py's body-side counter)."""

    def __init__(self, fn, build):
        self.num_traces = 0
        self._fn = build(self._tick, fn)

    def _tick(self):
        self.num_traces += 1

    @property
    def jitted(self):
        """The underlying jitted callable (for .lower() inspection —
        the donation-aliasing pin in tests)."""
        return self._fn

    def __call__(self, *args):
        return self._fn(*args)


def build_sharded_fused_step(spec: Int8ShardSpec, plan: ShardingPlan, *,
                             num_layers: Optional[int] = None,
                             donate: bool = True, mode: str = "pjit"):
    """The compile-once SHARDED shard step: ONE XLA program per serving
    step over the whole mesh.

    ``spec`` carries GLOBAL model dims (the whole 70B, not the per-chip
    shard); the plan's shardings slice it per device.  Signature is
    ``serve/shard.py build_fused_step``'s with split-named layer dicts
    (:func:`split_shard_weights_for_spec`): ``step(x0, layer_ws, caches,
    head, head_s, pt, lens, skey) -> (tok, caches, pt, lens, skey)``;
    caches / page table / lens / PRNG key are donated.

    ``mode="pjit"`` (default): global math + explicit in/out shardings
    (GSPMD inserts the collectives).  ``mode="shard_map"``: the
    explicit-collective per-device fallback, numerics-parity with pjit
    (tests/test_sharded_step.py).  Returns a :class:`_CountingStep`
    (callable; ``num_traces`` pins compile-once)."""
    shard_check(spec, plan)
    if mode not in ("pjit", "shard_map"):
        raise ValueError(f"mode must be 'pjit' or 'shard_map', got {mode!r}")
    donate_argnums = (2, 5, 6, 7) if donate else ()

    def _build(tick, _unused):
        def _body(x0, layer_ws, caches, head, head_s, pt, lens, skey,
                  comm):
            tick()  # trace-time only: the compile-once counter
            return _step_math(x0, layer_ws, caches, head, head_s, pt,
                              lens, skey, spec, comm)

        if mode == "pjit":
            comm = _GlobalComm(spec, plan)
            if num_layers is None:
                # shardings need the layer count up front; trace-time
                # len(layer_ws) would do, but jit in_shardings cannot
                return jax.jit(
                    lambda *a: _body(*a, comm),
                    donate_argnums=donate_argnums)
            in_sh, out_sh = plan.shard_step_shardings(num_layers)
            return compile_step_with_plan(
                lambda *a: _body(*a, comm), plan,
                in_shardings=in_sh, out_shardings=out_sh,
                donate_argnums=donate_argnums)
        # shard_map fallback: explicit per-device body
        if num_layers is None:
            raise ValueError("mode='shard_map' needs num_layers= (the "
                             "in_specs pytree is built up front)")
        comm = _ShardMapComm(spec, plan)
        in_sh, out_sh = plan.shard_step_shardings(num_layers)
        from flashinfer_tpu.utils import jax_shard_map

        mapped = jax_shard_map(
            lambda *a: _body(*a, comm), mesh=plan.mesh,
            in_specs=plan.spec_tree(in_sh),
            out_specs=plan.spec_tree(out_sh), check_vma=False,
        )
        return jax.jit(mapped, donate_argnums=donate_argnums)

    return _CountingStep(None, _build)


def build_sharded_per_op_step(spec: Int8ShardSpec, plan: ShardingPlan, *,
                              donate: bool = True):
    """The SAME sharded math in the pre-fused dispatch structure: one
    jitted sharded program PER LAYER plus a jitted epilogue, chained by
    a host loop — ``layers + 1`` dispatches (and their collectives) per
    step instead of 1.  The A/B twin of
    :func:`build_sharded_fused_step`; numerics identical."""
    shard_check(spec, plan)
    comm = _GlobalComm(spec, plan)
    layer_sh = plan.shard_layer_shardings()
    cache = plan.cache_sharding()
    x_sh = plan.named(plan.dp, None)
    pt_sh = plan.named(plan.dp, None)
    lens_sh = plan.named(plan.dp)
    layer_fn = compile_step_with_plan(
        lambda x, w, kcl, vcl, pt, lens: _sharded_layer(
            x, w, kcl, vcl, pt, lens, spec, comm),
        plan,
        in_shardings=(x_sh, dict(layer_sh), cache, cache, pt_sh, lens_sh),
        out_shardings=(x_sh, cache, cache),
        donate_argnums=(2, 3) if donate else (),
    )
    epilogue_fn = compile_step_with_plan(
        lambda x, head, head_s, skey: _sharded_epilogue(
            x, head, head_s, skey, spec, comm),
        plan,
        in_shardings=(x_sh, plan.named(None, plan.tp),
                      plan.named(None, plan.tp), plan.replicated),
        out_shardings=(plan.replicated, plan.replicated),
    )

    def step(x0, layer_ws, caches, head, head_s, pt, lens, skey):
        x = x0
        new_caches = []
        for w, (kcl, vcl) in zip(layer_ws, caches):
            x, kcl, vcl = layer_fn(x, w, kcl, vcl, pt, lens)
            new_caches.append((kcl, vcl))
        tok, skey = epilogue_fn(x, head, head_s, skey)
        return tok, new_caches, pt, lens, skey

    return step


class ShardedServingStep:
    """plan/run lifecycle over :func:`build_sharded_fused_step` —
    the mesh twin of ``serve/step.py``'s :class:`ServingStep`.

    >>> splan = ShardingPlan(mesh, dp="dp", tp="tp")
    >>> step = ShardedServingStep()
    >>> step.plan(spec, splan, num_layers=L)          # compile once
    >>> tok, caches, pt, lens, skey = step.run(
    ...     x0, layer_ws, caches, head, head_s, pt, lens, skey)

    ``num_traces`` pins compile-once; a trace beyond the first under a
    live plan increments the ``serve.step_retraces`` obs counter (the
    same catalog contract as the single-chip step)."""

    _STATE_NAMES = ("x0", "layer_ws", "caches", "head", "head_s",
                    "page_table", "kv_lens", "key")

    def __init__(self):
        self._plan: Optional[ShardingPlan] = None
        self._spec: Optional[Int8ShardSpec] = None
        self._step: Optional[_CountingStep] = None
        self._mode = "pjit"
        self._last_sig = None

    @property
    def num_traces(self) -> int:
        return 0 if self._step is None else self._step.num_traces

    @property
    def sharding_plan(self) -> Optional[ShardingPlan]:
        return self._plan

    @property
    def mesh_axes(self) -> str:
        return self._plan.mesh_axes if self._plan else ""

    def plan(self, spec: Int8ShardSpec, plan: ShardingPlan, *,
             num_layers: int, donate: bool = True,
             mode: str = "pjit") -> None:
        from flashinfer_tpu import obs

        replan = self._step is not None
        self._spec, self._plan, self._mode = spec, plan, mode
        self._step = build_sharded_fused_step(
            spec, plan, num_layers=num_layers, donate=donate, mode=mode)
        self._last_sig = None
        # the sharded plan's frozen statics for retrace-cause
        # attribution: shard spec + mesh identity + step shape
        obs.record_plan(self, replan=replan, statics=dict(
            spec=spec, mesh_axes=plan.mesh_axes,
            num_layers=int(num_layers), donate=bool(donate), mode=mode))

    @flashinfer_api(name="parallel.sharded_step")
    def run(self, x0, layer_ws, caches, head, head_s, pt, lens, skey):
        from flashinfer_tpu import obs

        if self._step is None:
            raise RuntimeError("plan() must be called before run()")
        tick = obs.steploop_begin("ShardedServingStep")
        signed = (x0, layer_ws, caches, head, head_s, pt, lens, skey)
        sig = obs.state_signature(signed, names=self._STATE_NAMES)
        if tick is not None:
            tick.mark("signature")
        before = self._step.num_traces
        t0 = time.perf_counter() if sig is not None else 0.0
        out = self._step(x0, layer_ws, caches, head, head_s, pt, lens,
                         skey)
        if tick is not None:
            tick.dispatched()
        if self._step.num_traces > before:
            if sig is not None:
                obs.record_span(f"{type(self).__name__}.trace_and_compile",
                                "compile", t0, time.perf_counter(),
                                wrapper=type(self).__name__,
                                trace_index=self._step.num_traces)
            if self._step.num_traces > 1:
                obs.counter_inc("serve.step_retraces",
                                wrapper=type(self).__name__)
                if sig is not None:
                    obs.record_retrace(
                        type(self).__name__,
                        obs.diff_state_sigs(self._last_sig, sig, signed))
        if sig is not None:
            self._last_sig = sig
        if tick is not None:
            jax.block_until_ready(out[0])  # completion probe (gate-ON)
            tick.done()
            tick.commit(tokens=int(x0.shape[0]))
        return out


# ---------------------------------------------------------------------------
# ServingStep (llama pytree) sharding table
# ---------------------------------------------------------------------------


def llama_step_shardings(plan: ShardingPlan, cfg, *,
                         weights_int8: bool = False):
    """(in_shardings, out_shardings) for ``ServingStep``'s jitted body
    ``(params, logits, caches, page_table, kv_lens, key)``: the
    ``models/llama.py`` TP weight table (column-shard q/k/v/gate/up,
    row-shard o/down, vocab-shard lm_head) as NamedShardings, batch
    state over dp, caches (pages over dp, KV heads over tp).

    dp-only plans move no contraction axis, so the sharded step stays
    tokens-BITWISE with the unsharded one; tp>1 splits the o/down/qkv
    contractions and reorders their f32 partial sums (documented
    tolerance — tests/test_sharded_step.py)."""
    from flashinfer_tpu.models.llama import _tp_param_specs

    def ns(p):
        return NamedSharding(plan.mesh, p)

    layer_specs = _tp_param_specs(cfg, plan.tp, quantized=weights_int8)
    param_sh = dict(
        embed=ns(P(None, None)),
        final_norm=ns(P(None)),
        lm_head=ns(P(None, plan.tp)),
        layers=[{k: ns(v) for k, v in layer_specs.items()}
                for _ in range(cfg.num_layers)],
    )
    if weights_int8:
        param_sh["lm_head_scale"] = ns(P(None, plan.tp))
    cache = plan.cache_sharding()
    caches_sh = [(cache, cache) for _ in range(cfg.num_layers)]
    logits_sh = plan.named(plan.dp, None)
    pt_sh = plan.named(plan.dp, None)
    lens_sh = plan.named(plan.dp)
    in_sh = (param_sh, logits_sh, caches_sh, pt_sh, lens_sh,
             plan.replicated)
    # tokens come back REPLICATED: the sampling chain must stay on the
    # replicated logits (see the threefry note in ServingStep.plan) —
    # a dp-sharded token output would let GSPMD re-partition the
    # sampler and fork its random stream per shard
    out_sh = (plan.replicated, logits_sh, caches_sh, pt_sh, lens_sh,
              plan.replicated)
    return in_sh, out_sh


# ---------------------------------------------------------------------------
# Axis selection (the parallel.* autotune knobs)
# ---------------------------------------------------------------------------


def default_tp(world_size: int, num_qo_heads: int,
               num_kv_heads: int) -> int:
    """Largest tp that tiles both head counts and the world size —
    the all-tp default (serving decode is TP-dominant; dp absorbs the
    remainder)."""
    return max(math.gcd(world_size,
                        math.gcd(num_qo_heads, num_kv_heads)), 1)


def plan_axes(world_size: int, *, hidden: int, num_qo_heads: int,
              num_kv_heads: int) -> Tuple[int, int, int]:
    """(dp, tp, ep) axis sizes for a serving mesh: the registered
    ``parallel.dp`` / ``parallel.tp`` / ``parallel.ep`` autotune knobs
    (shape key ``world_hidden_hq_hkv``), falling back to the all-tp
    default.  Invalid combinations (product != world, head counts not
    tiled) fall back too — a stale config entry must not build an
    uncompilable mesh."""
    from flashinfer_tpu.autotuner import AutoTuner

    key = (int(world_size), int(hidden), int(num_qo_heads),
           int(num_kv_heads))
    t = AutoTuner.get()
    tp = int(t.lookup("parallel.tp", key,
                      default=default_tp(world_size, num_qo_heads,
                                         num_kv_heads)))
    dp = int(t.lookup("parallel.dp", key,
                      default=max(world_size // max(tp, 1), 1)))
    ep = int(t.lookup("parallel.ep", key, default=1))
    # ep factors the tp axis (the Mapping moe_tp*moe_ep == tp contract)
    ok = (dp >= 1 and tp >= 1 and ep >= 1 and dp * tp == world_size
          and num_qo_heads % tp == 0 and num_kv_heads % tp == 0
          and tp % ep == 0)
    if not ok:
        tp = default_tp(world_size, num_qo_heads, num_kv_heads)
        dp, ep = world_size // tp, 1
    return dp, tp, ep


def make_serving_mesh(world_size: Optional[int] = None, *, hidden: int,
                      num_qo_heads: int, num_kv_heads: int,
                      devices=None) -> ShardingPlan:
    """Build a (dp, tp) serving mesh over the visible devices with
    knob-selected axis sizes — the one-call entry the bench and
    examples use."""
    devices = list(devices if devices is not None else jax.devices())
    if world_size is None:
        world_size = len(devices)
    dp, tp, _ = plan_axes(world_size, hidden=hidden,
                          num_qo_heads=num_qo_heads,
                          num_kv_heads=num_kv_heads)
    devs = np.array(devices[:world_size]).reshape(dp, tp)
    return ShardingPlan(Mesh(devs, ("dp", "tp")))
